"""Trace→schedule compiler: lower model-checker action traces into
runnable faultinject schedules.

The checker names crash interleavings abstractly (``work(1)`` then
``crash(1)`` then ``resolve(0,r0,abort)``); the faultinject plane kills
real processes at named sites (``collective.issue`` nth=6 → SIGKILL).
This module is the bridge (ISSUE 20 tentpole part 3): every checker
trace — a counterexample from a broken config, or a sampled coverage
path from a clean one — compiles into the faultinject JSON schedule
grammar (site / match / nth / action) plus a scenario descriptor the
runner ingests (``python -m torchft_tpu.faultinject.runner --compiled``),
so the interleavings the checker explored symbolically are replayed
against the real system and re-judged by the conformance gate.

Lowering maps the victim's *protocol phase at death* onto the nearest
real injection coordinate (the runner's victim is group 1; the model
victim is the first crashed replica):

=====================  ====================================================
model position         fault rule
=====================  ====================================================
crashed mid-round      ``commit.vote`` match="prepare" nth=votes+1 — died
after working,         between contributing the collective and casting the
before voting          commit vote (the barrier-drain site)
crashed after voting   ``collective.issue`` match="allreduce" nth=works+1 —
                       the vote is on the wire; the nearest runnable hook
                       is entering the NEXT step's collective
crashed before         ``quorum.reply`` nth=rounds — died on the quorum
working                reply, before contributing anything
``work_corrupt(v)``    ``collective.complete`` match="allreduce" nth=works
                       action=corrupt frac=0.05, with the divergence
                       sentinel+fence armed (the fence vetoes the commit,
                       so the run still ends bit-identical)
``heal_fail(v)``       survivor schedule ``ckpt.serve`` nth=1 drop — the
                       transfer dies on the SERVING side (the victim's
                       respawn env is scrubbed by design, so a healer-side
                       kill is not replayable; the serve drop is)
=====================  ====================================================

HA-tier actions (``lh_*``, ``delta*``, ``sub_*``) have no runnable
lowering until the Raft lighthouse lands: they are collected into the
schedule's ``unlowered`` list, the descriptor is still written (the
trace and the intended coordinates are the spec for that future wiring),
and ``runnable`` stays False unless at least one real rule lowered.

``compile_gate_schedules()`` compiles the shipped set from sampled
coverage paths of the single-lighthouse gate configs; the faultmatrix
tier replays them green today (tests/test_faultinject_compiled.py).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from torchft_tpu.analysis.protocol.spec import (
    SpecConfig,
    State,
    check_state,
    check_terminal,
    enabled_actions,
    init_state,
)

__all__ = [
    "CompiledSchedule",
    "compile_trace",
    "sample_paths",
    "compile_gate_schedules",
    "SHIPPED_DIR",
]

# the checked-in descriptors the runner's bare `--compiled` flag loads
SHIPPED_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "faultinject", "compiled",
)

_ACT = re.compile(r"^([a-z_]+)\(([^)]*)\)")


@dataclass
class CompiledSchedule:
    """One lowered trace: the scenario descriptor the runner ingests."""

    name: str
    description: str
    source: str                  # "counterexample" | "coverage"
    trace: List[str]
    victim: int                  # model replica index lowered to group 1
    victim_schedule: Optional[dict] = None
    survivor_schedule: Optional[dict] = None
    common_env: Dict[str, str] = field(default_factory=dict)
    expect_victim_death: bool = False
    unlowered: List[str] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    @property
    def runnable(self) -> bool:
        """At least one real rule lowered — an all-HA trace compiles to
        coordinates only the future Raft wiring can honor."""
        return bool(
            (self.victim_schedule or {}).get("rules")
            or (self.survivor_schedule or {}).get("rules")
        )

    def to_descriptor(self) -> dict:
        return {
            "name": self.name,
            "description": self.description,
            "source": self.source,
            "trace": list(self.trace),
            "victim": self.victim,
            "victim_schedule": self.victim_schedule,
            "survivor_schedule": self.survivor_schedule,
            "common_env": dict(self.common_env),
            "expect_victim_death": self.expect_victim_death,
            "unlowered": list(self.unlowered),
            "notes": list(self.notes),
            "runnable": self.runnable,
        }

    @classmethod
    def from_descriptor(cls, doc: dict) -> "CompiledSchedule":
        return cls(
            name=doc["name"],
            description=doc.get("description", ""),
            source=doc.get("source", "coverage"),
            trace=list(doc.get("trace", [])),
            victim=int(doc.get("victim", 1)),
            victim_schedule=doc.get("victim_schedule"),
            survivor_schedule=doc.get("survivor_schedule"),
            common_env=dict(doc.get("common_env", {})),
            expect_victim_death=bool(doc.get("expect_victim_death")),
            unlowered=list(doc.get("unlowered", [])),
            notes=list(doc.get("notes", [])),
        )


def _parse(label: str) -> Tuple[str, List[str]]:
    """``"vote(1)!stale"`` → ``("vote", ["1"])`` (suffix tags dropped —
    they annotate the invariant, not the coordinate)."""
    m = _ACT.match(label)
    if not m:
        return label, []
    return m.group(1), [a.strip() for a in m.group(2).split(",") if a]


# the HA tier: model actions with no real implementation to inject into
# yet (the Raft lighthouse / delta protocol / sub-aggregator tree)
_HA_PREFIXES = (
    "lh_", "delta", "sub_",
)


def compile_trace(
    trace: List[str],
    name: str,
    description: str = "",
    source: str = "coverage",
) -> CompiledSchedule:
    """Lower one checker action trace into a scenario descriptor.

    The victim is the first replica the trace crashes (no crash and no
    corrupt → nothing to inject; the descriptor comes back with no rules
    and ``runnable`` False). The schedule seed is derived from the trace
    so identical traces compile to identical schedules.
    """
    seed = zlib.crc32("|".join(trace).encode()) % 1000 or 1
    out = CompiledSchedule(
        name=name, description=description, source=source,
        trace=list(trace), victim=1,
    )

    victim: Optional[int] = None
    for label in trace:
        act, args = _parse(label)
        if act == "crash":
            victim = int(args[0])
            break
        if act == "work_corrupt":
            victim = int(args[0])
            break
    if victim is None:
        for label in trace:
            act, args = _parse(label)
            if act == "heal_fail":
                victim = int(args[0])
                break
    out.victim = victim if victim is not None else 1

    rules: List[dict] = []
    survivor_rules: List[dict] = []
    # the victim's walked protocol position
    works = votes = rounds = 0
    in_round = worked = voted = False
    crashed = False

    for label in trace:
        act, args = _parse(label)
        if any(act.startswith(p) for p in _HA_PREFIXES):
            out.unlowered.append(label)
            continue
        tgt: Optional[int] = None
        if args:
            head = args[0].split("<-")[0].split("->")[0]
            if head.isdigit():
                tgt = int(head)
        if act == "form":
            if not crashed and victim is not None:
                in_round, worked, voted = True, False, False
                rounds += 1
            continue
        if tgt != victim:
            continue
        if act == "work":
            works += 1
            worked = True
        elif act == "work_corrupt":
            works += 1
            worked = True
            rules.append({
                "site": "collective.complete", "match": "allreduce",
                "nth": works, "action": "corrupt", "frac": 0.05,
            })
            # the fence turns the planted corruption into an abort +
            # clean retry, so the compiled run still converges
            out.common_env["TORCHFT_DIVERGENCE_SENTINEL"] = "1"
            out.common_env["TORCHFT_DIVERGENCE_FENCE"] = "1"
            out.notes.append(
                f"{label}: corrupt lowered with the divergence fence "
                "armed (commit must abort, retry must be clean)"
            )
        elif act in ("vote", "vote_spec"):
            votes += 1
            voted = True
        elif act == "resolve":
            in_round = worked = voted = False
        elif act == "heal_fail":
            survivor_rules.append({
                "site": "ckpt.serve", "nth": 1, "action": "drop",
            })
            out.notes.append(
                f"{label}: healer-side failure lowered to the survivor's "
                "serve (the respawned victim's schedule is scrubbed by "
                "the runner, so the serving side carries the fault)"
            )
        elif act == "crash":
            if crashed:
                out.unlowered.append(label)
                out.notes.append(
                    f"{label}: second victim death not replayable (the "
                    "respawn env is scrubbed — one scheduled death per "
                    "incarnation)"
                )
                continue
            crashed = True
            if in_round and worked and not voted:
                rules.append({
                    "site": "commit.vote", "match": "prepare",
                    "nth": votes + 1, "action": "kill", "sig": 9,
                })
                out.notes.append(
                    f"{label}: died after contributing, before the "
                    f"commit vote → kill at the barrier drain "
                    f"(prepare #{votes + 1})"
                )
            elif in_round and voted:
                rules.append({
                    "site": "collective.issue", "match": "allreduce",
                    "nth": works + 1, "action": "kill", "sig": 9,
                })
                out.notes.append(
                    f"{label}: died with the vote on the wire → kill "
                    f"entering the next collective (allreduce "
                    f"#{works + 1})"
                )
            else:
                rules.append({
                    "site": "quorum.reply",
                    "nth": max(rounds, 1), "action": "kill", "sig": 9,
                })
                out.notes.append(
                    f"{label}: died before contributing → kill on the "
                    f"quorum reply (#{max(rounds, 1)})"
                )
            out.expect_victim_death = True

    if rules:
        out.victim_schedule = {"seed": seed, "rules": rules}
    if survivor_rules:
        out.survivor_schedule = {"seed": seed, "rules": survivor_rules}
    return out


# ---------------------------------------------------------------------------
# coverage-path sampling
# ---------------------------------------------------------------------------


def sample_paths(
    cfg: SpecConfig,
    want: int = 32,
    max_states: int = 200_000,
) -> List[List[str]]:
    """Deterministic DFS over ``cfg`` collecting up to ``want`` coverage
    paths: clean traces that reach a terminal with at least one commit
    AND contain at least one crash — the interleavings worth replaying.
    Violating paths are skipped (those are counterexamples; compile them
    from the checker's Violation directly)."""
    root = init_state(cfg)
    paths: List[List[str]] = []
    seen = {root}
    stack: List[Tuple[State, List[str]]] = [(root, [])]
    states = 0
    while stack and len(paths) < want and states < max_states:
        state, path = stack.pop()
        states += 1
        actions = enabled_actions(state, cfg)
        if not actions:
            if (
                state.commits
                and any(p.startswith("crash(") for p in path)
                and not check_terminal(state, cfg)
                and not check_state(state, cfg)
            ):
                paths.append(path)
            continue
        for label, nxt in actions:
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [label]))
    return paths


def _classify(cs: CompiledSchedule) -> Optional[str]:
    rules = (cs.victim_schedule or {}).get("rules", [])
    return rules[0]["site"] if rules else None


def compile_gate_schedules(
    cfg: Optional[SpecConfig] = None,
) -> List[CompiledSchedule]:
    """The shipped set: from sampled coverage paths of the ``sync-2g``
    gate config, one compiled schedule per distinct victim-death
    coordinate the lowering can express — kill at the quorum reply, kill
    at the commit-vote drain, kill entering the next collective. Each
    replays green through the faultmatrix runner (that's the gate)."""
    from torchft_tpu.analysis.protocol.checker import GATE_CONFIGS

    cfg = cfg or GATE_CONFIGS["sync-2g"]
    picked: Dict[str, CompiledSchedule] = {}
    descr = {
        "quorum.reply": (
            "compiled_kill_quorum_reply",
            "checker coverage path: the victim dies on a quorum reply "
            "before contributing; the cohort re-forms and converges "
            "(compiled from the sync-2g model by analysis.protocol."
            "compile)",
        ),
        "commit.vote": (
            "compiled_kill_commit_vote",
            "checker coverage path: the victim dies at the barrier "
            "drain after contributing, before its commit vote; the "
            "survivor's step aborts and the respawn heals (compiled "
            "from the sync-2g model)",
        ),
        "collective.issue": (
            "compiled_kill_next_collective",
            "checker coverage path: the victim dies entering the "
            "collective after a cast vote; the committed step survives "
            "it (compiled from the sync-2g model)",
        ),
    }
    for path in sample_paths(cfg):
        cs = compile_trace(path, name="tmp", source="coverage")
        site = _classify(cs)
        if site in descr and site not in picked:
            name, text = descr[site]
            cs.name, cs.description = name, text
            picked[site] = cs
        if len(picked) == len(descr):
            break
    return [picked[s] for s in sorted(picked)]


# ---------------------------------------------------------------------------
# CLI: write descriptors
# ---------------------------------------------------------------------------


def write_descriptors(
    schedules: List[CompiledSchedule], outdir: str
) -> List[str]:
    os.makedirs(outdir, exist_ok=True)
    written = []
    for cs in schedules:
        path = os.path.join(outdir, f"{cs.name}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(cs.to_descriptor(), f, indent=2, sort_keys=True)
            f.write("\n")
        written.append(path)
    return written


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="torchft_tpu.analysis.protocol.compile",
        description="compile checker traces into faultinject schedules",
    )
    ap.add_argument("--outdir", default=SHIPPED_DIR,
                    help="where descriptors land (default: the shipped "
                    "faultinject/compiled/ set)")
    ap.add_argument("--fixture", metavar="JSON",
                    help="compile the counterexample of a broken spec "
                    "fixture (tests/fixtures/analysis/spec_*.json) "
                    "instead of the gate coverage set")
    args = ap.parse_args(argv)

    if args.fixture:
        from torchft_tpu.analysis.protocol.checker import check

        with open(args.fixture, encoding="utf-8") as f:
            doc = json.load(f)
        doc.pop("_comment", None)
        expect = doc.pop("expect_violation", None)
        res = check(SpecConfig(**doc), max_violations=1)
        if not res.violations:
            print(f"{args.fixture}: no violation found — nothing to "
                  "compile", file=sys.stderr)
            return 1
        v = res.violations[0]
        base = os.path.splitext(os.path.basename(args.fixture))[0]
        cs = compile_trace(
            v.trace,
            name=f"counterexample_{base}",
            description=f"counterexample of {base} "
            f"({v.invariant}; expected {expect}): {v.detail}",
            source="counterexample",
        )
        written = write_descriptors([cs], args.outdir)
    else:
        written = write_descriptors(
            compile_gate_schedules(), args.outdir
        )
    for path in written:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        tag = "runnable" if doc["runnable"] else (
            f"NOT runnable ({len(doc['unlowered'])} unlowered HA "
            "action(s) — pending the Raft wiring)"
        )
        print(f"{path}: {tag}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
