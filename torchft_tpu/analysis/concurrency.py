"""Project-specific concurrency lint over the FT runtime modules.

The Manager runs a quorum long-poll thread, a commit-vote thread, a step
watchdog, death-watch/evict threads and a speculation fence — thread
discipline there is load-bearing for the paper's per-step recovery claim,
and the remaining ROADMAP corruption item is exactly the bug class that
races produce. torchft's Rust core gets this from the compiler; this AST
lint is the Python analogue: the threading contract becomes checkable
rules instead of prose.

Rules (ids are the suppression-key prefix):

``lock-order-cycle``
    A cycle in the lock-order graph extracted from nested ``with <lock>``
    scopes (including one level of same-file call propagation) — a
    lock-order inversion that can deadlock under the right interleaving.

``blocking-under-lock``
    A blocking call (socket IO, RPC ``.call``, ``time.sleep``,
    ``Future.wait``/``result``, thread ``join`` …) while holding a
    ``Lock``/``Condition``. ``cond.wait()`` on the *held* condition is
    exempt (it releases the lock). Documented-intentional cases (e.g. a
    dedicated per-socket send lock) are suppressed in the baseline with a
    reason.

``callback-under-lock``
    ``Future.set_result``/``set_exception`` invoked while holding a lock:
    continuations (``then`` chains, flight-recorder completions, user
    callbacks) run inline on the resolving thread, so they execute UNDER
    the held lock — a continuation that re-enters the owning object
    deadlocks. Resolve futures after releasing the lock.

``unguarded-shared-write`` / ``guard-not-held``
    A ``self.<attr>`` mutated from more than one thread entry point must
    carry a ``# guarded-by: <lock>`` annotation on its ``__init__``
    assignment (or ``# unguarded-ok: <reason>`` when a happens-before
    hand-off — not a lock — is the synchronizer; say which). With a
    ``guarded-by``, every mutation site must sit lexically under
    ``with self.<lock>``.

``cond-wait-no-loop``
    A ``Condition.wait()`` not wrapped in an enclosing ``while`` predicate
    loop (``wait_for`` is fine) — wakeups are allowed to be spurious.

``thread-unnamed`` / ``thread-not-daemon-or-joined``
    Every ``threading.Thread`` must be named (hang forensics — ``py-spy``
    dumps and flight-recorder triage key on thread names) and must be a
    daemon or explicitly joined.

Annotation grammar (trailing comment on the attribute's first assignment,
normally in ``__init__``; the continuation line below also counts)::

    self._step = 0          # guarded-by: _commit_mu
    self._healing = False   # unguarded-ok: quorum-thread handoff via
                            #   the wait_quorum() barrier

The annotation names the lock attribute without ``self.`` and applies
file-wide to that attribute name within its class.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from torchft_tpu.analysis.base import Finding, repo_root

__all__ = ["RUNTIME_MODULES", "analyze_source", "analyze_paths", "run"]

# The modules whose threading contract this lint enforces: the ISSUE 5
# list plus every thread-spawning module landed since (ISSUE 15 — the
# diagnosis/profiler/SLO/time-series monitors and the black box all run
# worker threads against Manager-visible state).
RUNTIME_MODULES = (
    "torchft_tpu/manager.py",
    "torchft_tpu/futures.py",
    "torchft_tpu/collectives.py",
    "torchft_tpu/collectives_device.py",
    "torchft_tpu/proxy.py",
    "torchft_tpu/telemetry/flight.py",
    "torchft_tpu/checkpointing/_rwlock.py",
    "torchft_tpu/faultinject/core.py",
    "torchft_tpu/telemetry/diagnosis.py",
    "torchft_tpu/telemetry/profiler.py",
    "torchft_tpu/telemetry/slo.py",
    "torchft_tpu/telemetry/timeseries.py",
    "torchft_tpu/telemetry/blackbox.py",
    "torchft_tpu/telemetry/critical_path.py",
)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_CONDITION_FACTORIES = {"Condition"}

# Attribute-call names considered blocking. Deliberately conservative:
# queue/dict get/put are ambiguous at the AST level and excluded; helper
# functions containing a direct blocking call are propagated one level so
# ``with lock: self._helper()`` is still caught.
_BLOCKING_ATTRS = {
    "sleep",              # time.sleep
    "sendall", "recv", "recv_into", "accept", "connect",
    "create_connection",  # socket IO
    "result",             # concurrent.futures / chained futures
    "wait",               # Future.wait / Event.wait / foreign cond.wait
    "join",               # thread join (str/path join excluded below)
    "call",               # NativeClient RPC
    "select",
}

# Future-resolution calls that run arbitrary continuations inline.
_CALLBACK_ATTRS = {"set_result", "set_exception"}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_UNGUARDED_OK_RE = re.compile(r"#\s*unguarded-ok:")

# Mutating method calls on a self attribute that count as writes.
_MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popleft", "remove", "update", "setdefault",
}


def _expr_id(node: ast.AST) -> str:
    """Stable textual identity for a lock expression: ``self._x`` stays
    qualified; ``p.cond`` becomes ``*.cond`` (instance-agnostic); a bare
    name stays itself."""
    if isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return f"self.{node.attr}"
        return f"*.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    return ast.dump(node)


class _FuncInfo:
    __slots__ = ("qualname", "node", "acquires", "blocks", "resolves", "calls")

    def __init__(self, qualname: str, node: ast.AST) -> None:
        self.qualname = qualname
        self.node = node
        self.acquires: List[Tuple[str, int]] = []  # (lock id, line)
        self.blocks = False     # body makes a direct blocking call
        self.resolves = False   # body resolves a future directly
        self.calls: List[str] = []


class _FileAnalysis:
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.findings: List[Finding] = []
        self.lock_attrs: Dict[str, str] = {}    # attr name -> kind
        self.module_locks: Dict[str, str] = {}  # module global -> kind
        self.funcs: Dict[str, _FuncInfo] = {}
        # class -> attr -> {(func qualname, line, lock held?)}
        self.writes: Dict[str, Dict[str, Set[Tuple[str, int, bool]]]] = {}
        # class -> attr -> (decl line, guarded-by lock, unguarded-ok?)
        self.attr_decl: Dict[str, Dict[str, Tuple[int, Optional[str], bool]]] = {}
        self.worker_entries: Dict[str, Set[str]] = {}  # class -> short names
        self.classes: List[str] = []
        # method short name -> qualnames defining it (for *.m() resolution)
        self.method_index: Dict[str, List[str]] = {}
        self._inside_while: Dict[int, bool] = {}

    # ------------------------------------------------------------------
    # pass 0: locks + parent/while map
    # ------------------------------------------------------------------

    def _lock_kind(self, call: ast.Call) -> Optional[str]:
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name in _CONDITION_FACTORIES:
            return "condition"
        if name in _LOCK_FACTORIES:
            return "lock"
        return None

    def prescan(self) -> None:
        def mark(node: ast.AST, inside: bool) -> None:
            self._inside_while[id(node)] = inside
            for child in ast.iter_child_nodes(node):
                mark(child, inside or isinstance(node, ast.While))

        mark(self.tree, False)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                kind = self._lock_kind(node.value)
                if not kind:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks[t.id] = kind
                    elif (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.lock_attrs[t.attr] = kind

    # ------------------------------------------------------------------
    # pass 1: per-function walk
    # ------------------------------------------------------------------

    def collect(self) -> None:
        # register every method/function FIRST so calls to later-defined
        # methods resolve (collection order must not matter), then walk
        self._register(self.tree.body, cls=None)
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._collect_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(node, self.funcs[node.name], None, [])

    def _register(self, body, cls: Optional[str]) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                self.classes.append(node.name)
                self.writes.setdefault(node.name, {})
                self.attr_decl.setdefault(node.name, {})
                self.worker_entries.setdefault(node.name, set())
                self._register(node.body, cls=node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{cls}.{node.name}" if cls else node.name
                self.funcs[q] = _FuncInfo(q, node)
                if cls:
                    self.method_index.setdefault(node.name, []).append(q)

    def _collect_class(self, cls: ast.ClassDef) -> None:
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{cls.name}.{item.name}"
                self._walk(item, self.funcs[q], cls.name, [])
            elif isinstance(item, ast.ClassDef):
                self._collect_class(item)

    def _collect_func(self, qualname: str, fn: ast.AST, cls: Optional[str]) -> None:
        info = _FuncInfo(qualname, fn)
        self.funcs[qualname] = info
        self._walk(fn, info, cls, lock_stack=[])

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        lid = _expr_id(expr)
        if "." in lid:
            attr = lid.split(".", 1)[1]
            return lid if attr in self.lock_attrs else None
        return lid if lid in self.module_locks else None

    def _lock_obj_kind(self, lid: str) -> Optional[str]:
        if "." in lid:
            return self.lock_attrs.get(lid.split(".", 1)[1])
        return self.module_locks.get(lid)

    def _annotation_for_line(self, lineno: int) -> Tuple[Optional[str], bool]:
        """Annotation for the declaration at ``lineno``: the line's own
        trailing comment, or the contiguous block of comment lines
        directly ABOVE it (multi-line reasons read best as a leading
        comment). A leading block annotates only the statement
        immediately below it."""
        candidates = []
        if lineno - 1 < len(self.lines):
            candidates.append(self.lines[lineno - 1])
        i = lineno - 2
        while i >= 0 and self.lines[i].strip().startswith("#"):
            candidates.append(self.lines[i])
            i -= 1
        for text in candidates:
            m = _GUARDED_BY_RE.search(text)
            if m:
                return m.group(1), False
            if _UNGUARDED_OK_RE.search(text):
                return None, True
        return None, False

    def _record_write(
        self, cls: Optional[str], attr: str, func: _FuncInfo, lineno: int,
        lock_stack: List[str],
    ) -> None:
        if cls is None:
            return
        self.writes.setdefault(cls, {}).setdefault(attr, set()).add(
            (func.qualname, lineno, bool(lock_stack))
        )
        decl = self.attr_decl.setdefault(cls, {})
        in_init = func.qualname.endswith(".__init__")
        prev = decl.get(attr)
        if prev is None or (in_init and prev[1] is None and not prev[2]):
            guard, ok = self._annotation_for_line(lineno)
            if prev is None or guard is not None or ok:
                decl[attr] = (lineno, guard, ok)

    def _assign_targets(self, node) -> List[ast.AST]:
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        flat: List[ast.AST] = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                flat.extend(t.elts)
            else:
                flat.append(t)
        return flat

    def _walk(
        self, node: ast.AST, func: _FuncInfo, cls: Optional[str],
        lock_stack: List[str],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child, func, cls, lock_stack)

    def _visit(
        self, child: ast.AST, func: _FuncInfo, cls: Optional[str],
        lock_stack: List[str],
    ) -> None:
        # every statement/expr node flows through here exactly once —
        # including a With that is itself a With-body statement (walking
        # only children would skip directly-nested `with a: with b:`,
        # losing exactly the edges the lock-order rule exists for)
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: executes later, in its own context — locks
            # held at the definition site do not surround its body
            self._collect_func(f"{func.qualname}.{child.name}", child, cls)
            return
        if isinstance(child, ast.Lambda):
            inner = _FuncInfo(f"{func.qualname}.<lambda>", child)
            self.funcs.setdefault(inner.qualname, inner)
            self._walk(child.body, inner, cls, [])
            return
        if isinstance(child, ast.With):
            held = [
                lid for item in child.items
                if (lid := self._resolve_lock(item.context_expr)) is not None
            ]
            for lid in held:
                func.acquires.append((lid, child.lineno))
            new_stack = lock_stack + held
            for body_item in child.body:
                self._visit(body_item, func, cls, new_stack)
            return
        if isinstance(child, ast.Call):
            self._handle_call(child, func, cls, lock_stack)
        elif isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if isinstance(child, ast.AnnAssign) and child.value is None:
                self._walk(child, func, cls, lock_stack)
                return
            for t in self._assign_targets(child):
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    self._record_write(cls, t.attr, func, child.lineno, lock_stack)
                elif (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Attribute)
                    and isinstance(t.value.value, ast.Name)
                    and t.value.value.id == "self"
                ):
                    self._record_write(
                        cls, t.value.attr, func, child.lineno, lock_stack
                    )
        self._walk(child, func, cls, lock_stack)

    def _callee_name(self, fn: ast.AST, cls: Optional[str]) -> Optional[str]:
        """Resolve a call target to a same-file function qualname (best
        effort): bare names, ``self.m``, and ``x.m`` when exactly one
        class in this file defines ``m``."""
        if isinstance(fn, ast.Name):
            return fn.id if fn.id in self.funcs else None
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" and cls:
                q = f"{cls}.{fn.attr}"
                return q if q in self.funcs else None
            # x.m(): resolvable when exactly one class in this file defines
            # m — except for generic verb names (wait/join/...) where the
            # direct blocking check is authoritative and a unique-method
            # match would be coincidence (p.cond.wait is not Work.wait)
            if fn.attr not in _BLOCKING_ATTRS:
                owners = self.method_index.get(fn.attr, [])
                if len(owners) == 1:
                    return owners[0]
        return None

    def _handle_call(
        self, call: ast.Call, func: _FuncInfo, cls: Optional[str],
        lock_stack: List[str],
    ) -> None:
        fn = call.func
        self._thread_rule(call, func)
        self._worker_entry_targets(call, cls)
        # mutating method on a self attribute counts as a write
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _MUTATORS
            and isinstance(fn.value, ast.Attribute)
            and isinstance(fn.value.value, ast.Name)
            and fn.value.value.id == "self"
        ):
            self._record_write(cls, fn.value.attr, func, call.lineno, lock_stack)
        callee = self._callee_name(fn, cls)
        if callee is not None:
            func.calls.append(callee)
        label = self._blocking_label(call, lock_stack)
        if label is not None:
            func.blocks = True
            if lock_stack:
                self.findings.append(Finding(
                    "blocking-under-lock", self.path, call.lineno,
                    f"{func.qualname}:{label}",
                    f"blocking call {label} while holding "
                    f"{'+'.join(lock_stack)} — every thread contending "
                    "that lock now waits out the slow path too",
                ))
        if isinstance(fn, ast.Attribute) and fn.attr in _CALLBACK_ATTRS:
            func.resolves = True
            if lock_stack:
                self.findings.append(Finding(
                    "callback-under-lock", self.path, call.lineno,
                    f"{func.qualname}:{_expr_id(fn.value)}.{fn.attr}",
                    f"future resolved ({fn.attr}) while holding "
                    f"{'+'.join(lock_stack)} — continuations run inline "
                    "under the lock; a callback that re-enters the owner "
                    "deadlocks",
                ))
        # cond-wait predicate-loop rule
        if isinstance(fn, ast.Attribute) and fn.attr == "wait":
            lid = _expr_id(fn.value)
            if self._lock_obj_kind(lid) == "condition":
                if not self._inside_while.get(id(call), False):
                    self.findings.append(Finding(
                        "cond-wait-no-loop", self.path, call.lineno,
                        f"{func.qualname}:{lid}",
                        "Condition.wait() outside a while predicate loop — "
                        "wakeups may be spurious; re-check the predicate "
                        "in a loop (or use wait_for)",
                    ))

    def _blocking_label(
        self, call: ast.Call, lock_stack: List[str]
    ) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            return fn.id if fn.id == "sleep" else None
        if not isinstance(fn, ast.Attribute) or fn.attr not in _BLOCKING_ATTRS:
            return None
        name = fn.attr
        if name == "join":
            v = fn.value
            if isinstance(v, (ast.Constant, ast.JoinedStr)):
                return None  # "sep".join(...)
            if isinstance(v, ast.Attribute) and v.attr == "path":
                return None  # os.path.join
            if isinstance(v, ast.Name) and v.id in ("os", "posixpath", "ntpath"):
                return None
        if name == "wait":
            # cond.wait() on the HELD condition releases it — canonical
            if _expr_id(fn.value) in lock_stack:
                return None
        return f"{_expr_id(fn.value)}.{name}"

    def _thread_rule(self, call: ast.Call, func: _FuncInfo) -> None:
        fn = call.func
        is_thread = (isinstance(fn, ast.Attribute) and fn.attr == "Thread") or (
            isinstance(fn, ast.Name) and fn.id == "Thread"
        )
        if not is_thread:
            return
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        if "target" not in kw and not call.args:
            return  # bare Thread() subclass/typing use
        if "name" not in kw:
            self.findings.append(Finding(
                "thread-unnamed", self.path, call.lineno, func.qualname,
                "threading.Thread without name= — unnamed threads make "
                "py-spy / flight-recorder triage of a wedged step guesswork",
            ))
        daemon = kw.get("daemon")
        is_daemon = isinstance(daemon, ast.Constant) and daemon.value is True
        if not is_daemon and ".join(" not in self.source:
            self.findings.append(Finding(
                "thread-not-daemon-or-joined", self.path, call.lineno,
                func.qualname,
                "thread is neither daemon=True nor joined anywhere in this "
                "file — it can outlive shutdown and touch freed state",
            ))

    def _worker_entry_targets(self, call: ast.Call, cls: Optional[str]) -> None:
        """A bound ``self.<method>`` (or local def) handed away as a call
        argument — Thread target, executor.submit fn, ``then`` callback,
        death-watch registration — marks that function as a worker-context
        entry point for the class. Non-function attributes picked up by
        this heuristic are inert (they never appear in the call graph)."""
        if cls is None:
            return
        cands: List[ast.AST] = list(call.args) + [
            k.value for k in call.keywords if k.arg
        ]
        for c in cands:
            if (
                isinstance(c, ast.Attribute)
                and isinstance(c.value, ast.Name)
                and c.value.id == "self"
            ):
                self.worker_entries.setdefault(cls, set()).add(c.attr)
            elif isinstance(c, ast.Name) and any(
                q == c.id  # module-level function
                # nested def (Class.method.inner); a bare Name can never
                # reference a bound method, so 2-segment names (which a
                # local variable shadowing the method name would match)
                # are excluded
                or (q.count(".") >= 2 and q.endswith(f".{c.id}"))
                for q in self.funcs
            ):
                self.worker_entries.setdefault(cls, set()).add(c.id)

    # ------------------------------------------------------------------
    # pass 2: propagation + graph rules
    # ------------------------------------------------------------------

    def propagate_under_lock(self) -> None:
        """One level: calling a same-file function that blocks (or
        resolves futures) while holding a lock is itself a finding."""
        blocking = {q for q, i in self.funcs.items() if i.blocks}
        resolving = {q for q, i in self.funcs.items() if i.resolves}
        for q, info in self.funcs.items():
            self._prop_walk(info.node, info, [], blocking, resolving)

    def _prop_walk(self, node, func, lock_stack, blocking, resolving) -> None:
        for child in ast.iter_child_nodes(node):
            self._prop_visit(child, func, lock_stack, blocking, resolving)

    def _prop_visit(self, child, func, lock_stack, blocking, resolving) -> None:
        cls = func.qualname.split(".")[0] if "." in func.qualname else None
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(child, ast.With):
            held = [
                lid for item in child.items
                if (lid := self._resolve_lock(item.context_expr)) is not None
            ]
            new_stack = lock_stack + held
            for body_item in child.body:
                self._prop_visit(body_item, func, new_stack, blocking, resolving)
            return
        if isinstance(child, ast.Call) and lock_stack:
            callee = self._callee_name(child.func, cls)
            if callee in blocking:
                self.findings.append(Finding(
                    "blocking-under-lock", self.path, child.lineno,
                    f"{func.qualname}:{callee}()",
                    f"call to {callee}() (which blocks) while holding "
                    f"{'+'.join(lock_stack)}",
                ))
            if callee in resolving:
                self.findings.append(Finding(
                    "callback-under-lock", self.path, child.lineno,
                    f"{func.qualname}:{callee}()",
                    f"call to {callee}() (which resolves futures, "
                    "running continuations inline) while holding "
                    f"{'+'.join(lock_stack)}",
                ))
        self._prop_walk(child, func, lock_stack, blocking, resolving)

    def lock_order_rule(self) -> None:
        edges: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], Tuple[str, int]] = {}

        def add_edge(a: str, b: str, where: str, line: int) -> None:
            if a != b:
                edges.setdefault(a, set()).add(b)
                sites.setdefault((a, b), (where, line))

        acq_by_func = {q: i.acquires for q, i in self.funcs.items()}
        for q, info in self.funcs.items():
            self._edge_walk(info.node, q, [], acq_by_func, add_edge)

        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(n: str) -> Optional[List[str]]:
            color[n] = 1
            stack.append(n)
            for m in sorted(edges.get(n, ())):
                if color.get(m, 0) == 1:
                    return stack[stack.index(m):] + [m]
                if color.get(m, 0) == 0:
                    cyc = dfs(m)
                    if cyc:
                        return cyc
            stack.pop()
            color[n] = 2
            return None

        for n in sorted(edges):
            if color.get(n, 0) == 0:
                cyc = dfs(n)
                if cyc:
                    pairs = [p for p in zip(cyc, cyc[1:]) if p in sites]
                    where = "; ".join(
                        f"{a}->{b} at {sites[(a, b)][0]}:{sites[(a, b)][1]}"
                        for a, b in pairs
                    )
                    line = sites[pairs[0]][1] if pairs else 0
                    self.findings.append(Finding(
                        "lock-order-cycle", self.path, line, "->".join(cyc),
                        f"lock-order inversion: {' -> '.join(cyc)} ({where})"
                        " — two threads taking these locks in opposing "
                        "order deadlock",
                    ))
                    return  # one cycle report per file is plenty

    def _edge_walk(self, node, q, lock_stack, acq_by_func, add_edge) -> None:
        for child in ast.iter_child_nodes(node):
            self._edge_visit(child, q, lock_stack, acq_by_func, add_edge)

    def _edge_visit(self, child, q, lock_stack, acq_by_func, add_edge) -> None:
        cls = q.split(".")[0] if "." in q else None
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(child, ast.With):
            held = [
                lid for item in child.items
                if (lid := self._resolve_lock(item.context_expr)) is not None
            ]
            for lid in held:
                for h in lock_stack:
                    add_edge(h, lid, q, child.lineno)
            new_stack = lock_stack + held
            for body_item in child.body:
                self._edge_visit(body_item, q, new_stack, acq_by_func, add_edge)
            return
        if isinstance(child, ast.Call) and lock_stack:
            callee = self._callee_name(child.func, cls)
            if callee in acq_by_func:
                for lid, _line in acq_by_func[callee]:
                    for h in lock_stack:
                        add_edge(h, lid, q, child.lineno)
        self._edge_walk(child, q, lock_stack, acq_by_func, add_edge)

    def shared_state_rule(self) -> None:
        for cls in self.classes:
            entries = self.worker_entries.get(cls, set())
            if not entries:
                continue
            graph: Dict[str, Set[str]] = {}
            for q, info in self.funcs.items():
                if not q.startswith(f"{cls}."):
                    continue
                short = q[len(cls) + 1:]
                graph[short] = {
                    c[len(cls) + 1:].split(".")[0]
                    for c in info.calls
                    if c.startswith(f"{cls}.")
                }

            def reach(start: str) -> Set[str]:
                seen = {start}
                frontier = [start]
                while frontier:
                    cur = frontier.pop()
                    for nxt in graph.get(cur, ()):
                        if nxt not in seen:
                            seen.add(nxt)
                            frontier.append(nxt)
                return seen

            worker_reach = {
                e: reach(e) for e in entries
                if e in graph and not e.startswith("__")
            }

            for attr, sites in self.writes.get(cls, {}).items():
                contexts: Set[str] = set()
                unheld: List[Tuple[str, int]] = []
                for qual, line, held in sites:
                    short = (
                        qual[len(cls) + 1:]
                        if qual.startswith(f"{cls}.") else qual
                    )
                    if short.endswith("__init__"):
                        continue  # construction happens-before thread start
                    base = short.split(".")[0]
                    leaf = short.split(".")[-1]
                    ctx = "main"
                    for entry, reached in worker_reach.items():
                        if base == entry or base in reached:
                            ctx = f"worker:{entry}"
                            break
                    if ctx == "main" and leaf != base and leaf in entries:
                        # nested def handed away as a callback/thread target
                        ctx = f"worker:{short}"
                    contexts.add(ctx)
                    if not held:
                        unheld.append((short, line))
                if len(contexts) < 2:
                    continue
                decl = self.attr_decl.get(cls, {}).get(attr)
                line0, guard, unguarded_ok = decl if decl else (0, None, False)
                if unguarded_ok:
                    continue
                if guard is None:
                    self.findings.append(Finding(
                        "unguarded-shared-write", self.path, line0,
                        f"{cls}.{attr}",
                        f"mutated from {len(contexts)} thread contexts "
                        f"({', '.join(sorted(contexts))}) with no "
                        "'# guarded-by: <lock>' (or '# unguarded-ok: "
                        "<reason>') annotation on its declaration",
                    ))
                    continue
                for short, line in unheld:
                    self.findings.append(Finding(
                        "guard-not-held", self.path, line,
                        f"{cls}.{attr}@{short}",
                        f"declared '# guarded-by: {guard}' but this write "
                        f"is not under 'with self.{guard}'",
                    ))


def analyze_source(path: str, source: str) -> List[Finding]:
    fa = _FileAnalysis(path, source)
    fa.prescan()
    fa.collect()
    fa.propagate_under_lock()
    fa.lock_order_rule()
    fa.shared_state_rule()
    # dedupe (propagation can re-derive a direct finding) + stable order
    seen: Set[Tuple] = set()
    out: List[Finding] = []
    for f in sorted(
        fa.findings, key=lambda f: (f.path, f.line, f.rule, f.symbol)
    ):
        k = (f.rule, f.path, f.line, f.symbol)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def analyze_paths(paths: List[str], root: Optional[str] = None) -> List[Finding]:
    root = root or repo_root()
    out: List[Finding] = []
    for rel in paths:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            out.extend(analyze_source(rel, f.read()))
    return out


def run(root: Optional[str] = None) -> List[Finding]:
    """Analyze the runtime module set (the repo gate)."""
    return analyze_paths(list(RUNTIME_MODULES), root=root)
