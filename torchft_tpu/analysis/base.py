"""Shared plumbing for the static-analysis suite: findings, baseline.

One gate, one format: every analyzer (concurrency lint, wire drift, doc
drift) emits :class:`Finding` records; ``__main__`` merges them against the
checked-in baseline/suppression file and produces a single exit code.

A suppression matches findings by **key** (``rule:path:symbol`` — line
numbers deliberately excluded so routine edits don't churn the baseline).
Every entry must carry a ``reason`` and must still match at least one live
finding: an entry that no longer fires is *stale* and is itself an error,
so the baseline can only shrink or stay justified — never rot.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Finding", "Baseline", "repo_root", "DEFAULT_BASELINE"]


def repo_root() -> str:
    """The repository root (two levels above this package)."""
    return os.path.normpath(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
    )


DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


@dataclass
class Finding:
    """One analyzer hit.

    ``rule``  — stable rule id (e.g. ``lock-order-cycle``).
    ``path``  — repo-relative file path.
    ``line``  — 1-based line (0 for whole-file/catalog findings).
    ``symbol``— the offending symbol (function, attribute, constant name);
                part of the suppression key, so keep it stable.
    ``message``— human explanation, with enough detail to fix or justify.
    """

    rule: str
    path: str
    line: int
    symbol: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "key": self.key,
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.symbol}: {self.message}"


@dataclass
class Baseline:
    """Checked-in suppression file (``analysis/baseline.json``)."""

    suppressions: List[Dict[str, str]] = field(default_factory=list)
    path: Optional[str] = None

    @staticmethod
    def load(path: str) -> "Baseline":
        if not os.path.exists(path):
            return Baseline(path=path)
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        entries = doc.get("suppressions", [])
        for e in entries:
            if "key" not in e or "reason" not in e:
                raise ValueError(
                    f"baseline entry must carry 'key' and 'reason': {e}"
                )
        return Baseline(suppressions=entries, path=path)

    def save(self, path: Optional[str] = None) -> None:
        path = path or self.path
        assert path is not None
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {"suppressions": self.suppressions}, f, indent=2, sort_keys=True
            )
            f.write("\n")

    def apply(
        self, findings: List[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[Dict[str, str]]]:
        """Split findings into (active, suppressed) and return the stale
        suppression entries (keys that matched nothing — themselves
        errors, so dead baseline entries can't accumulate)."""
        keys = {e["key"] for e in self.suppressions}
        active = [f for f in findings if f.key not in keys]
        suppressed = [f for f in findings if f.key in keys]
        live = {f.key for f in suppressed}
        stale = [e for e in self.suppressions if e["key"] not in live]
        return active, suppressed, stale
