"""Doc/registry drift rules — the bidirectional catalog checks.

Ported from the former ``tests/test_tracing.py::TestCatalogDriftCheck``
into the analyzer so there is ONE gate and one baseline format for every
drift class (a thin pytest wrapper keeps them in tier-1):

``metric-catalog-drift``
    Every ``tft_*`` family documented in ``docs/observability.md`` exists
    in the live telemetry registry, and every registered family is
    documented.

``event-catalog-drift``
    The event-kind table in ``docs/observability.md`` matches
    ``telemetry.events.CANONICAL_EVENTS`` exactly.

``fault-site-doc-drift``
    The site catalog table in ``docs/fault_injection.md`` matches
    ``faultinject.core.SITES`` exactly (new in this PR — the site list
    had no doc gate before).

``premerge-gate-drift``
    The gate-id table under "Pre-merge gates" in
    ``docs/static_analysis.md`` matches the ``record_gate`` call sites
    in ``scripts/premerge.sh`` exactly, both directions (ISSUE 20 —
    the ``--json`` summary is only CI-assertable if the documented gate
    list can't rot).
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from torchft_tpu.analysis.base import Finding, repo_root

__all__ = ["run", "check_metric_catalog", "check_event_catalog",
           "check_fault_sites_doc", "check_premerge_gates"]


def _read(root: str, rel: str) -> str:
    with open(os.path.join(root, rel), encoding="utf-8") as f:
        return f.read()


def check_metric_catalog(doc_text: str, registry_names: set) -> List[Finding]:
    doc_names = set(re.findall(r"^\| `(tft_[a-z0-9_]+)`", doc_text, re.M))
    finds: List[Finding] = []
    if not doc_names:
        return [Finding(
            "metric-catalog-drift", "docs/observability.md", 0, "<table>",
            "metric catalog table not found",
        )]
    for n in sorted(doc_names - registry_names):
        finds.append(Finding(
            "metric-catalog-drift", "docs/observability.md", 0, n,
            "documented metric family is not registered",
        ))
    for n in sorted(registry_names - doc_names):
        finds.append(Finding(
            "metric-catalog-drift", "docs/observability.md", 0, n,
            "registered metric family is not documented in the catalog",
        ))
    return finds


def check_event_catalog(doc_text: str, canonical: tuple) -> List[Finding]:
    try:
        start = doc_text.index("Event kinds and fields:")
    except ValueError:
        return [Finding(
            "event-catalog-drift", "docs/observability.md", 0, "<table>",
            "event-kinds table not found",
        )]
    section = doc_text[start:]
    end = section.find("\n## ")
    if end >= 0:
        section = section[:end]
    doc_kinds = set(re.findall(r"^\| `([a-z0-9_]+)`", section, re.M))
    finds: List[Finding] = []
    for n in sorted(doc_kinds - set(canonical)):
        finds.append(Finding(
            "event-catalog-drift", "docs/observability.md", 0, n,
            "documented event kind missing from CANONICAL_EVENTS",
        ))
    for n in sorted(set(canonical) - doc_kinds):
        finds.append(Finding(
            "event-catalog-drift", "docs/observability.md", 0, n,
            "CANONICAL_EVENTS kind missing from the docs table",
        ))
    return finds


def check_fault_sites_doc(doc_text: str, sites: tuple) -> List[Finding]:
    try:
        start = doc_text.index("## Site catalog")
    except ValueError:
        return [Finding(
            "fault-site-doc-drift", "docs/fault_injection.md", 0, "<table>",
            "site catalog section not found",
        )]
    section = doc_text[start:]
    end = section.find("\n## ", 1)
    if end >= 0:
        section = section[:end]
    doc_sites = set(re.findall(r"^\| `([a-z_.]+)`", section, re.M))
    finds: List[Finding] = []
    for n in sorted(doc_sites - set(sites)):
        finds.append(Finding(
            "fault-site-doc-drift", "docs/fault_injection.md", 0, n,
            "documented injection site missing from faultinject.core.SITES",
        ))
    for n in sorted(set(sites) - doc_sites):
        finds.append(Finding(
            "fault-site-doc-drift", "docs/fault_injection.md", 0, n,
            "SITES entry missing from the docs site catalog",
        ))
    return finds


def check_premerge_gates(doc_text: str, script_text: str) -> List[Finding]:
    """Bidirectional: ``record_gate "<id>"`` sites in premerge.sh vs the
    "Pre-merge gates" table in docs/static_analysis.md."""
    script_gates = set(re.findall(
        r'^\s*record_gate "([a-z0-9-]+)"', script_text, re.M,
    ))
    finds: List[Finding] = []
    if not script_gates:
        return [Finding(
            "premerge-gate-drift", "scripts/premerge.sh", 0, "<script>",
            "no record_gate call sites found — --json summary is empty",
        )]
    try:
        start = doc_text.index("### Pre-merge gates")
    except ValueError:
        return [Finding(
            "premerge-gate-drift", "docs/static_analysis.md", 0, "<table>",
            "'Pre-merge gates' section not found",
        )]
    section = doc_text[start:]
    # anchor on the gate table itself (header row + separator + rows) —
    # other tables share the section's heading level downstream
    m = re.search(
        r"^\| gate \|.*\n\|[-| ]+\|\n((?:\|.*\n)+)", section, re.M,
    )
    if m is None:
        return [Finding(
            "premerge-gate-drift", "docs/static_analysis.md", 0, "<table>",
            "gate table (header '| gate |') not found under "
            "'Pre-merge gates'",
        )]
    doc_gates = set(re.findall(r"^\| `([a-z0-9-]+)`", m.group(1), re.M))
    for n in sorted(doc_gates - script_gates):
        finds.append(Finding(
            "premerge-gate-drift", "docs/static_analysis.md", 0, n,
            "documented gate id has no record_gate site in "
            "scripts/premerge.sh",
        ))
    for n in sorted(script_gates - doc_gates):
        finds.append(Finding(
            "premerge-gate-drift", "scripts/premerge.sh", 0, n,
            "record_gate id missing from the docs 'Pre-merge gates' table",
        ))
    return finds


def run(root: Optional[str] = None) -> List[Finding]:
    root = root or repo_root()
    from torchft_tpu import telemetry
    from torchft_tpu.faultinject.core import SITES
    from torchft_tpu.telemetry.events import CANONICAL_EVENTS

    obs = _read(root, "docs/observability.md")
    fi = _read(root, "docs/fault_injection.md")
    sa = _read(root, "docs/static_analysis.md")
    premerge = _read(root, os.path.join("scripts", "premerge.sh"))
    registry_names = {
        name for name in telemetry.REGISTRY.dump() if name.startswith("tft_")
    }
    out: List[Finding] = []
    out += check_metric_catalog(obs, registry_names)
    out += check_event_catalog(obs, CANONICAL_EVENTS)
    out += check_fault_sites_doc(fi, SITES)
    out += check_premerge_gates(sa, premerge)
    return out
