"""Reconfigurable collectives — the data plane across replica groups.

The reference's equivalent layer is torch.distributed ProcessGroups that can
be re-created with a new store/rank/world each quorum
(/root/reference/torchft/process_group.py). A TPU-native design splits the
data plane in two:

* **within** a replica group: a jax.sharding.Mesh + pjit/shard_map — XLA
  emits ICI collectives; nothing here to manage (see torchft_tpu.parallel).
* **across** replica groups: membership changes every quorum, so these
  collectives live *outside* jit on host buffers, keeping the compiled step
  function stable while the replica axis resizes. ``CollectivesTcp`` is that
  backend (the Gloo analogue, riding DCN); ops take/return numpy arrays and
  return ``Work`` handles like torch PGs do.

The ``configure(store_addr, rank, world_size)`` verb is the reconfiguration
point (process_group.py:224-239): it abandons the previous epoch's sockets
and re-rendezvouses through the epoch-prefixed store namespace
(``{store}/torchft/{quorum_id}/{rank}`` — manager.py:472).

Wrappers mirror the reference: ``CollectivesDummy`` (no-op backend used to
soak init ops and for tests, process_group.py:450-558),
``ErrorSwallowingCollectives`` (first error latches, later ops no-op until
reconfigure, process_group.py:561-654) and ``ManagedCollectives`` (routes
through a Manager, process_group.py:657-722).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from abc import ABC, abstractmethod
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from enum import Enum
from typing import Callable, Dict, List, Optional

import numpy as np

from torchft_tpu.faultinject.core import fault_point
from torchft_tpu.futures import Future
from torchft_tpu.store import create_store_client
from torchft_tpu.wire_codec import WireCodec, get_codec

logger = logging.getLogger(__name__)

__all__ = [
    "ReduceOp",
    "Work",
    "Collectives",
    "CollectivesTcp",
    "CollectivesDummy",
    "ErrorSwallowingCollectives",
    "ManagedCollectives",
    "PeerGoneError",
    "record_wire_stage",
    "wire_stage_snapshot",
]


# ---------------------------------------------------------------------------
# Per-stage wall-clock accounting for the cross-group wire plane
# (docs/wire_plane.md): host-copy / quantize / wire / dequantize-reduce.
# The crossgroup bench reads these to attribute its gb_per_sec deltas to a
# stage instead of reporting an unexplained total (the old
# pipelined_bf16_wire row's 8.4%-only delta was exactly such a mystery).
# Since ISSUE 8 both functions are thin shims over the step-anatomy
# ledger (telemetry/anatomy.py) — ONE source of truth, so the crossgroup
# stages_per_round_s and the bench step_anatomy row can never drift apart
# (the shim's old private accumulator dict is gone). The ledger mirrors
# every record into tft_wire_stage_seconds_total as before.
# ---------------------------------------------------------------------------

from torchft_tpu.telemetry.anatomy import (  # noqa: E402
    LEDGER as _ANATOMY_LEDGER,
    WIRE_STAGES,
)


def record_wire_stage(stage: str, seconds: float) -> None:
    """Accumulate wall-clock into a wire-plane stage bucket — a shim over
    ``telemetry.anatomy.LEDGER.record(..., wire_total=True)``; main-thread
    records additionally join the current step-anatomy row."""
    _ANATOMY_LEDGER.record(stage, seconds, wire_total=True)


def wire_stage_snapshot(reset: bool = False) -> Dict[str, float]:
    """Process-cumulative seconds per wire-plane stage; ``reset`` moves
    the snapshot mark (the ledger's totals and the telemetry counters
    stay monotonic)."""
    return _ANATOMY_LEDGER.wire_stage_snapshot(reset)


class PeerGoneError(ConnectionError):
    """A socket-level failure talking to a specific peer rank.

    Carries ``peer_rank`` so the Manager can map the ring rank back to a
    replica_id and file an ``lh.evict`` report — active dead-peer
    detection that beats the passive heartbeat-lease floor the reference
    shares (src/lighthouse.rs:119-128)."""

    def __init__(self, peer_rank: int, msg: str = "") -> None:
        super().__init__(msg or f"connection to peer {peer_rank} failed")
        self.peer_rank = peer_rank

    def __reduce__(self):  # survive pickling through the proxy backend
        return (PeerGoneError, (self.peer_rank, str(self)))


class ReduceOp(Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"


_REDUCE_FNS: Dict[ReduceOp, Callable[[np.ndarray, np.ndarray], np.ndarray]] = {
    ReduceOp.SUM: lambda a, b: np.add(a, b, out=a),
    ReduceOp.AVG: lambda a, b: np.add(a, b, out=a),  # divided at the end
    ReduceOp.MAX: lambda a, b: np.maximum(a, b, out=a),
    ReduceOp.MIN: lambda a, b: np.minimum(a, b, out=a),
}


class Work:
    """Async op handle (torch Work analogue)."""

    def __init__(self, fut: Future) -> None:
        self._fut = fut

    def wait(self, timeout: Optional[timedelta] = None):
        return self._fut.wait(timeout)

    def get_future(self) -> Future:
        return self._fut

    @staticmethod
    def completed(value=None) -> "Work":
        return Work(Future.completed(value))

    @staticmethod
    def failed(exc: BaseException) -> "Work":
        fut: Future = Future()
        fut.set_exception(exc)
        return Work(fut)


class Collectives(ABC):
    """Abstract reconfigurable collectives over a replica axis."""

    @abstractmethod
    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        """Tear down the previous epoch and rendezvous a fresh one. Safe to
        call repeatedly; each call fully replaces connectivity."""

    @abstractmethod
    def allreduce(self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        """In-place allreduce of each array; future resolves to the list."""

    @abstractmethod
    def allgather(self, arr: np.ndarray) -> Work:
        """Future resolves to a list of ``world_size`` arrays, rank order."""

    @abstractmethod
    def broadcast(self, arr: np.ndarray, root: int = 0) -> Work:
        """In-place broadcast from ``root``; future resolves to the array."""

    @abstractmethod
    def reduce_scatter(
        self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        """Reduce ``world_size`` per-rank inputs; future resolves to this
        rank's reduced shard (``arrays[rank]``-shaped)."""

    @abstractmethod
    def alltoall(self, arrays: List[np.ndarray]) -> Work:
        """Exchange ``arrays[j]`` to rank j; future resolves to the received
        list in rank order. Shapes may vary per slot but must be
        SYMMETRIC: this rank's ``arrays[j]`` shape must equal rank j's
        ``arrays[this_rank]`` shape (the receive buffer is sized from the
        local input for that slot)."""

    @abstractmethod
    def send(self, arr: np.ndarray, dst: int, tag: int = 0) -> Work: ...

    @abstractmethod
    def recv(self, arr: np.ndarray, src: int, tag: int = 0) -> Work:
        """In-place receive into ``arr``.

        Point-to-point ops run concurrently (a worker pool, not the
        ordered collective-op thread). Frames are matched by ``tag``, so
        several outstanding recvs from one peer are safe with *distinct*
        tags; two concurrent recvs on the SAME (src, tag) race for frames
        in unspecified order — serialize them with ``wait()`` or use
        per-message tags (see checkpointing/collectives_transport.py)."""

    @abstractmethod
    def barrier(self) -> Work: ...

    @abstractmethod
    def size(self) -> int: ...

    @abstractmethod
    def rank(self) -> int: ...

    def plane_info(self) -> str:
        """Transport label for dashboards/metrics; backends override with
        their live routing (e.g. CollectivesTcp: cma / tcp-striped /
        python-ring). Wrappers must delegate to the inner backend."""
        return type(self).__name__

    def wire_codec(self) -> str:
        """Name of the codec large f32 allreduces ride the wire with
        (``"f32"`` = exact). Lossy codecs ("bfloat16"/"int8") are what
        :class:`~torchft_tpu.wire_codec.ErrorFeedback` compensates for;
        wrappers must delegate to the inner backend."""
        return "f32"

    def shutdown(self) -> None:  # noqa: B027 — optional hook
        pass


# ---------------------------------------------------------------------------
# TCP backend
# ---------------------------------------------------------------------------

_HELLO_MAGIC = 0x7F7A0001
_FRAME_HDR = struct.Struct("<II")  # (tag, length) — tag catches desync bugs

# CMA fast path for LARGE p2p frames when the data-plane probe proved the
# peers same-host: instead of streaming the payload, the sender ships a
# 16-byte {addr, nbytes} descriptor (tag | _CMA_FLAG) and the receiver
# pulls the bytes straight out of the sender's address space
# (process_vm_readv), then acks (tag | _ACK_FLAG) so the sender may reuse
# the buffer. This is what lifts checkpoint heals and other big p2p
# transfers to memcpy-class speed on one host. The top two tag bits are
# reserved for the protocol — structurally safe: public send/recv mask
# user tags to 24 bits and every internal tag space tops out at
# 0x0DFFFFFF.
_CMA_FLAG = 0x80000000
_ACK_FLAG = 0x40000000
_CMA_DESC = struct.Struct("<QQ")  # (addr, nbytes)


def _env_int(name: str, default: int) -> int:
    """Guarded env knob parse: a typo'd deploy config must fall back, not
    crash the worker at construction."""
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r; using %d", name, raw, default)
        return default


def _cma_p2p_min() -> int:
    return _env_int("TORCHFT_CMA_P2P_MIN", 1 << 20)


# Buffers whose pull-ack never arrived. PROCESS-GLOBAL and never dropped:
# process_vm_readv needs no socket, so a peer that already holds the
# descriptor in its kernel recv buffer can pull long after this epoch's
# sockets closed — the memory must stay pinned for the process lifetime.
# Growth is bounded by ack-failure events (rare); size is logged so a
# pathological loop is operator-visible.
_CMA_QUARANTINE: List[np.ndarray] = []

# PROCESS-LOCAL latch: the negotiation probe only proves a read of the
# LEFT ring neighbor, but a passing vote arms direct pulls between
# ARBITRARY rank pairs (p2p sends >= TORCHFT_CMA_P2P_MIN, descriptor
# pulls in _recv_matched). If process_vm_readv permission is pairwise-
# asymmetric (differing uids, YAMA ptrace_scope) the probe ring can pass
# while a non-adjacent pull fails at op time — and since the negotiation
# would re-succeed identically every epoch, the group would retry into
# the same failure forever. A failed pull latches this flag; the next
# epoch's negotiation publishes ok=0 so the whole group settles on TCP.
_CMA_BROKEN = False


def _cma_pull(pid: int, addr: int, view: memoryview) -> None:
    """process_vm_readv the peer's [addr, addr+len) into ``view``."""
    global _CMA_BROKEN
    import errno

    from torchft_tpu._native import cma_read_into

    inj = fault_point("cma.pull", match=f"pid{pid}", wire=True,
                      nbytes=len(view))
    if inj is not None and inj.action in ("torn", "drop"):
        # torn read: fill only a prefix of the caller's buffer (what a
        # pull from a peer dying mid-op would leave behind), then fail
        # the stream loudly so the step latches instead of committing
        # the partial bytes
        k = int(len(view) * inj.frac) if inj.action == "torn" else 0
        if k:
            cma_read_into(pid, addr, view[:k])
        raise ConnectionError(
            f"fault injection: torn CMA pull ({k}/{len(view)} bytes "
            f"from pid {pid})"
        )
    try:
        cma_read_into(pid, addr, view)
    except OSError as e:
        # Permission-class failures only: the probe ring proved a read of
        # the LEFT neighbor, so EPERM/EACCES on another pair means the
        # permission matrix is pairwise-asymmetric and every epoch's
        # negotiation would re-arm the same broken path. ESRCH/EFAULT
        # from a peer that just DIED is the normal FT case — re-quorum
        # recovers it and CMA must stay available for the next cohort.
        if e.errno in (errno.EPERM, errno.EACCES):
            _CMA_BROKEN = True
            logger.warning(
                "CMA pull from pid %d denied (%s); latching CMA off — "
                "next reconfigure converges the group to TCP", pid, e,
            )
        raise


def _send_frame(sock: socket.socket, tag: int, payload: memoryview) -> None:
    sock.sendall(_FRAME_HDR.pack(tag, len(payload)))
    sock.sendall(payload)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    n = len(view)
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            raise ConnectionError("peer closed connection")
        got += k


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return buf


def _bytes_view(arr: np.ndarray) -> memoryview:
    """Byte-level view of an array (frame lengths are in bytes)."""
    arr = np.ascontiguousarray(arr)
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError):
        # ml_dtypes (bfloat16/fp8) reject the buffer protocol directly; a
        # uint8 reinterpret view of the same memory does not
        return memoryview(arr.view(np.uint8)).cast("B")


def _flat_view(arr: np.ndarray) -> np.ndarray:
    """Flat in-place view; in-place collectives need contiguous arrays."""
    v = arr.reshape(-1)
    if v.size and not np.shares_memory(v, arr):
        raise ValueError("in-place collectives require contiguous arrays")
    return v


def _corrupt_buffers(result: Any, frac: float) -> None:
    """``corrupt(frac)`` injection semantics at ``collective.complete``:
    silently perturb the leading ``frac`` of the first finished buffer's
    elements on THIS replica only (+1.0 — finite, so nothing downstream
    errors; the corruption is only observable as cross-group digest /
    checksum divergence, which is exactly the hole the commit-time
    divergence sentinel exists to close)."""
    arrays = result if isinstance(result, (list, tuple)) else [result]
    for arr in arrays:
        if isinstance(arr, np.ndarray) and arr.size:
            n = max(1, int(arr.size * frac))
            flat = arr.reshape(-1)
            flat[:n] += flat.dtype.type(1)
            return


class _Peer:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        # One lock per direction: an op may concurrently send to and receive
        # from the same peer (ring steps do exactly that).
        self.send_lock = threading.Lock()
        self.recv_lock = threading.Lock()
        # Tag-matched receive state: concurrent ops (pipelined checkpoint
        # buffers, overlapped p2p + ring traffic) may interleave frames on
        # one socket; the reader thread stashes frames for tags other ops
        # are waiting on instead of declaring a desync.
        self.cond = threading.Condition(self.recv_lock)
        self.stash: Dict[int, List[bytearray]] = {}
        self.stash_bytes = 0
        self.reader_busy = False
        self.recv_error: Optional[BaseException] = None


class CollectivesTcp(Collectives):
    """Cross-replica-group collectives over TCP (Gloo analogue).

    Full-duplex mesh built lazily: both sides publish listeners through the
    store; for the pair (i, j) the higher rank dials the lower. Ring
    algorithms (reduce-scatter + allgather) bound per-step traffic to
    ``2 * nbytes / world``.
    """

    def __init__(
        self,
        timeout: timedelta = timedelta(seconds=60),
        hostname: Optional[str] = None,
        wire_dtype: Optional[str] = None,
        p2p_workers: int = 8,
        stash_limit: int = 1 << 30,
        native_plane: Optional[bool] = None,
        dp_stripes: Optional[int] = None,
    ) -> None:
        """
        Args:
            wire_dtype: optional on-the-wire compression for float32
                allreduce — a codec name from
                :mod:`torchft_tpu.wire_codec`: ``"bfloat16"`` halves DCN
                bytes, ``"int8"`` quarters them (per-chunk scale factors);
                partial sums are re-quantized each hop, accumulation stays
                f32, and the decoded average is bit-identical on every
                rank by construction (the allgather phase forwards the
                chunk owner's wire bytes verbatim). Defaults to the
                ``TORCHFT_WIRE_CODEC`` env knob, else exact f32. Opt-in,
                like the reference's NCCL bf16 gradient comms; pair lossy
                codecs with error feedback (docs/wire_plane.md).
            p2p_workers: thread pool size for send/recv ops — point-to-point
                transfers (checkpoint fan-out to several healing replicas,
                windowed buffer pipelines) run concurrently, off the ordered
                collective-op thread. Tag matching keeps interleaved frames
                safe (:meth:`_recv_matched`).
            stash_limit: byte cap on frames parked for tags no local op is
                consuming — the desync tripwire.
            native_plane: route large f32 allreduces through the striped
                C++ data plane (native/dataplane.cc) — the NCCL-role fast
                path (process_group.py:431-447): GIL-free, N sockets per
                peer, wire codec in C++. Default on; override with env
                ``TORCHFT_NATIVE_PLANE=0``. MUST agree across ranks (a
                split group would wait on different sockets), so setup
                failures raise instead of falling back.
            dp_stripes: sockets per peer for the native plane (default 4,
                env ``TORCHFT_DP_STRIPES``).
        """
        import os as _os

        if native_plane is None:
            native_plane = _os.environ.get("TORCHFT_NATIVE_PLANE", "1") != "0"
        if dp_stripes is None:
            dp_stripes = _env_int("TORCHFT_DP_STRIPES", 4)
        self._native_plane = native_plane
        self._dp_stripes = max(1, dp_stripes)
        self._dp = None  # NativeDataPlane for the current epoch
        self._dp_cma_pids: Optional[List[int]] = None  # p2p CMA fast path
        self._cma_p2p_min = _cma_p2p_min()  # resolved once, not per frame
        self._death_watch_cb: Optional[Callable[[int, int], None]] = None
        self._timeout = timeout
        self._hostname = hostname or socket.gethostname()
        if wire_dtype is None:
            wire_dtype = _os.environ.get("TORCHFT_WIRE_CODEC") or None
        self._codec: WireCodec = get_codec(wire_dtype or None)
        # per-epoch wire scratch (grown monotonically, cleared on
        # teardown): the ring must never allocate per chunk per round
        self._scratch_bufs: Dict[str, np.ndarray] = {}
        self._p2p_workers = p2p_workers
        self._stash_limit = stash_limit
        self._rank = -1
        self._world = 0
        self._generation = 0
        self._peers: Dict[int, _Peer] = {}  # guarded-by: _peers_lock
        self._peers_lock = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._acceptor: Optional[threading.Thread] = None
        self._store = None
        self._executor: Optional[ThreadPoolExecutor] = None
        self._ring_send_worker: Optional[ThreadPoolExecutor] = None
        self._p2p: Optional[ThreadPoolExecutor] = None
        self._op_seq = 0

    # -- lifecycle --

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._teardown()  # bumps _generation, so stale acceptors are fenced
        self._rank = rank
        self._world = world_size
        # Tags order ops SPMD-style, so every member must restart the
        # sequence together; configure() is that barrier (a rejoining
        # replica starts at 0 while survivors would otherwise keep counting).
        self._op_seq = 0
        with self._peers_lock:
            gen = self._generation
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tft_coll"
        )
        self._ring_send_worker = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tft_ring_send"
        )
        self._p2p = ThreadPoolExecutor(
            max_workers=self._p2p_workers, thread_name_prefix="tft_p2p"
        )
        if world_size == 1:
            return

        self._store = create_store_client(store_addr, connect_timeout=self._timeout)
        listener = socket.socket(socket.AF_INET6, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("::", 0))
        listener.listen(64)
        self._listener = listener
        port = listener.getsockname()[1]
        self._store.set(f"coll/addr/{rank}", f"{self._hostname}:{port}")

        self._acceptor = threading.Thread(
            target=self._accept_loop, args=(listener, gen), daemon=True,
            name="tft_accept",
        )
        self._acceptor.start()
        # Eagerly establish the full mesh so configure() surfaces
        # connectivity failures (and later ops can't stall on dial).
        deadline = self._timeout
        for peer in range(world_size):
            if peer == rank:
                continue
            if peer < rank:
                self._dial(peer, deadline)
        # Wait for all higher ranks to dial us.
        self._wait_for_peers(set(range(rank + 1, world_size)))
        if self._native_plane:
            self._configure_dp(rank, world_size)
        if self._death_watch_cb is not None:
            threading.Thread(
                target=self._death_watch_loop,
                args=(gen,),
                daemon=True,
                name="tft_death_watch",
            ).start()

    def set_death_watch(self, cb: Callable[..., None]) -> None:
        """Register a peer-death callback, called ``cb(ring_rank, gen)``
        with the ring rank whose socket hit EOF/error and the plane
        generation whose ring that rank belongs to (pair it with
        :meth:`plane_generation` to drop callbacks that raced a
        reconfigure — the same ring rank means a different replica in a
        different epoch). Armed at the NEXT configure(). This is the
        active failure detector: a SIGKILLed peer's FIN reaches every
        survivor within milliseconds, long before their next collective op
        touches the socket — the callback lets the Manager evict and
        re-quorum DURING the doomed step instead of at its own step
        boundary. False positives (a peer tearing down an old epoch early)
        are safe: eviction is liveness-probe-guarded at the lighthouse."""
        self._death_watch_cb = cb

    def plane_generation(self) -> int:
        """Monotonic epoch counter, bumped by every configure()/teardown.
        Death-watch callbacks carry the generation they were armed for."""
        with self._peers_lock:
            return self._generation

    def _death_watch_loop(self, gen: int) -> None:
        import select

        # Poll cadence bounds detection latency, which bounds the
        # survivor's blackout: at the old 200 ms the 1-of-4 kill measured
        # ~1.7 steady steps of blackout with ~100 ms of it just waiting
        # for the next poll. 25 ms puts detection well under one toy
        # step; the idle cost (40 wakeups/s per plane) is negligible.
        poll_ms = _env_int("TORCHFT_DEATH_WATCH_POLL_MS", 25)
        poll_rdhup = getattr(select, "POLLRDHUP", 0x2000)
        poller = select.poll()
        with self._peers_lock:
            if gen != self._generation:
                return
            fds = {}
            for r, p in self._peers.items():
                try:
                    fd = p.sock.fileno()
                except OSError:
                    continue
                fds[fd] = r
        for fd in fds:
            poller.register(fd, select.POLLERR | select.POLLHUP | poll_rdhup)
        reported: set = set()
        while True:
            with self._peers_lock:
                if gen != self._generation:
                    return
            try:
                events = poller.poll(poll_ms)
            except OSError:
                return
            for fd, ev in events:
                if ev & select.POLLNVAL:
                    try:
                        poller.unregister(fd)
                    except (KeyError, OSError):
                        pass
                    continue
                rank = fds.get(fd)
                if rank is None or rank in reported:
                    continue
                reported.add(rank)
                try:
                    poller.unregister(fd)
                except (KeyError, OSError):
                    pass
                with self._peers_lock:
                    if gen != self._generation:
                        return
                cb = self._death_watch_cb
                if cb is not None:
                    try:
                        cb(rank, gen)
                    except Exception:  # noqa: BLE001
                        logger.exception("death-watch callback failed")

    def _configure_dp(self, rank: int, world_size: int) -> None:
        """Stand up the striped C++ gradient plane for this epoch. Same
        rendezvous shape as the Python mesh (store-published listeners,
        higher ranks dial lower); failures RAISE — every rank must land on
        the same plane or the group deadlocks across planes."""
        from torchft_tpu._native import NativeDataPlane

        import time as _time

        # ONE deadline for the whole data-plane rendezvous (store gets,
        # every peer's stripe dials, readiness, CMA negotiation): an
        # unreachable peer must cost one timeout budget, not
        # world × nstripes of them
        deadline = _time.monotonic() + self._timeout.total_seconds()

        def left() -> timedelta:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError("data-plane rendezvous deadline exceeded")
            return timedelta(seconds=remaining)

        dp = NativeDataPlane(rank, world_size, self._dp_stripes)
        self._dp_cma = False
        try:
            self._store.set(f"coll/dpaddr/{rank}", f"{self._hostname}:{dp.port}")
            for peer in range(rank):
                addr = self._store.get(
                    f"coll/dpaddr/{peer}", timeout=left()
                ).decode()
                host, port = addr.rsplit(":", 1)
                dp.connect(
                    peer, host, int(port), int(left().total_seconds() * 1000)
                )
            dp.wait_ready(int(left().total_seconds() * 1000))
            self._maybe_enable_cma(dp, rank, world_size, left)
        except BaseException:
            dp.close()
            raise
        self._dp = dp

    def _maybe_enable_cma(self, dp, rank: int, world_size: int, remaining) -> None:
        """Negotiate the one-copy CMA transport (process_vm_readv pulls —
        the NCCL intra-node SHM/P2P analogue). Every rank probes its LEFT
        ring neighbor with a token read (proving same pid namespace +
        ptrace policy, not just same hostname) and publishes the result;
        the mode flips on only when ALL ranks proved their read, keeping
        the ring homogeneous — a mixed ring would deadlock or, with bf16
        wire, break bitwise determinism. Opt out: TORCHFT_DP_CMA=0."""
        import ctypes as ct
        import os
        import secrets

        # An opted-out rank STILL publishes its keys (with ok="0"): peers
        # that did not opt out would otherwise block their whole rendezvous
        # deadline on keys that never appear, failing configure on every
        # epoch instead of settling on TCP in one round.
        # the broken-latch counts as an opt-out: this rank votes ok=0 so
        # the group-wide all-ok conjunction converges everyone to TCP
        opt_out = os.environ.get("TORCHFT_DP_CMA", "1") == "0" or _CMA_BROKEN
        if _CMA_BROKEN:
            logger.info(
                "CMA disabled this epoch: a prior pull failed in this "
                "process (pairwise-asymmetric process_vm_readv permission)"
            )
        from torchft_tpu._native import cma_read

        token = secrets.token_bytes(16)
        # keep the probe target alive for the epoch (peers read it remotely)
        self._dp_probe_buf = ct.create_string_buffer(token, 16)
        self._store.set(
            f"coll/dpcma/{rank}",
            f"{self._hostname}|{os.getpid()}|{token.hex()}"
            f"|{ct.addressof(self._dp_probe_buf)}",
        )
        left = (rank - 1) % world_size
        ok = False
        if not opt_out:
            try:
                ent = self._store.get(
                    f"coll/dpcma/{left}", timeout=remaining()
                ).decode()
                lhost, lpid, ltok, laddr = ent.split("|")
                if lhost == self._hostname:
                    ok = (
                        cma_read(int(lpid), int(laddr), 16)
                        == bytes.fromhex(ltok)
                    )
            except Exception as e:  # noqa: BLE001 — any failure means TCP
                logger.info(
                    "CMA probe of rank %d failed (%s); staying on TCP", left, e
                )
        self._store.set(f"coll/dpcmaok/{rank}", "1" if ok else "0")
        pids = []
        all_ok = True
        for p in range(world_size):
            flag = self._store.get(
                f"coll/dpcmaok/{p}", timeout=remaining()
            ).decode()
            ent = self._store.get(
                f"coll/dpcma/{p}", timeout=remaining()
            ).decode()
            pids.append(int(ent.split("|")[1]))
            all_ok = all_ok and flag == "1"
        if all_ok:
            dp.enable_cma(pids)
            self._dp_cma = True
            self._dp_cma_pids = pids  # arms the p2p CMA fast path too
            logger.info(
                "data plane: CMA transport enabled (%d ranks, one host)",
                world_size,
            )

    def plane_info(self) -> str:
        """Which transport carries large f32 allreduces this epoch:
        ``"cma"`` (one-copy process_vm_readv pulls), ``"tcp-striped"``
        (C++ multi-socket ring) or ``"python-ring"`` (fallback)."""
        if self._dp is None:
            return "python-ring"
        return "cma" if getattr(self, "_dp_cma", False) else "tcp-striped"

    def wire_codec(self) -> str:
        # the CMA transport pulls exact f32 out of the peer's memory, so
        # a configured lossy codec is bypassed there (docs/wire_plane.md)
        if self._dp is not None and getattr(self, "_dp_cma", False):
            return "f32"
        return self._codec.name

    def _epoch_scratch(self, dtype: np.dtype, nelems: int,
                       slot: str = "") -> np.ndarray:
        """Per-epoch reusable scratch (grown monotonically, torn down
        with the epoch): the old ring's ``astype`` per chunk per round
        allocated on the hot path."""
        key = f"{slot}:{np.dtype(dtype).str}"
        buf = self._scratch_bufs.get(key)
        if buf is None or buf.size < nelems:
            buf = np.empty(max(nelems, 1), dtype=dtype)
            self._scratch_bufs[key] = buf
        return buf[:nelems]

    def _wait_for_peers(self, expected: set) -> None:
        import time

        deadline = time.monotonic() + self._timeout.total_seconds()
        while True:
            with self._peers_lock:
                missing = expected - set(self._peers)
            if not missing:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(f"peers never connected: {sorted(missing)}")
            time.sleep(0.01)

    def _accept_loop(self, listener: socket.socket, gen: int) -> None:
        while True:
            try:
                sock, _ = listener.accept()
            except OSError:
                return  # listener closed by teardown
            try:
                # deadline BEFORE the hello too: a connected-but-silent
                # dialer (killed mid-handshake, port scanner) must not wedge
                # the acceptor thread past the op timeout
                sock.settimeout(self._timeout.total_seconds())
                hello = _recv_exact(sock, 8)
                magic, peer_rank = struct.unpack("<II", bytes(hello))
                if magic != _HELLO_MAGIC:
                    sock.close()
                    continue
            except Exception:
                sock.close()
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._peers_lock:
                if gen != self._generation:
                    sock.close()
                    return
                self._peers[peer_rank] = _Peer(sock)

    def _dial(self, peer: int, timeout: timedelta) -> None:
        addr = self._store.get(f"coll/addr/{peer}", timeout=timeout).decode()
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection(
            (host, int(port)), timeout=timeout.total_seconds()
        )
        # keep the op-timeout deadline on the connected socket (a dead peer
        # mid-ring must not wedge the op thread past self._timeout)
        sock.settimeout(self._timeout.total_seconds())
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall(struct.pack("<II", _HELLO_MAGIC, self._rank))
        with self._peers_lock:
            self._peers[peer] = _Peer(sock)

    def _teardown(self) -> None:
        # Order matters (round-1 review weak #2): fence stale acceptor
        # threads, then unblock any op thread stuck in a socket syscall
        # (shutdown() wakes a blocked recv/send; close() alone does not on
        # Linux), THEN join the executor so reconfigure never leaks a
        # wedged worker thread.
        with self._peers_lock:
            # the epoch ends HERE, not at the next configure(): an old
            # acceptor completing a handshake after this point must never
            # insert its socket into the next epoch's peer map
            self._generation += 1
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._peers_lock:
            for p in self._peers.values():
                try:
                    p.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    p.sock.close()
                except OSError:
                    pass
            self._peers.clear()
        if self._dp is not None:
            # before joining the executor: closing the plane's sockets
            # unblocks an op thread parked inside the native allreduce
            self._dp.close()
            self._dp = None
        self._dp_cma_pids = None
        if self._executor is not None:
            self._executor.shutdown(wait=True, cancel_futures=True)
            self._executor = None
        if self._ring_send_worker is not None:
            self._ring_send_worker.shutdown(wait=True, cancel_futures=True)
            self._ring_send_worker = None
        if self._p2p is not None:
            self._p2p.shutdown(wait=True, cancel_futures=True)
            self._p2p = None
        # after the executors have drained: no op thread can still be
        # writing through these views
        self._scratch_bufs.clear()
        if self._store is not None:
            self._store.close()
            self._store = None

    def shutdown(self) -> None:
        self._teardown()

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    # -- plumbing --

    def _peer(self, rank: int) -> _Peer:
        with self._peers_lock:
            p = self._peers.get(rank)
        if p is None:
            raise RuntimeError(f"no connection to peer {rank}")
        return p

    def _submit(self, fn: Callable, p2p: bool = False, op: str = "") -> Work:
        """Run ``fn`` async. Collective ops share ONE ordered thread (SPMD
        tag sequencing + natural per-bucket pipelining); point-to-point ops
        go to the p2p pool so transfers to/from different peers — and
        windowed buffer pipelines to one peer — overlap. Tag matching in
        :meth:`_recv_matched` keeps the interleaved frames safe."""
        executor = self._p2p if p2p else self._executor
        assert executor is not None, "configure() must be called first"
        out: Future = Future()

        def run() -> None:
            try:
                result = fn()
                if op:
                    # completion-side injection site: a delay here holds
                    # the op thread (stalling the ring like a wedged
                    # peer); an error fails the finished op before its
                    # future resolves; `corrupt` silently perturbs the
                    # finished buffers on THIS replica only — the
                    # divergence-sentinel adversary (no error surfaces,
                    # so without the commit-time digest compare the
                    # corrupt averages would commit)
                    inj = fault_point(
                        "collective.complete", match=op, rank=self._rank,
                        wire=True,
                    )
                    if inj is not None:
                        if inj.action == "corrupt":
                            _corrupt_buffers(result, inj.frac)
                        elif inj.action in ("drop", "torn"):
                            # no wire semantics for these here: degrade
                            # to error so a schedule can never silently
                            # no-op (delay/kill were already applied
                            # inline by fault_point — re-raising them
                            # would turn a stall into a failed op)
                            raise inj.make_exception()
                out.set_result(result)
            except BaseException as e:  # noqa: BLE001 — propagate via future
                out.set_exception(e)

        task = executor.submit(run)

        def on_done(t) -> None:
            # teardown cancels queued tasks whose run() never executes; the
            # caller's Work future must still resolve or a timeout-less
            # wait() would hang forever
            if t.cancelled() and not out.done():
                from torchft_tpu import telemetry

                telemetry.FUTURE_CANCELS.inc()
                out.set_exception(
                    RuntimeError("collectives reconfigured before op ran")
                )

        task.add_done_callback(on_done)
        return Work(out)

    def _send_to(self, rank: int, tag: int, data: memoryview) -> None:
        inj = fault_point(
            "rpc.send", match=f"peer{rank}", wire=True,
            tag=tag, nbytes=len(data), rank=self._rank,
        )
        if inj is not None:
            if inj.action == "drop":
                return  # silently unsent: the peer's recv hits its deadline
            if inj.action == "torn":
                self._torn_send(rank, tag, data, inj.frac)  # raises
        if (
            self._dp_cma_pids is not None
            and len(data) >= self._cma_p2p_min
            and not (tag & (_CMA_FLAG | _ACK_FLAG))
        ):
            self._send_cma(rank, tag, data)
            return
        p = self._peer(rank)
        try:
            with p.send_lock:
                _send_frame(p.sock, tag, data)
        except (ConnectionError, OSError) as e:
            if isinstance(e, (socket.timeout, TimeoutError)):
                raise  # slow-but-alive peer: latch the error, don't accuse
            raise PeerGoneError(rank, f"send to peer {rank} failed: {e}") from e

    def _torn_send(self, rank: int, tag: int, data: memoryview,
                   frac: float) -> None:
        """Fault-injection wire primitive: frame a FULL-length header,
        ship only ``frac`` of the payload, then hard-cut the socket —
        exactly what a peer dying mid-send leaves on the wire. The
        receiver must surface a mid-frame EOF (never half-filled data
        reported as success); this side latches like any dead-peer send."""
        p = self._peer(rank)
        k = int(len(data) * frac)
        try:
            with p.send_lock:
                p.sock.sendall(_FRAME_HDR.pack(tag, len(data)))
                if k:
                    p.sock.sendall(data[:k])
        finally:
            try:
                p.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        raise PeerGoneError(
            rank,
            f"fault injection: torn send to peer {rank} "
            f"({k}/{len(data)} bytes)",
        )

    def _send_cma(self, rank: int, tag: int, data: memoryview) -> None:
        """Ship a pull descriptor instead of the payload; the buffer must
        stay untouched until the peer's ack (awaited here) confirms the
        pull completed."""
        arr = np.frombuffer(data, dtype=np.uint8)
        desc = _CMA_DESC.pack(arr.ctypes.data, len(data))
        p = self._peer(rank)
        try:
            with p.send_lock:
                _send_frame(p.sock, tag | _CMA_FLAG, memoryview(desc))
        except (ConnectionError, OSError) as e:
            if isinstance(e, (socket.timeout, TimeoutError)):
                raise
            raise PeerGoneError(rank, f"send to peer {rank} failed: {e}") from e
        # the ack rides the normal tag-matched machinery (interleaves
        # safely with any concurrent traffic on this socket)
        try:
            self._recv_from(rank, tag | _ACK_FLAG)
        except BaseException as e:
            # ANY failure to observe the ack leaves the descriptor
            # DANGLING: the peer may still pull that address later (it
            # needs no socket for the pull, only the 16 descriptor bytes
            # it may already hold). Letting the caller reuse/free the
            # memory would hand the peer silently corrupt bytes — the TCP
            # path streamed a copy and never had this hazard. Pin the
            # buffer for the PROCESS lifetime and poison the stream so
            # both sides reconfigure.
            _CMA_QUARANTINE.append(arr)
            q_bytes = sum(a.nbytes for a in _CMA_QUARANTINE)
            logger.warning(
                "CMA pull-ack from peer %d failed (%s); buffer quarantined "
                "(%d buffers, %.1f MB pinned process-wide)",
                rank, e, len(_CMA_QUARANTINE), q_bytes / 1e6,
            )
            with p.cond:
                p.recv_error = e
                p.cond.notify_all()
            try:
                p.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            if isinstance(e, TimeoutError):
                raise ConnectionError(
                    f"CMA pull-ack from peer {rank} timed out; epoch "
                    f"poisoned (descriptor quarantined)"
                ) from e
            raise
        del arr  # keep the source buffer alive until the ack

    def _recv_from(
        self, rank: int, tag: int, into: Optional[memoryview] = None
    ) -> Optional[bytearray]:
        """Tag-matched receive. With ``into``, a frame of exactly
        ``len(into)`` bytes is received straight into the caller's buffer
        (zero-copy) and None is returned; otherwise the frame bytes are
        returned."""
        p = self._peer(rank)
        try:
            fault_point(
                "rpc.recv", match=f"peer{rank}", tag=tag, rank=self._rank,
            )
            return self._recv_matched(p, rank, tag, into)
        except (ConnectionError, OSError) as e:
            if isinstance(e, (socket.timeout, TimeoutError)):
                raise  # slow-but-alive peer: latch the error, don't accuse
            raise PeerGoneError(rank, f"recv from peer {rank} failed: {e}") from e

    def _recv_matched(
        self, p: _Peer, rank: int, tag: int, into: Optional[memoryview]
    ) -> Optional[bytearray]:
        """Core of the concurrent-safe receive path.

        Several ops may receive from the same peer at once (pipelined
        checkpoint buffers, p2p overlapping ring traffic); frames for one op
        must not be consumed by another. One thread at a time becomes the
        socket reader; frames for other tags are parked in the peer's stash
        and their waiters notified. A hard stash cap keeps a genuine desync
        (a tag nobody will ever wait for) loud instead of an unbounded leak.
        """
        import time

        deadline = time.monotonic() + self._timeout.total_seconds()
        while True:
            with p.cond:
                while True:
                    if p.recv_error is not None:
                        # preserve the reader's error *class*: a timeout
                        # must stay a timeout for waiters too, or a slow-
                        # but-alive peer gets accused via PeerGoneError
                        if isinstance(
                            p.recv_error, (socket.timeout, TimeoutError)
                        ):
                            raise TimeoutError(
                                f"receive stream timed out: {p.recv_error!r}"
                            ) from p.recv_error
                        raise ConnectionError(
                            f"receive stream broken: {p.recv_error!r}"
                        ) from p.recv_error
                    q = p.stash.get(tag)
                    if q:
                        if into is not None and len(into) != len(q[0]):
                            # leave the frame stashed: another (correctly
                            # sized) recv may still claim it
                            raise RuntimeError(
                                f"tag {tag:#x}: frame is {len(q[0])} bytes, "
                                f"recv buffer is {len(into)}"
                            )
                        data = q.pop(0)
                        if not q:
                            del p.stash[tag]
                        p.stash_bytes -= len(data)
                        if into is not None:
                            into[:] = data
                            return None
                        return data
                    if not p.reader_busy:
                        p.reader_busy = True
                        break  # we read the socket
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"recv tag {tag:#x} timed out waiting for reader; "
                            f"stashed tags: {sorted(map(hex, p.stash))}"
                        )
                    p.cond.wait(remaining)
            got_tag = -1
            filled = False
            data = None
            try:
                hdr = _recv_exact(p.sock, _FRAME_HDR.size)
                got_tag, length = _FRAME_HDR.unpack(bytes(hdr))
                if got_tag & _CMA_FLAG:
                    # pull descriptor: fetch the payload from the sender's
                    # address space, then ack so it may reuse the buffer
                    got_tag &= ~_CMA_FLAG
                    desc = _recv_exact(p.sock, length)
                    addr, nbytes = _CMA_DESC.unpack(bytes(desc))
                    pids = self._dp_cma_pids  # teardown may None the field
                    if pids is None:
                        raise ConnectionError(
                            "CMA descriptor arrived during teardown"
                        )
                    pid = pids[rank]
                    if (
                        got_tag == tag
                        and into is not None
                        and len(into) == nbytes
                    ):
                        _cma_pull(pid, addr, into)
                        filled = True
                    else:
                        data = bytearray(nbytes)
                        _cma_pull(pid, addr, memoryview(data))
                    self._send_to(rank, got_tag | _ACK_FLAG, memoryview(b""))
                elif got_tag == tag and into is not None and len(into) == length:
                    _recv_exact_into(p.sock, into)
                    filled = True
                else:
                    data = _recv_exact(p.sock, length)
            except BaseException as e:
                with p.cond:
                    p.reader_busy = False
                    # the stream position is now undefined (possibly mid-
                    # frame): the epoch is poisoned until reconfigure
                    p.recv_error = e
                    p.cond.notify_all()
                raise
            with p.cond:
                p.reader_busy = False
                if got_tag == tag:
                    if into is not None and not filled:
                        # size mismatch: stash the frame (a correctly sized
                        # recv may claim it) and fail loudly
                        p.stash.setdefault(got_tag, []).append(data)
                        p.stash_bytes += len(data)
                        p.cond.notify_all()
                        raise RuntimeError(
                            f"tag {tag:#x}: frame is {len(data)} bytes, "
                            f"recv buffer is {len(into)}"
                        )
                    p.cond.notify_all()
                    return None if filled else data
                p.stash.setdefault(got_tag, []).append(data)
                p.stash_bytes += len(data)
                over = p.stash_bytes > self._stash_limit
                p.cond.notify_all()
                if over:
                    raise RuntimeError(
                        f"collective desync: {p.stash_bytes} bytes stashed "
                        f"while waiting for tag {tag:#x}; stashed tags "
                        f"{sorted(map(hex, p.stash))}"
                    )

    def _exchange(
        self,
        dst: int,
        send_data: memoryview,
        src: int,
        tag: int,
        into: Optional[memoryview] = None,
    ) -> Optional[bytearray]:
        """Simultaneously send to dst and receive from src (ring step) —
        the send runs on a persistent helper worker so large transfers
        can't deadlock on full OS socket buffers (round-3 review weak #4:
        a fresh Thread per hop burned hundreds of creations per step on
        the GIL; collective ops are serialized on the op thread, so ONE
        worker suffices). With ``into``, the frame lands directly in the
        caller's scratch buffer (no per-hop allocation)."""
        send_fut = self._ring_send_worker.submit(self._send_to, dst, tag, send_data)
        recv_exc: Optional[BaseException] = None
        data = None
        try:
            data = self._recv_from(src, tag, into=into)
        except BaseException as e:  # noqa: BLE001
            recv_exc = e
            # the epoch is doomed either way (a failed hop latches the
            # step and forces a flush re-quorum): unwedge a send parked on
            # a full buffer so the drain below doesn't stall recovery for
            # the full socket timeout
            try:
                self._peer(dst).sock.shutdown(socket.SHUT_RDWR)
            except Exception:  # noqa: BLE001
                pass
        send_exc: Optional[BaseException] = None
        try:
            send_fut.result()
        except BaseException as e:  # noqa: BLE001
            send_exc = e
        if recv_exc is not None:
            # prefer the ACCUSING error: a PeerGone names the dead peer
            # for eviction, a bare timeout does not
            if isinstance(send_exc, PeerGoneError) and not isinstance(
                recv_exc, PeerGoneError
            ):
                raise send_exc from recv_exc
            raise recv_exc
        if send_exc is not None:
            raise send_exc
        return data

    def _next_tag(self) -> int:
        self._op_seq = (self._op_seq + 1) & 0x00FFFFFF
        return self._op_seq

    def _count_op(self, op_name: str, nbytes: int = 0, tag: int = 0) -> int:
        """Count the op and record its issue in the flight recorder;
        returns the flight sequence id for completion marking."""
        from torchft_tpu import telemetry

        fault_point(
            "collective.issue", match=op_name,
            nbytes=nbytes, tag=tag, rank=self._rank,
        )
        plane = self.plane_info()
        telemetry.COLLECTIVE_OPS.labels(op=op_name, plane=plane).inc()
        return telemetry.FLIGHT.record_issue(
            op_name, plane, nbytes, tag=tag, rank=self._rank
        )

    def _track_flight(self, work: Work, fid: int) -> Work:
        """Mark the flight record completed/failed when the op resolves."""
        from torchft_tpu import telemetry

        work.get_future().then(
            lambda f: telemetry.FLIGHT.record_complete(fid, error=f.exception())
        )
        return work

    # -- collectives (all run on the op thread, SPMD-ordered) --

    def allreduce(self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        world, rank = self._world, self._rank
        tag = self._next_tag() | 0x01000000
        nbytes = sum(int(a.nbytes) for a in arrays)
        # counted at submission like every other op (uniform semantics);
        # bytes + latency are recorded at completion in run()
        fid = self._count_op("allreduce", nbytes, tag)

        def run() -> List[np.ndarray]:
            import time

            from torchft_tpu import telemetry

            t0 = time.perf_counter()
            if world > 1:
                # ops are serialized on the op thread, so arrays of one
                # allreduce may share the tag (it is a desync check, not a
                # demultiplexer; the native plane offsets per-stripe)
                for arr in arrays:
                    if self._dp_eligible(arr):
                        self._dp_allreduce(arr, op, tag)
                    else:
                        self._ring_allreduce(arr, op, tag)
                        if op == ReduceOp.AVG:
                            np.divide(arr, world, out=arr)
            telemetry.record_collective(
                "allreduce", nbytes, time.perf_counter() - t0,
                self.plane_info(), count_op=False,
            )
            return arrays

        return self._track_flight(self._submit(run, op="allreduce"), fid)

    def _dp_eligible(self, arr: np.ndarray) -> bool:
        if (
            self._dp is None
            or arr.dtype != np.float32
            or not arr.flags["C_CONTIGUOUS"]
        ):
            return False
        # the codec-name → DpCodec map lives ONCE, on the binding
        # (NativeDataPlane.CODEC); a Python-only codec with no native
        # twin keeps the Python ring so the compression contract holds
        from torchft_tpu._native import NativeDataPlane

        return self._codec.name in NativeDataPlane.CODEC

    def _dp_allreduce(self, arr: np.ndarray, op: ReduceOp, tag: int) -> None:
        """Hot path: the striped C++ ring (AVG divides natively; the wire
        codec — bf16 or int8 — runs in C++, with the same owner-bytes
        verbatim allgather as the Python ring so the decoded average is
        bit-identical on every rank)."""
        import time as _time

        from torchft_tpu._native import DataPlaneError

        dp = self._dp  # teardown may None the field mid-op
        if dp is None:
            raise RuntimeError("data plane torn down")
        t0 = _time.perf_counter()
        try:
            dp.allreduce(
                arr.ctypes.data,
                arr.size,
                op.value,
                self._codec.name,  # resolved via NativeDataPlane.CODEC
                tag,
                int(self._timeout.total_seconds() * 1000),
            )
        except DataPlaneError as e:
            if e.peer_rank >= 0:
                raise PeerGoneError(e.peer_rank, str(e)) from e
            raise
        finally:
            # codec work happens inside the C++ stripe workers and is not
            # separable from here; the whole native op lands in "wire"
            record_wire_stage("wire", _time.perf_counter() - t0)

    def _ring_allreduce(self, arr: np.ndarray, op: ReduceOp, tag: int) -> None:
        world, rank = self._world, self._rank
        right = (rank + 1) % world
        left = (rank - 1) % world
        reduce_fn = _REDUCE_FNS[op]

        flat = _flat_view(arr)
        bounds = np.linspace(0, flat.size, world + 1).astype(np.int64)
        chunks = [flat[bounds[i] : bounds[i + 1]] for i in range(world)]
        max_elems = max((int(c.size) for c in chunks), default=0)

        # optional lossy wire codec (f32 → bf16/int8 on the wire, f32
        # accumulation locally; wire_codec.py): 2-4x fewer DCN bytes/hop
        codec = self._codec
        lossy = codec.lossy and arr.dtype == np.float32 and flat.size > 0
        if lossy:
            self._ring_allreduce_codec(
                arr, op, tag, chunks, max_elems, reduce_fn
            )
            return

        import time as _time

        scratch = self._epoch_scratch(arr.dtype, max_elems)
        t_wire = 0.0

        # reduce-scatter phase
        for step in range(world - 1):
            send_idx = (rank - step) % world
            recv_idx = (rank - step - 1) % world
            n = int(chunks[recv_idx].size)
            view = scratch[:n]
            t0 = _time.perf_counter()
            self._exchange(
                right, _bytes_view(chunks[send_idx]), left, tag,
                into=_bytes_view(view),
            )
            t_wire += _time.perf_counter() - t0
            reduce_fn(chunks[recv_idx], view.reshape(chunks[recv_idx].shape))
        # allgather phase (raw bytes: every rank forwards the owner's
        # exact bytes, so the result is bitwise identical by construction)
        for step in range(world - 1):
            send_idx = (rank + 1 - step) % world
            recv_idx = (rank - step) % world
            n = int(chunks[recv_idx].size)
            view = scratch[:n]
            t0 = _time.perf_counter()
            self._exchange(
                right, _bytes_view(chunks[send_idx]), left, tag,
                into=_bytes_view(view),
            )
            t_wire += _time.perf_counter() - t0
            chunks[recv_idx][:] = view.reshape(chunks[recv_idx].shape)
        record_wire_stage("wire", t_wire)

    def _ring_allreduce_codec(
        self, arr: np.ndarray, op: ReduceOp, tag: int,
        chunks: List[np.ndarray], max_elems: int, reduce_fn,
    ) -> None:
        """Lossy-codec ring. Reduce-scatter ships freshly encoded partial
        sums per hop (re-quantized at each hop's own magnitude, residual
        handled one level up by error feedback); the allgather phase then
        forwards the chunk OWNER's wire bytes verbatim — decode work per
        rank, zero re-encode work, and bit-identity of the decoded average
        on every rank by construction rather than by fp-rounding luck."""
        import time as _time

        world, rank = self._world, self._rank
        right = (rank + 1) % world
        left = (rank - 1) % world
        codec = self._codec
        codec.ensure_capacity(max_elems)
        max_wire = codec.wire_nbytes(max_elems)
        # double buffer: at each allgather hop one holds the bytes being
        # forwarded while the other receives the next chunk's bytes
        buf_a = self._epoch_scratch(np.uint8, max_wire, slot="wireA")
        buf_b = self._epoch_scratch(np.uint8, max_wire, slot="wireB")
        t_quant = t_wire = t_dq = 0.0

        # reduce-scatter phase
        for step in range(world - 1):
            send_idx = (rank - step) % world
            recv_idx = (rank - step - 1) % world
            n = int(chunks[recv_idx].size)
            rn = codec.wire_nbytes(n)
            t0 = _time.perf_counter()
            sv = codec.encode_into(chunks[send_idx])
            t1 = _time.perf_counter()
            rv = buf_a[:rn]
            self._exchange(right, sv, left, tag, into=_bytes_view(rv))
            t2 = _time.perf_counter()
            incoming = codec.decode_tmp(rv, n)
            reduce_fn(
                chunks[recv_idx], incoming.reshape(chunks[recv_idx].shape)
            )
            t3 = _time.perf_counter()
            t_quant += t1 - t0
            t_wire += t2 - t1
            t_dq += t3 - t2

        # the owner of each fully reduced chunk encodes it ONCE; those
        # bytes circulate verbatim, and the owner itself keeps the decode
        # of its own bytes — every rank ends with the identical f32 image
        t0 = _time.perf_counter()
        owned = chunks[(rank + 1) % world]
        ow = codec.encode_into(owned)
        cur = buf_b[: len(ow)]
        cur[:] = np.frombuffer(ow, dtype=np.uint8)
        codec.decode_into(cur, owned)
        t_quant += _time.perf_counter() - t0

        # allgather phase: forward received wire bytes untouched
        bufs = (buf_a, buf_b)
        cur_view: np.ndarray = cur
        cur_i = 1  # cur lives in buf_b; buf_a is free to receive into
        for step in range(world - 1):
            recv_idx = (rank - step) % world
            n = int(chunks[recv_idx].size)
            rn = codec.wire_nbytes(n)
            rv = bufs[1 - cur_i][:rn]
            t0 = _time.perf_counter()
            self._exchange(
                right, _bytes_view(cur_view), left, tag, into=_bytes_view(rv)
            )
            t1 = _time.perf_counter()
            codec.decode_into(rv, chunks[recv_idx])
            t_dq += _time.perf_counter() - t1
            t_wire += t1 - t0
            # rv is next hop's outgoing frame; the old cur buffer is free
            cur_view, cur_i = rv, 1 - cur_i
        record_wire_stage("quantize", t_quant)
        record_wire_stage("wire", t_wire)
        record_wire_stage("dequant_reduce", t_dq)

    def allgather(self, arr: np.ndarray) -> Work:
        world, rank = self._world, self._rank
        tag = self._next_tag() | 0x02000000
        fid = self._count_op("allgather", int(arr.nbytes), tag)

        def run() -> List[np.ndarray]:
            out: List[Optional[np.ndarray]] = [None] * world
            out[rank] = arr.copy()
            if world > 1:
                right, left = (rank + 1) % world, (rank - 1) % world
                cur = np.ascontiguousarray(arr)
                cur_idx = rank
                for _ in range(world - 1):
                    data = self._exchange(right, _bytes_view(cur), left, tag)
                    cur_idx = (cur_idx - 1) % world
                    cur = np.frombuffer(data, dtype=arr.dtype).reshape(arr.shape).copy()
                    out[cur_idx] = cur
            return out  # type: ignore[return-value]

        return self._track_flight(self._submit(run, op="allgather"), fid)

    def broadcast(self, arr: np.ndarray, root: int = 0) -> Work:
        world, rank = self._world, self._rank
        tag = self._next_tag() | 0x03000000
        fid = self._count_op("broadcast", int(arr.nbytes), tag)

        def run() -> np.ndarray:
            if world > 1:
                if rank == root:
                    data = _bytes_view(arr)
                    for peer in range(world):
                        if peer != rank:
                            self._send_to(peer, tag, data)
                else:
                    data = self._recv_from(root, tag)
                    _flat_view(arr)[:] = np.frombuffer(data, dtype=arr.dtype)
            return arr

        return self._track_flight(self._submit(run, op="broadcast"), fid)

    def reduce_scatter(
        self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        world, rank = self._world, self._rank
        if len(arrays) != world:
            raise ValueError(f"reduce_scatter needs {world} inputs, got {len(arrays)}")
        tag = self._next_tag() | 0x04000000
        fid = self._count_op(
            "reduce_scatter", sum(int(a.nbytes) for a in arrays), tag
        )
        reduce_fn = _REDUCE_FNS[op]

        def run() -> np.ndarray:
            if world == 1:
                acc = arrays[0].copy()
            else:
                # Same schedule as the allreduce reduce-scatter phase: rank r
                # fully owns slot (r+1)%world afterwards, so permute inputs
                # one step (slot i holds input (i-1)%world) to make each rank
                # end up with the reduction of its *own* input index.
                right, left = (rank + 1) % world, (rank - 1) % world
                local = [
                    np.ascontiguousarray(arrays[(i - 1) % world]).copy()
                    for i in range(world)
                ]
                for step in range(world - 1):
                    send_idx = (rank - step) % world
                    recv_idx = (rank - step - 1) % world
                    data = self._exchange(
                        right, _bytes_view(local[send_idx]), left, tag
                    )
                    incoming = np.frombuffer(data, dtype=local[recv_idx].dtype)
                    reduce_fn(local[recv_idx], incoming.reshape(local[recv_idx].shape))
                acc = local[(rank + 1) % world]
            if op == ReduceOp.AVG:
                np.divide(acc, world, out=acc)
            return acc

        return self._track_flight(self._submit(run, op="reduce_scatter"), fid)

    def alltoall(self, arrays: List[np.ndarray]) -> Work:
        world, rank = self._world, self._rank
        if len(arrays) != world:
            raise ValueError(f"alltoall needs {world} inputs, got {len(arrays)}")
        tag = self._next_tag() | 0x05000000
        fid = self._count_op(
            "alltoall", sum(int(a.nbytes) for a in arrays), tag
        )

        def run() -> List[np.ndarray]:
            out: List[Optional[np.ndarray]] = [None] * world
            out[rank] = arrays[rank].copy()
            # Rotation schedule: round r sends to rank+r while receiving
            # from rank-r (full duplex), which is deadlock-free for any
            # world size — a pairwise send-then-recv ordering is not.
            for r in range(1, world):
                dst = (rank + r) % world
                src = (rank - r) % world
                data = self._exchange(dst, _bytes_view(arrays[dst]), src, tag)
                out[src] = (
                    np.frombuffer(data, dtype=arrays[src].dtype)
                    .reshape(arrays[src].shape)
                    .copy()
                )
            return out  # type: ignore[return-value]

        return self._track_flight(self._submit(run, op="alltoall"), fid)

    def send(self, arr: np.ndarray, dst: int, tag: int = 0) -> Work:
        wire_tag = 0x06000000 | (tag & 0xFFFFFF)
        fid = self._count_op("send", int(arr.nbytes), wire_tag)

        def run() -> None:
            self._send_to(dst, wire_tag, _bytes_view(arr))

        return self._track_flight(self._submit(run, p2p=True, op="send"), fid)

    def recv(self, arr: np.ndarray, src: int, tag: int = 0) -> Work:
        wire_tag = 0x06000000 | (tag & 0xFFFFFF)
        fid = self._count_op("recv", int(arr.nbytes), wire_tag)

        def run() -> np.ndarray:
            _flat_view(arr)  # contiguity check up front, like the old path
            done = self._recv_from(src, wire_tag, into=_bytes_view(arr))
            assert done is None, "into-receive must fill in place"
            return arr

        return self._track_flight(self._submit(run, p2p=True, op="recv"), fid)

    def barrier(self) -> Work:
        token = np.zeros(1, dtype=np.int32)
        world = self._world
        tag = self._next_tag() | 0x07000000
        fid = self._count_op("barrier", 0, tag)

        def run() -> None:
            if world > 1:
                self._ring_allreduce(token, ReduceOp.SUM, tag)

        return self._track_flight(self._submit(run, op="barrier"), fid)


# ---------------------------------------------------------------------------
# Wrappers
# ---------------------------------------------------------------------------


class CollectivesDummy(Collectives):
    """No-op backend: every op completes immediately with identity results
    (ProcessGroupDummy analogue, process_group.py:450-558)."""

    def __init__(self, rank: int = 0, world_size: int = 1) -> None:
        self._rank = rank
        self._world = world_size
        self.configure_count = 0

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._rank, self._world = rank, world_size
        self.configure_count += 1

    def allreduce(self, arrays, op=ReduceOp.SUM):
        return Work.completed(arrays)

    def allgather(self, arr):
        return Work.completed([arr.copy() for _ in range(self._world)])

    def broadcast(self, arr, root=0):
        return Work.completed(arr)

    def reduce_scatter(self, arrays, op=ReduceOp.SUM):
        return Work.completed(arrays[self._rank].copy())

    def alltoall(self, arrays):
        return Work.completed([a.copy() for a in arrays])

    def send(self, arr, dst, tag=0):
        return Work.completed(None)

    def recv(self, arr, src, tag=0):
        return Work.completed(arr)

    def barrier(self):
        return Work.completed(None)

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank


class ErrorSwallowingCollectives(Collectives):
    """First error latches; subsequent ops are no-ops until the next
    configure() (ErrorSwallowingProcessGroupWrapper analogue,
    process_group.py:561-654). Keeps a failed replica from hanging its
    whole group mid-step — the Manager discards the step at commit time."""

    def __init__(self, inner: Collectives) -> None:
        self._inner = inner
        self._error: Optional[Exception] = None

    @property
    def device_arrays(self) -> bool:
        return bool(getattr(self._inner, "device_arrays", False))

    def error(self) -> Optional[Exception]:
        return self._error

    def plane_info(self) -> str:
        return self._inner.plane_info()

    def wire_codec(self) -> str:
        return self._inner.wire_codec()

    def report_error(self, e: Exception) -> None:
        self._error = e

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._error = None
        self._inner.configure(store_addr, rank, world_size)

    def _guard(self, fn: Callable[[], Work], default) -> Work:
        if self._error is not None:
            return Work.completed(default)
        try:
            work = fn()
        except Exception as e:
            self.report_error(e)
            return Work.completed(default)

        def swallow(fut: Future):
            exc = fut.exception()
            if exc is not None and self._error is None:
                logger.exception("collective failed; latching error: %s", exc)
                self.report_error(
                    exc if isinstance(exc, Exception) else RuntimeError(str(exc))
                )
                return default
            return fut.value() if exc is None else default

        return Work(work.get_future().then(swallow))

    def allreduce(self, arrays, op=ReduceOp.SUM):
        return self._guard(lambda: self._inner.allreduce(arrays, op), arrays)

    def allgather(self, arr):
        return self._guard(
            lambda: self._inner.allgather(arr),
            [arr.copy() for _ in range(max(1, self._inner.size()))],
        )

    def broadcast(self, arr, root=0):
        return self._guard(lambda: self._inner.broadcast(arr, root), arr)

    def reduce_scatter(self, arrays, op=ReduceOp.SUM):
        return self._guard(
            lambda: self._inner.reduce_scatter(arrays, op), arrays[0].copy()
        )

    def alltoall(self, arrays):
        return self._guard(lambda: self._inner.alltoall(arrays), arrays)

    def send(self, arr, dst, tag=0):
        return self._guard(lambda: self._inner.send(arr, dst, tag), None)

    def recv(self, arr, src, tag=0):
        return self._guard(lambda: self._inner.recv(arr, src, tag), arr)

    def barrier(self):
        return self._guard(lambda: self._inner.barrier(), None)

    def size(self) -> int:
        return self._inner.size()

    def rank(self) -> int:
        return self._inner.rank()

    def shutdown(self) -> None:
        self._inner.shutdown()


class ManagedCollectives(Collectives):
    """Routes allreduce through a Manager so quorum waits, healing zeros and
    error reporting apply (ManagedProcessGroup analogue,
    process_group.py:657-722). ``size()`` reports the *participating* world
    size, which is how dynamic membership stays invisible to user code."""

    def __init__(self, manager) -> None:
        self._manager = manager

    def wire_codec(self) -> str:
        return self._manager.wire_codec()

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        raise RuntimeError("ManagedCollectives is configured by its Manager")

    def allreduce(self, arrays, op=ReduceOp.SUM):
        if len(arrays) != 1:
            raise ValueError("ManagedCollectives.allreduce takes a single array")
        return Work(self._manager.allreduce(arrays[0]))

    def allgather(self, arr):
        raise NotImplementedError("only allreduce is managed")

    def broadcast(self, arr, root=0):
        raise NotImplementedError("only allreduce is managed")

    def reduce_scatter(self, arrays, op=ReduceOp.SUM):
        raise NotImplementedError("only allreduce is managed")

    def alltoall(self, arrays):
        raise NotImplementedError("only allreduce is managed")

    def send(self, arr, dst, tag=0):
        raise NotImplementedError("only allreduce is managed")

    def recv(self, arr, src, tag=0):
        raise NotImplementedError("only allreduce is managed")

    def barrier(self):
        raise NotImplementedError("only allreduce is managed")

    def size(self) -> int:
        return self._manager.num_participants()

    def rank(self) -> int:
        rank = self._manager.participating_rank()
        return rank if rank is not None else 0
