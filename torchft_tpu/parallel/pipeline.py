"""Pipeline parallelism: a GPipe-style microbatched ring over the ``pp``
mesh axis.

The reference has no pipeline support (SURVEY.md §2.3 — PP: "No"); this is
part of the intra-group parallelism the TPU framework owns. Design: stage
parameters carry a leading ``[pp, ...]`` axis sharded over the ``pp`` mesh
axis; inside a partial-manual ``shard_map`` each stage runs every tick,
activations hop stage→stage via ``ppermute``, and microbatch m exits stage
P-1 at tick ``m + P - 1``. The fill/drain bubble is the standard GPipe
cost: utilization M / (M + P - 1) for M microbatches.

Reverse-mode AD through the scan + ppermute gives the backward pipeline
automatically (transposed permutes run the ring in reverse).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_forward"]


def pipeline_forward(
    stage_params: Any,
    x_mb: jnp.ndarray,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh,
    axis: str = "pp",
    sp_axis: str = "sp",
) -> jnp.ndarray:
    """Run microbatches through the stage pipeline.

    Args:
        stage_params: pytree, every leaf with leading axis ``pp_size``
            (sharded ``P(axis, ...)``)
        x_mb: ``[M, mb, S, D]`` microbatched activations (replicated over
            ``axis``; other mesh axes GSPMD-sharded as usual)
        stage_fn: ``(params_for_one_stage, [mb, S, D]) -> [mb, S, D]``.
            When the mesh has ``sp_axis`` > 1, the sequence axis is ALSO
            manual inside this region (Shardy rejects nested manual
            regions), so stage_fn sees the local S/sp block and must use
            sp-local ops (ring_attention_local, local positions).
    Returns:
        ``[M, mb, S, D]`` outputs of the final stage (replicated over
        ``axis`` so downstream ops don't care where they materialized).
    """
    pp = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != pp:
            raise ValueError(
                f"stage_params leading axis {leaf.shape[0]} != mesh {axis} "
                f"size {pp}: the model was configured for a different "
                f"pipeline depth than the mesh provides"
            )
    if pp == 1:
        squeezed = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        return jax.vmap(lambda x: stage_fn(squeezed, x))(x_mb)
    sp = mesh.shape.get(sp_axis, 1)

    m = x_mb.shape[0]
    ticks = m + pp - 1

    def per_stage(params_local, x_all):
        # params_local leaves: [1, ...] (this stage's slice) -> drop axis
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_local)
        my = jax.lax.axis_index(axis)
        is_first = my == 0
        is_last = my == pp - 1
        perm = [(r, (r + 1) % pp) for r in range(pp)]

        def tick(carry, t):
            cur, outputs = carry
            feed_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(
                is_first, jax.lax.dynamic_index_in_dim(x_all, feed_idx, 0, False), cur
            )
            y = stage_fn(params_local, inp)
            out_idx = t - (pp - 1)
            ci = jnp.clip(out_idx, 0, m - 1)
            valid = is_last & (out_idx >= 0)
            prev = jax.lax.dynamic_index_in_dim(outputs, ci, 0, False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, prev), ci, 0
            )
            cur = jax.lax.ppermute(y, axis, perm)
            return (cur, outputs), ()

        # initial carries must be VMA-typed as varying over every manual
        # axis the scan outputs vary over; deriving from x_all (zeroed, XLA
        # folds it) inherits the right set, then add 'pp' which enters via
        # axis_index/ppermute
        cur0, out0 = jax.lax.pcast(
            (x_all[0] * 0, x_all * 0), (axis,), to="varying"
        )
        (_, outputs), _ = jax.lax.scan(
            tick, (cur0, out0), jnp.arange(ticks)
        )
        # only the last stage holds real outputs; replicate over pp
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    manual = {axis} if sp == 1 else {axis, sp_axis}
    act_spec = P() if sp == 1 else P(None, None, sp_axis, None)
    # context mesh (set via jax.set_mesh) rather than an explicit one
    return jax.shard_map(
        per_stage,
        in_specs=(param_specs, act_spec),
        out_specs=act_spec,
        axis_names=manual,
    )(stage_params, x_mb)
