"""Pipeline parallelism: a GPipe-style microbatched ring over the ``pp``
mesh axis.

The reference has no pipeline support (SURVEY.md §2.3 — PP: "No"); this is
part of the intra-group parallelism the TPU framework owns. Design: stage
parameters carry a leading ``[pp, ...]`` axis sharded over the ``pp`` mesh
axis; inside a partial-manual ``shard_map`` each stage runs every tick,
activations hop stage→stage via ``ppermute``, and microbatch m exits stage
P-1 at tick ``m + P - 1``. The fill/drain bubble is the standard GPipe
cost: utilization M / (M + P - 1) for M microbatches.

Reverse-mode AD through the scan + ppermute gives the backward pipeline
automatically (transposed permutes run the ring in reverse).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import torchft_tpu.utils.jax_compat  # noqa: F401 — polyfills older jax

__all__ = ["pipeline_forward"]


def pipeline_forward(
    stage_params: Any,
    x_mb: jnp.ndarray,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh,
    axis: str = "pp",
    sp_axis: str = "sp",
    head_fn: Callable[..., Any] = None,
    head_params: Any = None,
    head_extras: tuple = (),
) -> Any:
    """Run microbatches through the stage pipeline.

    Args:
        stage_params: pytree, every leaf with leading axis ``pp_size``
            (sharded ``P(axis, ...)``)
        x_mb: ``[M, mb, S, D]`` microbatched activations (replicated over
            ``axis``; other mesh axes GSPMD-sharded as usual)
        stage_fn: ``(params_for_one_stage, [mb, S, D]) -> [mb, S, D]``.
            When the mesh has ``sp_axis`` > 1, the sequence axis is ALSO
            manual inside this region (Shardy rejects nested manual
            regions), so stage_fn sees the local S/sp block and must use
            sp-local ops (ring_attention_local, local positions).
        head_fn: optional ``(head_params, outputs, *head_extras) -> pytree``
            applied to the final-stage outputs INSIDE the manual region.
            Leaves must be sums over local elements (e.g. an NLL sum and a
            token count): they are summed across the manual axes and
            returned replicated. This is the cheap exit path — a scalar
            psum instead of replicating the full ``[M, mb, S, D]``
            activations over ``axis`` (which costs an O(activations)
            collective purely to make the result location-independent).
        head_params: pytree for ``head_fn``, replicated over the manual
            axes (sharding over auto axes, e.g. tp, passes through GSPMD).
        head_extras: extra arrays for ``head_fn``, microbatched like
            ``x_mb`` (leading M, sequence axis sp-sharded if sp > 1).
    Returns:
        Without ``head_fn``: ``[M, mb, S, D]`` outputs of the final stage,
        replicated over ``axis``. With ``head_fn``: its reduced pytree.
    """
    pp = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != pp:
            raise ValueError(
                f"stage_params leading axis {leaf.shape[0]} != mesh {axis} "
                f"size {pp}: the model was configured for a different "
                f"pipeline depth than the mesh provides"
            )
    sp = mesh.shape.get(sp_axis, 1)
    if pp == 1:
        squeezed = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        out = jax.vmap(lambda x: stage_fn(squeezed, x))(x_mb)
        if head_fn is None:
            return out
        if sp == 1:
            # no manual axes: local == global, sums need no reduction
            return head_fn(head_params, out, *head_extras)
        # keep the head's contract (it runs inside a manual region and may
        # use axis_index(sp)): manualize sp alone and psum its reductions
        act_spec1 = P(None, None, sp_axis, None)
        extra_spec1 = P(None, None, sp_axis)

        def sp_head(hp, o, *e):
            res = head_fn(hp, o, *e)
            return jax.tree_util.tree_map(
                lambda a: jax.lax.psum(a, sp_axis), res
            )

        return jax.shard_map(
            sp_head,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(), head_params),
                act_spec1,
                *[extra_spec1 for _ in head_extras],
            ),
            out_specs=P(),
            axis_names={sp_axis},
        )(head_params, out, *head_extras)

    m = x_mb.shape[0]
    ticks = m + pp - 1

    def per_stage(params_local, x_all, head_params, *extras):
        # params_local leaves: [1, ...] (this stage's slice) -> drop axis
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_local)
        my = jax.lax.axis_index(axis)
        is_first = my == 0
        is_last = my == pp - 1
        perm = [(r, (r + 1) % pp) for r in range(pp)]

        def tick(carry, t):
            cur, outputs = carry
            feed_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(
                is_first, jax.lax.dynamic_index_in_dim(x_all, feed_idx, 0, False), cur
            )
            y = stage_fn(params_local, inp)
            out_idx = t - (pp - 1)
            ci = jnp.clip(out_idx, 0, m - 1)
            valid = is_last & (out_idx >= 0)
            prev = jax.lax.dynamic_index_in_dim(outputs, ci, 0, False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, prev), ci, 0
            )
            cur = jax.lax.ppermute(y, axis, perm)
            return (cur, outputs), ()

        # initial carries must be VMA-typed as varying over every manual
        # axis the scan outputs vary over; deriving from x_all (zeroed, XLA
        # folds it) inherits the right set, then add 'pp' which enters via
        # axis_index/ppermute
        cur0, out0 = jax.lax.pcast(
            (x_all[0] * 0, x_all * 0), (axis,), to="varying"
        )
        (_, outputs), _ = jax.lax.scan(
            tick, (cur0, out0), jnp.arange(ticks)
        )
        if head_fn is not None:
            # the cheap exit: reduce on the last stage, psum the (scalar)
            # reductions over every manual axis — non-last stages computed
            # on zeros and are masked out; sp blocks each contribute their
            # local partial sum
            res = head_fn(head_params, outputs, *extras)
            reduce_axes = (axis,) if sp == 1 else (axis, sp_axis)
            return jax.tree_util.tree_map(
                lambda a: jax.lax.psum(
                    jnp.where(is_last, a, jnp.zeros_like(a)), reduce_axes
                ),
                res,
            )
        # only the last stage holds real outputs; replicate over pp
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        return jax.lax.psum(outputs, axis)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    manual = {axis} if sp == 1 else {axis, sp_axis}
    act_spec = P() if sp == 1 else P(None, None, sp_axis, None)
    # P() as a pytree-prefix spec: every head-output leaf comes back
    # replicated over the manual axes (they are full psum reductions)
    out_specs = act_spec if head_fn is None else P()
    head_param_specs = jax.tree_util.tree_map(lambda _: P(), head_params)
    extra_spec = P() if sp == 1 else P(None, None, sp_axis)
    extra_specs = tuple(extra_spec for _ in head_extras)
    # context mesh (set via jax.set_mesh) rather than an explicit one
    return jax.shard_map(
        per_stage,
        in_specs=(param_specs, act_spec, head_param_specs, *extra_specs),
        out_specs=out_specs,
        axis_names=manual,
    )(stage_params, x_mb, head_params, *head_extras)
