"""Device-mesh construction for the intra-replica-group axes.

The FT replicate axis is deliberately NOT part of this mesh (contrast with
the reference's ManagedDeviceMesh which splices the managed PG *into* the
torch DeviceMesh, process_group.py:1361-1606): a jitted step function bakes
the mesh shape into the compiled executable, so putting the elastic axis in
the mesh would force a recompile on every membership change. Keeping it
host-side (Manager + Collectives) is the TPU-native answer to the same
composition problem.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["MeshConfig", "make_mesh", "AXES"]

# canonical axis order: outermost (slowest, DCN-adjacent) first so that
# tp/sp land on the innermost ICI links where their collectives are hottest
AXES: Sequence[str] = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes for each named axis; 1 means the axis is inert (size-1 axes
    still exist in the mesh so one step function serves every layout)."""

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXES}

    @property
    def total(self) -> int:
        return int(np.prod(list(self.sizes.values())))


def make_mesh(config: MeshConfig, devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` with the canonical axis order."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < config.total:
        raise ValueError(
            f"mesh needs {config.total} devices, have {len(devices)}"
        )
    shape = tuple(config.sizes[a] for a in AXES)
    dev = np.array(devices[: config.total]).reshape(shape)
    return jax.sharding.Mesh(dev, AXES)
