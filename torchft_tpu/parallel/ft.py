"""FT × mesh composition — the HSDP story, TPU-native.

The reference splices its managed (elastic) process group into the torch
DeviceMesh so FSDP sees a "replicate" dim of dynamic size
(ManagedDeviceMesh / ft_init_device_mesh, process_group.py:1361-1606). The
TPU equivalent keeps the two planes apart by construction:

* inner: a fixed ``jax.sharding.Mesh`` (dp/fsdp/pp/ep/sp/tp) baked into the
  compiled TrainStep — never changes, never recompiles;
* outer: the Manager's replica axis on host buffers — gradients cross it
  via ``manager.allreduce`` between ``grads`` and ``apply``, so quorum
  membership changes are invisible to XLA.

``FTTrainer`` ties the two together and registers host-side state
snapshots with the Manager so live recovery (send/recv checkpoint) works
for sharded params: leaves are gathered to host for transfer and re-placed
with the TrainStep's shardings on load.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from torchft_tpu.checkpointing.serialization import to_host_tree
from torchft_tpu.ddp import allreduce_gradients
from torchft_tpu.manager import Manager
from torchft_tpu.parallel.train_step import TrainStep

__all__ = ["FTTrainer"]


class FTTrainer:
    def __init__(self, manager: Manager, train_step: TrainStep) -> None:
        self._manager = manager
        self._ts = train_step
        self._params: Optional[Any] = None
        self._opt_state: Optional[Any] = None

    # -- state (registered with the Manager for live recovery) --

    def init(self, rng) -> None:
        self._params = self._ts.init_params(rng)
        self._opt_state = self._ts.init_opt(self._params)
        self._manager.set_state_dict_fns(self.load_state_dict, self.state_dict)

    @property
    def params(self) -> Any:
        return self._params

    @property
    def opt_state(self) -> Any:
        return self._opt_state

    def state_dict(self) -> Dict[str, Any]:
        # host-side snapshot: on multi-host meshes each process contributes
        # its addressable shards; here the full gather is the transferable
        # representation for the checkpoint transports
        return {
            "params": to_host_tree(self._params),
            "opt_state": to_host_tree(self._opt_state),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        import jax

        # re-place the recovered host arrays onto the inner mesh with the
        # step's shardings (GSPMD re-shards on first use otherwise)
        self._params = jax.device_put(
            state["params"], self._ts._param_shardings
        )
        # opt_state shardings mirror params; let placement follow use
        self._opt_state = state["opt_state"]

    # -- drive --

    def step(self, tokens) -> Tuple[float, bool]:
        """One fault-tolerant step: quorum → device grads → cross-group
        average (host) → commit gate → device update. Returns
        (loss, committed)."""
        self._manager.start_quorum()
        tokens = self._ts.shard_batch(tokens)
        loss, grads = self._ts.grads(self._params, tokens)
        # cross the elastic replica axis on host
        grads = allreduce_gradients(self._manager, grads)
        committed = self._manager.should_commit()
        if committed:
            self._params, self._opt_state = self._ts.apply(
                self._params, self._opt_state, grads
            )
        return float(loss), committed
