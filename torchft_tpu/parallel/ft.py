"""FT × mesh composition — the HSDP story, TPU-native.

The reference splices its managed (elastic) process group into the torch
DeviceMesh so FSDP sees a "replicate" dim of dynamic size
(ManagedDeviceMesh / ft_init_device_mesh, process_group.py:1361-1606). The
TPU equivalent keeps the two planes apart by construction:

* inner: a fixed ``jax.sharding.Mesh`` (dp/fsdp/pp/ep/sp/tp) baked into the
  compiled TrainStep — never changes, never recompiles;
* outer: the Manager's replica axis on host buffers — gradients cross it
  via ``manager.allreduce`` between ``grads`` and ``apply``, so quorum
  membership changes are invisible to XLA.

``FTTrainer`` ties the two together and registers host-side state
snapshots with the Manager so live recovery (send/recv checkpoint) works
for sharded params: leaves are gathered to host for transfer and re-placed
with the TrainStep's shardings on load.

Pipelined commit (``Manager(commit_pipeline=True)`` /
``TORCHFT_COMMIT_PIPELINE=1``, docs/commit_pipeline.md): instead of
paying the per-step commit-vote RTT serially, ``step`` applies the
optimizer update immediately (non-donating, so the pre-update pytrees
stay alive on device as a rollback snapshot — references, not copies),
issues the vote asynchronously, and the NEXT step's forward/backward runs
while the vote is in flight. The vote resolves before the next step's own
collectives; on a veto the snapshot is restored and the in-flight batch
is replayed on the restored state — the committed state sequence is
bit-identical to sync mode.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from torchft_tpu.ddp import allreduce_gradients
from torchft_tpu.manager import Manager
from torchft_tpu.optim import SpeculativeCommitMixin
from torchft_tpu.parallel.train_step import TrainStep

__all__ = ["FTTrainer"]


class FTTrainer(SpeculativeCommitMixin):
    def __init__(self, manager: Manager, train_step: TrainStep) -> None:
        self._manager = manager
        self._ts = train_step
        self._params: Optional[Any] = None
        self._opt_state: Optional[Any] = None
        # pipelined commit (SpeculativeCommitMixin state): the pre-update
        # (params, opt_state) of the speculative step, alive until its
        # vote resolves. While set, state_dict() serves IT — a healing
        # peer must receive committed state, never a speculative update
        # that a veto would undo.
        self._snapshot = None
        self._replay_needed = False
        self.rollbacks = 0

    # -- state (registered with the Manager for live recovery) --

    def init(self, rng) -> None:
        self._params = self._ts.init_params(rng)
        self._opt_state = self._ts.init_opt(self._params)
        self._manager.set_state_dict_fns(self.load_state_dict, self.state_dict)
        if hasattr(self._manager, "set_heal_warmup"):
            self._manager.set_heal_warmup(self._heal_warmup)

    def _heal_warmup(self, spec_tree: Any) -> None:
        """Heal/compile overlap (docs/heal_plane.md): runs on a daemon
        thread as soon as the incoming checkpoint's header lands — AOT-
        compile the apply step from the transferred shapes while the
        stripes are still streaming, so the post-heal first step doesn't
        serialize recv → compile."""
        user = spec_tree.get("user") if isinstance(spec_tree, dict) else None
        if not isinstance(user, dict):
            return
        params, opt_state = user.get("params"), user.get("opt_state")
        if params is None or opt_state is None:
            return
        self._ts.warm_apply(params, opt_state)

    @property
    def params(self) -> Any:
        return self._params

    @property
    def opt_state(self) -> Any:
        return self._opt_state

    def state_dict(self) -> Dict[str, Any]:
        # hand the raw sharded jax.Arrays to the transports: flatten_state
        # ships each leaf per shard with its NamedSharding descriptor
        # (serialization.py "shards" infos — the DTensor-spec analogue,
        # pg_transport.py:104-114), so a sharded group never gathers the
        # full model onto one host and replicated copies ship once
        snap = self._snapshot
        if snap is not None:
            # mid-speculation: the committed state is the snapshot. The
            # Manager's speculation fence normally resolves the vote before
            # any heal serve, but a bounded fence timeout can still land
            # here — serving the snapshot is correct either way.
            return {"params": snap[0], "opt_state": snap[1]}
        return {"params": self._params, "opt_state": self._opt_state}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        import jax

        from torchft_tpu.checkpointing.serialization import from_transfer_tree

        # rebuild sharded leaves shard-by-shard on this group's mesh, then
        # pin params to the step's shardings (no-op when already placed)
        state = from_transfer_tree(state, self._ts.mesh)
        self._params = jax.device_put(
            state["params"], self._ts._param_shardings
        )
        # opt_state shardings mirror params; let placement follow use.
        # NOTE (flake post-mortem, PR 2): transferred dense leaves stay as
        # UNCOMMITTED host arrays on purpose. Re-committing them onto the
        # live tree's shardings via device_put looks like the obvious
        # placement-parity fix for the healed replica's retrace churn, but
        # in a multi-controller group it is wrong: jit-output scalars
        # (e.g. adam's count) carry shardings that device_put resolves to
        # THIS process's single local device, and the next `apply` then
        # rejects the mix of a global-mesh param with a single-device
        # opt leaf ("Received incompatible devices"). Leaving the leaves
        # uncommitted lets jit place them consistently on every process.
        self._opt_state = state["opt_state"]
        # a heal supersedes any speculative lineage: the received state IS
        # the committed one (the manager resolves the vote before heal
        # traffic, so this is belt-and-braces for the fence-timeout path).
        # That includes a pending replay — the next step's gradients are
        # taken on this healed state, so they are valid, not
        # vetoed-lineage leftovers
        self._snapshot = None
        self._replay_needed = False

    # -- pipelined-commit plumbing: SpeculativeCommitMixin provides
    # _on_vote_resolved / _consume_replay / finish --

    def _resolve_speculation(self) -> bool:
        """Resolve the previous step's in-flight vote (no-op when none).
        Returns True when a rollback happened — here or out-of-band —
        meaning the current batch's forward/backward ran on the restored
        state's vetoed successor and must be replayed."""
        if self._manager.pending_commit() is not None:
            self._manager.resolve_pending_commit()
        return self._consume_replay()

    # -- drive --

    def step(self, tokens) -> Tuple[float, bool]:
        """One fault-tolerant step: quorum → device grads → cross-group
        average (host) → commit gate → device update. Returns
        (loss, committed).

        In pipelined-commit mode the update is applied speculatively and
        the returned ``committed`` is the *expected* outcome (True); the
        authoritative result lands when the NEXT step (or :meth:`finish`)
        resolves the vote — a veto rolls the update back, replays, and
        bumps :attr:`rollbacks`."""
        self._manager.start_quorum()
        tokens = self._ts.shard_batch(tokens)
        # forward/backward first: in pipelined mode this is the compute
        # that hides the previous step's vote RTT
        loss, grads = self._ts.grads(self._params, tokens)
        if self._resolve_speculation():
            # previous step vetoed: grads above were taken on the now
            # rolled-back params — replay this batch on the restored state
            loss, grads = self._ts.grads(self._params, tokens)
        # cross the elastic replica axis on host
        grads = allreduce_gradients(self._manager, grads)
        if self._manager.speculation_allowed():
            # keep the pre-update trees alive (references, no copy) and
            # publish the snapshot BEFORE the apply so a concurrent
            # checkpoint serve never sees the speculative trees
            self._snapshot = (self._params, self._opt_state)
            self._params, self._opt_state = self._ts.apply(
                self._params, self._opt_state, grads, donate=False
            )
            self._manager.should_commit_async(
                on_resolved=self._on_vote_resolved
            )
            return float(loss), True
        committed = self._manager.should_commit()
        if committed:
            self._params, self._opt_state = self._ts.apply(
                self._params, self._opt_state, grads
            )
        return float(loss), committed
