"""Multi-host replica groups — jax.distributed wiring.

The reference's replica groups span hosts through torchrun: each group is
``torchrun --nnodes=1 --nproc_per_node=M`` and torch.distributed carries
the intra-group collectives (/root/reference/torchft/torchx.py:11-76). The
TPU-native equivalent is multi-controller JAX: every process of a group
calls ``jax.distributed.initialize`` against the group's coordinator, after
which ``jax.devices()`` is the group's *global* device list, the inner
``jax.sharding.Mesh`` spans hosts, and XLA runs the intra-group collectives
over ICI/DCN. The elastic cross-group axis stays outside (Manager +
CollectivesTcp per rank, same-rank peers across groups), so group
membership changes still never touch the compiled step.

Env contract (set by the launcher, torchelastic-style):

    TORCHFT_JAX_COORDINATOR   host:port of the group's jax coordinator
    RANK / WORLD_SIZE         this process's index / process count in group

Per-process accelerator visibility (e.g. 4 chips of a v5e host) comes from
the platform; on CPU tests ``--xla_force_host_platform_device_count``
gives each process N virtual devices.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["initialize_group", "is_initialized", "global_mesh"]

JAX_COORDINATOR_ENV = "TORCHFT_JAX_COORDINATOR"

_initialized = False


def is_initialized() -> bool:
    return _initialized


def initialize_group(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this replica group's JAX runtime. Reads the launcher env
    (TORCHFT_JAX_COORDINATOR / RANK / WORLD_SIZE) unless given explicitly;
    a no-op for single-process groups (no coordinator set) and when
    already initialized (idempotent, so library code may call it freely).

    Must run before any other jax API touches the backend."""
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get(JAX_COORDINATOR_ENV)
    if coordinator is None:
        return  # single-process group
    num_processes = (
        num_processes
        if num_processes is not None
        else int(os.environ["WORLD_SIZE"])
    )
    process_id = (
        process_id if process_id is not None else int(os.environ["RANK"])
    )
    if num_processes <= 1:
        return
    import jax

    from torchft_tpu.utils.jax_compat import enable_cpu_gloo_collectives

    enable_cpu_gloo_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def global_mesh(config):
    """The group-wide mesh: :func:`make_mesh` over the global device list
    (which spans every process of the group after :func:`initialize_group`).
    All processes must call with the same config."""
    import jax

    from torchft_tpu.parallel.mesh import make_mesh

    return make_mesh(config, devices=jax.devices())
