"""Intra-replica-group parallelism — the TPU-native data plane.

The reference's intra-group story is "bring your own torch parallelism"
(FSDP2/DTensor composed with the FT replicate axis via ManagedDeviceMesh,
process_group.py:1332-1606). On TPU the idiomatic equivalent is richer: one
``jax.sharding.Mesh`` over the group's chips with named axes

    dp    data parallel (batch)           — ICI all-reduce of grads
    fsdp  param/optimizer sharding (zero) — all-gather weights per layer
    pp    pipeline stages                 — microbatched ppermute ring
    sp    sequence/context parallel       — ring attention over seq blocks
    tp    tensor parallel (heads/ffn)     — XLA-inserted collectives
    ep    expert parallel (MoE experts)   — all-to-all token dispatch

XLA's GSPMD inserts the collectives from sharding annotations; only the
manual-overlap paths (ring attention, pipeline ring) use shard_map. The
fault-tolerance replica axis stays *outside* this mesh (host-side managed
collectives), so quorum membership changes never recompile the step.
"""

from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
from torchft_tpu.parallel.pipeline import pipeline_forward
from torchft_tpu.parallel.train_step import TrainStep

__all__ = ["MeshConfig", "make_mesh", "pipeline_forward", "TrainStep"]
