"""Mesh-aware train step builder.

Couples the flagship model to optax under jit with explicit shardings.
Two drive modes:

* ``step``  — fused grads+update, buffers donated; the single-replica-group
  hot path (everything stays on device).
* ``grads`` / ``apply`` — split pair for fault-tolerant cross-group
  training: grads come to host, the Manager averages them over the elastic
  replica axis (outside jit, so membership changes never recompile), then
  ``apply`` updates on device.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import torchft_tpu.utils.jax_compat  # noqa: F401 — polyfills older jax

from torchft_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    param_specs,
)

__all__ = ["TrainStep"]


class TrainStep:
    def __init__(self, cfg: TransformerConfig, tx, mesh) -> None:
        self.cfg = cfg
        self.tx = tx
        self.mesh = mesh
        self._pspecs = param_specs(cfg)
        self._param_shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), self._pspecs
        )
        self._batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))

        def compute_loss(params, tokens):
            return loss_fn(params, tokens, cfg, mesh)

        self._value_and_grad = jax.jit(jax.value_and_grad(compute_loss))

        def apply_updates(params, opt_state, grads):
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax

            return optax.apply_updates(params, updates), opt_state

        self._apply = jax.jit(apply_updates, donate_argnums=(0, 1))
        # pipelined-commit variant, compiled lazily: the inputs must NOT
        # be donated so the pre-update (params, opt_state) stays alive on
        # device as the rollback snapshot (a reference, not a copy)
        self._apply_updates_fn = apply_updates
        self._apply_keep = None

        def fused(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(compute_loss)(params, tokens)
            new_params, opt_state = apply_updates(params, opt_state, grads)
            return loss, new_params, opt_state

        self._fused = jax.jit(fused, donate_argnums=(0, 1))

    # -- state --

    def init_params(self, rng) -> Dict[str, Any]:
        with jax.set_mesh(self.mesh):
            params = jax.jit(
                lambda r: init_params(r, self.cfg),
                out_shardings=self._param_shardings,
            )(rng)
        return params

    def init_opt(self, params) -> Any:
        with jax.set_mesh(self.mesh):
            return jax.jit(self.tx.init)(params)

    def warm_apply(self, params_spec, opt_state_spec) -> None:
        """AOT-compile the donated ``apply`` jit from abstract specs (the
        heal/compile overlap, docs/heal_plane.md): called on a background
        thread while checkpoint stripes stream, so the healer's first
        post-heal apply finds the executable warm (via the shared jit
        lowering cache and/or the persistent XLA compilation cache)
        instead of paying the compile serially after recv. Grad specs
        mirror param specs (identical pytree/shapes/dtypes)."""
        with jax.set_mesh(self.mesh):
            self._apply.lower(params_spec, opt_state_spec, params_spec).compile()

    def shard_batch(self, tokens) -> jnp.ndarray:
        if not self._batch_sharding.is_fully_addressable:
            # multi-host group: every process holds the full batch (same
            # sampler state); carve out each local device's shard
            import numpy as np

            arr = np.asarray(tokens)
            return jax.make_array_from_callback(
                arr.shape, self._batch_sharding, lambda idx: arr[idx]
            )
        return jax.device_put(tokens, self._batch_sharding)

    # -- drive --

    @staticmethod
    def _record_compute(t0: float) -> None:
        # step-anatomy `compute` phase: main-thread time inside the jitted
        # calls (dispatch + any blocking; with async dispatch the device
        # tail lands in whoever blocks next — usually the host copy, which
        # the ledger attributes to host_copy/wire). Best-effort.
        import time as _time

        try:
            from torchft_tpu.telemetry.anatomy import LEDGER

            LEDGER.record("compute", _time.perf_counter() - t0)
        except Exception:  # noqa: BLE001 — observability never fails a step
            pass

    def step(self, params, opt_state, tokens) -> Tuple[jnp.ndarray, Any, Any]:
        """Fused grads+update (single replica group / no FT averaging)."""
        import time as _time

        t0 = _time.perf_counter()
        with jax.set_mesh(self.mesh):
            out = self._fused(params, opt_state, tokens)
        self._record_compute(t0)
        return out

    def grads(self, params, tokens) -> Tuple[jnp.ndarray, Any]:
        """Loss + gradient pytree (still on device)."""
        import time as _time

        t0 = _time.perf_counter()
        with jax.set_mesh(self.mesh):
            out = self._value_and_grad(params, tokens)
        self._record_compute(t0)
        return out

    def apply(self, params, opt_state, grads, donate: bool = True) -> Tuple[Any, Any]:
        """Apply (possibly host-averaged) grads.

        ``donate=False`` keeps the input buffers alive (at the cost of the
        update not being in-place) — required when the caller retains the
        pre-update trees as a pipelined-commit rollback snapshot."""
        import time as _time

        t0 = _time.perf_counter()
        with jax.set_mesh(self.mesh):
            if donate:
                out = self._apply(params, opt_state, grads)
            else:
                if self._apply_keep is None:
                    self._apply_keep = jax.jit(self._apply_updates_fn)
                out = self._apply_keep(params, opt_state, grads)
        self._record_compute(t0)
        return out
