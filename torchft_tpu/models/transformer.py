"""Decoder-only transformer LM — the flagship model.

Pure-JAX pytree params with explicit ``PartitionSpec``s per leaf:

* ``tp``  — attention heads and FFN hidden dim (megatron-style; XLA/GSPMD
  inserts the all-reduces from the shardings, nothing manual here)
* ``sp``  — sequence axis via ring attention (ops/attention.py)
* ``pp``  — layer stages via the microbatched ppermute ring
  (parallel/pipeline.py); stage params carry a leading [pp, Lp] axis
* ``ep``  — MoE experts (top-2 capacity dispatch, ops/layers.py)
* ``dp``/``fsdp`` — batch / parameter sharding

Layers within a stage run under ``lax.scan`` (one compile per stage, not
per layer) with ``jax.checkpoint`` rematerialization — compile time and
HBM both scale O(1) in depth.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import torchft_tpu.utils.jax_compat  # noqa: F401 — polyfills older jax

from torchft_tpu.ops.attention import (
    attention,
    chunked_attention,
    ring_attention,
    ring_attention_local,
)
from torchft_tpu.ops.layers import moe_dispatch, rms_norm, rotary_embed, swiglu

__all__ = [
    "TransformerConfig",
    "init_params",
    "param_specs",
    "forward",
    "loss_fn",
]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    head_dim: int = 64
    d_ff: int = 1408
    n_experts: int = 0  # 0 => dense FFN
    capacity_factor: float = 1.25
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16  # compute dtype (MXU-native)
    remat: bool = True
    # checkpoint policy under remat: "all" recomputes the whole layer in
    # the backward (lowest memory); "dots" saves matmul outputs and
    # recomputes only elementwise/softmax (MXU work runs once — the
    # round-5 sweet spot at short S where memory isn't the constraint)
    remat_policy: str = "all"
    pp: int = 1  # pipeline stages; n_layers % pp == 0
    microbatches: int = 0  # 0 => = pp
    # "auto" | "plain" | "chunked" | "flash". auto: plain XLA attention at
    # short S (it wins there), tiered chunked-scan attention
    # (ops/attention.chunked_attention, pure XLA) from s>=4096 — the
    # HBM-bandwidth path that took s=8192 from 15% to ~31% MFU on v5e and
    # makes s=32k single-chip viable; the pallas flash kernel engages only
    # for an explicit "flash" or past the scores-memory ceiling when
    # chunked can't run (S not divisible by the chunk)
    attention_impl: str = "auto"

    @property
    def layers_per_stage(self) -> int:
        assert self.n_layers % max(self.pp, 1) == 0
        return self.n_layers // max(self.pp, 1)

    @property
    def qkv_dim(self) -> int:
        return self.n_heads * self.head_dim


def init_params(rng, cfg: TransformerConfig) -> Dict[str, Any]:
    """Params as a pytree of float32 numpy-backed arrays; leading [pp, Lp]
    axes on per-layer tensors."""
    keys = jax.random.split(rng, 16)
    d, qkv, f = cfg.d_model, cfg.qkv_dim, cfg.d_ff
    lp, pp = cfg.layers_per_stage, max(cfg.pp, 1)

    def dense(key, *shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)
        )

    layers: Dict[str, Any] = {
        "ln1": jnp.ones((pp, lp, d), jnp.float32),
        "ln2": jnp.ones((pp, lp, d), jnp.float32),
        "wq": dense(keys[0], pp, lp, d, qkv, fan_in=d),
        "wk": dense(keys[1], pp, lp, d, qkv, fan_in=d),
        "wv": dense(keys[2], pp, lp, d, qkv, fan_in=d),
        "wo": dense(keys[3], pp, lp, qkv, d, fan_in=qkv),
    }
    if cfg.n_experts:
        e = cfg.n_experts
        layers.update(
            router=dense(keys[4], pp, lp, d, e, fan_in=d),
            w_gate=dense(keys[5], pp, lp, e, d, f, fan_in=d),
            w_in=dense(keys[6], pp, lp, e, d, f, fan_in=d),
            w_out=dense(keys[7], pp, lp, e, f, d, fan_in=f),
        )
    else:
        layers.update(
            w_gate=dense(keys[5], pp, lp, d, f, fan_in=d),
            w_in=dense(keys[6], pp, lp, d, f, fan_in=d),
            w_out=dense(keys[7], pp, lp, f, d, fan_in=f),
        )
    return {
        "embed": dense(keys[8], cfg.vocab_size, d, fan_in=1.0),
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "out": dense(keys[9], d, cfg.vocab_size, fan_in=d),
    }


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpec per leaf (matches init_params structure)."""
    row, col = P("pp", None, "fsdp", "tp"), P("pp", None, "tp", "fsdp")
    layers: Dict[str, Any] = {
        "ln1": P("pp", None, None),
        "ln2": P("pp", None, None),
        "wq": row,
        "wk": row,
        "wv": row,
        "wo": col,
    }
    if cfg.n_experts:
        layers.update(
            router=P("pp", None, "fsdp", None),
            w_gate=P("pp", None, "ep", "fsdp", "tp"),
            w_in=P("pp", None, "ep", "fsdp", "tp"),
            w_out=P("pp", None, "ep", "tp", "fsdp"),
        )
    else:
        layers.update(w_gate=row, w_in=row, w_out=col)
    return {
        # [V,D] with vocab UNSHARDED and D over (tp,fsdp): the same bytes
        # per device as the row+col P("tp","fsdp") layout, but the token
        # gather is fully local and the cotangent lands in the stored
        # layout — SPMD previously fell back to involuntary full
        # rematerialization on both (round-3 review missing #2)
        "embed": P(None, ("tp", "fsdp")),
        "layers": layers,
        "final_norm": P(None),
        "out": P("fsdp", "tp"),
    }


def _act_spec(sp_manual: bool = False) -> P:
    # inside a manual-sp region the sequence axis is already local; only
    # auto axes may appear in constraints
    return P(("dp", "fsdp"), None, None) if sp_manual else P(("dp", "fsdp"), "sp", None)


def _constrain(x: jnp.ndarray, spec: P) -> jnp.ndarray:
    """with_sharding_constraint that no-ops when there is no context mesh
    (single-chip / unsharded use)."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


def _ffn_dense(lp: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    return swiglu(x, lp["w_gate"], lp["w_in"], lp["w_out"])


def _ffn_moe(lp: Dict[str, Any], x: jnp.ndarray, cfg: TransformerConfig) -> jnp.ndarray:
    b, s, d = x.shape
    g = b * s
    tokens = x.reshape(g, d)
    gates = jax.nn.softmax(
        (tokens @ lp["router"]).astype(jnp.float32), axis=-1
    ).astype(x.dtype)
    capacity = max(
        1, int(np.ceil(2 * g / cfg.n_experts * cfg.capacity_factor))
    )
    dispatch, combine = moe_dispatch(gates, capacity)
    # [G,E,C] x [G,D] -> [E,C,D]: the all-to-all over `ep` falls out of the
    # expert-axis sharding on the einsum operands
    expert_in = jnp.einsum("gec,gd->ecd", dispatch, tokens)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, lp["w_gate"])) * jnp.einsum(
        "ecd,edf->ecf", expert_in, lp["w_in"]
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, lp["w_out"])
    out = jnp.einsum("gec,ecd->gd", combine, expert_out)
    return out.reshape(b, s, d)


def _flash_threshold_bytes() -> float:
    """Scores-memory ceiling above which auto engages the pallas kernel.

    When the materialized [B,H,S,S] scores exceed this, XLA's plain
    attention stops fitting HBM and the pallas kernel's O(S·block) memory
    becomes the only option. Below it, plain is strictly faster — a
    controlled plain-vs-flash comparison measured 46x at b1 h8 s8192 on
    v5e (the round-2 "flash at s>=8192" rule was costing auto users
    exactly that). Override via TORCHFT_TPU_FLASH_SCORES_GB for chips
    with a different HBM budget."""
    import os

    raw = os.environ.get("TORCHFT_TPU_FLASH_SCORES_GB", "4")
    try:
        return float(raw) * 1e9
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "ignoring malformed TORCHFT_TPU_FLASH_SCORES_GB=%r; using 4", raw
        )
        return 4e9


def _use_flash(
    cfg: TransformerConfig, seq_len: int, batch: int = 1, mesh=None
) -> bool:
    if cfg.attention_impl in ("plain", "chunked"):
        return False
    if cfg.attention_impl == "flash":
        return True
    if cfg.attention_impl != "auto":
        raise ValueError(
            "attention_impl must be 'auto'|'plain'|'chunked'|'flash', "
            f"got {cfg.attention_impl!r}"
        )
    # auto: engage the pallas kernel only when plain attention's scores
    # would blow PER-CHIP HBM — it is the memory-ceiling path, never the
    # speed path. The estimate divides the global shapes by the mesh's
    # batch (dp·fsdp) and head (tp) factors, and uses 4 bytes/element:
    # plain attention's softmax runs in f32 whatever the compute dtype.
    itemsize = max(jnp.dtype(cfg.dtype).itemsize, 4)
    batch_shards = heads_shards = 1
    if mesh is not None:
        batch_shards = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        heads_shards = mesh.shape.get("tp", 1)
    scores_bytes = (
        float(itemsize)
        * max(1, batch // batch_shards)
        * max(1, cfg.n_heads // heads_shards)
        * seq_len
        * seq_len
    )
    return (
        jax.default_backend() == "tpu"
        and scores_bytes > _flash_threshold_bytes()
        and seq_len % 128 == 0
    )


def _attn_chunk(seq_len: int) -> int:
    """Sequence-aware q-block size; TORCHFT_TPU_ATTN_CHUNK overrides
    (env-overridable, like every other knob in this file — an
    unparseable value is IGNORED, not treated as an override).
    Round-5 v5e sweep (full-model grads / FT-loop steps, d512 L8): C=128
    beats 256 by ~7% at s=8k and ~15% at s=32k (1046 vs 1241 ms with 16
    tiers) and is within noise at 1k-2k — smaller q-blocks keep the
    per-block f32 scores fusion-local deeper into the causal prefix.
    s=16k is the measured exception: C=256 with 16 tiers runs +6%
    (3.52 vs 3.33 steps/s, reproduced fresh-process) — at 1k-row
    segments the halved scan trip count beats the smaller working set."""
    import os

    raw = os.environ.get("TORCHFT_TPU_ATTN_CHUNK")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass  # fall through to the sequence-aware default
    return 256 if seq_len == 16384 else 128


def _attn_tiers() -> Optional[int]:
    """Causal k-prefix tier count override (TORCHFT_TPU_ATTN_TIERS);
    unset/invalid -> None, i.e. chunked_attention's adaptive pick."""
    import os

    raw = os.environ.get("TORCHFT_TPU_ATTN_TIERS")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _use_chunked(cfg: TransformerConfig, seq_len: int) -> bool:
    """Route to :func:`chunked_attention` (round-3 review missing #4: the
    4k–16k band sat at 15% MFU on XLA plain attention with no mitigation).
    Round-5 sweep moved the engage point down to 1024: even there plain
    attention's f32 [S,S] scores round-trip HBM (full-model grads at the
    d512/L8/b8/s1024 headline: 52 ms plain vs 41–44 ms chunked; s=2048:
    133 vs 92; s=512 is a wash, so plain keeps its simpler compile below
    1k). Pure XLA — works under GSPMD sharding AND inside the pipeline's
    manual region, unlike the pallas kernel. Override the engage point
    with TORCHFT_TPU_ATTN_CHUNKED_MIN_S. Sequences not divisible by the
    chunk fall back to plain (both explicit and auto)."""
    if seq_len % _attn_chunk(seq_len) != 0:
        return False
    if cfg.attention_impl == "chunked":
        return True
    if cfg.attention_impl != "auto":
        return False
    import os

    try:
        min_s = int(os.environ.get("TORCHFT_TPU_ATTN_CHUNKED_MIN_S", "1024"))
    except ValueError:
        min_s = 1024
    return seq_len >= min_s


def _flash_sharded(q, k, v, mesh):
    """Flash attention under GSPMD: pallas_call has no partitioning rules,
    so without shard_map the SPMD partitioner would all-gather q/k/v onto
    every chip. Attention is independent per (batch, head), so manualize
    the batch/head axes and run the kernel per shard."""
    from torchft_tpu.ops.pallas.flash_attention import flash_attention

    if mesh is None:
        return flash_attention(q, k, v, causal=True)
    spec = P(("dp", "fsdp"), None, "tp", None)
    return jax.shard_map(
        lambda q, k, v: flash_attention(q, k, v, causal=True),
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # ALL mesh axes must be manual here: any axis left auto keeps the
        # region under the SPMD partitioner, which refuses Mosaic calls
        # even at axis size 1 (tpu_custom_call "cannot be automatically
        # partitioned"). Axes beyond dp/fsdp/tp are replicated by the spec.
        axis_names=set(mesh.axis_names),
        # pallas_call's out_shape carries no varying-manual-axes type, which
        # the VMA checker would require; the kernel is per-shard local so
        # the check adds nothing here
        check_vma=False,
    )(q, k, v)


def _make_layer_fn(cfg: TransformerConfig, mesh, sp_manual: bool = False):
    sp_size = mesh.shape.get("sp", 1) if mesh is not None else 1

    def layer_fn(x: jnp.ndarray, lp: Dict[str, Any]) -> jnp.ndarray:
        x = _constrain(x, _act_spec(sp_manual))
        h = rms_norm(x, lp["ln1"], cfg.norm_eps)
        b, s, _ = h.shape  # s is the sp-local block inside a manual region
        if sp_manual and sp_size > 1:
            positions = jax.lax.axis_index("sp") * s + jnp.arange(s)
        else:
            positions = jnp.arange(s)
        q = (h @ lp["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
        v = (h @ lp["wv"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
        q = rotary_embed(q, positions, cfg.rope_theta)
        k = rotary_embed(k, positions, cfg.rope_theta)
        if sp_size > 1 and sp_manual:
            att = ring_attention_local(q, k, v, sp_size, causal=True)
        elif sp_size > 1:
            att = ring_attention(q, k, v, mesh, causal=True)
        elif _use_chunked(cfg, s):
            att = chunked_attention(
                q, k, v, causal=True, chunk=_attn_chunk(s),
                tiers=_attn_tiers(),
            )
        elif _use_flash(cfg, s, b, mesh):
            # flash needs its own (full) manual region, which can't nest
            # inside the pipeline's partial-manual shard_map (Shardy rejects
            # nested manual regions) — pp>1 long-context should shard the
            # sequence (sp), which routes to ring attention above
            inside_manual = sp_manual or (
                mesh is not None and mesh.shape.get("pp", 1) > 1
            )
            if inside_manual:
                if cfg.attention_impl == "flash":
                    raise ValueError(
                        "attention_impl='flash' cannot run inside the "
                        "pipeline's manual region (pp>1); shard the sequence "
                        "(sp>1, ring attention) for long context under pp"
                    )
                att = attention(q, k, v, causal=True)  # auto: quiet fallback
            else:
                att = _flash_sharded(q, k, v, mesh)
        else:
            att = attention(q, k, v, causal=True)
        x = x + att.reshape(b, s, cfg.qkv_dim) @ lp["wo"]

        h = rms_norm(x, lp["ln2"], cfg.norm_eps)
        if cfg.n_experts:
            x = x + _ffn_moe(lp, h, cfg)
        else:
            x = x + _ffn_dense(lp, h)
        return _constrain(x, _act_spec(sp_manual))

    return layer_fn


def _make_stage_fn(cfg: TransformerConfig, mesh, sp_manual: bool = False):
    layer_fn = _make_layer_fn(cfg, mesh, sp_manual)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "all":
            policy = None
        else:
            raise ValueError(
                f"remat_policy={cfg.remat_policy!r}: expected 'all' or "
                "'dots' (a typo here would silently pay full recompute)"
            )
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    def stage_fn(stage_params: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
        # stage_params leaves: [Lp, ...]; scan over the layer axis
        def body(x, lp):
            return layer_fn(x, lp), ()

        x, _ = jax.lax.scan(body, x, stage_params)
        return x

    return stage_fn


def _embed_lookup(
    params: Dict[str, Any], tokens: jnp.ndarray, dt
) -> jnp.ndarray:
    """Embedding gather with EXPLICIT gather partitioning (round-3 review
    missing #2): the table is stored P(None, ("tp","fsdp")) — vocab
    unsharded, D over (tp,fsdp) — so the token gather is fully LOCAL
    (SPMD cannot partition a vocab-sharded gather and previously fell
    back to "involuntary full rematerialization", replicating [V,D] on
    every device each step). Only the (much smaller) [B,S,D] activation
    is resharded to the standard spec afterwards."""
    embed = _constrain(params["embed"].astype(dt), P(None, ("tp", "fsdp")))
    tok = _constrain(tokens, P("dp", "sp"))
    x = jnp.take(embed, tok, axis=0)
    # reshard to the activation spec ONE axis move per step — GSPMD falls
    # back to a full-remat copy on the combined move (fsdp D→B while
    # dropping tp) but handles each single-axis hop efficiently
    x = _constrain(x, P("dp", "sp", ("tp", "fsdp")))
    x = _constrain(x, P(("dp", "fsdp"), "sp", "tp"))
    return _constrain(x, _act_spec())


def _hidden_states(
    params: Dict[str, Any],
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
) -> jnp.ndarray:
    """tokens [B, S] -> final-norm hidden states [B, S, D] in cfg.dtype
    (everything except the unembed — the chunked loss head consumes this
    without ever materializing [S, V] logits)."""
    from torchft_tpu.parallel.pipeline import pipeline_forward

    b, s = tokens.shape
    dt = cfg.dtype
    x = _embed_lookup(params, tokens, dt)

    layers = jax.tree_util.tree_map(lambda a: a.astype(dt), params["layers"])

    pp = max(cfg.pp, 1)
    if pp == 1:
        stage_fn = _make_stage_fn(cfg, mesh, sp_manual=False)
        x = stage_fn(jax.tree_util.tree_map(lambda a: a[0], layers), x)
    else:
        # inside the pipeline's manual region the sp axis is manual too
        # (Shardy forbids nested manual regions)
        sp_manual = mesh is not None and mesh.shape.get("sp", 1) > 1
        stage_fn = _make_stage_fn(cfg, mesh, sp_manual=sp_manual)
        m = cfg.microbatches or pp
        assert b % m == 0, f"batch {b} must divide into {m} microbatches"
        x_mb = x.reshape(m, b // m, s, -1)
        x_mb = pipeline_forward(layers, x_mb, stage_fn, mesh)
        x = x_mb.reshape(b, s, -1)

    return rms_norm(x, params["final_norm"].astype(dt), cfg.norm_eps)


def forward(
    params: Dict[str, Any],
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
) -> jnp.ndarray:
    """tokens [B, S] int32 -> logits [B, S, V] (compute in cfg.dtype,
    logits in float32)."""
    x = _hidden_states(params, tokens, cfg, mesh)
    return (x @ params["out"].astype(cfg.dtype)).astype(jnp.float32)


def loss_fn(
    params: Dict[str, Any],
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
) -> jnp.ndarray:
    """Next-token cross entropy; position S-1 is unsupervised (targets are
    tokens shifted left; same [B, S] shape keeps sp sharding aligned)."""
    if max(cfg.pp, 1) > 1 and mesh is not None:
        # pipelined training path: the head (final norm + unembed + NLL)
        # runs inside the pipeline's manual region on the last stage and
        # only SCALAR reductions cross the pp axis — the replicate-the-
        # activations psum the plain forward() pays is for logits
        # consumers, not the training loop
        return _pipelined_loss(params, tokens, cfg, mesh)
    b, s = tokens.shape
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    # Long-context memory wall: at s=32k vocab=32k the [B,S,V] f32 logits
    # alone are >4 GB and softmax doubles it — the attention ceiling
    # (flash) was solved but the HEAD would still OOM the chip. Chunk the
    # sequence through the unembed instead. Budget is PER DEVICE (logits
    # shard b over dp·fsdp and V over tp). Under sp>1 the s axis is
    # already sharded and a global-s scan would fight that sharding: the
    # dense path stays (its per-device logits are S/sp smaller), so scale
    # very long context under sp by adding sp shards, not chunking.
    if sp == 1 and _per_device_logit_elems(cfg, b, s, mesh) > _loss_chunk_elems():
        return _chunked_loss(params, tokens, cfg, mesh)
    logits = forward(params, tokens, cfg, mesh)
    targets = jnp.roll(tokens, -1, axis=1)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    mask = jnp.ones_like(nll).at[:, -1].set(0.0)
    return jnp.sum(nll * mask) / jnp.sum(mask)


def _loss_chunk_elems() -> int:
    """Logit-element budget above which the loss head chunks the sequence
    (default 2^27 ≈ 134M elems = 512 MB of f32 logits per live buffer).
    Override via TORCHFT_TPU_LOSS_CHUNK_ELEMS (also how tests force the
    chunked path on tiny shapes)."""
    import os

    try:
        return int(os.environ.get("TORCHFT_TPU_LOSS_CHUNK_ELEMS", 1 << 27))
    except ValueError:
        return 1 << 27


def _per_device_logit_elems(
    cfg: TransformerConfig, batch: int, seq_len: int, mesh
) -> int:
    """Per-device element count of the dense [B, S, V] logits: b shards
    over dp·fsdp, V over tp (the out matrix's tp sharding carries into
    the logits)."""
    batch_shards = vocab_shards = 1
    if mesh is not None:
        batch_shards = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        vocab_shards = mesh.shape.get("tp", 1)
    return (
        max(1, batch // batch_shards)
        * seq_len
        * max(1, cfg.vocab_size // vocab_shards)
    )


def _chunked_loss(
    params: Dict[str, Any],
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh=None,
) -> jnp.ndarray:
    """Cross entropy without materializing [B, S, V]: scan the unembed +
    softmax over sequence chunks, ``jax.checkpoint`` on the body so the
    backward rematerializes one chunk's logits at a time. Same numbers as
    the dense path (f32 log_softmax per position; accumulation order
    differs only in the final f32 sums)."""
    b, s = tokens.shape
    h = _hidden_states(params, tokens, cfg, mesh)
    out_w = params["out"].astype(cfg.dtype)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones((b, s), jnp.float32).at[:, -1].set(0.0)

    # chunk size straight from the per-device budget; s needn't divide —
    # the tail chunk is padded and masked out (any s, prime or odd, gets
    # full chunking)
    budget = max(1, _loss_chunk_elems())
    per_pos = _per_device_logit_elems(cfg, b, 1, mesh)
    chunk = max(1, min(s, budget // max(1, per_pos)))
    if chunk >= 128:
        chunk -= chunk % 128  # lane-aligned chunks
    n_chunks = -(-s // chunk)
    pad = n_chunks * chunk - s
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))  # zeros: padded positions

    hs = jnp.moveaxis(h.reshape(b, n_chunks, chunk, -1), 1, 0)
    ts = jnp.moveaxis(targets.reshape(b, n_chunks, chunk), 1, 0)
    ms = jnp.moveaxis(mask.reshape(b, n_chunks, chunk), 1, 0)

    @jax.checkpoint
    def body(carry, xt):
        h_c, t_c, m_c = xt
        logits = (h_c @ out_w).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, t_c[..., None], axis=-1)[..., 0]
        nll_sum, cnt = carry
        return (nll_sum + jnp.sum(nll * m_c), cnt + jnp.sum(m_c)), None

    (nll_sum, cnt), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (hs, ts, ms)
    )
    return nll_sum / cnt


def _pipelined_loss(
    params: Dict[str, Any],
    tokens: jnp.ndarray,
    cfg: TransformerConfig,
    mesh,
) -> jnp.ndarray:
    """pp>1 loss with the cheap pipeline exit (pipeline.py head_fn): same
    numbers as the forward()+loss composition, minus the O(activations)
    psum that existed only to replicate the last stage's outputs."""
    from torchft_tpu.parallel.pipeline import pipeline_forward

    b, s = tokens.shape
    dt = cfg.dtype
    pp = cfg.pp
    x = _embed_lookup(params, tokens, dt)
    layers = jax.tree_util.tree_map(lambda a: a.astype(dt), params["layers"])

    sp_size = mesh.shape.get("sp", 1)
    sp_manual = sp_size > 1
    stage_fn = _make_stage_fn(cfg, mesh, sp_manual=sp_manual)
    m = cfg.microbatches or pp
    assert b % m == 0, f"batch {b} must divide into {m} microbatches"
    x_mb = x.reshape(m, b // m, s, -1)
    # the shifted targets are built OUTSIDE the manual region so GSPMD
    # handles the cross-sp-block halo of the roll
    t_mb = jnp.roll(tokens, -1, axis=1).reshape(m, b // m, s)
    head_params = {
        "final_norm": params["final_norm"].astype(dt),
        "out": params["out"].astype(dt),
    }

    def head_fn(hp, outs, t):
        h = rms_norm(outs, hp["final_norm"], cfg.norm_eps)
        logits = (h @ hp["out"]).astype(jnp.float32)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logprobs, t[..., None], axis=-1)[..., 0]
        mask = jnp.ones_like(nll)
        if sp_manual:
            # global position S-1 lives in the LAST sp block only
            last_block = jax.lax.axis_index("sp") == sp_size - 1
            mask = mask.at[..., -1].set(jnp.where(last_block, 0.0, 1.0))
        else:
            mask = mask.at[..., -1].set(0.0)
        return {"nll": jnp.sum(nll * mask), "cnt": jnp.sum(mask)}

    res = pipeline_forward(
        layers,
        x_mb,
        stage_fn,
        mesh,
        head_fn=head_fn,
        head_params=head_params,
        head_extras=(t_mb,),
    )
    return res["nll"] / res["cnt"]
