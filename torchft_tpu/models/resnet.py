"""ResNet-18 (CIFAR variant) — the conv model family.

The reference's flagship real-data config is "ResNet-18 CIFAR-10 DDP with
kill/rejoin" (BASELINE.md config list; reference train_ddp.py:34-80 trains
it through torchvision). TPU-native rebuild: pure-JAX pytree params in
NHWC layout (the TPU conv-friendly layout — XLA lowers NHWC convs onto
the MXU without transposes), functional batch norm whose running stats
travel as explicit state (flax-style ``(params, batch_stats)``; torch's
module mutation has no JAX analogue), bf16 compute with f32 statistics.

DDP semantics match torch DDP: gradients average across replica groups;
batch-norm *running stats* stay local per group and ride the heal/disk
checkpoint state dict instead (torch DDP does not sync BN either —
broadcast-at-init + local updates).

CIFAR stem: 3×3 conv stride 1, no max-pool (the standard CIFAR ResNet-18
adaptation); stages [2,2,2,2] × channels [64,128,256,512].
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["ResNetConfig", "init", "apply", "loss_fn"]

_DN = ("NHWC", "HWIO", "NHWC")  # lax conv dimension numbers


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 10
    channels: Tuple[int, ...] = (64, 128, 256, 512)
    blocks_per_stage: Tuple[int, ...] = (2, 2, 2, 2)  # resnet-18
    bn_momentum: float = 0.9  # running = m*running + (1-m)*batch
    bn_eps: float = 1e-5
    dtype: Any = jnp.bfloat16  # compute dtype; stats/params stay f32


def _conv_init(key, kh, kw, cin, cout):
    # He/Kaiming normal (fan_out, relu) — the torchvision resnet init
    std = (2.0 / (kh * kw * cout)) ** 0.5
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * std


def _bn_init(c):
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
    }


def _bn_state(c):
    return {
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def init(rng, cfg: ResNetConfig = ResNetConfig()) -> Tuple[Dict, Dict]:
    """Returns ``(params, batch_stats)`` pytrees (both f32)."""
    n_convs = 2 + sum(cfg.blocks_per_stage) * 3  # stem + per-block worst case
    keys = iter(jax.random.split(rng, n_convs * 2 + 2))

    params: Dict[str, Any] = {
        "stem": {"conv": _conv_init(next(keys), 3, 3, 3, cfg.channels[0]),
                 "bn": _bn_init(cfg.channels[0])},
    }
    stats: Dict[str, Any] = {"stem": {"bn": _bn_state(cfg.channels[0])}}

    cin = cfg.channels[0]
    for s, (cout, n_blocks) in enumerate(
        zip(cfg.channels, cfg.blocks_per_stage)
    ):
        blocks = []
        blocks_stats = []
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk = {
                "conv1": _conv_init(next(keys), 3, 3, cin, cout),
                "bn1": _bn_init(cout),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout),
                # zero-init the residual's last BN scale (torchvision
                # zero_init_residual improves early training)
                "bn2": {**_bn_init(cout), "scale": jnp.zeros((cout,), jnp.float32)},
            }
            st = {"bn1": _bn_state(cout), "bn2": _bn_state(cout)}
            if stride != 1 or cin != cout:
                blk["down_conv"] = _conv_init(next(keys), 1, 1, cin, cout)
                blk["down_bn"] = _bn_init(cout)
                st["down_bn"] = _bn_state(cout)
            blocks.append(blk)
            blocks_stats.append(st)
            cin = cout
        params[f"stage{s}"] = blocks
        stats[f"stage{s}"] = blocks_stats

    params["fc"] = {
        "w": jax.random.normal(
            next(keys), (cfg.channels[-1], cfg.num_classes), jnp.float32
        )
        * (cfg.channels[-1] ** -0.5),
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params, stats


def _batch_norm(x, p, st, cfg: ResNetConfig, train: bool):
    """Returns (normalized x, new state). Stats compute in f32 regardless
    of the bf16 activations (small-batch variance in bf16 is garbage)."""
    if train:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        m = cfg.bn_momentum
        new_st = {
            "mean": m * st["mean"] + (1.0 - m) * mean,
            "var": m * st["var"] + (1.0 - m) * var,
        }
    else:
        mean, var = st["mean"], st["var"]
        new_st = st
    inv = jax.lax.rsqrt(var + cfg.bn_eps) * p["scale"]
    out = (x.astype(jnp.float32) - mean) * inv + p["bias"]
    return out.astype(x.dtype), new_st


def _block(x, blk, st, cfg: ResNetConfig, stride: int, train: bool):
    new_st = dict(st)
    y = jax.lax.conv_general_dilated(
        x, blk["conv1"].astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=_DN,
    )
    y, new_st["bn1"] = _batch_norm(y, blk["bn1"], st["bn1"], cfg, train)
    y = jax.nn.relu(y)
    y = jax.lax.conv_general_dilated(
        y, blk["conv2"].astype(x.dtype), (1, 1), "SAME", dimension_numbers=_DN
    )
    y, new_st["bn2"] = _batch_norm(y, blk["bn2"], st["bn2"], cfg, train)

    if "down_conv" in blk:
        x = jax.lax.conv_general_dilated(
            x, blk["down_conv"].astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=_DN,
        )
        x, new_st["down_bn"] = _batch_norm(
            x, blk["down_bn"], st["down_bn"], cfg, train
        )
    return jax.nn.relu(y + x), new_st


def apply(
    params: Dict,
    stats: Dict,
    images: jnp.ndarray,
    cfg: ResNetConfig = ResNetConfig(),
    train: bool = True,
) -> Tuple[jnp.ndarray, Dict]:
    """``images`` [B, 32, 32, 3] (NHWC, any float dtype) → (logits f32,
    new batch_stats). Pass ``train=False`` to use running stats."""
    x = images.astype(cfg.dtype)
    new_stats: Dict[str, Any] = {"stem": {}}
    x = jax.lax.conv_general_dilated(
        x, params["stem"]["conv"].astype(x.dtype), (1, 1), "SAME",
        dimension_numbers=_DN,
    )
    x, new_stats["stem"]["bn"] = _batch_norm(
        x, params["stem"]["bn"], stats["stem"]["bn"], cfg, train
    )
    x = jax.nn.relu(x)

    for s in range(len(cfg.channels)):
        blocks = params[f"stage{s}"]
        new_blocks = []
        for b, blk in enumerate(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            x, st = _block(x, blk, stats[f"stage{s}"][b], cfg, stride, train)
            new_blocks.append(st)
        new_stats[f"stage{s}"] = new_blocks

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global avg pool
    logits = x @ params["fc"]["w"] + params["fc"]["b"]
    return logits, new_stats


def loss_fn(
    params: Dict,
    stats: Dict,
    images: jnp.ndarray,
    labels: jnp.ndarray,
    cfg: ResNetConfig = ResNetConfig(),
) -> Tuple[jnp.ndarray, Dict]:
    """Mean cross-entropy; returns ``(loss, new_batch_stats)`` — pair with
    ``jax.value_and_grad(..., has_aux=True)``."""
    logits, new_stats = apply(params, stats, images, cfg, train=True)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll), new_stats
