"""Model families for the TPU-native framework.

The reference wraps user-supplied torch models (CIFAR CNN in train_ddp.py,
nn.Linear toys in tests); here the framework owns a mesh-aware model stack.
``transformer`` is the flagship: a decoder-only LM with dp/fsdp/pp/sp/tp/ep
shardings, dense or MoE FFNs, RoPE, RMSNorm and ring attention.
``resnet`` is the conv family (ResNet-18 CIFAR variant, NHWC, functional
batch norm) for the BASELINE "ResNet-18 CIFAR-10 DDP" config.
"""

from torchft_tpu.models import resnet
from torchft_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    forward,
    param_specs,
)

__all__ = [
    "TransformerConfig",
    "init_params",
    "loss_fn",
    "forward",
    "param_specs",
    "resnet",
]
