"""Standalone lighthouse CLI — ``python -m torchft_tpu.lighthouse``.

The ``torchft_lighthouse`` binary analogue (reference
src/bin/lighthouse.rs:10-23, CLI flags at src/lighthouse.rs:66-103). The
same server also ships as a native executable (``native/tft_lighthouse``)
for lighthouse-only boxes with no Python.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="torchft-tpu lighthouse: quorum coordinator + dashboard"
    )
    parser.add_argument("--bind", default="[::]:29510", help="host:port to bind")
    parser.add_argument(
        "--min_replicas", type=int, required=True,
        help="minimum replica groups required to form a quorum",
    )
    parser.add_argument("--join_timeout_ms", type=int, default=60000)
    parser.add_argument("--quorum_tick_ms", type=int, default=100)
    parser.add_argument("--heartbeat_timeout_ms", type=int, default=5000)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    from torchft_tpu.coordination import LighthouseServer

    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
    )
    logging.info(
        "lighthouse listening on %s (dashboard at /, Prometheus exposition "
        "at /metrics, JSON counters at /status.json)",
        server.address(),
    )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    server.shutdown()


if __name__ == "__main__":
    main()
