"""torchft_tpu — TPU-native per-step fault tolerance for replicated training.

A ground-up JAX/XLA re-design of the capabilities of torchft
(Krishn1412/torchft): dynamic quorums over replica groups (C++ Lighthouse),
per-group rank arbitration (C++ Manager), reconfigurable collectives, live
peer-to-peer checkpoint recovery, and training-loop wrappers (gradient
averaging, optimizer commit gating, LocalSGD/DiLoCo) — built on
pjit/shard_map meshes rather than NCCL process groups.
"""

__version__ = "0.1.0"

# Public API (reference: torchft/__init__.py:7-20 exports Manager,
# Optimizer, DistributedDataParallel, DistributedSampler and the PGs; the
# TPU-native equivalents below).
from torchft_tpu.collectives import (  # noqa: E402
    Collectives,
    CollectivesDummy,
    CollectivesTcp,
    ErrorSwallowingCollectives,
    ManagedCollectives,
)
from torchft_tpu.data import DistributedSampler  # noqa: E402
from torchft_tpu.manager import Manager, WorldSizeMode  # noqa: E402

__all__ = [
    "Manager",
    "WorldSizeMode",
    "DistributedSampler",
    "Collectives",
    "CollectivesTcp",
    "CollectivesDevice",
    "CollectivesDeviceDist",
    "CollectivesDummy",
    "ErrorSwallowingCollectives",
    "ManagedCollectives",
]


def __getattr__(name):
    # Heavier wrappers import jax/optax; load lazily so the coordination
    # layer stays importable on lighthouse-only hosts.
    if name == "telemetry":
        import torchft_tpu.telemetry as telemetry

        return telemetry
    if name == "ManagedOptimizer":
        from torchft_tpu.optim import ManagedOptimizer

        return ManagedOptimizer
    if name in ("LocalSGD", "DiLoCo"):
        import torchft_tpu.local_sgd as m

        return getattr(m, name)
    if name == "CollectivesProxy":
        from torchft_tpu.proxy import CollectivesProxy

        return CollectivesProxy
    if name == "CollectivesDevice":
        from torchft_tpu.collectives_device import CollectivesDevice

        return CollectivesDevice
    if name == "CollectivesDeviceDist":
        from torchft_tpu.collectives_device_dist import CollectivesDeviceDist

        return CollectivesDeviceDist
    if name == "FTTrainer":
        from torchft_tpu.parallel.ft import FTTrainer

        return FTTrainer
    if name == "ParameterServer":
        from torchft_tpu.parameter_server import ParameterServer

        return ParameterServer
    raise AttributeError(f"module 'torchft_tpu' has no attribute {name!r}")
