"""torchft_tpu — TPU-native per-step fault tolerance for replicated training.

A ground-up JAX/XLA re-design of the capabilities of torchft
(Krishn1412/torchft): dynamic quorums over replica groups (C++ Lighthouse),
per-group rank arbitration (C++ Manager), reconfigurable collectives, live
peer-to-peer checkpoint recovery, and training-loop wrappers (gradient
averaging, optimizer commit gating, LocalSGD/DiLoCo) — built on
pjit/shard_map meshes rather than NCCL process groups.
"""

__version__ = "0.1.0"
