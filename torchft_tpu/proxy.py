"""Subprocess-isolated collectives — the "Baby" process-group analogue.

Reference: ProcessGroupBabyGloo/BabyNCCL (process_group.py:795-1329): the
real transport runs in a *spawned child process* so a wedged or crashed
backend can be SIGKILLed and respawned without taking down the trainer.
On TPU the same hazard exists for the host-side DCN data plane (a peer
dies mid-collective and the socket never errors); `CollectivesProxy` wraps
any `Collectives` backend the same way:

* ``configure`` kills the previous child and spawns a fresh one that
  builds the backend and rendezvouses;
* every op ships its arrays to the child over monitored queues, executes
  synchronously there, and the result is copied back into the caller's
  buffers (in-place semantics preserved);
* child death surfaces as RuntimeError on the next op within ~1s — the
  Manager latches it and reconfigures at the next quorum.

Large ``allreduce`` payloads (gradient buckets) travel through POSIX
shared memory — the ``_maybe_share_tensors`` analogue
(process_group.py:775-786): the parent stages the buffers into a per-op
segment, the child runs the backend's in-place ring directly on the
mapped views, and the parent copies the reduced bytes back — one copy
each way instead of pickling megabytes through a pipe twice. Small or
non-numpy payloads (and every cold op) stay on the pickle path, which
keeps the child fully crash-isolated.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import threading
from datetime import timedelta
from multiprocessing import shared_memory
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu.checkpointing.serialization import _resolve_dtype
from torchft_tpu.collectives import Collectives, ReduceOp, Work
from torchft_tpu.futures import Future
from torchft_tpu.multiprocessing import MonitoredQueue

logger = logging.getLogger(__name__)

__all__ = ["CollectivesProxy"]

# below this total, pickling through the queue beats shm setup syscalls
_SHM_MIN_BYTES = 1 << 16
# the child attaches via /dev/shm/{name}, which only exists on Linux; on
# other POSIX platforms the pickle path works everywhere (round-2 advisor
# finding). Platform property — computed once, not per op.
_HAS_DEV_SHM = os.path.isdir("/dev/shm")


def _buf_views(buf, metas: List[Tuple[int, Tuple[int, ...], str]]) -> List[np.ndarray]:
    # go through a uint8 view: ml_dtypes (bfloat16/fp8) reject the raw
    # buffer protocol that np.ndarray(buffer=...) uses
    views = []
    for off, shape, dt in metas:
        dtype = _resolve_dtype(dt)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        views.append(
            np.frombuffer(buf, np.uint8, count=nbytes, offset=off)
            .view(dtype)
            .reshape(shape)
        )
    return views


def _safe_close(shm: shared_memory.SharedMemory) -> None:
    """Close the mapping; numpy views hold buffer exports until refcounts
    drop, so fall back to a gc pass (a still-open mapping only holds
    virtual memory — unlink is what frees /dev/shm space, and it never
    fails on open mappings)."""
    try:
        shm.close()
    except BufferError:
        import gc

        gc.collect()
        try:
            shm.close()
        except BufferError:
            pass


def _child_allreduce(backend: Collectives, buf, metas, op) -> None:
    # scoped so the views (and the Work future that captures them) are
    # dropped before the caller closes the mapping
    backend.allreduce(_buf_views(buf, metas), op).wait()


def _copy_out(shm, metas, arrays: List[np.ndarray]) -> None:
    for dst, view in zip(arrays, _buf_views(shm.buf, metas)):
        np.copyto(dst, view)


def _copy_in(shm, metas, arrays: List[np.ndarray]) -> None:
    for view, a in zip(_buf_views(shm.buf, metas), arrays):
        np.copyto(view, a)


def _worker(factory, store_addr, rank, world_size, tx, rx) -> None:
    """Child main: build the backend, rendezvous, serve ops sequentially."""
    try:
        backend: Collectives = factory()
        backend.configure(store_addr, rank, world_size)
        rx.put(("ready", None, None))
    except Exception as e:  # noqa: BLE001
        rx.put(("err", None, e))
        return
    while True:
        cmd = tx.get()
        if cmd is None:
            backend.shutdown()
            return
        op_id, name, args, kwargs = cmd
        try:
            if name == "allreduce_shm":
                shm_name, metas, op = args
                # attach by raw mmap of the POSIX segment: SharedMemory's
                # attach path registers with the resource tracker (CPython
                # <=3.12 has no track=False), which would both leak a
                # registration per op and let a dying child's tracker
                # unlink segments the parent still owns; a plain mmap has
                # no tracker involvement at all
                import mmap as mmap_mod
                import os

                fd = os.open(f"/dev/shm/{shm_name}", os.O_RDWR)
                try:
                    buf = mmap_mod.mmap(fd, 0)
                finally:
                    os.close(fd)
                try:
                    # the backend reduces IN PLACE on the mapped views; the
                    # reduced bytes are visible to the parent with no
                    # return payload
                    _child_allreduce(backend, buf, metas, op)
                    result = None
                finally:
                    try:
                        buf.close()
                    except BufferError:
                        pass  # views freed with the op; mapping dies with us
            elif name in ("plane_info", "wire_codec"):
                # metadata query, not an op: returns a plain string
                result = getattr(backend, name)()
            else:
                work = getattr(backend, name)(*args, **kwargs)
                result = work.wait()
            rx.put(("ok", op_id, result))
        except Exception as e:  # noqa: BLE001
            rx.put(("err", op_id, e))


class CollectivesProxy(Collectives):
    """Run a Collectives backend in a kill-safe child process."""

    def plane_info(self) -> str:
        # the inner backend lives in the child; report its live transport
        # under the isolation-layer prefix (fetched once per configure —
        # a silent CMA→TCP fallback must be visible on the dashboard, and
        # the kill-safe proxy deployment is exactly where that label was
        # being lost; ADVICE r5 #2)
        inner = self._inner_plane
        return f"proxy:{inner}" if inner else "proxy"

    def wire_codec(self) -> str:
        # fetched with the plane label at configure: the codec the child
        # backend actually rides (error feedback keys off it)
        return self._inner_codec or "f32"

    def __init__(
        self,
        factory: Callable[[], Collectives],
        timeout: timedelta = timedelta(seconds=60),
    ) -> None:
        """``factory`` must be picklable (module-level callable) — it runs
        in the spawned child to build the real backend."""
        self._factory = factory
        self._timeout = timeout
        self._ctx = mp.get_context("spawn")
        self._proc: Optional[mp.Process] = None
        self._tx: Optional[mp.Queue] = None
        self._rx: Optional[MonitoredQueue] = None
        self._rank = -1
        self._world = 0
        self._op_id = 0
        self._generation = 0
        self._pending: Dict[int, Future] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._drain: Optional[threading.Thread] = None
        self._inner_plane = ""  # child backend's live plane label
        self._inner_codec = ""  # child backend's live wire codec

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self.shutdown()
        self._rank, self._world = rank, world_size
        with self._lock:
            self._generation += 1
            gen = self._generation
        tx = self._ctx.Queue()
        rx = MonitoredQueue(self._ctx.Queue())
        proc = self._ctx.Process(
            target=_worker,
            args=(self._factory, store_addr, rank, world_size, tx, rx._q),
            daemon=True,
        )
        proc.start()
        try:
            status, _, err = rx.get(proc, timeout=self._timeout)
            if status == "err":
                raise err
        except BaseException:
            # never leave a live undrained child behind a failed handshake
            proc.kill()
            proc.join(timeout=2)
            raise
        self._proc, self._tx, self._rx = proc, tx, rx
        # drain thread closes over its own generation's proc/rx so a stale
        # thread from a previous child can never touch the new pending map
        self._drain = threading.Thread(
            target=self._drain_loop, args=(proc, rx, gen), daemon=True,
            name="tft_proxy_drain",
        )
        self._drain.start()
        # cache the child's live plane label once per epoch: configure is
        # where a backend settles its transport (e.g. CMA probe fails →
        # TCP), so one RPC here keeps plane_info() truthful and free
        self._inner_plane = ""
        self._inner_codec = ""
        try:
            from torchft_tpu.futures import future_wait

            self._inner_plane = str(
                future_wait(
                    self._submit("plane_info").get_future(),
                    timedelta(seconds=5),
                )
            )
            self._inner_codec = str(
                future_wait(
                    self._submit("wire_codec").get_future(),
                    timedelta(seconds=5),
                )
            )
        except Exception:  # noqa: BLE001 — label is best-effort cosmetics
            pass

    def _drain_loop(self, proc, rx: MonitoredQueue, gen: int) -> None:
        while True:
            try:
                status, op_id, payload = rx.get(proc, timeout=None)
            except Exception as e:  # noqa: BLE001 — child died: fail all pending
                with self._lock:
                    if gen != self._generation:
                        return  # a newer generation owns the pending map
                    pending, self._pending = self._pending, {}
                for fut in pending.values():
                    fut.set_exception(
                        RuntimeError(f"collectives child died: {e}")
                    )
                return
            with self._lock:
                if gen != self._generation:
                    return
                fut = self._pending.pop(op_id, None)
            if fut is None:
                continue
            if status == "ok":
                fut.set_result(payload)
            else:
                fut.set_exception(payload)

    def _submit(self, name: str, *args, **kwargs) -> Work:
        from torchft_tpu.faultinject.core import fault_point

        # parent-side site; the child backend's own hooks fire too (it
        # inherits TORCHFT_FAULT_SCHEDULE through the spawn env), so a
        # schedule can target either side of the isolation boundary
        fault_point("collective.issue", match=f"proxy.{name}")
        proc = self._proc
        if proc is None or not proc.is_alive():
            return Work(
                Future.failed(RuntimeError("collectives child is not running"))
            )
        fut: Future = Future()
        with self._lock:
            self._op_id += 1
            op_id = self._op_id
            self._pending[op_id] = fut
        try:
            MonitoredQueue(self._tx).put(
                (op_id, name, args, kwargs), proc, timeout=self._timeout
            )
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._pending.pop(op_id, None)
            return Work(Future.failed(e))
        return Work(fut)

    def _copy_back(self, work: Work, arrays: List[np.ndarray]) -> Work:
        """In-place semantics: copy the child's result into caller buffers."""

        def copy(fut: Future):
            result = fut.value()
            out = result if isinstance(result, list) else [result]
            if len(out) != len(arrays):
                raise RuntimeError(
                    f"proxy result count mismatch: sent {len(arrays)} "
                    f"arrays, child returned {len(out)}"
                )
            for dst, src in zip(arrays, out):
                if not isinstance(src, np.ndarray) or dst.shape != src.shape:
                    # a silent skip here would leave the caller's buffer
                    # stale while the Work reports success
                    raise RuntimeError(
                        f"proxy result mismatch: expected ndarray{dst.shape},"
                        f" got {type(src).__name__}"
                        f"{getattr(src, 'shape', '')}"
                    )
                np.copyto(dst, src)
            return result

        return Work(work.get_future().then(copy))

    # -- collectives --

    def allreduce(self, arrays, op: ReduceOp = ReduceOp.SUM) -> Work:
        total = sum(getattr(a, "nbytes", 0) for a in arrays)
        if (
            total >= _SHM_MIN_BYTES
            and _HAS_DEV_SHM
            and all(
                isinstance(a, np.ndarray) and a.flags.c_contiguous
                for a in arrays
            )
        ):
            return self._allreduce_shm(arrays, op)
        return self._copy_back(self._submit("allreduce", arrays, op), arrays)

    def _allreduce_shm(self, arrays: List[np.ndarray], op: ReduceOp) -> Work:
        """Hot path: stage buffers in a per-op shared-memory segment; the
        child reduces in place on the mapping, the parent copies back."""
        total = sum(a.nbytes for a in arrays)
        shm = shared_memory.SharedMemory(create=True, size=total)
        metas: List[Tuple[int, Tuple[int, ...], str]] = []
        off = 0
        for a in arrays:
            metas.append((off, a.shape, a.dtype.name))
            off += a.nbytes
        try:
            _copy_in(shm, metas, arrays)
        except BaseException:
            _safe_close(shm)
            shm.unlink()
            raise

        work = self._submit("allreduce_shm", shm.name, metas, op)

        def copy_back(fut: Future):
            try:
                fut.value()  # surface child errors
                _copy_out(shm, metas, arrays)
                return arrays
            finally:
                try:
                    shm.unlink()  # frees /dev/shm even with open mappings
                except FileNotFoundError:
                    pass
                _safe_close(shm)

        return Work(work.get_future().then(copy_back))

    def allgather(self, arr) -> Work:
        return self._submit("allgather", arr)

    def broadcast(self, arr, root: int = 0) -> Work:
        return self._copy_back(self._submit("broadcast", arr, root), [arr])

    def reduce_scatter(self, arrays, op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._submit("reduce_scatter", arrays, op)

    def alltoall(self, arrays) -> Work:
        return self._submit("alltoall", arrays)

    def send(self, arr, dst: int, tag: int = 0) -> Work:
        return self._submit("send", arr, dst, tag)

    def recv(self, arr, src: int, tag: int = 0) -> Work:
        return self._copy_back(self._submit("recv", arr, src, tag), [arr])

    def barrier(self) -> Work:
        return self._submit("barrier")

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def num_active_work(self) -> int:
        with self._lock:
            return len(self._pending)

    def kill_child(self) -> None:
        """Test hook / emergency hatch: SIGKILL the child (simulates a
        wedged backend)."""
        if self._proc is not None and self._proc.is_alive():
            self._proc.kill()

    def shutdown(self) -> None:
        if self._proc is not None:
            try:
                if self._proc.is_alive():
                    self._tx.put(None)
                self._proc.join(timeout=2)
                if self._proc.is_alive():
                    self._proc.kill()
                    self._proc.join(timeout=2)
            except Exception:  # noqa: BLE001
                pass
            self._proc = None
            self._tx = None
            self._rx = None
