"""Subprocess-isolated collectives — the "Baby" process-group analogue.

Reference: ProcessGroupBabyGloo/BabyNCCL (process_group.py:795-1329): the
real transport runs in a *spawned child process* so a wedged or crashed
backend can be SIGKILLed and respawned without taking down the trainer.
On TPU the same hazard exists for the host-side DCN data plane (a peer
dies mid-collective and the socket never errors); `CollectivesProxy` wraps
any `Collectives` backend the same way:

* ``configure`` kills the previous child and spawns a fresh one that
  builds the backend and rendezvouses;
* every op ships its arrays to the child over monitored queues, executes
  synchronously there, and the result is copied back into the caller's
  buffers (in-place semantics preserved);
* child death surfaces as RuntimeError on the next op within ~1s — the
  Manager latches it and reconfigures at the next quorum.

Payloads travel by pickle; for the cross-replica-group control volumes this
framework routes through the proxy (gradient buckets), the copy is cheap
relative to the network hop, and unlike the reference's shared-memory
tensors it keeps the child fully crash-isolated.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import threading
from datetime import timedelta
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from torchft_tpu.collectives import Collectives, ReduceOp, Work
from torchft_tpu.futures import Future
from torchft_tpu.multiprocessing import MonitoredQueue

logger = logging.getLogger(__name__)

__all__ = ["CollectivesProxy"]


def _worker(factory, store_addr, rank, world_size, tx, rx) -> None:
    """Child main: build the backend, rendezvous, serve ops sequentially."""
    try:
        backend: Collectives = factory()
        backend.configure(store_addr, rank, world_size)
        rx.put(("ready", None, None))
    except Exception as e:  # noqa: BLE001
        rx.put(("err", None, e))
        return
    while True:
        cmd = tx.get()
        if cmd is None:
            backend.shutdown()
            return
        op_id, name, args, kwargs = cmd
        try:
            work = getattr(backend, name)(*args, **kwargs)
            result = work.wait()
            rx.put(("ok", op_id, result))
        except Exception as e:  # noqa: BLE001
            rx.put(("err", op_id, e))


class CollectivesProxy(Collectives):
    """Run a Collectives backend in a kill-safe child process."""

    def __init__(
        self,
        factory: Callable[[], Collectives],
        timeout: timedelta = timedelta(seconds=60),
    ) -> None:
        """``factory`` must be picklable (module-level callable) — it runs
        in the spawned child to build the real backend."""
        self._factory = factory
        self._timeout = timeout
        self._ctx = mp.get_context("spawn")
        self._proc: Optional[mp.Process] = None
        self._tx: Optional[mp.Queue] = None
        self._rx: Optional[MonitoredQueue] = None
        self._rank = -1
        self._world = 0
        self._op_id = 0
        self._generation = 0
        self._pending: Dict[int, Future] = {}
        self._lock = threading.Lock()
        self._drain: Optional[threading.Thread] = None

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self.shutdown()
        self._rank, self._world = rank, world_size
        with self._lock:
            self._generation += 1
            gen = self._generation
        tx = self._ctx.Queue()
        rx = MonitoredQueue(self._ctx.Queue())
        proc = self._ctx.Process(
            target=_worker,
            args=(self._factory, store_addr, rank, world_size, tx, rx._q),
            daemon=True,
        )
        proc.start()
        try:
            status, _, err = rx.get(proc, timeout=self._timeout)
            if status == "err":
                raise err
        except BaseException:
            # never leave a live undrained child behind a failed handshake
            proc.kill()
            proc.join(timeout=2)
            raise
        self._proc, self._tx, self._rx = proc, tx, rx
        # drain thread closes over its own generation's proc/rx so a stale
        # thread from a previous child can never touch the new pending map
        self._drain = threading.Thread(
            target=self._drain_loop, args=(proc, rx, gen), daemon=True
        )
        self._drain.start()

    def _drain_loop(self, proc, rx: MonitoredQueue, gen: int) -> None:
        while True:
            try:
                status, op_id, payload = rx.get(proc, timeout=None)
            except Exception as e:  # noqa: BLE001 — child died: fail all pending
                with self._lock:
                    if gen != self._generation:
                        return  # a newer generation owns the pending map
                    pending, self._pending = self._pending, {}
                for fut in pending.values():
                    fut.set_exception(
                        RuntimeError(f"collectives child died: {e}")
                    )
                return
            with self._lock:
                if gen != self._generation:
                    return
                fut = self._pending.pop(op_id, None)
            if fut is None:
                continue
            if status == "ok":
                fut.set_result(payload)
            else:
                fut.set_exception(payload)

    def _submit(self, name: str, *args, **kwargs) -> Work:
        proc = self._proc
        if proc is None or not proc.is_alive():
            return Work(
                Future.failed(RuntimeError("collectives child is not running"))
            )
        fut: Future = Future()
        with self._lock:
            self._op_id += 1
            op_id = self._op_id
            self._pending[op_id] = fut
        try:
            MonitoredQueue(self._tx).put(
                (op_id, name, args, kwargs), proc, timeout=self._timeout
            )
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._pending.pop(op_id, None)
            return Work(Future.failed(e))
        return Work(fut)

    def _copy_back(self, work: Work, arrays: List[np.ndarray]) -> Work:
        """In-place semantics: copy the child's result into caller buffers."""

        def copy(fut: Future):
            result = fut.value()
            out = result if isinstance(result, list) else [result]
            if len(out) != len(arrays):
                raise RuntimeError(
                    f"proxy result count mismatch: sent {len(arrays)} "
                    f"arrays, child returned {len(out)}"
                )
            for dst, src in zip(arrays, out):
                if not isinstance(src, np.ndarray) or dst.shape != src.shape:
                    # a silent skip here would leave the caller's buffer
                    # stale while the Work reports success
                    raise RuntimeError(
                        f"proxy result mismatch: expected ndarray{dst.shape},"
                        f" got {type(src).__name__}"
                        f"{getattr(src, 'shape', '')}"
                    )
                np.copyto(dst, src)
            return result

        return Work(work.get_future().then(copy))

    # -- collectives --

    def allreduce(self, arrays, op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._copy_back(self._submit("allreduce", arrays, op), arrays)

    def allgather(self, arr) -> Work:
        return self._submit("allgather", arr)

    def broadcast(self, arr, root: int = 0) -> Work:
        return self._copy_back(self._submit("broadcast", arr, root), [arr])

    def reduce_scatter(self, arrays, op: ReduceOp = ReduceOp.SUM) -> Work:
        return self._submit("reduce_scatter", arrays, op)

    def alltoall(self, arrays) -> Work:
        return self._submit("alltoall", arrays)

    def send(self, arr, dst: int, tag: int = 0) -> Work:
        return self._submit("send", arr, dst, tag)

    def recv(self, arr, src: int, tag: int = 0) -> Work:
        return self._copy_back(self._submit("recv", arr, src, tag), [arr])

    def barrier(self) -> Work:
        return self._submit("barrier")

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def num_active_work(self) -> int:
        with self._lock:
            return len(self._pending)

    def kill_child(self) -> None:
        """Test hook / emergency hatch: SIGKILL the child (simulates a
        wedged backend)."""
        if self._proc is not None and self._proc.is_alive():
            self._proc.kill()

    def shutdown(self) -> None:
        if self._proc is not None:
            try:
                if self._proc.is_alive():
                    self._tx.put(None)
                self._proc.join(timeout=2)
                if self._proc.is_alive():
                    self._proc.kill()
                    self._proc.join(timeout=2)
            except Exception:  # noqa: BLE001
                pass
            self._proc = None
            self._tx = None
            self._rx = None
