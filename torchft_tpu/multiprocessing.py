"""Monitored IPC queues — child-liveness-aware multiprocessing plumbing.

Reference: torchft/multiprocessing.py:9-91. A plain mp.Queue.get() blocks
forever if the producer process died; `MonitoredQueue` polls the remote
process every second during get/put and raises RuntimeError the moment it
is gone, and re-raises Exception payloads on get. This is what makes the
subprocess-isolated collectives (`CollectivesProxy`) killable rather than
wedging the trainer.
"""

from __future__ import annotations

import multiprocessing as mp
import queue as _q
import time
from datetime import timedelta
from typing import Any, Optional, Union

__all__ = ["MonitoredQueue"]

_POLL_S = 1.0


class MonitoredQueue:
    def __init__(self, q: mp.Queue) -> None:
        self._q = q

    def _deadline(self, timeout: Optional[Union[float, timedelta]]) -> Optional[float]:
        if timeout is None:
            return None
        secs = timeout.total_seconds() if isinstance(timeout, timedelta) else timeout
        return time.monotonic() + secs

    def get(
        self,
        proc: mp.Process,
        timeout: Optional[Union[float, timedelta]] = None,
    ) -> Any:
        deadline = self._deadline(timeout)
        while True:
            if not proc.is_alive():
                raise RuntimeError(f"process {proc.pid} is dead (exitcode {proc.exitcode})")
            wait = _POLL_S
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("queue.get timed out")
                wait = min(wait, remaining)
            try:
                item = self._q.get(timeout=wait)
            except _q.Empty:
                continue
            if isinstance(item, Exception):
                raise item
            return item

    def put(
        self,
        item: Any,
        proc: mp.Process,
        timeout: Optional[Union[float, timedelta]] = None,
    ) -> None:
        deadline = self._deadline(timeout)
        while True:
            if not proc.is_alive():
                raise RuntimeError(f"process {proc.pid} is dead (exitcode {proc.exitcode})")
            wait = _POLL_S
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("queue.put timed out")
                wait = min(wait, remaining)
            try:
                self._q.put(item, timeout=wait)
                return
            except _q.Full:
                continue

    def close(self) -> None:
        self._q.close()
