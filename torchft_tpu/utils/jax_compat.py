"""Polyfills for older JAX runtimes.

The codebase targets the current stable JAX surface — ``jax.shard_map``,
``jax.set_mesh``, ``jax.sharding.get_abstract_mesh`` — but deployment
containers may ship an older 0.4.x wheel where shard_map still lives in
``jax.experimental`` (with a ``mesh``-required, ``auto``-complement
signature), ``set_mesh`` does not exist (the ``Mesh`` object itself is the
context manager) and there is no ``get_abstract_mesh`` (the ambient mesh
lives in the thread resource env).

Importing this module installs the missing attributes ONTO the jax
namespace (only when absent — a current JAX is untouched), so both library
code and tests can use the one modern spelling. Every jax-adjacent module
in the package imports it, which also covers subprocess entry points that
bypass tests/conftest.py.
"""

from __future__ import annotations

import contextlib

import jax

__all__ = ["install", "enable_cpu_gloo_collectives"]


def enable_cpu_gloo_collectives() -> None:
    """Pick the gloo cross-process collectives backend for CPU
    multi-controller runtimes. On older jax the default is 'none' and the
    first computation spanning processes dies with "Multiprocess
    computations aren't implemented on the CPU backend"; newer jax
    defaults to gloo, where this is a no-op. Call before
    ``jax.distributed.initialize``. Only acts when JAX_PLATFORMS pins
    cpu — on real accelerators the platform's own collectives rule."""
    import os

    if not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass  # option renamed/removed: the runtime default must do


def _context_mesh():
    """The ambient physical mesh of the old resource env, or None.

    Returns None inside a shard_map manual region: callers use this to
    gate ``with_sharding_constraint`` (modern jax keeps non-manual axes
    constrainable there, but the 0.4.x partitioner cannot — the constraint
    must be dropped, which is safe: the ex-auto axes are replicated inside
    translated regions, see ``shard_map`` below)."""
    from jax._src import core, mesh as mesh_lib

    if core.nonempty_axis_env():
        return None
    m = mesh_lib.thread_resources.env.physical_mesh
    return None if m is None or m.empty else m


def install() -> None:
    """Idempotently polyfill the modern API onto an old jax namespace."""
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(
            f,
            mesh=None,
            in_specs=None,
            out_specs=None,
            axis_names=None,
            check_vma=None,
            **kw,
        ):
            """Modern jax.shard_map surface over the 0.4.x experimental
            one. Mesh defaults to the ambient context mesh. A PARTIAL
            manual region (``axis_names`` ⊂ mesh axes) is translated to a
            FULL-manual one: 0.4.x partial-auto is broken (axis_index
            lowers to a PartitionId the SPMD partitioner rejects; scan +
            ppermute under auto axes trips a partitioner CHECK). Specs
            leave the ex-auto axes unmentioned, so inputs arrive
            replicated over them (GSPMD gathers at the region boundary)
            and outputs return replicated — semantics preserved at some
            gather/compute redundancy, which is acceptable on the old
            runtime. Replication of ex-auto axes cannot be certified by
            the 0.4.x rep checker, so it is disabled for translated
            regions. ``check_vma`` maps to ``check_rep``."""
            if mesh is None:
                mesh = _context_mesh()
                if mesh is None:
                    raise ValueError(
                        "shard_map: no mesh argument and no context mesh "
                        "(enter one with jax.set_mesh(mesh))"
                    )
            if check_vma is not None:
                kw.setdefault("check_rep", bool(check_vma))
            if axis_names is not None and frozenset(axis_names) != frozenset(
                mesh.axis_names
            ):
                kw["check_rep"] = False
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
            )

        jax.shard_map = shard_map

    if not hasattr(jax, "set_mesh"):

        @contextlib.contextmanager
        def set_mesh(mesh):
            # the Mesh object is its own context manager in 0.4.x; it
            # installs the resource env that with_sharding_constraint and
            # context-mesh shard_map read
            with mesh:
                yield mesh

        jax.set_mesh = set_mesh

    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _context_mesh

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axes, to=None):  # noqa: ARG001 — modern signature
            # 0.4.x has no varying-manual-axes type system, so casting a
            # value's VMA set is the identity
            return x

        jax.lax.pcast = pcast


install()
