"""Backend-selection helper shared by the example trainers and workers."""

from __future__ import annotations

import os

__all__ = ["pin_platform_from_env"]


def pin_platform_from_env() -> None:
    """Make ``JAX_PLATFORMS`` authoritative.

    Some environments register an accelerator PJRT plugin from
    sitecustomize that wins over the env var; setting the config key
    explicitly restores the documented env contract (e.g.
    ``JAX_PLATFORMS=cpu`` for the virtual CPU mesh in tests/launch
    recipes). Call before any other jax API touches the backend."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
