"""Binary wire codec — Python twin of ``native/wire.h``.

The C++ coordination core and Python speak the same compact TLV encoding
(the protobuf analogue for the reference's ``proto/torchft.proto``). Keep the
two implementations in sync.

Python values map as::

    int        <-> I64          float      <-> F64
    bool       <-> BOOL         str        <-> STR
    bytes      <-> BYTES        list       <-> LIST
    dict       <-> MAP          None       <-> NONE
"""

from __future__ import annotations

import struct
from typing import Any, Tuple

_I64 = 1
_F64 = 2
_BOOL = 3
_STR = 4
_BYTES = 5
_LIST = 6
_MAP = 7
_NONE = 8


def encode(v: Any) -> bytes:
    out = bytearray()
    _encode(v, out)
    return bytes(out)


def _encode(v: Any, out: bytearray) -> None:
    # NOTE: bool before int — bool is an int subclass.
    if v is None:
        out.append(_NONE)
    elif isinstance(v, bool):
        out.append(_BOOL)
        out.append(1 if v else 0)
    elif isinstance(v, int):
        out.append(_I64)
        out += struct.pack("<q", v)
    elif isinstance(v, float):
        out.append(_F64)
        out += struct.pack("<d", v)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(_STR)
        out += struct.pack("<I", len(b))
        out += b
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.append(_BYTES)
        out += struct.pack("<I", len(b))
        out += b
    elif isinstance(v, (list, tuple)):
        out.append(_LIST)
        out += struct.pack("<I", len(v))
        for e in v:
            _encode(e, out)
    elif isinstance(v, dict):
        out.append(_MAP)
        out += struct.pack("<I", len(v))
        # Sorted keys to match C++ std::map ordering (determinism only;
        # decoding does not depend on order).
        for k in sorted(v.keys()):
            kb = k.encode("utf-8")
            out += struct.pack("<H", len(kb))
            out += kb
            _encode(v[k], out)
    else:
        raise TypeError(f"cannot encode {type(v)}")


def decode(buf: bytes) -> Any:
    v, _ = _decode(memoryview(buf), 0)
    return v


def _decode(buf: memoryview, off: int) -> Tuple[Any, int]:
    tag = buf[off]
    off += 1
    if tag == _NONE:
        return None, off
    if tag == _I64:
        return struct.unpack_from("<q", buf, off)[0], off + 8
    if tag == _F64:
        return struct.unpack_from("<d", buf, off)[0], off + 8
    if tag == _BOOL:
        return buf[off] != 0, off + 1
    if tag == _STR:
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        return bytes(buf[off : off + n]).decode("utf-8"), off + n
    if tag == _BYTES:
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        return bytes(buf[off : off + n]), off + n
    if tag == _LIST:
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        lst = []
        for _ in range(n):
            e, off = _decode(buf, off)
            lst.append(e)
        return lst, off
    if tag == _MAP:
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        d = {}
        for _ in range(n):
            (klen,) = struct.unpack_from("<H", buf, off)
            off += 2
            k = bytes(buf[off : off + klen]).decode("utf-8")
            off += klen
            d[k], off = _decode(buf, off)
        return d, off
    raise ValueError(f"bad wire tag {tag} at offset {off - 1}")
