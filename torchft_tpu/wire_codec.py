"""Wire codecs + error feedback — the compression layer of the cross-group
gradient plane (docs/wire_plane.md).

The cross-group average is wire-bound (BENCH_r05: 0.144 GB/s serial /
0.609 GB/s pipelined on the host plane — a derived ~44 s per llama2-7B
f32 gradient tree), so the wire carries QUANTIZED bytes while local
accumulation stays f32. A codec maps an f32 chunk to its wire form and
back:

* ``f32``      — identity (4 bytes/elem), the exact default.
* ``bfloat16`` — round-to-nearest-even truncation (2 bytes/elem).
* ``int8``     — per-chunk symmetric quantization (1 byte/elem + a 4-byte
  f32 scale header per chunk): ``scale = max|x| / 127``,
  ``q = clip(rint(x / scale), -127, 127)``.

Codecs are applied ON THE WIRE, before striping: both the native striped
plane (native/dataplane.cc mirrors the byte formats here exactly) and the
Python ring (collectives.py) ship codec bytes per hop while reducing in
f32 locally. Bit-identity of the decoded average across replica groups —
the faultmatrix invariant — is guaranteed BY CONSTRUCTION, not by fp
luck: after the reduce-scatter phase the owner of each fully-reduced
chunk encodes it once, decodes those same bytes back into its own copy,
and the allgather phase forwards the owner's wire bytes VERBATIM; every
rank decodes identical bytes.

Quantization is lossy; :class:`ErrorFeedback` keeps convergence honest
(Vogels et al., PowerSGD, NeurIPS 2019; Karimireddy et al., EF-SGD): the
residual of each step's quantization is accumulated and added back before
the next quantize, so the error stays bounded instead of compounding.
Accumulators are commit-lineage-aware — ``commit()`` promotes the step's
pending residual, ``rollback()`` discards it (an aborted or vetoed step
must not corrupt the residual state) — and serialize through
``state_dict``/``load_state_dict`` so heal/checkpoint round-trips carry
them.

:func:`lowrank_compress`/:func:`lowrank_decompress` add the optional
PowerSGD-style rank-r projection for the DiLoCo outer step (the one place
staleness already tolerates approximation): the projection basis is drawn
from a SEEDED rng keyed on (leaf, sync ordinal), so every replica group
derives the same basis without communicating it.

All scratch is preallocated per codec instance and grown monotonically —
the hot path never allocates per chunk per round (the ``astype`` tax the
old ring paid).
"""

from __future__ import annotations

import logging
import os
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "WireCodec",
    "F32Codec",
    "Bf16Codec",
    "Int8Codec",
    "get_codec",
    "CODEC_NAMES",
    "ErrorFeedback",
    "lowrank_basis",
    "lowrank_compress",
    "lowrank_decompress",
]

_SCALE_HDR = struct.Struct("<f")  # int8 per-chunk scale prefix (LE f32)

CODEC_NAMES = ("f32", "bfloat16", "int8")


class WireCodec:
    """One codec instance per collectives backend: owns the preallocated
    encode/decode scratch (single-threaded use on the collective op
    thread). ``lossy`` codecs only apply to f32 arrays; callers route
    other dtypes through the identity codec."""

    name = "f32"
    lossy = False

    def __init__(self) -> None:
        self._wire: Optional[np.ndarray] = None  # uint8 encode scratch
        self._f32: Optional[np.ndarray] = None   # f32 decode/temp scratch

    # -- layout --

    def wire_nbytes(self, nelems: int, itemsize: int = 4) -> int:
        raise NotImplementedError

    # -- scratch --

    def ensure_capacity(self, max_elems: int, itemsize: int = 4) -> None:
        """Grow the scratch to hold one max-size chunk; call once per op
        (amortized: buffers persist and only ever grow)."""
        need = self.wire_nbytes(max_elems, itemsize)
        if self._wire is None or self._wire.size < need:
            self._wire = np.empty(need, dtype=np.uint8)
        if self.lossy and (self._f32 is None or self._f32.size < max_elems):
            self._f32 = np.empty(max_elems, dtype=np.float32)

    # -- codec --

    def encode_into(self, src: np.ndarray) -> memoryview:
        """Encode the 1-D chunk ``src`` into this codec's scratch; returns
        the wire-byte view (valid until the next encode_into)."""
        raise NotImplementedError

    def decode_into(self, wire: np.ndarray, dst: np.ndarray) -> None:
        """Decode wire bytes (uint8 array/view) into the 1-D chunk
        ``dst``, overwriting it."""
        raise NotImplementedError

    def decode_tmp(self, wire: np.ndarray, nelems: int) -> np.ndarray:
        """Decode into the codec's own f32 scratch (for reduce steps);
        the view is valid until the next decode_tmp/encode_into."""
        raise NotImplementedError

    def roundtrip(self, arr: np.ndarray) -> None:
        """In-place ``arr = decode(encode(arr))`` — projects onto the wire
        grid (what error feedback measures its residual against)."""
        flat = arr.reshape(-1)
        self.ensure_capacity(flat.size, arr.dtype.itemsize)
        w = self.encode_into(flat)
        self.decode_into(np.frombuffer(w, dtype=np.uint8), flat)


class F32Codec(WireCodec):
    """Identity codec — raw bytes on the wire, any dtype."""

    name = "f32"
    lossy = False

    def wire_nbytes(self, nelems: int, itemsize: int = 4) -> int:
        return nelems * itemsize

    def encode_into(self, src: np.ndarray) -> memoryview:
        # zero-copy: the chunk's own bytes ARE the wire form
        src = np.ascontiguousarray(src)
        try:
            return memoryview(src).cast("B")
        except (ValueError, TypeError):  # ml_dtypes reject buffer protocol
            return memoryview(src.view(np.uint8)).cast("B")

    def decode_into(self, wire: np.ndarray, dst: np.ndarray) -> None:
        dst.view(np.uint8).reshape(-1)[:] = np.frombuffer(
            wire, dtype=np.uint8, count=dst.nbytes
        )

    def decode_tmp(self, wire: np.ndarray, nelems: int) -> np.ndarray:
        raise NotImplementedError(
            "identity codec callers reduce straight from the typed view"
        )

    def roundtrip(self, arr: np.ndarray) -> None:  # exact — nothing to do
        return


class Bf16Codec(WireCodec):
    """f32 → bfloat16 truncation (round-to-nearest-even), 2 bytes/elem.
    Matches numpy/ml_dtypes ``astype`` semantics and the native plane's
    ``f32_to_bf16`` bit for bit."""

    name = "bfloat16"
    lossy = True

    def __init__(self) -> None:
        super().__init__()
        import ml_dtypes  # registers the bfloat16 dtype

        self._bf16 = np.dtype(ml_dtypes.bfloat16)

    def wire_nbytes(self, nelems: int, itemsize: int = 4) -> int:
        return nelems * 2

    def encode_into(self, src: np.ndarray) -> memoryview:
        n = src.size
        self.ensure_capacity(n)
        view = self._wire[: n * 2].view(self._bf16)
        view[:] = src  # casting assignment: no allocation
        return memoryview(self._wire[: n * 2])

    def decode_into(self, wire: np.ndarray, dst: np.ndarray) -> None:
        n = dst.size
        dst[:] = np.frombuffer(wire, dtype=self._bf16, count=n)

    def decode_tmp(self, wire: np.ndarray, nelems: int) -> np.ndarray:
        self.ensure_capacity(nelems)
        out = self._f32[:nelems]
        out[:] = np.frombuffer(wire, dtype=self._bf16, count=nelems)
        return out


class Int8Codec(WireCodec):
    """Per-chunk symmetric int8 quantization: a 4-byte f32 scale header
    followed by one int8 per element. ``scale = max|x|/127`` adapts per
    chunk per hop, so partial sums in the reduce-scatter phase re-quantize
    at their own magnitude. A chunk containing non-finite values encodes
    ``scale = NaN`` + zero payload, so NaN propagates loudly through the
    decode instead of being laundered into a finite average."""

    name = "int8"
    lossy = True

    def wire_nbytes(self, nelems: int, itemsize: int = 4) -> int:
        return 4 + nelems

    def encode_into(self, src: np.ndarray) -> memoryview:
        n = src.size
        self.ensure_capacity(n)
        wire = self._wire[: 4 + n]
        tmp = self._f32[:n]
        np.abs(src, out=tmp)
        amax = float(tmp.max()) if n else 0.0
        q = wire[4:].view(np.int8)
        if not np.isfinite(amax):
            _SCALE_HDR.pack_into(wire.data, 0, np.float32(np.nan))
            q.fill(0)
            return memoryview(wire)
        scale = np.float32(amax / 127.0) if amax > 0.0 else np.float32(0.0)
        _SCALE_HDR.pack_into(wire.data, 0, scale)
        if scale == 0.0:
            q.fill(0)
            return memoryview(wire)
        np.divide(src, scale, out=tmp)
        np.rint(tmp, out=tmp)
        np.clip(tmp, -127.0, 127.0, out=tmp)
        q[:] = tmp  # casting assignment
        return memoryview(wire)

    def _scale_of(self, wire: np.ndarray) -> float:
        return _SCALE_HDR.unpack_from(
            np.frombuffer(wire, dtype=np.uint8, count=4).tobytes(), 0
        )[0]

    def decode_into(self, wire: np.ndarray, dst: np.ndarray) -> None:
        n = dst.size
        scale = self._scale_of(wire)
        q = np.frombuffer(wire, dtype=np.int8, count=4 + n)[4:]
        dst[:] = q
        np.multiply(dst, np.float32(scale), out=dst)

    def decode_tmp(self, wire: np.ndarray, nelems: int) -> np.ndarray:
        self.ensure_capacity(nelems)
        out = self._f32[:nelems]
        self.decode_into(wire, out)
        return out


def get_codec(name: Optional[str]) -> WireCodec:
    """Codec by wire-dtype name (``None``/"f32"/"float32" → identity)."""
    if name in (None, "", "f32", "float32"):
        return F32Codec()
    if name == "bfloat16":
        return Bf16Codec()
    if name == "int8":
        return Int8Codec()
    raise ValueError(
        f"unknown wire codec {name!r}; expected one of {CODEC_NAMES}"
    )


# ---------------------------------------------------------------------------
# error feedback
# ---------------------------------------------------------------------------


class ErrorFeedback:
    """Persistent per-bucket quantization residuals with commit-lineage
    semantics.

    Per step, per bucket: ``apply(key, buf)`` adds the committed residual
    back into ``buf``, projects ``buf`` onto the codec's grid in place,
    and stages the new residual as PENDING. The caller then promotes or
    discards it with the step's fate: ``commit()`` after a committed
    step, ``rollback()`` after an abort/veto — a discarded step's
    residual must never leak into the next step's compensation (that is
    the "silent residual corruption" the faultmatrix scenarios assert
    against).

    Scope of the compensation: the residual measures the BUCKET-level
    projection. For bf16 (a per-element grid) the wire's subsequent
    encode of the projected values is exact, so the residual captures
    the full input-quantization error. For int8 the wire re-quantizes
    per ring chunk (and per native stripe) with its own scale, so a
    chunk whose magnitude sits far below the bucket max picks up an
    additional, finer-grid error that stays UNCOMPENSATED — bounded per
    step (≤ half a chunk-scale step per element) and of the same class
    as the per-hop partial-sum re-quantization error, which EF never
    covers either. What EF guarantees is that the dominant, coarse-grid
    error cannot accumulate across steps.

    State serializes via ``state_dict``/``load_state_dict`` so heals and
    disk checkpoints carry the accumulators (a healed replica restarting
    from zero residuals would re-pay the cold-start quantization bias).
    """

    def __init__(self, codec: WireCodec) -> None:
        if not codec.lossy:
            raise ValueError(
                "error feedback is meaningless on an exact codec"
            )
        self._codec = codec
        self._acc: Dict[str, np.ndarray] = {}       # committed residuals
        self._pending: Dict[str, np.ndarray] = {}   # this step's residuals
        self._pre: Dict[str, np.ndarray] = {}       # reusable pre-quant copies

    @property
    def codec(self) -> WireCodec:
        return self._codec

    def apply(self, key: str, buf: np.ndarray) -> None:
        """Compensate + project ``buf`` (owned, f32, 1-D) in place and
        stage the fresh residual under ``key``. Keys must be stable across
        steps (bucket ordinal + size); a stale key whose size changed is
        dropped rather than mis-added."""
        if buf.dtype != np.float32:
            return  # lossy wire only applies to f32 buffers
        acc = self._acc.get(key)
        if acc is not None:
            if acc.size == buf.size:
                buf += acc
            else:
                del self._acc[key]  # bucket plan changed: residual stale
        pre = self._pre.get(key)
        if pre is None or pre.size != buf.size:
            pre = np.empty_like(buf)
            self._pre[key] = pre
        pre[:] = buf
        self._codec.roundtrip(buf)   # project onto the wire grid
        np.subtract(pre, buf, out=pre)
        self._pending[key] = pre

    def commit(self) -> None:
        """Promote this step's pending residuals (the step committed)."""
        for key, pre in self._pending.items():
            acc = self._acc.get(key)
            if acc is None or acc.size != pre.size:
                self._acc[key] = pre.copy()
            else:
                acc[:] = pre
        self._pending.clear()

    def rollback(self) -> None:
        """Discard this step's pending residuals (abort/veto): the
        committed accumulators are untouched — exactly the state the
        replayed/retried step must compensate with."""
        self._pending.clear()

    def pending_keys(self) -> Tuple[str, ...]:
        return tuple(self._pending)

    def state_dict(self) -> Dict[str, Any]:
        # committed residuals only: a pending residual belongs to an
        # unresolved lineage and must never travel through a heal
        return {
            "codec": self._codec.name,
            "acc": {k: v.copy() for k, v in self._acc.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        if state.get("codec") not in (None, self._codec.name):
            # codec changed between checkpoint and restore: the residuals
            # measure a different grid — start clean rather than mis-add
            logger.warning(
                "error-feedback state was recorded for codec %r but the "
                "live codec is %r; dropping accumulators",
                state.get("codec"), self._codec.name,
            )
            self._acc = {}
        else:
            self._acc = {
                k: np.asarray(v, dtype=np.float32).copy()
                for k, v in state.get("acc", {}).items()
            }
        self._pending.clear()


class ErrorFeedbackBinding:
    """Resolves which :class:`ErrorFeedback` (if any) applies to a
    manager's LIVE data plane — the one shared implementation behind
    ``ManagedOptimizer`` and ``LocalSGD``/``DiLoCo``.

    ``explicit=None`` is auto mode (vetoed by ``TORCHFT_WIRE_EF=0``): the
    accumulator is created as soon as a lossy codec is observed — at
    construction if the plane already reports one, else lazily via
    :meth:`live` (a proxied backend only learns its child's codec at the
    first configure). ``live()`` also gates compensation OFF while the
    transport is exact (the CMA bypass): projecting onto a codec grid
    with no lossy wire underneath would ADD error (docs/wire_plane.md).
    ``explicit=False`` disables; an :class:`ErrorFeedback` instance is
    used as-is (shared)."""

    def __init__(self, manager: Any, explicit: Any = None) -> None:
        self._manager = manager
        self._auto = False
        self.instance: Optional[ErrorFeedback] = None
        if explicit is None:
            if os.environ.get("TORCHFT_WIRE_EF", "1") != "0":
                self._auto = True
                codec = get_codec(self._codec_name())
                if codec.lossy:
                    self.instance = ErrorFeedback(codec)
        elif explicit is not False:
            self.instance = explicit

    def _codec_name(self) -> str:
        # getattr: duck-typed test managers may predate the knob
        fn = getattr(self._manager, "wire_codec", None)
        return fn() if callable(fn) else "f32"

    def live(self) -> Optional[ErrorFeedback]:
        """The error feedback to use for THIS step/sync, or None when the
        live transport is exact."""
        name = self._codec_name()
        if name == "f32":
            return None
        if self.instance is None and self._auto:
            codec = get_codec(name)
            if codec.lossy:
                self.instance = ErrorFeedback(codec)
        return self.instance

    def ensure_for_state(self, ef_state: Any) -> Optional[ErrorFeedback]:
        """Restore path: a heal/checkpoint carries EF state, but in auto
        mode the instance may not exist yet (a proxied backend reports
        its codec only after the first configure — possibly AFTER the
        heal lands). Create it from the state's own codec name so the
        accumulators are adopted instead of silently dropped."""
        if (
            self.instance is None
            and self._auto
            and isinstance(ef_state, dict)
        ):
            try:
                codec = get_codec(ef_state.get("codec"))
            except ValueError:
                return None  # unknown codec in foreign state: skip
            if codec.lossy:
                self.instance = ErrorFeedback(codec)
        return self.instance


# ---------------------------------------------------------------------------
# PowerSGD-style low-rank projection (DiLoCo outer step)
# ---------------------------------------------------------------------------


def lowrank_basis(shape: Tuple[int, int], rank: int, seed: int) -> np.ndarray:
    """Deterministic orthonormal basis ``Q`` (n × rank) for the rank-r
    projection of an (m × n) matrix. Seeded, so every replica group
    derives the SAME basis from the same (leaf, sync ordinal) coordinates
    without shipping it — the cross-group average of projections is then
    well-defined.

    Determinism caveat (docs/wire_plane.md): "same" here requires every
    group to run the SAME numpy + BLAS/LAPACK wheels — the Generator
    stream and the QR bit-patterns are stable within one build, not
    contractually across builds (OpenBLAS vs MKL differ). A mixed-wheel
    fleet must not enable the low-rank outer step; the deployment story
    (one container image for all groups) satisfies this by construction."""
    _m, n = shape
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, rank)).astype(np.float32)
    q, _r = np.linalg.qr(g)
    return np.ascontiguousarray(q, dtype=np.float32)


def lowrank_compress(mat: np.ndarray, q: np.ndarray) -> np.ndarray:
    """Project ``mat`` (m × n) onto the basis: returns ``P = mat @ Q``
    (m × rank) — the only tensor that crosses the wire."""
    # asarray, not astype: callers guarantee f32, and astype's default
    # copy would duplicate the largest tensors in the outer-sync path
    return np.ascontiguousarray(np.asarray(mat, dtype=np.float32) @ q)


def lowrank_decompress(p: np.ndarray, q: np.ndarray) -> np.ndarray:
    """Reconstruct the rank-r approximation ``P @ Q^T`` (m × n)."""
    return p @ q.T


def lowrank_eligible(shape: Tuple[int, ...], rank: int) -> bool:
    """A leaf is worth projecting when it is a true 2-D matrix and the
    rank-r form is meaningfully smaller than the dense one."""
    if len(shape) != 2 or rank <= 0:
        return False
    m, n = shape
    return min(m, n) >= 4 * rank


class LowRankErrorFeedback:
    """Residual carry for the DiLoCo outer-step low-rank projection —
    same commit/rollback lineage contract as :class:`ErrorFeedback`, but
    the residual measures the projection error ``M − P·Qᵀ`` per leaf."""

    def __init__(self) -> None:
        self._acc: Dict[str, np.ndarray] = {}
        self._pending: Dict[str, np.ndarray] = {}

    def compensate(self, key: str, mat: np.ndarray) -> np.ndarray:
        acc = self._acc.get(key)
        if acc is not None and acc.shape == mat.shape:
            return mat + acc
        return mat

    def stage(self, key: str, mat: np.ndarray, approx: np.ndarray) -> None:
        self._pending[key] = mat - approx

    def commit(self) -> None:
        self._acc.update(self._pending)
        self._pending = {}

    def rollback(self) -> None:
        self._pending = {}

    def state_dict(self) -> Dict[str, Any]:
        return {"acc": {k: v.copy() for k, v in self._acc.items()}}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._acc = {
            k: np.asarray(v, dtype=np.float32).copy()
            for k, v in state.get("acc", {}).items()
        }
        self._pending = {}
