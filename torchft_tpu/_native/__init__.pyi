# Typed surface of the ctypes binding layer — the torchft/_torchft.pyi
# analogue (reference ships stubs for its Rust binary module; the ctypes
# internals here otherwise type as Any). coordination.py's wrapper classes
# (LighthouseServer/ManagerServer/ManagerClient/QuorumResult) are plain
# Python with inline annotations, consumed via the package's py.typed
# marker; this stub covers the layer beneath them.

from typing import Any, Dict, List, Tuple

OK: int
CANCELLED: int
INVALID_ARGUMENT: int
NOT_FOUND: int
DEADLINE_EXCEEDED: int
INTERNAL: int
UNAVAILABLE: int

class NativeClient:
    def __init__(self, addr: str, connect_timeout_ms: int) -> None: ...
    @property
    def addr(self) -> str: ...
    def call(
        self, method: str, req: Dict[str, Any], timeout_ms: int
    ) -> Dict[str, Any]: ...
    def close(self) -> None: ...

def lighthouse_create(
    bind: str,
    min_replicas: int,
    join_timeout_ms: int,
    quorum_tick_ms: int,
    heartbeat_timeout_ms: int,
    evict_probe_ms: int = ...,
) -> Tuple[int, str]: ...
def lighthouse_shutdown(h: int) -> None: ...
def manager_create(
    replica_id: str,
    lighthouse_addr: str,
    hostname: str,
    bind: str,
    store_addr: str,
    world_size: int,
    heartbeat_interval_ms: int,
    connect_timeout_ms: int,
) -> Tuple[int, str]: ...
def manager_shutdown(h: int) -> None: ...
def store_create(bind: str) -> Tuple[int, str]: ...
def store_shutdown(h: int) -> None: ...
LATHIST_BOUNDS_S: Tuple[float, ...]

def lathist_snapshot() -> Dict[str, Dict[str, Any]]: ...
def lathist_reset() -> None: ...
def tsdb_snapshot() -> Dict[str, Dict[str, Any]]: ...
def tsdb_reset() -> None: ...
def quorum_compute(state: Dict[str, Any]) -> Dict[str, Any]: ...
def compute_quorum_results(
    quorum: Dict[str, Any], replica_id: str, rank: int
) -> Dict[str, Any]: ...
def cma_read(pid: int, addr: int, n: int) -> bytes: ...
def cma_read_into(pid: int, addr: int, view: memoryview) -> None: ...

class BlobServer:
    port: int
    def __init__(self) -> None: ...
    def stage(self, ptrs: List[int], lens: List[int], token: int) -> None: ...
    def unstage(self) -> None: ...
    def close(self) -> None: ...

def blob_fetch(
    host: str,
    port: int,
    token: int,
    offset: int,
    length: int,
    view: memoryview,
    timeout_ms: int = ...,
) -> None: ...

class DataPlaneError(ConnectionError):
    peer_rank: int
    def __init__(self, peer_rank: int, msg: str) -> None: ...

class NativeDataPlane:
    DTYPE_F32: int
    OP: Dict[str, int]
    CODEC: Dict[str, int]
    rank: int
    world: int
    nstripes: int
    port: int
    def __init__(self, rank: int, world: int, nstripes: int = ...) -> None: ...
    def connect(
        self, peer: int, host: str, port: int, timeout_ms: int
    ) -> None: ...
    def wait_ready(self, timeout_ms: int) -> None: ...
    def enable_cma(self, pids: List[int]) -> None: ...
    def allreduce(
        self,
        ptr: int,
        nelems: int,
        op: str,
        codec: int | str = ...,
        tag: int = ...,
        timeout_ms: int = ...,
    ) -> None: ...
    def close(self) -> None: ...
