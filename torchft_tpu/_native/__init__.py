"""ctypes loader for the C++ coordination core (``native/``).

The reference binds its Rust core with pyo3 (/root/reference/src/lib.rs);
here the equivalent bridge is a C ABI + ctypes. If the shared library is
missing (fresh checkout), it is built on first import with ``make``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Any, Dict, Tuple

from torchft_tpu.utils import wire

_HERE = os.path.dirname(os.path.abspath(__file__))
# TORCHFT_NATIVE_LIB points the loader at an alternate build of the core —
# the sanitizer runs load libtftcore_asan.so/_ubsan.so this way (built by
# `make -C native asan|ubsan`; the ASan runtime must also be LD_PRELOADed
# since the interpreter itself is uninstrumented).
_LIB_OVERRIDE = os.environ.get("TORCHFT_NATIVE_LIB")
_LIB_PATH = _LIB_OVERRIDE or os.path.join(_HERE, "libtftcore.so")
_NATIVE_SRC = os.path.normpath(os.path.join(_HERE, "..", "..", "native"))

# RPC status codes (native/wire.h). CANCELLED and DEADLINE_EXCEEDED map to
# TimeoutError, everything else to RuntimeError — parity with the reference's
# Status -> PyErr mapping (src/lib.rs:380-398).
OK = 0
CANCELLED = 1
INVALID_ARGUMENT = 2
NOT_FOUND = 3
DEADLINE_EXCEEDED = 4
INTERNAL = 5
UNAVAILABLE = 6

_TIMEOUT_CODES = (CANCELLED, DEADLINE_EXCEEDED)


# The C ABI contract between this loader and libtftcore.so; must match
# native `tft_abi_version()`. v2: tft_dp_allreduce's wire_bf16 int became
# the DpCodec enum — calling an old build with codec=2 would silently run
# the bf16 wire, so a mismatch forces a rebuild instead of proceeding.
# v3: tft_lathist_snapshot/tft_lathist_reset (native latency histograms).
# v4: tft_blob_* (striped checkpoint blob plane, native/blob.cc).
# v5: divergence sentinel (mgr.should_commit digest fields + lh.digest
#     RPC) and crash-durable native blackbox breadcrumbs (blackbox.h) —
#     an old build would silently drop digests, so mismatch = rebuild.
# v6: fixed-retention time-series store (tsdb.h): tft_tsdb_snapshot/
#     tft_tsdb_reset, lighthouse /timeseries.json + piggyback series
#     ingest — an old build would silently drop every sample.
# v7: always-on sampling profiler (profiler.h): tft_prof_set_hz/hz/
#     snapshot/reset/samples_total + /diagnosis.json bundle index — an
#     old build would fail the loader's symbol lookup at import.
_ABI_VERSION = 7


def _build(force: bool = False) -> None:
    # Serialize concurrent first-import builds across worker processes
    # (multi-rank launches all hit this path on a fresh checkout).
    import fcntl

    lock_path = os.path.join(_HERE, ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if force and os.path.exists(_LIB_PATH):
                # another rank may have rebuilt while we waited on the
                # lock: re-check the on-disk ABI (via a temp copy — a
                # direct dlopen would pin the path in this namespace)
                # before paying a redundant full rebuild
                if _abi_of_file(_LIB_PATH) == _ABI_VERSION:
                    return
            if force or not os.path.exists(_LIB_PATH):
                subprocess.run(
                    ["make", "-s", "-B"] if force else ["make", "-s"],
                    cwd=_NATIVE_SRC,
                    check=True,
                    capture_output=True,
                )
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _abi_of(lib: ctypes.CDLL) -> int:
    try:
        fn = lib.tft_abi_version
    except AttributeError:
        return 1  # pre-versioning build
    fn.restype = ctypes.c_int
    fn.argtypes = []
    return int(fn())


def _abi_of_file(path: str) -> int:
    """ABI of an on-disk library, probed through a unique temp copy so
    the real path never enters this process's dlopen namespace (a cached
    mapping there would mask later rebuilds)."""
    import shutil
    import tempfile

    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        shutil.copy2(path, tmp)
        return _abi_of(ctypes.CDLL(tmp))
    except OSError:
        return 0  # unreadable/unloadable: treat as stale
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _load() -> ctypes.CDLL:
    if not os.path.exists(_LIB_PATH):
        if _LIB_OVERRIDE:
            raise RuntimeError(
                f"TORCHFT_NATIVE_LIB={_LIB_OVERRIDE} does not exist; build "
                "it first (e.g. `make -C native asan`)"
            )
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    if _abi_of(lib) != _ABI_VERSION:
        if _LIB_OVERRIDE:
            raise RuntimeError(
                f"TORCHFT_NATIVE_LIB={_LIB_OVERRIDE} reports ABI "
                f"{_abi_of(lib)}, this loader needs {_ABI_VERSION}; "
                "rebuild it (e.g. `make -C native asan`)"
            )
        # Stale build from an older checkout: rebuild in place, then load
        # the fresh object through a unique temp path — re-dlopen of the
        # SAME path can return the old mapping (the C++ runtime marks the
        # object NODELETE, so dlclose never unloads it). The temp file is
        # unlinked immediately after dlopen; the mapping stays valid.
        import shutil
        import tempfile

        _build(force=True)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
        os.close(fd)
        try:
            shutil.copy2(_LIB_PATH, tmp)
            lib = ctypes.CDLL(tmp)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        got = _abi_of(lib)
        if got != _ABI_VERSION:
            raise RuntimeError(
                f"native ABI mismatch persists after rebuild: library "
                f"reports {got}, loader needs {_ABI_VERSION} — stale "
                f"{_LIB_PATH}? remove it and re-import"
            )

    c = ctypes
    u8p = c.POINTER(c.c_uint8)

    lib.tft_buf_free.argtypes = [u8p]
    lib.tft_buf_free.restype = None

    lib.tft_lighthouse_create.argtypes = [
        c.c_char_p, c.c_uint64, c.c_uint64, c.c_uint64, c.c_uint64, c.c_uint64,
        c.c_char_p, c.c_int,
    ]
    lib.tft_lighthouse_create.restype = c.c_int64
    lib.tft_lighthouse_address.argtypes = [c.c_int64, c.c_char_p, c.c_int]
    lib.tft_lighthouse_address.restype = None
    lib.tft_lighthouse_shutdown.argtypes = [c.c_int64]
    lib.tft_lighthouse_shutdown.restype = None

    lib.tft_manager_create.argtypes = [
        c.c_char_p, c.c_char_p, c.c_char_p, c.c_char_p, c.c_char_p,
        c.c_uint64, c.c_int64, c.c_int64, c.c_char_p, c.c_int,
    ]
    lib.tft_manager_create.restype = c.c_int64
    lib.tft_manager_address.argtypes = [c.c_int64, c.c_char_p, c.c_int]
    lib.tft_manager_address.restype = None
    lib.tft_manager_shutdown.argtypes = [c.c_int64]
    lib.tft_manager_shutdown.restype = None

    lib.tft_store_create.argtypes = [c.c_char_p, c.c_char_p, c.c_int]
    lib.tft_store_create.restype = c.c_int64
    lib.tft_store_address.argtypes = [c.c_int64, c.c_char_p, c.c_int]
    lib.tft_store_address.restype = None
    lib.tft_store_shutdown.argtypes = [c.c_int64]
    lib.tft_store_shutdown.restype = None

    lib.tft_client_create.argtypes = [c.c_char_p, c.c_int64, c.c_char_p, c.c_int]
    lib.tft_client_create.restype = c.c_int64
    lib.tft_client_call.argtypes = [
        c.c_int64, c.c_char_p, u8p, c.c_int64, c.c_int64,
        c.POINTER(u8p), c.POINTER(c.c_int64), c.c_char_p, c.c_int,
    ]
    lib.tft_client_call.restype = c.c_int64
    lib.tft_client_free.argtypes = [c.c_int64]
    lib.tft_client_free.restype = None

    # native latency histograms (native/lathist.h)
    lib.tft_lathist_snapshot.argtypes = [
        c.POINTER(u8p), c.POINTER(c.c_int64), c.c_char_p, c.c_int,
    ]
    lib.tft_lathist_snapshot.restype = c.c_int64
    lib.tft_lathist_reset.argtypes = []
    lib.tft_lathist_reset.restype = None

    # time-series store (native/tsdb.h)
    lib.tft_tsdb_snapshot.argtypes = [
        c.POINTER(u8p), c.POINTER(c.c_int64), c.c_char_p, c.c_int,
    ]
    lib.tft_tsdb_snapshot.restype = c.c_int64
    lib.tft_tsdb_reset.argtypes = []
    lib.tft_tsdb_reset.restype = None

    # always-on sampling profiler (native/profiler.h)
    lib.tft_prof_set_hz.argtypes = [c.c_double]
    lib.tft_prof_set_hz.restype = None
    lib.tft_prof_hz.argtypes = []
    lib.tft_prof_hz.restype = c.c_double
    lib.tft_prof_snapshot.argtypes = [
        c.POINTER(u8p), c.POINTER(c.c_int64), c.c_char_p, c.c_int,
    ]
    lib.tft_prof_snapshot.restype = c.c_int64
    lib.tft_prof_samples_total.argtypes = []
    lib.tft_prof_samples_total.restype = c.c_int64
    lib.tft_prof_reset.argtypes = []
    lib.tft_prof_reset.restype = None

    lib.tft_quorum_compute.argtypes = [
        u8p, c.c_int64, c.POINTER(u8p), c.POINTER(c.c_int64), c.c_char_p, c.c_int,
    ]
    lib.tft_quorum_compute.restype = c.c_int64
    lib.tft_compute_quorum_results.argtypes = [
        u8p, c.c_int64, c.c_char_p, c.c_int64,
        c.POINTER(u8p), c.POINTER(c.c_int64), c.c_char_p, c.c_int,
    ]
    lib.tft_compute_quorum_results.restype = c.c_int64

    # striped cross-process gradient data plane (native/dataplane.cc)
    lib.tft_dp_create.argtypes = [c.c_int, c.c_int, c.c_int, c.c_char_p, c.c_int]
    lib.tft_dp_create.restype = c.c_int64
    lib.tft_dp_port.argtypes = [c.c_int64]
    lib.tft_dp_port.restype = c.c_int
    lib.tft_dp_connect.argtypes = [
        c.c_int64, c.c_int, c.c_char_p, c.c_int, c.c_int64, c.c_char_p, c.c_int,
    ]
    lib.tft_dp_connect.restype = c.c_int
    lib.tft_dp_wait_ready.argtypes = [c.c_int64, c.c_int64, c.c_char_p, c.c_int]
    lib.tft_dp_wait_ready.restype = c.c_int
    lib.tft_dp_enable_cma.argtypes = [
        c.c_int64, c.POINTER(c.c_int64), c.c_int, c.c_char_p, c.c_int,
    ]
    lib.tft_dp_enable_cma.restype = c.c_int
    lib.tft_dp_allreduce.argtypes = [
        c.c_int64, c.c_void_p, c.c_int64, c.c_int, c.c_int, c.c_int,
        c.c_uint32, c.c_int64, c.POINTER(c.c_int), c.c_char_p, c.c_int,
    ]
    lib.tft_dp_allreduce.restype = c.c_int
    lib.tft_dp_free.argtypes = [c.c_int64]
    lib.tft_dp_free.restype = None

    # striped checkpoint blob plane (native/blob.cc)
    lib.tft_blob_serve_create.argtypes = [c.c_char_p, c.c_int]
    lib.tft_blob_serve_create.restype = c.c_int64
    lib.tft_blob_serve_port.argtypes = [c.c_int64]
    lib.tft_blob_serve_port.restype = c.c_int
    lib.tft_blob_stage.argtypes = [
        c.c_int64, c.POINTER(c.c_uint64), c.POINTER(c.c_int64), c.c_int,
        c.c_uint64, c.c_char_p, c.c_int,
    ]
    lib.tft_blob_stage.restype = c.c_int
    lib.tft_blob_unstage.argtypes = [c.c_int64]
    lib.tft_blob_unstage.restype = c.c_int
    lib.tft_blob_serve_free.argtypes = [c.c_int64]
    lib.tft_blob_serve_free.restype = None
    lib.tft_blob_fetch.argtypes = [
        c.c_char_p, c.c_int, c.c_uint64, c.c_uint64, c.c_uint64,
        c.c_void_p, c.c_int64, c.c_char_p, c.c_int,
    ]
    lib.tft_blob_fetch.restype = c.c_int

    return lib


_lib = _load()

_ERRLEN = 1024


def _raise_status(code: int, msg: str) -> None:
    if code in _TIMEOUT_CODES:
        raise TimeoutError(msg)
    raise RuntimeError(msg)


def _errbuf() -> ctypes.Array:
    return ctypes.create_string_buffer(_ERRLEN)


def _take_out(outp: Any, outlen: Any) -> bytes:
    try:
        return ctypes.string_at(outp, outlen.value)
    finally:
        _lib.tft_buf_free(outp)


class NativeClient:
    """Generic RPC client over the C++ transport (retry/backoff/keepalive
    live in native/rpc.cc, parity with src/net.rs + src/retry.rs)."""

    def __init__(self, addr: str, connect_timeout_ms: int) -> None:
        err = _errbuf()
        self._h = _lib.tft_client_create(
            addr.encode(), int(connect_timeout_ms), err, _ERRLEN
        )
        if self._h == 0:
            _raise_status(UNAVAILABLE, err.value.decode())
        self._addr = addr

    @property
    def addr(self) -> str:
        return self._addr

    def call(self, method: str, req: Dict[str, Any], timeout_ms: int) -> Dict[str, Any]:
        buf = wire.encode(req)
        cbuf = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf) if buf else None
        outp = ctypes.POINTER(ctypes.c_uint8)()
        outlen = ctypes.c_int64()
        err = _errbuf()
        code = _lib.tft_client_call(
            self._h, method.encode(), cbuf, len(buf), int(timeout_ms),
            ctypes.byref(outp), ctypes.byref(outlen), err, _ERRLEN,
        )
        if code != OK:
            _raise_status(code, f"{method}: {err.value.decode()}")
        return wire.decode(_take_out(outp, outlen))

    def close(self) -> None:
        if self._h:
            _lib.tft_client_free(self._h)
            self._h = 0

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


def _server_address(getter: Any, h: int) -> str:
    buf = ctypes.create_string_buffer(512)
    getter(h, buf, 512)
    return buf.value.decode()


def lighthouse_create(
    bind: str,
    min_replicas: int,
    join_timeout_ms: int,
    quorum_tick_ms: int,
    heartbeat_timeout_ms: int,
    evict_probe_ms: int = 100,
) -> Tuple[int, str]:
    err = _errbuf()
    h = _lib.tft_lighthouse_create(
        bind.encode(), min_replicas, join_timeout_ms, quorum_tick_ms,
        heartbeat_timeout_ms, evict_probe_ms, err, _ERRLEN,
    )
    if h == 0:
        raise RuntimeError(err.value.decode())
    return h, _server_address(_lib.tft_lighthouse_address, h)


def lighthouse_shutdown(h: int) -> None:
    _lib.tft_lighthouse_shutdown(h)


def manager_create(
    replica_id: str,
    lighthouse_addr: str,
    hostname: str,
    bind: str,
    store_addr: str,
    world_size: int,
    heartbeat_interval_ms: int,
    connect_timeout_ms: int,
) -> Tuple[int, str]:
    err = _errbuf()
    h = _lib.tft_manager_create(
        replica_id.encode(), lighthouse_addr.encode(), hostname.encode(),
        bind.encode(), store_addr.encode(), world_size,
        heartbeat_interval_ms, connect_timeout_ms, err, _ERRLEN,
    )
    if h == 0:
        msg = err.value.decode()
        if "timed out" in msg:
            raise TimeoutError(msg)
        raise RuntimeError(msg)
    return h, _server_address(_lib.tft_manager_address, h)


def manager_shutdown(h: int) -> None:
    _lib.tft_manager_shutdown(h)


def store_create(bind: str) -> Tuple[int, str]:
    err = _errbuf()
    h = _lib.tft_store_create(bind.encode(), err, _ERRLEN)
    if h == 0:
        raise RuntimeError(err.value.decode())
    return h, _server_address(_lib.tft_store_address, h)


def store_shutdown(h: int) -> None:
    _lib.tft_store_shutdown(h)


def _pure_call(fn: Any, buf: bytes, *extra: Any) -> Dict[str, Any]:
    cbuf = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    outp = ctypes.POINTER(ctypes.c_uint8)()
    outlen = ctypes.c_int64()
    err = _errbuf()
    code = fn(cbuf, len(buf), *extra, ctypes.byref(outp), ctypes.byref(outlen),
              err, _ERRLEN)
    if code != OK:
        _raise_status(code, err.value.decode())
    return wire.decode(_take_out(outp, outlen))


def quorum_compute(state: Dict[str, Any]) -> Dict[str, Any]:
    """Run the C++ quorum_compute pure function on an explicit state.

    For unit tests (parity with src/lighthouse.rs:582-1001 table tests)."""
    return _pure_call(_lib.tft_quorum_compute, wire.encode(state))


def compute_quorum_results(
    quorum: Dict[str, Any], replica_id: str, rank: int
) -> Dict[str, Any]:
    """Run the C++ compute_quorum_results pure function.

    For unit tests (parity with src/manager.rs:720-850 table tests)."""
    return _pure_call(
        _lib.tft_compute_quorum_results, wire.encode(quorum),
        replica_id.encode(), rank,
    )


# Fixed log2 bucket grid of the native latency histograms, in seconds —
# MUST mirror native/lathist.h (kMinExp=-20, kNumBounds=27): one bucket
# per binary order of magnitude from ~1 µs to 64 s plus an overflow slot.
# Shared with telemetry.anatomy.LOG2_BUCKETS so Python- and native-side
# distributions live on one grid and cross-process merges are exact.
LATHIST_BOUNDS_S = tuple(2.0 ** e for e in range(-20, 7))


def lathist_snapshot() -> Dict[str, Dict[str, Any]]:
    """Snapshot this process's native latency histograms (dp.hop,
    dp.stripe, rpc.serve, quorum.fanout) as
    ``{op: {"counts": [int x 28], "count": int, "sum_ns": int}}``.
    ``counts`` are RAW per-bucket tallies on the fixed
    :data:`LATHIST_BOUNDS_S` grid (last slot = overflow), so merging two
    processes' snapshots is exact elementwise addition."""
    outp = ctypes.POINTER(ctypes.c_uint8)()
    outlen = ctypes.c_int64()
    err = _errbuf()
    code = _lib.tft_lathist_snapshot(
        ctypes.byref(outp), ctypes.byref(outlen), err, _ERRLEN
    )
    if code != OK:
        _raise_status(code, err.value.decode())
    return wire.decode(_take_out(outp, outlen))


def lathist_reset() -> None:
    """Zero every native latency histogram (tests/bench interval resets)."""
    _lib.tft_lathist_reset()


def tsdb_snapshot() -> Dict[str, Dict[str, Any]]:
    """Snapshot this process's time-series store (the in-process
    lighthouse's fixed-retention sample rings, ``native/tsdb.h``) as
    ``{replica: {series: {"samples": [[epoch, step, value], ...]}}}``,
    oldest-first per series — the test surface behind the lighthouse's
    ``GET /timeseries.json`` range queries."""
    outp = ctypes.POINTER(ctypes.c_uint8)()
    outlen = ctypes.c_int64()
    err = _errbuf()
    code = _lib.tft_tsdb_snapshot(
        ctypes.byref(outp), ctypes.byref(outlen), err, _ERRLEN
    )
    if code != OK:
        _raise_status(code, err.value.decode())
    return wire.decode(_take_out(outp, outlen))


def tsdb_reset() -> None:
    """Clear the process time-series store (tests)."""
    _lib.tft_tsdb_reset()


def prof_set_hz(hz: float) -> None:
    """Retarget the native sampling profiler's rate live (0 pauses, >0
    arms — the diagnosis engine's burst boost; see native/profiler.h)."""
    _lib.tft_prof_set_hz(float(hz))


def prof_hz() -> float:
    """The native profiler's effective sampling rate (resolving the
    ``TORCHFT_PROF_HZ`` env default on first call; 0 = disarmed)."""
    return float(_lib.tft_prof_hz())


def prof_snapshot() -> str:
    """Flamegraph-ready collapsed stacks of every native sample drained
    so far: ``"label;root;...;leaf count\\n"`` per unique (thread label,
    stack), sorted. Cumulative — diff two snapshots
    (:func:`torchft_tpu.telemetry.profiler.subtract_folded`) for a
    bounded capture window."""
    outp = ctypes.POINTER(ctypes.c_uint8)()
    outlen = ctypes.c_int64()
    err = _errbuf()
    code = _lib.tft_prof_snapshot(
        ctypes.byref(outp), ctypes.byref(outlen), err, _ERRLEN
    )
    if code != OK:
        _raise_status(code, err.value.decode())
    return _take_out(outp, outlen).decode(errors="replace")


def prof_samples_total() -> int:
    """Native samples aggregated since process start (or the last
    :func:`prof_reset`)."""
    return int(_lib.tft_prof_samples_total())


def prof_reset() -> None:
    """Drop every aggregated native sample (tests / capture windows)."""
    _lib.tft_prof_reset()


class _iovec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p), ("iov_len", ctypes.c_size_t)]


def _libc() -> ctypes.CDLL:
    global _LIBC
    if _LIBC is None:
        _LIBC = ctypes.CDLL("libc.so.6", use_errno=True)
        # ssize_t return: the default c_int would truncate >=2GiB pulls
        # into spurious errors or wrong offset advances
        _LIBC.process_vm_readv.restype = ctypes.c_ssize_t
        _LIBC.process_vm_readv.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(_iovec),
            ctypes.c_ulong,
            ctypes.POINTER(_iovec),
            ctypes.c_ulong,
            ctypes.c_ulong,
        ]
    return _LIBC


_LIBC: "ctypes.CDLL | None" = None


def cma_read_into(pid: int, addr: int, view: memoryview) -> None:
    """process_vm_readv ``len(view)`` bytes from ``pid``'s address space
    straight into the writable buffer ``view`` (single copy — the p2p CMA
    fast path's pull primitive). Raises OSError when the kernel says no."""
    libc = _libc()
    n = len(view)
    buf = (ctypes.c_char * n).from_buffer(view)
    off = 0
    while off < n:
        local = _iovec(ctypes.addressof(buf) + off, n - off)
        remote = _iovec(addr + off, n - off)
        got = libc.process_vm_readv(
            pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0
        )
        if got <= 0:
            raise OSError(ctypes.get_errno(), "process_vm_readv failed")
        off += got


def cma_read(pid: int, addr: int, n: int) -> bytes:
    """One process_vm_readv of ``n`` bytes from ``pid``'s address space —
    the rendezvous probe for the CMA transport (a token round-trip proves
    the published pid is addressable from THIS pid namespace and ptrace
    policy allows the attach). Raises OSError when the kernel says no."""
    libc = _libc()
    buf = ctypes.create_string_buffer(n)
    local = _iovec(ctypes.addressof(buf), n)
    remote = _iovec(addr, n)
    got = libc.process_vm_readv(
        pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0
    )
    if got != n:
        raise OSError(ctypes.get_errno(), "process_vm_readv failed")
    return buf.raw


class DataPlaneError(ConnectionError):
    """Native data-plane op failed; ``peer_rank`` is the ring rank whose
    socket broke (−1 when indeterminate) for eviction attribution."""

    def __init__(self, peer_rank: int, msg: str) -> None:
        super().__init__(msg)
        self.peer_rank = peer_rank


class BlobServer:
    """ctypes wrapper for the striped checkpoint blob plane's serving
    side (native/blob.cc): stages the flattened state tree's host buffers
    (scattered — no coalescing copy) and serves arbitrary byte ranges of
    their logical concatenation to healing peers, GIL-free. The caller
    must keep the staged buffers alive until :meth:`unstage` returns."""

    def __init__(self) -> None:
        err = _errbuf()
        self._h = _lib.tft_blob_serve_create(err, _ERRLEN)
        if self._h == 0:
            raise RuntimeError(f"blob server create: {err.value.decode()}")
        self.port = int(_lib.tft_blob_serve_port(self._h))

    def stage(self, ptrs: "list[int]", lens: "list[int]", token: int) -> None:
        """Open the serving window over the buffers at ``ptrs``/``lens``
        (base addresses + byte lengths, stream order). ``token`` names
        this staging generation; fetches carrying any other token are
        answered with a loud stale error, never stale bytes."""
        n = len(ptrs)
        arr_p = (ctypes.c_uint64 * n)(*ptrs)
        arr_l = (ctypes.c_int64 * n)(*lens)
        err = _errbuf()
        rc = _lib.tft_blob_stage(self._h, arr_p, arr_l, n, token, err, _ERRLEN)
        if rc != 0:
            raise RuntimeError(f"blob stage: {err.value.decode()}")

    def unstage(self) -> None:
        """Close the serving window; returns once no in-flight serve
        still reads the staged buffers (they may be freed after this)."""
        if self._h:
            _lib.tft_blob_unstage(self._h)

    def close(self) -> None:
        if self._h:
            _lib.tft_blob_serve_free(self._h)
            self._h = 0

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


def blob_fetch(
    host: str,
    port: int,
    token: int,
    offset: int,
    length: int,
    view: memoryview,
    timeout_ms: int = 60000,
) -> None:
    """Pull ``length`` bytes at ``offset`` of the peer's staged blob
    straight into the writable buffer ``view`` (the healer-side range
    primitive; the GIL is released for the duration). Raises
    TimeoutError on deadline, ConnectionError on any transfer failure —
    a cut connection surfaces as a failed range, never short data."""
    assert len(view) == length, (len(view), length)
    buf = (ctypes.c_char * length).from_buffer(view)
    err = _errbuf()
    rc = _lib.tft_blob_fetch(
        host.encode(), port, token, offset, length,
        ctypes.addressof(buf), timeout_ms, err, _ERRLEN,
    )
    if rc == -2:
        raise TimeoutError(f"blob fetch: {err.value.decode()}")
    if rc != 0:
        raise ConnectionError(f"blob fetch: {err.value.decode()}")


class NativeDataPlane:
    """ctypes wrapper for the striped C++ gradient plane (dataplane.cc).

    One instance per collectives epoch: rendezvous (store addresses,
    who-dials-whom) stays in Python; the hot allreduce bytes never touch
    the interpreter (ctypes drops the GIL for the duration of the call).
    """

    DTYPE_F32 = 0
    OP = {"sum": 0, "avg": 1, "max": 2, "min": 3}
    # wire codecs (native/dataplane.h DpCodec; formats mirror
    # torchft_tpu/wire_codec.py byte for byte)
    CODEC = {"f32": 0, "bfloat16": 1, "int8": 2}

    def __init__(self, rank: int, world: int, nstripes: int = 4) -> None:
        err = _errbuf()
        self._h = _lib.tft_dp_create(rank, world, nstripes, err, _ERRLEN)
        if self._h == 0:
            raise RuntimeError(f"dataplane create: {err.value.decode()}")
        self.rank = rank
        self.world = world
        self.nstripes = nstripes
        self.port = int(_lib.tft_dp_port(self._h))

    def connect(self, peer: int, host: str, port: int, timeout_ms: int) -> None:
        err = _errbuf()
        rc = _lib.tft_dp_connect(
            self._h, peer, host.encode(), port, timeout_ms, err, _ERRLEN
        )
        if rc != 0:
            raise DataPlaneError(
                peer, f"dataplane dial {peer}: {err.value.decode()}"
            )

    def wait_ready(self, timeout_ms: int) -> None:
        err = _errbuf()
        rc = _lib.tft_dp_wait_ready(self._h, timeout_ms, err, _ERRLEN)
        if rc != 0:
            raise TimeoutError(f"dataplane rendezvous: {err.value.decode()}")

    def enable_cma(self, pids: "list[int]") -> None:
        """Switch ring payloads to cross-memory attach (one-copy pulls
        from the left neighbor's address space). Caller must have proven
        all ranks same-host + CMA-capable; ``pids`` indexed by rank."""
        arr = (ctypes.c_int64 * len(pids))(*pids)
        err = _errbuf()
        rc = _lib.tft_dp_enable_cma(self._h, arr, len(pids), err, _ERRLEN)
        if rc != 0:
            raise RuntimeError(f"enable_cma: {err.value.decode()}")

    def allreduce(
        self,
        ptr: int,
        nelems: int,
        op: str,
        codec: "int | str" = 0,
        tag: int = 0,
        timeout_ms: int = 60000,
    ) -> None:
        """In-place f32 ring allreduce on the buffer at ``ptr``. Blocking —
        call from the collectives op thread; the GIL is released.
        ``codec`` selects the wire format (``CODEC`` map / DpCodec enum):
        lossy codecs quantize on the wire while accumulation stays f32,
        and the decoded result is bit-identical on every rank."""
        err = _errbuf()
        bad_peer = ctypes.c_int(-1)
        codec_i = self.CODEC[codec] if isinstance(codec, str) else int(codec)
        rc = _lib.tft_dp_allreduce(
            self._h, ptr, nelems, self.DTYPE_F32, self.OP[op],
            codec_i, tag, timeout_ms,
            ctypes.byref(bad_peer), err, _ERRLEN,
        )
        if rc == -2:
            # deadline, no peer named: slow-but-alive must be retryable,
            # never an eviction-worthy accusation
            raise TimeoutError(f"dataplane allreduce: {err.value.decode()}")
        if rc != 0:
            raise DataPlaneError(
                int(bad_peer.value),
                f"dataplane allreduce: {err.value.decode()}",
            )

    def close(self) -> None:
        if self._h:
            _lib.tft_dp_free(self._h)
            self._h = 0

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
