"""Deterministic fault-injection plane — site registry + schedule engine.

The paper's core claim (replica death costs at most one step) is only as
strong as the failure modes that can be reproduced on demand. Kill/restart
soaks rely on wall-clock races, so the interesting windows — a peer dying
*mid*-collective, a CMA pull torn halfway, a commit vote delayed past the
pipeline's speculation fence — fire rarely and can't be bisected. This
module makes them systematic: every layer faults currently hit by accident
gets a **named injection site**, and a **seeded schedule** decides,
deterministically, which occurrences of which sites fire which fault.

Sites (the catalog; call sites pass a free-form ``match`` label a rule can
substring-filter on):

========================  ====================================================
site                      where it fires
========================  ====================================================
``rpc.send``              wire-level frame send (``CollectivesTcp._send_to``)
``rpc.recv``              wire-level frame receive (``_recv_from``)
``collective.issue``      a collective op is submitted (all backends + proxy)
``collective.complete``   a collective op finished on the op thread
``cma.pull``              a process_vm_readv pull of a peer's buffer
``ckpt.serve``            the checkpoint HTTP server is about to stream
``ckpt.recv``             a healing replica starts fetching a checkpoint
``quorum.reply``          the quorum RPC reply reached this replica
``commit.vote``           the should_commit vote (``match="prepare"`` at the
                          barrier's drain, ``match="rpc"`` at the vote RPC)
``future.deadline``       a future is registered with the deadline manager
========================  ====================================================

Actions: ``delay(ms)``, ``drop``, ``error(exc)``, ``torn(frac)`` (partial
write / torn read — the mid-op-peer-death emulation), ``kill(sig)``, and
``corrupt(frac)`` (silent single-replica output perturbation of ``frac``
of a finished op's buffer — the divergence-sentinel adversary: no error
is raised, the corrupt averages would commit unless the commit-time
digest compare catches them). ``delay``/``error``/``kill`` are applied
inline by :func:`fault_point`; ``drop``/``torn``/``corrupt`` are
returned to wire-capable call sites (those passing ``wire=True``) which
implement the transport-specific semantics — at a non-wire site they
degrade to ``error`` so a schedule can never silently no-op.

Schedules are JSON (inline or ``@/path/to/file``) via
``TORCHFT_FAULT_SCHEDULE`` or :func:`configure`::

    {"seed": 7,
     "rules": [
       {"site": "rpc.recv",  "nth": 3, "action": "error",
        "exc": "ConnectionError"},
       {"site": "collective.issue", "match": "allreduce",
        "nth": 5, "action": "kill", "sig": 9},
       {"site": "commit.vote", "match": "rpc",
        "every": 2, "action": "delay", "ms": 150},
       {"site": "cma.pull", "p": 0.1, "action": "torn", "frac": 0.5}
     ]}

Matching is keyed by ``(site, match, nth/every/p/after)``: each rule
keeps its own hit counter; ``nth`` fires on the nth matching occurrence
(once), ``every`` on every k-th, ``p`` Bernoulli per occurrence from an
RNG seeded by ``(seed, rule index, site, match)`` — so a fixed seed
replays the IDENTICAL injection sequence (asserted by test) — and
``after`` on EVERY occurrence from the after-th onward (a mid-run onset:
the perf-regression scenario's level shift). ``limit`` caps total fires
(default 1 for ``nth``, unlimited otherwise).

Every fired injection emits a ``fault_injected`` telemetry event, bumps
``tft_faults_injected_total{site,action}``, lands in the collective flight
recorder ring, and — when ``TORCHFT_FAULT_EVIDENCE_DIR`` is set — appends
a JSONL evidence record (written *before* a ``kill`` executes) so the test
tier can tell an injected death from the documented environmental
corruption (see ``tests/conftest.skip_if_known_corruption``).

The native plane's compiled-in injection points (``native/faultinject.h``)
are env-gated siblings of this engine — the scenario runner translates
native-site scenarios into those env knobs; see ``docs/fault_injection.md``
for the combined catalog.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "SITES",
    "NATIVE_SITES",
    "ACTIONS",
    "ENV_CORRUPTION_SIGNATURES",
    "CORRUPTION_SIGNAL_RCS",
    "ENV_SCHEDULE",
    "ENV_EVIDENCE_DIR",
    "Injection",
    "FaultPlane",
    "configure",
    "active",
    "fault_point",
    "read_evidence",
]

ENV_SCHEDULE = "TORCHFT_FAULT_SCHEDULE"
ENV_EVIDENCE_DIR = "TORCHFT_FAULT_EVIDENCE_DIR"

SITES = (
    "rpc.send",
    "rpc.recv",
    "collective.issue",
    "collective.complete",
    "cma.pull",
    "ckpt.serve",
    "ckpt.recv",
    "quorum.reply",
    "commit.vote",
    "future.deadline",
)

# Site labels the NATIVE plane's evidence records may carry (the
# `fi::write_evidence` / `fi::kill_self` call sites in native/*.cc|h).
# conftest's injection-evidence check and the scenario runner treat these
# exactly like SITES when attributing a death to a scheduled injection;
# `python -m torchft_tpu.analysis` (wiredrift: fault-site-drift) keeps
# this tuple and the native call sites from drifting apart.
NATIVE_SITES = (
    "blob.serve",
    "cma.desc",
    "cma.pull",
    "commit.vote",
    "dp.hop",
    "rpc.send",
)

ACTIONS = ("delay", "drop", "error", "torn", "kill", "corrupt")

# Environmental-corruption catalog (ROADMAP open item, PR 2 post-mortem):
# on this box a worker can die of heap corruption (glibc aborts), its
# pytree-level symptom ("Too few elements for TreeDef node"), or a bare
# signal-class exit during multi-process churn — on UNMODIFIED checkouts
# too. The scenario runner records (not fails) such deaths and the test
# tier skips on them; both consume THIS tuple so a newly documented
# signature is recognized everywhere at once.
ENV_CORRUPTION_SIGNATURES = (
    "Too few elements for TreeDef node",
    "malloc(): ",
    "malloc_consolidate",
    "double free or corruption",
    "free(): invalid",
    "corrupted size vs. prev_size",
    "corrupted double-linked list",
    "Segmentation fault",
)

# signal-class deaths that glibc/the kernel may leave without any log
# output: SIGSEGV, SIGABRT, SIGBUS
CORRUPTION_SIGNAL_RCS = (-11, -6, -7)

# exception classes a rule may name; PeerGoneError is resolved lazily to
# avoid importing the collectives layer at schedule-parse time
_EXC_NAMES = ("ConnectionError", "TimeoutError", "OSError", "RuntimeError",
              "EOFError", "PeerGoneError")


def _resolve_exc(name: str):
    if name == "PeerGoneError":
        from torchft_tpu.collectives import PeerGoneError

        return PeerGoneError
    return {
        "ConnectionError": ConnectionError,
        "TimeoutError": TimeoutError,
        "OSError": OSError,
        "RuntimeError": RuntimeError,
        "EOFError": EOFError,
    }[name]


class Injection:
    """One fired rule, handed to the call site."""

    __slots__ = ("site", "match", "action", "ms", "frac", "sig", "exc",
                 "msg", "hit", "rule")

    def __init__(self, site: str, match: str, action: str, ms: float,
                 frac: float, sig: int, exc: str, msg: str, hit: int,
                 rule: int) -> None:
        self.site = site
        self.match = match
        self.action = action
        self.ms = ms
        self.frac = frac
        self.sig = sig
        self.exc = exc
        self.msg = msg
        self.hit = hit  # which occurrence of (site, rule-match) fired
        self.rule = rule

    def make_exception(self) -> BaseException:
        text = (
            f"fault injection: {self.site}[{self.match or '*'}] "
            f"hit {self.hit} action={self.action}"
            + (f" ({self.msg})" if self.msg else "")
        )
        cls = _resolve_exc(self.exc or "ConnectionError")
        try:
            from torchft_tpu.collectives import PeerGoneError

            if cls is PeerGoneError:
                return cls(0, text)
        except Exception:  # noqa: BLE001 — fall through to plain construct
            pass
        return cls(text)


class _Rule:
    def __init__(self, spec: Dict[str, Any], idx: int, seed: int) -> None:
        self.site = spec["site"]
        if self.site not in SITES:
            raise ValueError(
                f"unknown injection site {self.site!r}; known: {SITES}"
            )
        self.action = spec.get("action", "error")
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown action {self.action!r}; known: {ACTIONS}"
            )
        self.match = str(spec.get("match", ""))
        self.nth = spec.get("nth")
        self.every = spec.get("every")
        self.p = spec.get("p")
        # onset semantics (ISSUE 11): fire on EVERY matching occurrence
        # from the after-th onward — a mid-run level shift (the perf-
        # regression scenario's +150ms delay) needs a clean onset step,
        # which nth (one-shot) and every (periodic from the start)
        # cannot express
        self.after = spec.get("after")
        if sum(
            x is not None
            for x in (self.nth, self.every, self.p, self.after)
        ) > 1:
            raise ValueError(
                "rule may set at most one of nth/every/p/after"
            )
        # nth rules are one-shot by default; every/p unlimited (limit=0)
        default_limit = 1 if self.nth is not None else 0
        self.limit = int(spec.get("limit", default_limit))
        self.ms = float(spec.get("ms", 0.0))
        self.frac = float(spec.get("frac", 0.5))
        self.sig = int(spec.get("sig", 9))
        self.exc = spec.get("exc", "ConnectionError")
        if self.exc not in _EXC_NAMES:
            raise ValueError(
                f"unknown exc {self.exc!r}; known: {_EXC_NAMES}"
            )
        self.msg = str(spec.get("msg", ""))
        self.idx = idx
        # stable per-rule stream: crc32 keying (hash() is salted per
        # process, which would break cross-process replay)
        key = f"{seed}:{idx}:{self.site}:{self.match}".encode()
        self._rng = random.Random(zlib.crc32(key))
        self.hits = 0
        self.fires = 0

    def consider(self, match: str) -> bool:
        """Count a matching occurrence; True when this one fires.
        Called under the plane lock."""
        if self.match and self.match not in match:
            return False
        self.hits += 1
        if self.limit and self.fires >= self.limit:
            return False
        if self.nth is not None:
            fire = self.hits == int(self.nth)
        elif self.every is not None:
            fire = self.hits % int(self.every) == 0
        elif self.p is not None:
            fire = self._rng.random() < float(self.p)
        elif self.after is not None:
            fire = self.hits >= int(self.after)
        else:
            fire = True
        if fire:
            self.fires += 1
        return fire


class FaultPlane:
    """A parsed schedule plus its per-rule occurrence state."""

    def __init__(self, schedule: Dict[str, Any]) -> None:
        self.seed = int(schedule.get("seed", 0))
        self.rules = [
            _Rule(spec, i, self.seed)
            for i, spec in enumerate(schedule.get("rules", []))
        ]
        self._lock = threading.Lock()
        self.fired: List[Dict[str, Any]] = []
        self._evidence_dir = os.environ.get(ENV_EVIDENCE_DIR)

    def hit(self, site: str, match: str,
            ctx: Dict[str, Any]) -> Optional[Injection]:
        """Consult the schedule for one occurrence of ``site``; returns
        the fired Injection (first matching rule wins) or None."""
        inj: Optional[Injection] = None
        record: Optional[Dict[str, Any]] = None
        with self._lock:
            for rule in self.rules:
                if rule.site != site:
                    continue
                if rule.consider(match):
                    inj = Injection(
                        site, match, rule.action, rule.ms, rule.frac,
                        rule.sig, rule.exc, rule.msg, rule.hits, rule.idx,
                    )
                    record = {
                        "ts": time.time(),
                        "pid": os.getpid(),
                        "site": site,
                        "match": match,
                        "action": rule.action,
                        "hit": rule.hits,
                        "rule": rule.idx,
                    }
                    self.fired.append(record)
                    break
        if inj is None:
            return None
        self._write_evidence(record)
        self._account(inj, ctx)
        return inj

    def fired_sequence(self) -> List[Tuple[str, str, str, int]]:
        """The deterministic replay key: (site, match, action, hit) per
        fired injection, in firing order."""
        with self._lock:
            return [
                (r["site"], r["match"], r["action"], r["hit"])
                for r in self.fired
            ]

    # -- evidence + accounting -------------------------------------------

    def _write_evidence(self, record: Optional[Dict[str, Any]]) -> None:
        """Append the fired record to the per-pid evidence file. Written
        BEFORE the action executes so a kill's evidence survives it —
        this file is what lets the test tier distinguish a scheduled death
        from the documented environmental corruption."""
        if not self._evidence_dir or record is None:
            return
        try:
            os.makedirs(self._evidence_dir, exist_ok=True)
            path = os.path.join(
                self._evidence_dir, f"tft_fault_{os.getpid()}.json"
            )
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            logger.warning("fault evidence write failed", exc_info=True)

    def _account(self, inj: Injection, ctx: Dict[str, Any]) -> None:
        """Telemetry: event + counter + a flight-recorder ring entry, so
        evidence collection is automatic on every fire."""
        try:
            from torchft_tpu import telemetry

            telemetry.FAULTS_INJECTED.labels(
                site=inj.site, action=inj.action
            ).inc()
            telemetry.emit(
                "fault_injected",
                site=inj.site,
                action=inj.action,
                match=inj.match,
                hit=inj.hit,
            )
            fid = telemetry.FLIGHT.record_issue(
                f"fault.{inj.action}", inj.site,
                int(ctx.get("nbytes", 0) or 0),
                tag=int(ctx.get("tag", 0) or 0),
                rank=int(ctx.get("rank", -1) or -1),
            )
            telemetry.FLIGHT.record_complete(fid)
        except Exception:  # noqa: BLE001 — accounting must not mask the fault
            logger.exception("fault-injection accounting failed")


# process-global plane; _UNSET means "env not consulted yet"
_UNSET = object()
_PLANE: Any = _UNSET
_PLANE_LOCK = threading.Lock()


def _parse_schedule(raw: str) -> Dict[str, Any]:
    raw = raw.strip()
    if raw.startswith("@"):
        with open(raw[1:], encoding="utf-8") as f:
            raw = f.read()
    doc = json.loads(raw)
    if not isinstance(doc, dict):
        raise ValueError("fault schedule must be a JSON object")
    return doc


def configure(schedule: Any = None) -> Optional[FaultPlane]:
    """Install a schedule process-wide (dict, JSON string, ``@path``, or
    None to disable). Returns the installed plane (None when disabled).
    Replaces any previous plane and resets all occurrence counters — a
    reconfigure with the same schedule replays the same sequence."""
    global _PLANE
    with _PLANE_LOCK:
        if schedule is None:
            _PLANE = None
        else:
            if isinstance(schedule, str):
                schedule = _parse_schedule(schedule)
            _PLANE = FaultPlane(schedule)
        return _PLANE


def active() -> Optional[FaultPlane]:
    """The live plane, loading ``TORCHFT_FAULT_SCHEDULE`` on first use."""
    global _PLANE
    if _PLANE is _UNSET:
        with _PLANE_LOCK:
            if _PLANE is _UNSET:
                raw = os.environ.get(ENV_SCHEDULE)
                if not raw:
                    _PLANE = None
                else:
                    try:
                        _PLANE = FaultPlane(_parse_schedule(raw))
                        logger.info(
                            "fault-injection plane armed: %d rules, seed %d",
                            len(_PLANE.rules), _PLANE.seed,
                        )
                    except Exception:  # noqa: BLE001 — bad schedule: disable
                        logger.exception(
                            "ignoring malformed %s", ENV_SCHEDULE
                        )
                        _PLANE = None
    return _PLANE


def fault_point(site: str, match: str = "", wire: bool = False,
                **ctx: Any) -> Optional[Injection]:
    """The instrumentation hook. Near-zero cost when no schedule is
    loaded (one global read). Applies ``delay``/``error``/``kill``
    inline; returns ``drop``/``torn`` injections to wire-capable call
    sites (``wire=True``) and degrades them to ``error`` elsewhere."""
    plane = _PLANE if _PLANE is not _UNSET else active()
    if plane is None:
        return None
    inj = plane.hit(site, match, ctx)
    if inj is None:
        return None
    if inj.action == "delay":
        time.sleep(inj.ms / 1000.0)
        return inj
    if inj.action == "kill":
        logger.warning(
            "fault injection: killing pid %d with signal %d at %s[%s]",
            os.getpid(), inj.sig, site, match,
        )
        os.kill(os.getpid(), inj.sig)
        return inj  # non-fatal signals (incl. sig=0 probes) return
    if inj.action == "error" or not wire:
        raise inj.make_exception()
    return inj  # drop / torn / corrupt: the call site implements them


def read_evidence(evidence_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse every evidence file under ``evidence_dir`` (default: the
    ``TORCHFT_FAULT_EVIDENCE_DIR`` env) back into fired records — both
    this engine's JSONL and the native plane's single-line records."""
    import glob as _glob

    d = evidence_dir or os.environ.get(ENV_EVIDENCE_DIR)
    out: List[Dict[str, Any]] = []
    if not d:
        return out
    for path in sorted(_glob.glob(os.path.join(d, "tft_fault_*"))):
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return out
