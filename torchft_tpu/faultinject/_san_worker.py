"""Jax-free scenario worker for the sanitized fault matrix.

The ASan runtime and jaxlib cannot coexist in one process: ASan's
``__cxa_throw`` interceptor CHECK-fails inside jaxlib's MLIR bindings
during the very first jit trace, killing the worker before a scenario
even starts (and the interpreter is uninstrumented, so nothing useful is
reported). The heap-corruption suspects named by the ROADMAP open item —
the native data plane, the RPC layer, and the CMA pull path — are all
fully exercised by a numpy-only trainer, so ``--sanitize`` runs drive
THIS worker instead of ``examples/train_bytes.py``: the same
Manager / CollectivesTcp / quorum / heal / commit path, minus the jit'd
model.

Same launcher env contract as the example (``REPLICA_GROUP_ID``,
``NUM_REPLICA_GROUPS``, ``STEPS``, ``TORCHFT_LIGHTHOUSE``) and the same
final ``param_checksum=%.6f`` line the runner's cross-group invariant
check greps. Gradients are a pure function of ``(group, step)`` so a
retried, healed, or respawned step regenerates identical bytes — the
bit-identity assertion holds through any injection the schedule fires.
"""

from __future__ import annotations

import logging
import os
import sys
import time
from datetime import timedelta

import numpy as np

logging.basicConfig(
    level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
)
logger = logging.getLogger("san_worker")

assert "jax" not in sys.modules, (
    "the sanitize worker must stay jax-free (ASan's __cxa_throw "
    "interceptor aborts inside jaxlib's jit tracing)"
)

SHAPE = (256, 256)  # 256 KiB of f32: large enough for striped/CMA hops


def main() -> None:
    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.manager import Manager
    from torchft_tpu.store import StoreServer

    gid = int(os.environ["REPLICA_GROUP_ID"])
    num_groups = int(os.environ.get("NUM_REPLICA_GROUPS", "2"))
    steps = int(os.environ.get("STEPS", "10"))

    params = {"w": np.zeros(SHAPE, np.float32), "steps_seen": 0}

    def state_dict():
        return {"w": params["w"].copy(), "steps_seen": params["steps_seen"]}

    def load_state_dict(state) -> None:
        params["w"] = np.asarray(state["w"], np.float32).copy()
        params["steps_seen"] = int(state["steps_seen"])

    store = StoreServer()
    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=30)),
        load_state_dict=load_state_dict,
        state_dict=state_dict,
        min_replica_size=min(2, num_groups),
        replica_id=f"san_worker_{gid}",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        timeout=timedelta(seconds=30),
    )
    logger.info("start: gid=%d pid=%d steps=%d", gid, os.getpid(), steps)
    try:
        while manager.current_step() < steps:
            step = manager.current_step()
            try:
                manager.start_quorum()
                # pure function of (gid, step): retries and respawns
                # regenerate identical bytes, so every COMMITTED step's
                # average — and therefore the final checksum — is
                # bit-identical across groups
                rng = np.random.default_rng((gid << 24) ^ step)
                grad = rng.standard_normal(SHAPE).astype(np.float32)
                manager.allreduce(grad).wait()
                committed = manager.should_commit()
            except TimeoutError as e:
                # a quorum/op deadline blown while a peer is down is a
                # retry, not a crash (the runner's own deadline still
                # bounds a true wedge)
                logger.info("timeout, retrying step %d: %s", step, e)
                continue
            if committed:
                params["w"] -= 0.01 * grad
                params["steps_seen"] += 1
            else:
                time.sleep(0.2)  # same step retries: it didn't advance
        checksum = float(np.asarray(params["w"], np.float64).sum())
        logger.info(
            "done: step=%d param_checksum=%.6f",
            manager.current_step(), checksum,
        )
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


if __name__ == "__main__":
    main()
