"""Fault-injection scenario runner — drives the 2-group example trainer
through a deterministic failure matrix and asserts the end-to-end safety
invariant:

    **no committed step may carry corrupt averages** — survivor parameter
    checksums stay finite and bit-identical across groups, or the step
    must abort/veto/heal instead of committing.

Scenarios (each = one 2-group ``examples/train_bytes.py`` run with a
seeded schedule and/or native env knobs on a designated victim):

* ``kill_allreduce_{cma,tcp,python}`` — the victim dies MID-allreduce on
  each data plane the host path can select (CMA descriptor window /
  striped-TCP hop / python-ring frame send); the runner respawns it and
  the cohort must converge bit-identical.
* ``torn_stripe_tcp`` — a stripe's TCP frame is cut halfway (torn write);
  the victim survives, the step must latch + flush-re-quorum.
* ``torn_cma_pull`` — a CMA pull stops partway (torn read, the ROADMAP
  divergence hypothesis); the partial buffer must never average in.
* ``commit_vote_delay_pipeline`` — every 3rd should_commit vote delayed
  under ``TORCHFT_COMMIT_PIPELINE=1`` (the speculation fence must hold).
* ``ckpt_serve_death`` — the victim is killed, and the survivor's first
  checkpoint serve to the healer is cut mid-stream; the heal must retry,
  never stage torn state.

Workers that die WITH injection evidence (``TORCHFT_FAULT_EVIDENCE_DIR``)
are the scenario — they are respawned. A worker death carrying the
documented environmental-corruption signature but NO evidence marks the
scenario ``environmental`` (recorded, not a failure — see ROADMAP open
item). Anything else fails the run.

``--sanitize[=asan|tsan]`` rebuilds the native plane under the named
sanitizer (``make -C native asan``/``tsan``), runs a short matrix with
the sanitized core LD_PRELOAD-loaded into every worker, and fails on any
sanitizer report — ASan is the repeatable form of the ROADMAP's
heap-corruption hunt; TSan is its concurrency complement (the dynamic
side of ``python -m torchft_tpu.analysis``'s static lock rules).

Usage::

    python -m torchft_tpu.faultinject.runner --quick
    python -m torchft_tpu.faultinject.runner --scenario torn_cma_pull
    python -m torchft_tpu.faultinject.runner --sanitize --quick
    python -m torchft_tpu.faultinject.runner --sanitize=tsan --quick
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

REPO = os.path.normpath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)
_EXAMPLE = os.path.join(REPO, "examples", "train_bytes.py")

# environmental-corruption catalog — shared with tests/conftest.py via
# the package (running `-m torchft_tpu.faultinject.runner` imports the
# parent package anyway, so this adds no import cost)
from torchft_tpu.faultinject.core import (  # noqa: E402
    CORRUPTION_SIGNAL_RCS,
    ENV_CORRUPTION_SIGNATURES,
    read_evidence,
)


@dataclass
class Scenario:
    name: str
    description: str
    victim_env: Dict[str, str] = field(default_factory=dict)
    survivor_env: Dict[str, str] = field(default_factory=dict)
    common_env: Dict[str, str] = field(default_factory=dict)
    victim_schedule: Optional[dict] = None
    survivor_schedule: Optional[dict] = None
    expect_victim_death: bool = False
    quick: bool = True  # include in the --quick / --sanitize subset


SCENARIOS: List[Scenario] = [
    Scenario(
        name="kill_allreduce_cma",
        description="victim SIGKILLed after publishing a CMA pull "
        "descriptor (peer holds a descriptor into dying memory)",
        victim_env={"TORCHFT_FI_CMA_KILL": "3"},
        expect_victim_death=True,
    ),
    Scenario(
        name="kill_allreduce_tcp",
        description="victim SIGKILLed entering a striped-TCP hop "
        "mid-allreduce",
        common_env={"TORCHFT_DP_CMA": "0"},
        victim_env={"TORCHFT_FI_DP_KILL": "3"},
        expect_victim_death=True,
        quick=False,
    ),
    Scenario(
        name="kill_allreduce_python",
        description="victim SIGKILLed mid-frame-send on the python-ring "
        "plane",
        common_env={"TORCHFT_NATIVE_PLANE": "0"},
        victim_schedule={
            "seed": 1,
            "rules": [
                {"site": "rpc.send", "nth": 4, "action": "kill", "sig": 9}
            ],
        },
        expect_victim_death=True,
        quick=False,
    ),
    Scenario(
        name="torn_stripe_tcp",
        description="a striped-TCP hop is cut after half the payload "
        "(torn write); step must latch + flush, victim survives",
        common_env={"TORCHFT_DP_CMA": "0"},
        victim_env={"TORCHFT_FI_DP_CUT": "3:0.5"},
    ),
    Scenario(
        name="kill_streamed_bucket",
        description="victim SIGKILLed entering a striped hop while the "
        "int8-compressed streamed buckets are in flight — the survivor's "
        "step must latch+flush, and the error-feedback residuals staged "
        "for the doomed step must roll back with the commit lineage "
        "(asserted via final cross-group checksum bit-identity: a leaked "
        "residual would diverge the next committed average)",
        common_env={"TORCHFT_DP_CMA": "0", "TORCHFT_WIRE_CODEC": "int8"},
        victim_env={"TORCHFT_FI_DP_KILL": "3"},
        expect_victim_death=True,
        quick=False,
    ),
    Scenario(
        name="torn_compressed_frame",
        description="a striped hop carrying an int8-compressed frame is "
        "cut after half the payload (torn quantized wire): the receiver "
        "must surface a mid-frame EOF — a partial scale+payload must "
        "never dequantize into a committed average — and the aborted "
        "step's error-feedback residuals must not leak",
        common_env={"TORCHFT_DP_CMA": "0", "TORCHFT_WIRE_CODEC": "int8"},
        victim_env={"TORCHFT_FI_DP_CUT": "3:0.5"},
    ),
    Scenario(
        name="torn_cma_pull",
        description="a CMA pull stops halfway (torn read — the ROADMAP "
        "checksum-divergence hypothesis); partial bytes must never "
        "average into a committed step",
        victim_env={"TORCHFT_FI_CMA_TORN": "3:0.5"},
        # the divergence sentinel rides along: abstain semantics must
        # hold through torn-op aborts (no false latch), and the quick/
        # sanitizer matrix then drives the new lh.digest native path
        # under ASan/TSan (ISSUE 10 acceptance)
        common_env={"TORCHFT_DIVERGENCE_SENTINEL": "1"},
    ),
    Scenario(
        name="postmortem_kill_allreduce",
        description="victim SIGKILLed mid-allreduce (the CMA kill site); "
        "the postmortem tool — from the crash-durable black boxes ALONE — "
        "must name the victim replica, its last in-flight op (allreduce) "
        "and the quorum epoch, with checksums bit-identical after heal "
        "(custom runner: run_postmortem_scenario)",
        victim_env={"TORCHFT_FI_CMA_KILL": "3"},
        expect_victim_death=True,
    ),
    Scenario(
        name="corrupt_divergence",
        description="corrupt(frac) perturbs one replica's finished "
        "allreduce output (collective.complete) — silent, finite, no "
        "error raised: the PR 2 corrupt-commit hole. Three legs (custom "
        "runner run_divergence_scenario): sentinel-only must latch "
        "divergence within one commit of the injection; under "
        "TORCHFT_DIVERGENCE_FENCE=1 the commit must ABORT instead "
        "(checksums stay bit-identical); an equal-length control soak "
        "must latch nothing (digests are bit-identical by construction)",
        common_env={"TORCHFT_DIVERGENCE_SENTINEL": "1"},
        victim_schedule={
            "seed": 6,
            "rules": [
                {
                    "site": "collective.complete",
                    "match": "allreduce",
                    "nth": 5,
                    "action": "corrupt",
                    "frac": 0.05,
                }
            ],
        },
    ),
    Scenario(
        name="commit_vote_delay_pipeline",
        description="every 3rd commit vote delayed 150ms under the "
        "pipelined commit mode",
        common_env={"TORCHFT_COMMIT_PIPELINE": "1"},
        victim_schedule={
            "seed": 2,
            "rules": [
                {
                    "site": "commit.vote",
                    "match": "rpc",
                    "every": 3,
                    "action": "delay",
                    "ms": 150,
                }
            ],
        },
        quick=False,
    ),
    Scenario(
        name="straggler_group",
        description="+200ms skew injected into group 1's collective "
        "submissions (collective.issue delay); the fleet straggler "
        "detector (local-step p50s piggybacked to the lighthouse, "
        "leave-one-out fleet median baseline) must latch exactly that "
        "group within K fresh observations and emit exactly one latched "
        "straggler_detected event, a no-injection control soak of equal "
        "length must produce zero false positives, and checksums must "
        "stay bit-identical through the skew (custom runner: "
        "run_straggler_scenario)",
        victim_schedule={
            "seed": 4,
            "rules": [
                {
                    "site": "collective.issue",
                    "match": "allreduce",
                    "every": 1,
                    "action": "delay",
                    "ms": 200,
                }
            ],
        },
        quick=False,
    ),
    Scenario(
        name="diagnose_straggler",
        description="+200ms collective.issue delay on group 1 (the "
        "straggler_group signal) plus a 60ms native dp-hop delay on the "
        "same victim: the victim's OWN straggler latch (it hosts a "
        "FleetMonitor under TORCHFT_STRAGGLER_MONITOR=1) must auto-"
        "capture exactly ONE diagnosis bundle into TORCHFT_DIAG_DIR "
        "whose native collapsed stacks show the injected-delay frame "
        "(fi::sleep_ms) dominant in the victim's dp.pump hot stack; the "
        "survivor's engine must capture nothing (remote-subject filter); "
        "an equal-length control soak captures ZERO bundles; checksums "
        "bit-identical through the capture; the bundle round-trips "
        "through `postmortem --bundles` (custom runner: "
        "run_diagnose_scenario; --sanitize runs the same legs with the "
        "jax-free worker to prove the new profiler ASan/TSan-clean)",
        victim_schedule={
            "seed": 8,
            "rules": [
                {
                    "site": "collective.issue",
                    "match": "allreduce",
                    "every": 1,
                    "action": "delay",
                    "ms": 200,
                }
            ],
        },
        # native-layer delay on the same victim: lands inside the dp pump
        # threads, which is exactly where the native sampler must find it
        victim_env={"TORCHFT_FI_DP_DELAY_MS": "60"},
        # forced tcp-striped so the dp plane (and its pump threads) runs
        common_env={"TORCHFT_DP_CMA": "0"},
        quick=False,
    ),
    Scenario(
        name="perf_regression",
        description="+150ms collective.issue delay injected on group 1 "
        "MID-RUN (the `after` onset rule): the perf-regression sentinel "
        "(Page-Hinkley over the lighthouse's retained time series) must "
        "latch exactly once per shifted series, naming the injected "
        "group, within K commits of onset; critical-path attribution "
        "must blame that group for >=80% of post-onset gating seconds "
        "with a what-if estimate within 25% of the control leg's "
        "measured step rate; /timeseries.json must serve the full "
        "history across a replica kill/respawn (third leg); and an "
        "equal-length control soak must latch ZERO regressions (custom "
        "runner: run_perf_regression_scenario)",
        victim_schedule={
            "seed": 7,
            "rules": [
                {
                    "site": "collective.issue",
                    "match": "allreduce",
                    "after": 13,
                    "action": "delay",
                    "ms": 150,
                }
            ],
        },
        quick=False,
    ),
    Scenario(
        name="stripe_heal_peer_death",
        description="3 groups (custom runner): the victim g2 is "
        "SIGKILLed mid-run and respawns into a striped multi-source heal "
        "from the two survivors; survivor g1 is SIGKILLed by the native "
        "blob plane on its first stripe serve (TORCHFT_FI_BLOB_KILL) — "
        "the healer must re-stripe g1's pending ranges over g0 and "
        "complete the heal (composing with the PR 4 ckpt_serve_death "
        "retry), g1 respawns and heals striped itself, and all THREE "
        "groups' final checksums must be finite and bit-identical",
        victim_schedule={
            "seed": 5,
            "rules": [
                {
                    "site": "collective.issue",
                    "match": "allreduce",
                    "nth": 6,
                    "action": "kill",
                    "sig": 9,
                }
            ],
        },
        # forced tcp-striped on every group: a victim death on the CMA
        # plane latches broken-CMA (TCP fallback) on SOME survivors only,
        # and mixed planes mean mixed error-feedback enablement — the
        # state TREES then legitimately differ and the digest check
        # (correctly) excludes the odd source, defeating the scenario's
        # two-source premise
        common_env={"TORCHFT_DP_CMA": "0"},
        # g1 = the stripe-serving survivor: its first blob range serve is
        # during g2's re-heal (bootstrap heals are single-source from the
        # sorted-first group, g0, so g1 serves nothing before the kill)
        survivor_env={"TORCHFT_FI_BLOB_KILL": "1"},
        expect_victim_death=True,
    ),
    Scenario(
        name="ckpt_serve_death",
        description="victim killed mid-run; the survivor's first "
        "checkpoint serve to the healer is cut mid-stream (serve death "
        "mid-heal) — the heal must retry, never stage torn state",
        victim_schedule={
            "seed": 3,
            "rules": [
                {
                    "site": "collective.issue",
                    "match": "allreduce",
                    "nth": 6,
                    "action": "kill",
                    "sig": 9,
                }
            ],
        },
        survivor_schedule={
            "seed": 3,
            "rules": [{"site": "ckpt.serve", "nth": 1, "action": "drop"}],
        },
        expect_victim_death=True,
    ),
]


@dataclass
class Result:
    scenario: str
    status: str  # passed | environmental | failed
    detail: str = ""
    fired: int = 0
    respawns: int = 0
    checksums: Optional[List[str]] = None


# descriptors compiled from model-checker traces by
# `python -m torchft_tpu.analysis.protocol.compile` (ISSUE 20): the
# bare `--compiled` flag replays this checked-in set
COMPILED_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "compiled")


def load_compiled_scenarios(compiled_dir: str) -> List[Scenario]:
    """Compiled-schedule descriptors → scenarios. Non-runnable
    descriptors (unlowered HA coordinates awaiting the Raft wiring) are
    skipped loudly — silently dropping them would read as coverage."""
    out: List[Scenario] = []
    for path in sorted(glob.glob(os.path.join(compiled_dir, "*.json"))):
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not doc.get("runnable"):
            print(f"--- {doc.get('name', path)}: SKIPPED (not runnable: "
                  f"{len(doc.get('unlowered', []))} unlowered HA "
                  "action(s) — pending the Raft wiring)")
            continue
        out.append(Scenario(
            name=doc["name"],
            description=doc.get("description", ""),
            common_env=dict(doc.get("common_env", {})),
            victim_schedule=doc.get("victim_schedule"),
            survivor_schedule=doc.get("survivor_schedule"),
            expect_victim_death=bool(doc.get("expect_victim_death")),
            quick=False,
        ))
    return out


def _env_signature(text: str) -> Optional[str]:
    for sig in ENV_CORRUPTION_SIGNATURES:
        if sig in text:
            return sig
    return None


def _spawn(gid: int, lighthouse_addr: str, workdir: str, steps: int,
           env_extra: Dict[str, str],
           argv: Optional[List[str]] = None,
           num_groups: int = 2) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(
        REPLICA_GROUP_ID=str(gid),
        NUM_REPLICA_GROUPS=str(num_groups),
        STEPS=str(steps),
        BATCH="4",
        DATA_PATH=os.path.join(workdir, "corpus.bin"),
        TRACE_PATH=os.path.join(workdir, f"trace{gid}.jsonl"),
        TORCHFT_LIGHTHOUSE=lighthouse_addr,
        JAX_PLATFORMS="cpu",
        TORCHFT_FAULT_EVIDENCE_DIR=os.path.join(workdir, "evidence"),
        TORCHFT_EVENT_TRAIL=os.path.join(workdir, f"trail{gid}.jsonl"),
        # every worker keeps a crash-durable black box: scenario failures
        # auto-collect them into a postmortem report (ISSUE 10), and the
        # postmortem_kill_allreduce scenario asserts on them directly
        TORCHFT_BLACKBOX_DIR=os.path.join(workdir, "blackbox"),
    )
    env.update(env_extra)
    log = open(
        os.path.join(workdir, f"g{gid}.log"), "ab", buffering=0
    )
    return subprocess.Popen(
        argv or [sys.executable, _EXAMPLE],
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )


def _read_log(workdir: str, gid: int) -> str:
    try:
        with open(os.path.join(workdir, f"g{gid}.log"), "rb") as f:
            return f.read().decode(errors="replace")
    except OSError:
        return ""


def _worker_env(scn: Scenario, gid: int, respawn: bool = False
                ) -> Dict[str, str]:
    env = dict(scn.common_env)
    schedule = scn.survivor_schedule if gid == 0 else scn.victim_schedule
    env.update(scn.survivor_env if gid == 0 else scn.victim_env)
    if schedule is not None:
        env["TORCHFT_FAULT_SCHEDULE"] = json.dumps(schedule)
    if respawn:
        # injections fire in the FIRST incarnation only: occurrence
        # counters are per-process, so a respawned victim would re-arm
        # the same nth coordinates and die at the same point forever.
        # Plane-selection env (TORCHFT_DP_CMA etc.) stays.
        env.pop("TORCHFT_FAULT_SCHEDULE", None)
        for k in [k for k in env if k.startswith("TORCHFT_FI_")]:
            env.pop(k)
    return env


def run_scenario(scn: Scenario, workdir: str, steps: int = 16,
                 timeout_s: float = 600.0,
                 extra_env: Optional[Dict[str, str]] = None,
                 worker_argv: Optional[List[str]] = None) -> Result:
    """One 2-group run under the scenario's schedule; victim = group 1.

    ``extra_env``/``worker_argv`` are the sanitize hooks: the ASan env
    (TORCHFT_NATIVE_LIB + LD_PRELOAD) must reach ONLY the workers — the
    runner process itself is uninstrumented, and dlopen'ing the ASan
    core without its preloaded runtime aborts — and the workers must be
    the jax-free ``_san_worker`` (ASan's ``__cxa_throw`` interceptor is
    incompatible with jaxlib's jit tracing)."""
    from torchft_tpu.coordination import LighthouseServer

    os.makedirs(workdir, exist_ok=True)
    evidence_dir = os.path.join(workdir, "evidence")
    os.makedirs(evidence_dir, exist_ok=True)
    # deterministic toy corpus (no numpy needed: repeatable byte pattern)
    with open(os.path.join(workdir, "corpus.bin"), "wb") as f:
        f.write(bytes(range(256)) * 24)

    def worker_env(gid: int, respawn: bool = False) -> Dict[str, str]:
        env = dict(extra_env or {})
        env.update(_worker_env(scn, gid, respawn=respawn))
        return env

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    addr = lighthouse.address().split("//", 1)[-1]
    procs = {
        0: _spawn(0, addr, workdir, steps, worker_env(0), worker_argv),
        1: _spawn(1, addr, workdir, steps, worker_env(1), worker_argv),
    }
    respawns = 0
    consumed_kill_pids: set = set()  # evidence already honored by a respawn
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            # classify finished workers BEFORE the all-dead break: a
            # victim whose scheduled kill lands in the same 0.5s poll
            # window the survivor exits in must still be respawned
            for gid, p in list(procs.items()):
                if p.poll() is None or p.returncode == 0:
                    continue
                text = _read_log(workdir, gid)
                kills = [
                    r for r in read_evidence(evidence_dir)
                    if r.get("action") == "kill"
                    and r.get("pid") == p.pid
                    and p.pid not in consumed_kill_pids
                ]
                if kills and respawns < 4:
                    # a scheduled death (kill evidence written by THIS
                    # pid): the respawn IS the scenario. The respawned
                    # worker runs a scrubbed env — see _worker_env — so it
                    # rejoins, heals, and finishes.
                    consumed_kill_pids.add(p.pid)
                    respawns += 1
                    procs[gid] = _spawn(
                        gid, addr, workdir, steps,
                        worker_env(gid, respawn=True), worker_argv,
                    )
                elif _env_signature(text) \
                        or p.returncode in CORRUPTION_SIGNAL_RCS:
                    return Result(
                        scn.name, "environmental",
                        f"g{gid} rc={p.returncode} "
                        f"sig={_env_signature(text)!r} (documented "
                        "pre-existing corruption, no injection evidence)",
                        fired=len(read_evidence(evidence_dir)),
                        respawns=respawns,
                    )
                else:
                    return Result(
                        scn.name, "failed",
                        f"g{gid} rc={p.returncode} not explained by "
                        f"new injection evidence; log tail: "
                        f"{text[-1500:]}",
                        fired=len(read_evidence(evidence_dir)),
                        respawns=respawns,
                    )
            if all(p.poll() is not None for p in procs.values()):
                break  # every worker exited 0 (nonzero handled above)
            if time.monotonic() > deadline:
                return Result(
                    scn.name, "failed",
                    f"timeout after {timeout_s}s "
                    f"(alive: {sorted(g for g, p in procs.items() if p.poll() is None)}, "
                    f"done: { {g: p.returncode for g, p in procs.items() if p.poll() is not None} })",
                    respawns=respawns,
                )
            time.sleep(0.5)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lighthouse.shutdown()

    fired = read_evidence(evidence_dir)
    sums = []
    for gid in (0, 1):
        text = _read_log(workdir, gid)
        m = re.findall(r"param_checksum=(-?[\d.]+|nan|inf)", text)
        if not m:
            return Result(
                scn.name, "failed",
                f"g{gid} exited 0 but printed no param_checksum; "
                f"log tail: {text[-800:]}",
                fired=len(fired), respawns=respawns,
            )
        sums.append(m[-1])

    # THE invariant: finite and bit-identical across groups — a torn or
    # killed transfer never leaked into a committed average
    if any(s in ("nan", "inf") for s in sums):
        return Result(
            scn.name, "failed",
            f"non-finite committed checksums {sums} — corrupt averages "
            "committed (the divergence mode)",
            fired=len(fired), respawns=respawns, checksums=sums,
        )
    if sums[0] != sums[1]:
        return Result(
            scn.name, "failed",
            f"checksum divergence across groups: {sums}",
            fired=len(fired), respawns=respawns, checksums=sums,
        )
    if (scn.victim_schedule or scn.survivor_schedule or scn.victim_env) \
            and not fired:
        return Result(
            scn.name, "failed",
            "scenario completed but NO injection fired (schedule "
            "coordinates never hit — tighten nth/site)",
            respawns=respawns, checksums=sums,
        )
    if scn.expect_victim_death and respawns == 0:
        return Result(
            scn.name, "failed",
            "expected an injected victim death + respawn; none happened",
            fired=len(fired), checksums=sums,
        )
    return Result(
        scn.name, "passed", f"checksums {sums[0]} == {sums[1]}",
        fired=len(fired), respawns=respawns, checksums=sums,
    )


def run_stripe_heal_scenario(
    scn: Scenario, workdir: str, steps: int = 16, timeout_s: float = 600.0,
    extra_env: Optional[Dict[str, str]] = None,
    worker_argv: Optional[List[str]] = None,
) -> Result:
    """The ``stripe_heal_peer_death`` scenario (ISSUE 9): THREE groups so
    a striped heal has two sources to lose one of.

    Roles: g0 runs clean; g2 (victim) is SIGKILLed mid-allreduce by its
    schedule and respawned (scrubbed env) into a striped heal from
    {g0, g1}; g1 carries ``TORCHFT_FI_BLOB_KILL=1`` — its first native
    blob range serve (which is a stripe of g2's re-heal; bootstrap heals
    are single-source from the sorted-first group g0) SIGKILLs it
    mid-serve. The healer must re-stripe g1's pending ranges over g0 and
    complete the heal; g1 is respawned and heals striped itself. PASS =
    both deaths carry injection evidence, both victims respawned, and all
    three groups exit 0 with finite, bit-identical final checksums.
    Supports ``--sanitize`` (the jax-free numpy worker drives the same
    refactored native stripe/blob layer).

    The lighthouse runs ``min_replicas=3`` (all groups): with the default
    2, the two survivors finish the whole run and EXIT while the
    respawned victim is still booting (a few seconds of interpreter/jax
    import), leaving it alone with an unformable quorum — gating quorum
    formation on the full fleet keeps survivors parked (no commits)
    during each absence, which is also the configuration under which the
    striped heal deterministically has two sources."""
    from torchft_tpu.coordination import LighthouseServer

    os.makedirs(workdir, exist_ok=True)
    evidence_dir = os.path.join(workdir, "evidence")
    os.makedirs(evidence_dir, exist_ok=True)
    with open(os.path.join(workdir, "corpus.bin"), "wb") as f:
        f.write(bytes(range(256)) * 24)

    def worker_env(gid: int, respawn: bool = False) -> Dict[str, str]:
        env = dict(extra_env or {})
        env.update(scn.common_env)
        if gid == 1:
            env.update(scn.survivor_env)
            if scn.survivor_schedule is not None:
                env["TORCHFT_FAULT_SCHEDULE"] = json.dumps(
                    scn.survivor_schedule
                )
        elif gid == 2:
            env.update(scn.victim_env)
            if scn.victim_schedule is not None:
                env["TORCHFT_FAULT_SCHEDULE"] = json.dumps(scn.victim_schedule)
        if respawn:
            env.pop("TORCHFT_FAULT_SCHEDULE", None)
            for k in [k for k in env if k.startswith("TORCHFT_FI_")]:
                env.pop(k)
        return env

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=3)
    addr = lighthouse.address().split("//", 1)[-1]
    procs = {
        g: _spawn(g, addr, workdir, steps, worker_env(g), worker_argv,
                  num_groups=3)
        for g in (0, 1, 2)
    }
    respawns = 0
    consumed_kill_pids: set = set()
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            for gid, p in list(procs.items()):
                if p.poll() is None or p.returncode == 0:
                    continue
                text = _read_log(workdir, gid)
                kills = [
                    r for r in read_evidence(evidence_dir)
                    if r.get("action") == "kill"
                    and r.get("pid") == p.pid
                    and p.pid not in consumed_kill_pids
                ]
                if kills and respawns < 4:
                    consumed_kill_pids.add(p.pid)
                    respawns += 1
                    procs[gid] = _spawn(
                        gid, addr, workdir, steps,
                        worker_env(gid, respawn=True), worker_argv,
                        num_groups=3,
                    )
                elif _env_signature(text) \
                        or p.returncode in CORRUPTION_SIGNAL_RCS:
                    return Result(
                        scn.name, "environmental",
                        f"g{gid} rc={p.returncode} "
                        f"sig={_env_signature(text)!r}",
                        fired=len(read_evidence(evidence_dir)),
                        respawns=respawns,
                    )
                else:
                    return Result(
                        scn.name, "failed",
                        f"g{gid} rc={p.returncode} not explained by new "
                        f"injection evidence; log tail: {text[-1500:]}",
                        fired=len(read_evidence(evidence_dir)),
                        respawns=respawns,
                    )
            if all(p.poll() is not None for p in procs.values()):
                break
            if time.monotonic() > deadline:
                return Result(
                    scn.name, "failed",
                    f"timeout after {timeout_s}s (alive: "
                    f"{sorted(g for g, p in procs.items() if p.poll() is None)})",
                    respawns=respawns,
                )
            time.sleep(0.5)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lighthouse.shutdown()

    fired = read_evidence(evidence_dir)
    blob_kills = [
        r for r in fired
        if r.get("action") == "kill" and r.get("site") == "blob.serve"
    ]
    sums = []
    for gid in (0, 1, 2):
        text = _read_log(workdir, gid)
        m = re.findall(r"param_checksum=(-?[\d.]+|nan|inf)", text)
        if not m:
            return Result(
                scn.name, "failed",
                f"g{gid} exited 0 but printed no param_checksum; "
                f"log tail: {text[-800:]}",
                fired=len(fired), respawns=respawns,
            )
        sums.append(m[-1])
    if any(s in ("nan", "inf") for s in sums):
        return Result(
            scn.name, "failed",
            f"non-finite committed checksums {sums}",
            fired=len(fired), respawns=respawns, checksums=sums,
        )
    if len(set(sums)) != 1:
        return Result(
            scn.name, "failed",
            f"checksum divergence across 3 groups: {sums}",
            fired=len(fired), respawns=respawns, checksums=sums,
        )
    if not blob_kills:
        return Result(
            scn.name, "failed",
            "no blob.serve kill evidence — the stripe-serving survivor "
            "was never killed mid-serve (heal too early/late?)",
            fired=len(fired), respawns=respawns, checksums=sums,
        )
    if respawns < 2:
        return Result(
            scn.name, "failed",
            f"expected BOTH the victim and the stripe-serving survivor "
            f"to die+respawn; respawns={respawns}",
            fired=len(fired), respawns=respawns, checksums=sums,
        )
    return Result(
        scn.name, "passed",
        f"3-way checksums identical ({sums[0]}); blob-serve kill + "
        f"re-stripe survived",
        fired=len(fired), respawns=respawns, checksums=sums,
    )


def _final_checksums(workdir: str) -> "tuple[Optional[str], List[str]]":
    """Collect each group's final param_checksum; returns (error, sums) —
    error is a human-readable failure reason or None."""
    sums: List[str] = []
    for gid in (0, 1):
        text = _read_log(workdir, gid)
        m = re.findall(r"param_checksum=(-?[\d.]+|nan|inf)", text)
        if not m:
            return (
                f"g{gid} printed no param_checksum; log tail: {text[-800:]}",
                sums,
            )
        sums.append(m[-1])
    if any(s in ("nan", "inf") for s in sums):
        return (f"non-finite committed checksums {sums}", sums)
    if sums[0] != sums[1]:
        return (f"checksum divergence across groups: {sums}", sums)
    return (None, sums)


def run_straggler_scenario(
    scn: Scenario, workdir: str, steps: int = 16, timeout_s: float = 600.0,
) -> Result:
    """The straggler_group scenario (ISSUE 8 satellite): two legs.

    **Control leg** (runs first) — the soak with no injection; the
    detector must produce ZERO events (the false-positive gate the
    ROADMAP elastic-fleet item needs before staleness-bounded async
    commits can trust the signal). Its final per-replica local-step
    p50s also size the injected leg's skew: the factor-2.0 gate is on
    the *ratio* to the fleet median, so the skew must scale with
    whatever the host's steady step time happens to be that run.

    **Injected leg** — group 1 submits every allreduce ``2x`` the
    measured steady p50 late (floor 200 ms; the ``collective.issue``
    delay site). The runner hosts the fleet detector: a
    :class:`~torchft_tpu.telemetry.slo.FleetMonitor` polls the
    lighthouse's ``/cluster.json`` for the piggybacked local-step p50s
    and feeds a :class:`StragglerDetector` (factor 2.0, K=3 — tight
    enough to latch within the 16-step run, wide enough that scheduler
    jitter between two identical groups can't reach it). Asserts: the
    detector names exactly ``train_bytes_1``, emits exactly ONE latched
    ``straggler_detected`` event, and the final checksums are finite and
    bit-identical across groups (a delay must never corrupt averages).
    """
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.telemetry.slo import FleetMonitor, StragglerDetector

    victim_id = "train_bytes_1"
    detector_cfg = dict(factor=2.0, k=3)

    def leg(
        name: str, inject: bool, delay_ms: Optional[int] = None
    ) -> "tuple[Optional[str], List[Dict], int, Dict[str, float]]":
        """Run one 2-group soak; returns (error, detector_events, fired,
        final per-replica local-step p50s)."""
        wd = os.path.join(workdir, name)
        os.makedirs(wd, exist_ok=True)
        evidence_dir = os.path.join(wd, "evidence")
        os.makedirs(evidence_dir, exist_ok=True)
        with open(os.path.join(wd, "corpus.bin"), "wb") as f:
            f.write(bytes(range(256)) * 24)
        lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
        addr = lighthouse.address().split("//", 1)[-1]
        monitor = FleetMonitor(
            lighthouse.address(),
            detector=StragglerDetector(**detector_cfg),
            poll_s=0.25,
        )
        events: List[Dict] = []
        env0 = _worker_env(scn, 0)
        env1 = _worker_env(scn, 1)
        if not inject:
            env1.pop("TORCHFT_FAULT_SCHEDULE", None)
        elif delay_ms is not None:
            # weather-sized skew (see the leg ordering below): patch the
            # schedule's delay in place of the spec's floor value
            sched = json.loads(env1["TORCHFT_FAULT_SCHEDULE"])
            sched["rules"][0]["ms"] = int(delay_ms)
            env1["TORCHFT_FAULT_SCHEDULE"] = json.dumps(sched)
        procs = {
            0: _spawn(0, addr, wd, steps, env0),
            1: _spawn(1, addr, wd, steps, env1),
        }
        deadline = time.monotonic() + timeout_s
        err: Optional[str] = None
        p50s: Dict[str, float] = {}
        try:
            while True:
                # the runner IS the fleet monitor: poll synchronously so
                # the detection sequence is deterministic per leg
                try:
                    events.extend(monitor.poll_once())
                except Exception:  # noqa: BLE001 — scrape races are fine
                    pass
                done = {g: p.poll() for g, p in procs.items()}
                for gid, rc in done.items():
                    if rc is not None and rc != 0:
                        err = (
                            f"{name}: g{gid} rc={rc}; log tail: "
                            f"{_read_log(wd, gid)[-1000:]}"
                        )
                        break
                if err or all(rc is not None for rc in done.values()):
                    break
                if time.monotonic() > deadline:
                    err = f"{name}: timeout after {timeout_s}s"
                    break
                time.sleep(0.25)
            # final per-replica p50s: the control leg's steady step time
            # is what sizes the injected leg's skew
            try:
                from torchft_tpu.telemetry.native import poll_cluster

                cluster = poll_cluster(lighthouse.address()) or {}
                for rid, rec in (cluster.get("replicas") or {}).items():
                    try:
                        p50s[rid] = float(
                            rec.get("local_step_p50_s") or 0.0
                        )
                    except (TypeError, ValueError):
                        pass
            except Exception:  # noqa: BLE001 — best effort
                pass
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            lighthouse.shutdown()
        if err is None:
            cs_err, _sums = _final_checksums(wd)
            if cs_err:
                err = f"{name}: {cs_err}"
        return err, events, len(read_evidence(evidence_dir)), p50s

    # Control leg FIRST: beyond the false-positive gate, it measures the
    # box's steady local-step p50 so the injected skew can be sized
    # RELATIVE to it. The factor-2.0 detector needs p50+skew >= 2x the
    # fleet median — a fixed 200 ms skew that dwarfs an idle box's
    # ~0.15 s steps never crosses the gate on a loaded box running
    # ~0.5 s steps (found as a full-suite-only flake: the detector
    # mathematically could not latch under that day's load).
    ctl_err, ctl_events, _cf, ctl_p50s = leg("control", inject=False)
    if ctl_err:
        return Result(scn.name, "failed", ctl_err)
    if ctl_events:
        return Result(
            scn.name, "failed",
            f"control soak emitted detector events (false positives): "
            f"{ctl_events}",
        )
    steady = sorted(v for v in ctl_p50s.values() if v > 0)
    delay_ms = 200
    if steady:
        # 2x the steady p50 puts the victim's p50 at ~3x the fleet
        # median — comfortably past factor 2.0, while two identical
        # groups' jitter stays far below it
        delay_ms = max(200, int(2000 * steady[len(steady) // 2]))

    err, events, fired, _p50s = leg("injected", inject=True,
                                    delay_ms=delay_ms)
    if err:
        return Result(scn.name, "failed", err, fired=fired)
    detected = [e for e in events if e["event"] == "straggler_detected"]
    if len(detected) != 1:
        return Result(
            scn.name, "failed",
            f"expected exactly one latched straggler_detected, got "
            f"{len(detected)}: {detected}", fired=fired,
        )
    # the Manager appends a uuid4 suffix to every replica_id, so match on
    # the stable example-chosen prefix (2 groups: train_bytes_0 / _1)
    if not detected[0]["group"].startswith(victim_id):
        return Result(
            scn.name, "failed",
            f"detector named {detected[0]['group']!r}, not the skewed "
            f"group {victim_id!r}* ({detected[0]})", fired=fired,
        )
    if fired == 0:
        return Result(
            scn.name, "failed",
            "no injection evidence recorded — the delay never fired",
        )

    return Result(
        scn.name, "passed",
        f"latched {victim_id} once (p50 {detected[0]['p50_s']}s vs "
        f"baseline {detected[0]['baseline_s']}s, {delay_ms}ms skew); "
        f"control soak clean",
        fired=fired,
    )


def run_diagnose_scenario(
    scn: Scenario, workdir: str, steps: int = 24, timeout_s: float = 600.0,
    extra_env: Optional[Dict[str, str]] = None,
    worker_argv: Optional[List[str]] = None,
) -> Result:
    """The ``diagnose_straggler`` scenario (ISSUE 12): detection →
    diagnosis, end to end, in the victim's own process.

    **Injected leg** — group 1 submits every allreduce 200 ms late
    (the straggler signal) AND delays every native dp hop 60 ms
    (``TORCHFT_FI_DP_DELAY_MS`` — the frame the profiler must find).
    BOTH workers host a FleetMonitor (``TORCHFT_STRAGGLER_MONITOR=1``,
    factor 2.0, K=3) and a DiagnosisEngine (``TORCHFT_DIAG_DIR`` →
    one shared fleet dir). The victim's own monitor latches
    ``straggler_detected`` naming itself → its engine captures; the
    survivor's monitor latches the SAME event naming the victim → its
    engine's remote-subject filter drops it. Asserts: exactly ONE
    bundle fleet-wide, written by the victim, whose ``native.folded``
    shows the injected-delay frame (``fi::sleep_ms`` / nanosleep)
    dominant in the victim's ``dp.pump`` hot stack (top stack by count,
    and a majority share of pump samples); the bundle round-trips
    through ``postmortem --bundles``; checksums stay bit-identical.

    **Control leg** — identical env, no injection: ZERO bundles (the
    false-capture gate — an autopilot attaching evidence to an eviction
    must never fire on a healthy fleet).

    Under ``--sanitize`` the same two legs run with the jax-free numpy
    worker and the native profiler at 97 Hz (sampling pressure on the
    SIGPROF handler/seqlock/drain paths under ASan/TSan). The numpy
    worker's raw ``allreduce().wait()`` is not ledger-attributed as a
    barrier phase, so the victim's delay inflates BOTH groups' local
    time and the straggler compare cannot discriminate — the sanitized
    legs trigger through the victim-only step-time SLO instead
    (``TORCHFT_SLO_STEP_S``), which exercises the identical
    latch→capture path; bundle capture is still asserted, but
    stack-dominance is only checked when a native snapshot exists —
    sanitizer scheduling skews sampling too much to gate on
    percentages."""
    from torchft_tpu.coordination import LighthouseServer

    sanitized = worker_argv is not None
    if sanitized:
        # the SLO evaluator's min_events floor (8) sets the earliest
        # possible latch; leave enough post-latch steps for the capture
        # window to finish before the worker exits
        steps = max(steps, 20)
    # the jax-free sanitize worker names its replicas san_worker_<gid>
    victim_id = "san_worker_1" if sanitized else "train_bytes_1"

    def leg(name: str, inject: bool) -> "tuple[Optional[str], str, int]":
        """One 2-group soak; returns (error, leg_diag_dir, fired)."""
        wd = os.path.join(workdir, name)
        os.makedirs(wd, exist_ok=True)
        evidence_dir = os.path.join(wd, "evidence")
        os.makedirs(evidence_dir, exist_ok=True)
        leg_diag = os.path.join(wd, "diag")
        os.makedirs(leg_diag, exist_ok=True)
        with open(os.path.join(wd, "corpus.bin"), "wb") as f:
            f.write(bytes(range(256)) * 24)
        lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
        addr = lighthouse.address().split("//", 1)[-1]

        def env_for(gid: int) -> Dict[str, str]:
            env = dict(extra_env or {})
            env.update(_worker_env(scn, gid))
            env.update(
                # one shared fleet dir: "exactly one bundle" is a
                # fleet-wide claim, not a per-process one
                TORCHFT_DIAG_DIR=leg_diag,
                TORCHFT_DIAG_WINDOW_S="1.5",
                TORCHFT_PROF_BURST_HZ="97",
                # every group hosts the detector: the victim must latch
                # ITSELF for the self-capture path to fire
                TORCHFT_STRAGGLER_MONITOR="1",
                TORCHFT_STRAGGLER_FACTOR="2.0",
                TORCHFT_STRAGGLER_K="3",
                TORCHFT_STRAGGLER_POLL_S="0.25",
            )
            if sanitized:
                # sampling pressure on the new native paths is the point
                env["TORCHFT_PROF_HZ"] = "97"
                env["TORCHFT_DIAG_WINDOW_S"] = "0.75"
                # see docstring: the straggler compare can't discriminate
                # in the numpy worker — trigger via the victim-only SLO
                env.pop("TORCHFT_STRAGGLER_MONITOR", None)
                if gid == 1 and inject:
                    env["TORCHFT_SLO_STEP_S"] = "0.01"
            if not inject:
                env.pop("TORCHFT_FAULT_SCHEDULE", None)
                for k in [k for k in env if k.startswith("TORCHFT_FI_")]:
                    env.pop(k)
            return env

        procs = {
            0: _spawn(0, addr, wd, steps, env_for(0), worker_argv),
            1: _spawn(1, addr, wd, steps, env_for(1), worker_argv),
        }
        deadline = time.monotonic() + timeout_s
        err: Optional[str] = None
        try:
            while True:
                done = {g: p.poll() for g, p in procs.items()}
                for gid, rc in done.items():
                    if rc is not None and rc != 0:
                        err = (
                            f"{name}: g{gid} rc={rc}; log tail: "
                            f"{_read_log(wd, gid)[-1000:]}"
                        )
                        break
                if err or all(rc is not None for rc in done.values()):
                    break
                if time.monotonic() > deadline:
                    err = f"{name}: timeout after {timeout_s}s"
                    break
                time.sleep(0.25)
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            lighthouse.shutdown()
        if err is None:
            cs_err, _sums = _final_checksums(wd)
            if cs_err:
                err = f"{name}: {cs_err}"
        return err, leg_diag, len(read_evidence(evidence_dir))

    err, diag_dir_inj, fired = leg("injected", inject=True)
    if err:
        return Result(scn.name, "failed", err, fired=fired)
    if fired == 0:
        return Result(
            scn.name, "failed",
            "no injection evidence recorded — the delay never fired",
        )
    from torchft_tpu.telemetry.diagnosis import read_bundles

    bundles = read_bundles(diag_dir_inj)
    expect_trigger = "slo_breach" if sanitized else "straggler_detected"
    if (len(bundles) != 1) if not sanitized else (len(bundles) < 1):
        return Result(
            scn.name, "failed",
            f"expected exactly one diagnosis bundle fleet-wide, got "
            f"{len(bundles)}: {[b.get('bundle') for b in bundles]}",
            fired=fired,
        )
    b = bundles[0]
    trig = (b.get("trigger") or {}).get("event")
    if trig != expect_trigger:
        return Result(
            scn.name, "failed",
            f"bundle trigger is {trig!r}, not {expect_trigger} ({b})",
            fired=fired,
        )
    replica = str(b.get("replica_id") or "")
    if not replica.startswith(victim_id):
        return Result(
            scn.name, "failed",
            f"bundle written by {replica!r}, not the victim "
            f"{victim_id!r}* — the remote-subject filter failed",
            fired=fired,
        )
    # the diagnosis claim itself: the victim's native hot stack names
    # the injected delay. "Dominant" = the single most-sampled dp.pump
    # stack carries the delay frame AND delay frames hold a majority of
    # the victim's pump samples during the burst window.
    try:
        with open(
            os.path.join(b["_dir"], "native.folded"), encoding="utf-8"
        ) as f:
            folded = f.read()
    except OSError:
        folded = ""
    pump = [
        (line.rpartition(" ")[0], int(line.rpartition(" ")[2]))
        for line in folded.splitlines()
        if line.startswith("dp.pump") and line.rpartition(" ")[2].isdigit()
    ]
    # the HOT stack = samples doing stripe work (run_stripe and below).
    # A wall-clock sampler also sees the pump threads PARKED in their
    # job cond-wait while the python-side issue delay holds the step
    # back — that idleness is ambient truth, not the hot stack, and a
    # flamegraph reader filters it the same way.
    active = [(s, c) for s, c in pump if "run_stripe" in s]
    if active:
        total = sum(c for _s, c in active)
        delayed = sum(
            c for s, c in active if "sleep_ms" in s or "nanosleep" in s
        )
        top_stack = max(active, key=lambda sc: sc[1])[0]
        top_has_delay = "sleep_ms" in top_stack or "nanosleep" in top_stack
        if not top_has_delay or delayed * 2 < total:
            return Result(
                scn.name, "failed",
                f"injected-delay frame not dominant in the victim's "
                f"native hot stack: {delayed}/{total} active pump "
                f"samples, top stack {top_stack[:200]!r}",
                fired=fired,
            )
        dominance = (
            f"{delayed}/{total} active pump samples in the delay frame"
        )
    elif not sanitized:
        return Result(
            scn.name, "failed",
            "bundle carries no active dp.pump native stacks — the burst "
            f"window sampled no stripe work (folded: {folded[:300]!r})",
            fired=fired,
        )
    else:
        dominance = "no active native stacks (sanitizer skew: ok)"
    # round-trip: the postmortem CLI folds the bundle into the causal
    # timeline (latch -> capture -> evidence) from disk alone
    from torchft_tpu.telemetry import postmortem

    report = postmortem.analyze(workdir, bundles_dir=diag_dir_inj)
    caps = [
        r for r in report["timeline"] if r.get("k") == "diagnosis_captured"
    ]
    if not report.get("bundles") or not caps:
        return Result(
            scn.name, "failed",
            "postmortem --bundles did not fold the bundle into the "
            f"timeline (bundles={report.get('bundles')})",
            fired=fired,
        )

    ctl_err, diag_dir_ctl, _ = leg("control", inject=False)
    if ctl_err:
        return Result(scn.name, "failed", ctl_err, fired=fired)
    ctl_bundles = read_bundles(diag_dir_ctl)
    if ctl_bundles:
        return Result(
            scn.name, "failed",
            f"control soak captured {len(ctl_bundles)} bundle(s) — "
            f"false captures: {[b.get('bundle') for b in ctl_bundles]}",
            fired=fired,
        )
    return Result(
        scn.name, "passed",
        f"one bundle by {replica} ({dominance}); postmortem round-trip "
        "ok; control soak captured zero",
        fired=fired,
    )


def run_perf_regression_scenario(
    scn: Scenario, workdir: str, steps: int = 16, timeout_s: float = 600.0,
) -> Result:
    """The ``perf_regression`` scenario (ISSUE 11): three legs proving the
    fleet time machine end to end.

    **Control leg** — 2-group soak, no injection, the runner hosting the
    perf-regression sentinel (:class:`RegressionMonitor`) and the
    critical-path attributor (:class:`CriticalPathMonitor`) against the
    live lighthouse's ``/timeseries.json``. Must latch ZERO regressions
    (the false-positive gate).

    **Injected leg** — identical soak, but group 1 submits every
    allreduce late FROM the onset occurrence onward (the `after` rule —
    a level shift, not a transient), the shift sized at ~1x the control
    leg's measured median step wall (floor 150 ms) so the
    relative-threshold sentinel sees a doubling at any host load. Asserts: (a) the sentinel
    latches at least one series, every latch names the injected group,
    and each (replica, series) latches exactly once; (b) the first latch
    lands within K=10 commits of the measured onset step; (c) post-onset
    critical-path blame lands >=80% on the injected group; (d) the
    post-onset what-if steps/s estimate is within 25% of the measured
    no-injection step rate — the SAME leg's steady pre-onset window, so
    the two sides of the comparison share the box's load (the first cut
    compared against the control leg and failed whenever background load
    shifted between legs; a cross-leg reference measures the weather,
    not the estimator); (e) checksums stay finite and bit-identical (a
    delay must never corrupt averages).

    **Persistence leg** — group 1 is SIGKILLed mid-run and respawned
    (fresh replica uuid): after the run, ``/timeseries.json`` must still
    serve the DEAD incarnation's pre-kill ring alongside the respawn's —
    the full history across a kill/respawn, which is exactly what the
    postmortem consumer needs."""
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.telemetry.critical_path import CriticalPathMonitor
    from torchft_tpu.telemetry.regression import (
        RegressionDetector,
        RegressionMonitor,
    )

    victim_id = "train_bytes_1"
    leg_steps = max(steps, 28)  # PH warm-up + onset + detection margin
    K_COMMITS = 10
    # slightly conservative vs the defaults: this box runs 2 jax workers
    # on few cores, so per-step jitter is real — a wider drift allowance
    # keeps the control leg honest while the ~1x-median shift still
    # latches within a handful of samples
    det_cfg = dict(delta=0.1, lam=4.0, min_n=8, k=4)

    def leg(name: str, inject: bool, delay_ms: Optional[int] = None):
        """One monitored 2-group soak. Returns (err, reg_events,
        attributions, fired, onset_ts, workdir)."""
        wd = os.path.join(workdir, name)
        os.makedirs(wd, exist_ok=True)
        evidence_dir = os.path.join(wd, "evidence")
        os.makedirs(evidence_dir, exist_ok=True)
        with open(os.path.join(wd, "corpus.bin"), "wb") as f:
            f.write(bytes(range(256)) * 24)
        # the tsdb store is process-global (one lighthouse per process in
        # production); this runner hosts several lighthouses in ONE
        # process across legs/scenarios, so clear the store or every
        # previous leg's rings — same step numbers, different replicas —
        # contaminate this leg's /timeseries.json and mix into the
        # per-step attribution rows (found as a pytest-matrix-order
        # failure: a prior straggler leg's 0.4s locals out-gated the
        # live victim)
        from torchft_tpu import _native

        _native.tsdb_reset()
        lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
        addr = lighthouse.address().split("//", 1)[-1]
        monitor = RegressionMonitor(
            lighthouse.address(),
            detector=RegressionDetector(**det_cfg),
            poll_s=0.25,
        )
        cpm = CriticalPathMonitor(lighthouse.address())
        reg_events: List[Dict] = []
        attributions: List[Dict] = []
        env0 = _worker_env(scn, 0)
        env1 = _worker_env(scn, 1)
        if not inject:
            env1.pop("TORCHFT_FAULT_SCHEDULE", None)
        elif delay_ms is not None:
            # the level shift is sized off the control leg's measured
            # steady wall (see the call sites): PH's lambda/delta are
            # RELATIVE to the running location, so a fixed 150 ms shift
            # that latches instantly on idle ~0.08 s steps is invisible
            # on a loaded box running ~0.5 s steps
            sched = json.loads(env1["TORCHFT_FAULT_SCHEDULE"])
            sched["rules"][0]["ms"] = int(delay_ms)
            env1["TORCHFT_FAULT_SCHEDULE"] = json.dumps(sched)
        procs = {
            0: _spawn(0, addr, wd, leg_steps, env0),
            1: _spawn(1, addr, wd, leg_steps, env1),
        }
        deadline = time.monotonic() + timeout_s
        err: Optional[str] = None
        try:
            while True:
                # the runner IS the history-plane consumer: poll
                # synchronously so the detection sequence is
                # deterministic per leg; ONE fetch feeds both consumers
                try:
                    from torchft_tpu.telemetry.timeseries import (
                        poll_timeseries,
                    )

                    reply = poll_timeseries(lighthouse.address())
                    if reply:
                        reg_events.extend(monitor.poll_once(reply=reply))
                        attributions.extend(cpm.poll_once(reply=reply))
                except Exception:  # noqa: BLE001 — scrape races are fine
                    pass
                done = {g: p.poll() for g, p in procs.items()}
                for gid, rc in done.items():
                    if rc is not None and rc != 0:
                        err = (
                            f"{name}: g{gid} rc={rc}; log tail: "
                            f"{_read_log(wd, gid)[-1000:]}"
                        )
                        break
                if err or all(rc is not None for rc in done.values()):
                    break
                if time.monotonic() > deadline:
                    err = f"{name}: timeout after {timeout_s}s"
                    break
                time.sleep(0.25)
            # final sweep: the last steps' samples land with the final
            # quorum RPCs — poll once more, then force pending steps out
            try:
                reg_events.extend(monitor.poll_once())
                attributions.extend(cpm.poll_once())
                attributions.extend(cpm.drain())
            except Exception:  # noqa: BLE001
                pass
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            lighthouse.shutdown()
        if err is None:
            cs_err, _sums = _final_checksums(wd)
            if cs_err:
                err = f"{name}: {cs_err}"
        evidence = read_evidence(evidence_dir)
        onset_ts = min(
            (r["ts"] for r in evidence if r.get("action") == "delay"),
            default=None,
        )
        return err, reg_events, attributions, len(evidence), onset_ts, wd

    def onset_step_from_trail(wd: str, onset_ts: Optional[float]) -> int:
        """The first step COMMITTED after the first delay fired — the
        onset in commit coordinates (evidence records carry wall ts; the
        victim's trail carries (ts, step) for every commit)."""
        if onset_ts is None:
            return -1
        from torchft_tpu.telemetry.events import read_trail

        try:
            trail = read_trail(os.path.join(wd, "trail1.jsonl"))
        except OSError:
            return -1
        commits = sorted(
            (r["ts"], r.get("step", -1))
            for r in trail
            if r.get("event") == "commit"
        )
        for ts, step in commits:
            if ts >= onset_ts:
                return int(step)
        return -1

    # ---- control leg: the zero-false-latch gate -----------------------
    err, ctl_events, ctl_atts, _f, _o, _wd = leg("control", inject=False)
    if err:
        return Result(scn.name, "failed", err)
    ctl_regressions = [
        e for e in ctl_events if e["event"] == "perf_regression"
    ]
    if ctl_regressions:
        return Result(
            scn.name, "failed",
            f"control soak latched regressions (false positives): "
            f"{ctl_regressions}",
        )
    if not ctl_atts:
        return Result(
            scn.name, "failed",
            "control leg produced no critical-path attributions (no "
            "per-step series reached the lighthouse?)",
        )
    # size the injected shift off the measured steady wall (post-warm-up
    # commits only — the first ~8 steps are jit compiles): 1x the median
    # step time is a doubling, which the relative-lambda PH latches in a
    # handful of samples at ANY load level, where the spec's fixed
    # 150 ms floor only clears the gate on an idle box
    ctl_walls = sorted(
        a["wall_s"] for a in ctl_atts
        if a.get("wall_s") and a.get("step") is not None and a["step"] >= 8
    )
    delay_ms = 150
    if ctl_walls:
        delay_ms = max(150, int(1000 * ctl_walls[len(ctl_walls) // 2]))

    # ---- injected leg -------------------------------------------------
    err, events, atts, fired, onset_ts, wd = leg(
        "injected", inject=True, delay_ms=delay_ms
    )
    if err:
        return Result(scn.name, "failed", err, fired=fired)
    if fired == 0:
        return Result(
            scn.name, "failed",
            "no injection evidence recorded — the delay never fired",
        )
    regressions = [e for e in events if e["event"] == "perf_regression"]
    if not regressions:
        return Result(
            scn.name, "failed",
            f"sentinel latched nothing across {len(atts)} attributed "
            f"steps (events: {events})", fired=fired,
        )
    wrong = [
        e for e in regressions if not e["replica"].startswith(victim_id)
    ]
    if wrong:
        return Result(
            scn.name, "failed",
            f"sentinel named non-injected replica(s): {wrong}",
            fired=fired,
        )
    seen_series = [e["series"] for e in regressions]
    if len(seen_series) != len(set(seen_series)):
        return Result(
            scn.name, "failed",
            f"a series latched more than once in one episode: "
            f"{regressions}", fired=fired,
        )
    onset_step = onset_step_from_trail(wd, onset_ts)
    first_latch_step = min(e["step"] for e in regressions)
    if onset_step >= 0 and first_latch_step > onset_step + K_COMMITS:
        return Result(
            scn.name, "failed",
            f"first latch at step {first_latch_step}, more than "
            f"{K_COMMITS} commits after onset step {onset_step}",
            fired=fired,
        )
    # post-onset critical path: >=80% of blamed seconds on the victim
    post = [
        a for a in atts
        if a.get("step") is not None
        and (onset_step < 0 or a["step"] >= onset_step)
        and a.get("blame_s", 0) > 0
    ]
    blame_by: Dict[str, float] = {}
    for a in post:
        blame_by[a["gating"]] = blame_by.get(a["gating"], 0.0) + a["blame_s"]
    total_blame = sum(blame_by.values())
    victim_blame = sum(
        s for r, s in blame_by.items() if r.startswith(victim_id)
    )
    if total_blame <= 0 or victim_blame < 0.8 * total_blame:
        return Result(
            scn.name, "failed",
            f"post-onset blame not >=80% on {victim_id}: {blame_by} "
            f"(onset step {onset_step})", fired=fired,
        )
    # what-if: removing the gater's excess should recover the measured
    # no-injection rate — the SAME leg's steady pre-onset window (skip
    # the 30-40x jit warm-up steps), so estimator and reference share
    # the box's load (the Coz-style estimate the attribution exists to
    # produce)
    pre_walls = [
        a["wall_s"] for a in atts
        if a.get("wall_s") and a.get("step") is not None
        and 8 <= a["step"] < (onset_step if onset_step >= 0 else 10 ** 9)
    ]
    post_whatif = [a["whatif_wall_s"] for a in post if a.get("whatif_wall_s")]
    whatif_sps = (
        len(post_whatif) / sum(post_whatif) if post_whatif else 0.0
    )
    pre_sps = len(pre_walls) / sum(pre_walls) if pre_walls else 0.0
    if not whatif_sps or not pre_sps or abs(whatif_sps / pre_sps - 1.0) > 0.25:
        return Result(
            scn.name, "failed",
            f"what-if estimate {whatif_sps:.3f} steps/s not within 25% "
            f"of the pre-onset no-injection rate {pre_sps:.3f} steps/s",
            fired=fired,
        )

    # ---- persistence leg: kill/respawn, full history survives ---------
    p_err = _persistence_leg(workdir, leg_steps, timeout_s)
    if p_err:
        return Result(scn.name, "failed", p_err, fired=fired)

    return Result(
        scn.name, "passed",
        f"latched {sorted(set(seen_series))} on {victim_id}* at step "
        f"{first_latch_step} (onset {onset_step}); post-onset blame "
        f"{victim_blame / total_blame:.0%}; what-if {whatif_sps:.2f} vs "
        f"pre-onset {pre_sps:.2f} steps/s ({len(ctl_atts)}-step control "
        f"soak: zero latches); kill/respawn history served",
        fired=fired,
    )


def _persistence_leg(
    workdir: str, steps: int, timeout_s: float
) -> Optional[str]:
    """Kill group 1 mid-run, respawn it, and assert /timeseries.json
    still serves BOTH incarnations' rings (the dead uuid's pre-kill
    history + the respawn's post-heal samples). Returns an error string
    or None."""
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.telemetry.timeseries import poll_timeseries

    wd = os.path.join(workdir, "persistence")
    os.makedirs(wd, exist_ok=True)
    evidence_dir = os.path.join(wd, "evidence")
    os.makedirs(evidence_dir, exist_ok=True)
    with open(os.path.join(wd, "corpus.bin"), "wb") as f:
        f.write(bytes(range(256)) * 24)
    kill_schedule = json.dumps({
        "seed": 8,
        "rules": [{
            "site": "collective.issue", "match": "allreduce",
            "nth": 6, "action": "kill", "sig": 9,
        }],
    })
    # process-global store: clear the previous legs' rings (see leg())
    from torchft_tpu import _native

    _native.tsdb_reset()
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    addr = lighthouse.address().split("//", 1)[-1]
    procs = {
        0: _spawn(0, addr, wd, steps, {}),
        1: _spawn(1, addr, wd, steps,
                  {"TORCHFT_FAULT_SCHEDULE": kill_schedule}),
    }
    respawned = False
    deadline = time.monotonic() + timeout_s
    err: Optional[str] = None
    try:
        while True:
            for gid, p in list(procs.items()):
                if p.poll() is None or p.returncode == 0:
                    continue
                kills = [
                    r for r in read_evidence(evidence_dir)
                    if r.get("action") == "kill" and r.get("pid") == p.pid
                ]
                if kills and not respawned:
                    respawned = True
                    procs[gid] = _spawn(gid, addr, wd, steps, {})
                else:
                    err = (
                        f"persistence: g{gid} rc={p.returncode} "
                        f"unexplained; log tail: "
                        f"{_read_log(wd, gid)[-800:]}"
                    )
                    break
            if err or all(p.poll() is not None for p in procs.values()):
                break
            if time.monotonic() > deadline:
                err = f"persistence: timeout after {timeout_s}s"
                break
            time.sleep(0.5)
        if err is None and not respawned:
            err = "persistence: the scheduled kill never fired"
        if err is None:
            # the whole point: query the lighthouse BEFORE shutdown —
            # the dead incarnation's ring must still be there, next to
            # the respawn's
            reply = poll_timeseries(lighthouse.address())
            if not reply:
                err = "persistence: /timeseries.json unreachable"
            else:
                rings = {
                    rid: body for rid, body in reply["replicas"].items()
                    if "local_s" in body
                }
                g1 = [r for r in rings if r.startswith("train_bytes_1")]
                if len(g1) < 2:
                    err = (
                        f"persistence: expected BOTH g1 incarnations' "
                        f"rings (dead + respawn), got {sorted(rings)}"
                    )
                else:
                    # dead incarnation: pre-kill history retained; some
                    # ring reaches the end of the run
                    counts = {
                        r: len(rings[r]["local_s"]["samples"]) for r in g1
                    }
                    max_step = max(
                        s[1]
                        for body in rings.values()
                        for s in body["local_s"]["samples"]
                    )
                    if min(counts.values()) < 1:
                        err = (
                            f"persistence: an incarnation's ring is "
                            f"empty: {counts}"
                        )
                    elif max_step < steps - 4:
                        err = (
                            f"persistence: history stops at step "
                            f"{max_step} of {steps}"
                        )
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        lighthouse.shutdown()
    if err is None:
        cs_err, _sums = _final_checksums(wd)
        if cs_err:
            err = f"persistence: {cs_err}"
    return err


def run_postmortem_scenario(
    scn: Scenario, workdir: str, steps: int = 16, timeout_s: float = 600.0,
    extra_env: Optional[Dict[str, str]] = None,
    worker_argv: Optional[List[str]] = None,
) -> Result:
    """The ``postmortem_kill_allreduce`` scenario (ISSUE 10): the
    standard mid-allreduce SIGKILL run, then the forensic assertion —
    ``telemetry.postmortem`` pointed at the crash-durable black boxes
    ALONE (not the logs, not the evidence files) must name the victim
    replica, its last in-flight op, and the quorum epoch it died in."""
    res = run_scenario(scn, workdir, steps=steps, timeout_s=timeout_s,
                       extra_env=extra_env, worker_argv=worker_argv)
    if res.status != "passed":
        return res
    from torchft_tpu.telemetry import postmortem

    bb_dir = os.path.join(workdir, "blackbox")
    report = postmortem.analyze(bb_dir)
    victim = report.get("victim") or ""
    # the killed group is gid 1; its replica_id is the example-chosen
    # prefix + a uuid4 suffix — a bare "pid:N" means the boxes never
    # carried replica attribution, which is itself a failure
    if not victim.startswith(("train_bytes_1", "san_worker_1")):
        return Result(
            scn.name, "failed",
            f"postmortem (black boxes alone) named victim {victim!r}, "
            f"expected the killed group 1 replica; report: "
            f"{postmortem.render_text(report)}",
            fired=res.fired, respawns=res.respawns, checksums=res.checksums,
        )
    op = report.get("victim_inflight_op") or {}
    if op.get("op") != "allreduce":
        return Result(
            scn.name, "failed",
            f"postmortem named in-flight op {op!r}, expected an "
            "allreduce (the victim died mid-ring)",
            fired=res.fired, respawns=res.respawns, checksums=res.checksums,
        )
    if not isinstance(report.get("victim_epoch"), int) \
            or report["victim_epoch"] < 0:
        return Result(
            scn.name, "failed",
            f"postmortem recovered no quorum epoch for the victim "
            f"({report.get('victim_epoch')!r})",
            fired=res.fired, respawns=res.respawns, checksums=res.checksums,
        )
    with open(os.path.join(workdir, "evidence", "postmortem.json"),
              "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, default=str)
    return Result(
        scn.name, "passed",
        f"black boxes alone named victim={victim} inflight="
        f"{op.get('op')} epoch={report['victim_epoch']}; "
        f"checksums {res.checksums[0]} == {res.checksums[1]}",
        fired=res.fired, respawns=res.respawns, checksums=res.checksums,
    )


def run_divergence_scenario(
    scn: Scenario, workdir: str, steps: int = 16, timeout_s: float = 600.0,
    extra_env: Optional[Dict[str, str]] = None,
    worker_argv: Optional[List[str]] = None,
) -> Result:
    """The ``corrupt_divergence`` scenario (ISSUE 10): three legs.

    **sentinel leg** — ``corrupt(frac)`` silently perturbs group 1's
    finished allreduce output once. Nothing errors, the corrupt average
    COMMITS (this is the PR 2 hole) — so final checksums legitimately
    diverge; the assertion is that the lighthouse's commit-time digest
    compare latched (`divergence_total >= 1`) and that a worker trail
    records ``divergence_detected`` within one commit of the
    ``fault_injected`` record.

    **fence leg** — same injection under ``TORCHFT_DIVERGENCE_FENCE=1``:
    the lighthouse arbitrates BEFORE the decision publishes, the corrupt
    commit is vetoed on every group, and final checksums must be finite
    and bit-identical (the corruption never entered committed state).

    **control leg** — equal-length soak, sentinel + fence armed, no
    injection: ``divergence_total`` must be exactly 0 — committed state
    is bit-identical by construction, so any latch here is a false
    positive."""
    import urllib.request

    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.telemetry.events import read_trail

    def leg(name: str, inject: bool, fence: bool):
        """Returns (error, lighthouse_status, trails, sums)."""
        wd = os.path.join(workdir, name)
        os.makedirs(wd, exist_ok=True)
        os.makedirs(os.path.join(wd, "evidence"), exist_ok=True)
        with open(os.path.join(wd, "corpus.bin"), "wb") as f:
            f.write(bytes(range(256)) * 24)
        lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
        addr = lighthouse.address().split("//", 1)[-1]
        status: Dict = {}
        err: Optional[str] = None
        try:
            procs = {}
            for gid in (0, 1):
                env = dict(extra_env or {})
                env.update(_worker_env(scn, gid))
                if not inject:
                    env.pop("TORCHFT_FAULT_SCHEDULE", None)
                if fence:
                    env["TORCHFT_DIVERGENCE_FENCE"] = "1"
                procs[gid] = _spawn(gid, addr, wd, steps, env, worker_argv)
            deadline = time.monotonic() + timeout_s
            while True:
                done = {g: p.poll() for g, p in procs.items()}
                for gid, rc in done.items():
                    if rc is not None and rc != 0:
                        err = (f"{name}: g{gid} rc={rc}; log tail: "
                               f"{_read_log(wd, gid)[-1000:]}")
                if err or all(rc is not None for rc in done.values()):
                    break
                if time.monotonic() > deadline:
                    err = f"{name}: timeout after {timeout_s}s"
                    break
                time.sleep(0.5)
            # scrape the divergence latch BEFORE the lighthouse dies —
            # the counter lives in the coordinator, not the workers
            try:
                with urllib.request.urlopen(
                    f"http://{addr}/status.json", timeout=5
                ) as resp:
                    status = json.loads(resp.read().decode())
            except Exception as e:  # noqa: BLE001
                err = err or f"{name}: lighthouse scrape failed: {e}"
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            lighthouse.shutdown()
        trails = {
            gid: read_trail(os.path.join(wd, f"trail{gid}.jsonl"))
            for gid in (0, 1)
        }
        sums: List[str] = []
        for gid in (0, 1):
            m = re.findall(
                r"param_checksum=(-?[\d.]+|nan|inf)", _read_log(wd, gid)
            )
            sums.append(m[-1] if m else "")
        return err, status, trails, sums

    # -- sentinel leg: the corrupt average commits, the latch must fire
    err, status, trails, sums = leg("sentinel", inject=True, fence=False)
    if err:
        return Result(scn.name, "failed", err)
    if int(status.get("divergence_total", 0)) < 1:
        return Result(
            scn.name, "failed",
            f"corrupt output committed but the sentinel never latched "
            f"(divergence_total={status.get('divergence_total')})",
        )
    all_events = [r for t in trails.values() for r in t]
    # the trail's fault_injected record carries no step (the plane is
    # step-agnostic), but its BLACK-BOX mirror is stamped with the
    # Manager's step context — read the injection's step coordinate
    # from the crash-durable ring, which is exactly what it is for
    from torchft_tpu.telemetry.postmortem import collect_boxes

    corrupt_steps = [
        r.get("st")
        for b in collect_boxes(os.path.join(workdir, "sentinel", "blackbox"))
        for r in b["records"]
        if r.get("k") == "fault_injected" and r.get("action") == "corrupt"
    ]
    injected_steps = [
        s for s in corrupt_steps if isinstance(s, int) and s >= 0
    ]
    detected = sorted(
        r.get("step", 10**9)
        for r in all_events
        if r.get("event") == "divergence_detected"
    )
    if not detected:
        return Result(
            scn.name, "failed",
            "lighthouse latched but no worker trail carries "
            "divergence_detected (reply flag never surfaced)",
        )
    # "within one commit": the injection fired on the 5th allreduce
    # (~step 4); the latch must be visible by the following commit
    corrupt_step = min(injected_steps) if injected_steps else None
    if corrupt_step is not None and detected[0] > corrupt_step + 1:
        return Result(
            scn.name, "failed",
            f"sentinel latched at step {detected[0]}, more than one "
            f"commit after the injection at step {corrupt_step}",
        )
    if any(s in ("nan", "inf", "") for s in sums):
        return Result(
            scn.name, "failed",
            f"sentinel leg produced non-finite/missing checksums {sums}",
        )

    # -- fence leg: the corrupt commit must abort; checksums identical
    err, status, trails, sums = leg("fence", inject=True, fence=True)
    if err:
        return Result(scn.name, "failed", err)
    if int(status.get("divergence_total", 0)) < 1:
        return Result(
            scn.name, "failed",
            f"fence leg: sentinel never latched "
            f"(divergence_total={status.get('divergence_total')})",
        )
    aborts = [
        r for t in trails.values() for r in t if r.get("event") == "abort"
    ]
    if not aborts:
        return Result(
            scn.name, "failed",
            "fence leg: divergence latched but no abort recorded — the "
            "fence did not veto the corrupt commit",
        )
    if any(s in ("nan", "inf", "") for s in sums) or sums[0] != sums[1]:
        return Result(
            scn.name, "failed",
            f"fence leg: checksums {sums} — the vetoed corruption still "
            "reached committed state",
        )

    # -- control leg: zero false positives (digests identical by
    # construction on every committed step)
    err, status, _trails, sums = leg("control", inject=False, fence=True)
    if err:
        return Result(scn.name, "failed", err)
    if int(status.get("divergence_total", 0)) != 0:
        return Result(
            scn.name, "failed",
            f"control soak FALSE POSITIVE: divergence_total="
            f"{status.get('divergence_total')} with no injection",
        )
    if any(s in ("nan", "inf", "") for s in sums) or sums[0] != sums[1]:
        return Result(
            scn.name, "failed",
            f"control leg checksums {sums}",
        )
    return Result(
        scn.name, "passed",
        f"sentinel latched at step {detected[0]} (corrupt at "
        f"{corrupt_step}); fence aborted with identical checksums "
        f"{sums[0]}; control soak clean",
    )


def check_conformance(workdir: str) -> Optional[str]:
    """Spec-conformance replay of a finished scenario's evidence
    (ISSUE 15): every trail and black box under ``workdir`` is replayed
    against the executable FT-protocol spec, and any illegal transition
    FAILS the scenario — every scenario doubles as a conformance proof.
    Returns the rendered findings (None = conformance-clean)."""
    try:
        from torchft_tpu.analysis.protocol import check_tree

        rep = check_tree(workdir)
    except Exception as e:  # noqa: BLE001 — a broken checker must be loud
        return f"conformance replay itself failed: {e}"
    if rep.ok:
        return None
    return rep.render()


def collect_postmortem(workdir: str, detail: str = "") -> Optional[str]:
    """Auto-forensics on scenario failure: merge the run's black boxes,
    trails and evidence into one postmortem report under the evidence
    dir. Returns the report path (None when nothing could be written) —
    best-effort by design, a broken postmortem must never mask the
    scenario's own failure."""
    try:
        from torchft_tpu.telemetry import postmortem

        evidence_dir = os.path.join(workdir, "evidence")
        os.makedirs(evidence_dir, exist_ok=True)
        logs = []
        for path in sorted(glob.glob(os.path.join(workdir, "g*.log"))):
            try:
                with open(path, errors="replace") as f:
                    logs.append(f.read()[-20000:])
            except OSError:
                pass
        report = postmortem.analyze(workdir, log_text="\n".join(logs))
        report["scenario_detail"] = detail
        out = os.path.join(evidence_dir, "postmortem.json")
        with open(out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, default=str)
        print(f"    postmortem ({report['classification']}): {out}")
        return out
    except Exception as e:  # noqa: BLE001 — forensics must not mask failures
        print(f"    postmortem collection failed: {e}")
        return None


# ---------------------------------------------------------------------------
# sanitizer mode
# ---------------------------------------------------------------------------


def _libsan_path(runtime: str) -> str:
    cxx = os.environ.get("CXX", "g++")
    name = f"lib{runtime}.so"
    out = subprocess.run(
        [cxx, "-print-file-name=" + name],
        capture_output=True, text=True, check=True,
    ).stdout.strip()
    if not out or out == name:
        raise RuntimeError(f"{name} not found (is gcc installed?)")
    return out


def build_sanitized(kind: str) -> str:
    """``make -C native <kind>``; returns the sanitized .so path."""
    subprocess.run(
        ["make", "-C", os.path.join(REPO, "native"), kind], check=True
    )
    lib = os.path.join(
        REPO, "torchft_tpu", "_native", f"libtftcore_{kind}.so"
    )
    assert os.path.exists(lib), lib
    return lib


def sanitize_env(outdir: str, kind: str) -> Dict[str, str]:
    lib = build_sanitized(kind)
    env = {
        "TORCHFT_NATIVE_LIB": lib,
        "LD_PRELOAD": _libsan_path(kind),
    }
    if kind == "asan":
        # leaks are expected from the interpreter itself; we hunt
        # corruption (use-after-free, overflow), not leaks
        env["ASAN_OPTIONS"] = (
            "detect_leaks=0:abort_on_error=1:handle_abort=1:"
            f"log_path={os.path.join(outdir, 'asan')}"
        )
    else:
        # exitcode=0: a report must not kill the worker mid-scenario (the
        # matrix's bit-identity invariant still has to be checked); the
        # gate is the log scan below. Only the native .so is instrumented
        # — the interpreter's own accesses are invisible to TSan, but its
        # pthread mutex/cond use IS intercepted via LD_PRELOAD, so
        # happens-before through the GIL and ctypes boundaries is tracked
        # and native-plane races attribute to instrumented frames.
        env["TSAN_OPTIONS"] = (
            "exitcode=0:report_thread_leaks=0:second_deadlock_stack=1:"
            f"log_path={os.path.join(outdir, 'tsan')}"
        )
    return env


_SAN_REPORT_MARKERS = (
    "ERROR: AddressSanitizer",
    "WARNING: ThreadSanitizer",
    "ERROR: ThreadSanitizer",
    "runtime error:",
)


def scan_san_reports(outdir: str, kind: str) -> List[str]:
    hits = []
    for path in sorted(glob.glob(os.path.join(outdir, f"{kind}.*"))):
        try:
            with open(path, errors="replace") as f:
                text = f.read()
        except OSError:
            continue
        if any(m in text for m in _SAN_REPORT_MARKERS):
            hits.append(path)
    return hits


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="faultinject-runner", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--scenario", action="append", default=None,
                    help="run only these scenarios (repeatable)")
    ap.add_argument("--quick", action="store_true",
                    help="short matrix: the quick-subset scenarios, "
                    "fewer steps")
    ap.add_argument("--sanitize", nargs="?", const="asan", default=None,
                    choices=("asan", "tsan"), metavar="{asan,tsan}",
                    help="rebuild the native plane under the named "
                    "sanitizer (default asan) and fail on any report")
    ap.add_argument("--compiled", nargs="?", const=COMPILED_DIR,
                    default=None, metavar="DIR",
                    help="also run the compiled-schedule descriptors "
                    "under DIR (default: the shipped faultinject/"
                    "compiled set from the model checker); with no "
                    "--scenario/--quick, runs ONLY those")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-scenario wall-clock cap (seconds)")
    ap.add_argument("--outdir", default=None,
                    help="working dir (default: a fresh temp dir)")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    args = ap.parse_args(argv)

    if args.list:
        for s in SCENARIOS:
            print(f"{s.name:28s} {'[quick] ' if s.quick else '':8s}"
                  f"{s.description}")
        return 0

    outdir = args.outdir or tempfile.mkdtemp(prefix="tft_faultmatrix_")
    os.makedirs(outdir, exist_ok=True)
    steps = args.steps or (10 if (args.quick or args.sanitize) else 16)

    compiled = (
        load_compiled_scenarios(args.compiled) if args.compiled else []
    )
    selected = SCENARIOS
    if args.scenario:
        by_name = {s.name: s for s in SCENARIOS}
        by_name.update({s.name: s for s in compiled})
        unknown = [n for n in args.scenario if n not in by_name]
        if unknown:
            ap.error(f"unknown scenario(s) {unknown}; see --list")
        selected = [by_name[n] for n in args.scenario]
    elif args.quick or args.sanitize:
        selected = [s for s in SCENARIOS if s.quick] + compiled
    elif args.compiled:
        # a bare --compiled runs exactly the compiled tier
        selected = compiled

    extra_env: Optional[Dict[str, str]] = None
    worker_argv: Optional[List[str]] = None
    if args.sanitize:
        # worker-only env: the runner process must NOT load the ASan core
        # (its in-process lighthouse dlopen would abort without the
        # preloaded runtime), and the workers must be jax-free (ASan's
        # __cxa_throw interceptor CHECK-fails in jaxlib's jit tracing) —
        # the numpy worker drives the identical native-plane/RPC/heal
        # path, which is where every corruption suspect lives
        extra_env = sanitize_env(outdir, args.sanitize)
        worker_argv = [
            sys.executable, "-m", "torchft_tpu.faultinject._san_worker"
        ]
        print(f"sanitizer armed ({args.sanitize}): "
              f"{extra_env['TORCHFT_NATIVE_LIB']} (jax-free numpy worker)")

    results: List[Result] = []
    for scn in selected:
        wd = os.path.join(outdir, scn.name)
        shutil.rmtree(wd, ignore_errors=True)
        print(f"--- {scn.name}: {scn.description}")
        t0 = time.monotonic()
        if scn.name == "straggler_group":
            if args.sanitize:
                # the custom runner spawns plain jax workers and does not
                # thread the sanitizer env/argv — claiming a sanitized
                # PASS here would be a lie, so refuse loudly
                ap.error(
                    "straggler_group is not wired for --sanitize (the "
                    "detection loop needs the jax trainer's anatomy "
                    "piggyback); run it unsanitized"
                )
            # custom two-leg runner (injected + control soak) with the
            # fleet detector hosted by the runner process itself
            res = run_straggler_scenario(
                scn, wd, steps=steps, timeout_s=args.timeout
            )
        elif scn.name == "diagnose_straggler":
            # custom two-leg runner (injected + control soak): detection
            # fires IN the victim (it hosts its own FleetMonitor) so the
            # capture path is the production one. Sanitize-capable: same
            # legs with the jax-free worker + the profiler at 97 Hz.
            res = run_diagnose_scenario(
                scn, wd, steps=steps, timeout_s=args.timeout,
                extra_env=extra_env, worker_argv=worker_argv,
            )
        elif scn.name == "perf_regression":
            if args.sanitize:
                ap.error(
                    "perf_regression is not wired for --sanitize (the "
                    "detection loop needs the jax trainer's time-series "
                    "piggyback); run it unsanitized"
                )
            # custom three-leg runner (control + injected onset +
            # kill/respawn persistence) with the regression sentinel and
            # critical-path monitors hosted by the runner process
            res = run_perf_regression_scenario(
                scn, wd, steps=steps, timeout_s=args.timeout
            )
        elif scn.name == "stripe_heal_peer_death":
            # custom 3-group runner: a striped heal needs two sources so
            # one can die mid-serve (sanitize-capable — same worker argv)
            res = run_stripe_heal_scenario(
                scn, wd, steps=steps, timeout_s=args.timeout,
                extra_env=extra_env, worker_argv=worker_argv,
            )
        elif scn.name == "postmortem_kill_allreduce":
            # standard kill run + the forensic assertion on the black
            # boxes alone (sanitize-capable — same worker argv)
            res = run_postmortem_scenario(
                scn, wd, steps=steps, timeout_s=args.timeout,
                extra_env=extra_env, worker_argv=worker_argv,
            )
        elif scn.name == "corrupt_divergence":
            # three-leg sentinel/fence/control runner (sanitize-capable)
            res = run_divergence_scenario(
                scn, wd, steps=steps, timeout_s=args.timeout,
                extra_env=extra_env, worker_argv=worker_argv,
            )
        else:
            res = run_scenario(scn, wd, steps=steps, timeout_s=args.timeout,
                               extra_env=extra_env, worker_argv=worker_argv)
        if res.status == "passed":
            # conformance gate (ISSUE 15): a scenario that passed its
            # own assertions must ALSO have produced only protocol-legal
            # lifecycle transitions — an illegal one fails it from now on
            conf = check_conformance(wd)
            if conf is not None:
                res = Result(
                    res.scenario, "failed",
                    f"spec-conformance violation: {conf}",
                    fired=res.fired, respawns=res.respawns,
                    checksums=res.checksums,
                )
        res_s = time.monotonic() - t0
        print(
            f"    {res.status.upper()} in {res_s:.1f}s "
            f"(fired={res.fired} respawns={res.respawns}) {res.detail}"
        )
        if res.status != "passed":
            # auto-forensics (ISSUE 10): a failing or environmental run
            # leaves a merged postmortem report next to its evidence, so
            # triage starts from a reconstructed timeline instead of raw
            # logs — environmental skips become triaged artifacts
            collect_postmortem(wd, detail=res.detail)
        results.append(res)

    report = {
        "steps": steps,
        "sanitize": args.sanitize or False,
        "results": [r.__dict__ for r in results],
    }
    failed = [r for r in results if r.status == "failed"]
    if args.sanitize:
        hits = scan_san_reports(outdir, args.sanitize)
        report["sanitizer_reports"] = hits
        if hits:
            print(f"{args.sanitize.upper()} REPORTS ({len(hits)}):")
            for h in hits:
                print(f"  {h}")
                with open(h, errors="replace") as f:
                    head = f.read(2000)
                print("    " + "\n    ".join(head.splitlines()[:25]))
            failed.append(Result("sanitizer", "failed",
                                 f"{len(hits)} {args.sanitize} report(s)"))
        else:
            print("sanitizer: no reports")
    with open(os.path.join(outdir, "faultmatrix.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(f"report: {os.path.join(outdir, 'faultmatrix.json')}")

    env_skips = [r for r in results if r.status == "environmental"]
    if env_skips:
        print(f"environmental (documented corruption, recorded): "
              f"{[r.scenario for r in env_skips]}")
    if failed:
        print(f"FAILED: {[r.scenario for r in failed]}")
        return 1
    print("fault matrix clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
