"""Deterministic fault-injection plane for the FT runtime.

``faultinject.core`` is the site registry + seeded schedule engine (the
Python layers' injection points consult it through
:func:`~torchft_tpu.faultinject.core.fault_point`);
``faultinject.runner`` drives the 2-group example trainer through a
scenario matrix (mid-op kills per data plane, torn CMA pulls, delayed
commit votes, checkpoint-serve death) and asserts the end-to-end safety
invariant — no committed step may carry corrupt averages. See
``docs/fault_injection.md``.
"""

from torchft_tpu.faultinject.core import (
    ACTIONS,
    ENV_EVIDENCE_DIR,
    ENV_SCHEDULE,
    SITES,
    FaultPlane,
    Injection,
    active,
    configure,
    fault_point,
    read_evidence,
)

__all__ = [
    "ACTIONS",
    "ENV_EVIDENCE_DIR",
    "ENV_SCHEDULE",
    "SITES",
    "FaultPlane",
    "Injection",
    "active",
    "configure",
    "fault_point",
    "read_evidence",
]
