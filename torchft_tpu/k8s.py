"""Kubernetes deployment artifact — the TorchX component analogue.

Reference: torchft/torchx.py:11-76 maps N replica-group roles (each under
``torchrun --max_restarts=10``) onto a TorchX scheduler. The TPU-native
deployment target is GKE: this module renders plain core-v1/batch-v1
manifests (no CRDs required; the shapes line up 1:1 with a JobSet if you
prefer one) that materialize the launcher's documented env contract
(launcher.py module docstring) for ``N groups × M hosts``:

* a **lighthouse** Deployment + Service (the global quorum seed);
* per replica group: a headless Service + an **Indexed Job** of M pods.
  Pod index 0 hosts the group's KV store and jax coordinator (via
  ``launcher --k8s-worker``); every pod derives ``RANK`` from the Job
  completion index and finds its peers through stable DNS
  (``{job}-{index}.{headless-svc}``).

Restart semantics: the Job's ``backoffLimit`` plays launcher
``--max-restarts``; pods of a group share fate through the FT runtime
itself (a dead rank wedges the group's quorum participation, the
lighthouse evicts it, survivors re-quorum — the same flow the launcher
drives locally).

Render with::

    python -m torchft_tpu.launcher --emit-k8s --groups 4 --nproc 8 \\
        --image gcr.io/me/trainer:latest -- python examples/train_hsdp.py
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

__all__ = ["emit_manifests", "LIGHTHOUSE_PORT", "STORE_PORT", "COORD_PORT"]

LIGHTHOUSE_PORT = 29510
STORE_PORT = 29511
COORD_PORT = 29512


def _indent(block: str, n: int) -> str:
    pad = " " * n
    return "\n".join(pad + line if line else line for line in block.splitlines())


def _q(s: str) -> str:
    """YAML-safe string literal: JSON string escaping is a subset of YAML
    double-quoted scalars (repr() is NOT — backslashes/mixed quotes break)."""
    return json.dumps(s)


def _env_yaml(env: List[tuple]) -> str:
    out = []
    for name, value in env:
        if isinstance(value, dict):  # fieldRef
            out.append(
                f"- name: {name}\n"
                f"  valueFrom:\n"
                f"    fieldRef:\n"
                f"      fieldPath: {_q(value['fieldPath'])}"
            )
        else:
            out.append(f"- name: {name}\n  value: {_q(value)}")
    return "\n".join(out)


def emit_manifests(
    cmd: Sequence[str],
    *,
    name: str = "torchft",
    image: str = "IMAGE",
    num_groups: int = 2,
    nproc: int = 1,
    min_replicas: Optional[int] = None,
    max_restarts: int = 10,
    namespace: str = "default",
    tpu_accelerator: Optional[str] = None,
    tpu_topology: Optional[str] = None,
) -> str:
    """Render the full multi-document YAML for N groups × M hosts."""
    min_needed = min_replicas or num_groups
    docs: List[str] = []

    # -- lighthouse --------------------------------------------------------
    docs.append(
        f"""apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}-lighthouse
  namespace: {namespace}
  labels: {{app: {name}-lighthouse}}
spec:
  replicas: 1
  selector:
    matchLabels: {{app: {name}-lighthouse}}
  template:
    metadata:
      labels: {{app: {name}-lighthouse}}
    spec:
      containers:
      - name: lighthouse
        image: {image}
        command: ["python", "-m", "torchft_tpu.lighthouse"]
        args: ["--bind", "[::]:{LIGHTHOUSE_PORT}", "--min_replicas", "{min_needed}"]
        ports:
        - containerPort: {LIGHTHOUSE_PORT}"""
    )
    docs.append(
        f"""apiVersion: v1
kind: Service
metadata:
  name: {name}-lighthouse
  namespace: {namespace}
spec:
  selector: {{app: {name}-lighthouse}}
  ports:
  - port: {LIGHTHOUSE_PORT}
    targetPort: {LIGHTHOUSE_PORT}"""
    )

    # -- replica groups ----------------------------------------------------
    worker_cmd = [
        "python",
        "-m",
        "torchft_tpu.launcher",
        "--k8s-worker",
        "--",
        *cmd,
    ]
    # exec-form command: no shell, tokens rendered verbatim (JSON-escaped —
    # valid YAML double-quoted scalars for any token content)
    args_yaml = ", ".join(_q(a) for a in worker_cmd)
    for gid in range(num_groups):
        job = f"{name}-g{gid}"
        docs.append(
            f"""apiVersion: v1
kind: Service
metadata:
  name: {job}
  namespace: {namespace}
spec:
  clusterIP: None  # headless: stable {job}-{{index}}.{job} pod DNS
  selector: {{job-name: {job}}}
  ports:
  - name: store
    port: {STORE_PORT}
  - name: coord
    port: {COORD_PORT}"""
        )
        env = [
            ("TORCHFT_LIGHTHOUSE", f"{name}-lighthouse:{LIGHTHOUSE_PORT}"),
            ("REPLICA_GROUP_ID", str(gid)),
            ("NUM_REPLICA_GROUPS", str(num_groups)),
            ("WORLD_SIZE", str(nproc)),
            (
                "RANK",
                {
                    "fieldPath": (
                        "metadata.annotations"
                        "['batch.kubernetes.io/job-completion-index']"
                    )
                },
            ),
            # index-0 pod's stable DNS: hosts the group store + coordinator
            ("TORCHFT_GROUP_HOST0", f"{job}-0.{job}"),
        ]
        tpu_lines = ""
        if tpu_accelerator:
            topo = (
                f"\n        cloud.google.com/gke-tpu-topology: {tpu_topology}"
                if tpu_topology
                else ""
            )
            tpu_lines = f"""
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {tpu_accelerator}{topo}"""
        docs.append(
            f"""apiVersion: batch/v1
kind: Job
metadata:
  name: {job}
  namespace: {namespace}
spec:
  completionMode: Indexed
  completions: {nproc}
  parallelism: {nproc}
  backoffLimit: {max_restarts * max(1, nproc)}
  template:
    metadata:
      labels: {{job-name: {job}}}
    spec:
      subdomain: {job}
      restartPolicy: OnFailure{tpu_lines}
      containers:
      - name: trainer
        image: {image}
        command: [{args_yaml}]
        env:
{_indent(_env_yaml(env), 8)}
        ports:
        - containerPort: {STORE_PORT}
        - containerPort: {COORD_PORT}"""
        )
    return "\n---\n".join(docs) + "\n"
