"""Kubernetes deployment artifact — the TorchX component analogue.

Reference: torchft/torchx.py:11-76 maps N replica-group roles (each under
``torchrun --max_restarts=10``) onto a TorchX scheduler. The TPU-native
deployment target is GKE: this module renders plain core-v1/batch-v1
manifests (no CRDs required; the shapes line up 1:1 with a JobSet if you
prefer one) that materialize the launcher's documented env contract
(launcher.py module docstring) for ``N groups × M hosts``:

* a **lighthouse** Deployment + Service (the global quorum seed);
* per replica group: a headless Service + an **Indexed Job** of M pods.
  Pod index 0 hosts the group's KV store and jax coordinator (via
  ``launcher --k8s-worker``); every pod derives ``RANK`` from the Job
  completion index and finds its peers through stable DNS
  (``{job}-{index}.{headless-svc}``).

Restart semantics: the Job's ``backoffLimit`` plays launcher
``--max-restarts``; pods of a group share fate through the FT runtime
itself (a dead rank wedges the group's quorum participation, the
lighthouse evicts it, survivors re-quorum — the same flow the launcher
drives locally).

Render with::

    python -m torchft_tpu.launcher --emit-k8s --groups 4 --nproc 8 \\
        --image gcr.io/me/trainer:latest -- python examples/train_hsdp.py

Runnable workflow (round-5; the ``torchx run`` analogue — shells out to
``kubectl``, which owns auth/context exactly as TorchX defers to its
scheduler):

    # render + submit
    python -m torchft_tpu.launcher --emit-k8s ... -- python train.py \\
        | kubectl apply -f -
    # or in one step, plus status/teardown:
    python -m torchft_tpu.launcher --k8s-apply ... -- python train.py
    python -m torchft_tpu.launcher --k8s-status --name torchft
    python -m torchft_tpu.launcher --k8s-down --name torchft

Every emitted object carries the ``torchft-session: {name}`` label;
status and teardown select on it.
"""

from __future__ import annotations

import json
import subprocess
from typing import Dict, List, Optional, Sequence

__all__ = [
    "emit_manifests",
    "submit",
    "status",
    "teardown",
    "LIGHTHOUSE_PORT",
    "STORE_PORT",
    "COORD_PORT",
]

# selector label stamped on every emitted object: status/teardown key
SESSION_LABEL = "torchft-session"

LIGHTHOUSE_PORT = 29510
STORE_PORT = 29511
COORD_PORT = 29512


def _indent(block: str, n: int) -> str:
    pad = " " * n
    return "\n".join(pad + line if line else line for line in block.splitlines())


def _q(s: str) -> str:
    """YAML-safe string literal: JSON string escaping is a subset of YAML
    double-quoted scalars (repr() is NOT — backslashes/mixed quotes break)."""
    return json.dumps(s)


def _env_yaml(env: List[tuple]) -> str:
    out = []
    for name, value in env:
        if isinstance(value, dict):  # fieldRef
            out.append(
                f"- name: {name}\n"
                f"  valueFrom:\n"
                f"    fieldRef:\n"
                f"      fieldPath: {_q(value['fieldPath'])}"
            )
        else:
            out.append(f"- name: {name}\n  value: {_q(value)}")
    return "\n".join(out)


def emit_manifests(
    cmd: Sequence[str],
    *,
    name: str = "torchft",
    image: str = "IMAGE",
    num_groups: int = 2,
    nproc: int = 1,
    min_replicas: Optional[int] = None,
    max_restarts: int = 10,
    namespace: str = "default",
    tpu_accelerator: Optional[str] = None,
    tpu_topology: Optional[str] = None,
) -> str:
    """Render the full multi-document YAML for N groups × M hosts."""
    min_needed = min_replicas or num_groups
    docs: List[str] = []

    # -- lighthouse --------------------------------------------------------
    docs.append(
        f"""apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}-lighthouse
  namespace: {namespace}
  labels: {{app: {name}-lighthouse, {SESSION_LABEL}: {name}}}
spec:
  replicas: 1
  selector:
    matchLabels: {{app: {name}-lighthouse}}
  template:
    metadata:
      labels: {{app: {name}-lighthouse}}
    spec:
      containers:
      - name: lighthouse
        image: {image}
        command: ["python", "-m", "torchft_tpu.lighthouse"]
        args: ["--bind", "[::]:{LIGHTHOUSE_PORT}", "--min_replicas", "{min_needed}"]
        ports:
        - containerPort: {LIGHTHOUSE_PORT}"""
    )
    docs.append(
        f"""apiVersion: v1
kind: Service
metadata:
  name: {name}-lighthouse
  namespace: {namespace}
  labels: {{{SESSION_LABEL}: {name}}}
spec:
  selector: {{app: {name}-lighthouse}}
  ports:
  - port: {LIGHTHOUSE_PORT}
    targetPort: {LIGHTHOUSE_PORT}"""
    )

    # -- replica groups ----------------------------------------------------
    worker_cmd = [
        "python",
        "-m",
        "torchft_tpu.launcher",
        "--k8s-worker",
        "--",
        *cmd,
    ]
    # exec-form command: no shell, tokens rendered verbatim (JSON-escaped —
    # valid YAML double-quoted scalars for any token content)
    args_yaml = ", ".join(_q(a) for a in worker_cmd)
    for gid in range(num_groups):
        job = f"{name}-g{gid}"
        docs.append(
            f"""apiVersion: v1
kind: Service
metadata:
  name: {job}
  namespace: {namespace}
  labels: {{{SESSION_LABEL}: {name}}}
spec:
  clusterIP: None  # headless: stable {job}-{{index}}.{job} pod DNS
  selector: {{job-name: {job}}}
  ports:
  - name: store
    port: {STORE_PORT}
  - name: coord
    port: {COORD_PORT}"""
        )
        env = [
            ("TORCHFT_LIGHTHOUSE", f"{name}-lighthouse:{LIGHTHOUSE_PORT}"),
            ("REPLICA_GROUP_ID", str(gid)),
            ("NUM_REPLICA_GROUPS", str(num_groups)),
            ("WORLD_SIZE", str(nproc)),
            (
                "RANK",
                {
                    "fieldPath": (
                        "metadata.annotations"
                        "['batch.kubernetes.io/job-completion-index']"
                    )
                },
            ),
            # index-0 pod's stable DNS: hosts the group store + coordinator
            ("TORCHFT_GROUP_HOST0", f"{job}-0.{job}"),
        ]
        tpu_lines = ""
        if tpu_accelerator:
            topo = (
                f"\n        cloud.google.com/gke-tpu-topology: {tpu_topology}"
                if tpu_topology
                else ""
            )
            tpu_lines = f"""
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {tpu_accelerator}{topo}"""
        docs.append(
            f"""apiVersion: batch/v1
kind: Job
metadata:
  name: {job}
  namespace: {namespace}
  labels: {{{SESSION_LABEL}: {name}}}
spec:
  completionMode: Indexed
  completions: {nproc}
  parallelism: {nproc}
  backoffLimit: {max_restarts * max(1, nproc)}
  template:
    metadata:
      labels: {{job-name: {job}}}
    spec:
      subdomain: {job}
      restartPolicy: OnFailure{tpu_lines}
      containers:
      - name: trainer
        image: {image}
        command: [{args_yaml}]
        env:
{_indent(_env_yaml(env), 8)}
        ports:
        - containerPort: {STORE_PORT}
        - containerPort: {COORD_PORT}"""
        )
    return "\n---\n".join(docs) + "\n"


# ---------------------------------------------------------------------------
# runnable workflow (round-5 review missing #1): submit / status / teardown
# ---------------------------------------------------------------------------


def submit(
    manifests: str, *, namespace: str = "default", kubectl: str = "kubectl"
) -> None:
    """``kubectl apply`` the rendered manifests (stdin — nothing touches
    disk). Raises CalledProcessError on a rejected apply."""
    subprocess.run(
        [kubectl, "apply", "-n", namespace, "-f", "-"],
        input=manifests.encode(),
        check=True,
    )


def status(
    name: str, *, namespace: str = "default", kubectl: str = "kubectl"
) -> Dict[str, Dict[str, Dict[str, int]]]:
    """Session status by the ``torchft-session`` label: per-Job
    active/succeeded/failed pod counts + lighthouse availability."""
    proc = subprocess.run(
        [
            kubectl, "get", "jobs,deployments", "-n", namespace,
            "-l", f"{SESSION_LABEL}={name}", "-o", "json",
        ],
        capture_output=True,
    )
    if proc.returncode != 0:
        # surface kubectl's own diagnostic (bad context, missing ns, ...)
        raise RuntimeError(
            f"kubectl get failed (rc={proc.returncode}): "
            f"{proc.stderr.decode().strip()}"
        )
    out = proc.stdout
    res: Dict[str, Dict[str, Dict[str, int]]] = {"jobs": {}, "lighthouse": {}}
    for item in json.loads(out).get("items", []):
        kind = item.get("kind", "")
        iname = item.get("metadata", {}).get("name", "?")
        st = item.get("status", {}) or {}
        if kind == "Job":
            res["jobs"][iname] = {
                "active": int(st.get("active") or 0),
                "succeeded": int(st.get("succeeded") or 0),
                "failed": int(st.get("failed") or 0),
            }
        elif kind == "Deployment":
            res["lighthouse"][iname] = {
                "available": int(st.get("availableReplicas") or 0),
            }
    return res


def teardown(
    name: str, *, namespace: str = "default", kubectl: str = "kubectl"
) -> None:
    """Delete every object of the session (label-selected)."""
    subprocess.run(
        [
            kubectl, "delete", "jobs,services,deployments",
            "-n", namespace, "-l", f"{SESSION_LABEL}={name}",
            "--ignore-not-found",
        ],
        check=True,
    )
