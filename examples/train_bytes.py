"""Fault-tolerant byte-level LM on a real on-disk dataset.

The reference's flagship example trains CIFAR-10 from disk with a stateful
dataloader whose position survives restarts (train_ddp.py:34-80 + its
torchdata StatefulDataLoader use at :57-61). The TPU-native analogue: a
byte-level transformer LM over a real corpus file, with the
DistributedSampler's (epoch, position) derived from the *committed step
count* — the one clock every replica group provably agrees on — so

* a killed + restarted group resumes exactly where its last committed
  step left off (no sample double-trained, none skipped),
* groups can never desync epochs (the round-robin partition across
  groups stays disjoint through kill/heal/resume),
* a failed commit retries the SAME batch (the step didn't advance).

Each group appends one JSONL line per committed step to TRACE_PATH
recording the exact sample indices it trained on — the resume-correctness
proof harness (tests/test_data_example.py) kills a group mid-epoch,
restarts it, and replays the trace against an oracle sampler.

Env (launcher contract, see torchft_tpu/launcher.py):

    TORCHFT_LIGHTHOUSE  REPLICA_GROUP_ID  NUM_REPLICA_GROUPS  STEPS
    DATA_PATH    corpus file (built from this repo's own sources if absent)
    TRACE_PATH   committed-step JSONL (optional)
    CKPT_DIR / CKPT_EVERY   periodic disk checkpoints (optional)

Run::

    python -m torchft_tpu.launcher --groups 2 -- python examples/train_bytes.py
"""

import glob
import json
import logging
import os
import sys
from datetime import timedelta

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from torchft_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()
import jax
import jax.numpy as jnp
import optax

from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.data import DistributedSampler, step_indices as batch_indices
from torchft_tpu.manager import Manager
from torchft_tpu.optim import ManagedOptimizer
from torchft_tpu.store import StoreServer

logging.basicConfig(
    level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
)
logger = logging.getLogger("train_bytes")

SEQ = 128


def ensure_corpus(path: str) -> bytes:
    """Real bytes from disk: the framework's own sources, deterministic
    for every group of the same checkout (the CIFAR-download analogue)."""
    if not os.path.exists(path):
        root = os.path.join(os.path.dirname(__file__), "..", "torchft_tpu")
        files = sorted(glob.glob(os.path.join(root, "**", "*.py"), recursive=True))
        blob = b"".join(open(f, "rb").read() for f in files)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)  # atomic: concurrent groups race safely
    with open(path, "rb") as f:
        return f.read()


def main() -> None:
    replica_group = int(os.environ.get("REPLICA_GROUP_ID", 0))
    num_groups = int(os.environ.get("NUM_REPLICA_GROUPS", 2))
    steps = int(os.environ.get("STEPS", 20))
    batch = int(os.environ.get("BATCH", 8))
    data_path = os.environ.get("DATA_PATH", "/tmp/torchft_tpu_corpus.bin")
    trace_path = os.environ.get("TRACE_PATH")
    ckpt_dir = os.environ.get("CKPT_DIR")
    ckpt_every = int(os.environ.get("CKPT_EVERY", 5))

    store_addr = os.environ.get("TORCHFT_STORE_ADDR")
    store = None
    if store_addr is None:
        store = StoreServer()
        store_addr = store.address()

    corpus = np.frombuffer(ensure_corpus(data_path), dtype=np.uint8)
    n_windows = (len(corpus) - 1) // SEQ
    windows = corpus[: n_windows * SEQ].reshape(n_windows, SEQ)
    logger.info("corpus: %d bytes, %d windows of %d", len(corpus), n_windows, SEQ)

    from torchft_tpu.models.transformer import TransformerConfig, loss_fn

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    cfg = TransformerConfig(
        vocab_size=256,
        d_model=128,
        n_layers=2,
        n_heads=4,
        head_dim=32,
        d_ff=352,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
    )

    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=30)),
        load_state_dict=None,  # wired by ManagedOptimizer.init
        state_dict=None,
        min_replica_size=min(2, num_groups),
        replica_id=f"train_bytes_{replica_group}",
        store_addr=store_addr,
        rank=0,
        world_size=1,
        timeout=timedelta(seconds=30),
    )

    from torchft_tpu.models.transformer import init_params

    opt = ManagedOptimizer(manager, optax.adam(1e-3))
    opt.init(init_params(jax.random.PRNGKey(0), cfg))
    sampler = DistributedSampler(
        n_windows,
        replica_group=replica_group,
        num_replica_groups=num_groups,
        shuffle=True,
        seed=0,
    )

    value_and_grad = jax.jit(
        jax.value_and_grad(lambda p, toks: loss_fn(p, toks, cfg, None))
    )

    ckpt = None
    if ckpt_dir:
        from torchft_tpu.checkpointing.disk import DiskCheckpointer

        ckpt = DiskCheckpointer(
            ckpt_dir,
            manager,
            state_dict=lambda: {"opt": opt.state_dict(), "sampler": sampler.state_dict()},
            load_state_dict=lambda s: (
                opt.load_state_dict(s["opt"]),
                sampler.load_state_dict(s["sampler"]),
            ),
            every=ckpt_every,
            tag=f"group{replica_group}",
        )
        ckpt.restore()

    trace = open(trace_path, "a", buffering=1) if trace_path else None
    import time

    try:
        prev_step = manager.current_step()
        while manager.current_step() < steps:
            step = manager.current_step()
            ids = batch_indices(sampler, step, batch)
            tokens = jnp.asarray(windows[ids], jnp.int32)

            opt.begin_step()
            loss, grads = value_and_grad(opt.params, tokens)
            opt.step(grads)

            committed = manager.current_step() > prev_step
            if committed and manager.is_participating() and trace is not None:
                trace.write(
                    json.dumps({"step": step, "ids": ids.tolist()}) + "\n"
                )
            if not committed:
                time.sleep(0.2)  # same batch retries: step didn't advance
            prev_step = manager.current_step()
            logger.info(
                "step=%d participants=%d loss=%.4f",
                manager.current_step(),
                manager.num_participants(),
                float(loss),
            )
            if ckpt is not None:
                ckpt.maybe_save()
        checksum = float(
            sum(
                float(np.asarray(l, dtype=np.float64).sum())
                for l in jax.tree_util.tree_leaves(opt.params)
            )
        )
        logger.info(
            "done: step=%d param_checksum=%.6f", manager.current_step(), checksum
        )
    finally:
        if trace is not None:
            trace.close()
        manager.shutdown(wait=False)
        if store is not None:
            store.shutdown()


if __name__ == "__main__":
    main()
