"""Fault-tolerant ResNet-18 CIFAR-10 DDP — the reference's flagship
real-data config (BASELINE.md: "ResNet-18 CIFAR-10 DDP with kill/rejoin";
reference train_ddp.py:34-80).

TPU-native differences from the torch original: the model is the pure-JAX
NHWC ResNet (models/resnet.py) with functional batch norm — running stats
are explicit state that rides the heal/disk-checkpoint state dict (torch
DDP likewise keeps BN stats local per replica); the dataloader position
derives from the committed step count (torchft_tpu.data.step_indices), so
kill/rejoin can never skip or double-train a sample.

The dataset is a CIFAR-10-shaped on-disk .npz: real CIFAR-10 when a copy
exists at DATA_PATH (zero-egress environments can't download it), else a
deterministic learnable stand-in with the same shapes/dtypes generated
once and shared by every group — either way the input pipeline (disk →
sampler shards → augment → device) is the real one.

Env: TORCHFT_LIGHTHOUSE, REPLICA_GROUP_ID, NUM_REPLICA_GROUPS, STEPS,
BATCH, DATA_PATH, TRACE_PATH, CKPT_DIR, CKPT_EVERY (as train_bytes.py).

Run::

    python -m torchft_tpu.launcher --groups 2 -- python examples/train_cifar.py
"""

import json
import logging
import os
import sys
from datetime import timedelta

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from torchft_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()
import jax
import jax.numpy as jnp
import optax

from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.data import DistributedSampler, step_indices
from torchft_tpu.ddp import allreduce_gradients
from torchft_tpu.manager import Manager
from torchft_tpu.store import StoreServer

logging.basicConfig(
    level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
)
logger = logging.getLogger("train_cifar")


def ensure_dataset(path: str, n: int = 2048):
    """Load (or deterministically create) a CIFAR-10-shaped dataset:
    images uint8 [N,32,32,3], labels uint8 [N]."""
    if not os.path.exists(path):
        rng = np.random.default_rng(0)
        labels = rng.integers(0, 10, n).astype(np.uint8)
        # class-dependent structure (a colored gradient per class) + noise:
        # learnable, so training loss demonstrably falls
        yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 31.0
        base = np.stack([xx, yy, 1.0 - xx], axis=-1)  # [32,32,3]
        phase = (labels.astype(np.float32) / 10.0)[:, None, None, None]
        imgs = 127.5 * (1.0 + np.sin(6.28 * (base[None] + phase)))
        imgs = imgs + rng.normal(0, 16.0, imgs.shape)
        imgs = np.clip(imgs, 0, 255).astype(np.uint8)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        np.savez(tmp, images=imgs, labels=labels)
        os.replace(tmp + ".npz", path)  # np.savez appends .npz
    with np.load(path) as z:
        return z["images"], z["labels"]


def augment(imgs: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Standard CIFAR augmentation on host: pad-4 random crop + hflip."""
    n = len(imgs)
    padded = np.pad(imgs, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    out = np.empty_like(imgs)
    offs = rng.integers(0, 9, (n, 2))
    flips = rng.random(n) < 0.5
    for i in range(n):
        dy, dx = offs[i]
        crop = padded[i, dy : dy + 32, dx : dx + 32]
        out[i] = crop[:, ::-1] if flips[i] else crop
    return out


def main() -> None:
    replica_group = int(os.environ.get("REPLICA_GROUP_ID", 0))
    num_groups = int(os.environ.get("NUM_REPLICA_GROUPS", 2))
    steps = int(os.environ.get("STEPS", 20))
    batch = int(os.environ.get("BATCH", 32))
    data_path = os.environ.get("DATA_PATH", "/tmp/torchft_tpu_cifar.npz")
    trace_path = os.environ.get("TRACE_PATH")
    ckpt_dir = os.environ.get("CKPT_DIR")
    ckpt_every = int(os.environ.get("CKPT_EVERY", 5))

    store_addr = os.environ.get("TORCHFT_STORE_ADDR")
    store = None
    if store_addr is None:
        store = StoreServer()
        store_addr = store.address()

    images, labels = ensure_dataset(data_path)
    logger.info("dataset: %d images %s", len(images), images.shape[1:])

    from torchft_tpu.models import resnet

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    cfg = resnet.ResNetConfig(dtype=jnp.bfloat16 if on_tpu else jnp.float32)

    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=30)),
        load_state_dict=None,  # wired below (params + opt + bn stats)
        state_dict=None,
        min_replica_size=min(2, num_groups),
        replica_id=f"train_cifar_{replica_group}",
        store_addr=store_addr,
        rank=0,
        world_size=1,
        timeout=timedelta(seconds=30),
    )

    params, bn_stats = resnet.init(jax.random.PRNGKey(0), cfg)
    tx = optax.sgd(0.05, momentum=0.9)
    opt_state = tx.init(params)

    # heal state: params + optimizer + BN running stats, all together
    state = {"params": params, "opt_state": opt_state, "bn": bn_stats}

    def load_state(s):
        state.update(s)

    manager.set_state_dict_fns(load_state, lambda: dict(state))

    sampler = DistributedSampler(
        len(images),
        replica_group=replica_group,
        num_replica_groups=num_groups,
        shuffle=True,
        seed=0,
    )

    @jax.jit
    def grads_fn(params, bn, x, y):
        (loss, new_bn), grads = jax.value_and_grad(
            lambda p: resnet.loss_fn(p, bn, x, y, cfg), has_aux=True
        )(params)
        return loss, grads, new_bn

    @jax.jit
    def apply_fn(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    ckpt = None
    if ckpt_dir:
        from torchft_tpu.checkpointing.disk import DiskCheckpointer

        ckpt = DiskCheckpointer(
            ckpt_dir,
            manager,
            state_dict=lambda: dict(state),
            load_state_dict=load_state,
            every=ckpt_every,
            tag=f"group{replica_group}",
        )
        ckpt.restore()

    trace = open(trace_path, "a", buffering=1) if trace_path else None
    aug_rng = np.random.default_rng(1000 + replica_group)
    import time

    try:
        while manager.current_step() < steps:
            step = manager.current_step()
            ids = step_indices(sampler, step, batch)
            x = augment(images[ids], aug_rng).astype(np.float32) / 255.0
            y = jnp.asarray(labels[ids], jnp.int32)

            manager.start_quorum()
            loss, grads, new_bn = grads_fn(
                state["params"], state["bn"], jnp.asarray(x), y
            )
            grads = allreduce_gradients(manager, grads)
            if manager.should_commit():
                state["params"], state["opt_state"] = apply_fn(
                    state["params"], state["opt_state"], grads
                )
                if manager.is_participating():
                    # participants only: on a heal step should_commit just
                    # restored the peer's accumulated BN stats into
                    # state["bn"] — new_bn here came from the PRE-heal
                    # forward and would clobber them
                    state["bn"] = new_bn
                    if trace is not None:
                        trace.write(
                            json.dumps({"step": step, "ids": ids.tolist()})
                            + "\n"
                        )
            else:
                time.sleep(0.2)  # same batch retries: step didn't advance
            logger.info(
                "step=%d participants=%d loss=%.4f",
                manager.current_step(),
                manager.num_participants(),
                float(loss),
            )
            if ckpt is not None:
                ckpt.maybe_save()
        checksum = float(
            sum(
                float(np.asarray(l, dtype=np.float64).sum())
                for l in jax.tree_util.tree_leaves(state["params"])
            )
        )
        logger.info(
            "done: step=%d param_checksum=%.6f", manager.current_step(), checksum
        )
    finally:
        if trace is not None:
            trace.close()
        manager.shutdown(wait=False)
        if store is not None:
            store.shutdown()


if __name__ == "__main__":
    main()
