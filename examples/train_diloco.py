"""DiLoCo training example — the BASELINE.md "DiLoCo 4 groups" config.

Communication-reduced fault-tolerant training (arxiv 2311.08105): each
replica group runs ``SYNC_EVERY`` purely-local AdamW steps, then the
groups average *pseudogradients* through the quorum and apply an outer
Nesterov-SGD step. Crossing the elastic axis once per H inner steps is
what makes cross-datacenter (DCN-connected) replica groups practical.

Env (same launcher contract as train_ddp.py):

    TORCHFT_LIGHTHOUSE=host:port   lighthouse address
    REPLICA_GROUP_ID / NUM_REPLICA_GROUPS (default 4)
    OUTER_STEPS=4                  outer (sync) steps to run
    SYNC_EVERY=8                   inner steps between syncs

Run 4 groups under the launcher (``--min-replicas 2`` mirrors the
Manager's ``min_replica_size`` so survivors keep committing while a
killed group is down — the launcher's default lighthouse would otherwise
require all 4 to participate)::

    python -m torchft_tpu.launcher --groups 4 --min-replicas 2 -- \\
        python examples/train_diloco.py

Kill any group mid-run: the survivors' next sync commits without it (down
to min_replica_size), and a restarted group rejoins at the next quorum —
the failed group's inner steps are the only work lost.

Reference workflow: torchft/local_sgd.py:177-239 + train_ddp.py loop.
"""

import logging
import os
import sys
from datetime import timedelta

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from torchft_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # make JAX_PLATFORMS authoritative (cpu-mesh runs)
import jax
import optax

from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.data import DistributedSampler
from torchft_tpu.local_sgd import DiLoCo
from torchft_tpu.manager import Manager
from torchft_tpu.store import StoreServer

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s: %(message)s")
logger = logging.getLogger("train_diloco")


def make_dataset(n=4096, d=32, classes=10, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal((d, classes)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.1 * rng.standard_normal((n, classes)), axis=1)
    return x, y.astype(np.int32)


def init_params(d=32, hidden=64, classes=10, seed=42):
    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(d)
    return {
        "w1": (scale * rng.standard_normal((d, hidden))).astype(np.float32),
        "b1": np.zeros(hidden, np.float32),
        "w2": (scale * rng.standard_normal((hidden, classes))).astype(np.float32),
        "b2": np.zeros(classes, np.float32),
    }


def loss_fn(params, x, y):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def main() -> None:
    replica_group = int(os.environ.get("REPLICA_GROUP_ID", 0))
    num_groups = int(os.environ.get("NUM_REPLICA_GROUPS", 4))
    outer_steps = int(os.environ.get("OUTER_STEPS", 4))
    sync_every = int(os.environ.get("SYNC_EVERY", 8))
    batch = int(os.environ.get("BATCH", 64))

    store_addr = os.environ.get("TORCHFT_STORE_ADDR")
    store = None
    if store_addr is None:
        store = StoreServer()
        store_addr = store.address()

    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=30)),
        load_state_dict=None,
        state_dict=None,
        min_replica_size=min(2, num_groups),
        # DiLoCo's outer step must start from a fully-healed state
        # (local_sgd.py:195-199) — sync quorum heals before the sync math
        use_async_quorum=False,
        replica_id=f"diloco_{replica_group}",
        store_addr=store_addr,
        rank=int(os.environ.get("RANK", 0)),
        world_size=int(os.environ.get("WORLD_SIZE", 1)),
        timeout=timedelta(seconds=30),
        # the quorum interval spans a whole inner loop (manager.py
        # docstring guidance: quorum_timeout must cover it)
        quorum_timeout=timedelta(seconds=120),
    )

    x, y = make_dataset()
    inner_tx = optax.adamw(1e-3)
    outer_tx = optax.sgd(0.7, momentum=0.9, nesterov=True)
    state = {"params": init_params()}
    state["inner"] = inner_tx.init(state["params"])
    diloco = DiLoCo(manager, outer_tx, sync_every=sync_every)
    diloco.save(state["params"])

    # live recovery: a rejoining group receives params + the DiLoCo
    # backup/outer-optimizer state from a survivor at its next sync quorum
    def user_state_dict():
        return {"params": state["params"], "diloco": diloco.state_dict()}

    def user_load_state_dict(s):
        state["params"] = s["params"]
        state["inner"] = inner_tx.init(s["params"])
        diloco.load_state_dict(s["diloco"])

    manager.set_state_dict_fns(user_load_state_dict, user_state_dict)

    @jax.jit
    def inner_step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
        updates, opt_state = inner_tx.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    data_rng_step = 0
    try:
        while manager.current_step() < outer_steps:
            sampler = DistributedSampler(
                len(x),
                replica_group=replica_group,
                num_replica_groups=num_groups,
                shuffle=True,
                seed=0,
            )
            sampler.set_epoch(data_rng_step)
            idx = np.fromiter(iter(sampler), dtype=np.int64)[:batch]
            data_rng_step += 1

            loss, params, inner = inner_step(
                state["params"], state["inner"], x[idx], y[idx]
            )
            state["params"], state["inner"] = params, inner
            synced = diloco.step(params)
            if synced is not params:  # a sync ran (commit or rollback)
                state["params"] = synced
                # inner optimizer restarts from the outer point each round
                # (paper setup: fresh inner state per outer step)
                state["inner"] = inner_tx.init(synced)
                logger.info(
                    "outer step=%d participants=%d inner_loss=%.4f",
                    manager.current_step(),
                    manager.num_participants(),
                    float(loss),
                )
        final = sum(
            float(np.asarray(v).sum())
            for v in jax.tree_util.tree_leaves(state["params"])
        )
        logger.info(
            "done: outer_step=%d param_checksum=%.6f",
            manager.current_step(),
            final,
        )
    finally:
        manager.shutdown(wait=False)
        if store is not None:
            store.shutdown()


if __name__ == "__main__":
    main()
