"""HSDP training example — the BASELINE.md "HSDP Llama-2-7B" config shape.

The flagship composition: each replica group owns a fixed inner
``jax.sharding.Mesh`` (fsdp x tp [x sp x pp] — XLA's ICI collectives,
compiled once), while the Manager runs the elastic replica axis across
groups. Gradients cross it through ``allreduce_gradients`` — the
device-path backend (CollectivesDevice) when the groups share one JAX
runtime, host TCP (DCN) across processes. Group membership changes never
recompile the train step; a killed group live-heals its *sharded* params
shard-by-shard from a survivor (serialization.py "shards" transfer).

Env:

    TORCHFT_LIGHTHOUSE=host:port
    REPLICA_GROUP_ID / NUM_REPLICA_GROUPS (default 2)
    MODEL=tiny|llama2-7b           preset (default tiny; 7b needs >= 8
                                   real chips per group)
    DEVICES_PER_GROUP=4            carve jax.devices() per group when
                                   groups share one runtime (else use all)
    FSDP/TP/SP/PP                  inner mesh axis sizes (default 2/2/1/1)
    STEPS=3  BATCH=8  SEQ=16       training shape
    DATA_PLANE=tcp|device          cross-group backend (default tcp;
                                   device = colocated groups, one runtime)

Run 2 tiny groups on the virtual CPU mesh::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
    python -m torchft_tpu.launcher --groups 2 -- python examples/train_hsdp.py

Reference parity: fsdp_test.py:40-64 (fully_shard over ft_init_device_mesh)
re-designed TPU-first — the inner mesh is GSPMD shardings, not FSDP2.
"""

import logging
import os
import sys
from datetime import timedelta

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from torchft_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # make JAX_PLATFORMS authoritative (cpu-mesh runs)
import jax
import jax.numpy as jnp
import optax

from torchft_tpu.manager import Manager
from torchft_tpu.models.transformer import TransformerConfig
from torchft_tpu.parallel.ft import FTTrainer
from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
from torchft_tpu.parallel.multihost import initialize_group
from torchft_tpu.parallel.train_step import TrainStep
from torchft_tpu.store import StoreServer

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s: %(message)s")
logger = logging.getLogger("train_hsdp")

PRESETS = {
    # CPU-mesh testable
    "tiny": dict(
        vocab_size=64, d_model=16, n_layers=2, n_heads=2, head_dim=8, d_ff=32
    ),
    # Llama-2-7B shape (BASELINE.md north-star config); bf16, needs real
    # chips — fsdp>=8 per group on v5e for the ~13 GB of params+optimizer
    "llama2-7b": dict(
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        head_dim=128,
        d_ff=11008,
    ),
}


def main() -> None:
    replica_group = int(os.environ.get("REPLICA_GROUP_ID", 0))
    num_groups = int(os.environ.get("NUM_REPLICA_GROUPS", 2))
    steps = int(os.environ.get("STEPS", 3))
    batch = int(os.environ.get("BATCH", 8))
    seq = int(os.environ.get("SEQ", 16))
    preset = os.environ.get("MODEL", "tiny")

    store_addr = os.environ.get("TORCHFT_STORE_ADDR")
    store = None
    if store_addr is None:
        store = StoreServer()
        store_addr = store.address()

    initialize_group()  # multi-host group: join its jax runtime (no-op else)

    mesh_cfg = MeshConfig(
        fsdp=int(os.environ.get("FSDP", 2)),
        tp=int(os.environ.get("TP", 2)),
        sp=int(os.environ.get("SP", 1)),
        pp=int(os.environ.get("PP", 1)),
    )
    per_group = int(os.environ.get("DEVICES_PER_GROUP", 0))
    if per_group:
        devices = jax.devices()[
            replica_group * per_group : (replica_group + 1) * per_group
        ]
    else:
        devices = jax.devices()
    mesh = make_mesh(mesh_cfg, devices=devices)

    dtype = jnp.float32 if preset == "tiny" else jnp.bfloat16
    cfg = TransformerConfig(dtype=dtype, pp=mesh_cfg.pp, **PRESETS[preset])
    ts = TrainStep(cfg, optax.adamw(3e-4), mesh)

    if os.environ.get("DATA_PLANE", "tcp") == "device":
        from torchft_tpu.collectives_device import CollectivesDevice

        collectives = CollectivesDevice(timeout=timedelta(seconds=30))
    else:
        from torchft_tpu.collectives import CollectivesTcp

        collectives = CollectivesTcp(timeout=timedelta(seconds=30))

    manager = Manager(
        collectives=collectives,
        load_state_dict=None,  # wired by FTTrainer.init
        state_dict=None,
        min_replica_size=min(2, num_groups),
        replica_id=f"hsdp_{replica_group}",
        store_addr=store_addr,
        rank=int(os.environ.get("RANK", 0)),
        world_size=int(os.environ.get("WORLD_SIZE", 1)),
        timeout=timedelta(seconds=30),
    )
    try:
        trainer = FTTrainer(manager, ts)
        trainer.init(jax.random.PRNGKey(0))
        n_params = sum(
            int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(trainer.params)
        )
        logger.info(
            "model=%s params=%.1fM mesh=%s", preset, n_params / 1e6, mesh_cfg.sizes
        )

        import time

        data_rng = np.random.default_rng(1000 + replica_group)
        while manager.current_step() < steps:
            tokens = jnp.asarray(
                data_rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32
            )
            loss, committed = trainer.step(tokens)
            if not committed:
                time.sleep(0.2)  # back off while the quorum is short
            logger.info(
                "step=%d committed=%s participants=%d loss=%.4f",
                manager.current_step(),
                committed,
                manager.num_participants(),
                loss,
            )
        checksum = sum(
            float(jnp.sum(l.astype(jnp.float32)))
            for l in jax.tree_util.tree_leaves(trainer.params)
        )
        logger.info(
            "done: step=%d param_checksum=%.6f", manager.current_step(), checksum
        )
    finally:
        manager.shutdown(wait=False)
        if store is not None:
            store.shutdown()


if __name__ == "__main__":
    main()
