"""Fault-tolerant data-parallel training example — the reference
train_ddp.py analogue (/root/reference/train_ddp.py:34-152), jax-native.

One process per replica group (within a group, TPU chips are an inner jax
Mesh — see torchft_tpu.parallel). Configure via env:

    TORCHFT_LIGHTHOUSE=host:port   lighthouse address
    REPLICA_GROUP_ID=0             this group's id
    NUM_REPLICA_GROUPS=2           total groups (min replicas = 2 here)
    STEPS=20                       steps to train
    CKPT_DIR=/path                 enable periodic disk checkpoints there
    CKPT_EVERY=5                   checkpoint cadence (committed steps)
    DATA_PLANE=tcp|device-dist     cross-group backend (device-dist needs
                                   launcher --shared-runtime: one
                                   multi-controller runtime, psum on ICI)

Run a 2-group session (3 terminals)::

    python -m torchft_tpu.lighthouse --bind "[::]:29510" --min_replicas 2
    REPLICA_GROUP_ID=0 TORCHFT_LIGHTHOUSE=$(hostname):29510 python examples/train_ddp.py
    REPLICA_GROUP_ID=1 TORCHFT_LIGHTHOUSE=$(hostname):29510 python examples/train_ddp.py

Kill either trainer mid-run and restart it: it rejoins the quorum and
live-heals from the survivor, costing the cohort at most one step.

Two complementary recovery mechanisms, as in the reference: the live
quorum heal above covers *partial* failures (a peer survives to serve
state), and the periodic disk checkpoint covers *total* failures — with
CKPT_DIR set, every CKPT_EVERY committed steps the group writes
{manager state, params+optimizer, sampler position} atomically
(reference workflow: train_ddp.py:141-148, manager.py:83-85 docs) and a
restarted process resumes from it automatically, continuing bit-exactly.
"""

import logging
import os
import sys
from datetime import timedelta

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from torchft_tpu.utils.platform import pin_platform_from_env

pin_platform_from_env()  # make JAX_PLATFORMS authoritative (cpu-mesh runs)
import jax
import optax

from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.data import DistributedSampler
from torchft_tpu.manager import Manager
from torchft_tpu.optim import ManagedOptimizer
from torchft_tpu.store import StoreServer

logging.basicConfig(
    level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
)
logger = logging.getLogger("train_ddp")


def make_dataset(n=4096, d=32, classes=10, seed=7):
    """Synthetic classification set (CIFAR stand-in), identical everywhere."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal((d, classes)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.1 * rng.standard_normal((n, classes)), axis=1)
    return x, y.astype(np.int32)


def init_params(d=32, hidden=64, classes=10, seed=42):
    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(d)
    return {
        "w1": (scale * rng.standard_normal((d, hidden))).astype(np.float32),
        "b1": np.zeros(hidden, np.float32),
        "w2": (scale * rng.standard_normal((hidden, classes))).astype(np.float32),
        "b2": np.zeros(classes, np.float32),
    }


def loss_fn(params, x, y):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def main() -> None:
    replica_group = int(os.environ.get("REPLICA_GROUP_ID", 0))
    num_groups = int(os.environ.get("NUM_REPLICA_GROUPS", 2))
    steps = int(os.environ.get("STEPS", 20))
    batch = int(os.environ.get("BATCH", 64))
    ckpt_dir = os.environ.get("CKPT_DIR")
    ckpt_every = int(os.environ.get("CKPT_EVERY", 5))
    # launcher env contract (torchelastic analogue): a launcher-provided
    # store + RANK/WORLD_SIZE means this process is one rank of a
    # multi-process group; standalone runs make their own 1-rank group
    rank = int(os.environ.get("RANK", 0))
    world_size = int(os.environ.get("WORLD_SIZE", 1))
    store_addr = os.environ.get("TORCHFT_STORE_ADDR")
    store = None
    if store_addr is None:
        store = StoreServer()
        store_addr = store.address()
    # multi-host group: join the group-wide jax runtime (no-op without
    # TORCHFT_JAX_COORDINATOR); this example keeps compute replicated per
    # rank — a sharded inner mesh is what torchft_tpu.parallel is for
    from torchft_tpu.parallel.multihost import initialize_group

    initialize_group()

    # DATA_PLANE=tcp (default): host ring with the native striped/CMA
    # fast path. DATA_PLANE=device-dist: all groups share ONE
    # multi-controller jax runtime (launcher --shared-runtime) and the
    # averaging psum rides ICI — see README's plane-selection table.
    if os.environ.get("DATA_PLANE", "tcp") == "device-dist":
        from torchft_tpu.collectives_device_dist import (
            CollectivesDeviceDist,
            init_from_env,
        )

        if not init_from_env():
            raise SystemExit(
                "DATA_PLANE=device-dist requires the shared-runtime cohort "
                "env (run under `python -m torchft_tpu.launcher "
                "--shared-runtime`); without it every group would form its "
                "own 1-process runtime and quorum configure() would reject "
                "the cohort mismatch on every epoch"
            )
        collectives = CollectivesDeviceDist(timeout=timedelta(seconds=30))
    else:
        collectives = CollectivesTcp(timeout=timedelta(seconds=30))

    manager = Manager(
        collectives=collectives,
        load_state_dict=None,  # wired by ManagedOptimizer.init
        state_dict=None,
        min_replica_size=min(2, num_groups),
        replica_id=f"train_ddp_{replica_group}",
        store_addr=store_addr,
        rank=rank,
        world_size=world_size,
        timeout=timedelta(seconds=30),
    )

    x, y = make_dataset()
    opt = ManagedOptimizer(manager, optax.adam(1e-3))
    opt.init(init_params())
    sampler = DistributedSampler(
        len(x),
        replica_group=replica_group,
        num_replica_groups=num_groups,
        shuffle=True,
        seed=0,
    )
    value_and_grad = jax.jit(jax.value_and_grad(loss_fn))

    # periodic disk checkpoints (total-failure recovery; live quorum
    # healing covers partial failures): one writer per group, every rank
    # restores from the shared snapshot, restore happens BEFORE the first
    # quorum so a resumed group reports its true step and heals forward
    ckpt = None
    if ckpt_dir:
        from torchft_tpu.checkpointing.disk import DiskCheckpointer

        ckpt = DiskCheckpointer(
            ckpt_dir,
            manager,
            state_dict=lambda: {
                "opt": opt.state_dict(),
                "sampler": sampler.state_dict(),
            },
            load_state_dict=lambda s: (
                opt.load_state_dict(s["opt"]),
                sampler.load_state_dict(s["sampler"]),
            ),
            every=ckpt_every,
            tag=f"group{replica_group}",
            is_writer=(rank == 0),
        )
        ckpt.restore()

    import time

    try:
        def steps_in_flight() -> int:
            # committed steps plus the speculative one whose pipelined
            # vote is still in flight — the loop must count it or a
            # pipelined run would train one extra step past STEPS
            return manager.current_step() + (
                1 if manager.pending_commit() is not None else 0
            )

        prev_step = manager.current_step()
        while manager.current_step() < steps:
            while steps_in_flight() < steps:
                # in-flight count, not current_step(): during speculation
                # the committed counter lags one step, and feeding it to
                # the sampler would phase-shift the batch schedule vs
                # sync mode
                sampler.set_epoch(steps_in_flight())
                idx = np.fromiter(iter(sampler), dtype=np.int64)[:batch]

                opt.begin_step()  # async quorum overlaps the forward pass
                loss, grads = value_and_grad(opt.params, x[idx], y[idx])
                opt.step(grads)
                if (
                    manager.current_step() == prev_step
                    and manager.pending_commit() is None
                ):
                    # failed commit (e.g. waiting for enough replicas):
                    # back off instead of hammering the quorum in a busy
                    # loop. A pending pipelined vote is NOT a failed
                    # commit — the counter advances when the next step
                    # resolves it.
                    time.sleep(0.2)
                prev_step = manager.current_step()
                logger.info(
                    "step=%d batches_committed=%d participants=%d loss=%.4f",
                    manager.current_step(),
                    manager.batches_committed(),
                    manager.num_participants(),
                    float(loss),
                )
                if ckpt is not None:
                    ckpt.maybe_save()
            # pipelined commit (TORCHFT_COMMIT_PIPELINE=1): resolve the
            # trailing speculative vote (no-op in sync mode). If it is
            # VETOED the rollback leaves current_step < steps and the
            # outer loop trains the missing step(s) — sync parity: the
            # run always ends with exactly `steps` committed steps.
            opt.finish()
        final = jax.tree_util.tree_map(lambda a: np.asarray(a).sum(), opt.params)
        logger.info("done: step=%d param_checksum=%.6f",
                    manager.current_step(),
                    float(sum(float(v) for v in jax.tree_util.tree_leaves(final))))
    finally:
        manager.shutdown(wait=False)
        if store is not None:
            store.shutdown()


if __name__ == "__main__":
    main()
