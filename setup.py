"""Build hook: compile the C++ coordination core into the wheel.

The reference builds its Rust core with maturin (pyproject.toml there);
the TPU-native equivalent is a plain ``make -C native`` producing
``torchft_tpu/_native/libtftcore.so``. In-checkout use never needs this —
the library builds on first import (torchft_tpu/_native/__init__.py) —
but a wheel must ship the compiled artifact."""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

HERE = os.path.dirname(os.path.abspath(__file__))


class BuildWithNative(build_py):
    def run(self):
        native = os.path.join(HERE, "native")
        lib = os.path.join(HERE, "torchft_tpu", "_native", "libtftcore.so")
        if os.path.isdir(native):
            subprocess.run(["make", "-C", native], check=True)
        if not os.path.exists(lib):
            # never ship a wheel that can neither load nor rebuild the core
            raise RuntimeError(
                "native/ sources missing and libtftcore.so not prebuilt; "
                "build from a full checkout or sdist (MANIFEST.in grafts "
                "native/)"
            )
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
