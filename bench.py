"""Headline benchmark: fault-tolerant transformer training throughput.

Runs the full FT loop — real C++ lighthouse + manager, quorum per step,
commit vote per step — around the jitted bf16 transformer train step on
whatever accelerator is attached (TPU under the driver; CPU works too).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is 1.0 by definition: the reference (Krishn1412/torchft)
publishes no performance numbers (BASELINE.md), so the measured value IS
the baseline being established.
"""

import json
import logging
import os
import sys
import time
from datetime import timedelta

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

logging.basicConfig(level=logging.WARNING)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.models.transformer import TransformerConfig
    from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
    from torchft_tpu.parallel.train_step import TrainStep
    from torchft_tpu.store import StoreServer

    n_dev = len(jax.devices())
    on_tpu = jax.devices()[0].platform != "cpu"

    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=512,
        n_layers=8,
        n_heads=8,
        head_dim=64,
        d_ff=1408,
        dtype=jnp.bfloat16,
    )
    batch, seq = (8, 1024) if on_tpu else (4, 128)
    steps, warmup = (20, 3) if on_tpu else (5, 1)

    mesh = make_mesh(MeshConfig(dp=1))  # single chip; FT axis is host-side
    ts = TrainStep(cfg, optax.adamw(3e-4), mesh)
    params = ts.init_params(jax.random.PRNGKey(0))
    opt_state = ts.init_opt(params)

    # full FT control plane, 1 replica group
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=1)
    store = StoreServer()
    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=30)),
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=1,
        replica_id="bench",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse.address(),
    )

    rng = np.random.default_rng(0)
    tokens = ts.shard_batch(
        jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    )

    def ft_step(params, opt_state):
        # reference-faithful ordering: grads, then the commit vote gates the
        # optimizer step (manager.py:546-599). The split grads/apply pair is
        # also what makes rollback safe: apply() donates the old params only
        # after the group committed.
        manager.start_quorum()
        loss, grads = ts.grads(params, tokens)
        if manager.should_commit():
            params, opt_state = ts.apply(params, opt_state, grads)
        return loss, params, opt_state

    try:
        for _ in range(warmup):
            loss, params, opt_state = ft_step(params, opt_state)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, opt_state = ft_step(params, opt_state)
        # a host transfer is the only reliable completion fence on the
        # tunneled TPU backend (block_until_ready returns early there);
        # the final loss depends on the whole step chain
        float(loss)
        elapsed = time.perf_counter() - t0
    finally:
        manager.shutdown(wait=False)
        store.shutdown()
        lighthouse.shutdown()

    steps_per_sec = steps / elapsed
    tokens_per_sec = steps_per_sec * batch * seq
    print(
        json.dumps(
            {
                "metric": "ft_transformer_train_steps_per_sec_per_chip",
                "value": round(steps_per_sec, 4),
                "unit": f"steps/s (bf16 d512 L8 b{batch} s{seq}; {tokens_per_sec:.0f} tok/s; full quorum+commit per step)",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
