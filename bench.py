"""Headline benchmark: fault-tolerant transformer training throughput.

Runs the full FT loop — real C++ lighthouse + manager, quorum per step,
commit vote per step — around the jitted bf16 transformer train step on
whatever accelerator is attached (TPU under the driver; CPU works too).
The headline is a SINGLE replica group on one chip (median of 3 runs,
spread reported): the per-step FT control path is fully real; the cross-
group psum no-ops at world=1, so the real 2-group averaging costs are
measured by dedicated extras instead of mislabeled into the headline
(round-2 review weak #1/#2):

* ``cpu_mesh_2group`` — REAL device-path 'ft'-axis psum between two
  groups on a virtual 8-CPU mesh, relative overhead;
* ``crossgroup_host_plane`` — two separate OS processes over the TCP
  ring (serial vs pipelined vs bf16 wire, derived llama2-7b cost);
* a long-context s=4096 variant, a 647M-param scale variant, and the
  recovery envelope BASELINE.md names (SIGKILL 1 of 2 groups).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

vs_baseline is 1.0 by definition: the reference (Krishn1412/torchft)
publishes no performance numbers (BASELINE.md), so the measured value IS
the baseline being established.
"""

import json
import logging
import os
import sys
import time
from contextlib import contextmanager
from datetime import timedelta

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

logging.basicConfig(level=logging.WARNING)

# bf16 peak FLOP/s per chip by device kind (public spec sheets)
_PEAK_BF16 = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12,  # v6e / Trillium
    "v6e": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_BF16.items():
        if key in kind:
            return val
    return 0.0  # unknown chip: MFU omitted


def _model_flops_per_step(cfg, n_params: int, batch: int, seq: int) -> float:
    # fwd+bwd matmul FLOPs: 6*N per token, + attention 12*L*S*d per token
    # (QK^T and AV each 2*S*d MACs per token per layer, x3 for fwd+bwd)
    per_token = 6.0 * n_params + 12.0 * cfg.n_layers * seq * cfg.d_model
    return per_token * batch * seq


@contextmanager
def _single_group_ft_runtime(replica_id: str, use_async_quorum: bool = True):
    """Full FT control plane for a 1-group bench: C++ lighthouse + store +
    Manager over the device-path data plane (on a multi-group slice the
    same code averages over the 'ft' mesh axis via ICI, no host staging).
    Also clears jax caches first: compiled programs pin device buffers and
    bench variants don't share shapes."""
    import gc

    import jax

    gc.collect()
    jax.clear_caches()

    from torchft_tpu.collectives_device import CollectivesDevice
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.store import StoreServer

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=1)
    store = StoreServer()
    manager = Manager(
        collectives=CollectivesDevice(timeout=timedelta(seconds=30)),
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=1,
        replica_id=replica_id,
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse.address(),
        use_async_quorum=use_async_quorum,
    )
    try:
        yield manager
    finally:
        manager.shutdown(wait=False)
        store.shutdown()
        lighthouse.shutdown()


def train_bench(cfg, batch, seq, steps, warmup, averaging: bool,
                use_async_quorum: bool = True):
    """Measured FT train loop; returns steps/s."""
    import jax
    import jax.numpy as jnp
    import optax

    from torchft_tpu.ddp import allreduce_gradients
    from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
    from torchft_tpu.parallel.train_step import TrainStep

    with _single_group_ft_runtime("bench", use_async_quorum) as manager:
        mesh = make_mesh(MeshConfig(dp=1))  # single chip; FT axis is cross-group
        ts = TrainStep(cfg, optax.adamw(3e-4), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt_state = ts.init_opt(params)
        rng = np.random.default_rng(0)
        tokens = ts.shard_batch(
            jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        )

        def ft_step(params, opt_state):
            # reference-faithful ordering (manager.py:546-599): quorum,
            # grads, cross-group average, then the commit vote gates the
            # optimizer step. apply() donates the old params post-commit.
            manager.start_quorum()
            loss, grads = ts.grads(params, tokens)
            if averaging:
                grads = allreduce_gradients(manager, grads)
            if manager.should_commit():
                params, opt_state = ts.apply(params, opt_state, grads)
            return loss, params, opt_state

        for _ in range(warmup):
            loss, params, opt_state = ft_step(params, opt_state)
        if warmup:
            float(loss)  # fence warmup work out of the timed window
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, opt_state = ft_step(params, opt_state)
        # a host transfer is the only reliable completion fence on the
        # tunneled TPU backend (block_until_ready returns early there);
        # the final loss depends on the whole step chain
        float(loss)
        elapsed = time.perf_counter() - t0

    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params)
    )
    return steps / elapsed, n_params


def _run_json_subprocess(cmd, timeout_s: float, env_extra=None) -> dict:
    """Run a bench worker; parse the last stdout line as JSON.

    The worker runs in its own session and a timeout kills the whole
    process group — a wedged grandchild (e.g. a re-exec'd worker holding
    the inherited stdout pipe) must fail the variant, not hang bench.py
    in communicate() forever."""
    import signal
    import subprocess

    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        start_new_session=True,
        # the child's `python -m torchft_tpu.benchmarks.*` resolves the
        # package from its cwd; anchor it to the repo root so bench.py
        # works when invoked from anywhere (ADVICE r5 #1)
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.wait()
        raise
    if proc.returncode != 0:
        raise RuntimeError(
            f"{cmd[-1]} failed rc={proc.returncode}: {err.decode()[-1500:]}"
        )
    return json.loads(out.decode().strip().splitlines()[-1])


# "Higher is better" fields the cross-round regression gate compares.
_GATE_FIELDS = ("steps_per_sec", "gb_per_sec", "imgs_per_sec")
_GATE_TOLERANCE_PCT = 15.0  # past run-to-run spread on this 1-core box
# The crossgroup wire rows run 2 worker processes + parent on ONE core;
# their r04->r05 swings were -21%..+769% with immediate isolated re-runs
# landing back inside the old band (e.g. raw_cma 1.307 -> 1.046 flagged,
# re-run alone 1.188) — a 15% gate on them is all noise. Wider, still
# finite: a real transport regression (say, CMA silently off) is >2x.
# resnet18_cifar: ~10-15 ms steps against ~5 tunnel RPCs each — the row
# is dispatch-latency-bound and its isolated per-invocation median spans
# 44-96 steps/s on this box (resnet_ft.py round-5 addendum)
_GATE_WIDE_ROWS = {
    "crossgroup_host_plane", "resnet18_cifar", "crossgroup_compressed",
}
_GATE_WIDE_TOLERANCE_PCT = 40.0


def _apply_regression_gate(extra: dict, headline_sps: float) -> None:
    """Annotate every comparable row with its delta vs the previous
    round's committed snapshot (bench_baseline.json) and collect rows
    past tolerance into extra['regressions'] — the gate round-4 lacked
    when resnet18_cifar silently lost 44% to suite interference."""
    base_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json"
    )
    try:
        with open(base_path) as f:
            baseline = json.load(f)
    except (OSError, ValueError):
        extra["regressions"] = ["bench_baseline.json missing/unreadable"]
        return

    regressions = []

    def gate_row(name: str, row: dict, base_row: dict, tol: float) -> None:
        for field in _GATE_FIELDS:
            now, was = row.get(field), base_row.get(field)
            if isinstance(was, (int, float)) and was and now is None:
                # a previously-measured row lost its metric (worker error
                # or vanished key): exactly the silent loss the gate is
                # for — flag loudly instead of skipping
                regressions.append(
                    f"{name}.{field}: {was} -> MISSING "
                    f"({row.get('error', 'field absent')})"
                )
                continue
            if not (
                isinstance(now, (int, float)) and isinstance(was, (int, float))
            ) or not was:
                continue
            delta = (now / was - 1.0) * 100.0
            row[f"delta_vs_prev_pct_{field}"] = round(delta, 1)
            if delta < -tol:
                regressions.append(
                    f"{name}.{field}: {was} -> {now} ({delta:+.1f}%)"
                )
        # gb_per_sec & friends live one level down in composite rows
        # (e.g. crossgroup_host_plane.heal_cma) — recurse one level
        for sub, subrow in row.items():
            base_sub = base_row.get(sub)
            if isinstance(subrow, dict) and isinstance(base_sub, dict):
                gate_row(f"{name}.{sub}", subrow, base_sub, tol)

    def gate_resnet_on_max(row: dict, base_row: dict) -> bool:
        """resnet18_cifar is dispatch-latency-bound: its isolated
        per-invocation median spans 44-96 steps/s on this box, wider than
        any sane tolerance. Contention only SUBTRACTS (the
        cpu_mesh_2group rationale), so gate on max(runs) — the run least
        touched by tunnel weather — instead of the median (ADVICE r5 #4).
        Returns True when the max-run gate applied (generic gate skipped)."""
        now_runs = row.get("runs_steps_per_sec")
        was_runs = base_row.get("runs_steps_per_sec")
        if not (
            isinstance(now_runs, list) and now_runs
            and isinstance(was_runs, list) and was_runs
        ):
            return False  # old-format row: fall back to the generic gate
        now, was = max(now_runs), max(was_runs)
        if not was:
            return False
        delta = (now / was - 1.0) * 100.0
        row["delta_vs_prev_pct_max_steps_per_sec"] = round(delta, 1)
        if delta < -_GATE_WIDE_TOLERANCE_PCT:
            regressions.append(
                f"resnet18_cifar.max(runs_steps_per_sec): {was} -> {now} "
                f"({delta:+.1f}%)"
            )
        return True

    def base_has_gated_metric(base_row: dict) -> bool:
        for field in _GATE_FIELDS:
            if isinstance(base_row.get(field), (int, float)):
                return True
        return any(
            isinstance(sub, dict) and base_has_gated_metric(sub)
            for sub in base_row.values()
        )

    for name, row in extra.items():
        base_row = baseline.get(name)
        if isinstance(row, dict) and isinstance(base_row, dict):
            row_has_data = any(
                isinstance(v, dict) or k in _GATE_FIELDS
                for k, v in row.items()
            )
            if "error" in row and not row_has_data and (
                base_has_gated_metric(base_row)
                # rows without a gated throughput metric (step_anatomy)
                # opt into the whole-row-error check by carrying
                # _gate_presence in the baseline snapshot
                or base_row.get("_gate_presence")
            ):
                # a whole-row failure must not silently bypass the gate:
                # the baseline measured this row, so losing it entirely is
                # the loudest regression there is (gate_row's per-field
                # MISSING check only fires when the sub-dicts survive)
                regressions.append(
                    f"{name}: previously-measured row errored "
                    f"({str(row['error'])[:200]})"
                )
                continue
            if name == "resnet18_cifar" and gate_resnet_on_max(row, base_row):
                continue
            tol = (
                _GATE_WIDE_TOLERANCE_PCT
                if name in _GATE_WIDE_ROWS
                else _GATE_TOLERANCE_PCT
            )
            gate_row(name, row, base_row, tol)
    was_h = baseline.get("_headline_steps_per_sec")
    if isinstance(was_h, (int, float)) and was_h:
        delta = (headline_sps / was_h - 1.0) * 100.0
        extra["headline_delta_vs_prev_pct"] = round(delta, 1)
        if delta < -_GATE_TOLERANCE_PCT:
            regressions.append(
                f"headline: {was_h} -> {round(headline_sps, 3)} "
                f"({delta:+.1f}%)"
            )
    extra["regressions"] = regressions


def headline_config():
    """The ONE headline model config — also imported by the subprocess
    workers (benchmarks/long_context.py) so the long-context rows can
    never silently diverge from the headline model."""
    import jax.numpy as jnp

    from torchft_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=32000,
        d_model=512,
        n_layers=8,
        n_heads=8,
        head_dim=64,
        d_ff=1408,
        dtype=jnp.bfloat16,
    )


def main() -> None:
    import jax

    on_tpu = jax.devices()[0].platform != "cpu"

    cfg = headline_config()
    batch, seq = (8, 1024) if on_tpu else (4, 128)
    steps, warmup = (20, 3) if on_tpu else (5, 1)

    # 3 runs: the round-2 → round-1 "regression" (17.7 vs 20.0 steps/s)
    # turned out to be unreported run-to-run variance/host contamination;
    # the headline is now the median with the spread alongside
    n_runs = 3 if on_tpu else 1
    runs = []
    noavg_runs = []
    n_params = 0
    for _ in range(n_runs):  # interleaved: both variants see the same drift
        r, n_params = train_bench(cfg, batch, seq, steps, warmup, averaging=True)
        runs.append(r)
        noavg_runs.append(
            train_bench(cfg, batch, seq, steps, warmup, averaging=False)[0]
        )
    runs.sort()
    noavg_runs.sort()
    sps = runs[len(runs) // 2]
    sps_noavg = noavg_runs[len(noavg_runs) // 2]
    tokens_per_sec = sps * batch * seq
    overhead_pct = (sps_noavg - sps) / sps_noavg * 100.0 if sps_noavg else 0.0

    peak = _peak_flops(jax.devices()[0])
    flops = _model_flops_per_step(cfg, n_params, batch, seq)
    mfu_pct = (sps * flops / peak * 100.0) if peak else None

    extra = {
        "data_plane": "device-path (CollectivesDevice); SINGLE replica "
        "group on one chip, so the cross-group psum no-ops at world=1 — "
        "what IS measured per step: real quorum RPC + commit vote + the "
        "managed-op machinery + jitted 1/n normalization. Real 2-group "
        "averaging costs: see cpu_mesh_2group (device path) and "
        "crossgroup_host_plane (separate processes).",
        "headline_runs_steps_per_sec": [round(r, 4) for r in runs],
        "headline_spread_pct": round(
            (max(runs) - min(runs)) / sps * 100.0, 2
        ),
        "steps_per_sec_no_ft_control": round(sps_noavg, 4),
        "noavg_runs_steps_per_sec": [round(r, 4) for r in noavg_runs],
        "ft_control_overhead_pct": round(overhead_pct, 2),
        "n_params": n_params,
        "mfu_pct": round(mfu_pct, 2) if mfu_pct is not None else None,
        "config": {
            "model": "d512 L8 h8 ff1408 vocab32k bf16",
            # measured, not assumed (round-4 review weak #4): remat=True
            # BEATS remat=False at this config (19.1 vs 16.3 steps/s) —
            # without checkpoint XLA spills activations to HBM; the
            # recompute is cheaper than the spill traffic
            "remat": True,
            "attention": "tiered chunked-scan, C=128 (auto rule engages "
            "at s>=1024 since round 5 — plain attention's f32 [S,S] "
            "scores already round-trip HBM at the headline length)",
            "batch": batch,
            "seq": seq,
            "steps": steps,
            "warmup": warmup,
            "optimizer": "adamw(3e-4), fused-apply donated buffers",
            "jax": jax.__version__,
            "device": getattr(jax.devices()[0], "device_kind", "?"),
        },
    }

    # Step-anatomy row (ISSUE 8): the headline loop ran through the REAL
    # instrumented Manager in this process, so the process ledger holds a
    # per-step phase decomposition of exactly those steps. Embeds per-
    # phase p50/p99, a p50-sum-vs-wall-p50 reconciliation (idle is the
    # residual, so per-step sums are exact and the p50 composition should
    # land within a few percent), and ft_control_overhead_pct derived
    # from the ledger (quorum_wait + commit_barrier share of the wall
    # p50) — replacing the old hand-computed ft_control_overhead_split.
    # Native-plane latency p50/p99s (quorum fan-out, RPC serve) ride
    # along from the in-process lathist snapshot.
    try:
        from torchft_tpu import telemetry as _tm
        from torchft_tpu.telemetry.anatomy import lathist_quantile
        from torchft_tpu.telemetry.native import native_latency_snapshot

        anatomy = _tm.LEDGER.summary()
        wall_p50 = float(anatomy.get("wall_p50_s") or 0.0)
        phases = anatomy.get("phases", {})
        phase_sum_p50 = sum(p["p50_s"] for p in phases.values())
        ctl_p50 = sum(
            phases.get(p, {}).get("p50_s", 0.0)
            for p in ("quorum_wait", "commit_barrier")
        )
        row = {
            "_gate_presence": True,
            "steps": anatomy.get("steps"),
            "phases": {
                k: {"p50_s": v["p50_s"], "p99_s": v["p99_s"]}
                for k, v in phases.items()
            },
            "wall_p50_s": round(wall_p50, 6),
            "wall_p99_s": anatomy.get("wall_p99_s"),
            "local_p50_s": anatomy.get("local_p50_s"),
            "phase_sum_p50_s": round(phase_sum_p50, 6),
            "reconciliation_pct": (
                round((phase_sum_p50 / wall_p50 - 1.0) * 100.0, 2)
                if wall_p50
                else None
            ),
            "ft_control_overhead_pct": (
                round(ctl_p50 / wall_p50 * 100.0, 2) if wall_p50 else None
            ),
            "note": "per-phase p50/p99 over the in-process headline steps "
            "(both variants); idle is the residual so per-step phase sums "
            "equal wall exactly — reconciliation_pct is the p50-"
            "composition error; ft_control_overhead_pct = "
            "(quorum_wait+commit_barrier) p50 share of wall p50",
        }
        native = native_latency_snapshot()
        if native:
            row["native_latency"] = {
                op: {
                    "count": int(h["count"]),
                    "p50_s": round(lathist_quantile(h, 0.5), 6),
                    "p99_s": round(lathist_quantile(h, 0.99), 6),
                }
                for op, h in sorted(native.items())
                if int(h["count"])
            }
        extra["step_anatomy"] = row
    except Exception as e:  # noqa: BLE001 — observability never fails bench
        extra["step_anatomy"] = {"error": str(e)}

    # ResNet-18 CIFAR (BASELINE.md config list): conv family through the
    # same FT loop; imgs/s per chip. OWN process, first touch of the chip
    # among subprocess extras — round-4's 88->49 "regression" was suite
    # interference from running last inside this process (see
    # torchft_tpu/benchmarks/resnet_ft.py for the post-mortem).
    if on_tpu:
        try:
            extra["resnet18_cifar"] = _run_json_subprocess(
                [sys.executable, "-m", "torchft_tpu.benchmarks.resnet_ft"],
                timeout_s=900,
            )
        except Exception as e:  # noqa: BLE001
            extra["resnet18_cifar"] = {"error": str(e)}

    # long-context variants + the 647M scale variant (TPU only), in their
    # OWN process (benchmarks/long_context.py): the auto rule routes
    # s>=1024 to tiered chunked-scan attention; round-4 took s=8192 from
    # 15.0% to ~31% MFU and round 5 found the in-process rows depressed
    # ~10% by the headline runs' leftover state — same interference class
    # as the resnet row, same fix.
    if on_tpu:
        try:
            extra.update(
                _run_json_subprocess(
                    [
                        sys.executable, "-m",
                        "torchft_tpu.benchmarks.long_context",
                    ],
                    timeout_s=1500,
                )
            )
        except Exception as e:  # noqa: BLE001
            # mark EVERY expected row errored: a vanished row would
            # silently bypass the regression gate (it only walks keys
            # present in extra), defeating its purpose
            for key in (
                "long_context_s4096", "long_context_s8192",
                "long_context_s16384", "long_context_s32768", "scale_647M",
            ):
                extra[key] = {"error": str(e)}

    # sync-vs-async quorum, measured in the regime use_async_quorum exists
    # for: 2 groups + a synthetic RTT on the quorum RPC (round-4 review
    # weak #2/#3: the old single-group localhost A/B measured 0.19% —
    # noise — and was mis-cited as a ~10% gain). Interleaved median-of-7
    # with spreads; the artifact behind the manager.py default.
    try:
        extra["quorum_overlap"] = _run_json_subprocess(
            [sys.executable, "-m", "torchft_tpu.benchmarks.quorum_overlap"],
            timeout_s=900,
            env_extra={"JAX_PLATFORMS": "cpu"},
        )
    except Exception as e:  # noqa: BLE001
        extra["quorum_overlap"] = {"error": str(e)}

    # quorum fan-out p50/p99 vs group count (ISSUE 10 satellite — the
    # measurement the ROADMAP HA open item names, extended to 128/256 in
    # ISSUE 11 per the ROADMAP's explicit 256+ ask): N in-process
    # manager servers against one lighthouse, read off the PR 8 native
    # quorum.fanout latency histogram. Own process so the N-group
    # lathist never contaminates this process's step-anatomy row.
    try:
        extra.update(
            _run_json_subprocess(
                [sys.executable, "-m", "torchft_tpu.benchmarks.quorum_scale"],
                # 256 servers' worth of thread/boot time on a small box
                timeout_s=1200,
                env_extra={"JAX_PLATFORMS": "cpu"},
            )
        )
    except Exception as e:  # noqa: BLE001
        extra["quorum_scale"] = {"error": str(e)}

    # pipelined-vs-sync COMMIT barrier, same protocol as quorum_overlap:
    # 2 groups + a synthetic RTT on the should_commit RPC, interleaved
    # median-of-7 with spreads — the artifact behind commit_pipeline=True
    # (this PR's tentpole; speculative apply + rollback machinery live)
    try:
        extra["commit_pipeline"] = _run_json_subprocess(
            [sys.executable, "-m", "torchft_tpu.benchmarks.commit_pipeline"],
            timeout_s=900,
            env_extra={"JAX_PLATFORMS": "cpu"},
        )
    except Exception as e:  # noqa: BLE001
        extra["commit_pipeline"] = {"error": str(e)}

    # Always-on profiler overhead (ISSUE 12): the SAME headline leg
    # armed at default Hz vs disarmed, interleaved medians — acceptance
    # gate <=2%. Own process so the A/B toggling (and its samples) never
    # contaminate this process's ledger/lathist rows.
    try:
        extra.update(
            _run_json_subprocess(
                [
                    sys.executable, "-m",
                    "torchft_tpu.benchmarks.profiler_overhead",
                ],
                timeout_s=1200,
                env_extra={"JAX_PLATFORMS": "cpu"},
            )
        )
    except Exception as e:  # noqa: BLE001
        extra["profiler_overhead"] = {"error": str(e)}

    # REAL on-chip 2-group averaging: two processes time-sharing the chip
    # over the host plane (round-4 review weak #8). See the module
    # docstring for the two box constraints this row records.
    if on_tpu:
        try:
            extra["tpu_2group_hostplane"] = _run_json_subprocess(
                [sys.executable, "-m", "torchft_tpu.benchmarks.tpu_2group"],
                timeout_s=900,
            )
        except Exception as e:  # noqa: BLE001
            extra["tpu_2group_hostplane"] = {"error": str(e)}

    # DiLoCo 4-group effective cost (BASELINE.md target config): per-sync
    # seconds + amortized overhead over the host plane
    try:
        extra["diloco_4group"] = _run_json_subprocess(
            [sys.executable, "-m", "torchft_tpu.benchmarks.diloco"],
            timeout_s=600,
            env_extra={"JAX_PLATFORMS": "cpu"},
        )
    except Exception as e:  # noqa: BLE001
        extra["diloco_4group"] = {"error": str(e)}

    # REAL 2-group device-path averaging on a virtual 8-CPU mesh (round-2
    # review weak #1: the single-chip headline can't measure it)
    try:
        extra["cpu_mesh_2group"] = _run_json_subprocess(
            [sys.executable, "-m", "torchft_tpu.benchmarks.cpu_mesh_2group"],
            timeout_s=900,
            # pre-set the virtual-mesh env so the worker skips its re-exec
            # (a grandchild would outlive a group-kill on timeout)
            env_extra={
                "_TFT_CPU2G": "1",
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (
                    os.environ.get("XLA_FLAGS", "")
                    + " --xla_force_host_platform_device_count=8"
                ).strip(),
            },
        )
    except Exception as e:  # noqa: BLE001 — secondary metric, best-effort
        extra["cpu_mesh_2group"] = {"error": str(e)}

    # cross-PROCESS host data plane (the north-star multi-host topology):
    # serial vs pipelined vs bf16-wire, with derived llama2-7b cost
    try:
        extra["crossgroup_host_plane"] = _run_json_subprocess(
            [
                sys.executable,
                "-m",
                "torchft_tpu.benchmarks.crossgroup",
                "--total-mb",
                "128",
                "--rounds",
                "2",
            ],
            timeout_s=900,
        )
    except Exception as e:  # noqa: BLE001
        extra["crossgroup_host_plane"] = {"error": str(e)}

    # int8-compressed wire over the forced tcp-striped plane (serial +
    # streamed) — the wire-speed tentpole row, gated on gb_per_sec so a
    # codec/overlap regression fails loudly (docs/wire_plane.md)
    try:
        extra["crossgroup_compressed"] = _run_json_subprocess(
            [
                sys.executable,
                "-m",
                "torchft_tpu.benchmarks.crossgroup",
                "--compressed",
                "--total-mb",
                "128",
                "--rounds",
                "2",
            ],
            timeout_s=900,
        )
    except Exception as e:  # noqa: BLE001
        extra["crossgroup_compressed"] = {"error": str(e)}

    # recovery envelope (BASELINE.md driver metric): SIGKILL 1 of N replica
    # groups on CPU, measure blackout + rejoin. N=4 is the BASELINE
    # north-star shape; blackout is in *toy* step units (real training
    # steps are >= 10x longer, so "< 1 step" holds whenever a step
    # exceeds ~0.3 s).
    from torchft_tpu.benchmarks.recovery import measure_recovery

    for key, kwargs in (("recovery", {}), ("recovery_1of4", {"num_groups": 4})):
        try:
            extra[key] = measure_recovery(**kwargs).as_dict()
        except Exception as e:  # noqa: BLE001 — best-effort secondary metric
            extra[key] = {"error": str(e)}

    # Telemetry snapshot alongside the perf rows: the headline loop above
    # ran through the REAL instrumented Manager in this process, so the
    # snapshot records how much FT control traffic (quorums, heals,
    # allreduce bytes) and what step-time distribution produced these
    # numbers — perf trajectory and FT behavior land in one BENCH_*.json
    # row instead of needing a post-mortem rerun.
    try:
        from torchft_tpu import telemetry as _telemetry

        extra["telemetry"] = _telemetry.summary()
    except Exception as e:  # noqa: BLE001 — observability never fails bench
        extra["telemetry"] = {"error": str(e)}

    # The driver tail-captures stdout, so the COMPACT headline must be the
    # LAST line (round-3 verdict weak #1: the r03 headline was truncated
    # away by the verbose extras that followed it).  Verbose extras go to a
    # file and to an earlier stdout line; the final line is small enough to
    # always survive a tail capture.
    _apply_regression_gate(extra, sps)
    if extra.get("regressions"):
        print(
            json.dumps({"regression_gate": extra["regressions"]}),
            file=sys.stderr,
        )

    extra_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_extra.json")
    try:
        with open(extra_path, "w") as f:
            json.dump(extra, f, indent=1)
    except OSError:
        pass
    print(json.dumps({"extra": extra}))
    print(
        json.dumps(
            {
                "metric": "ft_transformer_train_steps_per_sec_per_chip",
                "value": round(sps, 4),
                "unit": f"steps/s (bf16 d512 L8 b{batch} s{seq}; "
                f"{tokens_per_sec:.0f} tok/s; single replica group, full "
                f"quorum+commit FT control per step; median of "
                f"{len(runs)} runs; extras on the previous line and in "
                f"bench_extra.json)",
                "vs_baseline": 1.0,
                "extra_keys": sorted(extra),
            }
        )
    )


if __name__ == "__main__":
    main()
