// Standalone lighthouse CLI — the torchft_lighthouse binary analogue
// (/root/reference/src/bin/lighthouse.rs:10-23). Flags mirror LighthouseOpt
// (src/lighthouse.rs:66-103) including defaults.
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "coord.h"

static void usage() {
  fprintf(stderr,
          "usage: tft_lighthouse --min_replicas N [--bind [::]:29510]\n"
          "  [--join_timeout_ms 60000] [--quorum_tick_ms 100]\n"
          "  [--heartbeat_timeout_ms 5000] [--evict_probe_ms 100]\n");
  exit(2);
}

int main(int argc, char** argv) {
  std::string bind = "[::]:29510";
  tft::LighthouseOpt opt;
  bool have_min = false;
  for (int i = 1; i < argc; i++) {
    auto need = [&](const char* flag) -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "%s requires a value\n", flag);
        usage();
      }
      return argv[++i];
    };
    if (!strcmp(argv[i], "--bind"))
      bind = need("--bind");
    else if (!strcmp(argv[i], "--min_replicas")) {
      opt.min_replicas = strtoull(need("--min_replicas"), nullptr, 10);
      have_min = true;
    } else if (!strcmp(argv[i], "--join_timeout_ms"))
      opt.join_timeout_ms = strtoull(need("--join_timeout_ms"), nullptr, 10);
    else if (!strcmp(argv[i], "--quorum_tick_ms"))
      opt.quorum_tick_ms = strtoull(need("--quorum_tick_ms"), nullptr, 10);
    else if (!strcmp(argv[i], "--heartbeat_timeout_ms"))
      opt.heartbeat_timeout_ms =
          strtoull(need("--heartbeat_timeout_ms"), nullptr, 10);
    else if (!strcmp(argv[i], "--evict_probe_ms"))
      opt.evict_probe_ms = strtoull(need("--evict_probe_ms"), nullptr, 10);
    else
      usage();
  }
  if (!have_min) usage();

  // Block SIGINT/SIGTERM before any server threads spawn so they inherit
  // the blocked mask and the signals reach sigwait instead of killing a
  // worker thread.
  sigset_t set;
  sigemptyset(&set);
  sigaddset(&set, SIGINT);
  sigaddset(&set, SIGTERM);
  sigprocmask(SIG_BLOCK, &set, nullptr);

  try {
    tft::Lighthouse lh(bind, opt);
    int sig = 0;
    sigwait(&set, &sig);
    lh.shutdown();
  } catch (const std::exception& e) {
    fprintf(stderr, "lighthouse failed: %s\n", e.what());
    return 1;
  }
  return 0;
}
