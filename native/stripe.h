// torchft_tpu native core — shared stripe layer.
//
// The framing and socket plumbing that used to live private to the
// gradient data plane (dataplane.cc): frame headers, poll-bounded
// small-message send/recv, socket tuning, and the deterministic stripe
// partition. Factored out so BOTH striped planes — the ring allreduce
// (dataplane.cc) and the checkpoint blob transfer (blob.cc) — speak one
// dialect: same header shape, same deadline semantics, same torn-frame
// failure mode (a cut connection surfaces as a short read, never as a
// short frame that could be mistaken for data).
#ifndef TFT_STRIPE_H_
#define TFT_STRIPE_H_

#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "rpc.h"  // now_ms / errno_str

namespace tft {
namespace stripeio {

// one frame on a stripe socket: {tag, payload length}; the payload
// follows immediately (dataplane hop frames and blob range replies both
// validate the echoed header before trusting a single payload byte)
struct HopHdr {
  uint32_t tag;
  uint32_t len;
};

constexpr int kSockBuf = 1 << 22;  // 4 MB: loopback throughput

inline void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

inline void tune_socket(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int buf = kSockBuf;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
}

// EAGAIN/EWOULDBLOCK may be the same value (they are on Linux) — the
// guard keeps the portable double-check without tripping -Wlogical-op
// in every nonblocking pump
inline bool err_wouldblock(int e) {
#if defined(EWOULDBLOCK) && EWOULDBLOCK != EAGAIN
  if (e == EWOULDBLOCK) return true;
#endif
  return e == EAGAIN;
}

// poll-bounded helpers for small control messages and bulk payloads on a
// nonblocking socket; both loop to the absolute deadline (now_ms clock)
inline bool send_all(int fd, const void* buf, size_t n, int64_t deadline_ms,
                     bool* timed_out, std::string* err) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = ::send(fd, (const uint8_t*)buf + off, n - off, MSG_NOSIGNAL);
    if (k > 0) {
      off += (size_t)k;
      continue;
    }
    if (k < 0 && err_wouldblock(errno)) {
      int64_t left = deadline_ms - now_ms();
      if (left <= 0) {
        *timed_out = true;
        *err = "send deadline exceeded";
        return false;
      }
      pollfd pfd{fd, POLLOUT, 0};
      ::poll(&pfd, 1, (int)(left > 200 ? 200 : left));
      continue;
    }
    *err = std::string("send: ") + (k == 0 ? "closed" : errno_str(errno));
    return false;
  }
  return true;
}

inline bool recv_all(int fd, void* buf, size_t n, int64_t deadline_ms,
                     bool* timed_out, std::string* err) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = ::recv(fd, (uint8_t*)buf + off, n - off, 0);
    if (k > 0) {
      off += (size_t)k;
      continue;
    }
    if (k < 0 && err_wouldblock(errno)) {
      int64_t left = deadline_ms - now_ms();
      if (left <= 0) {
        *timed_out = true;
        *err = "recv deadline exceeded";
        return false;
      }
      pollfd pfd{fd, POLLIN, 0};
      ::poll(&pfd, 1, (int)(left > 200 ? 200 : left));
      continue;
    }
    *err = std::string("recv: ") + (k == 0 ? "closed" : errno_str(errno));
    return false;
  }
  return true;
}

// Deterministic stripe partition of `nelems` elements into at most
// `nstripes` contiguous stripes, each boundary aligned down to `align`
// elements (the data plane uses 16 so reduce loops stay vectorizable and
// no stripe's chunk is pathologically small). bounds has nstripes+1
// entries; stripe s covers [bounds[s], bounds[s+1]).
inline std::vector<int64_t> stripe_bounds(int64_t nelems, int nstripes,
                                          int64_t align) {
  std::vector<int64_t> sb((size_t)nstripes + 1);
  for (int s = 0; s <= nstripes; ++s) {
    sb[(size_t)s] = ((nelems * s / nstripes) / align) * align;
  }
  sb[(size_t)nstripes] = nelems;
  return sb;
}

}  // namespace stripeio
}  // namespace tft

#endif  // TFT_STRIPE_H_
