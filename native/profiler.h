// torchft_tpu native core — always-on sampling profiler for the GIL-free
// planes (ISSUE 12).
//
// The Python-side telemetry can sample interpreter threads with
// sys._current_frames, but the hot native threads — the dp stripe pumps,
// the rpc serve loop, the blob range servers — never touch the
// interpreter, so until now "which code inside the slow phase" was
// unanswerable for exactly the threads that carry the bytes. This header
// is the Google-Wide-Profiler-shaped answer:
//
//   * threads REGISTER themselves once at entry (ThreadGuard — a handful
//     of stores; the per-hop hot path gains literally zero instructions);
//   * a single sampler thread ticks at TORCHFT_PROF_HZ (default
//     kDefaultHz, 0 = disarmed: no handler installed, no sampler thread,
//     no signals — zero cost) and tgkill()s each registered thread with
//     SIGPROF;
//   * the signal handler backtrace()s into a lock-free per-thread ring
//     (per-slot seqlock, every field an atomic — TSan-clean by
//     construction, async-signal-safe: backtrace is preloaded at arm
//     time so its lazy libgcc dlopen never runs in a handler);
//   * the sampler drains rings into a process-wide collapsed-stack
//     aggregate, rendered on demand as flamegraph-ready .folded text
//     ("label;root;...;leaf count") with dladdr+demangle symbolization;
//   * tft_prof_set_hz() retargets the rate live — the diagnosis engine
//     (telemetry/diagnosis.py) boosts to TORCHFT_PROF_BURST_HZ for a
//     bounded capture window, then restores.
//
// Signal-safety contract with the transport planes: every registered
// thread runs nonblocking sockets with EINTR-tolerant poll loops
// (stripe.h ignores poll's rc and re-checks the deadline; rpc.cc/blob.cc
// `continue` on EINTR), and the handler is installed SA_RESTART for the
// blocking-socket paths — a sample can delay a hop by microseconds but
// never fail it.
#ifndef TFT_PROFILER_H_
#define TFT_PROFILER_H_

#include <cxxabi.h>
#include <dlfcn.h>
#include <execinfo.h>
#include <pthread.h>
#include <signal.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>

namespace tft {
namespace prof {

constexpr int kMaxFrames = 24;
// backtrace()'s top frames are the handler itself + the kernel signal
// trampoline; the interrupted code starts below them
constexpr int kSkipFrames = 2;
constexpr int kMaxThreads = 64;
constexpr int kRing = 128;  // samples buffered per thread between drains
constexpr double kDefaultHz = 11.0;  // prime-ish: avoids lockstep with
                                     // 10ms schedulers and 100Hz ticks

struct Slot {
  // seqlock: even = stable, odd = handler writing. All payload fields
  // are relaxed atomics so a torn concurrent read is impossible (and
  // TSan sees no data race); seq validates consistency.
  std::atomic<uint32_t> seq{0};
  std::atomic<int> n{0};
  std::atomic<void*> pc[kMaxFrames];
};

struct ThreadRec {
  // 0 = free, 1 = claiming, 2 = active. Retired slots return to 0 after
  // the owner drains its own ring (unregister_thread), so churning
  // connection threads recycle the fixed table.
  std::atomic<int> state{0};
  std::atomic<long> tid{0};  // kernel tid (tgkill target; safe vs exit)
  char label[24] = {0};
  std::atomic<uint64_t> head{0};  // samples ever written by the handler
  uint64_t drained = 0;           // guarded-by: State::agg_mu
  Slot ring[kRing];
};

struct State {
  ThreadRec threads[kMaxThreads];
  std::atomic<double> hz{-1.0};      // -1 = env not parsed yet
  std::atomic<long> sampler_pid{0};  // pid owning the live sampler thread
  std::atomic<uint64_t> samples{0};  // drained into the aggregate
  std::atomic<uint64_t> dropped{0};  // ring overruns between drains
  std::atomic<uint64_t> table_full{0};  // threads that ran unprofiled
  std::mutex agg_mu;  // aggregate + every ring's drained cursor
  // collapsed-stack aggregate: key = label '\0' raw leaf-first pc array
  std::map<std::string, uint64_t> agg;
  std::mutex arm_mu;  // handler install + sampler start + hz writes
  bool handler_installed = false;
  bool atfork_installed = false;
};

inline State& S() {
  static State s;
  return s;
}

// The handler finds its own record by tid scan instead of a
// thread_local pointer: this library is dlopen'd, so a thread_local
// here would live in dynamic TLS — whose deallocation at thread reap
// TSan cannot pair with the thread's own last write (a hard false
// positive) — and a 64-entry atomic scan is both async-signal-safe and
// cheaper than it sounds (one pass per sample, not per hop).
inline ThreadRec* find_self() {
  // release-order(fn): (state, tid) acquire-loads pair with
  // register_thread's release publication — the record's fields are
  // fully written before state flips to 2
  long tid = (long)syscall(SYS_gettid);
  State& st = S();
  for (int i = 0; i < kMaxThreads; ++i) {
    ThreadRec& r = st.threads[i];
    if (r.state.load(std::memory_order_acquire) == 2 &&
        r.tid.load(std::memory_order_acquire) == tid)
      return &r;
  }
  return nullptr;
}

// ---- signal handler (async-signal-safe: backtrace preloaded, atomics
// only) ---------------------------------------------------------------------

inline void sig_handler(int, siginfo_t*, void*) {
  int saved_errno = errno;
  ThreadRec* r = find_self();
  if (!r) {
    errno = saved_errno;
    return;  // unregistered thread (tid recycling race): ignore
  }
  void* buf[kMaxFrames + kSkipFrames];
  int n = ::backtrace(buf, kMaxFrames + kSkipFrames);
  int keep = n - kSkipFrames;
  if (keep < 0) keep = 0;
  if (keep > kMaxFrames) keep = kMaxFrames;
  // relaxed-ok(fn): single-writer seqlock write side — the explicit
  // release fence below orders the payload stores, and the even-seq +
  // head release-stores publish the slot (see the seqlock comment)
  uint64_t h = r->head.load(std::memory_order_relaxed);
  Slot& s = r->ring[h % kRing];
  uint32_t q = s.seq.load(std::memory_order_relaxed);
  // standard seqlock writer: the odd store must be ordered BEFORE the
  // payload stores (a release store only orders what precedes it), so
  // the barrier between them is an explicit release fence — without it
  // a weakly-ordered CPU could publish new frames under an old even
  // seq and a concurrent drain would validate a mixed-generation stack
  s.seq.store(q + 1, std::memory_order_relaxed);  // odd: writing
  std::atomic_thread_fence(std::memory_order_release);
  for (int i = 0; i < keep; ++i)
    s.pc[i].store(buf[i + kSkipFrames], std::memory_order_relaxed);
  s.n.store(keep, std::memory_order_relaxed);
  s.seq.store(q + 2, std::memory_order_release);  // even: stable
  r->head.store(h + 1, std::memory_order_release);
  errno = saved_errno;
}

// ---- drain (under State::agg_mu) ------------------------------------------

inline void drain_ring_locked(ThreadRec& r) {
  // release-order(fn): seqlock read side — the head/seq acquire-loads
  // pair with the handler's release stores; the relaxed payload loads
  // are validated by the seq re-check under the acquire fence (a torn
  // read fails the re-check and the slot is skipped)
  State& st = S();
  uint64_t head = r.head.load(std::memory_order_acquire);
  if (head > r.drained + kRing) {
    st.dropped.fetch_add(head - r.drained - kRing,
                         std::memory_order_relaxed);
    r.drained = head - kRing;
  }
  for (uint64_t i = r.drained; i < head; ++i) {
    Slot& s = r.ring[i % kRing];
    uint32_t q1 = s.seq.load(std::memory_order_acquire);
    if (q1 & 1) continue;  // handler mid-write (wrap race): skip
    void* pcs[kMaxFrames];
    int n = s.n.load(std::memory_order_relaxed);
    if (n < 0 || n > kMaxFrames) continue;
    for (int j = 0; j < n; ++j)
      pcs[j] = s.pc[j].load(std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s.seq.load(std::memory_order_relaxed) != q1) continue;  // torn
    std::string key(r.label);
    key.push_back('\0');
    key.append(reinterpret_cast<const char*>(pcs),
               (size_t)n * sizeof(void*));
    st.agg[key]++;
    st.samples.fetch_add(1, std::memory_order_relaxed);
  }
  r.drained = head;
}

inline void drain_all_locked() {
  State& st = S();
  for (int i = 0; i < kMaxThreads; ++i) {
    ThreadRec& r = st.threads[i];
    // release-order: state==2 pairs with register_thread's publication
    if (r.state.load(std::memory_order_acquire) == 2) drain_ring_locked(r);
  }
}

// ---- sampler ---------------------------------------------------------------

inline void sampler_loop() {
  // release-order(fn): sampler_pid/hz/state/tid acquire-loads pair with
  // the release stores in ensure_running/set_hz/register_thread; a
  // stale read only delays one tick or skips one retiring thread
  State& st = S();
  const long pid = (long)getpid();
  for (;;) {
    if (st.sampler_pid.load(std::memory_order_acquire) != pid)
      return;  // superseded (fork) — the owning pid runs its own loop
    double hz = st.hz.load(std::memory_order_acquire);
    if (hz <= 0) {
      // paused (set_hz(0)): stay alive so a later boost resumes instantly
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
      continue;
    }
    for (int i = 0; i < kMaxThreads; ++i) {
      ThreadRec& r = st.threads[i];
      if (r.state.load(std::memory_order_acquire) != 2) continue;
      long tid = r.tid.load(std::memory_order_acquire);
      if (tid > 0) syscall(SYS_tgkill, pid, tid, SIGPROF);
    }
    double period = 1.0 / hz;
    if (period < 0.001) period = 0.001;  // 1 kHz ceiling
    std::this_thread::sleep_for(std::chrono::duration<double>(period));
    {
      std::lock_guard<std::mutex> g(st.agg_mu);
      drain_all_locked();
    }
  }
}

inline double env_hz() {
  const char* v = std::getenv("TORCHFT_PROF_HZ");
  if (!v || !*v) return kDefaultHz;
  return std::atof(v);
}

// fork safety: the sampler thread does not survive fork, and agg_mu must
// not be held across it (a child forked mid-drain would deadlock on its
// first snapshot). Registered once, at first arm.
inline void atfork_prepare() {
  S().arm_mu.lock();
  S().agg_mu.lock();
}
inline void atfork_release() {
  S().agg_mu.unlock();
  S().arm_mu.unlock();
}

inline void ensure_running() {
  // release-order(fn): double-checked arm — the relaxed re-read of
  // sampler_pid runs under arm_mu (the mutex is the ordering there),
  // and the pid release-store publishes handler install before the
  // sampler thread's first acquire-load of it
  State& st = S();
  if (st.hz.load(std::memory_order_acquire) <= 0) return;  // disarmed
  const long pid = (long)getpid();
  if (st.sampler_pid.load(std::memory_order_acquire) == pid) return;
  std::lock_guard<std::mutex> g(st.arm_mu);
  if (st.sampler_pid.load(std::memory_order_relaxed) == pid) return;
  if (!st.atfork_installed) {
    pthread_atfork(atfork_prepare, atfork_release, atfork_release);
    st.atfork_installed = true;
  }
  if (!st.handler_installed) {
    // preload backtrace's lazy libgcc_s dlopen OUTSIDE signal context
    void* warm[2];
    ::backtrace(warm, 2);
    struct sigaction sa;
    std::memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = sig_handler;
    sa.sa_flags = SA_RESTART | SA_SIGINFO;
    sigemptyset(&sa.sa_mask);
    sigaction(SIGPROF, &sa, nullptr);
    st.handler_installed = true;
  }
  // ownership flag BEFORE the spawn: the loop's first act is to check
  // it, and a fresh thread can win that race against a late store
  st.sampler_pid.store(pid, std::memory_order_release);
  std::thread(sampler_loop).detach();
}

inline void maybe_arm() {
  // release-order(fn): double-checked hz arm — the relaxed re-read runs
  // under arm_mu; the release store publishes env_hz to the acquire
  // readers (current_hz, sampler_loop)
  State& st = S();
  if (st.hz.load(std::memory_order_acquire) < 0) {
    std::lock_guard<std::mutex> g(st.arm_mu);
    if (st.hz.load(std::memory_order_relaxed) < 0)
      st.hz.store(env_hz(), std::memory_order_release);
  }
  ensure_running();
}

inline double current_hz() {
  // release-order: pairs with set_hz/maybe_arm release stores
  double hz = S().hz.load(std::memory_order_acquire);
  return hz < 0 ? 0.0 : hz;
}

inline void set_hz(double hz) {
  // release-order: publishes hz to the sampler/arm acquire loads
  S().hz.store(hz, std::memory_order_release);
  if (hz > 0) ensure_running();
}

// ---- thread registration ---------------------------------------------------

inline ThreadRec* register_thread(const char* label) {
  // release-order(fn): the slot-claim CAS (acq_rel: pairs with
  // unregister's release of state=0) and the relaxed ring scrub all
  // happen-before the tid/state release publication that find_self and
  // the sampler acquire-pair with
  maybe_arm();
  State& st = S();
  for (int i = 0; i < kMaxThreads; ++i) {
    ThreadRec& r = st.threads[i];
    int expect = 0;
    if (!r.state.compare_exchange_strong(expect, 1,
                                         std::memory_order_acq_rel))
      continue;
    std::snprintf(r.label, sizeof(r.label), "%s", label);
    // scrub the previous tenant's ring so stale seq parity / samples
    // can't leak into this thread's stacks
    for (int j = 0; j < kRing; ++j) {
      r.ring[j].seq.store(0, std::memory_order_relaxed);
      r.ring[j].n.store(0, std::memory_order_relaxed);
    }
    r.head.store(0, std::memory_order_relaxed);
    {
      std::lock_guard<std::mutex> g(st.agg_mu);
      r.drained = 0;
    }
    r.tid.store((long)syscall(SYS_gettid), std::memory_order_release);
    r.state.store(2, std::memory_order_release);
    return &r;
  }
  // table full: this thread runs unprofiled — counted, and surfaced as
  // a synthetic line in every snapshot (caps must be LOUD: a flamegraph
  // with silently-partial coverage reads as "that plane isn't hot")
  st.table_full.fetch_add(1, std::memory_order_relaxed);
  return nullptr;
}

inline void unregister_thread(ThreadRec* r) {
  // release-order(fn): tid clear + state=0 release-publish the
  // retirement (the next register's acq_rel CAS pairs with state); see
  // the in-flight-SIGPROF comment below
  if (!r) return;
  // an in-flight SIGPROF to this thread stops matching once the tid
  // clears (a handler interrupting THIS function sees either the old
  // tid — sample lands in the ring we are about to drain — or no match)
  r->tid.store(0, std::memory_order_release);
  State& st = S();
  {
    // the owner drains its own tail so no samples are lost and the slot
    // can be recycled immediately (the sampler's drains serialize on the
    // same mutex)
    std::lock_guard<std::mutex> g(st.agg_mu);
    drain_ring_locked(*r);
  }
  r->state.store(0, std::memory_order_release);
}

struct ThreadGuard {
  explicit ThreadGuard(const char* label)
      : rec_(register_thread(label)) {}
  ~ThreadGuard() { unregister_thread(rec_); }
  ThreadGuard(const ThreadGuard&) = delete;
  ThreadGuard& operator=(const ThreadGuard&) = delete;

 private:
  ThreadRec* rec_;
};

// ---- snapshot / render -----------------------------------------------------

inline std::string symbolize(void* pc) {
  static std::mutex mu;
  static std::map<void*, std::string> cache;
  std::lock_guard<std::mutex> g(mu);
  auto it = cache.find(pc);
  if (it != cache.end()) return it->second;
  std::string name;
  Dl_info info;
  if (dladdr(pc, &info) && info.dli_sname) {
    int status = 0;
    char* dem =
        abi::__cxa_demangle(info.dli_sname, nullptr, nullptr, &status);
    if (status == 0 && dem) {
      name = dem;
      // folded format separators must not appear inside a frame name
      for (char& c : name)
        if (c == ';') c = ':';
      std::free(dem);
    } else {
      name = info.dli_sname;
      if (dem) std::free(dem);
    }
  } else if (dladdr(pc, &info) && info.dli_fname) {
    const char* base = std::strrchr(info.dli_fname, '/');
    char buf[128];
    std::snprintf(buf, sizeof(buf), "%s+0x%zx",
                  base ? base + 1 : info.dli_fname,
                  (size_t)((char*)pc - (char*)info.dli_fbase));
    name = buf;
  } else {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "0x%zx", (size_t)pc);
    name = buf;
  }
  cache[pc] = name;
  return name;
}

// Flamegraph-ready collapsed stacks: one line per unique
// (thread label, stack), root-first frames, space, count. Deterministic
// order (sorted keys) so snapshot diffs are stable.
inline std::string snapshot_folded() {
  State& st = S();
  std::lock_guard<std::mutex> g(st.agg_mu);
  drain_all_locked();
  std::ostringstream o;
  for (const auto& [key, cnt] : st.agg) {
    size_t z = key.find('\0');
    if (z == std::string::npos) continue;
    o << key.substr(0, z);
    const char* raw = key.data() + z + 1;
    size_t n = (key.size() - z - 1) / sizeof(void*);
    // pcs are leaf-first (backtrace order); folded wants root-first
    for (size_t i = n; i > 0; --i) {
      void* pc;
      std::memcpy(&pc, raw + (i - 1) * sizeof(void*), sizeof(void*));
      o << ";" << symbolize(pc);
    }
    o << " " << cnt << "\n";
  }
  // loud-cap meta lines: coverage gaps travel WITH the evidence they
  // degrade (a bundle consumer or flamegraph reader sees them inline)
  // relaxed-ok: monotonic stat counters, no ordering needed
  uint64_t tf = st.table_full.load(std::memory_order_relaxed);
  if (tf) o << "_prof.meta;unprofiled_threads_table_full " << tf << "\n";
  // relaxed-ok: monotonic stat counter, no ordering needed
  uint64_t dr = st.dropped.load(std::memory_order_relaxed);
  if (dr) o << "_prof.meta;samples_dropped_ring_overrun " << dr << "\n";
  return o.str();
}

inline uint64_t samples_total() {
  // relaxed-ok: monotonic stat counter, no ordering needed
  return S().samples.load(std::memory_order_relaxed);
}

inline void reset() {
  // relaxed-ok(fn): the counter clears run under agg_mu (the mutex is
  // the ordering); the state/head acquire-loads pair with the
  // registration/handler release stores
  State& st = S();
  std::lock_guard<std::mutex> g(st.agg_mu);
  // fast-forward every cursor so buffered-but-undrained samples from
  // before the reset can't resurface in the next snapshot
  for (int i = 0; i < kMaxThreads; ++i) {
    ThreadRec& r = st.threads[i];
    if (r.state.load(std::memory_order_acquire) == 2)
      r.drained = r.head.load(std::memory_order_acquire);
  }
  st.agg.clear();
  st.samples.store(0, std::memory_order_relaxed);
  st.dropped.store(0, std::memory_order_relaxed);
  st.table_full.store(0, std::memory_order_relaxed);
}

}  // namespace prof
}  // namespace tft

#endif  // TFT_PROFILER_H_
