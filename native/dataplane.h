// torchft_tpu native core — striped cross-process gradient data plane.
//
// The role NCCL plays for the reference's cross-replica-group gradient
// averaging (/root/reference/torchft/process_group.py:431-447): a
// line-rate, GIL-free allreduce between OS processes. Python's TCP ring
// (torchft_tpu/collectives.py) tops out well under loopback line rate —
// every hop pays Python thread creation, GIL handoffs, and interpreted
// framing — so the HOT DATA PATH lives here: persistent per-stripe worker
// threads drive a ring allreduce over N parallel sockets per peer with
// nonblocking full-duplex pumps, f32 accumulate, and optional bf16 wire
// encoding, all without touching the interpreter. Rendezvous, epochs,
// tags and fallback ops stay in Python (collectives.py) — this plane is
// reconfigured by constructing a fresh instance per quorum epoch.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace tft {

// element dtypes on the local buffer
enum class DpDtype : int { kF32 = 0 };
// reduce ops (AVG divides after the allgather phase)
enum class DpOp : int { kSum = 0, kAvg = 1, kMax = 2, kMin = 3 };
// wire codecs (torchft_tpu/wire_codec.py mirrors these formats byte for
// byte; values must match the ctypes binding's NativeDataPlane.CODEC):
//   kF32  — raw 4 bytes/elem
//   kBf16 — round-to-nearest-even truncation, 2 bytes/elem
//   kInt8 — per-chunk symmetric quantization: a 4-byte LE f32 scale
//           header (max|x|/127; NaN when the chunk holds non-finite
//           values so NaN propagates loudly) + one int8 per element
enum class DpCodec : int { kF32 = 0, kBf16 = 1, kInt8 = 2 };

class DataPlane {
 public:
  // Listens on an ephemeral port and starts the acceptor + stripe workers.
  // Throws std::runtime_error on bind failure.
  DataPlane(int rank, int world, int nstripes);
  ~DataPlane();

  DataPlane(const DataPlane&) = delete;
  DataPlane& operator=(const DataPlane&) = delete;

  int port() const { return port_; }

  // Dial all stripe sockets to a lower-ranked peer (higher ranks dial
  // lower, mirroring the Python plane's convention). Returns false + err.
  bool connect_peer(int peer, const std::string& host, int port,
                    int64_t timeout_ms, std::string* err);

  // Block until every peer has all nstripes sockets established.
  bool wait_ready(int64_t timeout_ms, std::string* err);

  // Switch payload transport to cross-memory attach (process_vm_readv):
  // ring hops exchange tiny {tag,len,addr} descriptors + acks over the
  // stripe sockets and pull the payload straight out of the left
  // neighbor's address space — one copy at memcpy speed, no loopback-TCP
  // syscall tax. Caller (Python rendezvous) must have verified every rank
  // is same-host and CMA-capable (token-checked probe); pids is indexed
  // by ring rank. The wire codec is bypassed (payloads stay exact f32 —
  // deterministic since the chunk owner's bytes are distributed verbatim).
  void enable_cma(const std::vector<int64_t>& pids);

  // In-place ring allreduce of nelems f32 starting at data. Blocking;
  // returns 0 on success, -1 on socket failure with *bad_peer set to the
  // ring rank whose socket failed (or -1 if indeterminate), or -2 on
  // DEADLINE with *bad_peer = -1 — a slow-but-alive peer must surface as
  // a retryable timeout, never as an eviction-worthy accusation (the
  // Python mesh draws the same line). With a lossy codec the wire
  // carries encoded bytes while accumulation stays f32; the allgather
  // phase forwards the chunk owner's wire bytes VERBATIM, so the decoded
  // average is bit-identical on every rank by construction.
  int allreduce(void* data, int64_t nelems, DpDtype dtype, DpOp op,
                DpCodec codec, uint32_t tag, int64_t timeout_ms,
                int* bad_peer, std::string* err);

  void shutdown();

 private:
  struct Job {
    uint8_t* base = nullptr;   // stripe start
    int64_t nelems = 0;        // stripe elements
    DpOp op = DpOp::kSum;
    DpCodec codec = DpCodec::kF32;
    uint32_t tag = 0;
    int64_t deadline_ms = 0;  // absolute, now_ms() clock
  };
  struct Stripe {
    std::thread worker;
    std::mutex mu;
    std::condition_variable cv;
    bool has_job = false;
    bool done = false;
    Job job;
    int rc = 0;
    int bad_peer = -1;
    std::string err;
    // per-epoch wire scratch (vectors keep their capacity across jobs,
    // so the hot path never allocates after the first round)
    std::vector<uint8_t> scratch_send;  // wire-encoded outgoing chunk
    std::vector<uint8_t> scratch_recv;  // wire-encoded incoming chunk
    std::vector<uint8_t> scratch_fwd;   // verbatim-forward double buffer
  };

  void accept_loop();
  void hello_handshake(int fd, uint64_t id);
  void worker_loop(int stripe_idx);
  int run_stripe(int stripe_idx, Job& job, int* bad_peer, std::string* err);
  bool hop(int send_fd, int recv_fd, const uint8_t* sbuf, size_t sn,
           uint8_t* rbuf, size_t rn, uint32_t tag, int64_t deadline_ms,
           bool* send_failed, bool* timed_out, std::string* err);
  bool cma_hop(int send_fd, int recv_fd, const uint8_t* sbuf, size_t sn,
               uint8_t* rbuf, size_t rn, uint32_t tag, int64_t deadline_ms,
               bool* send_failed, bool* timed_out, std::string* err);
  int fd_for(int peer, int stripe);

  int rank_;
  int world_;
  int nstripes_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::thread acceptor_;
  std::atomic<bool> closed_{false};

  std::mutex socks_mu_;
  std::condition_variable socks_cv_;
  // socks_[peer][stripe] = fd (or -1)
  std::map<int, std::vector<int>> socks_;

  std::vector<std::unique_ptr<Stripe>> stripes_;

  // atomic publication flag: enable_cma() runs on the Python control
  // thread AFTER the stripe workers (started in the constructor) are
  // already live — peer_pids_ is written first, then cma_ is
  // store(release)d, and the workers' load(acquire) in run_stripe/
  // cma_hop makes the pids visible. A plain bool here is a data race
  // (the publication relied on the job-queue mutex by accident).
  std::atomic<bool> cma_{false};
  std::vector<int64_t> peer_pids_;  // published by cma_ release-store

  // hello handshakes run off the accept thread so one stalled dial can't
  // starve every other peer's stripe connections during rendezvous;
  // finished threads announce their id and the accept loop reaps them
  std::mutex hello_mu_;
  std::map<uint64_t, std::thread> hello_threads_;
  std::vector<uint64_t> hello_finished_;
  uint64_t next_hello_id_ = 0;
  std::set<int> hello_fds_;  // in-flight, shut down on close
};

}  // namespace tft
