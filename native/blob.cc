// torchft_tpu native core — striped checkpoint blob plane.
// See blob.h for the protocol and staging contract.

#include "blob.h"

#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>

#include "faultinject.h"  // env-gated injection (torn serve, serve kill)
#include "profiler.h"     // always-on sampling (blob serve thread stacks)
#include "rpc.h"          // tcp_listen / tcp_connect / listen_port / now_ms
#include "stripe.h"       // shared stripe framing/socket plumbing

namespace tft {

namespace {

// serve-side request deadline: one range on loopback/DCN completes in
// well under this; a wedged healer is kicked off its socket by unstage()
// long before the deadline matters
constexpr int64_t kServeTimeoutMs = 120000;
constexpr int64_t kIdleTimeoutMs = 30000;

// process-wide serve counter for the env-gated injection points (same
// process-stable coordinate scheme as the data plane's hop counters)
std::atomic<long> g_fi_blob_serves{0};

}  // namespace

BlobServer::BlobServer() {
  std::string err;
  listen_fd_ = tcp_listen("[::]:0", &err);
  if (listen_fd_ < 0) {
    throw std::runtime_error("blob listen failed: " + err);
  }
  port_ = listen_port(listen_fd_);
  acceptor_ = std::thread([this] { accept_loop(); });
}

BlobServer::~BlobServer() { shutdown(); }

void BlobServer::shutdown() {
  bool was = closed_.exchange(true);
  if (was) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
  }
  {
    std::lock_guard<std::mutex> g(mu_);
    staged_ = false;
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    cv_.notify_all();
  }
  if (acceptor_.joinable()) acceptor_.join();
  for (;;) {
    std::thread t;
    {
      std::lock_guard<std::mutex> g(mu_);
      if (conn_threads_.empty()) break;
      auto it = conn_threads_.begin();
      t = std::move(it->second);
      conn_threads_.erase(it);
    }
    if (t.joinable()) t.join();
  }
  listen_fd_ = -1;
}

void BlobServer::accept_loop() {
  uint64_t next_id = 0;
  while (!closed_.load()) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (closed_.load()) return;
      if (errno == EINTR) continue;
      return;  // listener closed
    }
    stripeio::tune_socket(fd);
    stripeio::set_nonblock(fd);
    // reap finished handlers before spawning the next: one-shot range
    // connections finish fast, so the announced-finished list keeps the
    // map from growing across many heals (joins here never block long —
    // a finished id's thread is past its serve loop)
    std::vector<std::thread> reap;
    {
      std::lock_guard<std::mutex> g(mu_);
      if (closed_.load()) {
        ::close(fd);
        return;
      }
      for (uint64_t done_id : conn_finished_) {
        auto it = conn_threads_.find(done_id);
        if (it != conn_threads_.end()) {
          reap.push_back(std::move(it->second));
          conn_threads_.erase(it);
        }
      }
      conn_finished_.clear();
      uint64_t id = next_id++;
      conn_fds_.insert(fd);
      conn_threads_.emplace(
          id, std::thread([this, fd, id] { serve_conn(fd, id); }));
    }
    for (auto& t : reap) {
      if (t.joinable()) t.join();
    }
  }
}

void BlobServer::serve_conn(int fd, uint64_t id) {
  prof::ThreadGuard prof_guard("blob.serve");
  for (;;) {
    BlobReq req{};
    bool timed_out = false;
    std::string err;
    if (!stripeio::recv_all(fd, &req, sizeof(req),
                            now_ms() + kIdleTimeoutMs, &timed_out, &err) ||
        req.magic != kBlobMagic) {
      break;  // client done (EOF), garbage, or idle
    }
    if (!serve_one(fd, req, now_ms() + kServeTimeoutMs, &err)) break;
  }
  std::lock_guard<std::mutex> g(mu_);
  conn_fds_.erase(fd);
  ::close(fd);
  conn_finished_.push_back(id);  // the accept loop joins us later
}

bool BlobServer::serve_one(int fd, const BlobReq& req, int64_t deadline_ms,
                           std::string* err) {
  // env-gated injection (docs/fault_injection.md): SIGKILL on the nth
  // range serve this process runs (stripe-serving peer death mid-heal —
  // the stripe_heal_peer_death scenario), or promise the full length and
  // cut after a fraction (torn stripe serve; the healer must see a short
  // read, never short data)
  static const long fi_kill = fi::parse_long("TORCHFT_FI_BLOB_KILL");
  static const fi::NthSpec fi_cut = fi::parse_nth("TORCHFT_FI_BLOB_CUT");
  long fi_h = 0;
  if (fi_kill > 0 || fi_cut.nth > 0) fi_h = ++g_fi_blob_serves;
  if (fi_kill > 0 && fi_h == fi_kill) fi::kill_self("blob.serve", fi_h);

  // snapshot the staged layout + verdict under the lock, pin the
  // buffers with active_serves_ (unstage waits it out before the caller
  // may free). NO socket IO under mu_: a stalled client would otherwise
  // hold the mutex against stage()/unstage() — the quorum-critical path
  // — for up to the serve deadline, and the unstage kick itself needs
  // mu_ (the blocking-under-lock class the repo's own lint forbids).
  std::vector<uint64_t> bases;
  std::vector<int64_t> lens;
  std::vector<uint64_t> prefix;
  BlobStatus verdict = BlobStatus::kOk;
  {
    std::lock_guard<std::mutex> g(mu_);
    if (!staged_ || req.token != token_) {
      verdict = BlobStatus::kStale;
    } else if (req.len == 0 || req.offset > total_ ||
               req.len > total_ - req.offset) {
      verdict = BlobStatus::kBadRange;
    } else {
      bases = bases_;
      lens = lens_;
      prefix = prefix_;
      ++active_serves_;
    }
  }
  if (verdict != BlobStatus::kOk) {
    BlobRsp rsp{kBlobMagic, (uint32_t)verdict, 0};
    bool to = false;
    return stripeio::send_all(fd, &rsp, sizeof(rsp), deadline_ms, &to, err);
  }

  bool ok = true;
  {
    bool timed_out = false;
    BlobRsp rsp{kBlobMagic, (uint32_t)BlobStatus::kOk, req.len};
    ok = stripeio::send_all(fd, &rsp, sizeof(rsp), deadline_ms, &timed_out,
                            err);
    // torn-serve budget: full header already sent, cut after frac bytes
    uint64_t budget = req.len;
    bool torn = false;
    if (ok && fi_cut.nth > 0 && fi_h == fi_cut.nth) {
      budget = (uint64_t)((double)req.len * fi_cut.frac);
      torn = true;
      fi::write_evidence("blob.serve", fi_h, "torn");
    }
    // walk the scattered buffers overlapping [offset, offset+len)
    uint64_t off = req.offset;
    uint64_t remaining = req.len;
    size_t i = (size_t)(std::upper_bound(prefix.begin(), prefix.end(), off) -
                        prefix.begin()) - 1;
    while (ok && remaining > 0 && budget > 0 && i < bases.size()) {
      uint64_t in_buf = off - prefix[i];
      uint64_t avail = (uint64_t)lens[i] - in_buf;
      uint64_t n = std::min(remaining, avail);
      n = std::min(n, budget);
      if (n > 0) {
        ok = stripeio::send_all(fd, (const void*)(uintptr_t)(bases[i] + in_buf),
                                (size_t)n, deadline_ms, &timed_out, err);
        off += n;
        remaining -= n;
        budget -= n;
      }
      if (in_buf + n >= (uint64_t)lens[i]) ++i;
    }
    if (torn) {
      // hard-cut mid-body, exactly like the serving process dying: the
      // client's recv must fail the range, never accept a short one
      ::shutdown(fd, SHUT_RDWR);
      ok = false;
      *err = "fault injection: torn blob serve";
    }
  }
  {
    std::lock_guard<std::mutex> g(mu_);
    --active_serves_;
    cv_.notify_all();
  }
  return ok;
}

void BlobServer::stage(const uint64_t* bases, const int64_t* lens, int nbufs,
                       uint64_t token) {
  std::unique_lock<std::mutex> g(mu_);
  // a restage must never swap the layout under an in-flight serve (the
  // old buffers may be freed the moment this returns): close the window
  // first, kick live connections, and wait the serves out
  staged_ = false;
  for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  cv_.wait(g, [&] { return active_serves_ == 0; });
  bases_.assign(bases, bases + nbufs);
  lens_.assign(lens, lens + nbufs);
  prefix_.resize((size_t)nbufs);
  uint64_t acc = 0;
  for (int i = 0; i < nbufs; ++i) {
    prefix_[(size_t)i] = acc;
    acc += (uint64_t)lens[i];
  }
  total_ = acc;
  token_ = token;
  staged_ = true;
}

void BlobServer::unstage() {
  std::unique_lock<std::mutex> g(mu_);
  if (!staged_ && active_serves_ == 0) return;
  staged_ = false;
  // in-flight payload sends still read the staged buffers: kick them off
  // their sockets so the wait below is bounded by a failed send, not by
  // a slow healer's timeout
  for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  cv_.wait(g, [&] { return active_serves_ == 0; });
}

int blob_fetch(const std::string& host, int port, uint64_t token,
               uint64_t offset, uint64_t len, void* dst, int64_t timeout_ms,
               std::string* err) {
  int64_t deadline = now_ms() + timeout_ms;
  int fd = tcp_connect(host, port, timeout_ms, err);
  if (fd < 0) return -1;
  stripeio::tune_socket(fd);
  stripeio::set_nonblock(fd);
  bool timed_out = false;
  int rc = -1;
  do {
    BlobReq req{kBlobMagic, 0, token, offset, len};
    if (!stripeio::send_all(fd, &req, sizeof(req), deadline, &timed_out, err))
      break;
    BlobRsp rsp{};
    if (!stripeio::recv_all(fd, &rsp, sizeof(rsp), deadline, &timed_out, err))
      break;
    if (rsp.magic != kBlobMagic) {
      *err = "blob: bad reply magic";
      break;
    }
    if (rsp.status != (uint32_t)BlobStatus::kOk) {
      *err = rsp.status == (uint32_t)BlobStatus::kStale
                 ? "blob: stale token (checkpoint window closed)"
                 : "blob: bad range";
      break;
    }
    if (rsp.len != len) {
      *err = "blob: length mismatch";
      break;
    }
    if (!stripeio::recv_all(fd, dst, (size_t)len, deadline, &timed_out, err))
      break;
    rc = 0;
  } while (false);
  ::close(fd);
  if (rc != 0 && timed_out) return -2;
  return rc;
}

}  // namespace tft

// ---- C ABI for ctypes ------------------------------------------------------

namespace {

std::mutex g_blob_mu;
int64_t g_blob_next = 1;
std::map<int64_t, std::shared_ptr<tft::BlobServer>> g_blobs;

std::shared_ptr<tft::BlobServer> blob_get(int64_t h) {
  std::lock_guard<std::mutex> g(g_blob_mu);
  auto it = g_blobs.find(h);
  return it == g_blobs.end() ? nullptr : it->second;
}

void blob_set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    strncpy(err, msg.c_str(), (size_t)errlen - 1);
    err[errlen - 1] = '\0';
  }
}

}  // namespace

extern "C" {

int64_t tft_blob_serve_create(char* err, int errlen) {
  try {
    auto srv = std::make_shared<tft::BlobServer>();
    std::lock_guard<std::mutex> g(g_blob_mu);
    int64_t h = g_blob_next++;
    g_blobs[h] = std::move(srv);
    return h;
  } catch (const std::exception& e) {
    blob_set_err(err, errlen, e.what());
    return 0;
  }
}

int tft_blob_serve_port(int64_t h) {
  auto srv = blob_get(h);
  return srv ? srv->port() : -1;
}

int tft_blob_stage(int64_t h, const uint64_t* bases, const int64_t* lens,
                   int nbufs, uint64_t token, char* err, int errlen) {
  auto srv = blob_get(h);
  if (!srv) {
    blob_set_err(err, errlen, "bad handle");
    return -1;
  }
  srv->stage(bases, lens, nbufs, token);
  return 0;
}

int tft_blob_unstage(int64_t h) {
  auto srv = blob_get(h);
  if (!srv) return -1;
  srv->unstage();
  return 0;
}

void tft_blob_serve_free(int64_t h) {
  std::shared_ptr<tft::BlobServer> srv;
  {
    std::lock_guard<std::mutex> g(g_blob_mu);
    auto it = g_blobs.find(h);
    if (it == g_blobs.end()) return;
    srv = std::move(it->second);
    g_blobs.erase(it);
  }
  srv->shutdown();
}

int tft_blob_fetch(const char* host, int port, uint64_t token,
                   uint64_t offset, uint64_t len, void* dst,
                   int64_t timeout_ms, char* err, int errlen) {
  std::string e;
  int rc = tft::blob_fetch(host ? host : "", port, token, offset, len, dst,
                           timeout_ms, &e);
  if (rc != 0) blob_set_err(err, errlen, e);
  return rc;
}

}  // extern "C"
