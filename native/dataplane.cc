// torchft_tpu native core — striped cross-process gradient data plane.
// See dataplane.h for the design rationale.

#include "dataplane.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <chrono>
#include <cmath>
#include <cstring>
#include <limits>
#include <stdexcept>

#include "blackbox.h"     // crash-durable dp.hop / dp.stripe breadcrumbs
#include "faultinject.h"  // env-gated injection points (torn hops, kills)
#include "lathist.h"      // dp.hop / dp.stripe latency histograms
#include "profiler.h"     // always-on sampling (dp pump thread stacks)
#include "rpc.h"  // tcp_listen / tcp_connect / listen_port / now_ms
#include "stripe.h"  // shared stripe framing/partition (also used by blob.cc)

namespace tft {

// the shared stripe layer owns the framing/socket plumbing both striped
// planes (allreduce + checkpoint blob) speak — see stripe.h
using stripeio::err_wouldblock;
using stripeio::HopHdr;
using stripeio::set_nonblock;
using stripeio::tune_socket;

namespace {

constexpr uint32_t kHelloMagic = 0x7F7A0D01;  // distinct from control hello

struct CmaDesc {
  uint32_t tag;
  uint32_t len;
  uint64_t addr;
};

// bf16 round-to-nearest-even, matching numpy/ml_dtypes astype semantics
// for the values gradients take (the Python wire codec this plane must be
// bitwise-consistent with — collectives.py pack()/round-trip).
inline uint16_t f32_to_bf16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  if ((x & 0x7FFFFFFFu) > 0x7F800000u) {  // NaN: quiet, keep payload bit
    return (uint16_t)((x >> 16) | 0x0040);
  }
  uint32_t lsb = (x >> 16) & 1u;
  x += 0x7FFFu + lsb;
  return (uint16_t)(x >> 16);
}

inline float bf16_to_f32(uint16_t h) {
  uint32_t x = ((uint32_t)h) << 16;
  float f;
  std::memcpy(&f, &x, 4);
  return f;
}

void encode_bf16(const float* src, uint16_t* dst, size_t n) {
  for (size_t i = 0; i < n; ++i) dst[i] = f32_to_bf16(src[i]);
}

// int8 wire format (must match wire_codec.Int8Codec byte for byte): a
// 4-byte LE f32 scale header (max|x|/127; NaN when the chunk holds any
// non-finite value, so NaN propagates loudly through the decode instead
// of being laundered into a finite average) followed by one int8 per
// element, round-to-nearest-even like np.rint.
size_t wire_nbytes(DpCodec codec, size_t nelems) {
  switch (codec) {
    case DpCodec::kBf16:
      return nelems * 2;
    case DpCodec::kInt8:
      return 4 + nelems;
    case DpCodec::kF32:
    default:
      return nelems * 4;
  }
}

// round-half-even without a libm call: adding/subtracting 1.5*2^23
// rounds any |v| < 2^22 to the nearest even integer in the default FP
// mode, and the expression vectorizes to two adds (baseline x86-64 has
// no roundss, so nearbyintf would be a per-element function call — it
// measured as the whole int8 row's bottleneck on a 2-core box). Inputs
// here satisfy |v| <= 127(1+eps) by construction (scale = amax/127).
inline float round_half_even_small(float v) {
  const float magic = 12582912.0f;  // 1.5 * 2^23
  return (v + magic) - magic;
}

void encode_int8(const float* src, uint8_t* dst, size_t n) {
  float amax = 0.0f;
  bool finite = true;
  for (size_t i = 0; i < n; ++i) {
    float a = std::fabs(src[i]);
    if (!std::isfinite(a)) finite = false;
    if (a > amax) amax = a;
  }
  float scale;
  if (!finite) {
    scale = std::numeric_limits<float>::quiet_NaN();
  } else {
    scale = amax > 0.0f ? amax / 127.0f : 0.0f;
  }
  std::memcpy(dst, &scale, 4);
  int8_t* q = (int8_t*)(dst + 4);
  if (!finite || scale == 0.0f) {
    std::memset(q, 0, n);
    return;
  }
  for (size_t i = 0; i < n; ++i) {
    float v = round_half_even_small(src[i] / scale);
    if (v > 127.0f) v = 127.0f;
    if (v < -127.0f) v = -127.0f;
    q[i] = (int8_t)v;
  }
}

void decode_int8(const uint8_t* wire, float* dst, size_t n) {
  float scale;
  std::memcpy(&scale, wire, 4);
  const int8_t* q = (const int8_t*)(wire + 4);
  for (size_t i = 0; i < n; ++i) dst[i] = (float)q[i] * scale;
}

// NaN-propagating max/min, matching np.maximum/np.minimum (the Python
// ring's semantics): a NaN in either operand wins — allreduce-MAX is used
// as a grad-norm overflow tripwire and must not launder NaN away.
inline float nan_max(float a, float b) {
  if (std::isnan(a) || std::isnan(b)) return std::numeric_limits<float>::quiet_NaN();
  return a > b ? a : b;
}
inline float nan_min(float a, float b) {
  if (std::isnan(a) || std::isnan(b)) return std::numeric_limits<float>::quiet_NaN();
  return a < b ? a : b;
}

void reduce_f32(float* acc, const float* in, size_t n, DpOp op) {
  switch (op) {
    case DpOp::kSum:
    case DpOp::kAvg:
      for (size_t i = 0; i < n; ++i) acc[i] += in[i];
      break;
    case DpOp::kMax:
      for (size_t i = 0; i < n; ++i) acc[i] = nan_max(acc[i], in[i]);
      break;
    case DpOp::kMin:
      for (size_t i = 0; i < n; ++i) acc[i] = nan_min(acc[i], in[i]);
      break;
  }
}

void reduce_from_bf16(float* acc, const uint16_t* in, size_t n, DpOp op) {
  switch (op) {
    case DpOp::kSum:
    case DpOp::kAvg:
      for (size_t i = 0; i < n; ++i) acc[i] += bf16_to_f32(in[i]);
      break;
    case DpOp::kMax:
      for (size_t i = 0; i < n; ++i) acc[i] = nan_max(acc[i], bf16_to_f32(in[i]));
      break;
    case DpOp::kMin:
      for (size_t i = 0; i < n; ++i) acc[i] = nan_min(acc[i], bf16_to_f32(in[i]));
      break;
  }
}

void reduce_from_int8(float* acc, const uint8_t* wire, size_t n, DpOp op) {
  float scale;
  std::memcpy(&scale, wire, 4);
  const int8_t* q = (const int8_t*)(wire + 4);
  switch (op) {
    case DpOp::kSum:
    case DpOp::kAvg:
      for (size_t i = 0; i < n; ++i) acc[i] += (float)q[i] * scale;
      break;
    case DpOp::kMax:
      for (size_t i = 0; i < n; ++i) acc[i] = nan_max(acc[i], (float)q[i] * scale);
      break;
    case DpOp::kMin:
      for (size_t i = 0; i < n; ++i) acc[i] = nan_min(acc[i], (float)q[i] * scale);
      break;
  }
}

// poll-bounded small-message helpers now live in the shared stripe layer
// (stripe.h send_all/recv_all); these aliases keep the CMA control-message
// call sites reading as before
constexpr auto send_small = stripeio::send_all;
constexpr auto recv_small = stripeio::recv_all;

// process-wide hop counters for the env-gated injection points: the
// schedule coordinate is "the nth hop this PROCESS runs", stable across
// plane re-rendezvous (a per-plane counter would reset on every quorum)
std::atomic<long> g_fi_hops{0};
std::atomic<long> g_fi_cma_hops{0};

}  // namespace

DataPlane::DataPlane(int rank, int world, int nstripes)
    : rank_(rank), world_(world), nstripes_(nstripes) {
  std::string err;
  listen_fd_ = tcp_listen("[::]:0", &err);
  if (listen_fd_ < 0) {
    throw std::runtime_error("dataplane listen failed: " + err);
  }
  port_ = listen_port(listen_fd_);
  for (int s = 0; s < nstripes_; ++s) {
    stripes_.push_back(std::make_unique<Stripe>());
  }
  for (int s = 0; s < nstripes_; ++s) {
    stripes_[s]->worker = std::thread([this, s] { worker_loop(s); });
  }
  acceptor_ = std::thread([this] { accept_loop(); });
}

DataPlane::~DataPlane() { shutdown(); }

void DataPlane::shutdown() {
  bool was = closed_.exchange(true);
  if (was) return;
  // wake the acceptor
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
  }
  // unblock any in-flight hop
  {
    std::lock_guard<std::mutex> g(socks_mu_);
    for (auto& kv : socks_) {
      for (int fd : kv.second) {
        if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
      }
    }
    socks_cv_.notify_all();
  }
  // wake + join workers
  for (auto& st : stripes_) {
    {
      std::lock_guard<std::mutex> g(st->mu);
      st->cv.notify_all();
    }
    if (st->worker.joinable()) st->worker.join();
  }
  if (acceptor_.joinable()) acceptor_.join();
  {
    // in-flight hellos: shut their fds so the reads fail fast, then join
    std::lock_guard<std::mutex> g(hello_mu_);
    for (int fd : hello_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  for (;;) {
    std::thread t;
    {
      std::lock_guard<std::mutex> g(hello_mu_);
      if (hello_threads_.empty()) break;
      auto it = hello_threads_.begin();
      t = std::move(it->second);
      hello_threads_.erase(it);
    }
    if (t.joinable()) t.join();
  }
  {
    std::lock_guard<std::mutex> g(socks_mu_);
    for (auto& kv : socks_) {
      for (int& fd : kv.second) {
        if (fd >= 0) ::close(fd);
        fd = -1;
      }
    }
  }
  listen_fd_ = -1;
}

void DataPlane::accept_loop() {
  while (!closed_.load()) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (closed_.load()) return;
      if (errno == EINTR) continue;
      return;  // listener closed
    }
    // hello runs on its own short-lived thread: one stalled or garbage
    // connection must not starve the other world*nstripes dials of the
    // rendezvous window. Finished threads are reaped here so a long-lived
    // plane poked by scanners/redials doesn't grow thread objects forever.
    std::vector<std::thread> reap;
    {
      std::lock_guard<std::mutex> g(hello_mu_);
      if (closed_.load()) {
        ::close(fd);
        return;
      }
      for (uint64_t id : hello_finished_) {
        auto it = hello_threads_.find(id);
        if (it != hello_threads_.end()) {
          reap.push_back(std::move(it->second));
          hello_threads_.erase(it);
        }
      }
      hello_finished_.clear();
      uint64_t id = next_hello_id_++;
      hello_fds_.insert(fd);
      hello_threads_.emplace(
          id, std::thread([this, fd, id] { hello_handshake(fd, id); }));
    }
    for (auto& t : reap) {
      if (t.joinable()) t.join();
    }
  }
}

void DataPlane::hello_handshake(int fd, uint64_t id) {
  // hello: {magic, rank, stripe} — bounded read
  uint32_t hello[3];
  bool ok = read_exact(fd, hello, sizeof(hello), now_ms() + 10000) &&
            hello[0] == kHelloMagic;
  int peer = ok ? (int)hello[1] : -1;
  int stripe = ok ? (int)hello[2] : -1;
  {
    std::lock_guard<std::mutex> g(hello_mu_);
    hello_fds_.erase(fd);
    hello_finished_.push_back(id);
  }
  if (!ok || peer < 0 || peer >= world_ || stripe < 0 ||
      stripe >= nstripes_) {
    ::close(fd);
    return;
  }
  tune_socket(fd);
  set_nonblock(fd);
  std::lock_guard<std::mutex> g(socks_mu_);
  if (closed_.load()) {
    ::close(fd);
    return;
  }
  auto& v = socks_[peer];
  if (v.empty()) v.assign(nstripes_, -1);
  if (v[stripe] >= 0) ::close(v[stripe]);
  v[stripe] = fd;
  socks_cv_.notify_all();
}

bool DataPlane::connect_peer(int peer, const std::string& host, int port,
                             int64_t timeout_ms, std::string* err) {
  // ONE deadline across all stripes — an unreachable peer must cost one
  // timeout budget, not nstripes of them
  int64_t deadline = now_ms() + timeout_ms;
  for (int s = 0; s < nstripes_; ++s) {
    int64_t left = deadline - now_ms();
    if (left <= 0) {
      *err = "connect deadline exceeded";
      return false;
    }
    int fd = tcp_connect(host, port, left, err);
    if (fd < 0) return false;
    uint32_t hello[3] = {kHelloMagic, (uint32_t)rank_, (uint32_t)s};
    if (!write_all(fd, hello, sizeof(hello))) {
      ::close(fd);
      *err = "hello write failed";
      return false;
    }
    tune_socket(fd);
    set_nonblock(fd);
    std::lock_guard<std::mutex> g(socks_mu_);
    auto& v = socks_[peer];
    if (v.empty()) v.assign(nstripes_, -1);
    if (v[s] >= 0) ::close(v[s]);
    v[s] = fd;
  }
  return true;
}

bool DataPlane::wait_ready(int64_t timeout_ms, std::string* err) {
  int64_t deadline = now_ms() + timeout_ms;
  std::unique_lock<std::mutex> g(socks_mu_);
  for (;;) {
    bool ready = true;
    for (int p = 0; p < world_ && ready; ++p) {
      if (p == rank_) continue;
      auto it = socks_.find(p);
      if (it == socks_.end()) {
        ready = false;
        break;
      }
      for (int fd : it->second) {
        if (fd < 0) {
          ready = false;
          break;
        }
      }
    }
    if (ready) return true;
    if (closed_.load()) {
      *err = "dataplane shut down";
      return false;
    }
    int64_t left = deadline - now_ms();
    if (left <= 0) {
      *err = "timeout waiting for stripe peers";
      return false;
    }
    cv_wait_deadline(socks_cv_, g, now_ms() + (left > 100 ? 100 : left));
  }
}

int DataPlane::fd_for(int peer, int stripe) {
  std::lock_guard<std::mutex> g(socks_mu_);
  auto it = socks_.find(peer);
  if (it == socks_.end() || it->second[stripe] < 0) return -1;
  return it->second[stripe];
}

// Full-duplex pump: send sn bytes (header+payload already framed by the
// caller into sbuf layout via two-phase state) while receiving rn bytes.
// Uses poll() on both fds so a full send buffer can't deadlock against a
// peer doing the same (the reason the Python path burned a thread per hop).
bool DataPlane::hop(int send_fd, int recv_fd, const uint8_t* sbuf, size_t sn,
                    uint8_t* rbuf, size_t rn, uint32_t tag,
                    int64_t deadline_ms, bool* send_failed, bool* timed_out,
                    std::string* err) {
  // env-gated injection points (see faultinject.h): torn write / kill /
  // delay on the nth hop this process runs. Zero-cost when disarmed.
  static const fi::NthSpec fi_cut = fi::parse_nth("TORCHFT_FI_DP_CUT");
  static const long fi_kill = fi::parse_long("TORCHFT_FI_DP_KILL");
  static const long fi_delay = fi::parse_long("TORCHFT_FI_DP_DELAY_MS");
  if (fi_cut.nth > 0 || fi_kill > 0 || fi_delay > 0) {
    long h = ++g_fi_hops;
    if (fi_delay > 0) fi::sleep_ms(fi_delay);
    if (fi_kill > 0 && h == fi_kill) fi::kill_self("dp.hop", h);
    if (fi_cut.nth > 0 && h == fi_cut.nth) {
      // torn stripe write: full-length header, a fraction of the
      // payload, then a hard cut — the peer must see a mid-frame EOF
      // (its recv errors), never a short frame it could mistake for data
      HopHdr thdr{tag, (uint32_t)sn};
      bool to = false;
      std::string e2;
      size_t kbytes = (size_t)((double)sn * fi_cut.frac);
      fi::write_evidence("dp.hop", h, "torn");
      if (send_small(send_fd, &thdr, sizeof(thdr), deadline_ms, &to, &e2) &&
          kbytes > 0) {
        send_small(send_fd, sbuf, kbytes, deadline_ms, &to, &e2);
      }
      ::shutdown(send_fd, SHUT_RDWR);
      *send_failed = true;
      *timed_out = false;
      *err = "fault injection: torn stripe write (hop " + std::to_string(h) +
             ", " + std::to_string(kbytes) + "/" + std::to_string(sn) +
             " bytes)";
      return false;
    }
  }

  HopHdr shdr{tag, (uint32_t)sn};
  HopHdr rhdr{0, 0};
  size_t s_off = 0, r_off = 0;
  size_t sh_off = 0, rh_off = 0;  // header progress
  *send_failed = false;

  while (sh_off < sizeof(shdr) || s_off < sn || rh_off < sizeof(rhdr) ||
         r_off < rn) {
    struct pollfd pfd[2];
    int n = 0;
    int send_i = -1, recv_i = -1;
    if (sh_off < sizeof(shdr) || s_off < sn) {
      pfd[n].fd = send_fd;
      pfd[n].events = POLLOUT;
      pfd[n].revents = 0;
      send_i = n++;
    }
    if (rh_off < sizeof(rhdr) || r_off < rn) {
      pfd[n].fd = recv_fd;
      pfd[n].events = POLLIN;
      pfd[n].revents = 0;
      recv_i = n++;
    }
    int64_t left = deadline_ms - now_ms();
    if (left <= 0) {
      *timed_out = true;
      *err = "hop deadline exceeded";
      return false;
    }
    int pr = ::poll(pfd, n, (int)(left > 200 ? 200 : left));
    if (closed_.load()) {
      *err = "dataplane shut down";
      return false;
    }
    if (pr < 0) {
      if (errno == EINTR) continue;
      *err = std::string("poll: ") + errno_str(errno);
      return false;
    }
    if (send_i >= 0 && (pfd[send_i].revents & (POLLOUT | POLLERR | POLLHUP))) {
      // scatter-gather: header + payload leave in ONE sendmsg from their
      // own buffers — no coalescing copy, and the common case is a
      // single syscall per pump instead of two
      while (sh_off < sizeof(shdr) || s_off < sn) {
        iovec iov[2];
        int cnt = 0;
        if (sh_off < sizeof(shdr)) {
          iov[cnt].iov_base = (uint8_t*)&shdr + sh_off;
          iov[cnt].iov_len = sizeof(shdr) - sh_off;
          ++cnt;
        }
        if (s_off < sn) {
          iov[cnt].iov_base = (void*)(sbuf + s_off);
          iov[cnt].iov_len = sn - s_off;
          ++cnt;
        }
        msghdr mh{};
        mh.msg_iov = iov;
        mh.msg_iovlen = cnt;
        ssize_t k = ::sendmsg(send_fd, &mh, MSG_NOSIGNAL);
        if (k > 0) {
          size_t adv = (size_t)k;
          if (sh_off < sizeof(shdr)) {
            size_t h = sizeof(shdr) - sh_off;
            size_t hh = adv < h ? adv : h;
            sh_off += hh;
            adv -= hh;
          }
          s_off += adv;
        } else if (k < 0 && err_wouldblock(errno)) {
          break;
        } else {
          *send_failed = true;
          *err = std::string("send: ") + (k == 0 ? "closed" : errno_str(errno));
          return false;
        }
      }
    }
    if (recv_i >= 0 && (pfd[recv_i].revents & (POLLIN | POLLERR | POLLHUP))) {
      while (rh_off < sizeof(rhdr)) {
        ssize_t k = ::recv(recv_fd, (uint8_t*)&rhdr + rh_off,
                           sizeof(rhdr) - rh_off, 0);
        if (k > 0) {
          rh_off += (size_t)k;
          if (rh_off == sizeof(rhdr)) {
            if (rhdr.tag != tag || rhdr.len != rn) {
              *err = "stripe frame mismatch: tag " + std::to_string(rhdr.tag) +
                     "/" + std::to_string(tag) + " len " +
                     std::to_string(rhdr.len) + "/" + std::to_string(rn);
              return false;
            }
          }
        } else if (k < 0 && err_wouldblock(errno)) {
          break;
        } else {
          *err = std::string("recv: ") + (k == 0 ? "closed" : errno_str(errno));
          return false;
        }
      }
      while (rh_off == sizeof(rhdr) && r_off < rn) {
        ssize_t k = ::recv(recv_fd, rbuf + r_off, rn - r_off, 0);
        if (k > 0) {
          r_off += (size_t)k;
        } else if (k < 0 && err_wouldblock(errno)) {
          break;
        } else {
          *err = std::string("recv: ") + (k == 0 ? "closed" : errno_str(errno));
          return false;
        }
      }
    }
  }
  return true;
}

void DataPlane::enable_cma(const std::vector<int64_t>& pids) {
  peer_pids_ = pids;
  // release-order: the store publishes peer_pids_ to the already-
  // running stripe workers (acquire-load in run_stripe); see the
  // member comment
  cma_.store(true, std::memory_order_release);
}

// CMA hop: descriptors and acks ride the stripe socket; the payload is
// pulled straight from the left neighbor's address space. Message flow per
// socket direction is clean: descs flow rank→right, acks flow reader→owner
// (so on my left socket I read descs and write acks; on my right socket I
// write descs and read acks) — with world=2 both are the same fd and the
// peer's desc→ack send order keeps the stream unambiguous.
bool DataPlane::cma_hop(int send_fd, int recv_fd, const uint8_t* sbuf,
                        size_t sn, uint8_t* rbuf, size_t rn, uint32_t tag,
                        int64_t deadline_ms, bool* send_failed,
                        bool* timed_out, std::string* err) {
  const int left = (rank_ - 1 + world_) % world_;
  *send_failed = false;
  // env-gated injection points: die with a published pull descriptor
  // outstanding (the torn-read window the ROADMAP divergence hypothesis
  // names), or tear this hop's own pull partway.
  static const long fi_cma_kill = fi::parse_long("TORCHFT_FI_CMA_KILL");
  static const fi::NthSpec fi_cma_torn =
      fi::parse_nth("TORCHFT_FI_CMA_TORN");
  long fi_h = 0;
  if (fi_cma_kill > 0 || fi_cma_torn.nth > 0) fi_h = ++g_fi_cma_hops;
  CmaDesc mine{tag, (uint32_t)sn, (uint64_t)(uintptr_t)sbuf};
  if (!send_small(send_fd, &mine, sizeof(mine), deadline_ms, timed_out, err)) {
    *send_failed = true;
    return false;
  }
  if (fi_cma_kill > 0 && fi_h == fi_cma_kill) {
    // the right neighbor now holds {addr, len} into THIS address space;
    // dying here is exactly "peer death mid-op with a dangling pull"
    fi::kill_self("cma.desc", fi_h);
  }
  CmaDesc theirs{};
  if (!recv_small(recv_fd, &theirs, sizeof(theirs), deadline_ms, timed_out,
                  err)) {
    return false;
  }
  if (theirs.tag != tag || theirs.len != rn) {
    *err = "cma desc mismatch: tag " + std::to_string(theirs.tag) + "/" +
           std::to_string(tag) + " len " + std::to_string(theirs.len) + "/" +
           std::to_string(rn);
    return false;
  }
  size_t goal = rn;
  if (fi_cma_torn.nth > 0 && fi_h == fi_cma_torn.nth) {
    // torn CMA read: stop the pull partway and fail the hop — the
    // partially-filled buffer must latch the step, never average in
    goal = (size_t)((double)rn * fi_cma_torn.frac);
    fi::write_evidence("cma.pull", fi_h, "torn");
  }
  size_t off = 0;
  while (off < goal) {
    iovec lv{rbuf + off, goal - off};
    iovec rv{(void*)(uintptr_t)(theirs.addr + off), goal - off};
    ssize_t k = ::process_vm_readv((pid_t)peer_pids_[left], &lv, 1, &rv, 1, 0);
    if (k <= 0) {
      *err = std::string("process_vm_readv: ") +
             (k == 0 ? "zero read" : errno_str(errno));
      return false;
    }
    off += (size_t)k;
  }
  if (goal < rn) {
    *err = "fault injection: torn CMA pull (" + std::to_string(goal) + "/" +
           std::to_string(rn) + " bytes)";
    return false;
  }
  uint32_t ack = tag;
  if (!send_small(recv_fd, &ack, sizeof(ack), deadline_ms, timed_out, err)) {
    return false;
  }
  uint32_t rack = 0;
  if (!recv_small(send_fd, &rack, sizeof(rack), deadline_ms, timed_out, err)) {
    *send_failed = true;
    return false;
  }
  if (rack != tag) {
    *err = "cma ack mismatch";
    *send_failed = true;
    return false;
  }
  return true;
}

int DataPlane::run_stripe(int stripe_idx, Job& job, int* bad_peer,
                          std::string* err) {
  const int right = (rank_ + 1) % world_;
  const int left = (rank_ - 1 + world_) % world_;
  int send_fd = fd_for(right, stripe_idx);
  int recv_fd = fd_for(left, stripe_idx);
  if (send_fd < 0 || recv_fd < 0) {
    *bad_peer = send_fd < 0 ? right : left;
    *err = "stripe socket missing";
    return -1;
  }

  // CMA pulls exact f32 out of the peer's memory — the wire codec is
  // moot (and the exactness is deterministic: the owner's bytes are
  // distributed verbatim in the allgather phase)
  // release-order: one acquire-load per job pairs with enable_cma's
  // release-store so peer_pids_ is fully visible before the first CMA
  // hop of this job
  const bool use_cma = cma_.load(std::memory_order_acquire);
  if (use_cma) job.codec = DpCodec::kF32;
  const DpCodec codec = job.codec;

  float* flat = (float*)job.base;
  int64_t n = job.nelems;
  std::vector<int64_t> bounds(world_ + 1);
  for (int i = 0; i <= world_; ++i) bounds[i] = n * i / world_;
  auto chunk_ptr = [&](int i) { return flat + bounds[i]; };
  auto chunk_n = [&](int i) { return (size_t)(bounds[i + 1] - bounds[i]); };

  size_t max_chunk = 0;
  for (int i = 0; i < world_; ++i) {
    if (chunk_n(i) > max_chunk) max_chunk = chunk_n(i);
  }
  const size_t max_wire = wire_nbytes(codec, max_chunk);
  auto& st = *stripes_[stripe_idx];
  st.scratch_send.resize(max_wire);
  st.scratch_recv.resize(max_wire);
  if (codec != DpCodec::kF32) st.scratch_fwd.resize(max_wire);

  auto prep_send = [&](int idx) -> std::pair<const uint8_t*, size_t> {
    size_t cn = chunk_n(idx);
    switch (codec) {
      case DpCodec::kBf16:
        encode_bf16(chunk_ptr(idx), (uint16_t*)st.scratch_send.data(), cn);
        return {st.scratch_send.data(), cn * 2};
      case DpCodec::kInt8:
        encode_int8(chunk_ptr(idx), st.scratch_send.data(), cn);
        return {st.scratch_send.data(), 4 + cn};
      case DpCodec::kF32:
      default:
        // zero-copy: the chunk's own bytes are the wire form
        return {(const uint8_t*)chunk_ptr(idx), cn * 4};
    }
  };

  bool send_failed = false;
  bool timed_out = false;
  auto do_hop = [&](const uint8_t* sb, size_t sn, uint8_t* rb, size_t rn) {
    // per-hop latency histogram (full-duplex send+recv pump — the wait
    // for a slow left neighbor lands here, which is what makes the
    // distribution a straggler lens); failed hops record too: a
    // deadline'd hop's duration is exactly the evidence wanted
    int64_t t0 = lathist::now_ns();
    bool ok = use_cma ? cma_hop(send_fd, recv_fd, sb, sn, rb, rn, job.tag,
                                job.deadline_ms, &send_failed, &timed_out, err)
                      : hop(send_fd, recv_fd, sb, sn, rb, rn, job.tag,
                            job.deadline_ms, &send_failed, &timed_out, err);
    int64_t hop_ns = lathist::now_ns() - t0;
    lathist::observe(lathist::kDpHop, (double)hop_ns / 1e9);
    // crash-durable breadcrumb: a worker SIGKILLed mid-allreduce leaves
    // its last hops (a = op tag, b = ok flag) in the black box — the
    // postmortem's "what was in flight" answer for the native plane
    bb::record(bb::kDpHop, -1, -1, (int64_t)job.tag, ok ? 1 : 0);
    return ok;
  };
  // a deadline or LOCAL shutdown names NO peer: slow-but-alive (or our
  // own teardown) must surface as retryable, not as an eviction-worthy
  // accusation against an innocent neighbor
  auto fail = [&]() {
    if (timed_out) {
      *bad_peer = -1;
      return -2;
    }
    if (closed_.load()) {
      *bad_peer = -1;
      return -1;
    }
    *bad_peer = send_failed ? right : left;
    return -1;
  };
  // reduce-scatter phase: every hop ships a freshly encoded partial sum
  // (re-quantized at its own magnitude); accumulation stays f32
  for (int step = 0; step < world_ - 1; ++step) {
    int send_idx = ((rank_ - step) % world_ + world_) % world_;
    int recv_idx = ((rank_ - step - 1) % world_ + world_) % world_;
    auto [sb, sn] = prep_send(send_idx);
    size_t rn = wire_nbytes(codec, chunk_n(recv_idx));
    if (!do_hop(sb, sn, st.scratch_recv.data(), rn)) {
      return fail();
    }
    switch (codec) {
      case DpCodec::kBf16:
        reduce_from_bf16(chunk_ptr(recv_idx),
                         (const uint16_t*)st.scratch_recv.data(),
                         chunk_n(recv_idx), job.op);
        break;
      case DpCodec::kInt8:
        reduce_from_int8(chunk_ptr(recv_idx), st.scratch_recv.data(),
                         chunk_n(recv_idx), job.op);
        break;
      case DpCodec::kF32:
      default:
        reduce_f32(chunk_ptr(recv_idx), (const float*)st.scratch_recv.data(),
                   chunk_n(recv_idx), job.op);
        break;
    }
  }
  if (codec == DpCodec::kF32) {
    // raw allgather: f32 lands straight in the target chunk and the
    // forwarded bytes are the owner's bytes by nature
    for (int step = 0; step < world_ - 1; ++step) {
      int send_idx = ((rank_ + 1 - step) % world_ + world_) % world_;
      int recv_idx = ((rank_ - step) % world_ + world_) % world_;
      auto [sb, sn] = prep_send(send_idx);
      float* dst = chunk_ptr(recv_idx);
      size_t cn = chunk_n(recv_idx);
      if (!do_hop(sb, sn, (uint8_t*)dst, cn * 4)) {
        return fail();
      }
    }
  } else if (world_ > 1) {
    // lossy allgather: the owner of each fully reduced chunk encodes it
    // ONCE; its wire bytes then circulate VERBATIM (intermediate ranks
    // forward what they received, zero re-encode work) and the owner
    // keeps the decode of its own bytes — every rank lands on the
    // identical f32 image by construction, not by fp-rounding luck
    // (collectives.py's _ring_allreduce_codec is the same schedule)
    int owned = (rank_ + 1) % world_;
    size_t own_wire = wire_nbytes(codec, chunk_n(owned));
    switch (codec) {
      case DpCodec::kBf16:
        encode_bf16(chunk_ptr(owned), (uint16_t*)st.scratch_fwd.data(),
                    chunk_n(owned));
        for (size_t i = 0; i < chunk_n(owned); ++i) {
          chunk_ptr(owned)[i] =
              bf16_to_f32(((const uint16_t*)st.scratch_fwd.data())[i]);
        }
        break;
      case DpCodec::kInt8:
        encode_int8(chunk_ptr(owned), st.scratch_fwd.data(), chunk_n(owned));
        decode_int8(st.scratch_fwd.data(), chunk_ptr(owned), chunk_n(owned));
        break;
      default:
        break;
    }
    uint8_t* cur = st.scratch_fwd.data();
    size_t cur_n = own_wire;
    uint8_t* spare = st.scratch_recv.data();
    for (int step = 0; step < world_ - 1; ++step) {
      int recv_idx = ((rank_ - step) % world_ + world_) % world_;
      size_t cn = chunk_n(recv_idx);
      size_t rn = wire_nbytes(codec, cn);
      if (!do_hop(cur, cur_n, spare, rn)) {
        return fail();
      }
      if (codec == DpCodec::kBf16) {
        const uint16_t* in = (const uint16_t*)spare;
        float* dst = chunk_ptr(recv_idx);
        for (size_t i = 0; i < cn; ++i) dst[i] = bf16_to_f32(in[i]);
      } else {
        decode_int8(spare, chunk_ptr(recv_idx), cn);
      }
      uint8_t* t = cur;
      cur = spare;
      spare = t;
      cur_n = rn;
    }
  }
  if (job.op == DpOp::kAvg) {
    float inv = 1.0f / (float)world_;
    for (int64_t i = 0; i < n; ++i) flat[i] *= inv;
  }
  return 0;
}

void DataPlane::worker_loop(int stripe_idx) {
  // samples name this thread "dp.pump" in the collapsed stacks; the
  // per-hop path itself gains zero instructions (registration happens
  // once, here — see profiler.h)
  prof::ThreadGuard prof_guard("dp.pump");
  auto& st = *stripes_[stripe_idx];
  for (;;) {
    Job job;
    {
      std::unique_lock<std::mutex> g(st.mu);
      st.cv.wait(g, [&] { return st.has_job || closed_.load(); });
      if (closed_.load()) return;
      job = st.job;
      st.has_job = false;
    }
    int bad_peer = -1;
    std::string err;
    int rc = 0;
    if (job.nelems > 0) {
      int64_t t0 = lathist::now_ns();
      rc = run_stripe(stripe_idx, job, &bad_peer, &err);
      lathist::observe(lathist::kDpStripe,
                       (double)(lathist::now_ns() - t0) / 1e9);
      // stripe-level breadcrumb (a = op tag, b = rc): pairs with the
      // per-hop records to name the exact stripe a death interrupted
      bb::record(bb::kDpStripe, -1, -1, (int64_t)job.tag, rc);
    }
    {
      std::lock_guard<std::mutex> g(st.mu);
      st.rc = rc;
      st.bad_peer = bad_peer;
      st.err = err;
      st.done = true;
      st.cv.notify_all();
    }
  }
}

int DataPlane::allreduce(void* data, int64_t nelems, DpDtype dtype, DpOp op,
                         DpCodec codec, uint32_t tag, int64_t timeout_ms,
                         int* bad_peer, std::string* err) {
  *bad_peer = -1;
  if (dtype != DpDtype::kF32) {
    *err = "unsupported dtype";
    return -1;
  }
  if (codec != DpCodec::kF32 && codec != DpCodec::kBf16 &&
      codec != DpCodec::kInt8) {
    *err = "unsupported wire codec";
    return -1;
  }
  if (world_ <= 1 || nelems == 0) return 0;
  int64_t deadline = now_ms() + timeout_ms;
  // stripe partition: contiguous, 16-element aligned so reduce loops stay
  // vectorizable and no stripe's chunk is pathologically small
  int ns = nstripes_;
  if (nelems < ns * 64) ns = 1;
  std::vector<int64_t> sb = stripeio::stripe_bounds(nelems, ns, 16);
  for (int s = 0; s < ns; ++s) {
    auto& st = *stripes_[s];
    std::lock_guard<std::mutex> g(st.mu);
    st.job.base = (uint8_t*)((float*)data + sb[s]);
    st.job.nelems = sb[s + 1] - sb[s];
    st.job.op = op;
    st.job.codec = codec;
    st.job.tag = tag + (uint32_t)s;
    st.job.deadline_ms = deadline;
    st.has_job = true;
    st.done = false;
    st.cv.notify_all();
  }
  // aggregate: a concrete socket failure (-1, names a peer) outranks a
  // bare deadline (-2) from another stripe
  int rc = 0;
  for (int s = 0; s < ns; ++s) {
    auto& st = *stripes_[s];
    std::unique_lock<std::mutex> g(st.mu);
    st.cv.wait(g, [&] { return st.done || closed_.load(); });
    if (!st.done) {
      // Shutdown raced the op. A worker may still be inside run_stripe
      // writing into the CALLER's buffer; returning -1 now would let
      // Python free/reuse that memory under the worker's pen (shutdown's
      // join runs on a different thread and doesn't gate this return).
      // has_job still set means the worker exited at the top of its loop
      // WITHOUT taking the job — nobody will touch the buffer; otherwise
      // the worker is mid-job and, with the sockets now closed, will
      // promptly fail the next hop and set done.
      st.cv.wait(g, [&] { return st.done || st.has_job; });
      if (rc == 0) {
        *err = "dataplane shut down";
        rc = -1;
        *bad_peer = -1;
      }
      continue;
    }
    if (st.rc != 0 && (rc == 0 || (rc == -2 && st.rc == -1))) {
      rc = st.rc;
      *bad_peer = st.bad_peer;
      *err = st.err;
    }
  }
  return rc;
}

}  // namespace tft

// ---- C ABI for ctypes ------------------------------------------------------

namespace {

std::mutex g_dp_mu;
int64_t g_dp_next = 1;
std::map<int64_t, std::shared_ptr<tft::DataPlane>> g_dps;

std::shared_ptr<tft::DataPlane> dp_get(int64_t h) {
  std::lock_guard<std::mutex> g(g_dp_mu);
  auto it = g_dps.find(h);
  return it == g_dps.end() ? nullptr : it->second;
}

void dp_set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    strncpy(err, msg.c_str(), (size_t)errlen - 1);
    err[errlen - 1] = '\0';
  }
}

}  // namespace

extern "C" {

// Bumped whenever the ctypes-visible surface changes SHAPE or MEANING
// (v2: tft_dp_allreduce's `wire_bf16` int became the DpCodec enum — a
// stale library would silently reinterpret codec=2 as wire_bf16=true;
// v3: tft_lathist_snapshot/tft_lathist_reset added — a stale build would
// fail the loader's symbol lookup at import;
// v4: tft_blob_* striped checkpoint blob plane added (blob.cc)).
// The Python loader (_native/__init__.py) refuses to run a mismatched
// build and rebuilds in place.
// v5: mgr.should_commit carries divergence-sentinel digests, lh.digest
// RPC added, native blackbox breadcrumbs (blackbox.h) compiled in.
// v6: fixed-retention time-series store (tsdb.h): tft_tsdb_snapshot/
// tft_tsdb_reset + lighthouse /timeseries.json ingest.
// v7: always-on sampling profiler (profiler.h): tft_prof_set_hz/hz/
// snapshot/reset/samples_total — a stale build would fail the loader's
// symbol lookup at import.
int tft_abi_version() { return 7; }

int64_t tft_dp_create(int rank, int world, int nstripes, char* err,
                      int errlen) {
  try {
    auto dp = std::make_shared<tft::DataPlane>(rank, world, nstripes);
    std::lock_guard<std::mutex> g(g_dp_mu);
    int64_t h = g_dp_next++;
    g_dps[h] = std::move(dp);
    return h;
  } catch (const std::exception& e) {
    dp_set_err(err, errlen, e.what());
    return 0;
  }
}

int tft_dp_port(int64_t h) {
  auto dp = dp_get(h);
  return dp ? dp->port() : -1;
}

int tft_dp_connect(int64_t h, int peer, const char* host, int port,
                   int64_t timeout_ms, char* err, int errlen) {
  auto dp = dp_get(h);
  if (!dp) {
    dp_set_err(err, errlen, "bad handle");
    return -1;
  }
  std::string e;
  if (!dp->connect_peer(peer, host, port, timeout_ms, &e)) {
    dp_set_err(err, errlen, e);
    return -1;
  }
  return 0;
}

int tft_dp_wait_ready(int64_t h, int64_t timeout_ms, char* err, int errlen) {
  auto dp = dp_get(h);
  if (!dp) {
    dp_set_err(err, errlen, "bad handle");
    return -1;
  }
  std::string e;
  if (!dp->wait_ready(timeout_ms, &e)) {
    dp_set_err(err, errlen, e);
    return -1;
  }
  return 0;
}

int tft_dp_enable_cma(int64_t h, const int64_t* pids, int n, char* err,
                      int errlen) {
  auto dp = dp_get(h);
  if (!dp) {
    dp_set_err(err, errlen, "bad handle");
    return -1;
  }
  dp->enable_cma(std::vector<int64_t>(pids, pids + n));
  return 0;
}

int tft_dp_allreduce(int64_t h, void* data, int64_t nelems, int dtype, int op,
                     int codec, uint32_t tag, int64_t timeout_ms,
                     int* bad_peer, char* err, int errlen) {
  auto dp = dp_get(h);
  if (!dp) {
    dp_set_err(err, errlen, "bad handle");
    return -1;
  }
  std::string e;
  int bp = -1;
  int rc = dp->allreduce(data, nelems, (tft::DpDtype)dtype, (tft::DpOp)op,
                         (tft::DpCodec)codec, tag, timeout_ms, &bp, &e);
  if (bad_peer) *bad_peer = bp;
  if (rc != 0) dp_set_err(err, errlen, e);
  return rc;
}

void tft_dp_free(int64_t h) {
  std::shared_ptr<tft::DataPlane> dp;
  {
    std::lock_guard<std::mutex> g(g_dp_mu);
    auto it = g_dps.find(h);
    if (it == g_dps.end()) return;
    dp = std::move(it->second);
    g_dps.erase(it);
  }
  dp->shutdown();
}

}  // extern "C"
