// Compiled-in, env-gated fault injection for the native data/control
// plane — the C++ sibling of torchft_tpu/faultinject/core.py. Always
// compiled (no build flag): a disarmed site costs one cached getenv and,
// when any knob in its file is set, one relaxed atomic increment — the
// hot path keeps its hooks in production builds so the exact binary that
// ships can reproduce a failure.
//
// Knobs (parsed once per process, static at the call site):
//
//   TORCHFT_FI_DP_CUT=<nth>[:<frac>]   cut the <nth> stripe hop after
//                                      sending <frac> (default 0.5) of
//                                      the payload: a torn TCP write
//                                      mid-allreduce — the receiver sees
//                                      a mid-frame EOF, never short data
//   TORCHFT_FI_DP_KILL=<nth>           SIGKILL this process entering the
//                                      <nth> stripe hop (peer death
//                                      mid-transfer)
//   TORCHFT_FI_DP_DELAY_MS=<ms>        sleep before every stripe hop
//   TORCHFT_FI_CMA_KILL=<nth>          SIGKILL right after publishing the
//                                      <nth> CMA pull descriptor — the
//                                      peer then holds a descriptor into
//                                      dying memory, the exact window the
//                                      torn-read divergence hypothesis
//                                      needs
//   TORCHFT_FI_CMA_TORN=<nth>[:<frac>] pull only <frac> of the <nth> CMA
//                                      hop's bytes, then fail the hop
//   TORCHFT_FI_RPC_CUT=<method>:<nth>  cut the client frame of the <nth>
//                                      call to <method> mid-body (torn
//                                      control-plane write)
//   TORCHFT_FI_SRV_DELAY=<method>:<ms> delay every server reply to
//                                      <method> by <ms> (quorum.reply /
//                                      commit.vote latency injection at
//                                      the native layer)
//   TORCHFT_FI_COMMIT_REPLY_DROP=<nth> fail the <nth> mgr.should_commit
//                                      reply with UNAVAILABLE (a lost
//                                      vote decision)
//
// Fired kills append an evidence record under
// TORCHFT_FAULT_EVIDENCE_DIR (same format the Python engine writes) so
// the test tier can tell an injected death from the documented
// environmental heap corruption.

#ifndef TFT_FAULTINJECT_H_
#define TFT_FAULTINJECT_H_

#include <unistd.h>

#include <atomic>
#include <chrono>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>

namespace tft {
namespace fi {

struct NthSpec {
  long nth = 0;      // 0 = disarmed
  double frac = 0.5;
};

inline NthSpec parse_nth(const char* env) {
  NthSpec s;
  const char* v = std::getenv(env);
  if (!v || !*v) return s;
  s.nth = std::atol(v);
  const char* c = std::strchr(v, ':');
  if (c) s.frac = std::atof(c + 1);
  return s;
}

inline long parse_long(const char* env) {
  const char* v = std::getenv(env);
  return (v && *v) ? std::atol(v) : 0;
}

struct MethodSpec {
  std::string method;  // empty = disarmed
  long n = 0;          // nth for CUT, ms for DELAY
};

inline MethodSpec parse_method(const char* env) {
  MethodSpec s;
  const char* v = std::getenv(env);
  if (!v || !*v) return s;
  const char* c = std::strrchr(v, ':');
  if (!c) return s;
  s.method.assign(v, c - v);
  s.n = std::atol(c + 1);
  return s;
}

// noinline on purpose: the sampling profiler (profiler.h) must be able
// to name this frame in a victim's collapsed stacks — the
// diagnose_straggler scenario asserts the injected delay dominates the
// victim's hot stack, which needs `fi::sleep_ms` to survive as a symbol
// instead of folding into the hop loop.
__attribute__((noinline)) inline void sleep_ms(long ms) {
  std::this_thread::sleep_for(std::chrono::milliseconds(ms));
}

// Evidence record, same directory + JSONL shape as the Python engine's
// FaultPlane._write_evidence — conftest's injection-evidence check and
// the scenario runner read both interchangeably.
inline void write_evidence(const char* site, long hit, const char* action) {
  const char* dir = std::getenv("TORCHFT_FAULT_EVIDENCE_DIR");
  if (!dir || !*dir) return;
  char path[512];
  std::snprintf(path, sizeof(path), "%s/tft_fault_%d_native.json", dir,
                (int)getpid());
  FILE* f = std::fopen(path, "a");
  if (!f) return;
  std::fprintf(f,
               "{\"site\": \"%s\", \"action\": \"%s\", \"hit\": %ld, "
               "\"pid\": %d, \"native\": true}\n",
               site, action, hit, (int)getpid());
  std::fflush(f);
  ::fsync(fileno(f));
  std::fclose(f);
}

inline void kill_self(const char* site, long hit) {
  write_evidence(site, hit, "kill");
  std::fprintf(stderr, "fault injection: SIGKILL at %s hit %ld (pid %d)\n",
               site, hit, (int)getpid());
  std::fflush(stderr);
  ::raise(SIGKILL);
}

}  // namespace fi
}  // namespace tft

#endif  // TFT_FAULTINJECT_H_
