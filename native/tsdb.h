// torchft_tpu native core — fixed-retention time-series store (ISSUE 11).
//
// Every observability surface before this one was either instantaneous
// (/metrics, /cluster.json hold each replica's LATEST report) or post-hoc
// (black box, postmortem). This store is the missing axis: a bounded ring
// of samples per (replica, series) on the lighthouse, fed by the SAME
// quorum-piggyback telemetry the cluster aggregation already ingests, so
// "when did the fleet get slow" is answerable from one range query.
//
// Design constraints, in order:
//   * samples are keyed by (epoch, step) — the clock-sync-free coordinates
//     everything else in this repo orders by — never by wall time;
//   * the lighthouse stays SCHEMA-BLIND: a sample is an opaque series
//     name (string) plus one double; the Python replica decides what to
//     publish (telemetry/timeseries.py builds the map), so the Python
//     telemetry schema evolves without touching the C++ core — the same
//     contract as the verbatim-spliced summary/anatomy digests;
//   * fixed retention (TORCHFT_TSDB_RETAIN samples per series) and fixed
//     fan-out caps (TORCHFT_TSDB_MAX_SERIES per replica, 256 replicas):
//     a chatty or malicious reporter must never OOM the coordinator;
//   * rings for dead replicas are RETAINED (up to the replica cap): the
//     history of a killed group is exactly what the postmortem needs, and
//     a respawned group (fresh uuid suffix) gets its own ring — so
//     /timeseries.json serves the full history across a kill/respawn.
//
// One process-global store (like lathist.h): the lighthouse ingests under
// its own mutex here (a leaf lock — never taken while holding another),
// tests snapshot it through the C ABI (tft_tsdb_snapshot), and the HTTP
// side renders range queries (since-step cursor, stride downsampling).
#pragma once

#include <cstdint>
#include <cstdlib>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <vector>

namespace tft {
namespace tsdb {

inline long env_long(const char* name, long dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  long out = strtol(v, &end, 10);
  return (end && *end == '\0') ? out : dflt;
}

struct Sample {
  int64_t epoch = -1;
  int64_t step = -1;
  double value = 0.0;
};

// One bounded ring of samples, oldest evicted first. A report repeating
// the step of the previous sample OVERWRITES it (reports ride every
// quorum RPC; a re-quorum within one step must not burn retention), and
// out-of-order steps append normally — a respawned process restarting at
// step 0 legitimately goes backwards before its heal jumps it forward.
struct Ring {
  std::vector<Sample> buf;
  size_t cap = 0;
  size_t next = 0;   // insertion cursor
  bool full = false;
  int64_t last_step = INT64_MIN;
  size_t last_idx = 0;
  uint64_t total = 0;  // samples ever ingested (evictions included)

  void add(const Sample& s) {
    if (cap == 0) return;
    if (!buf.empty() && s.step == last_step && s.step >= 0) {
      buf[last_idx] = s;  // refresh, don't burn retention
      return;
    }
    if (buf.size() < cap) {
      last_idx = buf.size();
      buf.push_back(s);
      next = buf.size() % cap;
      full = buf.size() == cap;
    } else {
      last_idx = next;
      buf[next] = s;
      next = (next + 1) % cap;
      full = true;
    }
    last_step = s.step;
    total++;
  }

  // oldest-first copy
  std::vector<Sample> ordered() const {
    std::vector<Sample> out;
    out.reserve(buf.size());
    if (full && !buf.empty()) {
      for (size_t i = 0; i < buf.size(); i++)
        out.push_back(buf[(next + i) % buf.size()]);
    } else {
      out = buf;
    }
    return out;
  }
};

class Store {
 public:
  Store()
      : retain_((size_t)env_long("TORCHFT_TSDB_RETAIN", 512)),
        max_series_((size_t)env_long("TORCHFT_TSDB_MAX_SERIES", 64)) {}

  size_t retain() const { return retain_; }

  // One replica report's worth of samples, all at (epoch, step).
  void ingest(const std::string& replica, int64_t epoch, int64_t step,
              const std::map<std::string, double>& values) {
    if (step < 0 || values.empty()) return;
    std::lock_guard<std::mutex> g(mu_);
    auto rit = data_.find(replica);
    if (rit == data_.end()) {
      if (data_.size() >= kMaxReplicas) {
        // evict the replica whose newest sample is stalest — dead uuids
        // from long-gone respawn generations go first, and the CURRENT
        // incident's rings (actively written) are never the minimum
        auto oldest = data_.begin();
        uint64_t oldest_seq = UINT64_MAX;
        for (auto it = data_.begin(); it != data_.end(); ++it) {
          uint64_t seq = last_ingest_seq_.count(it->first)
                             ? last_ingest_seq_[it->first]
                             : 0;
          if (seq < oldest_seq) {
            oldest_seq = seq;
            oldest = it;
          }
        }
        last_ingest_seq_.erase(oldest->first);
        data_.erase(oldest);
      }
      rit = data_.emplace(replica, std::map<std::string, Ring>{}).first;
    }
    last_ingest_seq_[replica] = ++ingest_seq_;
    auto& series = rit->second;
    for (const auto& [name, value] : values) {
      auto sit = series.find(name);
      if (sit == series.end()) {
        if (series.size() >= max_series_) {
          dropped_series_++;  // loud on /metrics, never silent
          continue;
        }
        sit = series.emplace(name, Ring{}).first;
        sit->second.cap = retain_;
        sit->second.buf.reserve(retain_ < 64 ? retain_ : 64);
      }
      sit->second.add(Sample{epoch, step, value});
    }
  }

  uint64_t dropped_series() const {
    std::lock_guard<std::mutex> g(mu_);
    return dropped_series_;
  }

  // Full ordered copy (C-ABI snapshot + tests).
  std::map<std::string, std::map<std::string, std::vector<Sample>>> dump()
      const {
    std::lock_guard<std::mutex> g(mu_);
    std::map<std::string, std::map<std::string, std::vector<Sample>>> out;
    for (const auto& [rid, series] : data_)
      for (const auto& [name, ring] : series)
        out[rid][name] = ring.ordered();
    return out;
  }

  void reset() {
    std::lock_guard<std::mutex> g(mu_);
    data_.clear();
    last_ingest_seq_.clear();
    dropped_series_ = 0;
  }

  // Range-query JSON for GET /timeseries.json. Filters: substring match
  // on replica/series (empty = all), since = exclusive step cursor,
  // max_points = stride-downsample cap per series (0 = raw; the LAST
  // sample always survives so a cursor loop never misses the tip).
  // json_escape is injected so this header stays independent of coord.cc.
  template <typename Esc>
  std::string render_json(const std::string& replica_filter,
                          const std::string& series_filter,
                          int64_t since_step, size_t max_points,
                          int64_t now_unix_ms, Esc json_escape) const {
    std::lock_guard<std::mutex> g(mu_);
    std::ostringstream o;
    char buf[64];
    // cursor.max_step is documented as "the next `since` value": when a
    // since-filtered query matches nothing new it must echo the cursor
    // back, never regress to -1 (an idle fleet would reset incremental
    // consumers into refetching the whole retention window)
    int64_t fleet_max_step = since_step;
    o << "{\"retain\":" << retain_ << ",\"now_unix_ms\":" << now_unix_ms
      << ",\"dropped_series\":" << dropped_series_ << ",\"replicas\":{";
    bool first_r = true;
    for (const auto& [rid, series] : data_) {
      if (!replica_filter.empty() &&
          rid.find(replica_filter) == std::string::npos)
        continue;
      if (!first_r) o << ",";
      first_r = false;
      o << "\"" << json_escape(rid) << "\":{";
      bool first_s = true;
      for (const auto& [name, ring] : series) {
        if (!series_filter.empty() &&
            name.find(series_filter) == std::string::npos)
          continue;
        std::vector<Sample> all = ring.ordered();
        std::vector<const Sample*> sel;
        sel.reserve(all.size());
        for (const auto& s : all)
          if (s.step > since_step) sel.push_back(&s);
        size_t stride = 1;
        if (max_points > 0 && sel.size() > max_points)
          stride = (sel.size() + max_points - 1) / max_points;
        if (!first_s) o << ",";
        first_s = false;
        o << "\"" << json_escape(name) << "\":{\"count\":" << sel.size()
          << ",\"total\":" << ring.total << ",\"stride\":" << stride
          << ",\"samples\":[";
        bool first_p = true;
        for (size_t i = 0; i < sel.size(); i++) {
          // stride-sample, but always keep the newest point: a since-
          // cursor consumer advances from the tip it actually saw
          if (i % stride != 0 && i != sel.size() - 1) continue;
          if (!first_p) o << ",";
          first_p = false;
          snprintf(buf, sizeof buf, "%.9g", sel[i]->value);
          o << "[" << sel[i]->epoch << "," << sel[i]->step << "," << buf
            << "]";
        }
        o << "]}";
        if (!sel.empty())
          fleet_max_step =
              fleet_max_step > sel.back()->step ? fleet_max_step
                                                : sel.back()->step;
      }
      o << "}";
    }
    o << "},\"cursor\":{\"max_step\":" << fleet_max_step << "}}";
    return o.str();
  }

  // Unicode sparkline of one series' newest `width` samples (dashboard
  // trend column). Empty string when the series has no samples.
  std::string spark(const std::string& replica, const std::string& name,
                    size_t width) const {
    static const char* kBlocks[] = {"▁", "▂", "▃", "▄",
                                    "▅", "▆", "▇", "█"};
    std::lock_guard<std::mutex> g(mu_);
    auto rit = data_.find(replica);
    if (rit == data_.end()) return "";
    auto sit = rit->second.find(name);
    if (sit == rit->second.end()) return "";
    std::vector<Sample> all = sit->second.ordered();
    if (all.empty()) return "";
    size_t start = all.size() > width ? all.size() - width : 0;
    double lo = all[start].value, hi = all[start].value;
    for (size_t i = start; i < all.size(); i++) {
      lo = all[i].value < lo ? all[i].value : lo;
      hi = all[i].value > hi ? all[i].value : hi;
    }
    std::string out;
    for (size_t i = start; i < all.size(); i++) {
      int idx = hi > lo
                    ? (int)((all[i].value - lo) / (hi - lo) * 7.0 + 0.5)
                    : 0;
      if (idx < 0) idx = 0;
      if (idx > 7) idx = 7;
      out += kBlocks[idx];
    }
    return out;
  }

 private:
  static constexpr size_t kMaxReplicas = 256;
  mutable std::mutex mu_;
  size_t retain_;
  size_t max_series_;
  std::map<std::string, std::map<std::string, Ring>> data_;
  std::map<std::string, uint64_t> last_ingest_seq_;
  uint64_t ingest_seq_ = 0;
  uint64_t dropped_series_ = 0;
};

inline Store& store() {
  static Store s;
  return s;
}

}  // namespace tsdb
}  // namespace tft
