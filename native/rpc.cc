#include "rpc.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <random>

#include "blackbox.h"     // crash-durable rpc.serve breadcrumbs
#include "faultinject.h"  // env-gated injection points (torn frames, delays)
#include "lathist.h"      // rpc.serve latency histogram
#include "profiler.h"     // always-on sampling (rpc serve / quorum fan-out)

namespace tft {

int64_t now_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(steady_clock::now().time_since_epoch())
      .count();
}

std::string errno_str(int e) {
  char buf[128];
  // GNU strerror_r: fills buf OR returns a pointer to an immutable
  // static string — either way no shared mutable state (see rpc.h)
  return std::string(strerror_r(e, buf, sizeof(buf)));
}

static void set_keepalive(int fd) {
  int on = 1;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &on, sizeof(on));
  // Mirror the reference's HTTP2 keepalive cadence (60s interval / 20s
  // timeout, src/net.rs:11-16) at the TCP level.
  int idle = 60, intvl = 20, cnt = 3;
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
  int nodelay = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nodelay, sizeof(nodelay));
}

bool parse_addr(const std::string& addr, std::string* host, int* port) {
  std::string a = addr;
  for (const char* scheme : {"http://", "tft://", "tcp://"}) {
    if (a.rfind(scheme, 0) == 0) {
      a = a.substr(strlen(scheme));
      break;
    }
  }
  // strip any trailing path
  auto slash = a.find('/');
  if (slash != std::string::npos) a = a.substr(0, slash);
  // [v6]:port or host:port
  if (!a.empty() && a[0] == '[') {
    auto close = a.find(']');
    if (close == std::string::npos) return false;
    *host = a.substr(1, close - 1);
    if (close + 1 >= a.size() || a[close + 1] != ':') return false;
    *port = atoi(a.c_str() + close + 2);
    return true;
  }
  auto colon = a.rfind(':');
  if (colon == std::string::npos) return false;
  *host = a.substr(0, colon);
  *port = atoi(a.c_str() + colon + 1);
  return *port > 0 || a.substr(colon + 1) == "0";
}

int tcp_listen(const std::string& bind_addr, std::string* err) {
  std::string host;
  int port = 0;
  if (!parse_addr(bind_addr, &host, &port)) {
    if (err) *err = "bad bind address: " + bind_addr;
    return -1;
  }
  // Prefer IPv6 dual-stack like the reference's default [::] bind.
  bool v6 = host.empty() || host == "::" || host.find(':') != std::string::npos;
  int fd = socket(v6 ? AF_INET6 : AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    if (err) *err = std::string("socket: ") + errno_str(errno);
    return -1;
  }
  int on = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &on, sizeof(on));
  int rc;
  if (v6) {
    int off = 0;
    setsockopt(fd, IPPROTO_IPV6, IPV6_V6ONLY, &off, sizeof(off));
    sockaddr_in6 sa{};
    sa.sin6_family = AF_INET6;
    sa.sin6_port = htons((uint16_t)port);
    if (host.empty() || host == "::")
      sa.sin6_addr = in6addr_any;
    else if (inet_pton(AF_INET6, host.c_str(), &sa.sin6_addr) != 1) {
      if (err) *err = "bad v6 address: " + host;
      close(fd);
      return -1;
    }
    rc = bind(fd, (sockaddr*)&sa, sizeof(sa));
  } else {
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    if (host.empty() || host == "0.0.0.0")
      sa.sin_addr.s_addr = INADDR_ANY;
    else if (inet_pton(AF_INET, host.c_str(), &sa.sin_addr) != 1) {
      // resolve hostname
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res) {
        if (err) *err = "cannot resolve: " + host;
        close(fd);
        return -1;
      }
      sa.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    rc = bind(fd, (sockaddr*)&sa, sizeof(sa));
  }
  if (rc != 0 || listen(fd, 1024) != 0) {
    if (err) *err = std::string("bind/listen: ") + errno_str(errno);
    close(fd);
    return -1;
  }
  return fd;
}

int listen_port(int fd) {
  sockaddr_storage ss{};
  socklen_t len = sizeof(ss);
  if (getsockname(fd, (sockaddr*)&ss, &len) != 0) return 0;
  if (ss.ss_family == AF_INET6) return ntohs(((sockaddr_in6*)&ss)->sin6_port);
  return ntohs(((sockaddr_in*)&ss)->sin_port);
}

int tcp_connect(const std::string& host, int port, int64_t timeout_ms,
                std::string* err) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof(portbuf), "%d", port);
  std::string h = host.empty() ? "localhost" : host;
  int rc = getaddrinfo(h.c_str(), portbuf, &hints, &res);
  if (rc != 0 || !res) {
    if (err) *err = "resolve " + h + ": " + gai_strerror(rc);
    return -1;
  }
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    // non-blocking connect with timeout
    int flags = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    rc = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      rc = poll(&pfd, 1, (int)timeout_ms);
      if (rc == 1) {
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
        rc = soerr == 0 ? 0 : -1;
        if (soerr != 0 && err) *err = errno_str(soerr);
      } else {
        rc = -1;
        if (err) *err = "connect timeout";
      }
    } else if (rc != 0 && err) {
      *err = errno_str(errno);
    }
    if (rc == 0) {
      fcntl(fd, F_SETFL, flags);  // back to blocking
      set_keepalive(fd);
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0 && err && err->empty()) *err = "connect failed";
  return fd;
}

bool read_exact(int fd, void* buf, size_t n, int64_t deadline_abs_ms) {
  char* p = (char*)buf;
  while (n > 0) {
    if (deadline_abs_ms > 0) {
      int64_t left = deadline_abs_ms - now_ms();
      if (left <= 0) return false;
      pollfd pfd{fd, POLLIN, 0};
      int rc = poll(&pfd, 1, (int)std::min<int64_t>(left, 60000));
      if (rc == 0) continue;  // re-check deadline
      if (rc < 0) {
        if (errno == EINTR) continue;
        return false;
      }
    }
    ssize_t k = recv(fd, p, n, 0);
    if (k == 0) return false;
    if (k < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= (size_t)k;
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n > 0) {
    ssize_t k = send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= (size_t)k;
  }
  return true;
}

// ---- server --------------------------------------------------------------

bool RpcServer::start(const std::string& bind_addr, RpcHandler handler,
                      HttpHandler http_handler, std::string* err) {
  listen_fd_ = tcp_listen(bind_addr, err);
  if (listen_fd_ < 0) return false;
  port_ = listen_port(listen_fd_);
  handler_ = std::move(handler);
  http_handler_ = std::move(http_handler);
  running_.store(true);
  accept_thread_ = std::thread([this] { accept_loop(); });
  return true;
}

void RpcServer::shutdown() {
  if (!running_.exchange(false)) return;
  ::shutdown(listen_fd_, SHUT_RDWR);
  close(listen_fd_);
  {
    std::lock_guard<std::mutex> g(conns_mu_);
    for (int fd : conns_) ::shutdown(fd, SHUT_RDWR);
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  // Join all connection threads. Owners must cancel any in-handler blocking
  // waits (cv broadcasts, client aborts) *before* calling this so the join
  // completes promptly; once it returns, no thread touches handler state.
  std::map<uint64_t, std::thread> threads;
  {
    std::lock_guard<std::mutex> g(conns_mu_);
    threads.swap(conn_threads_);
  }
  for (auto& [id, t] : threads)
    if (t.joinable()) t.join();
}

void RpcServer::accept_loop() {
  while (running_.load()) {
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      break;  // listener closed
    }
    set_keepalive(fd);
    std::vector<std::thread> reaped;
    {
      std::lock_guard<std::mutex> g(conns_mu_);
      // Reap threads for connections that already finished (join is
      // instant once a thread has announced itself in finished_threads_).
      for (uint64_t id : finished_threads_) {
        auto it = conn_threads_.find(id);
        if (it != conn_threads_.end()) {
          reaped.push_back(std::move(it->second));
          conn_threads_.erase(it);
        }
      }
      finished_threads_.clear();
      conns_.insert(fd);
      uint64_t id = next_thread_id_++;
      conn_threads_.emplace(id, std::thread([this, fd, id] {
        // one guard covers the whole connection: rpc dispatch AND the
        // ManagerSrv quorum fan-out both run on these threads, so their
        // stacks land in the "rpc.serve" collapsed-stack bucket
        prof::ThreadGuard prof_guard("rpc.serve");
        serve_conn(fd);
        std::lock_guard<std::mutex> g2(conns_mu_);
        conns_.erase(fd);
        close(fd);
        finished_threads_.push_back(id);
      }));
    }
    for (auto& t : reaped)
      if (t.joinable()) t.join();
  }
}

static std::string http_error(int code, const std::string& msg) {
  char head[128];
  snprintf(head, sizeof(head),
           "HTTP/1.1 %d Error\r\nContent-Type: text/plain\r\nContent-Length: "
           "%zu\r\nConnection: close\r\n\r\n",
           code, msg.size());
  return std::string(head) + msg;
}

void RpcServer::serve_conn(int fd) {
  char magic[4];
  if (!read_exact(fd, magic, 4, 0)) return;
  if (memcmp(magic, "TFT1", 4) != 0) {
    // Plain HTTP (dashboard / status) on the same port, like the
    // reference's accept_http1 tonic server (src/lighthouse.rs:349-355).
    std::string req(magic, 4);
    char c;
    // read until end of headers (or 64KB cap)
    while (req.size() < 65536 &&
           req.find("\r\n\r\n") == std::string::npos) {
      ssize_t k = recv(fd, &c, 1, 0);
      if (k <= 0) return;
      req.push_back(c);
    }
    auto sp1 = req.find(' ');
    auto sp2 = req.find(' ', sp1 + 1);
    if (sp1 == std::string::npos || sp2 == std::string::npos) {
      std::string resp = http_error(400, "bad request");
      write_all(fd, resp.data(), resp.size());
      return;
    }
    std::string method = req.substr(0, sp1);
    std::string path = req.substr(sp1 + 1, sp2 - sp1 - 1);
    std::string resp;
    if (http_handler_) {
      try {
        resp = http_handler_(method, path);
      } catch (const std::exception& e) {
        resp = http_error(500, std::string("Something went wrong: ") + e.what());
      }
    } else {
      resp = http_error(404, "not found");
    }
    write_all(fd, resp.data(), resp.size());
    return;
  }
  // Frame loop.
  while (running_.load()) {
    uint8_t lenbuf[4];
    if (!read_exact(fd, lenbuf, 4, 0)) return;
    uint32_t len = (uint32_t)lenbuf[0] | ((uint32_t)lenbuf[1] << 8) |
                   ((uint32_t)lenbuf[2] << 16) | ((uint32_t)lenbuf[3] << 24);
    if (len > (1u << 30)) return;  // 1GB sanity cap
    std::string payload(len, '\0');
    if (!read_exact(fd, payload.data(), len, 0)) return;

    Value resp = Value::M();
    // rpc.serve distribution: dispatch + handler time, error paths
    // included (socket reads excluded; a long-poll quorum wait is part
    // of the handler by design and shows up here — the serve tail IS
    // the control plane's latency story)
    int64_t serve_t0 = lathist::now_ns();
    try {
      Value req = decode(payload);
      std::string method = req.gets("_m");
      int64_t timeout_ms = req.geti("_d", 60000);
      int64_t deadline = now_ms() + timeout_ms;
      // env-gated injection: stretch this method's server-side handling
      // (e.g. TORCHFT_FI_SRV_DELAY=mgr.should_commit:200 is a commit-vote
      // RTT the pipelined mode must hide)
      static const fi::MethodSpec fi_dly =
          fi::parse_method("TORCHFT_FI_SRV_DELAY");
      if (fi_dly.n > 0 && method == fi_dly.method) fi::sleep_ms(fi_dly.n);
      resp = handler_(method, req, deadline);
      if (resp.type != Value::Type::MAP) resp = Value::M();
      resp.set("_s", Value::I(OK));
    } catch (const RpcError& e) {
      resp = Value::M();
      resp.set("_s", Value::I(e.code));
      resp.set("_e", Value::S(e.what()));
    } catch (const std::exception& e) {
      resp = Value::M();
      resp.set("_s", Value::I(INTERNAL));
      resp.set("_e", Value::S(e.what()));
    }
    int64_t serve_ns = lathist::now_ns() - serve_t0;
    lathist::observe(lathist::kRpcServe, (double)serve_ns / 1e9);
    // crash-durable breadcrumb: the last RPCs a dying server handled
    // (a = status code, b = serve ns) survive a SIGKILL mid-serve
    bb::record(bb::kRpcServe, -1, -1, resp.geti("_s", OK), serve_ns);
    std::string body = encode(resp);
    uint8_t out[4] = {(uint8_t)(body.size() & 0xff),
                      (uint8_t)((body.size() >> 8) & 0xff),
                      (uint8_t)((body.size() >> 16) & 0xff),
                      (uint8_t)((body.size() >> 24) & 0xff)};
    if (!write_all(fd, out, 4) || !write_all(fd, body.data(), body.size()))
      return;
  }
}

// ---- client --------------------------------------------------------------

RpcClient::RpcClient(const std::string& addr, int64_t connect_timeout_ms)
    : addr_(addr), connect_timeout_ms_(connect_timeout_ms) {
  if (!parse_addr(addr, &host_, &port_))
    throw RpcError(INVALID_ARGUMENT, "bad address: " + addr);
  std::lock_guard<std::mutex> g(mu_);
  ensure_connected(connect_timeout_ms);
}

RpcClient::~RpcClient() { disconnect(); }

void RpcClient::disconnect() {
  // close only ever happens under fd_mu_ — see abort()
  std::lock_guard<std::mutex> g(fd_mu_);
  int fd = fd_.exchange(-1);
  if (fd >= 0) close(fd);
}

void RpcClient::abort() {
  // Intentionally does not take mu_ (a blocked call() holds it — making
  // that call fail fast is the whole point). shutdown() on the fd makes
  // the blocked recv/send fail; the call() path then disconnects and
  // reconnects on next use. fd_mu_ serializes us against disconnect()'s
  // close: without it, the fd NUMBER could be closed and recycled by an
  // unrelated subsystem (stripe socket, checkpoint HTTP) between our
  // load and the shutdown, tearing down someone else's live connection.
  std::lock_guard<std::mutex> g(fd_mu_);
  int fd = fd_.load();
  if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
}

void RpcClient::ensure_connected(int64_t timeout_ms) {
  if (fd_ >= 0) return;
  // Exponential backoff retry, parity with src/retry.rs:6-41
  // (initial 10ms per lib.rs usage, factor 2, max 3s, jitter).
  int64_t deadline = now_ms() + timeout_ms;
  int64_t backoff = 10;
  std::mt19937_64 rng(std::random_device{}());
  std::string err;
  while (true) {
    int64_t left = deadline - now_ms();
    if (left <= 0)
      throw RpcError(UNAVAILABLE,
                     "connect to " + addr_ + " timed out: " + err);
    int fd = tcp_connect(host_, port_, std::min<int64_t>(left, 5000), &err);
    if (fd >= 0) {
      if (!write_all(fd, "TFT1", 4)) {
        close(fd);
        err = "handshake write failed";
      } else {
        fd_ = fd;
        return;
      }
    }
    int64_t jitter = (int64_t)(rng() % (backoff / 2 + 1));
    int64_t sleep_ms = std::min<int64_t>(backoff + jitter, deadline - now_ms());
    if (sleep_ms > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(sleep_ms));
    backoff = std::min<int64_t>(backoff * 2, 3000);
  }
}

Value RpcClient::call(const std::string& method, Value req, int64_t timeout_ms) {
  std::lock_guard<std::mutex> g(mu_);
  ensure_connected(connect_timeout_ms_);
  req.set("_m", Value::S(method));
  req.set("_d", Value::I(timeout_ms));
  std::string body = encode(req);
  uint8_t lenbuf[4] = {(uint8_t)(body.size() & 0xff),
                       (uint8_t)((body.size() >> 8) & 0xff),
                       (uint8_t)((body.size() >> 16) & 0xff),
                       (uint8_t)((body.size() >> 24) & 0xff)};
  // env-gated injection: cut the nth call to <method> mid-body — a torn
  // control-plane frame (the server must drop the desynced stream, the
  // caller sees UNAVAILABLE and retries on a fresh connection)
  static const fi::MethodSpec fi_cut = fi::parse_method("TORCHFT_FI_RPC_CUT");
  if (fi_cut.n > 0 && method == fi_cut.method) {
    static std::atomic<long> fi_calls{0};
    long c = ++fi_calls;
    if (c == fi_cut.n) {
      fi::write_evidence("rpc.send", c, "torn");
      write_all(fd_, lenbuf, 4);
      write_all(fd_, body.data(), body.size() / 2);
      ::shutdown(fd_, SHUT_RDWR);
      disconnect();
      throw RpcError(UNAVAILABLE,
                     "fault injection: torn rpc frame for " + method);
    }
  }
  if (!write_all(fd_, lenbuf, 4) || !write_all(fd_, body.data(), body.size())) {
    disconnect();
    throw RpcError(UNAVAILABLE, "send to " + addr_ + " failed");
  }
  // Client-side deadline = request deadline + grace so the server-side
  // DEADLINE_EXCEEDED normally wins; a dead server trips this instead.
  int64_t deadline = now_ms() + timeout_ms + 2000;
  uint8_t rlen[4];
  if (!read_exact(fd_, rlen, 4, deadline)) {
    disconnect();
    throw RpcError(DEADLINE_EXCEEDED, method + " to " + addr_ + " timed out");
  }
  uint32_t len = (uint32_t)rlen[0] | ((uint32_t)rlen[1] << 8) |
                 ((uint32_t)rlen[2] << 16) | ((uint32_t)rlen[3] << 24);
  if (len > (1u << 30)) {
    disconnect();
    throw RpcError(INTERNAL, "oversized response");
  }
  std::string payload(len, '\0');
  if (!read_exact(fd_, payload.data(), len, deadline)) {
    disconnect();
    throw RpcError(DEADLINE_EXCEEDED, method + " response truncated/timed out");
  }
  Value resp = decode(payload);
  int64_t status = resp.geti("_s", INTERNAL);
  if (status != OK)
    throw RpcError((Status)status, resp.gets("_e", "unknown error"));
  return resp;
}

}  // namespace tft
