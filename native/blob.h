// torchft_tpu native core — striped checkpoint blob plane.
//
// The checkpoint-transfer sibling of the gradient data plane: a healer
// pulls byte ranges of the staged (flattened) state tree from every live
// peer in parallel, GIL-free, over the shared stripe layer (stripe.h).
// The Python HTTP transport stays the control plane (metadata, stripe
// plan, differential negotiation); this plane only moves the bulk bytes
// — one BlobServer per checkpoint transport, staged/unstaged in lockstep
// with the HTTP serving window so both planes serve the same bytes.
//
// Protocol (per request; connections are one-shot per range — the
// client is a short-lived fetch thread and loopback/DC connection setup
// is noise next to MB-scale ranges):
//
//   client -> BlobReq { magic, token, offset, len }
//   server -> BlobRsp { magic, status, len } + len payload bytes
//
// `token` names the staging generation: a request against a stale or
// unstaged window is answered with kStale and NO payload, so a healer
// can never stream bytes from a superseded checkpoint (the torn-state
// class of bugs the PR 4 ckpt_serve_death scenario guards against).
#ifndef TFT_BLOB_H_
#define TFT_BLOB_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace tft {

struct BlobReq {
  uint32_t magic;
  uint32_t reserved;
  uint64_t token;
  uint64_t offset;
  uint64_t len;
};

struct BlobRsp {
  uint32_t magic;
  uint32_t status;  // BlobStatus
  uint64_t len;
};

enum class BlobStatus : uint32_t {
  kOk = 0,
  kStale = 1,     // token does not match the staged generation
  kBadRange = 2,  // offset/len outside the staged blob
};

constexpr uint32_t kBlobMagic = 0x7F7A0DB1;  // distinct from dp/ctl hellos

class BlobServer {
 public:
  // Listens on an ephemeral port and starts the acceptor. Throws
  // std::runtime_error on bind failure.
  BlobServer();
  ~BlobServer();

  BlobServer(const BlobServer&) = delete;
  BlobServer& operator=(const BlobServer&) = delete;

  int port() const { return port_; }

  // Stage the logical concatenation of `nbufs` scattered buffers (the
  // flattened state tree's host arrays — no coalescing copy). The caller
  // (Python transport) must keep the buffers alive until unstage()
  // returns. `token` names this staging generation.
  void stage(const uint64_t* bases, const int64_t* lens, int nbufs,
             uint64_t token);

  // Close the serving window: mark the generation stale, kick in-flight
  // serves off their sockets, and return once no serve still reads the
  // staged buffers (so the caller may free them). Bounded: active
  // connections are shut down first, so serves fail fast.
  void unstage();

  void shutdown();

 private:
  void accept_loop();
  void serve_conn(int fd, uint64_t id);
  bool serve_one(int fd, const BlobReq& req, int64_t deadline_ms,
                 std::string* err);

  int listen_fd_ = -1;
  int port_ = 0;
  std::thread acceptor_;
  std::atomic<bool> closed_{false};

  std::mutex mu_;
  std::condition_variable cv_;
  bool staged_ = false;           // guarded-by: mu_
  uint64_t token_ = 0;            // guarded-by: mu_
  std::vector<uint64_t> bases_;   // guarded-by: mu_
  std::vector<int64_t> lens_;     // guarded-by: mu_
  std::vector<uint64_t> prefix_;  // guarded-by: mu_ (prefix[i] = start of buf i)
  uint64_t total_ = 0;            // guarded-by: mu_
  int active_serves_ = 0;         // guarded-by: mu_ (serves inside a payload)
  std::set<int> conn_fds_;        // guarded-by: mu_ (live connections)
  // connection handler threads, reaped by the acceptor (same pattern as
  // the data plane's hello threads: finished handlers announce their id,
  // the accept loop joins them — a long-lived process serving many heals
  // must not accumulate joinable thread stacks until shutdown)
  std::map<uint64_t, std::thread> conn_threads_;  // guarded-by: mu_
  std::vector<uint64_t> conn_finished_;           // guarded-by: mu_
};

// Client side: pull `len` bytes at `offset` of the staged blob into
// `dst`. Returns 0 on success, -1 on failure (mid-stream EOF, stale
// token, bad range — *err says which), -2 on deadline.
int blob_fetch(const std::string& host, int port, uint64_t token,
               uint64_t offset, uint64_t len, void* dst, int64_t timeout_ms,
               std::string* err);

}  // namespace tft

#endif  // TFT_BLOB_H_
