// torchft_tpu native core — coordination types + pure quorum logic +
// Lighthouse / Manager servers.
//
// C++ re-implementation of the reference's Rust coordination core:
//   * Lighthouse  — global quorum over replica groups
//     (/root/reference/src/lighthouse.rs)
//   * Manager     — per-replica-group rank arbiter
//     (/root/reference/src/manager.rs)
// The two decision procedures (quorum_compute, compute_quorum_results) are
// pure functions over value types, exactly as in the reference, so they are
// unit-testable without any sockets.
#pragma once

#include <atomic>
#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "rpc.h"
#include "telemetry_delta.h"
#include "wire.h"

namespace tft {

// ---- wire-level data types (proto/torchft.proto analogues) ---------------

// proto QuorumMember (torchft.proto:38-45)
struct QuorumMember {
  std::string replica_id;
  std::string address;        // manager RPC address
  std::string store_address;  // replica group's KV store address
  int64_t step = 0;
  uint64_t world_size = 0;
  bool shrink_only = false;
  // Which transport carries this group's large allreduces (reported by
  // the Python Manager: "cma" | "tcp-striped" | "python-ring" | "device"
  // ...); surfaced on the dashboard/metrics so an operator can see a
  // group that silently fell back to a slower plane (round-4 review).
  std::string plane;
  // Data-plane flush request (extension beyond the reference): a group whose
  // collectives latched an error asks for a quorum_id bump so EVERY group
  // reconfigures into a fresh rendezvous epoch — the reference can only
  // recover a wedged backend via process restart (membership change).
  int64_t commit_failures = 0;

  Value to_value() const;
  static QuorumMember from_value(const Value& v);
};

// proto Quorum (torchft.proto:47-51)
struct Quorum {
  int64_t quorum_id = 0;
  std::vector<QuorumMember> participants;
  int64_t created_unix_ms = 0;

  Value to_value() const;
  static Quorum from_value(const Value& v);
};

// proto ManagerQuorumResponse (torchft.proto:79-93)
struct ManagerQuorumResult {
  int64_t quorum_id = 0;
  std::string recover_src_manager_address;
  std::optional<int64_t> recover_src_rank;
  std::vector<int64_t> recover_dst_ranks;
  std::string store_address;
  int64_t max_step = 0;
  std::optional<int64_t> max_rank;
  int64_t max_world_size = 0;
  int64_t replica_rank = 0;
  int64_t replica_world_size = 0;
  bool heal = false;       // this rank fetches recovery state
  bool group_heal = false; // any local rank heals → the whole group
                           // contributes zeros (participation gate must be
                           // rank-plane-consistent; extension beyond the
                           // reference's per-rank flag, manager.py:268-269)
  // Quorum members' replica_ids in replica_rank order, so the data plane can
  // map a failed peer's ring rank back to a replica_id for lh.evict reports.
  std::vector<std::string> participant_ids;
  // Striped multi-source heal (docs/heal_plane.md): manager addresses of
  // EVERY max-step cohort member (bit-identical committed state, so any
  // of them can serve any stripe) — except at bootstrap (max_step == 0),
  // where states are not yet proven identical and only the single
  // bootstrap source is listed. heal_pending tells up-to-date members
  // that SOMEONE heals this round, so they all stage a checkpoint even
  // when the round-robin assigned them no healer of their own.
  std::vector<std::string> recover_src_addresses;
  bool heal_pending = false;

  Value to_value() const;
};

// ---- pure decision procedures --------------------------------------------

struct LighthouseOpt {
  uint64_t min_replicas = 1;
  uint64_t join_timeout_ms = 60000;
  uint64_t quorum_tick_ms = 100;
  uint64_t heartbeat_timeout_ms = 5000;
  // Survivor-reported eviction (lh.evict): before expiring an accused
  // replica's heartbeat, the lighthouse actively probes its manager address
  // with this connect timeout. Probe success = report ignored, so a false
  // report about a live peer is a no-op; probe failure = immediate expiry,
  // beating the passive heartbeat-lease floor (src/lighthouse.rs:119-128
  // has only the passive path).
  uint64_t evict_probe_ms = 100;
};

struct MemberDetails {
  int64_t joined_ms = 0;  // monotonic timestamp of quorum join
  QuorumMember member;
};

struct LighthouseState {
  std::map<std::string, MemberDetails> participants;
  std::map<std::string, int64_t> heartbeats;  // replica_id -> last beat (ms)
  std::optional<Quorum> prev_quorum;
  int64_t quorum_id = 0;
};

// Per-replica telemetry snapshot, piggybacked by replicas on their quorum
// (and optionally heartbeat) traffic. The lighthouse stores it verbatim —
// the summary is an opaque JSON object and the span batches are raw Chrome
// trace-event fragments — so the Python telemetry schema can evolve
// without touching the C++ core.
struct ReplicaTelemetry {
  int64_t last_ms = 0;      // wall-clock ms of the last report
  int64_t step = -1;        // replica's committed step at report time
  bool stuck = false;       // step watchdog latched a stall
  double last_heal_ts = 0;  // unix seconds of the last heal (0 = never)
  // Step-anatomy scalars (ISSUE 8): the replica's rolling p50 of LOCAL
  // step time (wall minus peer-wait phases — the straggler-discriminating
  // signal, computed replica-side by telemetry.anatomy), and the
  // replica-side burn-rate SLO evaluator's latched breach flag (rendered
  // as a red column next to STUCK).
  double local_step_p50_s = 0;
  bool slo_breach = false;
  std::string summary_json; // compact counters digest (JSON object)
  std::string anatomy_json; // per-phase step-anatomy digest (JSON object)
  // Reports whose anatomy digest exceeded the 64 KiB piggyback cap: the
  // digest is DROPPED (never truncated into /cluster.json — a sliced
  // JSON object would parse as garbage downstream) and this counter
  // makes the drop loud on /cluster.json + /metrics (ISSUE 11).
  int64_t anatomy_oversized = 0;
  // Diagnosis-bundle availability (ISSUE 12): replicas announce how many
  // latch-triggered deep-capture bundles they have written under their
  // TORCHFT_DIAG_DIR, plus the most recent bundle's name and the
  // replica-local directory — served at GET /diagnosis.json so an
  // operator (or the postmortem tool) knows where the evidence lives
  // without asking every host.
  int64_t diag_bundles = 0;
  std::string diag_last;  // most recent bundle name (size-capped)
  std::string diag_dir;   // replica-local bundle directory (size-capped)
  std::vector<std::string> span_batches;  // chrome trace-event fragments
  size_t span_bytes = 0;    // bytes across span_batches (for the cap)
};

// Returns (members or nullopt, human-readable reason).
// Mirrors quorum_compute (src/lighthouse.rs:113-241): healthy-filter by
// heartbeat age, shrink_only candidate filtering, fast quorum when all prev
// members are healthy participants, min_replicas floor, split-brain guard
// (participants must exceed half the heartbeating set), join-timeout
// straggler wait.
std::pair<std::optional<std::vector<QuorumMember>>, std::string> quorum_compute(
    int64_t now_ms, const LighthouseState& state, const LighthouseOpt& opt);

// Mirrors compute_quorum_results (src/manager.rs:357-480): sort by
// replica_id; max-step cohort; primary store selection rank % cohort;
// recover_dst = behind-or-(step0-non-primary); round-robin source
// assignment offset by local rank.
// Throws RpcError(NOT_FOUND) if replica_id is absent from the quorum.
ManagerQuorumResult compute_quorum_results(const std::string& replica_id,
                                           int64_t rank, const Quorum& quorum);

// ---- Lighthouse server ----------------------------------------------------

class Lighthouse {
 public:
  Lighthouse(const std::string& bind, const LighthouseOpt& opt);
  ~Lighthouse();
  void shutdown();

  std::string address() const;
  int port() const { return server_.port(); }

 private:
  friend class LighthouseTestPeer;
  Value handle_rpc(const std::string& method, const Value& req,
                   int64_t deadline);
  Value handle_quorum(const Value& req, int64_t deadline);
  Value handle_evict(const Value& req);
  // Divergence sentinel (lh.digest): record one replica's commit-time
  // state digest for its (epoch, step) cohort, compare within the
  // cohort, latch on mismatch; wait=true long-polls until the full
  // cohort reported (the fence path).
  Value handle_digest(const Value& req, int64_t deadline);
  std::string handle_http(const std::string& method, const std::string& path);
  void tick_loop();
  // Must hold mu_. Runs one quorum evaluation and publishes if met.
  void quorum_tick();
  // Must hold mu_. Stores one replica's piggybacked telemetry report.
  void ingest_telemetry(const std::string& replica_id, const Value& v);
  // Must hold mu_. Applies one delta-encoded piggyback blob (ISSUE 16)
  // onto the replica's incarnation chain and refreshes the legacy
  // ReplicaTelemetry row from the decoded flat state.
  void ingest_tdelta(const std::string& replica_id, const std::string& blob);
  // Must hold mu_. Per-replica telemetry ack for quorum replies:
  // {incarnation_hex: {"ver": version, "resync": bool}}.
  Value telemetry_ack(const std::string& replica_id);
  // Must hold mu_. Time-gated fold of the fleet histograms into the
  // TSDB's "_fleet" pseudo-replica (TORCHFT_TELEMETRY_ROLLUP_S cadence).
  void maybe_rollup_fleet();
  std::string status_html();
  std::string cluster_json(const std::string& query);
  std::string fleet_json(const std::string& query);
  std::string diagnosis_json();
  std::string merged_trace_json();
  static std::string http_error_page(const std::string& msg);

  LighthouseOpt opt_;
  RpcServer server_;
  std::string hostname_;

  std::mutex mu_;
  std::condition_variable cv_;
  LighthouseState state_;
  uint64_t quorum_seq_ = 0;          // bumps every published quorum
  std::map<uint64_t, Quorum> published_;  // seq -> quorum (last few kept)
  std::string last_reason_;
  // FT runtime observability (round-5: dashboard shows evictions/flushes)
  int64_t evictions_total_ = 0;
  int64_t flush_requests_total_ = 0;
  std::vector<std::string> recent_evictions_;  // "victim < reporter @ unix_s"
  // Cluster telemetry aggregation (PR 2): per-replica rolling store fed by
  // piggybacked reports, served at /cluster.json and merged at /trace.
  std::map<std::string, ReplicaTelemetry> telemetry_;
  // Oversized-digest drops across all replicas (loud-degrade counter for
  // the 64 KiB piggyback cap; per-replica counts live in telemetry_).
  int64_t telemetry_oversized_total_ = 0;
  // Delta-piggyback decode chains (ISSUE 16): replica -> incarnation ->
  // state. A respawned pid shows up as a NEW incarnation: it gets a
  // fresh chain (answered with a resync request until its FULL arrives)
  // while the dead incarnation's chain ages out — it can never inherit
  // the dead pid's interning dictionary or delta base. Bounded per
  // replica (kMaxChainsPerReplica in coord.cc) and by kMaxReplicas.
  std::map<std::string, std::map<std::string, tftdelta::DecodeState>>
      delta_states_;
  // Self-metering (ISSUE 16): telemetry bytes by channel. piggyback =
  // delta blobs ingested, spans = span fragments ingested (both under
  // mu_); scrape = HTTP bytes served by the telemetry endpoints
  // (atomic: handle_http composes some replies without mu_).
  uint64_t telemetry_bytes_piggyback_ = 0;
  uint64_t telemetry_bytes_spans_ = 0;
  // relaxed-ok: monotonic stat counter bumped from HTTP serving threads
  // and read by /metrics scrapes — no ordering needed across channels
  std::atomic<uint64_t> telemetry_bytes_scrape_{0};
  uint64_t telemetry_delta_blobs_total_ = 0;
  uint64_t telemetry_delta_fulls_total_ = 0;
  uint64_t telemetry_delta_resyncs_total_ = 0;  // rejected/out-of-chain
  int64_t last_fleet_rollup_ms_ = 0;
  // Divergence sentinel (ISSUE 10): commit-time digest rounds keyed by
  // (epoch, step). Every committed step's post-reduce state is
  // bit-identical across the cohort by construction, so two distinct
  // digests in one round IS the corrupt-commit failure mode — latch it
  // before nan propagates. Bounded to the last few rounds.
  struct DigestRound {
    std::map<std::string, std::string> digests;  // replica_id -> digest
    bool diverged = false;
    // replies delivered for a diverged round: once every reporter has
    // been answered (vetoed), the round retires so the RETRY of the
    // same (epoch, step) — commit aborts don't advance the step —
    // compares fresh digests instead of inheriting the stale verdict
    // (the global latch/counter persist; only the round resets).
    int answered = 0;
  };
  std::map<std::pair<int64_t, int64_t>, DigestRound> digest_rounds_;
  bool divergence_detected_ = false;   // global latch (never clears)
  int64_t divergence_total_ = 0;       // rounds that diverged
  std::string last_divergence_;        // human-readable incident detail
  std::set<std::string> diverged_replicas_;  // red dashboard column

  std::atomic<bool> running_{true};
  std::thread tick_thread_;
};

// ---- Manager server --------------------------------------------------------

class ManagerSrv {
 public:
  ManagerSrv(const std::string& replica_id, const std::string& lighthouse_addr,
             const std::string& hostname, const std::string& bind,
             const std::string& store_addr, uint64_t world_size,
             int64_t heartbeat_interval_ms, int64_t connect_timeout_ms);
  ~ManagerSrv();
  void shutdown();

  std::string address() const;
  int port() const { return server_.port(); }

 private:
  Value handle_rpc(const std::string& method, const Value& req,
                   int64_t deadline);
  Value handle_quorum(const Value& req, int64_t deadline);
  Value handle_should_commit(const Value& req, int64_t deadline);
  void heartbeat_loop();

  std::string replica_id_;
  std::string hostname_;
  std::string store_address_;
  std::string lighthouse_addr_;
  uint64_t world_size_;
  int64_t heartbeat_interval_ms_;
  int64_t connect_timeout_ms_;

  RpcServer server_;
  std::unique_ptr<RpcClient> lighthouse_client_;  // for quorum calls

  // Divergence sentinel: one lh.digest round trip per commit when armed
  // — a per-step hot path, so keep a persistent dedicated connection
  // (the shared lighthouse_client_ may be parked in a long-poll quorum
  // call; reconnecting per commit would pay a TCP handshake every
  // step). Created eagerly so the pointer is immutable and shutdown can
  // abort a blocked fence wait; RpcClient itself serializes concurrent
  // calls and reconnects after failures/aborts.
  std::unique_ptr<RpcClient> digest_client_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::map<int64_t, std::string> checkpoint_metadata_;
  std::set<int64_t> participants_;
  int64_t pending_commit_failures_ = 0;  // max over this round's ranks
  std::string pending_plane_;  // last plane reported by a local rank
  // Telemetry piggyback: latest per-rank report this round; span
  // fragments are concatenated across ranks, scalars last-write-wins.
  Value pending_telemetry_;    // NONE when nothing to forward
  std::string pending_spans_;  // accumulated chrome fragments this round
  // Delta piggyback blobs this round (ISSUE 16): accumulated as a LIST,
  // never last-write-wins — each local rank's encoder owns a version
  // chain, and dropping one rank's blob would break its chain into a
  // permanent resync storm. Bounded (see handle_quorum).
  std::vector<std::string> pending_tdeltas_;
  size_t pending_tdelta_bytes_ = 0;
  // Most recent telemetry ack from the lighthouse's quorum reply,
  // re-attached to every local rank's mgr.quorum reply so each rank's
  // encoder sees its own incarnation's ack.
  Value last_tack_;
  uint64_t quorum_seq_ = 0;
  std::map<uint64_t, Quorum> quorums_;  // seq -> delivered quorum
  std::optional<std::string> quorum_error_;  // lighthouse failure fan-out

  std::set<int64_t> commit_votes_;
  std::set<int64_t> commit_failures_;
  uint64_t commit_seq_ = 0;
  std::map<uint64_t, bool> commit_decisions_;
  // Divergence sentinel: this round's per-rank state digests (folded in
  // rank order into one group digest and reported to the lighthouse by
  // the round-completing rank), the round's fence request, and the
  // per-decision divergence flag echoed to every local rank.
  std::map<int64_t, std::string> commit_digests_;
  bool commit_fence_ = false;
  int64_t commit_epoch_ = -1;
  std::map<uint64_t, bool> commit_divergence_;

  std::atomic<bool> running_{true};
  std::thread heartbeat_thread_;
};

// ---- KV store (TCPStore analogue) -----------------------------------------

class KvStore {
 public:
  explicit KvStore(const std::string& bind);
  ~KvStore();
  void shutdown();
  std::string address() const;
  int port() const { return server_.port(); }

 private:
  Value handle_rpc(const std::string& method, const Value& req,
                   int64_t deadline);

  RpcServer server_;
  std::string hostname_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
  std::atomic<bool> running_{true};
};

std::string get_hostname();

}  // namespace tft
