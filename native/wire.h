// torchft_tpu native core — wire codec.
//
// A compact, dependency-free binary encoding shared between the C++
// coordination core and the Python client (torchft_tpu/utils/wire.py).
// Plays the role of protobuf in the reference (/root/reference/proto/
// torchft.proto) — same message *semantics*, different encoding, since this
// image ships no gRPC/protobuf dev headers and the control-plane traffic is
// tiny (a few hundred bytes per step).
//
// Encoding (all integers little-endian):
//   value   := tag(u8) payload
//   tag     := 1 I64 | 2 F64 | 3 BOOL | 4 STR | 5 BYTES | 6 LIST | 7 MAP | 8 NONE
//   I64/F64 := 8 bytes
//   BOOL    := 1 byte
//   STR     := u32 len + utf-8 bytes      BYTES := u32 len + bytes
//   LIST    := u32 count + count values
//   MAP     := u32 count + count * (u16 keylen + key + value)
//
// RPC framing (rpc.h): 4-byte magic "TFT1" once per connection, then
// u32-length-prefixed frames, each a MAP value.
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

namespace tft {

struct WireError : std::runtime_error {
  explicit WireError(const std::string& m) : std::runtime_error(m) {}
};

struct Value {
  enum class Type : uint8_t {
    I64 = 1,
    F64 = 2,
    BOOL = 3,
    STR = 4,
    BYTES = 5,
    LIST = 6,
    MAP = 7,
    NONE = 8,
  };

  Type type = Type::NONE;
  int64_t i = 0;
  double f = 0.0;
  bool b = false;
  std::string s;  // STR and BYTES
  std::vector<Value> list;
  std::map<std::string, Value> map;

  Value() = default;

  static Value I(int64_t v) {
    Value x;
    x.type = Type::I64;
    x.i = v;
    return x;
  }
  static Value F(double v) {
    Value x;
    x.type = Type::F64;
    x.f = v;
    return x;
  }
  static Value B(bool v) {
    Value x;
    x.type = Type::BOOL;
    x.b = v;
    return x;
  }
  static Value S(std::string v) {
    Value x;
    x.type = Type::STR;
    x.s = std::move(v);
    return x;
  }
  static Value Bytes(std::string v) {
    Value x;
    x.type = Type::BYTES;
    x.s = std::move(v);
    return x;
  }
  static Value L(std::vector<Value> v = {}) {
    Value x;
    x.type = Type::LIST;
    x.list = std::move(v);
    return x;
  }
  static Value M() {
    Value x;
    x.type = Type::MAP;
    return x;
  }
  static Value None() { return Value(); }

  bool is_none() const { return type == Type::NONE; }

  bool has(const std::string& k) const {
    return type == Type::MAP && map.count(k) > 0;
  }
  const Value& at(const std::string& k) const {
    auto it = map.find(k);
    if (it == map.end()) throw WireError("missing field: " + k);
    return it->second;
  }
  // Accessors with defaults for optional fields.
  int64_t geti(const std::string& k, int64_t d = 0) const {
    auto it = map.find(k);
    return it == map.end() || it->second.is_none() ? d : it->second.i;
  }
  bool getb(const std::string& k, bool d = false) const {
    auto it = map.find(k);
    return it == map.end() || it->second.is_none() ? d : it->second.b;
  }
  std::string gets(const std::string& k, const std::string& d = "") const {
    auto it = map.find(k);
    return it == map.end() || it->second.is_none() ? d : it->second.s;
  }
  Value& set(const std::string& k, Value v) {
    map[k] = std::move(v);
    return *this;
  }
};

namespace detail {

inline void put_u8(std::string& out, uint8_t v) { out.push_back((char)v); }
inline void put_u16(std::string& out, uint16_t v) {
  out.push_back((char)(v & 0xff));
  out.push_back((char)(v >> 8));
}
inline void put_u32(std::string& out, uint32_t v) {
  for (int k = 0; k < 4; k++) out.push_back((char)((v >> (8 * k)) & 0xff));
}
inline void put_u64(std::string& out, uint64_t v) {
  for (int k = 0; k < 8; k++) out.push_back((char)((v >> (8 * k)) & 0xff));
}

struct Reader {
  const uint8_t* p;
  size_t n;
  size_t off = 0;

  void need(size_t k) const {
    if (off + k > n) throw WireError("truncated message");
  }
  uint8_t u8() {
    need(1);
    return p[off++];
  }
  uint16_t u16() {
    need(2);
    uint16_t v = (uint16_t)p[off] | ((uint16_t)p[off + 1] << 8);
    off += 2;
    return v;
  }
  uint32_t u32() {
    need(4);
    uint32_t v = 0;
    for (int k = 0; k < 4; k++) v |= (uint32_t)p[off + k] << (8 * k);
    off += 4;
    return v;
  }
  uint64_t u64() {
    need(8);
    uint64_t v = 0;
    for (int k = 0; k < 8; k++) v |= (uint64_t)p[off + k] << (8 * k);
    off += 8;
    return v;
  }
  std::string str(size_t len) {
    need(len);
    std::string s((const char*)p + off, len);
    off += len;
    return s;
  }
};

}  // namespace detail

inline void encode(const Value& v, std::string& out) {
  using detail::put_u16;
  using detail::put_u32;
  using detail::put_u64;
  using detail::put_u8;
  put_u8(out, (uint8_t)v.type);
  switch (v.type) {
    case Value::Type::I64:
      put_u64(out, (uint64_t)v.i);
      break;
    case Value::Type::F64: {
      uint64_t bits;
      std::memcpy(&bits, &v.f, 8);
      put_u64(out, bits);
      break;
    }
    case Value::Type::BOOL:
      put_u8(out, v.b ? 1 : 0);
      break;
    case Value::Type::STR:
    case Value::Type::BYTES:
      put_u32(out, (uint32_t)v.s.size());
      out.append(v.s);
      break;
    case Value::Type::LIST:
      put_u32(out, (uint32_t)v.list.size());
      for (const auto& e : v.list) encode(e, out);
      break;
    case Value::Type::MAP:
      put_u32(out, (uint32_t)v.map.size());
      for (const auto& kv : v.map) {
        put_u16(out, (uint16_t)kv.first.size());
        out.append(kv.first);
        encode(kv.second, out);
      }
      break;
    case Value::Type::NONE:
      break;
  }
}

inline std::string encode(const Value& v) {
  std::string out;
  encode(v, out);
  return out;
}

inline Value decode_one(detail::Reader& r, int depth = 0) {
  if (depth > 64) throw WireError("nesting too deep");
  Value v;
  uint8_t tag = r.u8();
  v.type = (Value::Type)tag;
  switch (v.type) {
    case Value::Type::I64:
      v.i = (int64_t)r.u64();
      break;
    case Value::Type::F64: {
      uint64_t bits = r.u64();
      std::memcpy(&v.f, &bits, 8);
      break;
    }
    case Value::Type::BOOL:
      v.b = r.u8() != 0;
      break;
    case Value::Type::STR:
    case Value::Type::BYTES:
      v.s = r.str(r.u32());
      break;
    case Value::Type::LIST: {
      uint32_t n = r.u32();
      v.list.reserve(n);
      for (uint32_t k = 0; k < n; k++) v.list.push_back(decode_one(r, depth + 1));
      break;
    }
    case Value::Type::MAP: {
      uint32_t n = r.u32();
      for (uint32_t k = 0; k < n; k++) {
        std::string key = r.str(r.u16());
        v.map[key] = decode_one(r, depth + 1);
      }
      break;
    }
    case Value::Type::NONE:
      break;
    default:
      throw WireError("bad tag " + std::to_string(tag));
  }
  return v;
}

inline Value decode(const uint8_t* p, size_t n) {
  detail::Reader r{p, n};
  Value v = decode_one(r);
  return v;
}

inline Value decode(const std::string& s) {
  return decode((const uint8_t*)s.data(), s.size());
}

// RPC status codes (mirrors the subset of gRPC statuses the reference maps
// to Python exceptions — /root/reference/src/lib.rs:380-398).
enum Status : int64_t {
  OK = 0,
  CANCELLED = 1,
  INVALID_ARGUMENT = 2,
  NOT_FOUND = 3,
  DEADLINE_EXCEEDED = 4,
  INTERNAL = 5,
  UNAVAILABLE = 6,
};

struct RpcError : std::runtime_error {
  Status code;
  RpcError(Status c, const std::string& m) : std::runtime_error(m), code(c) {}
};

}  // namespace tft
