#!/usr/bin/env python3
"""clang-tidy gate for the native plane (driven by `make -C native tidy`).

Runs clang-tidy (config: native/.clang-tidy) over the given sources,
normalizes each finding to a stable key

    <check-id>:<file>:<function-or-line-bucket>

and diffs the set against ``tidy-baseline.txt``:

  * a finding NOT in the baseline  -> NEW, gate fails (exit 1)
  * a baseline entry that no longer fires -> STALE, gate fails (exit 1)
    (same stale-suppression contract as torchft_tpu/analysis)
  * clang-tidy binary missing      -> exit 3 with instructions

Keys bucket line numbers to the nearest 10 so unrelated edits above a
baselined finding don't churn the baseline; refresh with --update.

The dev container ships g++ only (no llvm) — exit 3 there is expected
and documented in docs/static_analysis.md; the locally-runnable subset
is `make -C native warn` (strict gcc warnings, -Werror).
"""

from __future__ import annotations

import argparse
import re
import shutil
import subprocess
import sys

# clang-tidy diagnostic line: <path>:<line>:<col>: warning: <msg> [<check>]
_DIAG = re.compile(
    r"^(?P<path>[^:\s][^:]*):(?P<line>\d+):\d+:\s+"
    r"(?:warning|error):\s+.*\[(?P<check>[\w.,-]+)\]\s*$"
)


def normalize(path: str, line: int, check: str) -> str:
    # strip any leading dirs: the gate runs from native/ but clang-tidy
    # may print absolute paths
    name = path.rsplit("/", 1)[-1]
    bucket = (line // 10) * 10
    return f"{check}:{name}:{bucket}"


def parse_findings(output: str) -> "set[str]":
    found = set()
    for ln in output.splitlines():
        m = _DIAG.match(ln.strip())
        if m:
            # a single diag can carry a comma-joined check list
            for check in m.group("check").split(","):
                found.add(normalize(m.group("path"), int(m.group("line")), check))
    return found


def read_baseline(path: str) -> "set[str]":
    entries = set()
    try:
        with open(path, "r", encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if ln and not ln.startswith("#"):
                    entries.add(ln)
    except FileNotFoundError:
        pass
    return entries


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clang-tidy", default="clang-tidy")
    ap.add_argument("--baseline", default="tidy-baseline.txt")
    ap.add_argument("--sources", nargs="+", required=True)
    ap.add_argument(
        "--update", action="store_true",
        help="rewrite the baseline from the current findings",
    )
    ap.add_argument(
        "compile_flags", nargs="*",
        help="flags after `--` are passed to clang-tidy's compiler invocation",
    )
    args = ap.parse_args()

    if shutil.which(args.clang_tidy) is None:
        print(
            f"tidy_gate: '{args.clang_tidy}' not found. This container has "
            "no llvm toolchain; run `make -C native warn` for the "
            "gcc-runnable subset, or run the tidy gate on a machine with "
            "clang-tidy >= 12 (config: native/.clang-tidy). "
            "See docs/static_analysis.md.",
            file=sys.stderr,
        )
        return 3

    cmd = [args.clang_tidy, "--quiet", *args.sources, "--", *args.compile_flags]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    findings = parse_findings(proc.stdout + proc.stderr)

    if args.update:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write(
                "# clang-tidy baseline — one normalized key per line\n"
                "# (<check>:<file>:<line-bucket>); regenerate with\n"
                "#   python3 tidy_gate.py --update ...  (via `make tidy`)\n"
            )
            for key in sorted(findings):
                f.write(key + "\n")
        print(f"tidy_gate: baseline rewritten with {len(findings)} entries")
        return 0

    baseline = read_baseline(args.baseline)
    new = sorted(findings - baseline)
    stale = sorted(baseline - findings)

    for key in new:
        print(f"NEW      {key}")
    for key in stale:
        print(f"STALE    {key}  (baseline entry no longer fires — remove it)")
    ok = not new and not stale
    print(
        f"tidy_gate: {len(findings)} finding(s), {len(new)} new, "
        f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
