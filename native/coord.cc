#include "coord.h"

#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <iterator>
#include <sstream>

#include "blackbox.h"     // crash-durable quorum/commit breadcrumbs
#include "faultinject.h"  // env-gated injection points (reply delay/drop)
#include "lathist.h"      // quorum.fanout latency histogram + exports
#include "tsdb.h"         // fixed-retention (replica, series) sample rings

namespace tft {

static int64_t wall_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(system_clock::now().time_since_epoch())
      .count();
}

std::string get_hostname() {
  char buf[256];
  if (gethostname(buf, sizeof(buf)) != 0) return "localhost";
  buf[sizeof(buf) - 1] = '\0';
  return buf;
}

static void logline(const std::string& msg) {
  fprintf(stderr, "[tftcore %lld] %s\n", (long long)wall_ms(), msg.c_str());
}

// ---- value conversions ----------------------------------------------------

Value QuorumMember::to_value() const {
  Value v = Value::M();
  v.set("replica_id", Value::S(replica_id));
  v.set("address", Value::S(address));
  v.set("store_address", Value::S(store_address));
  v.set("step", Value::I(step));
  v.set("world_size", Value::I((int64_t)world_size));
  v.set("shrink_only", Value::B(shrink_only));
  v.set("commit_failures", Value::I(commit_failures));
  v.set("plane", Value::S(plane));
  return v;
}

QuorumMember QuorumMember::from_value(const Value& v) {
  QuorumMember m;
  m.replica_id = v.gets("replica_id");
  m.address = v.gets("address");
  m.store_address = v.gets("store_address");
  m.step = v.geti("step");
  m.world_size = (uint64_t)v.geti("world_size");
  m.shrink_only = v.getb("shrink_only");
  m.commit_failures = v.geti("commit_failures", 0);
  m.plane = v.has("plane") ? v.gets("plane") : "";
  return m;
}

Value Quorum::to_value() const {
  Value v = Value::M();
  v.set("quorum_id", Value::I(quorum_id));
  Value parts = Value::L();
  for (const auto& p : participants) parts.list.push_back(p.to_value());
  v.set("participants", parts);
  v.set("created", Value::I(created_unix_ms));
  return v;
}

Quorum Quorum::from_value(const Value& v) {
  Quorum q;
  q.quorum_id = v.geti("quorum_id");
  q.created_unix_ms = v.geti("created");
  if (v.has("participants"))
    for (const auto& p : v.at("participants").list)
      q.participants.push_back(QuorumMember::from_value(p));
  return q;
}

Value ManagerQuorumResult::to_value() const {
  Value v = Value::M();
  v.set("quorum_id", Value::I(quorum_id));
  v.set("recover_src_manager_address", Value::S(recover_src_manager_address));
  v.set("recover_src_rank", recover_src_rank.has_value()
                                ? Value::I(*recover_src_rank)
                                : Value::None());
  Value dst = Value::L();
  for (int64_t r : recover_dst_ranks) dst.list.push_back(Value::I(r));
  v.set("recover_dst_ranks", dst);
  v.set("store_address", Value::S(store_address));
  v.set("max_step", Value::I(max_step));
  v.set("max_rank", max_rank.has_value() ? Value::I(*max_rank) : Value::None());
  v.set("max_world_size", Value::I(max_world_size));
  v.set("replica_rank", Value::I(replica_rank));
  v.set("replica_world_size", Value::I(replica_world_size));
  v.set("heal", Value::B(heal));
  v.set("group_heal", Value::B(group_heal));
  Value ids = Value::L();
  for (const auto& id : participant_ids) ids.list.push_back(Value::S(id));
  v.set("participant_ids", ids);
  Value srcs = Value::L();
  for (const auto& a : recover_src_addresses) srcs.list.push_back(Value::S(a));
  v.set("recover_src_addresses", srcs);
  v.set("heal_pending", Value::B(heal_pending));
  return v;
}

// ---- pure decision procedures --------------------------------------------

static bool quorum_changed(const std::vector<QuorumMember>& a,
                           const std::vector<QuorumMember>& b) {
  // Member *identity* only — step changes don't bump quorum_id
  // (src/lighthouse.rs:105-110).
  if (a.size() != b.size()) return true;
  for (size_t i = 0; i < a.size(); i++)
    if (a[i].replica_id != b[i].replica_id) return true;
  return false;
}

std::pair<std::optional<std::vector<QuorumMember>>, std::string> quorum_compute(
    int64_t now, const LighthouseState& state, const LighthouseOpt& opt) {
  std::set<std::string> healthy_replicas;
  for (const auto& [id, beat] : state.heartbeats)
    if (now - beat < (int64_t)opt.heartbeat_timeout_ms)
      healthy_replicas.insert(id);

  // std::map keeps participants sorted by replica_id, giving the consistent
  // candidate ordering the reference gets via an explicit sort
  // (src/lighthouse.rs:141-142).
  std::map<std::string, const MemberDetails*> healthy_participants;
  for (const auto& [id, det] : state.participants)
    if (healthy_replicas.count(id)) healthy_participants[id] = &det;

  std::vector<QuorumMember> candidates;
  candidates.reserve(healthy_participants.size());
  bool shrink_only = false;
  for (const auto& [id, det] : healthy_participants) {
    candidates.push_back(det->member);
    shrink_only = shrink_only || det->member.shrink_only;
  }

  std::ostringstream meta;
  meta << "[" << healthy_participants.size() << "/" << state.participants.size()
       << " participants healthy][" << healthy_replicas.size()
       << " heartbeating][shrink_only=" << (shrink_only ? "true" : "false")
       << "]";
  std::string metadata = meta.str();

  if (state.prev_quorum.has_value()) {
    const Quorum& prev = *state.prev_quorum;
    std::set<std::string> prev_ids;
    for (const auto& p : prev.participants) prev_ids.insert(p.replica_id);

    if (shrink_only) {
      std::vector<QuorumMember> filtered;
      for (auto& c : candidates)
        if (prev_ids.count(c.replica_id)) filtered.push_back(c);
      candidates = std::move(filtered);
    }

    bool is_fast = true;
    for (const auto& p : prev.participants)
      if (!healthy_participants.count(p.replica_id)) {
        is_fast = false;
        break;
      }
    if (is_fast)
      return {candidates, "Fast quorum found! " + metadata};
  }

  if (healthy_participants.size() < opt.min_replicas)
    return {std::nullopt,
            "New quorum not ready, only have " +
                std::to_string(healthy_participants.size()) +
                " participants, need min_replicas " +
                std::to_string(opt.min_replicas) + " " + metadata};

  // Split-brain guard: require a strict majority of heartbeating replicas
  // (src/lighthouse.rs:202-213).
  if (healthy_participants.size() <= healthy_replicas.size() / 2)
    return {std::nullopt,
            "New quorum not ready, only have " +
                std::to_string(healthy_participants.size()) +
                " participants, need at least half of " +
                std::to_string(healthy_replicas.size()) + " healthy workers " +
                metadata};

  bool all_healthy_joined =
      healthy_participants.size() == healthy_replicas.size();
  int64_t first_joined = now;
  for (const auto& [id, det] : healthy_participants)
    first_joined = std::min(first_joined, det->joined_ms);
  if (!all_healthy_joined &&
      now - first_joined < (int64_t)opt.join_timeout_ms)
    return {std::nullopt,
            "Valid quorum with " +
                std::to_string(healthy_participants.size()) +
                " participants, waiting for " +
                std::to_string(healthy_replicas.size() -
                               healthy_participants.size()) +
                " healthy but not participating stragglers due to join "
                "timeout " +
                metadata};

  return {candidates, "Valid quorum found " + metadata};
}

ManagerQuorumResult compute_quorum_results(const std::string& replica_id,
                                           int64_t rank,
                                           const Quorum& quorum) {
  std::vector<QuorumMember> participants = quorum.participants;
  std::sort(participants.begin(), participants.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  int64_t replica_rank = -1;
  for (size_t i = 0; i < participants.size(); i++)
    if (participants[i].replica_id == replica_id) {
      replica_rank = (int64_t)i;
      break;
    }
  if (replica_rank < 0)
    throw RpcError(NOT_FOUND, "replica " + replica_id +
                                  " not participating in returned quorum");

  int64_t max_step = 0;
  for (const auto& p : participants) max_step = std::max(max_step, p.step);

  std::vector<size_t> max_idx;  // indices of members at max step
  for (size_t i = 0; i < participants.size(); i++)
    if (participants[i].step == max_step) max_idx.push_back(i);

  std::optional<int64_t> max_rank;
  for (size_t i = 0; i < max_idx.size(); i++)
    if (participants[max_idx[i]].replica_id == replica_id) {
      max_rank = (int64_t)i;
      break;
    }

  // The primary store for this local rank, striped over the max-step cohort
  // (src/manager.rs:397-399).
  const QuorumMember& primary =
      participants[max_idx[(size_t)rank % max_idx.size()]];

  // Bootstrap source: at max_step == 0 every group heals from ONE replica
  // (the cohort's first), NOT the rank-striped primary. The reference
  // stripes here too (src/manager.rs:406-416), but with multi-rank groups
  // striping makes EVERY group heal some rank plane, so the group-level
  // zero-contribution gate zeros every group and the first committed step
  // is a pure weight-decay update (round-2 advisor finding, coord.cc:270).
  // A single bootstrap source leaves one group contributing real gradients
  // and still lands all groups on bit-identical state.
  const QuorumMember& bootstrap_src = participants[max_idx[0]];

  // recover_dst: behind the max step, or (first step and not the bootstrap
  // source) — src/manager.rs:403-416, with the bootstrap deviation above.
  std::vector<size_t> all_recover_dst;
  for (size_t i = 0; i < participants.size(); i++) {
    const auto& p = participants[i];
    if (p.step != max_step ||
        (max_step == 0 && bootstrap_src.replica_id != p.replica_id))
      all_recover_dst.push_back(i);
  }
  std::set<size_t> dst_set(all_recover_dst.begin(), all_recover_dst.end());
  std::vector<size_t> up_to_date;
  for (size_t i = 0; i < participants.size(); i++)
    if (!dst_set.count(i)) up_to_date.push_back(i);

  // Round-robin recoverers onto sources, offset by the local rank so
  // different local ranks fan out over different sources
  // (src/manager.rs:430-447).
  std::map<size_t, std::vector<int64_t>> assignments;
  std::optional<int64_t> recover_src_rank;
  for (size_t i = 0; i < all_recover_dst.size(); i++) {
    size_t src = up_to_date[(i + (size_t)rank) % up_to_date.size()];
    assignments[src].push_back((int64_t)all_recover_dst[i]);
    if ((int64_t)all_recover_dst[i] == replica_rank)
      recover_src_rank = (int64_t)src;
  }

  // group_heal: does ANY local rank of this replica heal this round?
  // Participation (zero-contribution) must be decided at group level —
  // rank planes averaging different participant sets would silently
  // diverge a multi-rank group's replicated or sharded state. (The
  // reference gates participation on the per-rank flag, manager.py:268-269,
  // which is only sound for 1-rank groups.) With the single bootstrap
  // source above, a group either heals on EVERY plane or on none, so
  // group_heal reduces to the recover_dst condition.
  const QuorumMember& me = participants[(size_t)replica_rank];
  bool group_heal =
      me.step != max_step ||
      (max_step == 0 && bootstrap_src.replica_id != me.replica_id);

  ManagerQuorumResult out;
  out.quorum_id = quorum.quorum_id;
  out.heal = recover_src_rank.has_value();
  out.group_heal = group_heal;
  out.recover_src_rank = recover_src_rank;
  if (recover_src_rank.has_value())
    out.recover_src_manager_address =
        participants[(size_t)*recover_src_rank].address;
  auto it = assignments.find((size_t)replica_rank);
  if (it != assignments.end()) out.recover_dst_ranks = it->second;
  out.store_address = primary.store_address;
  out.max_step = max_step;
  out.max_rank = max_rank;
  out.max_world_size = (int64_t)max_idx.size();
  out.replica_rank = replica_rank;
  out.replica_world_size = (int64_t)participants.size();
  for (const auto& p : participants) out.participant_ids.push_back(p.replica_id);
  // Striped-heal source list: every max-step cohort member holds the
  // bit-identical committed state, so a healer may pull stripes from all
  // of them in parallel. EXCEPT at bootstrap — before the first
  // committed sync the groups' states are merely same-shaped, not
  // identical, so only the single bootstrap source is sound (the same
  // reasoning as the bootstrap_src deviation above).
  out.heal_pending = !all_recover_dst.empty();
  if (max_step == 0) {
    out.recover_src_addresses.push_back(bootstrap_src.address);
  } else {
    for (size_t i : max_idx)
      out.recover_src_addresses.push_back(participants[i].address);
  }
  return out;
}

// ---- Lighthouse -----------------------------------------------------------

Lighthouse::Lighthouse(const std::string& bind, const LighthouseOpt& opt)
    : opt_(opt), hostname_(get_hostname()) {
  std::string err;
  bool ok = server_.start(
      bind,
      [this](const std::string& m, const Value& r, int64_t d) {
        return handle_rpc(m, r, d);
      },
      [this](const std::string& m, const std::string& p) {
        return handle_http(m, p);
      },
      &err);
  if (!ok) throw RpcError(UNAVAILABLE, "lighthouse bind failed: " + err);
  tick_thread_ = std::thread([this] { tick_loop(); });
  logline("Lighthouse listening on " + address());
}

Lighthouse::~Lighthouse() { shutdown(); }

void Lighthouse::shutdown() {
  if (!running_.exchange(false)) return;
  {
    // Hold mu_ while notifying so parked handler waits can't miss the
    // running_ flip (lost-wakeup window of cv_.wait_until).
    std::lock_guard<std::mutex> g(mu_);
    cv_.notify_all();
  }
  if (tick_thread_.joinable()) tick_thread_.join();
  server_.shutdown();
}

std::string Lighthouse::address() const {
  return "http://" + hostname_ + ":" + std::to_string(server_.port());
}

void Lighthouse::tick_loop() {
  std::unique_lock<std::mutex> lk(mu_);
  while (running_.load()) {
    cv_wait_deadline(cv_, lk, now_ms() + opt_.quorum_tick_ms,
                     [this] { return !running_.load(); });
    if (!running_.load()) break;
    quorum_tick();
  }
}

void Lighthouse::quorum_tick() {
  auto [met, reason] = quorum_compute(now_ms(), state_, opt_);
  last_reason_ = reason;
  if (!met.has_value()) return;

  // A participant with latched data-plane errors requests a flush: bump the
  // quorum_id even though membership is unchanged, so every group abandons
  // the broken epoch and re-rendezvouses (no reference analogue — it can
  // only reconfigure via membership change, i.e. process restart).
  bool flush = false;
  for (const auto& m : *met) flush = flush || m.commit_failures > 0;
  if (flush) flush_requests_total_++;

  if (!state_.prev_quorum.has_value() ||
      quorum_changed(*met, state_.prev_quorum->participants) || flush) {
    state_.quorum_id += 1;
    logline(std::string(flush ? "Data-plane flush requested"
                              : "Detected quorum change") +
            ", bumping quorum_id to " + std::to_string(state_.quorum_id));
  }
  Quorum q;
  q.quorum_id = state_.quorum_id;
  q.participants = *met;
  q.created_unix_ms = wall_ms();

  state_.prev_quorum = q;
  state_.participants.clear();

  published_[++quorum_seq_] = q;
  while (published_.size() > 16) published_.erase(published_.begin());
  // crash-durable quorum-transition breadcrumb (a = participants,
  // b = flush): the epoch history survives a lighthouse death
  bb::record(bb::kQuorumPublish, state_.quorum_id, -1,
             (int64_t)q.participants.size(), flush ? 1 : 0);
  cv_.notify_all();
}

Value Lighthouse::handle_rpc(const std::string& method, const Value& req,
                             int64_t deadline) {
  if (method == "lh.quorum") return handle_quorum(req, deadline);
  if (method == "lh.heartbeat") {
    std::lock_guard<std::mutex> g(mu_);
    const std::string id = req.gets("replica_id");
    state_.heartbeats[id] = now_ms();
    if (req.has("telemetry")) ingest_telemetry(id, req.at("telemetry"));
    return Value::M();
  }
  if (method == "lh.evict") return handle_evict(req);
  if (method == "lh.digest") return handle_digest(req, deadline);
  throw RpcError(INVALID_ARGUMENT, "unknown method " + method);
}

Value Lighthouse::handle_digest(const Value& req, int64_t deadline) {
  // Divergence sentinel (ISSUE 10): every committed step's post-reduce
  // state is bit-identical across the cohort BY CONSTRUCTION (the
  // allgather forwards owner bytes verbatim — docs/wire_plane.md), so a
  // digest mismatch within one (epoch, step) round is the corrupt-commit
  // failure mode itself: a mid-op peer death or torn read that slipped
  // into an average. Latch it here, at the commit boundary, instead of
  // noticing the loss going nan thousands of steps later.
  const std::string replica = req.gets("replica_id");
  const std::string digest = req.gets("digest");
  const int64_t epoch = req.geti("epoch", -1);
  const int64_t step = req.geti("step", -1);
  const bool wait = req.getb("wait", false);
  const int64_t cohort_hint = req.geti("cohort", 0);
  if (replica.empty() || digest.empty())
    throw RpcError(INVALID_ARGUMENT, "digest: missing replica_id/digest");

  std::unique_lock<std::mutex> lk(mu_);
  const auto key = std::make_pair(epoch, step);
  digest_rounds_[key].digests[replica] = digest;
  // bound the store; never evict the round being served
  while (digest_rounds_.size() > 8 && digest_rounds_.begin()->first != key)
    digest_rounds_.erase(digest_rounds_.begin());

  auto check_round = [&](DigestRound& round) {
    // "-" is the abstain marker: a group whose step aborts locally (a
    // torn op means its digest covers fewer reduces) still reports —
    // completing the fence's cohort wait — but never enters the
    // comparison: only COMMITTING states must agree.
    std::map<std::string, int> freq;
    for (const auto& [id, d] : round.digests)
      if (d != "-") freq[d]++;
    if (freq.size() <= 1) return;
    const bool first_latch = !round.diverged;
    round.diverged = true;
    divergence_detected_ = true;
    if (first_latch) divergence_total_++;  // one incident per round
    // minority replicas go red on the dashboard; a 1-vs-1 split names
    // both — the postmortem assigns blame, the sentinel only latches.
    // Re-evaluated on every report so a LATE reporter with yet another
    // digest (3-group fleets) is still attributed, not just the pair
    // that tripped the first latch.
    int majority = 0;
    for (const auto& [d, n] : freq) majority = std::max(majority, n);
    std::ostringstream detail;
    detail << "epoch " << epoch << " step " << step << ":";
    for (const auto& [id, d] : round.digests) {
      if (d != "-" && (freq[d] < majority || majority == 1))
        diverged_replicas_.insert(id);
      detail << " " << id << "=" << d.substr(0, 16);
    }
    last_divergence_ = detail.str();
    if (first_latch) {
      bb::record(bb::kDivergence, epoch, step,
                 (int64_t)round.digests.size(), (int64_t)freq.size());
      logline("DIVERGENCE detected at " + last_divergence_);
    }
  };
  check_round(digest_rounds_[key]);
  cv_.notify_all();

  if (wait) {
    // fence path: block until the full cohort reported (or the round
    // already diverged — no point waiting to learn more). Cohort size
    // is the current quorum; a caller outside any quorum must pass the
    // explicit `cohort` hint (unit tests).
    size_t cohort = cohort_hint > 0
                        ? (size_t)cohort_hint
                        : (state_.prev_quorum.has_value()
                               ? state_.prev_quorum->participants.size()
                               : 1);
    bool ok = cv_wait_deadline(cv_, lk, deadline, [&] {
      if (!running_.load()) return true;
      auto it = digest_rounds_.find(key);
      return it == digest_rounds_.end() ||
             it->second.digests.size() >= cohort || it->second.diverged;
    });
    if (!running_.load())
      throw RpcError(CANCELLED, "lighthouse shutting down");
    if (!ok)
      throw RpcError(DEADLINE_EXCEEDED,
                     "digest cohort wait timed out (a fleet must opt "
                     "every group into the fence)");
  }
  auto it = digest_rounds_.find(key);
  bool diverged_round = it != digest_rounds_.end() && it->second.diverged;
  int64_t reports =
      it != digest_rounds_.end() ? (int64_t)it->second.digests.size() : 0;
  if (diverged_round) {
    // retire the round once every reporter has its veto: an aborted
    // step RETRIES under the same (epoch, step), and a sticky per-round
    // verdict would veto the clean retry forever (observed as a fence
    // livelock in the corrupt_divergence scenario bring-up)
    if (++it->second.answered >= (int)it->second.digests.size())
      digest_rounds_.erase(it);
  }
  Value out = Value::M();
  out.set("match", Value::B(!diverged_round));
  out.set("divergence", Value::B(divergence_detected_));
  out.set("reports", Value::I(reports));
  return out;
}

void Lighthouse::ingest_telemetry(const std::string& replica_id,
                                  const Value& v) {
  // Stores are verbatim: summary is an opaque JSON object string, spans are
  // raw Chrome trace-event fragments (comma-joined objects, no brackets).
  // Caps bound memory per replica and across replicas — telemetry from a
  // chatty or malicious report must never OOM the coordinator.
  static constexpr size_t kMaxSpanBytesPerReplica = 1 << 20;  // 1 MiB
  static constexpr size_t kMaxBatchesPerReplica = 64;
  static constexpr size_t kMaxReplicas = 256;
  if (v.type != Value::Type::MAP) return;
  if (telemetry_.count(replica_id) == 0 && telemetry_.size() >= kMaxReplicas) {
    // evict the stalest entry (dead uuids from respawned groups)
    auto oldest = telemetry_.begin();
    for (auto it = telemetry_.begin(); it != telemetry_.end(); ++it)
      if (it->second.last_ms < oldest->second.last_ms) oldest = it;
    telemetry_.erase(oldest);
  }
  ReplicaTelemetry& t = telemetry_[replica_id];
  t.last_ms = now_ms();  // monotonic, same clock as heartbeats
  if (v.has("step")) t.step = v.geti("step", t.step);
  if (v.has("stuck")) t.stuck = v.getb("stuck", false);
  if (v.has("last_heal_ts")) t.last_heal_ts = v.at("last_heal_ts").f;
  if (v.has("local_step_p50_s"))
    t.local_step_p50_s = v.at("local_step_p50_s").f;
  if (v.has("slo_breach")) t.slo_breach = v.getb("slo_breach", false);
  std::string summary = v.gets("summary");
  // minimal validation: the summary is spliced raw into /cluster.json, so
  // only accept something that at least looks like a JSON object
  if (!summary.empty() && summary.front() == '{' && summary.back() == '}')
    t.summary_json = std::move(summary);
  // step-anatomy digest: same verbatim-splice contract as the summary
  // (the lighthouse never parses the Python telemetry schema); size-
  // capped — a malformed reporter must not grow the coordinator's store.
  // Oversize degrades LOUDLY: the digest (and any stale predecessor) is
  // dropped and counted, never truncated into /cluster.json (ISSUE 11).
  std::string anatomy = v.gets("anatomy");
  if (!anatomy.empty()) {
    if (anatomy.size() > (1u << 16)) {
      t.anatomy_json.clear();
      t.anatomy_oversized++;
      telemetry_oversized_total_++;
      logline("telemetry from " + replica_id + ": anatomy digest " +
              std::to_string(anatomy.size()) +
              " bytes exceeds the 64KiB piggyback cap — dropped (not "
              "truncated)");
    } else if (anatomy.front() == '{' && anatomy.back() == '}') {
      t.anatomy_json = std::move(anatomy);
    }
  }
  // diagnosis-bundle availability (ISSUE 12): counts + names only — the
  // bundles themselves stay on the replica's disk; size caps keep a
  // malformed reporter from growing the coordinator's store
  if (v.has("diag_bundles"))
    t.diag_bundles = v.geti("diag_bundles", t.diag_bundles);
  // cap overflow replaces the stored value with a loud marker instead
  // of silently keeping the STALE predecessor: /diagnosis.json and the
  // dashboard would otherwise point an operator at the previous
  // incarnation's evidence path as if it were current
  std::string diag_last = v.gets("diag_last");
  if (!diag_last.empty())
    t.diag_last = diag_last.size() <= 256 ? std::move(diag_last)
                                          : std::string("(oversized)");
  std::string diag_dir = v.gets("diag_dir");
  if (!diag_dir.empty())
    t.diag_dir = diag_dir.size() <= 512 ? std::move(diag_dir)
                                        : std::string("(oversized)");
  // time-series ingest (ISSUE 11): an opaque {series-name: double} map
  // sampled at the report's (epoch, step) coordinates. The lighthouse
  // stays schema-blind — names mean whatever the Python side says.
  if (v.has("series") && v.at("series").type == Value::Type::MAP) {
    std::map<std::string, double> samples;
    for (const auto& [name, sv] : v.at("series").map) {
      if (sv.type == Value::Type::F64)
        samples[name] = sv.f;
      else if (sv.type == Value::Type::I64)
        samples[name] = (double)sv.i;
      else if (sv.type == Value::Type::BOOL)
        samples[name] = sv.b ? 1.0 : 0.0;
    }
    // refuse non-finite samples at the door: %.9g would render them as
    // "inf"/"nan" — INVALID JSON — and one bad report would blind every
    // /timeseries.json consumer for the whole retention window
    for (auto it = samples.begin(); it != samples.end();)
      it = std::isfinite(it->second) ? std::next(it) : samples.erase(it);
    tsdb::store().ingest(replica_id, v.geti("epoch", -1),
                         v.geti("step", -1), samples);
  }
  std::string spans = v.gets("spans");
  if (!spans.empty() && spans.size() <= kMaxSpanBytesPerReplica) {
    telemetry_bytes_spans_ += spans.size();
    t.span_batches.push_back(std::move(spans));
    t.span_bytes += t.span_batches.back().size();
    while (t.span_batches.size() > kMaxBatchesPerReplica ||
           t.span_bytes > kMaxSpanBytesPerReplica) {
      t.span_bytes -= t.span_batches.front().size();
      t.span_batches.erase(t.span_batches.begin());
    }
  }
  // Delta-encoded piggybacks (ISSUE 16): a singular blob (the Manager's
  // direct heartbeat push) or a batch the manager server accumulated
  // across its local ranks this round. Processed AFTER the legacy
  // fields so a mixed-mode payload behaves like two reports.
  if (v.has("tdelta") && v.at("tdelta").type == Value::Type::BYTES)
    ingest_tdelta(replica_id, v.at("tdelta").s);
  if (v.has("tdeltas") && v.at("tdeltas").type == Value::Type::LIST)
    for (const Value& blob : v.at("tdeltas").list)
      if (blob.type == Value::Type::BYTES)
        ingest_tdelta(replica_id, blob.s);
}

void Lighthouse::ingest_tdelta(const std::string& replica_id,
                               const std::string& blob) {
  // One incarnation chain per (replica, sender incarnation): a respawn
  // is a NEW chain by construction (fresh random incarnation), so it
  // can never inherit the dead pid's interning dictionary or delta
  // base; the dead chain ages out below while its TSDB ring is
  // retained (PR 11 dead-ring semantics are per replica_id, untouched).
  static constexpr size_t kMaxChainsPerReplica = 4;
  static constexpr size_t kMaxBlobBytes = 1 << 16;
  if (blob.size() < 11 || blob.size() > kMaxBlobBytes) {
    telemetry_delta_resyncs_total_++;
    return;
  }
  telemetry_bytes_piggyback_ += blob.size();
  bool full = ((uint8_t)blob[2] & tftdelta::kFlagFull) != 0;
  std::string inc = blob.substr(3, 8);
  auto& chains = delta_states_[replica_id];
  auto it = chains.find(inc);
  if (it == chains.end()) {
    if (!full) {
      // delta for a chain we do not hold (lighthouse restart, or the
      // blob beat its own FULL after a respawn): park a resync request
      // under this incarnation so the next quorum reply asks for FULL
      auto& st = chains[inc];
      st.inc = inc;
      st.resync = true;
      st.last_ms = now_ms();
      telemetry_delta_resyncs_total_++;
      return;
    }
    while (chains.size() >= kMaxChainsPerReplica) {
      auto oldest = chains.begin();
      for (auto c = chains.begin(); c != chains.end(); ++c)
        if (c->second.last_ms < oldest->second.last_ms) oldest = c;
      chains.erase(oldest);
    }
  }
  tftdelta::DecodeState& st = chains[inc];
  st.last_ms = now_ms();
  std::string err;
  std::vector<std::string> changed;
  if (!tftdelta::apply(st, blob, &err, &changed)) {
    telemetry_delta_resyncs_total_++;
    logline("telemetry delta from " + replica_id + "/" +
            tftdelta::inc_hex(inc) + " rejected (" + err +
            "); full resync requested");
    return;
  }
  telemetry_delta_blobs_total_++;
  if (full) telemetry_delta_fulls_total_++;
  // refresh the legacy row from the decoded flat state so every
  // downstream surface (/cluster.json, /metrics, straggler detector,
  // dashboard) is format-blind. Same kMaxReplicas eviction pressure as
  // the legacy path via the telemetry_ map itself.
  ReplicaTelemetry& t = telemetry_[replica_id];
  t.last_ms = now_ms();
  auto leaf = [&](const char* key) -> const tftdelta::Leaf* {
    auto f = st.flat.find(key);
    return f == st.flat.end() ? nullptr : &f->second;
  };
  if (const auto* l = leaf("step"))
    t.step = l->type == tftdelta::kI64 ? l->i : t.step;
  if (const auto* l = leaf("stuck")) t.stuck = l->b;
  if (const auto* l = leaf("slo_breach")) t.slo_breach = l->b;
  if (const auto* l = leaf("last_heal_ts"))
    t.last_heal_ts = l->type == tftdelta::kF64 ? l->f : (double)l->i;
  if (const auto* l = leaf("local_step_p50_s"))
    t.local_step_p50_s = l->type == tftdelta::kF64 ? l->f : (double)l->i;
  if (const auto* l = leaf("diag_bundles"))
    t.diag_bundles = l->type == tftdelta::kI64 ? l->i : t.diag_bundles;
  if (const auto* l = leaf("diag_last"))
    t.diag_last = l->s.size() <= 256 ? l->s : std::string("(oversized)");
  if (const auto* l = leaf("diag_dir"))
    t.diag_dir = l->s.size() <= 512 ? l->s : std::string("(oversized)");
  t.summary_json = tftdelta::subtree_json(st, "summary");
  t.anatomy_json = tftdelta::subtree_json(st, "anatomy");
  // TSDB ingest: under delta, exactly the series whose value MOVED this
  // blob (an unchanged sample is absent — the ring's consumers cursor
  // by step, so a skipped flat sample costs nothing). Coordinates ride
  // the same blob as top-level step/epoch leaves.
  int64_t epoch = -1, step = -1;
  if (const auto* l = leaf("epoch"))
    epoch = l->type == tftdelta::kI64 ? l->i : -1;
  if (const auto* l = leaf("step"))
    step = l->type == tftdelta::kI64 ? l->i : -1;
  static const std::string kSeriesPfx =
      std::string("series") + tftdelta::kSep;
  std::map<std::string, double> samples;
  for (const std::string& key : changed) {
    if (key.compare(0, kSeriesPfx.size(), kSeriesPfx) != 0) continue;
    auto f = st.flat.find(key);
    if (f == st.flat.end()) continue;
    double val = 0;
    if (f->second.type == tftdelta::kF64)
      val = f->second.f;
    else if (f->second.type == tftdelta::kI64)
      val = (double)f->second.i;
    else if (f->second.type == tftdelta::kBool)
      val = f->second.b ? 1.0 : 0.0;
    else
      continue;
    if (std::isfinite(val)) samples[key.substr(kSeriesPfx.size())] = val;
  }
  if (!samples.empty()) tsdb::store().ingest(replica_id, epoch, step, samples);
  maybe_rollup_fleet();
}

Value Lighthouse::telemetry_ack(const std::string& replica_id) {
  Value ack = Value::M();
  auto it = delta_states_.find(replica_id);
  if (it == delta_states_.end()) return ack;
  for (auto& [inc, st] : it->second) {
    Value a = Value::M();
    a.set("ver", Value::I((int64_t)st.version));
    a.set("resync", Value::B(st.resync));
    ack.set(tftdelta::inc_hex(inc), a);
  }
  return ack;
}

void Lighthouse::maybe_rollup_fleet() {
  // Fold the fleet's piggybacked wall/local histograms into "_fleet"
  // pseudo-replica series at a bounded cadence: the fold is O(replicas
  // x buckets), so running it per-ingest would be O(fleet^2) per round
  // at 1000 groups. TORCHFT_TELEMETRY_ROLLUP_S (default 1s, 0=off)
  // bounds it to O(replicas) per second regardless of quorum rate.
  static const double interval_s = [] {
    const char* e = getenv("TORCHFT_TELEMETRY_ROLLUP_S");
    if (!e || !*e) return 1.0;
    char* end = nullptr;
    double v = strtod(e, &end);
    return (end == e || v < 0) ? 1.0 : v;
  }();
  if (interval_s <= 0) return;
  int64_t now = now_ms();
  if (now - last_fleet_rollup_ms_ < (int64_t)(interval_s * 1000)) return;
  last_fleet_rollup_ms_ = now;
  std::map<std::string, tftdelta::HistCounts> fleet;
  int64_t max_step = -1, max_epoch = -1;
  for (const auto& [rid, chains] : delta_states_) {
    (void)rid;
    for (const auto& [inc, st] : chains) {
      (void)inc;
      tftdelta::fold_hists(st, fleet);
    }
  }
  for (const auto& [rid, t] : telemetry_) {
    (void)rid;
    max_step = std::max(max_step, t.step);
  }
  std::map<std::string, double> samples;
  for (const char* name : {"wall", "local"}) {
    auto it = fleet.find(name);
    if (it == fleet.end()) continue;
    samples[std::string("fleet.") + name + "_p50_s"] =
        tftdelta::grid_quantile(it->second, 0.5);
    samples[std::string("fleet.") + name + "_p99_s"] =
        tftdelta::grid_quantile(it->second, 0.99);
  }
  samples["fleet.groups"] = (double)telemetry_.size();
  int64_t stuck = 0;
  for (const auto& [rid, t] : telemetry_) {
    (void)rid;
    if (t.stuck) stuck++;
  }
  samples["fleet.stuck"] = (double)stuck;
  tsdb::store().ingest("_fleet", max_epoch, max_step, samples);
}

Value Lighthouse::handle_evict(const Value& req) {
  // Survivor-reported eviction: a replica whose data-plane op failed with a
  // connection reset names the dead peer, and the lighthouse expires its
  // heartbeat *immediately* instead of waiting out the lease — the passive
  // floor the reference shares (src/lighthouse.rs:119-128). Guards:
  // (a) only a current quorum member may report, and only about a
  //     co-member of that quorum;
  // (b) the lighthouse actively probes the accused manager's address first
  //     (single TCP connect, evict_probe_ms): a live process accepts, so a
  //     false report about a live peer is a no-op.
  const std::string reporter = req.gets("reporter");
  const std::string victim = req.gets("victim");
  std::string victim_addr;
  int64_t reported_at;
  {
    std::lock_guard<std::mutex> g(mu_);
    if (!state_.prev_quorum.has_value())
      throw RpcError(INVALID_ARGUMENT, "evict: no quorum yet");
    if (reporter == victim)
      throw RpcError(INVALID_ARGUMENT, "evict: self-report rejected");
    bool reporter_ok = false, victim_ok = false;
    for (const auto& p : state_.prev_quorum->participants) {
      if (p.replica_id == reporter) reporter_ok = true;
      if (p.replica_id == victim) {
        victim_ok = true;
        victim_addr = p.address;
      }
    }
    if (!reporter_ok)
      throw RpcError(INVALID_ARGUMENT,
                     "evict: reporter " + reporter +
                         " is not a member of the current quorum");
    if (!victim_ok)
      throw RpcError(NOT_FOUND, "evict: victim " + victim +
                                    " is not a member of the current quorum");
    reported_at = now_ms();
  }

  // Probe outside the lock: one TCP connect to the victim's manager server.
  // A SIGKILLed process yields an instant refusal; a live one accepts.
  bool alive = false;
  std::string host;
  int port = 0;
  if (parse_addr(victim_addr, &host, &port)) {
    std::string err;
    int fd = tcp_connect(host, port, (int64_t)opt_.evict_probe_ms, &err);
    if (fd >= 0) {
      ::close(fd);
      alive = true;
    }
  }

  std::lock_guard<std::mutex> g(mu_);
  if (alive) {
    logline("evict report for " + victim + " from " + reporter +
            " ignored: probe succeeded (replica is alive)");
    return Value::M().set("evicted", Value::B(false));
  }
  auto it = state_.heartbeats.find(victim);
  if (it != state_.heartbeats.end() && it->second > reported_at) {
    // Fresh heartbeat raced the probe — the replica is alive.
    logline("evict report for " + victim + " ignored: heartbeat arrived");
    return Value::M().set("evicted", Value::B(false));
  }
  state_.heartbeats.erase(victim);
  state_.participants.erase(victim);
  evictions_total_++;
  recent_evictions_.push_back(victim + " < " + reporter + " @ " +
                              std::to_string(wall_ms() / 1000));
  if (recent_evictions_.size() > 16)
    recent_evictions_.erase(recent_evictions_.begin());
  logline("evicted " + victim + " (reported dead by " + reporter +
          ", liveness probe failed)");
  if (running_.load()) quorum_tick();
  return Value::M().set("evicted", Value::B(true));
}

Value Lighthouse::handle_quorum(const Value& req, int64_t deadline) {
  if (!req.has("requester"))
    throw RpcError(INVALID_ARGUMENT, "missing requester");
  QuorumMember requester = QuorumMember::from_value(req.at("requester"));

  std::unique_lock<std::mutex> lk(mu_);
  // Implicit heartbeat + registration (src/lighthouse.rs:455-467).
  state_.heartbeats[requester.replica_id] = now_ms();
  state_.participants[requester.replica_id] =
      MemberDetails{now_ms(), requester};
  if (req.has("telemetry"))
    ingest_telemetry(requester.replica_id, req.at("telemetry"));
  uint64_t seen = quorum_seq_;
  // Proactive tick so a fast quorum resolves without waiting a full tick
  // (src/lighthouse.rs:470-473).
  quorum_tick();

  while (true) {
    bool ok = cv_wait_deadline(
        cv_, lk, deadline,
        [&] { return quorum_seq_ > seen || !running_.load(); });
    if (!running_.load()) throw RpcError(CANCELLED, "lighthouse shutting down");
    if (!ok) throw RpcError(DEADLINE_EXCEEDED, "quorum wait timed out");
    // Deliver published quorums in order; return on the first containing the
    // requester, else re-register and keep waiting
    // (src/lighthouse.rs:478-499).
    while (seen < quorum_seq_) {
      seen++;
      auto it = published_.find(seen);
      if (it == published_.end()) continue;
      for (const auto& p : it->second.participants)
        if (p.replica_id == requester.replica_id) {
          Value out = Value::M();
          out.set("quorum", it->second.to_value());
          // telemetry ack (ISSUE 16): per-incarnation delta versions +
          // resync requests, relayed by the manager server to every
          // local rank's encoder. Computed here (still under mu_) so
          // the ack reflects the blobs this very call ingested.
          Value tack = telemetry_ack(requester.replica_id);
          if (!tack.map.empty()) out.set("tack", tack);
          return out;
        }
    }
    state_.participants[requester.replica_id] =
        MemberDetails{now_ms(), requester};
  }
}

static std::string html_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '<')
      out += "&lt;";
    else if (c == '>')
      out += "&gt;";
    else if (c == '&')
      out += "&amp;";
    else
      out.push_back(c);
  }
  return out;
}

static std::string prom_escape(const std::string& s) {
  // Prometheus exposition label-value escaping: \ " and newline
  std::string out;
  for (char c : s) {
    if (c == '\\')
      out += "\\\\";
    else if (c == '"')
      out += "\\\"";
    else if (c == '\n')
      out += "\\n";
    else
      out.push_back(c);
  }
  return out;
}

static std::string json_escape(const std::string& s) {
  // JSON string-body escaping (prom_escape is a Prometheus label escaper
  // and lets control chars other than \n through raw — a tab in a
  // user-chosen replica_id would break /status.json)
  std::ostringstream o;
  for (unsigned char c : s) {
    if (c == '\\' || c == '"') {
      o << '\\' << c;
    } else if (c < 0x20) {
      char buf[8];
      snprintf(buf, sizeof buf, "\\u%04x", c);
      o << buf;
    } else {
      o << c;
    }
  }
  return o.str();
}

static std::string http_ok(const std::string& body,
                           const std::string& ctype = "text/html") {
  std::ostringstream o;
  o << "HTTP/1.1 200 OK\r\nContent-Type: " << ctype
    << "\r\nContent-Length: " << body.size() << "\r\nConnection: close\r\n\r\n"
    << body;
  return o.str();
}

std::string Lighthouse::status_html() {
  std::unique_lock<std::mutex> lk(mu_);
  auto [met, reason] = quorum_compute(now_ms(), state_, opt_);
  (void)met;
  std::ostringstream o;
  int64_t max_step = -1;
  if (state_.prev_quorum)
    for (const auto& p : state_.prev_quorum->participants)
      max_step = std::max(max_step, p.step);
  o << "<h2>Quorum</h2><p>quorum_id: " << state_.quorum_id
    << "</p><p>status: " << html_escape(reason) << "</p>";
  if (state_.prev_quorum) {
    int64_t age_ms = wall_ms() - state_.prev_quorum->created_unix_ms;
    o << "<p>age: " << age_ms / 1000.0 << "s</p>";
    o << "<table border=1 cellpadding=4><tr><th>replica_id</th><th>step</th>"
         "<th>plane</th><th>manager</th><th>store</th><th>world_size</th>"
         "<th>flush</th><th></th></tr>";
    for (const auto& p : state_.prev_quorum->participants) {
      bool recovering = p.step != max_step;
      o << "<tr" << (recovering ? " style=\"background:orange\"" : "") << "><td>"
        << html_escape(p.replica_id) << (recovering ? " (recovering)" : "")
        << "</td><td>" << p.step << "</td><td>"
        << html_escape(p.plane.empty() ? "?" : p.plane) << "</td><td>"
        << html_escape(p.address)
        << "</td><td>" << html_escape(p.store_address) << "</td><td>"
        << p.world_size << "</td><td>" << p.commit_failures
        << "</td><td><form method=post action=\"/replica/"
        << html_escape(p.replica_id)
        << "/kill\"><button>Kill</button></form></td></tr>";
    }
    o << "</table>";
  } else {
    o << "<p>No quorum yet.</p>";
  }
  o << "<h2>Heartbeats</h2><table border=1 cellpadding=4>"
       "<tr><th>replica_id</th><th>age</th></tr>";
  int64_t now = now_ms();
  for (const auto& [id, beat] : state_.heartbeats) {
    bool old = now - beat >= (int64_t)opt_.heartbeat_timeout_ms;
    o << "<tr" << (old ? " style=\"background:orange\"" : "") << "><td>"
      << html_escape(id) << "</td><td>" << (now - beat) / 1000.0
      << "s</td></tr>";
  }
  o << "</table>";
  if (!telemetry_.empty()) {
    // Per-replica health: the operator triage table. last_seen is the
    // telemetry report age (reports ride quorum traffic, so a healthy
    // training loop refreshes it every step).
    o << "<h2>Replica health</h2><table border=1 cellpadding=4>"
         "<tr><th>replica_id</th><th>last report</th><th>step</th>"
         "<th>last heal</th><th>local p50</th><th>trend</th><th>stuck</th>"
         "<th>SLO</th><th>digest</th><th>diag</th></tr>";
    // two clocks on purpose: report ages use the monotonic clock that
    // stamped last_ms (mixing in wall time would show epoch-offset
    // garbage), while last_heal_ts is a unix timestamp from the replica
    // and must be compared against wall time
    int64_t mono_now = now_ms();
    double wall_now_s = wall_ms() / 1000.0;
    for (const auto& [id, t] : telemetry_) {
      o << "<tr" << (t.stuck ? " style=\"background:red\"" : "") << "><td>"
        << html_escape(id) << "</td><td>" << (mono_now - t.last_ms) / 1000.0
        << "s ago</td><td>" << t.step << "</td><td>";
      if (t.last_heal_ts > 0)
        o << (wall_now_s - t.last_heal_ts) << "s ago";
      else
        o << "never";
      // sparkline over the retained local-step series (tsdb, ISSUE 11):
      // the dashboard answers "when did this replica get slow" at a
      // glance instead of only showing the instantaneous p50
      std::string trend = tsdb::store().spark(id, "local_s", 32);
      if (trend.empty()) trend = tsdb::store().spark(id, "local_p50_s", 32);
      o << "</td><td>" << t.local_step_p50_s << "s</td><td>"
        << (trend.empty() ? "-" : trend) << "</td><td>"
        << (t.stuck ? "STUCK" : "ok")
        // the burn-rate SLO column (ISSUE 8): red next to the PR 2 STUCK
        // flag, driven by the replica-side evaluator's piggybacked latch
        << "</td><td" << (t.slo_breach ? " style=\"background:red\"" : "")
        << ">" << (t.slo_breach ? "BREACH" : "ok")
        // divergence-sentinel column (ISSUE 10): red when this replica's
        // commit-time state digest was in a diverged cohort round
        << "</td><td"
        << (diverged_replicas_.count(id) ? " style=\"background:red\"" : "")
        << ">" << (diverged_replicas_.count(id) ? "DIVERGED" : "ok")
        // diagnosis-bundle column (ISSUE 12): bundle count + the latest
        // bundle's name, linked to the fleet index so an operator lands
        // on the evidence one click after the red latch column
        << "</td><td>";
    if (t.diag_bundles > 0)
      o << "<a href=\"/diagnosis.json\">" << t.diag_bundles << " ("
        << html_escape(t.diag_last) << ")</a>";
    else
      o << "-";
    o << "</td></tr>";
    }
    o << "</table><p><a href=\"/cluster.json\">cluster.json</a> | "
         "<a href=\"/diagnosis.json\">diagnosis.json</a> | "
         "<a href=\"/trace\">merged trace (open in Perfetto)</a></p>";
  }
  // fleet rollup strip (ISSUE 16): the dashboard reads the same folded
  // histograms /fleet.json serves, so a 1000-group fleet's health is
  // one line here instead of a 1000-row table scroll
  {
    std::map<std::string, tftdelta::HistCounts> fleet;
    for (const auto& [rid, chains] : delta_states_) {
      (void)rid;
      for (const auto& [inc, st] : chains) {
        (void)inc;
        tftdelta::fold_hists(st, fleet);
      }
    }
    auto wit = fleet.find("wall");
    o << "<h2>Fleet rollup</h2><p>groups reporting: " << telemetry_.size();
    if (wit != fleet.end()) {
      char p50[32], p99[32];
      snprintf(p50, sizeof p50, "%.4f",
               tftdelta::grid_quantile(wit->second, 0.5));
      snprintf(p99, sizeof p99, "%.4f",
               tftdelta::grid_quantile(wit->second, 0.99));
      o << " | fleet step wall p50: " << p50 << "s p99: " << p99 << "s";
    }
    o << " | piggyback bytes: " << telemetry_bytes_piggyback_
      << " | <a href=\"/fleet.json\">fleet.json</a></p>";
  }
  o << "<h2>FT events</h2><p>evictions: " << evictions_total_
    << " | data-plane flush re-quorums: " << flush_requests_total_
    << " | divergence incidents: " << divergence_total_ << "</p>";
  if (divergence_detected_)
    o << "<p style=\"background:red\">DIVERGENCE latched: "
      << html_escape(last_divergence_) << "</p>";
  if (!recent_evictions_.empty()) {
    o << "<table border=1 cellpadding=4><tr><th>recent evictions "
         "(victim &lt; reporter @ unix s)</th></tr>";
    for (auto it = recent_evictions_.rbegin(); it != recent_evictions_.rend();
         ++it)
      o << "<tr><td>" << html_escape(*it) << "</td></tr>";
    o << "</table>";
  }
  return o.str();
}

// Minimal query-string split: "a=1&b=2" -> {a:1, b:2} (no %-decoding —
// every consumer passes plain replica ids / integers).
static std::map<std::string, std::string> parse_query(
    const std::string& qs) {
  std::map<std::string, std::string> out;
  size_t start = 0;
  while (start < qs.size()) {
    size_t amp = qs.find('&', start);
    std::string kv = qs.substr(
        start, amp == std::string::npos ? std::string::npos : amp - start);
    auto eq = kv.find('=');
    if (eq != std::string::npos)
      out[kv.substr(0, eq)] = kv.substr(eq + 1);
    if (amp == std::string::npos) break;
    start = amp + 1;
  }
  return out;
}

std::string Lighthouse::cluster_json(const std::string& query) {
  // One page answering "which replica stalled, in which state, during
  // which epoch": per-replica last report age, step, heal recency, stuck
  // flag, and the replica's own counters digest (spliced verbatim — it is
  // already a JSON object produced by telemetry.summary()).
  //
  // Pagination (ISSUE 16): a 1000-replica fleet's full sweep is several
  // MB — ?cursor=<replica_id>(exclusive)&limit=<n> windows the replica
  // map in id order (next_cursor in the reply is the next call's
  // cursor), and ?since=<ms> filters to replicas whose last report is
  // at most that old. Parameterless scrapes keep the full legacy shape.
  auto params = parse_query(query);
  std::string cursor = params.count("cursor") ? params["cursor"] : "";
  size_t limit = 0;
  if (params.count("limit"))
    limit = (size_t)strtoul(params["limit"].c_str(), nullptr, 10);
  int64_t since_ms = -1;
  if (params.count("since"))
    since_ms = strtoll(params["since"].c_str(), nullptr, 10);
  std::unique_lock<std::mutex> lk(mu_);
  int64_t now = now_ms();  // monotonic: ages only, never absolute times
  std::ostringstream o;
  o << "{\"now_unix_ms\":" << wall_ms() << ",\"quorum_id\":"
    << state_.quorum_id
    // divergence-sentinel latch (ISSUE 10): fleet-level, so one scrape
    // answers "did any committed step's state ever disagree"
    << ",\"divergence_detected\":"
    << (divergence_detected_ ? "true" : "false")
    << ",\"divergence_total\":" << divergence_total_
    << ",\"replica_count\":" << telemetry_.size() << ",\"replicas\":{";
  bool first = true;
  std::string next_cursor;
  bool truncated = false;
  size_t returned = 0;
  for (auto mit = cursor.empty() ? telemetry_.begin()
                                 : telemetry_.upper_bound(cursor);
       mit != telemetry_.end(); ++mit) {
    const auto& id = mit->first;
    const auto& t = mit->second;
    if (since_ms >= 0 && (now - t.last_ms) > since_ms) continue;
    if (limit && returned >= limit) {
      // the cursor is EXCLUSIVE (resume via upper_bound), so it must
      // name the last id this page returned, not the first one it
      // didn't — naming the unreturned id would skip it entirely
      truncated = true;
      break;
    }
    returned++;
    next_cursor = id;
    if (!first) o << ",";
    first = false;
    // fixed-point: default ostream precision would render real unix
    // timestamps in scientific notation with ~1000 s of rounding error
    char heal_ts[32];
    snprintf(heal_ts, sizeof heal_ts, "%.3f", t.last_heal_ts);
    char p50[32];
    snprintf(p50, sizeof p50, "%.6f", t.local_step_p50_s);
    o << "\"" << json_escape(id) << "\":{\"last_seen_ms_ago\":"
      << (now - t.last_ms) << ",\"step\":" << t.step
      << ",\"stuck\":" << (t.stuck ? "true" : "false")
      << ",\"last_heal_ts\":" << heal_ts
      << ",\"local_step_p50_s\":" << p50
      << ",\"slo_breach\":" << (t.slo_breach ? "true" : "false")
      << ",\"diverged\":"
      << (diverged_replicas_.count(id) ? "true" : "false")
      << ",\"summary\":"
      << (t.summary_json.empty() ? "{}" : t.summary_json)
      << ",\"anatomy\":"
      << (t.anatomy_json.empty() ? "{}" : t.anatomy_json)
      << ",\"anatomy_oversized\":" << t.anatomy_oversized
      << ",\"heartbeat_ms_ago\":";
    auto hb = state_.heartbeats.find(id);
    if (hb != state_.heartbeats.end())
      o << (now - hb->second);
    else
      o << "null";
    o << "}";
  }
  o << "}";
  if (truncated && !next_cursor.empty())
    o << ",\"next_cursor\":\"" << json_escape(next_cursor) << "\"";
  o << "}";
  return o.str();
}

std::string Lighthouse::fleet_json(const std::string& query) {
  // relaxed-ok(fn): telemetry_bytes_scrape_ reads — monotonic stat
  // counter, no ordering needed
  // Compact fleet rollup (ISSUE 16): the scrape whose size is
  // O(#histograms + #phases), NOT O(fleet). Per-replica log2 histograms
  // ride the delta piggyback as absolute bucket counts; folding them
  // here is elementwise addition on the shared lathist grid (exact by
  // construction, PR 8), so fleet percentiles need no per-replica rows.
  // ?group=<replica_id> adds that one group's own percentile block —
  // the drill-down path after the fleet view flags an anomaly.
  auto params = parse_query(query);
  std::string group = params.count("group") ? params["group"] : "";
  std::unique_lock<std::mutex> lk(mu_);
  int64_t now = now_ms();
  int64_t stuck = 0, breach = 0, min_step = -1, max_step = -1;
  for (const auto& [id, t] : telemetry_) {
    (void)id;
    if (t.stuck) stuck++;
    if (t.slo_breach) breach++;
    if (min_step < 0 || t.step < min_step) min_step = t.step;
    max_step = std::max(max_step, t.step);
  }
  std::map<std::string, tftdelta::HistCounts> fleet;
  size_t delta_replicas = 0;
  for (const auto& [rid, chains] : delta_states_) {
    (void)rid;
    if (!chains.empty()) delta_replicas++;
    for (const auto& [inc, st] : chains) {
      (void)inc;
      tftdelta::fold_hists(st, fleet);
    }
  }
  auto hist_block = [](std::ostringstream& o,
                       const std::map<std::string, tftdelta::HistCounts>& hs) {
    bool first = true;
    o << "{";
    for (const auto& [name, counts] : hs) {
      if (!first) o << ",";
      first = false;
      char p50[32], p95[32], p99[32];
      snprintf(p50, sizeof p50, "%.6f", tftdelta::grid_quantile(counts, 0.5));
      snprintf(p95, sizeof p95, "%.6f", tftdelta::grid_quantile(counts, 0.95));
      snprintf(p99, sizeof p99, "%.6f", tftdelta::grid_quantile(counts, 0.99));
      o << "\"" << json_escape(name)
        << "\":{\"count\":" << tftdelta::hist_total(counts)
        << ",\"p50_s\":" << p50 << ",\"p95_s\":" << p95 << ",\"p99_s\":"
        << p99 << "}";
    }
    o << "}";
  };
  std::ostringstream o;
  o << "{\"now_unix_ms\":" << wall_ms() << ",\"quorum_id\":"
    << state_.quorum_id << ",\"groups\":" << telemetry_.size()
    << ",\"delta_groups\":" << delta_replicas << ",\"stuck\":" << stuck
    << ",\"slo_breach\":" << breach << ",\"min_step\":" << min_step
    << ",\"max_step\":" << max_step << ",\"hist\":";
  hist_block(o, fleet);
  o << ",\"telemetry\":{\"delta_blobs_total\":"
    << telemetry_delta_blobs_total_
    << ",\"delta_fulls_total\":" << telemetry_delta_fulls_total_
    << ",\"delta_resyncs_total\":" << telemetry_delta_resyncs_total_
    << ",\"bytes\":{\"piggyback\":" << telemetry_bytes_piggyback_
    << ",\"spans\":" << telemetry_bytes_spans_ << ",\"scrape\":"
    << telemetry_bytes_scrape_.load(std::memory_order_relaxed) << "}}";
  if (!group.empty()) {
    std::map<std::string, tftdelta::HistCounts> gh;
    auto git = delta_states_.find(group);
    if (git != delta_states_.end())
      for (const auto& [inc, st] : git->second) {
        (void)inc;
        tftdelta::fold_hists(st, gh);
      }
    o << ",\"group\":{\"id\":\"" << json_escape(group) << "\"";
    auto tit = telemetry_.find(group);
    if (tit != telemetry_.end())
      o << ",\"step\":" << tit->second.step << ",\"stuck\":"
        << (tit->second.stuck ? "true" : "false") << ",\"last_seen_ms_ago\":"
        << (now - tit->second.last_ms);
    o << ",\"hist\":";
    hist_block(o, gh);
    o << "}";
  }
  o << "}";
  return o.str();
}

std::string Lighthouse::diagnosis_json() {
  // Fleet index of latch-triggered diagnosis bundles (ISSUE 12): which
  // replica captured evidence, how much, and where it lives. The status
  // hint is explicit — "empty" (fleet wired, nothing captured: the
  // healthy answer) vs a populated "ok" — so a scraper never has to
  // guess what a bare empty map means (the ambiguity that bit the
  // PR 11 /critical_path.json bring-up).
  std::unique_lock<std::mutex> lk(mu_);
  int64_t total = 0;
  for (const auto& [id, t] : telemetry_) {
    (void)id;
    total += t.diag_bundles;
  }
  std::ostringstream o;
  o << "{\"status\":\"" << (total > 0 ? "ok" : "empty")
    << "\",\"bundles_total\":" << total << ",\"replicas\":{";
  bool first = true;
  for (const auto& [id, t] : telemetry_) {
    if (!first) o << ",";
    first = false;
    o << "\"" << json_escape(id) << "\":{\"bundles\":" << t.diag_bundles
      << ",\"last\":\"" << json_escape(t.diag_last) << "\",\"dir\":\""
      << json_escape(t.diag_dir) << "\",\"step\":" << t.step << "}";
  }
  o << "}}";
  return o.str();
}

std::string Lighthouse::merged_trace_json() {
  // Chrome trace-event JSON merging every replica's piggybacked span
  // batches onto one timeline. Batches are comma-joined fragments of
  // already-serialized trace events (tracing.py drain_chrome_fragment),
  // so the merge is pure concatenation — the C++ core never parses spans.
  std::unique_lock<std::mutex> lk(mu_);
  std::ostringstream o;
  o << "{\"displayTimeUnit\":\"ms\",\"traceEvents\":[";
  bool first = true;
  for (const auto& [id, t] : telemetry_) {
    (void)id;
    for (const auto& frag : t.span_batches) {
      if (frag.empty()) continue;
      if (!first) o << ",";
      first = false;
      o << frag;
    }
  }
  o << "]}";
  return o.str();
}

std::string Lighthouse::handle_http(const std::string& method,
                                    const std::string& path) {
  // relaxed-ok(fn): telemetry_bytes_scrape_ updates/reads — monotonic
  // stat counter metering served body bytes, no ordering needed
  if (method == "GET" && path == "/") {
    return http_ok(
        "<!doctype html><html><head><title>torchft_tpu lighthouse</title>"
        "<meta http-equiv=refresh content=1 url=/></head>"
        "<body><h1>torchft_tpu lighthouse</h1><div id=s></div>"
        "<script>async function t(){let r=await fetch('/status');"
        "document.getElementById('s').innerHTML=await r.text();}"
        "t();setInterval(t,1000);</script></body></html>");
  }
  if (method == "GET" && path == "/status") return http_ok(status_html());
  // telemetry egress self-metering (ISSUE 16): every scrape channel's
  // bytes land in torchft_telemetry_bytes_total{channel="scrape"}
  auto serve_json = [this](const std::string& body) {
    // relaxed-ok: monotonic stat counter (see coord.h declaration)
    telemetry_bytes_scrape_.fetch_add(body.size(),
                                      std::memory_order_relaxed);
    return http_ok(body, "application/json");
  };
  if (method == "GET" && path.rfind("/cluster.json", 0) == 0) {
    auto qpos = path.find('?');
    return serve_json(cluster_json(
        qpos == std::string::npos ? "" : path.substr(qpos + 1)));
  }
  if (method == "GET" && path.rfind("/fleet.json", 0) == 0) {
    auto qpos = path.find('?');
    return serve_json(fleet_json(
        qpos == std::string::npos ? "" : path.substr(qpos + 1)));
  }
  if (method == "GET" && path == "/diagnosis.json")
    return serve_json(diagnosis_json());
  // Range queries over the retained time series (ISSUE 11). Query
  // params: replica=<substr> series=<substr> since=<step, exclusive>
  // max_points=<downsample cap per series>. The `cursor.max_step` in
  // the reply is the next `since` for an incremental consumer.
  if (method == "GET" && path.rfind("/timeseries.json", 0) == 0) {
    std::string replica_f, series_f;
    int64_t since = -1;
    size_t max_points = 0;
    auto qpos = path.find('?');
    if (qpos != std::string::npos) {
      std::string qs = path.substr(qpos + 1);
      size_t start = 0;
      while (start < qs.size()) {
        size_t amp = qs.find('&', start);
        std::string kv = qs.substr(
            start, amp == std::string::npos ? std::string::npos
                                            : amp - start);
        auto eq = kv.find('=');
        if (eq != std::string::npos) {
          std::string k = kv.substr(0, eq), v = kv.substr(eq + 1);
          if (k == "replica") replica_f = v;
          else if (k == "series") series_f = v;
          else if (k == "since") since = strtoll(v.c_str(), nullptr, 10);
          else if (k == "max_points")
            max_points = (size_t)strtoul(v.c_str(), nullptr, 10);
        }
        if (amp == std::string::npos) break;
        start = amp + 1;
      }
    }
    std::string ts_body = tsdb::store().render_json(
        replica_f, series_f, since, max_points, wall_ms(), json_escape);
    // relaxed-ok: monotonic stat counter (see coord.h declaration)
    telemetry_bytes_scrape_.fetch_add(ts_body.size(),
                                      std::memory_order_relaxed);
    return http_ok(ts_body, "application/json");
  }
  if (method == "GET" && path == "/trace") {
    std::string trace_body = merged_trace_json();
    // relaxed-ok: monotonic stat counter (see coord.h declaration)
    telemetry_bytes_scrape_.fetch_add(trace_body.size(),
                                      std::memory_order_relaxed);
    return http_ok(trace_body, "application/json");
  }
  if (method == "GET" && path == "/metrics") {
    // Prometheus text exposition — observability the reference lacks
    // (SURVEY §5.5: "No metrics export"). Scrape-friendly names under a
    // single torchft_ prefix.
    std::unique_lock<std::mutex> lk(mu_);
    int64_t now = now_ms();
    std::ostringstream o;
    o << "# TYPE torchft_quorum_id counter\n"
      << "torchft_quorum_id " << state_.quorum_id << "\n"
      << "# TYPE torchft_participants gauge\n"
      << "torchft_participants "
      << (state_.prev_quorum ? (int64_t)state_.prev_quorum->participants.size()
                             : 0)
      << "\n"
      << "# TYPE torchft_heartbeating_replicas gauge\n"
      << "torchft_heartbeating_replicas " << state_.heartbeats.size() << "\n";
    if (state_.prev_quorum) {
      o << "# TYPE torchft_quorum_age_seconds gauge\n"
        << "torchft_quorum_age_seconds "
        << (wall_ms() - state_.prev_quorum->created_unix_ms) / 1000.0 << "\n"
        << "# TYPE torchft_member_step gauge\n";
      int64_t mstep = -1, recovering = 0;
      for (const auto& p : state_.prev_quorum->participants)
        mstep = std::max(mstep, p.step);
      for (const auto& p : state_.prev_quorum->participants) {
        if (p.step != mstep) recovering++;
        o << "torchft_member_step{replica_id=\""
          << prom_escape(p.replica_id) << "\"} " << p.step << "\n";
      }
      o << "# TYPE torchft_member_info gauge\n";
      for (const auto& p : state_.prev_quorum->participants)
        o << "torchft_member_info{replica_id=\"" << prom_escape(p.replica_id)
          << "\",plane=\"" << prom_escape(p.plane) << "\"} 1\n";
      o << "# TYPE torchft_recovering_members gauge\n"
        << "torchft_recovering_members " << recovering << "\n";
    }
    o << "# TYPE torchft_evictions_total counter\n"
      << "torchft_evictions_total " << evictions_total_ << "\n"
      << "# TYPE torchft_flush_requests_total counter\n"
      << "torchft_flush_requests_total " << flush_requests_total_ << "\n"
      // loud-degrade counters (ISSUE 11): oversized anatomy digests
      // dropped at the 64KiB piggyback cap, and series past the per-
      // replica TSDB fan-out cap — silence here would mean silent loss
      << "# TYPE torchft_telemetry_oversized_total counter\n"
      << "torchft_telemetry_oversized_total " << telemetry_oversized_total_
      << "\n"
      // telemetry self-metering (ISSUE 16): bytes by channel plus the
      // delta-chain health counters — a resync storm (respawn loops, a
      // lossy reply path) shows up here before it shows up as cost
      << "# TYPE torchft_telemetry_bytes_total counter\n"
      << "torchft_telemetry_bytes_total{channel=\"piggyback\"} "
      << telemetry_bytes_piggyback_ << "\n"
      << "torchft_telemetry_bytes_total{channel=\"spans\"} "
      << telemetry_bytes_spans_ << "\n"
      << "torchft_telemetry_bytes_total{channel=\"scrape\"} "
      // relaxed-ok: monotonic stat counter (see coord.h declaration)
      << telemetry_bytes_scrape_.load(std::memory_order_relaxed) << "\n"
      << "# TYPE torchft_telemetry_delta_blobs_total counter\n"
      << "torchft_telemetry_delta_blobs_total "
      << telemetry_delta_blobs_total_ << "\n"
      << "# TYPE torchft_telemetry_delta_fulls_total counter\n"
      << "torchft_telemetry_delta_fulls_total "
      << telemetry_delta_fulls_total_ << "\n"
      << "# TYPE torchft_telemetry_delta_resyncs_total counter\n"
      << "torchft_telemetry_delta_resyncs_total "
      << telemetry_delta_resyncs_total_ << "\n"
      << "# TYPE torchft_tsdb_dropped_series_total counter\n"
      << "torchft_tsdb_dropped_series_total "
      << tsdb::store().dropped_series() << "\n"
      << "# TYPE torchft_divergence_total counter\n"
      << "torchft_divergence_total " << divergence_total_ << "\n"
      << "# TYPE torchft_divergence_detected gauge\n"
      << "torchft_divergence_detected " << (divergence_detected_ ? 1 : 0)
      << "\n";
    o << "# TYPE torchft_heartbeat_age_seconds gauge\n";
    for (const auto& [id, beat] : state_.heartbeats)
      o << "torchft_heartbeat_age_seconds{replica_id=\"" << prom_escape(id)
        << "\"} " << (now - beat) / 1000.0 << "\n";
    if (!telemetry_.empty()) {
      // step-anatomy scalars piggybacked by the replicas (ISSUE 8):
      // local-step p50s feed the fleet straggler detector, slo_breach is
      // the replica-side burn-rate evaluator's latch
      o << "# TYPE torchft_replica_local_step_p50_seconds gauge\n";
      for (const auto& [id, t] : telemetry_)
        o << "torchft_replica_local_step_p50_seconds{replica_id=\""
          << prom_escape(id) << "\"} " << t.local_step_p50_s << "\n";
      o << "# TYPE torchft_slo_breach gauge\n";
      for (const auto& [id, t] : telemetry_)
        o << "torchft_slo_breach{replica_id=\"" << prom_escape(id) << "\"} "
          << (t.slo_breach ? 1 : 0) << "\n";
    }
    // native latency histograms (lathist.h): whatever this process
    // recorded — rpc.serve always; dp.* / quorum.fanout too when the
    // lighthouse shares a process with a worker (in-process tests)
    lathist::render_prometheus(o);
    std::string metrics_body = o.str();
    // relaxed-ok: monotonic stat counter (see coord.h declaration)
    telemetry_bytes_scrape_.fetch_add(metrics_body.size(),
                                      std::memory_order_relaxed);
    return http_ok(metrics_body, "text/plain; version=0.0.4");
  }
  if (method == "GET" && path == "/status.json") {
    std::unique_lock<std::mutex> lk(mu_);
    std::ostringstream o;
    o << "{\"quorum_id\":" << state_.quorum_id << ",\"num_participants\":"
      << (state_.prev_quorum ? (int64_t)state_.prev_quorum->participants.size()
                             : -1)
      << ",\"heartbeats\":" << state_.heartbeats.size()
      << ",\"evictions_total\":" << evictions_total_
      << ",\"flush_requests_total\":" << flush_requests_total_
      << ",\"divergence_total\":" << divergence_total_
      << ",\"divergence_detected\":"
      << (divergence_detected_ ? "true" : "false");
    if (state_.prev_quorum) {
      int64_t mstep = -1;
      for (const auto& p : state_.prev_quorum->participants)
        mstep = std::max(mstep, p.step);
      o << ",\"max_step\":" << mstep << ",\"members\":[";
      bool first = true;
      for (const auto& p : state_.prev_quorum->participants) {
        if (!first) o << ",";
        first = false;
        o << "{\"replica_id\":\"" << json_escape(p.replica_id)
          << "\",\"step\":" << p.step << ",\"plane\":\""
          << json_escape(p.plane) << "\",\"recovering\":"
          << (p.step != mstep ? "true" : "false")
          << ",\"commit_failures\":" << p.commit_failures << "}";
      }
      o << "]";
    }
    o << ",\"recent_evictions\":[";
    bool first = true;
    for (const auto& ev : recent_evictions_) {
      if (!first) o << ",";
      first = false;
      o << "\"" << json_escape(ev) << "\"";
    }
    o << "],\"latency\":";
    // native latency histograms, raw per-bucket counts (fixed log2
    // bounds, so merging counts across processes is exact addition)
    lathist::render_json(o);
    o << "}";
    return http_ok(o.str(), "application/json");
  }
  // POST /replica/{id}/kill → forward to that replica's manager
  // (src/lighthouse.rs:412-437).
  const std::string pre = "/replica/";
  if (method == "POST" && path.rfind(pre, 0) == 0 &&
      path.size() > pre.size() + 5 &&
      path.substr(path.size() - 5) == "/kill") {
    std::string replica_id =
        path.substr(pre.size(), path.size() - pre.size() - 5);
    std::string addr;
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (state_.prev_quorum)
        for (const auto& p : state_.prev_quorum->participants)
          if (p.replica_id == replica_id) addr = p.address;
    }
    if (addr.empty()) return http_error_page("failed to find replica");
    try {
      RpcClient client(addr, 10000);
      client.call("mgr.kill", Value::M().set("msg", Value::S("killed from dashboard")),
                  10000);
    } catch (const std::exception& e) {
      return http_error_page(e.what());
    }
    return http_ok("ok", "text/plain");
  }
  return http_ok("not found", "text/plain");
}

std::string Lighthouse::http_error_page(const std::string& msg) {
  std::string body = "Something went wrong: " + msg;
  std::ostringstream o;
  o << "HTTP/1.1 500 Error\r\nContent-Type: text/plain\r\nContent-Length: "
    << body.size() << "\r\nConnection: close\r\n\r\n"
    << body;
  return o.str();
}

// ---- Manager --------------------------------------------------------------

ManagerSrv::ManagerSrv(const std::string& replica_id,
                       const std::string& lighthouse_addr,
                       const std::string& hostname, const std::string& bind,
                       const std::string& store_addr, uint64_t world_size,
                       int64_t heartbeat_interval_ms,
                       int64_t connect_timeout_ms)
    : replica_id_(replica_id),
      hostname_(hostname.empty() ? get_hostname() : hostname),
      store_address_(store_addr),
      lighthouse_addr_(lighthouse_addr),
      world_size_(world_size),
      heartbeat_interval_ms_(heartbeat_interval_ms),
      connect_timeout_ms_(connect_timeout_ms) {
  // Connect to the lighthouse eagerly; construction fails if unreachable,
  // matching Manager::new (src/manager.rs:97).
  lighthouse_client_ =
      std::make_unique<RpcClient>(lighthouse_addr, connect_timeout_ms);
  digest_client_ =
      std::make_unique<RpcClient>(lighthouse_addr, connect_timeout_ms);
  std::string err;
  bool ok = server_.start(
      bind,
      [this](const std::string& m, const Value& r, int64_t d) {
        return handle_rpc(m, r, d);
      },
      nullptr, &err);
  if (!ok) throw RpcError(UNAVAILABLE, "manager bind failed: " + err);
  heartbeat_thread_ = std::thread([this] { heartbeat_loop(); });
  logline("Manager " + replica_id_ + " listening on " + address());
}

ManagerSrv::~ManagerSrv() { shutdown(); }

void ManagerSrv::shutdown() {
  if (!running_.exchange(false)) return;
  // A handler may be blocked inside the lighthouse long-poll holding mu_;
  // abort the socket first so it fails fast and releases the lock. Same
  // for a digest fence wait blocked on the lighthouse cohort.
  lighthouse_client_->abort();
  digest_client_->abort();
  {
    std::lock_guard<std::mutex> g(mu_);
    cv_.notify_all();
  }
  if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
  server_.shutdown();
}

std::string ManagerSrv::address() const {
  return "http://" + hostname_ + ":" + std::to_string(server_.port());
}

void ManagerSrv::heartbeat_loop() {
  // Own connection so the long-poll quorum call on lighthouse_client_
  // never delays heartbeats (src/manager.rs:155-166 clones the channel).
  std::unique_ptr<RpcClient> client;
  while (running_.load()) {
    try {
      if (!client)
        client = std::make_unique<RpcClient>(lighthouse_addr_, 5000);
      client->call("lh.heartbeat",
                   Value::M().set("replica_id", Value::S(replica_id_)), 5000);
    } catch (const std::exception&) {
      client.reset();  // reconnect next round
    }
    int64_t slept = 0;
    while (running_.load() && slept < heartbeat_interval_ms_) {
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      slept += 10;
    }
  }
}

Value ManagerSrv::handle_rpc(const std::string& method, const Value& req,
                             int64_t deadline) {
  if (method == "mgr.quorum") return handle_quorum(req, deadline);
  if (method == "mgr.should_commit") return handle_should_commit(req, deadline);
  if (method == "mgr.checkpoint_metadata") {
    std::lock_guard<std::mutex> g(mu_);
    auto it = checkpoint_metadata_.find(req.geti("rank"));
    if (it == checkpoint_metadata_.end())
      throw RpcError(INVALID_ARGUMENT, "rank not found");
    return Value::M().set("checkpoint_metadata", Value::S(it->second));
  }
  if (method == "mgr.kill") {
    logline("got kill request: " + req.gets("msg"));
    if (getenv("TORCHFT_TPU_SOFT_KILL") == nullptr) {
      fflush(nullptr);
      _exit(1);
    }
    return Value::M();  // soft kill for in-process tests
  }
  if (method == "mgr.ping") return Value::M();  // liveness probe target
  if (method == "mgr.evict") {
    // Forward a local rank's dead-peer report to the lighthouse with this
    // group's identity as the reporter. A fresh client: lighthouse_client_
    // may be parked in a long-poll quorum call under mu_.
    const std::string victim = req.gets("victim");
    if (victim.empty() || victim == replica_id_)
      throw RpcError(INVALID_ARGUMENT, "evict: bad victim " + victim);
    RpcClient client(lighthouse_addr_, connect_timeout_ms_);
    Value lreq = Value::M();
    lreq.set("reporter", Value::S(replica_id_));
    lreq.set("victim", Value::S(victim));
    return client.call("lh.evict", lreq, req.geti("_d", 5000));
  }
  throw RpcError(INVALID_ARGUMENT, "unknown method " + method);
}

Value ManagerSrv::handle_quorum(const Value& req, int64_t deadline) {
  int64_t rank = req.geti("rank");
  int64_t step = req.geti("step");
  int64_t timeout_ms = req.geti("_d", 60000);

  std::unique_lock<std::mutex> lk(mu_);
  checkpoint_metadata_[rank] = req.gets("checkpoint_metadata");
  participants_.insert(rank);
  pending_commit_failures_ =
      std::max(pending_commit_failures_, req.geti("commit_failures", 0));
  if (req.has("plane")) pending_plane_ = req.gets("plane");
  if (req.has("telemetry") && req.at("telemetry").type == Value::Type::MAP) {
    // Scalars: last-writer-wins across this round's local ranks. Span
    // fragments: concatenated, so no rank's spans are dropped.
    const Value& t = req.at("telemetry");
    std::string spans = t.gets("spans");
    // cap: repeated failed quorum rounds must not accumulate fragments
    // without bound (they are re-attempted until the lighthouse answers)
    if (!spans.empty() && pending_spans_.size() + spans.size() < (1u << 20)) {
      if (!pending_spans_.empty()) pending_spans_ += ",";
      pending_spans_ += spans;
    }
    if (t.has("tdelta") && t.at("tdelta").type == Value::Type::BYTES) {
      // Delta blobs (ISSUE 16) accumulate as a LIST — each local rank's
      // encoder owns a version chain, and last-write-wins would break a
      // dropped rank's chain into a permanent resync storm. Bounded:
      // repeated failed rounds degrade by dropping the OLDEST blob
      // (the chain self-heals via resync) rather than growing forever.
      const std::string& blob = t.at("tdelta").s;
      static constexpr size_t kMaxPendingBlobs = 64;
      static constexpr size_t kMaxPendingBytes = 1 << 19;  // 512 KiB
      while (!pending_tdeltas_.empty() &&
             (pending_tdeltas_.size() >= kMaxPendingBlobs ||
              pending_tdelta_bytes_ + blob.size() > kMaxPendingBytes)) {
        pending_tdelta_bytes_ -= pending_tdeltas_.front().size();
        pending_tdeltas_.erase(pending_tdeltas_.begin());
      }
      if (blob.size() <= kMaxPendingBytes) {
        pending_tdelta_bytes_ += blob.size();
        pending_tdeltas_.push_back(blob);
      }
    } else {
      pending_telemetry_ = t;
    }
  }
  uint64_t seen = quorum_seq_;

  if (participants_.size() >= world_size_) {
    participants_.clear();
    logline("Manager " + replica_id_ + ": all workers joined, starting quorum");
    QuorumMember me;
    me.replica_id = replica_id_;
    me.address = address();
    me.store_address = store_address_;
    me.step = step;
    me.world_size = world_size_;
    me.shrink_only = req.getb("shrink_only");
    me.commit_failures = pending_commit_failures_;
    me.plane = pending_plane_;
    pending_commit_failures_ = 0;
    // consumed like the flush counter above: a later quorum round that
    // omits 'plane' must not report this epoch's stale transport label
    pending_plane_.clear();
    Value lreq = Value::M();
    lreq.set("requester", me.to_value());
    if (!pending_telemetry_.is_none() || !pending_tdeltas_.empty() ||
        !pending_spans_.empty()) {
      Value t = pending_telemetry_.is_none() ? Value::M()
                                             : pending_telemetry_;
      if (!pending_tdeltas_.empty()) {
        Value batch = Value::L();
        for (auto& blob : pending_tdeltas_)
          batch.list.push_back(Value::Bytes(std::move(blob)));
        t.set("tdeltas", batch);
        pending_tdeltas_.clear();
        pending_tdelta_bytes_ = 0;
      }
      if (!pending_spans_.empty()) t.set("spans", Value::S(pending_spans_));
      lreq.set("telemetry", t);
      pending_telemetry_ = Value::None();
      pending_spans_.clear();
    }
    // Like the reference (src/manager.rs:181 TODO), the lock is held for the
    // duration of the lighthouse call; peer handlers are parked in cv waits.
    // quorum.fanout distribution: the full lh.quorum round trip — the
    // long-poll until the fleet's quorum forms, i.e. the per-step control
    // cost the HA roadmap item needs p50/p99-vs-group-count for
    int64_t fanout_t0 = lathist::now_ns();
    try {
      Value resp = lighthouse_client_->call("lh.quorum", lreq, timeout_ms);
      lathist::observe(lathist::kQuorumFanout,
                       (double)(lathist::now_ns() - fanout_t0) / 1e9);
      // mark recorded: a WireError from the parse below must not make
      // the catch block observe the SAME round trip a second time
      fanout_t0 = -1;
      // telemetry ack relay (ISSUE 16): every local rank's quorum reply
      // carries the ack map so each rank's encoder finds its own
      // incarnation; kept across rounds (a round whose lreq carried no
      // telemetry still relays the last known versions)
      if (resp.has("tack")) last_tack_ = resp.at("tack");
      Quorum q = Quorum::from_value(resp.at("quorum"));
      quorums_[++quorum_seq_] = q;
      quorum_error_.reset();
      while (quorums_.size() > 16) quorums_.erase(quorums_.begin());
    } catch (const std::exception& e) {
      // Fan the failure out to all waiting local ranks (the reference only
      // surfaces it on the triggering rank and lets peers hit their own
      // deadline; propagating is strictly more informative). std::exception,
      // not just RpcError: a malformed lighthouse reply makes from_value/
      // at() throw WireError, and an escaping exception here skips BOTH the
      // seq bump and notify_all — every peer handler parked in the cv wait
      // below would stall until its own deadline [bugprone-exception-escape
      // class; flagged while wiring the clang-tidy gate].
      if (fanout_t0 >= 0)
        lathist::observe(lathist::kQuorumFanout,
                         (double)(lathist::now_ns() - fanout_t0) / 1e9);
      quorum_error_ = std::string(e.what());
      quorum_seq_++;
    }
    cv_.notify_all();
  }

  bool ok = cv_wait_deadline(
      cv_, lk, deadline,
      [&] { return quorum_seq_ > seen || !running_.load(); });
  if (!running_.load()) throw RpcError(CANCELLED, "manager shutting down");
  if (!ok) throw RpcError(DEADLINE_EXCEEDED, "quorum wait timed out");

  // Take the first quorum delivered after we joined.
  uint64_t mine = seen + 1;
  auto it = quorums_.find(mine);
  if (it == quorums_.end()) {
    if (quorum_error_.has_value())
      throw RpcError(CANCELLED, "lighthouse quorum failed: " + *quorum_error_);
    // The expected seq was trimmed from the 16-deep window: this rank
    // stalled for >16 quorums. Delivering an older quorum here would
    // silently reconfigure it into a dead epoch (round-2 verdict weak #6)
    // — error loudly instead so the straggler re-joins fresh.
    logline("Manager " + replica_id_ + " rank " + std::to_string(rank) +
            ": quorum seq " + std::to_string(mine) +
            " trimmed from window (stalled >16 quorums); erroring straggler");
    throw RpcError(CANCELLED,
                   "quorum window overrun: this rank stalled for more than "
                   "16 quorum rounds; re-join with a fresh quorum call");
  }
  ManagerQuorumResult res = compute_quorum_results(replica_id_, rank, it->second);
  // crash-durable breadcrumb: the last quorum this rank was delivered
  // (a = rank, b = heal) — pairs with the lighthouse's publish records
  bb::record(bb::kQuorumDeliver, res.quorum_id, res.max_step, rank,
             res.heal ? 1 : 0);
  Value out = res.to_value();
  // per-rank ack relay (ISSUE 16): read under mu_ (still held here),
  // BEFORE the injected delay below may drop the lock
  if (!last_tack_.is_none()) out.set("tack", last_tack_);
  // env-gated injection: hold the computed quorum reply (outside the
  // lock — peer ranks' handlers must not stall behind the injected delay)
  static const long fi_qd =
      fi::parse_long("TORCHFT_FI_QUORUM_REPLY_DELAY_MS");
  if (fi_qd > 0) {
    lk.unlock();
    fi::sleep_ms(fi_qd);
  }
  return out;
}

Value ManagerSrv::handle_should_commit(const Value& req, int64_t deadline) {
  int64_t rank = req.geti("rank");
  int64_t step = req.geti("step", -1);
  bool vote = req.getb("should_commit");

  std::unique_lock<std::mutex> lk(mu_);
  if (!vote) commit_failures_.insert(rank);
  commit_votes_.insert(rank);
  // Divergence sentinel (ISSUE 10): each local rank may attach a digest
  // of its post-reduce state; the round-completing rank folds them (in
  // rank order — cross-group comparison is per rank plane) into one
  // group digest and reports it to the lighthouse's (epoch, step)
  // cohort compare. `fence` asks the lighthouse to arbitrate BEFORE the
  // decision publishes, closing the corrupt-commit hole at the source.
  if (req.has("digest")) {
    commit_digests_[rank] = req.gets("digest");
    commit_epoch_ = req.geti("epoch", commit_epoch_);
    commit_fence_ = commit_fence_ || req.getb("fence", false);
  }
  uint64_t seen = commit_seq_;

  if (commit_votes_.size() >= world_size_) {
    bool decision = commit_failures_.empty();
    bool divergence = false;
    // Consume the round's state BEFORE any unlock: a retrying rank's
    // vote landing while the digest exchange is in flight must start a
    // FRESH round (park below at < world_size votes), never observe the
    // still-full vote set and publish a duplicate decision.
    bool any_abstain = false;
    std::string group;
    for (const auto& [r, d] : commit_digests_) {
      if (d == "-") any_abstain = true;
      group += std::to_string(r) + ":" + d + ";";
    }
    const bool have_digests = !commit_digests_.empty();
    // one abstaining rank abstains the whole group (its plane's state
    // is not committing cleanly)
    if (any_abstain) group = "-";
    const bool fence = commit_fence_;
    const int64_t ep = commit_epoch_;
    commit_digests_.clear();
    commit_fence_ = false;
    commit_votes_.clear();
    commit_failures_.clear();
    if (have_digests) {
      // Lighthouse exchange OUTSIDE the lock (every local rank of THIS
      // round has voted and is parked in the cv wait below; the round's
      // own state was consumed above). Report even on a local veto: the
      // other groups' fence waits gate on the FULL cohort, and a silent
      // absence would stretch their commit to the deadline for a step
      // that aborts anyway.
      lk.unlock();
      bool match = true;
      try {
        Value dreq = Value::M();
        dreq.set("replica_id", Value::S(replica_id_));
        dreq.set("epoch", Value::I(ep));
        dreq.set("step", Value::I(step));
        dreq.set("digest", Value::S(group));
        dreq.set("wait", Value::B(fence));
        int64_t to_ms =
            fence ? std::max((int64_t)1000, deadline - now_ms()) : 5000;
        Value dresp = digest_client_->call("lh.digest", dreq, to_ms);
        match = dresp.getb("match", true);
        divergence = dresp.getb("divergence", false);
      } catch (const std::exception& e) {
        // best-effort when the lighthouse can't answer: fail OPEN — a
        // missing compare cannot corrupt state, and quorum formation
        // (which also needs the lighthouse) is the real gate on
        // progress. The fence only vetoes on a POSITIVE mismatch.
        logline(std::string("divergence digest exchange failed: ") +
                e.what());
      }
      lk.lock();
      if (fence && !match) {
        logline("DIVERGENCE FENCE: vetoing commit at step " +
                std::to_string(step));
        decision = false;
        divergence = true;
      }
    }
    logline("should_commit completed decision=" +
            std::string(decision ? "true" : "false"));
    bb::record(bb::kCommitDecision, ep, step, decision ? 1 : 0,
               divergence ? 1 : 0);
    commit_decisions_[++commit_seq_] = decision;
    commit_divergence_[commit_seq_] = divergence;
    while (commit_decisions_.size() > 16)
      commit_decisions_.erase(commit_decisions_.begin());
    while (commit_divergence_.size() > 16)
      commit_divergence_.erase(commit_divergence_.begin());
    cv_.notify_all();
  }

  bool ok = cv_wait_deadline(
      cv_, lk, deadline,
      [&] { return commit_seq_ > seen || !running_.load(); });
  if (!running_.load()) throw RpcError(CANCELLED, "manager shutting down");
  if (!ok) throw RpcError(DEADLINE_EXCEEDED, "should_commit wait timed out");

  auto it = commit_decisions_.find(seen + 1);
  if (it == commit_decisions_.end()) {
    // Same window-overrun rule as handle_quorum: never hand a straggler a
    // stale decision silently (round-2 verdict weak #6).
    logline("Manager " + replica_id_ + " rank " + std::to_string(rank) +
            ": commit decision seq " + std::to_string(seen + 1) +
            " trimmed from window; erroring straggler");
    throw RpcError(CANCELLED,
                   "commit window overrun: decision for this round was "
                   "trimmed; treat the step as failed and re-quorum");
  }
  const bool decision = it->second;
  auto dit = commit_divergence_.find(seen + 1);
  const bool divergence_flag =
      dit != commit_divergence_.end() && dit->second;
  lk.unlock();
  // env-gated injection on the vote DECISION path: delay the reply
  // (commit-barrier RTT) or drop the nth one (a lost decision — the
  // caller times out and must treat the step as failed)
  static const long fi_cd =
      fi::parse_long("TORCHFT_FI_COMMIT_REPLY_DELAY_MS");
  if (fi_cd > 0) fi::sleep_ms(fi_cd);
  static const long fi_drop = fi::parse_long("TORCHFT_FI_COMMIT_REPLY_DROP");
  if (fi_drop > 0) {
    static std::atomic<long> fi_replies{0};
    long r = ++fi_replies;
    if (r == fi_drop) {
      fi::write_evidence("commit.vote", r, "drop");
      throw RpcError(UNAVAILABLE, "fault injection: dropped commit reply");
    }
  }
  return Value::M()
      .set("should_commit", Value::B(decision))
      .set("divergence", Value::B(divergence_flag));
}

// ---- KV store -------------------------------------------------------------

KvStore::KvStore(const std::string& bind) : hostname_(get_hostname()) {
  std::string err;
  bool ok = server_.start(
      bind,
      [this](const std::string& m, const Value& r, int64_t d) {
        return handle_rpc(m, r, d);
      },
      nullptr, &err);
  if (!ok) throw RpcError(UNAVAILABLE, "store bind failed: " + err);
}

KvStore::~KvStore() { shutdown(); }

void KvStore::shutdown() {
  if (!running_.exchange(false)) return;
  {
    std::lock_guard<std::mutex> g(mu_);
    cv_.notify_all();
  }
  server_.shutdown();
}

std::string KvStore::address() const {
  return hostname_ + ":" + std::to_string(server_.port());
}

Value KvStore::handle_rpc(const std::string& method, const Value& req,
                          int64_t deadline) {
  if (method == "store.set") {
    std::lock_guard<std::mutex> g(mu_);
    data_[req.gets("k")] = req.gets("v");
    cv_.notify_all();
    return Value::M();
  }
  if (method == "store.get") {
    std::unique_lock<std::mutex> lk(mu_);
    const std::string k = req.gets("k");
    bool wait = req.getb("wait", true);
    if (wait) {
      bool ok = cv_wait_deadline(
          cv_, lk, deadline,
          [&] { return data_.count(k) > 0 || !running_.load(); });
      if (!ok || !data_.count(k))
        throw RpcError(DEADLINE_EXCEEDED, "store.get timed out waiting for " + k);
    } else if (!data_.count(k)) {
      throw RpcError(NOT_FOUND, "key not found: " + k);
    }
    return Value::M().set("v", Value::Bytes(data_[k]));
  }
  if (method == "store.add") {
    // Counters live in data_ as decimal strings so get/wait/del/keys all
    // observe them (TCPStore add/get interop semantics).
    std::lock_guard<std::mutex> g(mu_);
    const std::string k = req.gets("k");
    int64_t v = 0;
    auto it = data_.find(k);
    if (it != data_.end() && !it->second.empty())
      v = strtoll(it->second.c_str(), nullptr, 10);
    v += req.geti("delta", 1);
    data_[k] = std::to_string(v);
    cv_.notify_all();
    return Value::M().set("v", Value::I(v));
  }
  if (method == "store.del") {
    std::lock_guard<std::mutex> g(mu_);
    data_.erase(req.gets("k"));
    return Value::M();
  }
  if (method == "store.keys") {
    std::lock_guard<std::mutex> g(mu_);
    const std::string pre = req.gets("prefix");
    Value out = Value::L();
    for (const auto& [k, v] : data_)
      if (k.rfind(pre, 0) == 0) out.list.push_back(Value::S(k));
    return Value::M().set("keys", out);
  }
  throw RpcError(INVALID_ARGUMENT, "unknown method " + method);
}

}  // namespace tft
