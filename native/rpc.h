// torchft_tpu native core — framed RPC server/client over TCP.
//
// Replaces the reference's tonic gRPC stack (/root/reference/src/net.rs,
// src/retry.rs, src/timeout.rs) with a dependency-free equivalent:
//   * thread-per-connection server that also answers plain HTTP on the same
//     port (the reference merges axum HTTP + tonic gRPC on one listener,
//     src/lighthouse.rs:320-358),
//   * client with exponential-backoff connect retries (retry.rs:6-41) and
//     TCP keepalives (net.rs:8-20),
//   * per-request deadline carried in-band ("_d" ms field — the grpc-timeout
//     header analogue, src/timeout.rs:18-61) and enforced on both sides.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <functional>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "wire.h"

namespace tft {

int64_t now_ms();  // monotonic clock, milliseconds

// Thread-safe strerror. glibc < 2.32 keeps strerror()'s result in one
// shared static buffer (clang-tidy: concurrency-mt-unsafe), and the
// stripe workers hit error paths concurrently — two simultaneous hop
// failures could interleave each other's message text.
std::string errno_str(int e);

// Condition-variable wait against an absolute now_ms() deadline.
//
// Production builds wait on the steady clock directly. Under
// -fsanitize=thread the SAME deadline is converted to a system_clock
// wait: libstdc++ implements steady-clock cv waits via
// pthread_cond_clockwait when glibc provides it (>= 2.30), and gcc 10's
// libtsan has NO interceptor for clockwait — the wait's internal
// unlock/relock becomes invisible, TSan believes the mutex is still
// held, and every later interaction with it reports phantom
// double-locks and races where both sides "hold" the lock (observed as
// ~18 reports/worker across the whole fault matrix before this shim).
// system_clock waits go through pthread_cond_timedwait, which IS
// intercepted. The only semantic difference — sensitivity to wall-clock
// jumps — is confined to sanitizer runs.
template <typename Pred>
inline bool cv_wait_deadline(std::condition_variable& cv,
                             std::unique_lock<std::mutex>& lk,
                             int64_t deadline_ms, Pred pred) {
#if defined(__SANITIZE_THREAD__)
  auto sys = std::chrono::system_clock::now() +
             std::chrono::milliseconds(deadline_ms - now_ms());
  return cv.wait_until(lk, sys, pred);
#else
  return cv.wait_until(
      lk,
      std::chrono::steady_clock::time_point(
          std::chrono::milliseconds(deadline_ms)),
      pred);
#endif
}

// no-predicate form: returns on notify OR deadline (caller re-checks
// its own condition, e.g. the wait_ready poll loop)
inline void cv_wait_deadline(std::condition_variable& cv,
                             std::unique_lock<std::mutex>& lk,
                             int64_t deadline_ms) {
#if defined(__SANITIZE_THREAD__)
  cv.wait_until(lk, std::chrono::system_clock::now() +
                        std::chrono::milliseconds(deadline_ms - now_ms()));
#else
  cv.wait_until(lk, std::chrono::steady_clock::time_point(
                        std::chrono::milliseconds(deadline_ms)));
#endif
}

// ---- low-level socket helpers -------------------------------------------
// fd < 0 on failure. host may be a hostname, IPv4/IPv6 literal, or empty
// (bind: all interfaces).
int tcp_listen(const std::string& bind_addr, std::string* err);
int tcp_connect(const std::string& host, int port, int64_t timeout_ms,
                std::string* err);
int listen_port(int fd);
bool read_exact(int fd, void* buf, size_t n, int64_t deadline_ms);
bool write_all(int fd, const void* buf, size_t n);

// Parse "http://host:port", "tft://host:port", or "host:port".
bool parse_addr(const std::string& addr, std::string* host, int* port);

// ---- server --------------------------------------------------------------

// Handler: gets the decoded request MAP (with "_m" method and "_d" deadline
// in ms already interpreted into deadline: absolute now_ms()+_d). Returns the
// response body; throws RpcError to return a non-OK status.
using RpcHandler =
    std::function<Value(const std::string& method, const Value& req,
                        int64_t deadline_ms_abs)>;

// HTTP handler: request line + headers already consumed; returns full HTTP
// response bytes. method is "GET"/"POST", path like "/status".
using HttpHandler =
    std::function<std::string(const std::string& method, const std::string& path)>;

class RpcServer {
 public:
  RpcServer() = default;
  ~RpcServer() { shutdown(); }

  // Starts listening + accept thread. Returns false and sets err on failure.
  bool start(const std::string& bind_addr, RpcHandler handler,
             HttpHandler http_handler, std::string* err);
  void shutdown();
  int port() const { return port_; }
  bool running() const { return running_.load(); }

 private:
  void accept_loop();
  void serve_conn(int fd);

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  RpcHandler handler_;
  HttpHandler http_handler_;

  std::mutex conns_mu_;
  std::set<int> conns_;
  // Joinable connection threads keyed by id; joined in shutdown() after
  // their fds are shut down and the owner has cancelled any in-handler
  // waits, so handler state is never touched after the owner destructs.
  // Finished threads announce themselves so the accept loop can reap.
  std::map<uint64_t, std::thread> conn_threads_;
  std::vector<uint64_t> finished_threads_;
  uint64_t next_thread_id_ = 0;
};

// ---- client --------------------------------------------------------------

class RpcClient {
 public:
  // Connects eagerly, retrying with exponential backoff until
  // connect_timeout_ms elapses (parity with the reference's retrying
  // connect, src/net.rs:22-34). Throws RpcError(UNAVAILABLE) on failure.
  RpcClient(const std::string& addr, int64_t connect_timeout_ms);
  ~RpcClient();

  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  // Sends {._m=method, ._d=timeout_ms, ...req} and waits for the response.
  // Throws RpcError on transport failure / deadline / non-OK status.
  Value call(const std::string& method, Value req, int64_t timeout_ms);

  // Cross-thread cancel: shuts down the socket so a blocked call() fails
  // promptly. The client stays usable (it reconnects on the next call).
  void abort();

  const std::string& addr() const { return addr_; }

 private:
  void ensure_connected(int64_t timeout_ms);
  void disconnect();

  std::string addr_;
  std::string host_;
  int port_ = 0;
  int64_t connect_timeout_ms_;
  std::mutex mu_;
  // atomic: abort() reads it WITHOUT mu_ (a blocked call() holds the
  // lock, which is the whole point of abort) while call()'s
  // disconnect/reconnect writes it under mu_ — a plain int is a data
  // race. fd_mu_ additionally serializes abort()'s shutdown against
  // disconnect()'s close so the fd number can't be recycled in between
  // (never held across blocking IO; strictly after mu_ when both are
  // taken, so no ordering cycle).
  std::mutex fd_mu_;
  std::atomic<int> fd_{-1};
};

}  // namespace tft
