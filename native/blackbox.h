// Crash-durable native breadcrumbs — the C++ sibling of
// torchft_tpu/telemetry/blackbox.py.
//
// The hot native paths (stripe hops, the RPC serve loop, quorum
// transitions) run GIL-free and leave no trace when the process dies
// mid-op — which is exactly when their last actions are the evidence a
// postmortem needs. This header writes fixed-size records into an
// mmap'd ring file: dirtied mmap pages belong to the kernel's page
// cache, so a SIGKILL/SIGSEGV loses at most the one record being
// written (its CRC won't validate — the reader skips it), never the
// trail behind it.
//
// Lock-free by construction: one relaxed fetch_add claims a slot, the
// record body is written, the CRC is stored last. Two writers can only
// collide after a full ring lap mid-write, which the CRC again turns
// into a skipped record instead of corrupt evidence. Disarmed
// (TORCHFT_BLACKBOX_DIR unset), a record() call is one static load.
//
// File layout ("<dir>/tft_bb_<pid>_native.bb"):
//   header (64 B): "TFTBBNA1" | u32 cap_records | u32 pid | pad
//   records: cap_records x 64 B, slot = seq % cap
//
// Record (64 B, little-endian; torchft_tpu/telemetry/blackbox.py
// read_native_blackbox() parses it byte for byte):
//   u32 magic "NTBB" | u16 site | u16 flags | u64 seq | u64 ts_ns(wall)
//   | i64 epoch | i64 step | i64 a | i64 b | u32 crc32(first 56 B)
//   | u32 pad
//
// Ring bytes come from TORCHFT_BLACKBOX_SIZE (shared with the Python
// ring; default 1 MiB => 16k records).

#ifndef TFT_BLACKBOX_H_
#define TFT_BLACKBOX_H_

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace tft {
namespace bb {

// Site ids are wire-stable: telemetry/blackbox.py NATIVE_SITES_BB maps
// them back to names for the merged postmortem timeline.
enum Site : uint16_t {
  kDpHop = 1,
  kDpStripe = 2,
  kRpcServe = 3,
  kQuorumPublish = 4,
  kQuorumDeliver = 5,
  kCommitDecision = 6,
  kDivergence = 7,
};

constexpr uint32_t kRecMagic = 0x4242544E;  // "NTBB"
constexpr size_t kRecSize = 64;
constexpr size_t kHeaderSize = 64;

// zlib-compatible CRC-32 (poly 0xEDB88320), table built once.
inline uint32_t crc32(const uint8_t* data, size_t n) {
  static const auto* table = [] {
    auto* t = new uint32_t[256];
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
    return t;
  }();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Ring {
  uint8_t* base = nullptr;   // mmap base (nullptr = disarmed)
  uint32_t cap = 0;          // record slots
  std::atomic<uint64_t> seq{0};
};

inline Ring& ring() {
  static Ring r;
  static std::atomic<int> state{0};  // 0 = uninit, 1 = armed, -1 = off
  // release-order(fn): the final state store publishes the fully
  // initialized mapping (base/cap written first); the acquire load
  // pairs with it. The benign one-time-init race is documented below.
  int s = state.load(std::memory_order_acquire);
  if (s != 0) return r;
  // One-time init; a benign race here at worst re-runs the (idempotent)
  // open on two threads — the loser's mapping leaks one ring, and both
  // write valid records into whichever base wins the final store.
  const char* dir = std::getenv("TORCHFT_BLACKBOX_DIR");
  if (!dir || !*dir) {
    state.store(-1, std::memory_order_release);
    return r;
  }
  long bytes = 1 << 20;
  if (const char* sz = std::getenv("TORCHFT_BLACKBOX_SIZE")) {
    long v = std::atol(sz);
    if (v >= 4096) bytes = v;
  }
  uint32_t cap = (uint32_t)((bytes - (long)kHeaderSize) / (long)kRecSize);
  if (cap < 16) cap = 16;
  size_t total = kHeaderSize + (size_t)cap * kRecSize;
  char path[512];
  std::snprintf(path, sizeof(path), "%s/tft_bb_%d_native.bb", dir,
                (int)getpid());
  int fd = ::open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) {
    state.store(-1, std::memory_order_release);
    return r;
  }
  if (ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    state.store(-1, std::memory_order_release);
    return r;
  }
  void* m = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) {
    state.store(-1, std::memory_order_release);
    return r;
  }
  uint8_t* b = (uint8_t*)m;
  std::memset(b, 0, kHeaderSize);
  std::memcpy(b, "TFTBBNA1", 8);
  uint32_t pid = (uint32_t)getpid();
  std::memcpy(b + 8, &cap, 4);
  std::memcpy(b + 12, &pid, 4);
  r.cap = cap;
  r.base = b;
  state.store(1, std::memory_order_release);
  return r;
}

inline void record(Site site, int64_t epoch, int64_t step, int64_t a,
                   int64_t b) {
  Ring& r = ring();
  if (r.base == nullptr) return;
  // relaxed-ok: seq only allots slots; the readers are post-mortem
  // (the mmap outlives the process), so no live happens-before exists
  // to preserve — each record is CRC-framed against torn writes
  uint64_t seq = r.seq.fetch_add(1, std::memory_order_relaxed) + 1;
  uint8_t* slot = r.base + kHeaderSize + (size_t)(seq % r.cap) * kRecSize;
  uint64_t ts_ns = (uint64_t)std::chrono::duration_cast<
                       std::chrono::nanoseconds>(
                       std::chrono::system_clock::now().time_since_epoch())
                       .count();
  uint8_t rec[kRecSize];
  std::memset(rec, 0, sizeof(rec));
  uint32_t magic = kRecMagic;
  uint16_t s16 = (uint16_t)site;
  uint16_t flags = 0;
  std::memcpy(rec + 0, &magic, 4);
  std::memcpy(rec + 4, &s16, 2);
  std::memcpy(rec + 6, &flags, 2);
  std::memcpy(rec + 8, &seq, 8);
  std::memcpy(rec + 16, &ts_ns, 8);
  std::memcpy(rec + 24, &epoch, 8);
  std::memcpy(rec + 32, &step, 8);
  std::memcpy(rec + 40, &a, 8);
  std::memcpy(rec + 48, &b, 8);
  uint32_t crc = crc32(rec, 56);
  std::memcpy(rec + 56, &crc, 4);
  // Invalidate the slot's old CRC first, then body, CRC last: a reader
  // (post-mortem, different process) can never validate a half-written
  // record, and a crash mid-copy leaves a CRC-failing slot — one lost
  // record, never corrupt evidence.
  std::memset(slot + 56, 0, 8);
  std::memcpy(slot, rec, 56);
  std::memcpy(slot + 56, rec + 56, 8);
}

}  // namespace bb
}  // namespace tft

#endif  // TFT_BLACKBOX_H_
