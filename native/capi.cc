// torchft_tpu native core — C ABI for Python ctypes bindings.
//
// The reference exposes its Rust core to Python via pyo3
// (/root/reference/src/lib.rs). pybind11 isn't available in this image, so
// we expose a small C ABI instead and keep the binding layer in
// torchft_tpu/_native/__init__.py. Complex values (RPC requests/responses,
// pure-function inputs) travel as wire-codec buffers (wire.h), which the
// Python side encodes/decodes with torchft_tpu/utils/wire.py.
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>

#include "coord.h"
#include "lathist.h"
#include "profiler.h"
#include "rpc.h"
#include "tsdb.h"
#include "wire.h"

using namespace tft;

namespace {

std::mutex g_mu;
int64_t g_next = 1;
std::map<int64_t, std::unique_ptr<Lighthouse>> g_lighthouses;
std::map<int64_t, std::unique_ptr<ManagerSrv>> g_managers;
std::map<int64_t, std::unique_ptr<KvStore>> g_stores;
// shared_ptr: a call may be in flight on another thread when the handle is
// freed; the last owner destroys the client.
std::map<int64_t, std::shared_ptr<RpcClient>> g_clients;

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    strncpy(err, msg.c_str(), (size_t)errlen - 1);
    err[errlen - 1] = '\0';
  }
}

void copy_str(const std::string& s, char* buf, int buflen) {
  if (buf && buflen > 0) {
    strncpy(buf, s.c_str(), (size_t)buflen - 1);
    buf[buflen - 1] = '\0';
  }
}

uint8_t* alloc_out(const std::string& s, int64_t* outlen) {
  uint8_t* p = (uint8_t*)malloc(s.size());
  if (p) memcpy(p, s.data(), s.size());
  *outlen = (int64_t)s.size();
  return p;
}

}  // namespace

extern "C" {

// ---- buffers ----
void tft_buf_free(uint8_t* p) { free(p); }

// ---- lighthouse ----
int64_t tft_lighthouse_create(const char* bind, uint64_t min_replicas,
                              uint64_t join_timeout_ms, uint64_t quorum_tick_ms,
                              uint64_t heartbeat_timeout_ms,
                              uint64_t evict_probe_ms, char* err,
                              int errlen) {
  try {
    LighthouseOpt opt;
    opt.min_replicas = min_replicas;
    opt.join_timeout_ms = join_timeout_ms;
    opt.quorum_tick_ms = quorum_tick_ms;
    opt.heartbeat_timeout_ms = heartbeat_timeout_ms;
    opt.evict_probe_ms = evict_probe_ms;
    auto lh = std::make_unique<Lighthouse>(bind, opt);
    std::lock_guard<std::mutex> g(g_mu);
    int64_t h = g_next++;
    g_lighthouses[h] = std::move(lh);
    return h;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return 0;
  }
}

void tft_lighthouse_address(int64_t h, char* buf, int buflen) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_lighthouses.find(h);
  copy_str(it != g_lighthouses.end() ? it->second->address() : "", buf, buflen);
}

void tft_lighthouse_shutdown(int64_t h) {
  std::unique_ptr<Lighthouse> lh;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_lighthouses.find(h);
    if (it == g_lighthouses.end()) return;
    lh = std::move(it->second);
    g_lighthouses.erase(it);
  }
  lh->shutdown();
}

// ---- manager ----
int64_t tft_manager_create(const char* replica_id, const char* lighthouse_addr,
                           const char* hostname, const char* bind,
                           const char* store_addr, uint64_t world_size,
                           int64_t heartbeat_interval_ms,
                           int64_t connect_timeout_ms, char* err, int errlen) {
  try {
    auto m = std::make_unique<ManagerSrv>(
        replica_id, lighthouse_addr, hostname, bind, store_addr, world_size,
        heartbeat_interval_ms, connect_timeout_ms);
    std::lock_guard<std::mutex> g(g_mu);
    int64_t h = g_next++;
    g_managers[h] = std::move(m);
    return h;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return 0;
  }
}

void tft_manager_address(int64_t h, char* buf, int buflen) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_managers.find(h);
  copy_str(it != g_managers.end() ? it->second->address() : "", buf, buflen);
}

void tft_manager_shutdown(int64_t h) {
  std::unique_ptr<ManagerSrv> m;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_managers.find(h);
    if (it == g_managers.end()) return;
    m = std::move(it->second);
    g_managers.erase(it);
  }
  m->shutdown();
}

// ---- kv store ----
int64_t tft_store_create(const char* bind, char* err, int errlen) {
  try {
    auto s = std::make_unique<KvStore>(bind);
    std::lock_guard<std::mutex> g(g_mu);
    int64_t h = g_next++;
    g_stores[h] = std::move(s);
    return h;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return 0;
  }
}

void tft_store_address(int64_t h, char* buf, int buflen) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_stores.find(h);
  copy_str(it != g_stores.end() ? it->second->address() : "", buf, buflen);
}

void tft_store_shutdown(int64_t h) {
  std::unique_ptr<KvStore> s;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_stores.find(h);
    if (it == g_stores.end()) return;
    s = std::move(it->second);
    g_stores.erase(it);
  }
  s->shutdown();
}

// ---- generic RPC client ----
// Returns handle > 0, or 0 with err set.
int64_t tft_client_create(const char* addr, int64_t connect_timeout_ms,
                          char* err, int errlen) {
  try {
    auto c = std::make_shared<RpcClient>(addr, connect_timeout_ms);
    std::lock_guard<std::mutex> g(g_mu);
    int64_t h = g_next++;
    g_clients[h] = std::move(c);
    return h;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return 0;
  }
}

// Returns the RPC status code (0 = OK). On OK, *out/*outlen hold the encoded
// response map (caller frees with tft_buf_free). On failure err holds the
// message.
int64_t tft_client_call(int64_t h, const char* method, const uint8_t* req,
                        int64_t reqlen, int64_t timeout_ms, uint8_t** out,
                        int64_t* outlen, char* err, int errlen) {
  std::shared_ptr<RpcClient> c;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_clients.find(h);
    if (it == g_clients.end()) {
      set_err(err, errlen, "bad client handle");
      return INVALID_ARGUMENT;
    }
    c = it->second;
  }
  try {
    Value v = req && reqlen > 0 ? decode(req, (size_t)reqlen) : Value::M();
    Value resp = c->call(method, std::move(v), timeout_ms);
    std::string enc = encode(resp);
    *out = alloc_out(enc, outlen);
    return OK;
  } catch (const RpcError& e) {
    set_err(err, errlen, e.what());
    return e.code;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return INTERNAL;
  }
}

void tft_client_free(int64_t h) {
  std::shared_ptr<RpcClient> c;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_clients.find(h);
    if (it == g_clients.end()) return;
    c = std::move(it->second);
    g_clients.erase(it);
  }
  // Unblock any in-flight call; the concurrent caller still holds a
  // shared_ptr, so destruction happens after its call returns.
  c->abort();
}

// ---- pure decision procedures (for unit tests, mirroring the reference's
// in-file Rust tests of quorum_compute / compute_quorum_results) ----

// state_buf encodes:
// { now: I64, participants: [{joined_ms, member}], heartbeats: [{replica_id,
//   at_ms}], prev_quorum: quorum|none,
//   opt: {min_replicas, join_timeout_ms, heartbeat_timeout_ms} }
// Response: { quorum: [member]|none, reason: str }
int64_t tft_quorum_compute(const uint8_t* state_buf, int64_t len, uint8_t** out,
                           int64_t* outlen, char* err, int errlen) {
  try {
    Value v = decode(state_buf, (size_t)len);
    LighthouseState st;
    int64_t now = v.geti("now");
    if (v.has("participants"))
      for (const auto& p : v.at("participants").list)
        st.participants[p.at("member").gets("replica_id")] = MemberDetails{
            p.geti("joined_ms"), QuorumMember::from_value(p.at("member"))};
    if (v.has("heartbeats"))
      for (const auto& hb : v.at("heartbeats").list)
        st.heartbeats[hb.gets("replica_id")] = hb.geti("at_ms");
    if (v.has("prev_quorum") && !v.at("prev_quorum").is_none())
      st.prev_quorum = Quorum::from_value(v.at("prev_quorum"));
    LighthouseOpt opt;
    if (v.has("opt")) {
      const Value& o = v.at("opt");
      opt.min_replicas = (uint64_t)o.geti("min_replicas", 1);
      opt.join_timeout_ms = (uint64_t)o.geti("join_timeout_ms", 60000);
      opt.heartbeat_timeout_ms = (uint64_t)o.geti("heartbeat_timeout_ms", 5000);
    }
    auto [met, reason] = quorum_compute(now, st, opt);
    Value resp = Value::M();
    if (met.has_value()) {
      Value l = Value::L();
      for (const auto& m : *met) l.list.push_back(m.to_value());
      resp.set("quorum", l);
    } else {
      resp.set("quorum", Value::None());
    }
    resp.set("reason", Value::S(reason));
    std::string enc = encode(resp);
    *out = alloc_out(enc, outlen);
    return OK;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return INTERNAL;
  }
}

// ---- native latency histograms (lathist.h) ----

// Snapshot every native latency histogram of THIS process as an encoded
// Value map:
//   { "<op>": { "counts": [I64 x 28], "count": I64, "sum_ns": I64 } }
// Bucket bounds are the fixed log2 grid (2^-20 .. 2^6 s + overflow) shared
// with telemetry.anatomy.LOG2_BUCKETS — identical in every process, so a
// consumer merges two snapshots by elementwise count addition, exactly.
int64_t tft_lathist_snapshot(uint8_t** out, int64_t* outlen, char* err,
                             int errlen) {
  try {
    Value resp = Value::M();
    // relaxed-ok(fn): snapshot reads of the monotonic lathist counters
    // (raw buckets merge exactly across processes; a concurrent
    // observe skews one sample at most)
    for (int op = 0; op < lathist::kNumOps; ++op) {
      const lathist::Hist& h = lathist::get((lathist::Op)op);
      Value counts = Value::L();
      for (int i = 0; i <= lathist::kNumBounds; ++i)
        counts.list.push_back(Value::I(
            (int64_t)h.counts[i].load(std::memory_order_relaxed)));
      Value one = Value::M();
      one.set("counts", counts);
      one.set("count",
              Value::I((int64_t)h.count.load(std::memory_order_relaxed)));
      one.set("sum_ns",
              Value::I((int64_t)h.sum_ns.load(std::memory_order_relaxed)));
      resp.set(lathist::op_name(op), one);
    }
    std::string enc = encode(resp);
    *out = alloc_out(enc, outlen);
    return OK;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return INTERNAL;
  }
}

void tft_lathist_reset() { lathist::reset_all(); }

// ---- time-series store (tsdb.h) ----

// Snapshot THIS process's tsdb store (the in-process lighthouse's sample
// rings) as an encoded Value map:
//   { "<replica>": { "<series>": { "samples": [[epoch, step, value]...] } } }
// Oldest-first per series — the test surface behind /timeseries.json.
int64_t tft_tsdb_snapshot(uint8_t** out, int64_t* outlen, char* err,
                          int errlen) {
  try {
    Value resp = Value::M();
    auto dump = tsdb::store().dump();
    for (const auto& [rid, series] : dump) {
      Value rv = Value::M();
      for (const auto& [name, samples] : series) {
        Value sv = Value::M();
        Value l = Value::L();
        for (const auto& s : samples) {
          Value p = Value::L();
          p.list.push_back(Value::I(s.epoch));
          p.list.push_back(Value::I(s.step));
          p.list.push_back(Value::F(s.value));
          l.list.push_back(p);
        }
        sv.set("samples", l);
        rv.set(name, sv);
      }
      resp.set(rid, rv);
    }
    std::string enc = encode(resp);
    *out = alloc_out(enc, outlen);
    return OK;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return INTERNAL;
  }
}

void tft_tsdb_reset() { tsdb::store().reset(); }

// ---- always-on sampling profiler (profiler.h) ----

// Retarget the sampling rate live (the diagnosis engine's burst window);
// 0 pauses sampling, >0 arms it (installing the SIGPROF handler and the
// sampler thread on first use).
void tft_prof_set_hz(double hz) { prof::set_hz(hz); }

// Effective rate: the env default is resolved lazily at first thread
// registration, so this also forces that resolution (the overhead-smoke
// legs read it to prove which mode they measured).
double tft_prof_hz() {
  prof::maybe_arm();
  return prof::current_hz();
}

// Flamegraph-ready collapsed stacks of every sample drained so far:
// "label;root;...;leaf count\n" per unique stack, sorted. Cumulative —
// the caller diffs two snapshots (telemetry.profiler.subtract_folded)
// for a bounded capture window.
int64_t tft_prof_snapshot(uint8_t** out, int64_t* outlen, char* err,
                          int errlen) {
  try {
    *out = alloc_out(prof::snapshot_folded(), outlen);
    return OK;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return INTERNAL;
  }
}

int64_t tft_prof_samples_total() { return (int64_t)prof::samples_total(); }

void tft_prof_reset() { prof::reset(); }

// quorum_buf encodes a Quorum value. Response: ManagerQuorumResult map.
int64_t tft_compute_quorum_results(const uint8_t* quorum_buf, int64_t len,
                                   const char* replica_id, int64_t rank,
                                   uint8_t** out, int64_t* outlen, char* err,
                                   int errlen) {
  try {
    Quorum q = Quorum::from_value(decode(quorum_buf, (size_t)len));
    ManagerQuorumResult res = compute_quorum_results(replica_id, rank, q);
    std::string enc = encode(res.to_value());
    *out = alloc_out(enc, outlen);
    return OK;
  } catch (const RpcError& e) {
    set_err(err, errlen, e.what());
    return e.code;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return INTERNAL;
  }
}

}  // extern "C"
