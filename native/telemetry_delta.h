// Delta-encoded telemetry piggyback decoder + fleet rollup fold (ISSUE 16).
//
// The Python side (torchft_tpu/telemetry/fleetdelta.py, the format owner)
// emits versioned binary blobs: dictionary-interned keys + only-changed
// leaves since the last acked version, FULL state on a fresh incarnation
// or a requested resync. This header is the lighthouse's receiving end:
//
//   * DecodeState — one incarnation chain's interning dictionary +
//     current flat {path: leaf} state + version;
//   * apply()     — parse a blob onto a DecodeState (never throws:
//     malformed or out-of-chain input returns false and flags resync,
//     answered via the quorum-reply ack);
//   * subtree_json() — rebuild the nested JSON object for a path prefix
//     (the verbatim-splice summary/anatomy strings /cluster.json serves);
//   * fold_hists()/grid_quantile() — elementwise-exact merge of the
//     piggybacked log2 histogram buckets across replicas (the grid is
//     lathist.h's: identical bounds, so the fold is count addition) and
//     the interpolated percentile read /fleet.json serves.
//
// Wire format v1 (see fleetdelta.py for the authoritative layout):
//   0xD7 | fmt=1 | flags(bit0 FULL) | 8B incarnation | varint version |
//   varint base_version | varint count | entries
//   entry: varint keyref=(id<<1)|define [varint len + UTF-8 key] |
//          type byte (0 DEL, 1 F64 LE, 2 I64 zigzag, 3 BOOL, 4 STR,
//          5 BYTES) | value
//
// Path segments are joined by 0x1f; a 0x1e-prefixed segment is a list
// index ("\x1e#" = list length) so JSON rebuild emits arrays.
//
// Concurrency: everything here is called by the Lighthouse under its
// mu_; no atomics, no locks of its own.
#pragma once

#include <array>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "lathist.h"

namespace tftdelta {

namespace lathist = tft::lathist;

constexpr uint8_t kMagic = 0xD7;
constexpr uint8_t kFmtVersion = 1;
constexpr uint8_t kFlagFull = 0x01;
constexpr char kSep = '\x1f';
constexpr char kIdx = '\x1e';
constexpr size_t kNumBuckets = lathist::kNumBounds + 1;  // 28

enum LeafType : uint8_t {
  kDel = 0,
  kF64 = 1,
  kI64 = 2,
  kBool = 3,
  kStr = 4,
  kBytes = 5,
};

struct Leaf {
  uint8_t type = kF64;
  double f = 0.0;
  int64_t i = 0;
  bool b = false;
  std::string s;  // STR and BYTES
};

// One incarnation chain's receiver state. A respawned sender has a new
// random incarnation, so it can never alias this dictionary or base —
// the kill/respawn resync guarantee is structural, not best-effort.
struct DecodeState {
  std::string inc;               // 8-byte incarnation
  uint64_t version = 0;          // version of the state held in `flat`
  std::vector<std::string> keys; // interning dictionary, id-dense
  std::map<std::string, Leaf> flat;
  bool resync = false;           // we want a FULL from this sender
  int64_t last_ms = 0;           // for per-replica chain eviction
  uint64_t blobs = 0, bytes = 0;
};

inline bool read_varint(const std::string& b, size_t& off, uint64_t* out) {
  uint64_t n = 0;
  int shift = 0;
  while (off < b.size()) {
    uint8_t byte = (uint8_t)b[off++];
    n |= (uint64_t)(byte & 0x7F) << shift;
    if (!(byte & 0x80)) {
      *out = n;
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

inline int64_t unzigzag(uint64_t n) {
  return (int64_t)(n >> 1) ^ -(int64_t)(n & 1);
}

// Apply one blob. Returns true when the state advanced; false leaves the
// state unchanged (apart from `resync`) and fills `err`. `changed`, when
// non-null, collects the applied keys (the per-step series samples the
// TSDB ingests — under delta, exactly the values that moved).
inline bool apply(DecodeState& st, const std::string& blob, std::string* err,
                  std::vector<std::string>* changed = nullptr) {
  auto fail = [&](const char* why) {
    st.resync = true;
    if (err) *err = why;
    return false;
  };
  if (blob.size() < 11 || (uint8_t)blob[0] != kMagic)
    return fail("bad magic");
  if ((uint8_t)blob[1] != kFmtVersion) return fail("format version skew");
  bool full = ((uint8_t)blob[2] & kFlagFull) != 0;
  std::string inc = blob.substr(3, 8);
  size_t off = 11;
  uint64_t version = 0, base = 0, count = 0;
  if (!read_varint(blob, off, &version) || !read_varint(blob, off, &base) ||
      !read_varint(blob, off, &count))
    return fail("truncated header");
  if (!full && (st.inc != inc || st.version != base))
    return fail("incarnation/base mismatch");
  // parse into a staging list first: a truncated entry mid-blob must not
  // leave half a delta applied (the sender's shadow assumes all-or-none)
  std::vector<std::pair<std::string, Leaf>> staged;
  std::vector<std::string> new_keys;
  size_t dict_base = full ? 0 : st.keys.size();
  for (uint64_t e = 0; e < count; e++) {
    uint64_t ref = 0;
    if (!read_varint(blob, off, &ref)) return fail("truncated keyref");
    std::string key;
    if (ref & 1) {
      uint64_t klen = 0;
      if (!read_varint(blob, off, &klen) || off + klen > blob.size())
        return fail("truncated key def");
      key = blob.substr(off, klen);
      off += klen;
      if ((ref >> 1) != dict_base + new_keys.size())
        return fail("non-dense key id");
      new_keys.push_back(key);
    } else {
      uint64_t id = ref >> 1;
      if (id < dict_base) {
        key = st.keys[id];
      } else if (id - dict_base < new_keys.size()) {
        key = new_keys[id - dict_base];
      } else {
        return fail("unknown key id");
      }
    }
    if (off >= blob.size()) return fail("truncated type");
    uint8_t type = (uint8_t)blob[off++];
    Leaf leaf;
    leaf.type = type;
    switch (type) {
      case kDel:
        break;
      case kF64: {
        if (off + 8 > blob.size()) return fail("truncated f64");
        uint64_t bits = 0;
        memcpy(&bits, blob.data() + off, 8);  // little-endian hosts only
        double d;
        memcpy(&d, &bits, 8);
        leaf.f = d;
        off += 8;
        break;
      }
      case kI64: {
        uint64_t zz = 0;
        if (!read_varint(blob, off, &zz)) return fail("truncated i64");
        leaf.i = unzigzag(zz);
        break;
      }
      case kBool: {
        if (off >= blob.size()) return fail("truncated bool");
        leaf.b = blob[off++] != 0;
        break;
      }
      case kStr:
      case kBytes: {
        uint64_t slen = 0;
        if (!read_varint(blob, off, &slen) || off + slen > blob.size())
          return fail("truncated string");
        leaf.s = blob.substr(off, slen);
        off += slen;
        break;
      }
      default:
        return fail("unknown leaf type");
    }
    staged.emplace_back(std::move(key), std::move(leaf));
  }
  // commit
  if (full) {
    st.inc = inc;
    st.keys.clear();
    st.flat.clear();
  }
  for (auto& k : new_keys) st.keys.push_back(std::move(k));
  for (auto& [key, leaf] : staged) {
    if (leaf.type == kDel)
      st.flat.erase(key);
    else
      st.flat[key] = std::move(leaf);
    if (changed) changed->push_back(key);
  }
  st.version = version;
  st.resync = false;
  st.blobs++;
  st.bytes += blob.size();
  return true;
}

// ------------------------------------------------------- JSON rebuild

inline void json_escape_into(std::ostringstream& o, const std::string& s) {
  for (unsigned char c : s) {
    if (c == '\\' || c == '"') {
      o << '\\' << c;
    } else if (c < 0x20) {
      char buf[8];
      snprintf(buf, sizeof buf, "\\u%04x", c);
      o << buf;
    } else {
      o << c;
    }
  }
}

inline void leaf_json(std::ostringstream& o, const Leaf& l) {
  switch (l.type) {
    case kF64: {
      if (!std::isfinite(l.f)) {
        o << "null";  // JSON has no inf/nan; absence-as-null, never "inf"
        break;
      }
      char buf[40];
      snprintf(buf, sizeof buf, "%.12g", l.f);
      o << buf;
      break;
    }
    case kI64:
      o << l.i;
      break;
    case kBool:
      o << (l.b ? "true" : "false");
      break;
    default:  // kStr / kBytes render as (escaped) strings
      o << '"';
      json_escape_into(o, l.s);
      o << '"';
      break;
  }
}

// Path-tree node for rebuilding nested JSON out of the flat state.
struct TreeNode {
  const Leaf* leaf = nullptr;
  std::map<std::string, TreeNode> kids;
};

inline void tree_json(std::ostringstream& o, const TreeNode& n) {
  if (n.leaf && n.kids.empty()) {
    leaf_json(o, *n.leaf);
    return;
  }
  // list detection: any 0x1e-prefixed child segment
  bool is_list = false;
  for (const auto& [seg, kid] : n.kids) {
    (void)kid;
    if (!seg.empty() && seg[0] == kIdx) {
      is_list = true;
      break;
    }
  }
  if (is_list) {
    long long len = -1;
    std::map<long long, const TreeNode*> by_idx;
    for (const auto& [seg, kid] : n.kids) {
      if (seg.empty() || seg[0] != kIdx) continue;
      if (seg == std::string(1, kIdx) + "#") {
        if (kid.leaf && kid.leaf->type == kI64) len = kid.leaf->i;
        continue;
      }
      long long i = strtoll(seg.c_str() + 1, nullptr, 10);
      by_idx[i] = &kid;
    }
    if (len < 0)
      len = by_idx.empty() ? 0 : by_idx.rbegin()->first + 1;
    o << '[';
    for (long long i = 0; i < len; i++) {
      if (i) o << ',';
      auto it = by_idx.find(i);
      if (it == by_idx.end())
        o << "null";
      else
        tree_json(o, *it->second);
    }
    o << ']';
    return;
  }
  o << '{';
  bool first = true;
  for (const auto& [seg, kid] : n.kids) {
    if (!first) o << ',';
    first = false;
    o << '"';
    json_escape_into(o, seg);
    o << "\":";
    tree_json(o, kid);
  }
  o << '}';
}

// Nested JSON object for every flat key under `prefix` (e.g. "summary");
// "{}" when the subtree is empty. The rebuilt text is what /cluster.json
// splices where the legacy path spliced the sender's verbatim JSON.
inline std::string subtree_json(const DecodeState& st,
                                const std::string& prefix) {
  std::string want = prefix + kSep;
  TreeNode root;
  bool any = false;
  for (auto it = st.flat.lower_bound(want); it != st.flat.end(); ++it) {
    const std::string& key = it->first;
    if (key.compare(0, want.size(), want) != 0) break;
    any = true;
    TreeNode* node = &root;
    size_t start = want.size();
    while (true) {
      size_t sep = key.find(kSep, start);
      std::string seg = key.substr(
          start, sep == std::string::npos ? std::string::npos : sep - start);
      node = &node->kids[seg];
      if (sep == std::string::npos) break;
      start = sep + 1;
    }
    node->leaf = &it->second;
  }
  if (!any) return "{}";
  std::ostringstream o;
  tree_json(o, root);
  return o.str();
}

// ------------------------------------------------------- fleet rollup

using HistCounts = std::array<uint64_t, kNumBuckets>;

// Fold one chain's piggybacked histogram buckets ("hist\x1f<name>\x1f<i>"
// leaves, absolute per-bucket counts) into `out[name]`. Elementwise
// addition on the shared log2 grid — EXACT, the PR 8 merge property.
inline void fold_hists(const DecodeState& st,
                       std::map<std::string, HistCounts>& out) {
  std::string want = std::string("hist") + kSep;
  for (auto it = st.flat.lower_bound(want); it != st.flat.end(); ++it) {
    const std::string& key = it->first;
    if (key.compare(0, want.size(), want) != 0) break;
    size_t sep = key.rfind(kSep);
    if (sep == std::string::npos || sep < want.size()) continue;
    std::string name = key.substr(want.size(), sep - want.size());
    long idx = strtol(key.c_str() + sep + 1, nullptr, 10);
    if (idx < 0 || (size_t)idx >= kNumBuckets) continue;
    int64_t c = 0;
    if (it->second.type == kI64)
      c = it->second.i;
    else if (it->second.type == kF64)
      c = (int64_t)it->second.f;
    if (c <= 0) continue;
    auto& h = out[name];
    h[(size_t)idx] += (uint64_t)c;
  }
}

// Interpolated quantile over folded counts — lathist::quantile's math on
// a plain array (same grid: bucket i spans (2^(i-21), 2^(i-20)] s).
inline double grid_quantile(const HistCounts& counts, double q) {
  uint64_t total = 0;
  for (uint64_t c : counts) total += c;
  if (total == 0) return 0.0;
  double target = q * (double)total;
  double acc = 0.0;
  for (size_t i = 0; i < kNumBuckets; i++) {
    double nxt = acc + (double)counts[i];
    if (nxt >= target && counts[i]) {
      double frac = (target - acc) / (double)counts[i];
      double lo = i == 0 ? 0.0 : lathist::bound_s((int)i - 1);
      double hi = i < (size_t)lathist::kNumBounds
                      ? lathist::bound_s((int)i)
                      : lathist::bound_s(lathist::kNumBounds - 1) * 2.0;
      return lo + (hi - lo) * frac;
    }
    acc = nxt;
  }
  return lathist::bound_s(lathist::kNumBounds - 1) * 2.0;
}

inline uint64_t hist_total(const HistCounts& counts) {
  uint64_t total = 0;
  for (uint64_t c : counts) total += c;
  return total;
}

inline std::string inc_hex(const std::string& inc) {
  static const char* hexd = "0123456789abcdef";
  std::string out;
  out.reserve(inc.size() * 2);
  for (unsigned char c : inc) {
    out.push_back(hexd[c >> 4]);
    out.push_back(hexd[c & 0xF]);
  }
  return out;
}

}  // namespace tftdelta
