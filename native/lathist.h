// torchft_tpu native core — latency histograms for the hot native paths.
//
// The Python registry (torchft_tpu/telemetry/registry.py) can't see inside
// the C++ plane: stripe hops, the RPC serve loop and the quorum fan-out all
// run GIL-free, so until now the native side exported counters only — no
// distributions (ISSUE 8). These histograms are the missing lens:
//
//   * fixed log2 bucket bounds (2^-20 s .. 2^6 s, one bucket per binary
//     order of magnitude, + overflow) shared with the Python side's
//     LOG2_BUCKETS — identical bounds in every process make cross-process
//     merging EXACT: merge = elementwise count addition, no re-binning;
//   * lock-free recording (one ilogb + two relaxed atomic adds), cheap
//     enough for the per-hop path;
//   * a small fixed registry (no dynamic allocation, no locks) rendered
//     by the lighthouse at /metrics (Prometheus) and /status.json, and
//     snapshot through the C ABI (tft_lathist_snapshot) so worker
//     processes surface their dp.* distributions through Python telemetry.
#pragma once

#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <sstream>
#include <string>

namespace tft {
namespace lathist {

// steady-clock nanoseconds for the recording sites (now_ms() is too
// coarse for sub-millisecond hops)
inline int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Bucket i counts observations in (2^(i-21), 2^(i-20)] seconds; the last
// slot is the overflow (> 2^6 s). 27 finite bounds: 2^-20 .. 2^6.
constexpr int kNumBounds = 27;
constexpr int kMinExp = -20;  // bound[0] = 2^-20 s (~1 us)

inline double bound_s(int i) { return std::ldexp(1.0, kMinExp + i); }

inline int bucket_index(double seconds) {
  if (!(seconds > 0)) return 0;
  // ilogb(2^-20) == -20 exactly; values in (2^(e), 2^(e+1)) report e, and
  // an exact power 2^e must land in ITS OWN bucket (le = 2^e is
  // inclusive), so shift only strictly-greater values up.
  int e = std::ilogb(seconds);
  double lo = std::ldexp(1.0, e);
  int idx = e - kMinExp + (seconds > lo ? 1 : 0);
  if (idx < 0) return 0;
  if (idx > kNumBounds) return kNumBounds;  // overflow slot
  return idx;
}

struct Hist {
  std::atomic<uint64_t> counts[kNumBounds + 1];
  std::atomic<uint64_t> sum_ns{0};
  std::atomic<uint64_t> count{0};

  void observe_s(double seconds) {
    // relaxed-ok(fn): monotonic stat counters — a reader may see a
    // torn cross-counter view (one in-flight sample of skew between
    // bucket/sum/count); scrape-side estimates, no ordering needed
    if (seconds < 0) seconds = 0;
    counts[bucket_index(seconds)].fetch_add(1, std::memory_order_relaxed);
    sum_ns.fetch_add((uint64_t)(seconds * 1e9), std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
  }

  void reset() {
    // relaxed-ok(fn): stat clear — concurrent observers may interleave
    // with the zeroing, counts stay internally valid (never negative)
    for (auto& c : counts) c.store(0, std::memory_order_relaxed);
    sum_ns.store(0, std::memory_order_relaxed);
    count.store(0, std::memory_order_relaxed);
  }
};

// The fixed op set. Names are wire-stable: the Python snapshot, the
// lighthouse render and tests all key on them.
//   dp.hop         — one stripe ring hop (TCP pump or CMA pull round)
//   dp.stripe      — one stripe's whole allreduce job (run_stripe)
//   rpc.serve      — server-side handling of one RPC frame
//   quorum.fanout  — ManagerSrv's lh.quorum call to the lighthouse
//                    (the per-step quorum fan-out the HA roadmap item
//                    needs p50/p99 for)
enum Op { kDpHop = 0, kDpStripe, kRpcServe, kQuorumFanout, kNumOps };

inline const char* op_name(int op) {
  switch (op) {
    case kDpHop: return "dp.hop";
    case kDpStripe: return "dp.stripe";
    case kRpcServe: return "rpc.serve";
    case kQuorumFanout: return "quorum.fanout";
    default: return "?";
  }
}

inline Hist& get(Op op) {
  static Hist hists[kNumOps];
  return hists[op];
}

inline void observe(Op op, double seconds) { get(op).observe_s(seconds); }

inline void reset_all() {
  for (int i = 0; i < kNumOps; ++i) get((Op)i).reset();
}

// Interpolated quantile from the cumulative bucket counts (the scrape-side
// histogram_quantile estimate; 0 when empty).
inline double quantile(const Hist& h, double q) {
  // relaxed-ok(fn): snapshot reads of monotonic counters — a
  // scrape-side estimate, not an invariant; no ordering needed
  uint64_t counts[kNumBounds + 1];
  uint64_t total = 0;
  for (int i = 0; i <= kNumBounds; ++i) {
    counts[i] = h.counts[i].load(std::memory_order_relaxed);
    total += counts[i];
  }
  if (total == 0) return 0.0;
  double target = q * (double)total;
  double acc = 0, lo = 0;
  for (int i = 0; i < kNumBounds; ++i) {
    double nxt = acc + (double)counts[i];
    if (nxt >= target && counts[i]) {
      double frac = (target - acc) / (double)counts[i];
      if (frac < 0) frac = 0;
      if (frac > 1) frac = 1;
      return lo + (bound_s(i) - lo) * frac;
    }
    acc = nxt;
    lo = bound_s(i);
  }
  return bound_s(kNumBounds - 1);  // overflow clamps to the last bound
}

// Prometheus exposition under the native torchft_ prefix (le values are
// exact powers of two; %.9g renders them round-trip-exact).
inline void render_prometheus(std::ostringstream& o) {
  // relaxed-ok(fn): snapshot reads of monotonic counters for the
  // exposition text; a concurrent observe skews one bucket at most
  o << "# TYPE torchft_latency_seconds histogram\n";
  char buf[64];
  for (int op = 0; op < kNumOps; ++op) {
    const Hist& h = get((Op)op);
    uint64_t cum = 0;
    for (int i = 0; i <= kNumBounds; ++i) {
      cum += h.counts[i].load(std::memory_order_relaxed);
      if (i < kNumBounds) {
        snprintf(buf, sizeof buf, "%.9g", bound_s(i));
        o << "torchft_latency_seconds_bucket{op=\"" << op_name(op)
          << "\",le=\"" << buf << "\"} " << cum << "\n";
      } else {
        o << "torchft_latency_seconds_bucket{op=\"" << op_name(op)
          << "\",le=\"+Inf\"} " << cum << "\n";
      }
    }
    snprintf(buf, sizeof buf, "%.9g",
             (double)h.sum_ns.load(std::memory_order_relaxed) / 1e9);
    o << "torchft_latency_seconds_sum{op=\"" << op_name(op) << "\"} " << buf
      << "\n"
      << "torchft_latency_seconds_count{op=\"" << op_name(op) << "\"} "
      << h.count.load(std::memory_order_relaxed) << "\n";
  }
}

// Compact JSON for /status.json: raw (non-cumulative) per-bucket counts so
// a consumer can merge across processes exactly, plus p50/p99 convenience.
inline void render_json(std::ostringstream& o) {
  // relaxed-ok(fn): snapshot reads of monotonic counters (raw buckets
  // merge exactly across processes; a concurrent observe skews one)
  char buf[64];
  o << "{";
  for (int op = 0; op < kNumOps; ++op) {
    const Hist& h = get((Op)op);
    if (op) o << ",";
    o << "\"" << op_name(op) << "\":{\"counts\":[";
    for (int i = 0; i <= kNumBounds; ++i) {
      if (i) o << ",";
      o << h.counts[i].load(std::memory_order_relaxed);
    }
    snprintf(buf, sizeof buf, "%.9g",
             (double)h.sum_ns.load(std::memory_order_relaxed) / 1e9);
    o << "],\"count\":" << h.count.load(std::memory_order_relaxed)
      << ",\"sum_s\":" << buf;
    snprintf(buf, sizeof buf, "%.9g", quantile(h, 0.5));
    o << ",\"p50_s\":" << buf;
    snprintf(buf, sizeof buf, "%.9g", quantile(h, 0.99));
    o << ",\"p99_s\":" << buf << "}";
  }
  o << "}";
}

}  // namespace lathist
}  // namespace tft
