#!/usr/bin/env bash
# Pre-merge gate — the checklist that used to live only as prose in
# docs/static_analysis.md, as one runnable script (ISSUE 11):
#
#   1. the static-analysis gate  (python -m torchft_tpu.analysis)
#   2. the native strict-warning build  (make -C native warn, -Werror)
#   3. the quick faultmatrix subset  (runner --quick)
#   4. the profiler-overhead smoke  (armed-at-default-Hz vs disarmed
#      headline leg, gate <=2% — ISSUE 12; the always-on claim stays a
#      measured fact, not an assumption)
#
# Exit 0 = every gate clean. Each gate runs even if an earlier one
# failed, so one invocation reports the full damage; the exit code is
# the OR of the gates. Tier-1 pytest is NOT included here — it has its
# own driver and a ~15 min budget; this script is the fast (<10 min)
# "can I even propose this diff" check.
#
# Usage:
#   scripts/premerge.sh              # all four gates
#   scripts/premerge.sh --no-matrix  # skip the faultmatrix (seconds-fast)
#   scripts/premerge.sh --no-smoke   # skip the profiler-overhead smoke
set -u -o pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

RUN_MATRIX=1
RUN_SMOKE=1
for arg in "$@"; do
  case "$arg" in
    --no-matrix) RUN_MATRIX=0 ;;
    --no-smoke) RUN_SMOKE=0 ;;
    *) echo "unknown arg: $arg (known: --no-matrix --no-smoke)" >&2; exit 2 ;;
  esac
done

rc=0
fail() { echo "premerge: GATE FAILED: $1" >&2; rc=1; }

echo "=== [1/4] static-analysis gate (python -m torchft_tpu.analysis) ==="
if ! JAX_PLATFORMS=cpu python -m torchft_tpu.analysis; then
  fail "analysis"
fi

echo "=== [2/4] native strict-warning build (make -C native warn) ==="
if ! make -C native warn; then
  fail "native warn"
fi

if [ "$RUN_MATRIX" = 1 ]; then
  echo "=== [3/4] quick faultmatrix subset (runner --quick) ==="
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.faultinject.runner --quick \
      --outdir "${TMPDIR:-/tmp}/premerge_faultmatrix"; then
    fail "faultmatrix --quick"
  fi
else
  echo "=== [3/4] faultmatrix skipped (--no-matrix) ==="
fi

if [ "$RUN_SMOKE" = 1 ]; then
  echo "=== [4/4] profiler-overhead smoke (armed vs disarmed, gate <=2%) ==="
  # a single short leg on a loaded box can swing past the gate on
  # weather (the row's own note says so) — one breach earns one retry,
  # and only a breach on BOTH runs fails the gate
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.profiler_overhead \
      --smoke; then
    echo "premerge: smoke breached once — retrying (box weather?)" >&2
    if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.profiler_overhead \
        --smoke; then
      fail "profiler-overhead smoke (breached twice)"
    fi
  fi
else
  echo "=== [4/4] profiler-overhead smoke skipped (--no-smoke) ==="
fi

if [ "$rc" = 0 ]; then
  echo "premerge: all gates clean"
fi
exit "$rc"
