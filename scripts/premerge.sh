#!/usr/bin/env bash
# Pre-merge gate — the checklist that used to live only as prose in
# docs/static_analysis.md, as one runnable script (ISSUE 11, extended by
# ISSUE 15 and ISSUE 20):
#
#   1. the static-analysis gate  (python -m torchft_tpu.analysis —
#      concurrency lint, wire/doc drift, and the clang-free native
#      concurrency lint; incrementally cached under .analysis_cache/)
#   2. the native strict-warning build  (make -C native warn, -Werror);
#      when clang-tidy is on PATH the full `make -C native tidy` gate
#      runs too instead of being silently skipped
#   3. the quick faultmatrix subset  (runner --quick) — every scenario
#      now also replays spec-conformance-clean or fails
#   4. the profiler-overhead smoke  (armed-at-default-Hz vs disarmed
#      headline leg, gate <=2% — ISSUE 12)
#   5. the telemetry-overhead smoke  (piggyback armed vs disarmed
#      headline leg, gate <=1% / TORCHFT_TELEMETRY_BUDGET_PCT —
#      ISSUE 16's self-metering budget)
#   6. the protocol verification gate (ISSUE 15/20): bounded model check
#      of the quorum/commit spec AND the HA lighthouse tier (crash at
#      every transition point, POR+symmetry reductions) + a conformance
#      replay of the quick matrix's trails
#
# Exit 0 = every gate clean. Each gate runs even if an earlier one
# failed, so one invocation reports the full damage; the exit code is
# the OR of the gates. Tier-1 pytest is NOT included here — it has its
# own driver and a ~15 min budget; this script is the fast (<10 min)
# "can I even propose this diff" check.
#
# Usage:
#   scripts/premerge.sh              # all six gates
#   scripts/premerge.sh --no-matrix  # skip the faultmatrix (seconds-fast;
#                                    # gate 6 then skips the replay leg)
#   scripts/premerge.sh --no-smoke   # skip both overhead smokes
#   scripts/premerge.sh --json       # append a machine-readable per-gate
#                                    # summary (name/status/seconds) as the
#                                    # final stdout line — skips (e.g. the
#                                    # clang-tidy exit-3 skip) are VISIBLE
#                                    # records, never silent
#
# The gate-name ids recorded by --json are drift-checked against the
# docs/static_analysis.md "Pre-merge gates" table by
# `python -m torchft_tpu.analysis` (docdrift: premerge-gate-drift).
set -u -o pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

RUN_MATRIX=1
RUN_SMOKE=1
JSON_OUT=0
for arg in "$@"; do
  case "$arg" in
    --no-matrix) RUN_MATRIX=0 ;;
    --no-smoke) RUN_SMOKE=0 ;;
    --json) JSON_OUT=1 ;;
    *) echo "unknown arg: $arg (known: --no-matrix --no-smoke --json)" >&2
       exit 2 ;;
  esac
done

rc=0
GATE_RECORDS=()
fail() { echo "premerge: GATE FAILED: $1" >&2; rc=1; }
# record_gate <name> <passed|failed|skipped> <seconds> — one record per
# gate id; the docdrift premerge-gate-drift rule greps these call sites
record_gate() {
  GATE_RECORDS+=("{\"name\":\"$1\",\"status\":\"$2\",\"seconds\":$3}")
}

echo "=== [1/6] static-analysis gate (python -m torchft_tpu.analysis) ==="
t0=$SECONDS
if JAX_PLATFORMS=cpu python -m torchft_tpu.analysis; then
  record_gate "analysis" passed $((SECONDS - t0))
else
  fail "analysis"
  record_gate "analysis" failed $((SECONDS - t0))
fi

echo "=== [2/6] native strict-warning build (make -C native warn) ==="
t0=$SECONDS
if make -C native warn; then
  record_gate "native-warn" passed $((SECONDS - t0))
else
  fail "native warn"
  record_gate "native-warn" failed $((SECONDS - t0))
fi
# the real clang-tidy gate, when the toolchain is present: exit-3
# (clang-tidy missing) stays a skip with a message AND a skipped record
# in the --json summary, but a container that HAS clang-tidy runs the
# full baseline-diffed gate — no more silently weaker checking on
# better-equipped boxes
if command -v clang-tidy >/dev/null 2>&1; then
  echo "--- clang-tidy present: running make -C native tidy"
  t0=$SECONDS
  if make -C native tidy; then
    record_gate "native-tidy" passed $((SECONDS - t0))
  else
    fail "native tidy"
    record_gate "native-tidy" failed $((SECONDS - t0))
  fi
else
  echo "--- clang-tidy not on PATH: tidy gate skipped (make warn ran)"
  record_gate "native-tidy" skipped 0
fi

MATRIX_DIR="${TMPDIR:-/tmp}/premerge_faultmatrix"
if [ "$RUN_MATRIX" = 1 ]; then
  echo "=== [3/6] quick faultmatrix subset (runner --quick) ==="
  t0=$SECONDS
  if JAX_PLATFORMS=cpu python -m torchft_tpu.faultinject.runner --quick \
      --outdir "$MATRIX_DIR"; then
    record_gate "faultmatrix-quick" passed $((SECONDS - t0))
  else
    fail "faultmatrix --quick"
    record_gate "faultmatrix-quick" failed $((SECONDS - t0))
  fi
else
  echo "=== [3/6] faultmatrix skipped (--no-matrix) ==="
  record_gate "faultmatrix-quick" skipped 0
fi

if [ "$RUN_SMOKE" = 1 ]; then
  echo "=== [4/6] profiler-overhead smoke (armed vs disarmed, gate <=2%) ==="
  # a single short leg on a loaded box can swing past the gate on
  # weather (the row's own note says so) — one breach earns one retry,
  # and only a breach on BOTH runs fails the gate
  t0=$SECONDS
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.profiler_overhead \
      --smoke; then
    echo "premerge: smoke breached once — retrying (box weather?)" >&2
    if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.profiler_overhead \
        --smoke; then
      fail "profiler-overhead smoke (breached twice)"
      record_gate "profiler-smoke" failed $((SECONDS - t0))
    else
      record_gate "profiler-smoke" passed $((SECONDS - t0))
    fi
  else
    record_gate "profiler-smoke" passed $((SECONDS - t0))
  fi
else
  echo "=== [4/6] profiler-overhead smoke skipped (--no-smoke) ==="
  record_gate "profiler-smoke" skipped 0
fi

if [ "$RUN_SMOKE" = 1 ]; then
  echo "=== [5/6] telemetry-overhead smoke (piggyback armed vs disarmed, gate <=1%) ==="
  # same weather policy as gate 4: one breach earns one retry
  t0=$SECONDS
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.telemetry_overhead \
      --smoke; then
    echo "premerge: smoke breached once — retrying (box weather?)" >&2
    if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.telemetry_overhead \
        --smoke; then
      fail "telemetry-overhead smoke (breached twice)"
      record_gate "telemetry-smoke" failed $((SECONDS - t0))
    else
      record_gate "telemetry-smoke" passed $((SECONDS - t0))
    fi
  else
    record_gate "telemetry-smoke" passed $((SECONDS - t0))
  fi
else
  echo "=== [5/6] telemetry-overhead smoke skipped (--no-smoke) ==="
  record_gate "telemetry-smoke" skipped 0
fi

echo "=== [6/6] protocol verification (model check + conformance replay) ==="
PROTO_ARGS=()
if [ "$RUN_MATRIX" = 1 ] && [ -d "$MATRIX_DIR" ]; then
  PROTO_ARGS+=(--conformance "$MATRIX_DIR")
fi
t0=$SECONDS
if JAX_PLATFORMS=cpu python -m torchft_tpu.analysis.protocol \
    ${PROTO_ARGS[@]+"${PROTO_ARGS[@]}"}; then
  record_gate "protocol" passed $((SECONDS - t0))
else
  fail "protocol verification"
  record_gate "protocol" failed $((SECONDS - t0))
fi

if [ "$rc" = 0 ]; then
  echo "premerge: all gates clean"
fi
if [ "$JSON_OUT" = 1 ]; then
  ok=$([ "$rc" = 0 ] && echo true || echo false)
  gates=$(IFS=,; echo "${GATE_RECORDS[*]}")
  echo "{\"ok\":${ok},\"gates\":[${gates}]}"
fi
exit "$rc"
