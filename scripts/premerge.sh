#!/usr/bin/env bash
# Pre-merge gate — the checklist that used to live only as prose in
# docs/static_analysis.md, as one runnable script (ISSUE 11):
#
#   1. the static-analysis gate  (python -m torchft_tpu.analysis)
#   2. the native strict-warning build  (make -C native warn, -Werror)
#   3. the quick faultmatrix subset  (runner --quick)
#
# Exit 0 = every gate clean. Each gate runs even if an earlier one
# failed, so one invocation reports the full damage; the exit code is
# the OR of the gates. Tier-1 pytest is NOT included here — it has its
# own driver and a ~15 min budget; this script is the fast (<10 min)
# "can I even propose this diff" check.
#
# Usage:
#   scripts/premerge.sh              # all three gates
#   scripts/premerge.sh --no-matrix  # skip the faultmatrix (seconds-fast)
set -u -o pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

RUN_MATRIX=1
for arg in "$@"; do
  case "$arg" in
    --no-matrix) RUN_MATRIX=0 ;;
    *) echo "unknown arg: $arg (known: --no-matrix)" >&2; exit 2 ;;
  esac
done

rc=0
fail() { echo "premerge: GATE FAILED: $1" >&2; rc=1; }

echo "=== [1/3] static-analysis gate (python -m torchft_tpu.analysis) ==="
if ! JAX_PLATFORMS=cpu python -m torchft_tpu.analysis; then
  fail "analysis"
fi

echo "=== [2/3] native strict-warning build (make -C native warn) ==="
if ! make -C native warn; then
  fail "native warn"
fi

if [ "$RUN_MATRIX" = 1 ]; then
  echo "=== [3/3] quick faultmatrix subset (runner --quick) ==="
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.faultinject.runner --quick \
      --outdir "${TMPDIR:-/tmp}/premerge_faultmatrix"; then
    fail "faultmatrix --quick"
  fi
else
  echo "=== [3/3] faultmatrix skipped (--no-matrix) ==="
fi

if [ "$rc" = 0 ]; then
  echo "premerge: all gates clean"
fi
exit "$rc"
