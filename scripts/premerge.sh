#!/usr/bin/env bash
# Pre-merge gate — the checklist that used to live only as prose in
# docs/static_analysis.md, as one runnable script (ISSUE 11, extended by
# ISSUE 15):
#
#   1. the static-analysis gate  (python -m torchft_tpu.analysis —
#      concurrency lint, wire/doc drift, and the clang-free native
#      concurrency lint)
#   2. the native strict-warning build  (make -C native warn, -Werror);
#      when clang-tidy is on PATH the full `make -C native tidy` gate
#      runs too instead of being silently skipped
#   3. the quick faultmatrix subset  (runner --quick) — every scenario
#      now also replays spec-conformance-clean or fails
#   4. the profiler-overhead smoke  (armed-at-default-Hz vs disarmed
#      headline leg, gate <=2% — ISSUE 12)
#   5. the telemetry-overhead smoke  (piggyback armed vs disarmed
#      headline leg, gate <=1% / TORCHFT_TELEMETRY_BUDGET_PCT —
#      ISSUE 16's self-metering budget)
#   6. the protocol verification gate (ISSUE 15): exhaustive bounded
#      model check of the quorum/commit spec (crash at every transition
#      point) + a conformance replay of the quick matrix's trails
#
# Exit 0 = every gate clean. Each gate runs even if an earlier one
# failed, so one invocation reports the full damage; the exit code is
# the OR of the gates. Tier-1 pytest is NOT included here — it has its
# own driver and a ~15 min budget; this script is the fast (<10 min)
# "can I even propose this diff" check.
#
# Usage:
#   scripts/premerge.sh              # all six gates
#   scripts/premerge.sh --no-matrix  # skip the faultmatrix (seconds-fast;
#                                    # gate 6 then skips the replay leg)
#   scripts/premerge.sh --no-smoke   # skip both overhead smokes
set -u -o pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

RUN_MATRIX=1
RUN_SMOKE=1
for arg in "$@"; do
  case "$arg" in
    --no-matrix) RUN_MATRIX=0 ;;
    --no-smoke) RUN_SMOKE=0 ;;
    *) echo "unknown arg: $arg (known: --no-matrix --no-smoke)" >&2; exit 2 ;;
  esac
done

rc=0
fail() { echo "premerge: GATE FAILED: $1" >&2; rc=1; }

echo "=== [1/6] static-analysis gate (python -m torchft_tpu.analysis) ==="
if ! JAX_PLATFORMS=cpu python -m torchft_tpu.analysis; then
  fail "analysis"
fi

echo "=== [2/6] native strict-warning build (make -C native warn) ==="
if ! make -C native warn; then
  fail "native warn"
fi
# the real clang-tidy gate, when the toolchain is present: exit-3
# (clang-tidy missing) stays a skip with a message, but a container
# that HAS clang-tidy runs the full baseline-diffed gate — no more
# silently weaker checking on better-equipped boxes
if command -v clang-tidy >/dev/null 2>&1; then
  echo "--- clang-tidy present: running make -C native tidy"
  if ! make -C native tidy; then
    fail "native tidy"
  fi
else
  echo "--- clang-tidy not on PATH: tidy gate skipped (make warn ran)"
fi

MATRIX_DIR="${TMPDIR:-/tmp}/premerge_faultmatrix"
if [ "$RUN_MATRIX" = 1 ]; then
  echo "=== [3/6] quick faultmatrix subset (runner --quick) ==="
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.faultinject.runner --quick \
      --outdir "$MATRIX_DIR"; then
    fail "faultmatrix --quick"
  fi
else
  echo "=== [3/6] faultmatrix skipped (--no-matrix) ==="
fi

if [ "$RUN_SMOKE" = 1 ]; then
  echo "=== [4/6] profiler-overhead smoke (armed vs disarmed, gate <=2%) ==="
  # a single short leg on a loaded box can swing past the gate on
  # weather (the row's own note says so) — one breach earns one retry,
  # and only a breach on BOTH runs fails the gate
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.profiler_overhead \
      --smoke; then
    echo "premerge: smoke breached once — retrying (box weather?)" >&2
    if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.profiler_overhead \
        --smoke; then
      fail "profiler-overhead smoke (breached twice)"
    fi
  fi
else
  echo "=== [4/6] profiler-overhead smoke skipped (--no-smoke) ==="
fi

if [ "$RUN_SMOKE" = 1 ]; then
  echo "=== [5/6] telemetry-overhead smoke (piggyback armed vs disarmed, gate <=1%) ==="
  # same weather policy as gate 4: one breach earns one retry
  if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.telemetry_overhead \
      --smoke; then
    echo "premerge: smoke breached once — retrying (box weather?)" >&2
    if ! JAX_PLATFORMS=cpu python -m torchft_tpu.benchmarks.telemetry_overhead \
        --smoke; then
      fail "telemetry-overhead smoke (breached twice)"
    fi
  fi
else
  echo "=== [5/6] telemetry-overhead smoke skipped (--no-smoke) ==="
fi

echo "=== [6/6] protocol verification (model check + conformance replay) ==="
PROTO_ARGS=()
if [ "$RUN_MATRIX" = 1 ] && [ -d "$MATRIX_DIR" ]; then
  PROTO_ARGS+=(--conformance "$MATRIX_DIR")
fi
if ! JAX_PLATFORMS=cpu python -m torchft_tpu.analysis.protocol \
    ${PROTO_ARGS[@]+"${PROTO_ARGS[@]}"}; then
  fail "protocol verification"
fi

if [ "$rc" = 0 ]; then
  echo "premerge: all gates clean"
fi
exit "$rc"
