"""Crash-durable black box + fleet postmortem tests (ISSUE 10).

The contract under test: everything written to the mmap'd ring before a
process death — including SIGKILL mid-write — is recoverable, a torn
tail is *skipped* (CRC) and never surfaces as a corrupt record, and the
postmortem merge orders multiple replicas' records causally by the
clock-sync-free (epoch, step, seq) coordinates.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import time

import pytest

from tests.conftest import scaled_timeout
from torchft_tpu.telemetry import postmortem
from torchft_tpu.telemetry.blackbox import (
    _FRAME,
    _FRAME_MAGIC,
    _HEADER_SIZE,
    BlackBox,
    read_blackbox,
    read_native_blackbox,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBlackBoxRing:
    def test_round_trip_and_order(self, tmp_path):
        path = str(tmp_path / "a.bb")
        bb = BlackBox(path)
        bb.set_context(replica_id="rep_a", step=0, quorum_epoch=1)
        bb.record("quorum_start", step=0)
        bb.record("op_issue", op="allreduce", fseq=1, plane="tcp")
        bb.set_context(step=1, quorum_epoch=2)
        bb.record("op_complete", fseq=1, status="completed")
        bb.close()

        records, meta = read_blackbox(path)
        assert meta["replica"] == "rep_a"
        assert meta["torn"] == 0
        kinds = [r["k"] for r in records]
        assert kinds == ["ctx", "quorum_start", "op_issue", "op_complete"]
        # seq strictly increasing; context coordinates stamped
        assert [r["q"] for r in records] == sorted(r["q"] for r in records)
        assert records[1]["ep"] == 1 and records[1]["st"] == 0
        assert records[3]["ep"] == 2 and records[3]["st"] == 1

    def test_wraparound_keeps_latest(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_BLACKBOX_SIZE", "4096")
        path = str(tmp_path / "w.bb")
        bb = BlackBox(path)
        for i in range(500):  # far more than a 4 KiB ring holds
            bb.record("tick", i=i)
        bb.close()
        records, meta = read_blackbox(path)
        assert records, "wraparound must not lose everything"
        ticks = [r["i"] for r in records if r["k"] == "tick"]
        # the newest record always survives, and recovered ticks are a
        # contiguous tail of the sequence (modulo the one frame torn by
        # the wrap point, which the reader skips, never corrupts)
        assert ticks[-1] == 499
        assert all(b > a for a, b in zip(ticks, ticks[1:]))
        assert len(ticks) > 10

    def test_torn_tail_skipped_never_corrupt(self, tmp_path):
        path = str(tmp_path / "t.bb")
        bb = BlackBox(path)
        bb.record("good", n=1)
        bb.record("victim", n=2)
        bb.close()
        # flip one payload byte of the LAST frame: its CRC must fail and
        # the record must vanish — not parse with a wrong field
        with open(path, "r+b") as f:
            raw = bytearray(f.read())
        off = _HEADER_SIZE
        frames = []
        while off + _FRAME.size <= len(raw):
            magic, plen, _crc = _FRAME.unpack_from(raw, off)
            if magic != _FRAME_MAGIC:
                break
            frames.append((off, plen))
            off += _FRAME.size + plen + ((-plen) % 4)
        assert len(frames) == 2
        last_off, last_len = frames[-1]
        raw[last_off + _FRAME.size + 5] ^= 0xFF
        with open(path, "wb") as f:
            f.write(raw)
        records, meta = read_blackbox(path)
        assert [r["k"] for r in records] == ["good"]
        assert meta["torn"] >= 1

    def test_sigkill_durability(self, tmp_path):
        """A writer SIGKILLed mid-stream leaves a CRC-valid box: every
        recovered record parses, sequence numbers are sane, and at least
        the records written before the marker survive."""
        box_dir = str(tmp_path)
        marker = str(tmp_path / "marker")
        code = f"""
import os
os.environ["TORCHFT_BLACKBOX_DIR"] = {box_dir!r}
from torchft_tpu.telemetry.blackbox import BLACKBOX
BLACKBOX.set_context(replica_id="kill_me", step=0, quorum_epoch=7)
i = 0
while True:
    BLACKBOX.record("spin", i=i)
    i += 1
    if i == 200:
        open({marker!r}, "w").close()
"""
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            cwd=REPO,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            deadline = time.monotonic() + scaled_timeout(60)
            while not os.path.exists(marker):
                assert proc.poll() is None, "writer died early"
                assert time.monotonic() < deadline, "writer never reached marker"
                time.sleep(0.01)
            # kill mid-write: the writer is spinning on record()
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=scaled_timeout(30))
        finally:
            if proc.poll() is None:
                proc.kill()
        boxes = [
            f for f in os.listdir(box_dir)
            if f.endswith(".bb") and not f.endswith("_native.bb")
        ]
        assert len(boxes) == 1
        records, meta = read_blackbox(os.path.join(box_dir, boxes[0]))
        assert meta["replica"] == "kill_me"
        spins = [r for r in records if r["k"] == "spin"]
        assert len(spins) >= 100, "pre-marker records must survive SIGKILL"
        # every recovered record is fully valid JSON with the stamped
        # coordinates — a torn record may be MISSING, never corrupt
        for r in spins:
            assert r["ep"] == 7 and isinstance(r["i"], int)
        assert all(
            b["q"] > a["q"] for a, b in zip(records, records[1:])
        )


class TestNativeBlackBox:
    def test_native_ring_recovers(self, tmp_path):
        """Exercise the native plane with the box armed (fresh process —
        the env is read once per process at first record) and parse the
        breadcrumbs back: rpc.serve + quorum transitions, CRC-valid."""
        box_dir = str(tmp_path)
        code = f"""
import os
os.environ["TORCHFT_BLACKBOX_DIR"] = {box_dir!r}
from datetime import timedelta
from torchft_tpu.coordination import LighthouseServer, LighthouseClient
lh = LighthouseServer(bind="[::]:0", min_replicas=1)
c = LighthouseClient(lh.address(), connect_timeout=timedelta(seconds=5))
c.heartbeat("bbtest")
c.digest("gA", epoch=1, step=1, digest="x", wait=False)
c.digest("gB", epoch=1, step=1, digest="y", wait=False)
c.close()
lh.shutdown()
"""
        subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO,
            check=True,
            timeout=scaled_timeout(120),
            capture_output=True,
        )
        boxes = [
            f for f in os.listdir(box_dir) if f.endswith("_native.bb")
        ]
        assert len(boxes) == 1
        records, meta = read_native_blackbox(os.path.join(box_dir, boxes[0]))
        assert meta["torn"] == 0
        kinds = {r["k"] for r in records}
        assert "rpc.serve" in kinds
        assert "divergence" in kinds  # the mismatched digests above
        div = [r for r in records if r["k"] == "divergence"][0]
        assert div["ep"] == 1 and div["st"] == 1
        # seq-ordered, wall-clock timestamps plausibly recent
        assert all(
            b["q"] > a["q"] for a, b in zip(records, records[1:])
        )
        assert abs(records[-1]["ts"] - time.time()) < 3600

    def test_native_record_struct_is_64_bytes(self):
        # the Python parser and native/blackbox.h must stay in lockstep
        from torchft_tpu.telemetry.blackbox import _NATIVE_REC

        assert _NATIVE_REC.size == 64
        assert struct.calcsize("<IHHQQqqqqII") == 64


class TestPostmortemMerge:
    def _two_boxes(self, tmp_path):
        a = BlackBox(str(tmp_path / "tft_bb_1.bb"))
        a.set_context(replica_id="rep_a", step=0, quorum_epoch=1)
        a.record("quorum_start", step=0)
        a.record("op_issue", op="allreduce", plane="tcp", fseq=1)
        a.set_context(step=1, quorum_epoch=2)
        a.record("op_issue", op="allreduce", plane="tcp", fseq=2)
        a.close()  # "dies" with fseq=2 in flight at epoch 2
        b = BlackBox(str(tmp_path / "tft_bb_2.bb"))
        b.set_context(replica_id="rep_b", step=0, quorum_epoch=1)
        b.record("quorum_start", step=0)
        b.set_context(step=1, quorum_epoch=2)
        b.record("peer_death", ring_rank=0, replica="rep_a", step=1)
        b.record("abort", step=1)
        b.close()

    def test_merge_ordering_and_victim(self, tmp_path):
        self._two_boxes(tmp_path)
        report = postmortem.analyze(str(tmp_path))
        # causal order: every epoch-1 record precedes every epoch-2 one,
        # regardless of which replica wrote it
        eps = [
            r["ep"] for r in report["timeline"] if r.get("ep", -1) >= 0
        ]
        assert eps == sorted(eps)
        assert report["victim"] == "rep_a"
        assert report["victim_inflight_op"]["op"] == "allreduce"
        assert report["victim_inflight_op"]["fseq"] == 2
        assert report["victim_epoch"] == 2
        assert report["first_anomaly"]["k"] == "peer_death"
        assert report["classification"] == "new-bug"

    def test_injected_classification_wins(self, tmp_path):
        self._two_boxes(tmp_path)
        # fault-plane evidence present -> the death was scheduled
        with open(tmp_path / "tft_fault_1.json", "w") as f:
            f.write(json.dumps({"site": "cma.pull", "action": "kill",
                                "pid": 1, "hit": 3}) + "\n")
        report = postmortem.analyze(str(tmp_path))
        assert report["classification"] == "injected"

    def test_environmental_classification(self, tmp_path):
        self._two_boxes(tmp_path)
        report = postmortem.analyze(
            str(tmp_path),
            log_text="worker: malloc(): invalid size (unsorted)",
        )
        assert report["classification"] == "environmental"

    def test_trail_records_merge_only_without_boxes(self, tmp_path):
        # trail-only directory: trails ARE the timeline
        with open(tmp_path / "trail0.jsonl", "w") as f:
            f.write(json.dumps({"ts": time.time(), "event": "commit",
                                "step": 0}) + "\n")
            f.write('{"torn tail')  # must be skipped, not fatal
        report = postmortem.analyze(str(tmp_path))
        assert any(
            r["k"] == "commit" and r["src"] == "trail"
            for r in report["timeline"]
        )
        assert report["trails_mirrored_by_boxes"] is False
        # with boxes present, trails are an exact mirror of the boxes'
        # event records — merging both would double-count every
        # peer_death accusation, so they are skipped
        self._two_boxes(tmp_path)
        report = postmortem.analyze(str(tmp_path))
        assert report["trails_mirrored_by_boxes"] is True
        assert not any(r["src"] == "trail" for r in report["timeline"])
        deaths = [
            r for r in report["timeline"] if r["k"] == "peer_death"
        ]
        assert len(deaths) == 1  # once, not once-per-surface

    def test_recovery_emits_event(self, tmp_path):
        from torchft_tpu import telemetry

        self._two_boxes(tmp_path)
        telemetry.EVENTS.clear()
        postmortem.analyze(str(tmp_path))
        recs = telemetry.EVENTS.recent(event="blackbox_recovered")
        assert recs and recs[-1]["boxes"] == 2

    def test_cli(self, tmp_path, capsys):
        self._two_boxes(tmp_path)
        out_json = str(tmp_path / "report.json")
        rc = postmortem.main([str(tmp_path), "--json", out_json])
        assert rc == 2  # new-bug classification is a loud exit
        text = capsys.readouterr().out
        assert "victim: rep_a" in text
        assert "in-flight at death: allreduce" in text
        with open(out_json) as f:
            assert json.load(f)["victim"] == "rep_a"


class TestEventTrailMirror:
    def test_emit_mirrors_into_blackbox(self, tmp_path, monkeypatch):
        from torchft_tpu import telemetry

        path = str(tmp_path / "m.bb")
        telemetry.BLACKBOX.configure(path)
        try:
            telemetry.emit("commit", step=42, participants=2)
        finally:
            telemetry.BLACKBOX.configure(None)
        records, _meta = read_blackbox(path)
        commits = [r for r in records if r["k"] == "commit"]
        assert commits and commits[0]["step"] == 42

    def test_flight_mirrors_into_blackbox(self, tmp_path):
        from torchft_tpu import telemetry

        path = str(tmp_path / "f.bb")
        telemetry.BLACKBOX.configure(path)
        try:
            fid = telemetry.FLIGHT.record_issue(
                "allreduce", "tcp", 128, tag=9, rank=0
            )
            telemetry.FLIGHT.record_complete(fid)
        finally:
            telemetry.BLACKBOX.configure(None)
        records, _meta = read_blackbox(path)
        kinds = [r["k"] for r in records]
        assert "op_issue" in kinds and "op_complete" in kinds
        issue = [r for r in records if r["k"] == "op_issue"][0]
        assert issue["op"] == "allreduce" and issue["fseq"] == fid

    def test_disarmed_record_is_noop(self, monkeypatch):
        # no env, no configure: record must be silent and cheap
        monkeypatch.delenv("TORCHFT_BLACKBOX_DIR", raising=False)
        bb = BlackBox()
        bb.record("anything", x=1)
        assert bb.path is None
