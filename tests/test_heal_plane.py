"""Heal plane (ISSUE 9): stripe planning, the native blob plane,
striped multi-source recv (incl. a source dying mid-heal), differential
heal serialization, the commit trail, staging-window consistency, and
the heal/compile overlap hook. See docs/heal_plane.md."""

from __future__ import annotations

import threading
import time
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu.checkpointing import delta as dm
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.serialization import (
    flatten_state,
    spec_tree_from_header,
    unflatten_state,
)
from torchft_tpu.checkpointing.stripes import (
    slice_buffers,
    stripe_ranges,
)

T = timedelta(seconds=20)


def _state(seed: int = 0):
    rng = np.random.default_rng(seed)
    return {
        "big": rng.standard_normal((512, 512)).astype(np.float32),
        "small": np.arange(37, dtype=np.int64),
        "empty": np.zeros(0, dtype=np.float32),
        "scalar": np.float32(3.25),
        "obj": {"step": seed, "note": "x"},
    }


# ---------------------------------------------------------------------------
# stripe planning
# ---------------------------------------------------------------------------


class TestStripeRanges:
    def test_covers_exactly_and_balances(self):
        total = 10_000_000 + 13
        for n in (1, 2, 3, 7):
            ranges = stripe_ranges(total, n)
            assert sum(ln for _, ln in ranges) == total
            # contiguous, ordered, non-overlapping
            pos = 0
            for off, ln in ranges:
                assert off == pos and ln > 0
                pos += ln
            # byte balance: one large leaf cannot skew a stripe — ranges
            # differ by at most the alignment quantum + remainder
            lens = [ln for _, ln in ranges]
            assert max(lens) - min(lens) <= 64 + total % 64

    def test_deterministic_and_degenerate(self):
        assert stripe_ranges(1000, 3) == stripe_ranges(1000, 3)
        assert stripe_ranges(0, 4) == []
        # tiny blob: fewer ranges than requested, still covering
        ranges = stripe_ranges(10, 8)
        assert sum(ln for _, ln in ranges) == 10

    def test_slice_buffers_round_trip_with_zero_len(self):
        bufs = [
            np.arange(100, dtype=np.uint8),
            np.zeros(0, dtype=np.uint8),
            np.arange(50, dtype=np.float32).view(np.uint8),
        ]
        sizes = [b.nbytes for b in bufs]
        total = sum(sizes)
        flat = b"".join(bytes(b) for b in bufs)
        for off, ln in stripe_ranges(total, 3) + [(0, total), (99, 150)]:
            got = b"".join(
                bytes(mv) for mv in slice_buffers(bufs, sizes, off, ln)
            )
            assert got == flat[off : off + ln], (off, ln)


# ---------------------------------------------------------------------------
# native blob plane
# ---------------------------------------------------------------------------


class TestNativeBlob:
    def test_round_trip_stale_and_unstage(self):
        from torchft_tpu import _native

        srv = _native.BlobServer()
        try:
            a = np.arange(5000, dtype=np.float32)
            z = np.zeros(0, dtype=np.uint8)
            b = np.arange(17, dtype=np.uint8)
            bufs = [a, z, b]
            srv.stage([x.ctypes.data for x in bufs],
                      [x.nbytes for x in bufs], token=7)
            total = sum(x.nbytes for x in bufs)
            dst = memoryview(bytearray(total))
            # ranges crossing buffer boundaries
            for off, ln in stripe_ranges(total, 3):
                _native.blob_fetch(
                    "localhost", srv.port, 7, off, ln, dst[off : off + ln]
                )
            assert bytes(dst) == bytes(a.view(np.uint8)) + bytes(b)
            # stale token is a loud error, never stale bytes
            with pytest.raises(ConnectionError, match="stale"):
                _native.blob_fetch("localhost", srv.port, 8, 0, 4, dst[:4])
            with pytest.raises(ConnectionError, match="range"):
                _native.blob_fetch(
                    "localhost", srv.port, 7, total - 2, 8, dst[:8]
                )
            srv.unstage()
            with pytest.raises(ConnectionError, match="stale"):
                _native.blob_fetch("localhost", srv.port, 7, 0, 4, dst[:4])
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# striped multi-source recv
# ---------------------------------------------------------------------------


@pytest.fixture
def transports():
    made = []

    def make():
        t = HTTPTransport(T, hostname="localhost")
        made.append(t)
        return t

    yield make
    for t in made:
        t.shutdown()


def _tree_equal(a, b):
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert str(ta) == str(tb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))


class TestStripedMultiSource:
    def test_two_sources_bit_identical(self, transports):
        state = _state(1)
        s1, s2, rx = transports(), transports(), transports()
        s1.send_checkpoint([1], 3, state, T)
        s2.send_checkpoint([1], 3, state, T)
        out = rx.recv_checkpoint_multi([s1.metadata(), s2.metadata()], 3, T)
        _tree_equal(out, state)
        stats = rx.last_heal_stats
        assert stats["mode"] == "striped"
        assert stats["nsources"] == 2
        # per-source throughput attribution present for every source
        for src_stats in stats["sources"].values():
            assert src_stats["bytes"] > 0 and "gb_per_sec" in src_stats
        assert {"meta_s", "recv_s", "decode_s"} <= set(stats["stages"])

    def test_divergent_source_excluded(self, transports):
        # a source staging DIFFERENT bytes (diverged LocalSGD inner
        # state) must be excluded, never mixed in
        state, other = _state(1), _state(2)
        s1, s2, rx = transports(), transports(), transports()
        s1.send_checkpoint([1], 4, state, T)
        s2.send_checkpoint([1], 4, other, T)
        out = rx.recv_checkpoint_multi([s1.metadata(), s2.metadata()], 4, T)
        _tree_equal(out, state)
        assert rx.last_heal_stats["nsources"] == 1

    def test_healed_round_trip_source_not_excluded(self, transports):
        # pickle is not canonical: a heal-round-tripped tree serializes
        # to a different HEADER than a freshly-built one — the digest
        # must be over buffer bytes so such a source still stripes
        state = _state(1)
        h, b = flatten_state(state)
        rebuilt = unflatten_state(h, b)  # the once-healed lineage
        s1, s2, rx = transports(), transports(), transports()
        s1.send_checkpoint([1], 5, state, T)
        s2.send_checkpoint([1], 5, rebuilt, T)
        out = rx.recv_checkpoint_multi([s1.metadata(), s2.metadata()], 5, T)
        _tree_equal(out, state)
        assert rx.last_heal_stats["nsources"] == 2

    def test_source_death_mid_heal_re_stripes(self, transports):
        state = _state(3)
        s1, rx = transports(), transports()
        s2 = HTTPTransport(T, hostname="localhost")
        s1.send_checkpoint([1], 6, state, T)
        s2.send_checkpoint([1], 6, state, T)
        s2.shutdown()  # dies after planning sees it — ranges must move
        out = rx.recv_checkpoint_multi([s1.metadata(), s2.metadata()], 6, T)
        _tree_equal(out, state)

    def test_header_cb_fires_with_spec_tree(self, transports):
        state = _state(4)
        s1, rx = transports(), transports()
        s1.send_checkpoint([1], 7, state, T)
        seen = []
        rx.recv_checkpoint_multi(
            [s1.metadata()], 7, T, header_cb=lambda h: seen.append(h)
        )
        assert len(seen) == 1
        spec = spec_tree_from_header(seen[0])
        assert spec["big"].shape == (512, 512)
        assert np.dtype(spec["big"].dtype) == np.float32
        assert spec["empty"].shape == (0,)
        assert spec["obj"] == {"step": 4, "note": "x"}  # obj leaves verbatim

    def test_single_source_path(self, transports):
        state = _state(5)
        s1, rx = transports(), transports()
        s1.send_checkpoint([1], 8, state, T)
        out = rx.recv_checkpoint_multi([s1.metadata()], 8, T)
        _tree_equal(out, state)


# ---------------------------------------------------------------------------
# differential heal
# ---------------------------------------------------------------------------


class TestDifferentialHeal:
    def _staged_pair(self):
        """(state@S as the healer holds it, state@S+1 with one changed
        leaf) — 'frozen' and 'empty' unchanged, 'big'/'scalar'/'obj'
        changed."""
        s0 = _state(1)
        s1 = dict(s0)
        s1["big"] = s0["big"] * 2.0
        s1["scalar"] = np.float32(4.5)
        s1["obj"] = {"step": 99, "note": "x"}
        return s0, s1

    def test_delta_ships_strictly_fewer_bytes_and_round_trips(self, transports):
        s0, s1 = self._staged_pair()
        h0, b0 = flatten_state(s0)
        srv, rx = transports(), transports()
        trail = dm.CommitTrail(horizon=4)
        srv.commit_trail = trail
        d0 = trail.record(3, b0)
        own = (b0, dm.tree_digest(d0))
        srv.send_checkpoint([1], 4, s1, T)
        out = rx.recv_checkpoint_multi(
            [srv.metadata()], 4, T, since_step=3, own=own
        )
        _tree_equal(out, s1)  # dtype/shape/zero-length preserved
        stats = rx.last_heal_stats
        assert stats["mode"] == "delta"
        full_bytes = len(h0) + sum(int(b.nbytes) for b in b0)
        # the acceptance criterion: a 1-step absence ships STRICTLY
        # fewer bytes than the full heal
        assert stats["bytes"] < full_bytes
        # only the changed array buffer travelled (big; scalar/obj are
        # non-ndarray leaves riding the header, frozen/empty are reused
        # from the healer's own buffers)
        assert stats["delta"]["changed"] == 1

    def test_digest_mismatch_falls_back_to_full(self, transports):
        s0, s1 = self._staged_pair()
        _, b0 = flatten_state(s0)
        srv, rx = transports(), transports()
        trail = dm.CommitTrail(horizon=4)
        srv.commit_trail = trail
        trail.record(3, b0)
        srv.send_checkpoint([1], 4, s1, T)
        out = rx.recv_checkpoint_multi(
            [srv.metadata()], 4, T, since_step=3, own=(b0, "0badd1635")
        )
        _tree_equal(out, s1)
        assert rx.last_heal_stats["mode"] == "striped"

    def test_trail_horizon_eviction_forces_full(self, transports):
        s0, s1 = self._staged_pair()
        _, b0 = flatten_state(s0)
        srv, rx = transports(), transports()
        trail = dm.CommitTrail(horizon=2)
        srv.commit_trail = trail
        d0 = trail.record(3, b0)
        own = (b0, dm.tree_digest(d0))
        # two more steps evict step 3 past the horizon
        trail.record(4, b0)
        trail.record(5, b0)
        assert trail.get(3) is None
        assert trail.steps() == [4, 5]
        srv.send_checkpoint([1], 6, s1, T)
        out = rx.recv_checkpoint_multi(
            [srv.metadata()], 6, T, since_step=3, own=own
        )
        _tree_equal(out, s1)
        assert rx.last_heal_stats["mode"] == "striped"

    def test_apply_delta_layout_checks(self):
        s0, _ = self._staged_pair()
        h, b = flatten_state(s0)
        with pytest.raises(ValueError, match="truncated"):
            dm.apply_delta(
                {"header": h, "changed": [0], "sizes": [8]}, b"", b
            )
        with pytest.raises(ValueError, match="out of range"):
            dm.apply_delta(
                {"header": h, "changed": [99], "sizes": [1]}, b"\0", b
            )

    def test_build_delta_refusals(self):
        s0, s1 = self._staged_pair()
        h1, b1 = flatten_state(s1)
        d1 = dm.leaf_digests(b1)
        # no trail entry
        assert dm.build_delta(h1, b1, d1, None, "x") is None
        # tree digest mismatch
        ent = {"tree": "notit", "leaves": d1, "sizes": [b.nbytes for b in b1]}
        assert dm.build_delta(h1, b1, d1, ent, "x") is None
        # leaf-count drift
        ent = {"tree": "t", "leaves": d1 + ["extra"], "sizes": []}
        assert dm.build_delta(h1, b1, d1, ent, "t") is None


# ---------------------------------------------------------------------------
# staging-window consistency (serve overlapping a commit)
# ---------------------------------------------------------------------------


class TestServingWindowConsistency:
    def test_restage_never_serves_mixed_bytes(self, transports):
        """A slow reader overlapping disallow+restage must get either the
        OLD staging in full or a loud failure — never bytes of both. The
        write lock waits readers out; the blob plane's token turns any
        post-restage fetch into a stale error."""
        state_a, state_b = _state(1), _state(2)
        srv = transports()
        rx = transports()
        srv.send_checkpoint([1], 1, state_a, T)
        total = srv._total
        meta_a = __import__("pickle").loads(
            b"".join(srv._render_stripemeta())
        )
        errors, goods = [], []

        def reader():
            dst = memoryview(bytearray(total))
            try:
                from torchft_tpu import _native

                for off, ln in stripe_ranges(total, 4):
                    _native.blob_fetch(
                        "localhost", meta_a["blob_port"], meta_a["token"],
                        off, ln, dst[off : off + ln],
                    )
                goods.append(bytes(dst))
            except ConnectionError as e:
                errors.append(str(e))

        threads = [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.005)
        srv.send_checkpoint([1], 2, state_b, T)  # disallow + restage
        for t in threads:
            t.join()
        _, bufs_a = flatten_state(state_a)
        flat_a = b"".join(bytes(np.ascontiguousarray(b).view(np.uint8))
                          for b in bufs_a)
        for g in goods:
            assert g == flat_a  # completed reads are the OLD bytes, whole
        for e in errors:
            assert "stale" in e or "recv" in e or "closed" in e

    def test_rwlock_per_acquire_timeout(self):
        from torchft_tpu.checkpointing._rwlock import RWLock

        lock = RWLock(timeout=30.0)
        lock.w_acquire()
        t0 = time.perf_counter()
        with pytest.raises(TimeoutError):
            lock.r_acquire(timeout=0.1)
        assert time.perf_counter() - t0 < 5.0  # bounded, not the default

    def test_commit_trail_thread_consistency(self):
        """Concurrent record (commit boundary) and get (a serve) must
        always observe a complete entry or none."""
        trail = dm.CommitTrail(horizon=4)
        bufs = [np.arange(64, dtype=np.uint8)]
        stop = threading.Event()
        bad = []

        def server():
            while not stop.is_set():
                for s in range(16):
                    ent = trail.get(s)
                    if ent is not None and (
                        "tree" not in ent or len(ent["leaves"]) != 1
                    ):
                        bad.append(ent)

        th = threading.Thread(target=server)
        th.start()
        for s in range(16):
            trail.record(s, bufs)
        stop.set()
        th.join()
        assert not bad
        assert len(trail.steps()) == 4  # horizon enforced throughout


# ---------------------------------------------------------------------------
# quorum plumbing + manager staging fan-out
# ---------------------------------------------------------------------------


class TestQuorumHealSources:
    def _quorum(self, steps):
        members = [
            {
                "replica_id": f"g{i}",
                "address": f"addr{i}",
                "store_address": f"store{i}",
                "step": s,
                "world_size": 1,
                "shrink_only": False,
            }
            for i, s in enumerate(steps)
        ]
        return {"quorum_id": 9, "participants": members, "created": 0}

    def test_cohort_addresses_and_heal_pending(self):
        from torchft_tpu import _native

        # g2 behind: sources = the whole max-step cohort, everyone sees
        # heal_pending
        out = _native.compute_quorum_results(self._quorum([5, 5, 3]), "g0", 0)
        assert out["heal_pending"] is True
        assert out["recover_src_addresses"] == ["addr0", "addr1"]
        out2 = _native.compute_quorum_results(self._quorum([5, 5, 3]), "g2", 0)
        assert out2["heal"] is True
        assert out2["recover_src_addresses"] == ["addr0", "addr1"]

    def test_bootstrap_single_source(self):
        from torchft_tpu import _native

        # max_step == 0: states are not yet proven identical — only the
        # bootstrap source is a sound stripe source
        out = _native.compute_quorum_results(self._quorum([0, 0, 0]), "g1", 0)
        assert out["heal_pending"] is True
        assert out["recover_src_addresses"] == ["addr0"]

    def test_no_heal_no_pending(self):
        from torchft_tpu import _native

        out = _native.compute_quorum_results(self._quorum([4, 4, 4]), "g1", 0)
        assert out["heal_pending"] is False
