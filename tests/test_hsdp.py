"""HSDP composition: FT replica groups × sharded inner mesh (fsdp_test.py /
device_mesh_test.py analogue, but with the framework's own model stack).

Two replica groups as threads, each owning a disjoint 4-device inner mesh
(dp=2 × tp=2) running the sharded transformer TrainStep; gradients cross
the elastic replica axis through the Manager. Includes a kill/heal pass for
sharded state (live checkpoint of sharded params).
"""

import dataclasses
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tests.test_integration import FailureInjector, Runner
from torchft_tpu.collectives import CollectivesTcp
from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager
from torchft_tpu.models.transformer import TransformerConfig
from torchft_tpu.parallel.ft import FTTrainer
from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
from torchft_tpu.parallel.train_step import TrainStep

# compile-heavy slow tier: excluded from the default run (pyproject addopts)
pytestmark = pytest.mark.slow

CFG = TransformerConfig(
    vocab_size=64,
    d_model=16,
    n_layers=2,
    n_heads=2,
    head_dim=8,
    d_ff=32,
    dtype=jnp.float32,
)

# inner-mesh variants: the parallelism x FT matrix. Each replica group owns
# a disjoint 4-device mesh of the given shape; "moe" exercises expert
# parallelism (top-2 capacity dispatch over ep), "sp" exercises ring
# attention across the sequence axis — both under live sharded heal.
VARIANTS: Dict[str, Any] = {
    "dp_tp": (MeshConfig(dp=2, tp=2), CFG),
    # 4 experts (2 per ep shard) keeps top-2 selection and capacity drops
    # load-bearing — with n_experts=2 every token would hit both experts
    "moe_ep": (MeshConfig(ep=2, tp=2), dataclasses.replace(CFG, n_experts=4)),
    "sp_ring": (MeshConfig(sp=2, tp=2), CFG),
}


def hsdp_train_loop(
    rank: int,
    store_addr: str,
    runner: Runner,
    total_steps: int = 3,
    backend: str = "tcp",
    variant: str = "dp_tp",
) -> Dict[str, Any]:
    devices = jax.devices()[runner.replica_id * 4 : (runner.replica_id + 1) * 4]
    mesh_cfg, cfg = VARIANTS[variant]
    mesh = make_mesh(mesh_cfg, devices=devices)
    ts = TrainStep(cfg, optax.sgd(0.05), mesh)

    if backend == "device":
        from torchft_tpu.collectives_device import CollectivesDevice

        collectives = CollectivesDevice(timeout=timedelta(seconds=10))
    else:
        collectives = CollectivesTcp(timeout=timedelta(seconds=10))

    manager = Manager(
        collectives=collectives,
        load_state_dict=None,  # wired by FTTrainer.init
        state_dict=None,
        min_replica_size=2,
        replica_id=str(runner.replica_id),
        store_addr=store_addr,
        rank=rank,
        world_size=runner.world_size,
        lighthouse_addr=runner.lighthouse_address,
        timeout=timedelta(seconds=10),
    )
    try:
        trainer = FTTrainer(manager, ts)
        trainer.init(jax.random.PRNGKey(0))

        data_rng = np.random.default_rng(3000 + runner.replica_id * 13)
        while manager.current_step() < total_steps:
            tokens = jnp.asarray(
                data_rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32
            )
            trainer.step(tokens)
            runner.failure_injector.check(rank, manager.current_step())

        return {
            "params": jax.tree_util.tree_map(np.asarray, trainer.params),
            "step": manager.current_step(),
        }
    finally:
        manager.shutdown(wait=False)


def _run(injectors, backend: str = "tcp", variant: str = "dp_tp"):
    import functools

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    try:
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [
                ex.submit(
                    Runner(
                        replica_id=i,
                        lighthouse_address=lighthouse.address(),
                        failure_injector=inj,
                        train_loop=functools.partial(
                            hsdp_train_loop, backend=backend, variant=variant
                        ),
                    ).run_replica
                )
                for i, inj in enumerate(injectors)
            ]
            return [f.result(timeout=180) for f in futs]
    finally:
        lighthouse.shutdown()


def assert_equal_params(results):
    # bit-identical, not allclose: lockstep replicas reduce and apply the
    # exact same f32 values, the reference's integ tests assert state-dict
    # equality (manager_integ_test.py:203-230) and so do we
    a, b = results[0][0]["params"], results[1][0]["params"]
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


@pytest.mark.parametrize("backend", ["tcp", "device"])
def test_hsdp_healthy(backend):
    results = _run([FailureInjector(), FailureInjector()], backend=backend)
    assert_equal_params(results)


@pytest.mark.parametrize("backend", ["tcp", "device"])
def test_hsdp_recovery_sharded_heal(backend):
    """Killed group heals its *sharded* params from the survivor."""
    results = _run(
        [FailureInjector(), FailureInjector().fail_at(0, 2)], backend=backend
    )
    assert_equal_params(results)


@pytest.mark.parametrize("variant", ["moe_ep", "sp_ring"])
def test_recovery_other_inner_meshes(variant):
    """The parallelism x FT matrix: expert-parallel MoE and ring-attention
    (sequence-parallel) inner meshes also kill/heal to bit-identical
    state — intra-group parallelism the reference doesn't have, under the
    reference's recovery bar."""
    results = _run(
        [FailureInjector(), FailureInjector().fail_at(0, 2)], variant=variant
    )
    assert_equal_params(results)
