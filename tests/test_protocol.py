"""Tests for the FT-protocol verification plane (ISSUE 15 + ISSUE 20).

Five layers, mirroring the package:

* **model checker** — the shipped gate configurations must verify clean
  under exhaustive bounded exploration (crash injected at every
  transition point), and every deliberately-broken spec variant (the
  seeded fixtures) must produce exactly its planted violation class —
  the checker is itself code under test, so both directions matter;
* **reductions** (ISSUE 20) — POR + symmetry must reproduce the PR 15
  verdicts at ≥5× fewer explored states, bitstate must mark itself
  approximate, and budget truncation must be loud, never a silent pass;
* **the HA tier** (ISSUE 20) — the four Raft-lighthouse gate configs
  verify clean within their stated state budgets, and each broken HA
  variant fixture is caught with its planted invariant + a trace, in
  both reduced and reference modes;
* **trace conformance** — each illegal-transition rule catches its
  seeded trail (the ``trail_healing_commit.jsonl`` fixture et al.) and
  passes legal lifecycles, including the SIGKILL+respawn append pattern
  real faultmatrix trails produce;
* **the trace→schedule compiler + CLI** — checker traces lower into the
  faultinject grammar deterministically (the shipped
  ``faultinject/compiled/`` descriptors are pinned regenerable), and
  ``python -m torchft_tpu.analysis.protocol`` is premerge gate [6] with
  its exit-code contract pinned here.
"""

import json
import os
import signal
import subprocess
import sys

from torchft_tpu.analysis.protocol import SpecConfig, check
from torchft_tpu.analysis.protocol.checker import (
    GATE_CONFIGS,
    HA_STATE_BUDGETS,
)
from torchft_tpu.analysis.protocol.compile import (
    compile_gate_schedules,
    compile_trace,
    sample_paths,
)
from torchft_tpu.analysis.protocol.conformance import (
    check_records,
    check_trail_file,
)

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")

# PR 15's plain-DFS explored-state counts for the legacy gate configs —
# measured by running the PR 15 checker (commit 7020015) against the
# unchanged single-lighthouse spec. The ISSUE 20 acceptance bar: the
# POR+symmetry checker reproduces these verdicts at >=5x fewer states.
PR15_STATES = {
    "sync-2g": 3082,
    "pipelined-2g": 6126,
    "divergence-fenced-2g": 14416,
    "sync-3g": 118466,
}

# fixture -> the SpecConfig knob whose healthy setting makes it clean
HA_FIXTURES = {
    "spec_split_brain_leaders.json": ("raft_single_vote", True),
    "spec_stale_leader_commit.json": ("stale_leader_fence", True),
    "spec_out_of_order_delta.json": ("ordered_deltas", True),
}


def _kinds(result):
    return sorted({v.invariant for v in result.violations})


def _load_fixture(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        doc = json.load(f)
    doc.pop("_comment", None)
    expect = doc.pop("expect_violation")
    return doc, expect


# ---------------------------------------------------------------------------
# model checker: the shipped protocol verifies clean
# ---------------------------------------------------------------------------


class TestModelChecker:
    def test_sync_2g_clean(self):
        r = check(GATE_CONFIGS["sync-2g"])
        assert r.ok, [v.render() for v in r.violations]
        # exhaustive means EXPLORED: a broken scheduler that visits 3
        # states would also report "no violations" (reduced counts —
        # the PR 15 plain-DFS bound lives in TestReductions)
        assert r.states > 100
        assert r.terminals > 0
        assert not r.truncated and not r.approximate

    def test_pipelined_2g_clean(self):
        r = check(GATE_CONFIGS["pipelined-2g"])
        assert r.ok, [v.render() for v in r.violations]
        assert r.states > 100

    def test_divergence_fenced_2g_clean(self):
        r = check(GATE_CONFIGS["divergence-fenced-2g"])
        assert r.ok, [v.render() for v in r.violations]
        assert r.states > 100

    def test_sync_3g_clean(self):
        # ~118k states under PR 15's plain DFS; symmetry over 3
        # interchangeable groups makes it tier-1-sized now
        r = check(GATE_CONFIGS["sync-3g"])
        assert r.ok and not r.truncated
        assert r.states > 100

    def test_crash_interleaved_at_every_point(self):
        """The SIGKILL-anywhere contract: with a crash budget, the
        explored transition multiset contains a crash from many distinct
        predecessor depths — spot-check by counting crash transitions."""
        from torchft_tpu.analysis.protocol.spec import (
            enabled_actions,
            init_state,
        )

        cfg = GATE_CONFIGS["sync-2g"]
        state = init_state(cfg)
        labels = [a for a, _s in enabled_actions(state, cfg)]
        assert "crash(0)" in labels and "crash(1)" in labels
        # take a non-crash step; the crash action must still be offered
        _label, nxt = next(
            (a, s) for a, s in enabled_actions(state, cfg)
            if a.startswith("join")
        )
        labels2 = [a for a, _s in enabled_actions(nxt, cfg)]
        assert "crash(0)" in labels2 and "crash(1)" in labels2


# ---------------------------------------------------------------------------
# model checker: every broken variant is caught (seeded spec fixtures)
# ---------------------------------------------------------------------------


class TestBrokenVariantsCaught:
    def test_double_commit_fixture(self):
        """The seeded split-brain spec (join barrier off) must produce
        the double-commit interleaving — and the same bounds with the
        barrier ON must not."""
        with open(os.path.join(FIXTURES, "spec_double_commit.json")) as f:
            doc = json.load(f)
        expect = doc.pop("expect_violation")
        doc.pop("_comment")
        broken = SpecConfig(**doc)
        r = check(broken)
        assert expect in _kinds(r), _kinds(r)
        # the violation comes with an executable reproduction trace
        bad = next(v for v in r.violations if v.invariant == expect)
        assert any(t.startswith("form(") for t in bad.trace)
        fixed = SpecConfig(**{**doc, "join_barrier": True})
        assert check(fixed).ok

    def test_speculation_fence_load_bearing(self):
        """PR 3: fence off -> a healer observes speculative state."""
        broken = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=3, crash_budget=1,
            respawn_budget=1, speculation=True, fence_speculation=False,
        )
        assert "I3-healer-fence" in _kinds(check(broken))
        fixed = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=3, crash_budget=1,
            respawn_budget=1, speculation=True,
        )
        assert check(fixed).ok

    def test_residual_rollback_load_bearing(self):
        """PR 6: a vetoed speculative update must roll the
        error-feedback residual back with the weights."""
        broken = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=2, crash_budget=1,
            respawn_budget=0, speculation=True, rollback_residual=False,
        )
        assert "I4-residual-rollback" in _kinds(check(broken))

    def test_divergence_fence_load_bearing(self):
        """PR 10: sentinel/fence off -> a silently-corrupt compute
        commits a second lineage."""
        broken = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=2, crash_budget=0,
            respawn_budget=0, corrupt_budget=1, fence_divergence=False,
        )
        assert "I1-unique-commit" in _kinds(check(broken))


# ---------------------------------------------------------------------------
# checker scale-up: POR + symmetry + bitstate + budgets (ISSUE 20)
# ---------------------------------------------------------------------------


class TestReductions:
    def test_legacy_verdicts_identical_at_5x_fewer_states(self):
        """The acceptance bar: all four PR 15 gate configs, identical
        (clean) verdicts, >=5x fewer explored states under the default
        POR+symmetry reductions."""
        for name, pr15 in PR15_STATES.items():
            r = check(GATE_CONFIGS[name])
            assert r.ok and not r.truncated, name
            assert r.states * 5 <= pr15, (name, r.states, pr15)

    def test_reductions_agree_with_reference_mode(self):
        """Soundness spot-check: reductions on vs off, same verdict —
        on a clean config AND on a broken one (the violation must
        survive the pruning)."""
        for name in ("sync-2g", "pipelined-2g"):
            red = check(GATE_CONFIGS[name])
            ref = check(GATE_CONFIGS[name], por=False, symmetry=False)
            assert red.ok and ref.ok, name
        doc, expect = _load_fixture("spec_double_commit.json")
        broken = SpecConfig(**doc)
        red = check(broken, max_violations=1)
        ref = check(broken, max_violations=1, por=False, symmetry=False)
        assert expect in _kinds(red) and expect in _kinds(ref)

    def test_bitstate_is_loudly_approximate(self):
        r = check(GATE_CONFIGS["sync-2g"], bitstate=True)
        assert r.approximate is True
        # and the exact default never claims to be approximate
        assert check(GATE_CONFIGS["sync-2g"]).approximate is False

    def test_budget_truncation_is_not_a_clean_verdict(self):
        r = check(GATE_CONFIGS["sync-2g"], max_states=50)
        assert r.truncated
        assert not r.ok  # a truncated run must never read as verified
        assert r.truncated_states > 0  # the unexplored frontier is counted

    def test_early_stop_on_max_violations(self):
        """``max_violations=1`` turns a broken fixture into a fast
        fail-on-first run — marked truncated, never ok."""
        doc, expect = _load_fixture("spec_stale_leader_commit.json")
        fast = check(SpecConfig(**doc), max_violations=1)
        assert len(fast.violations) == 1
        assert fast.violations[0].invariant == expect
        assert fast.truncated and not fast.ok
        full = check(SpecConfig(**doc))
        assert fast.states < full.states


# ---------------------------------------------------------------------------
# the HA tier: Raft lighthouse + membership deltas + quorum tree
# ---------------------------------------------------------------------------


class TestHaGates:
    def test_ha_gate_configs_clean_within_stated_budget(self):
        ha = {n: c for n, c in GATE_CONFIGS.items() if n.startswith("ha-")}
        assert len(ha) >= 4, sorted(ha)
        for name, cfg in ha.items():
            budget = HA_STATE_BUDGETS[name]
            r = check(cfg, max_states=budget)
            assert r.ok and not r.truncated, (
                name, r.states, [v.render() for v in r.violations],
            )
            assert r.states <= budget


class TestBrokenHaVariantsCaught:
    def test_each_fixture_caught_with_planted_class_and_trace(self):
        """Every broken HA fixture fires EXACTLY its planted invariant —
        in the reduced mode and in the reference (no-POR, no-symmetry)
        mode, with a rendered action trace either way."""
        for name in HA_FIXTURES:
            doc, expect = _load_fixture(name)
            broken = SpecConfig(**doc)
            for kwargs in ({}, {"por": False, "symmetry": False}):
                r = check(broken, max_violations=1, **kwargs)
                assert _kinds(r) == [expect], (name, kwargs, _kinds(r))
                v = r.violations[0]
                assert v.trace, (name, kwargs)
                assert expect in v.render()

    def test_fixed_twins_are_clean(self):
        """The same bounds with the protection ON must verify clean —
        each HA protection is proven load-bearing."""
        for name, (knob, healthy) in HA_FIXTURES.items():
            doc, _expect = _load_fixture(name)
            doc[knob] = healthy
            r = check(SpecConfig(**doc))
            assert r.ok, (name, [v.render() for v in r.violations])


# ---------------------------------------------------------------------------
# trace conformance
# ---------------------------------------------------------------------------


class TestConformance:
    def test_healing_commit_fixture_caught(self):
        rep = check_trail_file(
            os.path.join(FIXTURES, "trail_healing_commit.jsonl")
        )
        assert [f.rule for f in rep.findings] == ["healing-commit"]
        assert rep.findings[0].step == 4

    def test_legal_lifecycle_passes(self):
        legal = [
            {"event": "quorum_start", "step": 0},
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "commit", "step": 0},
            {"event": "quorum_start", "step": 1},
            {"event": "quorum_ready", "quorum_id": 2, "step": 1},
            {"event": "heal_begin", "step": 5},
            {"event": "heal_end", "step": 5},
            {"event": "commit", "step": 5},
            {"event": "abort", "step": 6},
            {"event": "quorum_start", "step": 6},
            {"event": "quorum_ready", "quorum_id": 3, "step": 6},
            {"event": "commit", "step": 6},
        ]
        rep = check_records(legal, "legal")
        assert rep.ok, [f.render() for f in rep.findings]

    def test_respawn_append_pattern_legal(self):
        """A respawned worker appends to the same trail: its step-0
        quorum_start resets per-process trackers, so re-healing and
        re-committing an already-seen step is legal — but the epoch
        must stay monotone across the respawn."""
        records = [
            {"event": "quorum_ready", "quorum_id": 3, "step": 0},
            {"event": "commit", "step": 0},
            {"event": "commit", "step": 1},
            # process died; respawn starts over
            {"event": "quorum_start", "step": 0},
            {"event": "quorum_ready", "quorum_id": 7, "step": 0},
            {"event": "heal_begin", "step": 1},
            {"event": "heal_end", "step": 1},
            {"event": "commit", "step": 1},
        ]
        assert check_records(records).ok
        # same pattern with a REGRESSING epoch after respawn: illegal
        bad = list(records)
        bad[4] = {"event": "quorum_ready", "quorum_id": 2, "step": 0}
        rep = check_records(bad)
        assert [f.rule for f in rep.findings] == ["epoch-regression"]

    def test_epoch_regression_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 5, "step": 0},
            {"event": "quorum_ready", "quorum_id": 4, "step": 0},
        ])
        assert [f.rule for f in rep.findings] == ["epoch-regression"]

    def test_double_commit_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "commit", "step": 2},
            {"event": "commit", "step": 2},
        ])
        assert [f.rule for f in rep.findings] == ["step-regression"]

    def test_heal_failed_then_commit_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "heal_begin", "step": 2},
            {"event": "heal_failed", "step": 2},
            {"event": "commit", "step": 2},
        ])
        assert [f.rule for f in rep.findings] == ["heal-failed-commit"]
        # ... but a commit after the NEXT quorum is the legal retry
        rep2 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "heal_begin", "step": 2},
            {"event": "heal_failed", "step": 2},
            {"event": "quorum_ready", "quorum_id": 2, "step": 0},
            {"event": "heal_begin", "step": 2},
            {"event": "heal_end", "step": 2},
            {"event": "commit", "step": 2},
        ])
        assert rep2.ok, [f.render() for f in rep2.findings]

    def test_fence_veto_bypass_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "divergence_detected", "step": 3, "fence": True},
            {"event": "commit", "step": 3},
        ])
        assert [f.rule for f in rep.findings] == ["diverged-commit"]
        # sentinel-only (fence unarmed): the commit is the documented
        # detect-don't-veto mode — legal
        rep2 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "divergence_detected", "step": 3, "fence": False},
            {"event": "commit", "step": 3},
        ])
        assert rep2.ok
        # the real fence flow (corrupt_divergence fence leg): veto ->
        # abort -> RE-QUORUM -> clean retry of the same step commits
        rep3 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "divergence_detected", "step": 4, "fence": True},
            {"event": "abort", "step": 4},
            {"event": "quorum_ready", "quorum_id": 1, "step": 4},
            {"event": "commit", "step": 4},
        ])
        assert rep3.ok, [f.render() for f in rep3.findings]

    def test_rollback_of_commit_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "commit", "step": 3},
            {"event": "commit_rollback", "step": 3},
        ])
        assert [f.rule for f in rep.findings] == ["rollback-of-commit"]
        # the legal veto pairing: abort then rollback, never committed
        rep2 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "abort", "step": 3},
            {"event": "commit_rollback", "step": 3},
        ])
        assert rep2.ok

    def test_blackbox_record_shape_accepted(self):
        """Black-box mirror records use the compact {k, st, ep} shape;
        the normalizer maps them onto the same rules."""
        rep = check_records([
            {"k": "quorum_ready", "quorum_id": 5, "st": 0},
            {"k": "quorum_ready", "quorum_id": 4, "st": 0},
        ])
        assert [f.rule for f in rep.findings] == ["epoch-regression"]


# ---------------------------------------------------------------------------
# trace -> schedule compiler (ISSUE 20 tentpole part 3)
# ---------------------------------------------------------------------------


class TestCompileTrace:
    PREFIX = ["join(0)", "join(1)", "form(r0,step=0)"]

    def test_crash_after_work_before_vote(self):
        cs = compile_trace(self.PREFIX + ["work(1)", "crash(1)"], name="t")
        assert cs.victim == 1 and cs.expect_victim_death and cs.runnable
        (rule,) = cs.victim_schedule["rules"]
        assert rule == {"site": "commit.vote", "match": "prepare",
                        "nth": 1, "action": "kill", "sig": 9}

    def test_crash_after_vote(self):
        cs = compile_trace(
            self.PREFIX + ["work(1)", "vote(1)", "crash(1)"], name="t",
        )
        (rule,) = cs.victim_schedule["rules"]
        # the vote is on the wire; the nearest hook is the NEXT collective
        assert rule["site"] == "collective.issue"
        assert rule["match"] == "allreduce" and rule["nth"] == 2

    def test_crash_before_contributing(self):
        cs = compile_trace(self.PREFIX + ["crash(0)"], name="t")
        assert cs.victim == 0
        (rule,) = cs.victim_schedule["rules"]
        assert rule["site"] == "quorum.reply" and rule["nth"] == 1

    def test_work_corrupt_arms_the_fence(self):
        cs = compile_trace(self.PREFIX + ["work_corrupt(0)"], name="t")
        (rule,) = cs.victim_schedule["rules"]
        assert rule["site"] == "collective.complete"
        assert rule["action"] == "corrupt"
        assert cs.common_env["TORCHFT_DIVERGENCE_FENCE"] == "1"
        assert not cs.expect_victim_death

    def test_heal_fail_lowers_to_survivor_serve_drop(self):
        cs = compile_trace(self.PREFIX + ["heal_fail(1)"], name="t")
        assert cs.victim_schedule is None
        (rule,) = cs.survivor_schedule["rules"]
        assert rule == {"site": "ckpt.serve", "nth": 1, "action": "drop"}
        assert cs.runnable

    def test_ha_actions_collect_as_unlowered(self):
        trace = ["lh_campaign(0,t1)", "lh_elect(0,t1)", "delta(1,v1)"]
        cs = compile_trace(trace, name="t")
        assert cs.unlowered == trace
        assert not cs.runnable  # coordinates await the Raft wiring

    def test_second_crash_of_victim_is_unlowerable(self):
        cs = compile_trace(
            self.PREFIX + ["crash(1)", "respawn(1)", "crash(1)"], name="t",
        )
        assert len(cs.victim_schedule["rules"]) == 1
        assert cs.unlowered == ["crash(1)"]

    def test_compilation_is_deterministic(self):
        trace = self.PREFIX + ["work(1)", "crash(1)"]
        a = compile_trace(trace, name="t").to_descriptor()
        b = compile_trace(trace, name="t").to_descriptor()
        assert a == b

    def test_descriptor_round_trip(self):
        from torchft_tpu.analysis.protocol.compile import CompiledSchedule

        cs = compile_trace(self.PREFIX + ["work(1)", "crash(1)"], name="t")
        doc = cs.to_descriptor()
        assert CompiledSchedule.from_descriptor(doc).to_descriptor() == doc


class TestCompiledGateSet:
    def test_sample_paths_are_crash_bearing(self):
        paths = sample_paths(GATE_CONFIGS["sync-2g"], want=8)
        assert paths
        for p in paths:
            assert any(lbl.startswith("crash(") for lbl in p)

    def test_three_distinct_death_coordinates(self):
        schedules = compile_gate_schedules()
        sites = {s.victim_schedule["rules"][0]["site"] for s in schedules}
        assert sites == {"quorum.reply", "commit.vote", "collective.issue"}
        for s in schedules:
            assert s.runnable and s.expect_victim_death and s.trace

    def test_shipped_descriptors_are_regenerable(self):
        """The checked-in faultinject/compiled/*.json set is exactly what
        the compiler produces today — descriptor drift fails here."""
        from torchft_tpu.analysis.protocol.compile import SHIPPED_DIR

        for cs in compile_gate_schedules():
            path = os.path.join(SHIPPED_DIR, f"{cs.name}.json")
            with open(path, encoding="utf-8") as f:
                assert json.load(f) == cs.to_descriptor(), path

    def test_runner_loads_shipped_set(self):
        from torchft_tpu.faultinject.runner import (
            COMPILED_DIR,
            load_compiled_scenarios,
        )

        scenarios = load_compiled_scenarios(COMPILED_DIR)
        assert len(scenarios) >= 3
        for s in scenarios:
            assert s.victim_schedule["rules"]
            assert s.expect_victim_death and not s.quick


# ---------------------------------------------------------------------------
# round trip: checker violation -> schedule -> real fire -> conformance
# ---------------------------------------------------------------------------


_ROUNDTRIP_WORKER = """\
import json, sys

# the illegal transition the model trace encodes, as a real trail --
# written BEFORE the fault loop so it survives the scheduled SIGKILL
with open(sys.argv[2], "w") as f:
    for rec in [
        {"event": "quorum_ready", "quorum_id": 1, "step": 0},
        {"event": "heal_begin", "step": 2},
        {"event": "commit", "step": 2},
    ]:
        f.write(json.dumps(rec) + "\\n")

from torchft_tpu.faultinject.core import fault_point
for _ in range(50):
    fault_point(sys.argv[1], sys.argv[3])
sys.exit(7)  # the schedule failed to kill us
"""


class TestTraceRoundTrip:
    def test_counterexample_fires_and_conformance_classifies(self, tmp_path):
        """Satellite: checker violation trace -> compiled schedule -> the
        planted site actually fires (evidence record, SIGKILL death) ->
        conformance classifies the illegal transition."""
        from torchft_tpu.analysis.protocol.compile import main as cmain
        from torchft_tpu.faultinject.core import read_evidence

        # 1. broken HA fixture -> counterexample descriptor via the CLI
        fixture = os.path.join(FIXTURES, "spec_out_of_order_delta.json")
        assert cmain(["--fixture", fixture, "--outdir", str(tmp_path)]) == 0
        desc = tmp_path / "counterexample_spec_out_of_order_delta.json"
        doc = json.loads(desc.read_text())
        assert doc["source"] == "counterexample"
        assert doc["runnable"], doc  # the crash lowered to a real site
        assert doc["unlowered"]  # the delta ops await the Raft wiring
        rule = doc["victim_schedule"]["rules"][0]
        assert rule["action"] == "kill" and rule["sig"] == 9

        # 2. replay: a worker hits the planted site until the schedule
        # kills it; the evidence record proves the site fired
        worker = tmp_path / "worker.py"
        worker.write_text(_ROUNDTRIP_WORKER)
        trail = tmp_path / "trail0.jsonl"
        evdir = tmp_path / "evidence"
        env = dict(os.environ)
        env.pop("TORCHFT_FAULT_SCHEDULE", None)
        env["TORCHFT_FAULT_SCHEDULE"] = json.dumps(doc["victim_schedule"])
        env["TORCHFT_FAULT_EVIDENCE_DIR"] = str(evdir)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, str(worker), rule["site"], str(trail),
             rule.get("match", "")],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == -signal.SIGKILL, (
            proc.returncode, proc.stdout, proc.stderr,
        )
        fired = read_evidence(str(evdir))
        assert any(
            r.get("site") == rule["site"] and r.get("action") == "kill"
            for r in fired
        ), fired

        # 3. the trail the worker left behind carries the model-level
        # illegal transition; conformance names it
        rep = check_trail_file(str(trail))
        assert [f.rule for f in rep.findings] == ["healing-commit"]


# ---------------------------------------------------------------------------
# CLI (premerge gate [6])
# ---------------------------------------------------------------------------


class TestProtocolCli:
    def test_conformance_only_exit_codes(self, tmp_path):
        clean = tmp_path / "clean"
        clean.mkdir()
        (clean / "trail0.jsonl").write_text(
            '{"event": "quorum_ready", "quorum_id": 1, "step": 0}\n'
            '{"event": "commit", "step": 0}\n'
        )
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis.protocol",
             "--skip-model", "--conformance", str(clean)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

        bad = tmp_path / "bad"
        bad.mkdir()
        (bad / "trail0.jsonl").write_text(
            '{"event": "quorum_ready", "quorum_id": 1, "step": 0}\n'
            '{"event": "heal_begin", "step": 2}\n'
            '{"event": "commit", "step": 2}\n'
        )
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis.protocol",
             "--skip-model", "--conformance", str(bad)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "healing-commit" in proc.stdout

    def test_model_check_cli_single_config(self):
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis.protocol",
             "--config", "sync-2g", "--json"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["ok"] is True
        assert doc["model"]["sync-2g"]["violations"] == []
        assert doc["model"]["sync-2g"]["states"] > 100
        assert doc["model"]["sync-2g"]["truncated"] is False
        assert doc["model"]["sync-2g"]["approximate"] is False
