"""Tests for the FT-protocol verification plane (ISSUE 15).

Three layers, mirroring the package:

* **model checker** — the shipped gate configurations must verify clean
  under exhaustive bounded exploration (crash injected at every
  transition point), and every deliberately-broken spec variant (the
  seeded fixtures) must produce exactly its planted violation class —
  the checker is itself code under test, so both directions matter;
* **trace conformance** — each illegal-transition rule catches its
  seeded trail (the ``trail_healing_commit.jsonl`` fixture et al.) and
  passes legal lifecycles, including the SIGKILL+respawn append pattern
  real faultmatrix trails produce;
* **the CLI** — ``python -m torchft_tpu.analysis.protocol`` is premerge
  gate [5]; its exit-code contract is pinned here.
"""

import json
import os
import subprocess
import sys

from torchft_tpu.analysis.protocol import SpecConfig, check
from torchft_tpu.analysis.protocol.checker import GATE_CONFIGS
from torchft_tpu.analysis.protocol.conformance import (
    check_records,
    check_trail_file,
)

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")


def _kinds(result):
    return sorted({v.invariant for v in result.violations})


# ---------------------------------------------------------------------------
# model checker: the shipped protocol verifies clean
# ---------------------------------------------------------------------------


class TestModelChecker:
    def test_sync_2g_clean(self):
        r = check(GATE_CONFIGS["sync-2g"])
        assert r.ok, [v.render() for v in r.violations]
        # exhaustive means EXPLORED: a broken scheduler that visits 3
        # states would also report "no violations"
        assert r.states > 1000
        assert r.terminals > 0

    def test_pipelined_2g_clean(self):
        r = check(GATE_CONFIGS["pipelined-2g"])
        assert r.ok, [v.render() for v in r.violations]
        assert r.states > 1000

    def test_divergence_fenced_2g_clean(self):
        r = check(GATE_CONFIGS["divergence-fenced-2g"])
        assert r.ok, [v.render() for v in r.violations]
        assert r.states > 1000

    # sync-3g (~100k states) runs in premerge gate [5], not tier-1.

    def test_crash_interleaved_at_every_point(self):
        """The SIGKILL-anywhere contract: with a crash budget, the
        explored transition multiset contains a crash from many distinct
        predecessor depths — spot-check by counting crash transitions."""
        from torchft_tpu.analysis.protocol.spec import (
            enabled_actions,
            init_state,
        )

        cfg = GATE_CONFIGS["sync-2g"]
        state = init_state(cfg)
        labels = [a for a, _s in enabled_actions(state, cfg)]
        assert "crash(0)" in labels and "crash(1)" in labels
        # take a non-crash step; the crash action must still be offered
        _label, nxt = next(
            (a, s) for a, s in enabled_actions(state, cfg)
            if a.startswith("join")
        )
        labels2 = [a for a, _s in enabled_actions(nxt, cfg)]
        assert "crash(0)" in labels2 and "crash(1)" in labels2


# ---------------------------------------------------------------------------
# model checker: every broken variant is caught (seeded spec fixtures)
# ---------------------------------------------------------------------------


class TestBrokenVariantsCaught:
    def test_double_commit_fixture(self):
        """The seeded split-brain spec (join barrier off) must produce
        the double-commit interleaving — and the same bounds with the
        barrier ON must not."""
        with open(os.path.join(FIXTURES, "spec_double_commit.json")) as f:
            doc = json.load(f)
        expect = doc.pop("expect_violation")
        doc.pop("_comment")
        broken = SpecConfig(**doc)
        r = check(broken)
        assert expect in _kinds(r), _kinds(r)
        # the violation comes with an executable reproduction trace
        bad = next(v for v in r.violations if v.invariant == expect)
        assert any(t.startswith("form(") for t in bad.trace)
        fixed = SpecConfig(**{**doc, "join_barrier": True})
        assert check(fixed).ok

    def test_speculation_fence_load_bearing(self):
        """PR 3: fence off -> a healer observes speculative state."""
        broken = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=3, crash_budget=1,
            respawn_budget=1, speculation=True, fence_speculation=False,
        )
        assert "I3-healer-fence" in _kinds(check(broken))
        fixed = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=3, crash_budget=1,
            respawn_budget=1, speculation=True,
        )
        assert check(fixed).ok

    def test_residual_rollback_load_bearing(self):
        """PR 6: a vetoed speculative update must roll the
        error-feedback residual back with the weights."""
        broken = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=2, crash_budget=1,
            respawn_budget=0, speculation=True, rollback_residual=False,
        )
        assert "I4-residual-rollback" in _kinds(check(broken))

    def test_divergence_fence_load_bearing(self):
        """PR 10: sentinel/fence off -> a silently-corrupt compute
        commits a second lineage."""
        broken = SpecConfig(
            n_replicas=2, min_replicas=1, max_rounds=2, crash_budget=0,
            respawn_budget=0, corrupt_budget=1, fence_divergence=False,
        )
        assert "I1-unique-commit" in _kinds(check(broken))


# ---------------------------------------------------------------------------
# trace conformance
# ---------------------------------------------------------------------------


class TestConformance:
    def test_healing_commit_fixture_caught(self):
        rep = check_trail_file(
            os.path.join(FIXTURES, "trail_healing_commit.jsonl")
        )
        assert [f.rule for f in rep.findings] == ["healing-commit"]
        assert rep.findings[0].step == 4

    def test_legal_lifecycle_passes(self):
        legal = [
            {"event": "quorum_start", "step": 0},
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "commit", "step": 0},
            {"event": "quorum_start", "step": 1},
            {"event": "quorum_ready", "quorum_id": 2, "step": 1},
            {"event": "heal_begin", "step": 5},
            {"event": "heal_end", "step": 5},
            {"event": "commit", "step": 5},
            {"event": "abort", "step": 6},
            {"event": "quorum_start", "step": 6},
            {"event": "quorum_ready", "quorum_id": 3, "step": 6},
            {"event": "commit", "step": 6},
        ]
        rep = check_records(legal, "legal")
        assert rep.ok, [f.render() for f in rep.findings]

    def test_respawn_append_pattern_legal(self):
        """A respawned worker appends to the same trail: its step-0
        quorum_start resets per-process trackers, so re-healing and
        re-committing an already-seen step is legal — but the epoch
        must stay monotone across the respawn."""
        records = [
            {"event": "quorum_ready", "quorum_id": 3, "step": 0},
            {"event": "commit", "step": 0},
            {"event": "commit", "step": 1},
            # process died; respawn starts over
            {"event": "quorum_start", "step": 0},
            {"event": "quorum_ready", "quorum_id": 7, "step": 0},
            {"event": "heal_begin", "step": 1},
            {"event": "heal_end", "step": 1},
            {"event": "commit", "step": 1},
        ]
        assert check_records(records).ok
        # same pattern with a REGRESSING epoch after respawn: illegal
        bad = list(records)
        bad[4] = {"event": "quorum_ready", "quorum_id": 2, "step": 0}
        rep = check_records(bad)
        assert [f.rule for f in rep.findings] == ["epoch-regression"]

    def test_epoch_regression_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 5, "step": 0},
            {"event": "quorum_ready", "quorum_id": 4, "step": 0},
        ])
        assert [f.rule for f in rep.findings] == ["epoch-regression"]

    def test_double_commit_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "commit", "step": 2},
            {"event": "commit", "step": 2},
        ])
        assert [f.rule for f in rep.findings] == ["step-regression"]

    def test_heal_failed_then_commit_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "heal_begin", "step": 2},
            {"event": "heal_failed", "step": 2},
            {"event": "commit", "step": 2},
        ])
        assert [f.rule for f in rep.findings] == ["heal-failed-commit"]
        # ... but a commit after the NEXT quorum is the legal retry
        rep2 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "heal_begin", "step": 2},
            {"event": "heal_failed", "step": 2},
            {"event": "quorum_ready", "quorum_id": 2, "step": 0},
            {"event": "heal_begin", "step": 2},
            {"event": "heal_end", "step": 2},
            {"event": "commit", "step": 2},
        ])
        assert rep2.ok, [f.render() for f in rep2.findings]

    def test_fence_veto_bypass_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "divergence_detected", "step": 3, "fence": True},
            {"event": "commit", "step": 3},
        ])
        assert [f.rule for f in rep.findings] == ["diverged-commit"]
        # sentinel-only (fence unarmed): the commit is the documented
        # detect-don't-veto mode — legal
        rep2 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "divergence_detected", "step": 3, "fence": False},
            {"event": "commit", "step": 3},
        ])
        assert rep2.ok
        # the real fence flow (corrupt_divergence fence leg): veto ->
        # abort -> RE-QUORUM -> clean retry of the same step commits
        rep3 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "divergence_detected", "step": 4, "fence": True},
            {"event": "abort", "step": 4},
            {"event": "quorum_ready", "quorum_id": 1, "step": 4},
            {"event": "commit", "step": 4},
        ])
        assert rep3.ok, [f.render() for f in rep3.findings]

    def test_rollback_of_commit_caught(self):
        rep = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "commit", "step": 3},
            {"event": "commit_rollback", "step": 3},
        ])
        assert [f.rule for f in rep.findings] == ["rollback-of-commit"]
        # the legal veto pairing: abort then rollback, never committed
        rep2 = check_records([
            {"event": "quorum_ready", "quorum_id": 1, "step": 0},
            {"event": "abort", "step": 3},
            {"event": "commit_rollback", "step": 3},
        ])
        assert rep2.ok

    def test_blackbox_record_shape_accepted(self):
        """Black-box mirror records use the compact {k, st, ep} shape;
        the normalizer maps them onto the same rules."""
        rep = check_records([
            {"k": "quorum_ready", "quorum_id": 5, "st": 0},
            {"k": "quorum_ready", "quorum_id": 4, "st": 0},
        ])
        assert [f.rule for f in rep.findings] == ["epoch-regression"]


# ---------------------------------------------------------------------------
# CLI (premerge gate [5])
# ---------------------------------------------------------------------------


class TestProtocolCli:
    def test_conformance_only_exit_codes(self, tmp_path):
        clean = tmp_path / "clean"
        clean.mkdir()
        (clean / "trail0.jsonl").write_text(
            '{"event": "quorum_ready", "quorum_id": 1, "step": 0}\n'
            '{"event": "commit", "step": 0}\n'
        )
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis.protocol",
             "--skip-model", "--conformance", str(clean)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

        bad = tmp_path / "bad"
        bad.mkdir()
        (bad / "trail0.jsonl").write_text(
            '{"event": "quorum_ready", "quorum_id": 1, "step": 0}\n'
            '{"event": "heal_begin", "step": 2}\n'
            '{"event": "commit", "step": 2}\n'
        )
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis.protocol",
             "--skip-model", "--conformance", str(bad)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "healing-commit" in proc.stdout

    def test_model_check_cli_single_config(self):
        proc = subprocess.run(
            [sys.executable, "-m", "torchft_tpu.analysis.protocol",
             "--config", "sync-2g", "--json"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["ok"] is True
        assert doc["model"]["sync-2g"]["violations"] == []
        assert doc["model"]["sync-2g"]["states"] > 1000
