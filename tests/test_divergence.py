"""Divergence sentinel tests (ISSUE 10).

Covers the three layers separately so a failure names its layer: the
lighthouse's ``lh.digest`` cohort compare (latch, abstain, fence wait,
scrape surfaces), the manager server's vote-barrier digest exchange
(fence veto through ``mgr.should_commit``), and the Python Manager's
digest production (post-reduce fold, abstain on a doomed step). The
end-to-end corrupt-then-latch proof lives in the faultmatrix
(``corrupt_divergence`` scenario).
"""

import json
import threading
import urllib.request
from datetime import timedelta
from unittest.mock import MagicMock, patch

import numpy as np
import pytest

from torchft_tpu.collectives import CollectivesDummy
from torchft_tpu.coordination import (
    LighthouseClient,
    LighthouseServer,
    ManagerClient,
    ManagerServer,
)
from torchft_tpu.manager import MANAGER_ADDR_KEY, REPLICA_ID_KEY, Manager
from torchft_tpu.store import StoreClient, StoreServer


def _get_json(addr: str, path: str):
    if "://" not in addr:
        addr = "http://" + addr
    with urllib.request.urlopen(f"{addr}{path}", timeout=5) as resp:
        return json.loads(resp.read().decode())


class TestLighthouseDigestCompare:
    def test_match_then_mismatch_latches(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            c = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            r = c.digest("gA", epoch=1, step=5, digest="aaaa")
            assert r["match"] is True and r["divergence"] is False
            r = c.digest("gB", epoch=1, step=5, digest="aaaa")
            assert r["match"] is True and r["divergence"] is False
            # same epoch, NEXT step, one perturbed digest -> latch
            c.digest("gA", epoch=1, step=6, digest="cccc")
            r = c.digest("gB", epoch=1, step=6, digest="dddd")
            assert r["match"] is False and r["divergence"] is True
            # the latch is global and sticky: a later clean round still
            # reports the fleet-level divergence flag
            r = c.digest("gA", epoch=1, step=7, digest="e")
            assert r["match"] is True and r["divergence"] is True
            c.close()
        finally:
            lh.shutdown()

    def test_abstain_never_enters_comparison(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            c = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            # one group aborts its step (abstain marker), one commits:
            # no divergence — only committing states must agree
            c.digest("gA", epoch=2, step=1, digest="-")
            r = c.digest("gB", epoch=2, step=1, digest="real")
            assert r["match"] is True and r["divergence"] is False
            assert r["reports"] == 2  # the abstain still completed the round
            c.close()
        finally:
            lh.shutdown()

    def test_fence_wait_blocks_until_cohort(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            out = {}

            def report(name, digest):
                c = LighthouseClient(
                    lh.address(), connect_timeout=timedelta(seconds=5)
                )
                out[name] = c.digest(
                    name, epoch=3, step=1, digest=digest,
                    wait=True, cohort=2, timeout=timedelta(seconds=20),
                )
                c.close()

            t = threading.Thread(target=report, args=("gA", "x"))
            t.start()
            import time

            time.sleep(0.2)
            assert "gA" not in out, "fence wait returned before the cohort"
            report("gB", "y")
            t.join(timeout=20)
            assert out["gA"]["match"] is False
            assert out["gB"]["match"] is False
        finally:
            lh.shutdown()

    def test_scrape_surfaces(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            addr = lh.address()
            c = LighthouseClient(addr, connect_timeout=timedelta(seconds=5))
            c.digest("gA", epoch=4, step=1, digest="p")
            c.digest("gB", epoch=4, step=1, digest="q")
            c.close()
            status = _get_json(addr, "/status.json")
            assert status["divergence_detected"] is True
            assert status["divergence_total"] == 1
            cluster = _get_json(addr, "/cluster.json")
            assert cluster["divergence_detected"] is True
            assert cluster["divergence_total"] == 1
            with urllib.request.urlopen(f"{addr}/metrics", timeout=5) as r:
                text = r.read().decode()
            assert "torchft_divergence_total 1" in text
            assert "torchft_divergence_detected 1" in text
        finally:
            lh.shutdown()

    def test_missing_fields_rejected(self):
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            c = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            with pytest.raises(RuntimeError):
                c.digest("", epoch=0, step=0, digest="x")
            c.close()
        finally:
            lh.shutdown()


class TestManagerSrvFence:
    def _setup(self):
        lh = LighthouseServer(
            bind="[::]:0", min_replicas=1, join_timeout_ms=100
        )
        mgr = ManagerServer(
            replica_id="rep_0", lighthouse_addr=lh.address(),
            hostname="localhost", bind="[::]:0", store_addr="s",
            world_size=1,
        )
        client = ManagerClient(
            mgr.address(), connect_timeout=timedelta(seconds=10)
        )
        # form the quorum once so the fence's cohort (= quorum size, 1)
        # is defined
        client._quorum(
            rank=0, step=0, checkpoint_metadata="m",
            shrink_only=False, timeout=timedelta(seconds=10),
        )
        return lh, mgr, client

    def test_clean_digest_commits(self):
        lh, mgr, client = self._setup()
        try:
            decision = client.should_commit(
                0, 0, True, timeout=timedelta(seconds=10),
                digest="d0", epoch=1, fence=True,
            )
            assert decision is True
            assert client.last_divergence is False
        finally:
            client.close()
            mgr.shutdown()
            lh.shutdown()

    def test_fence_vetoes_on_mismatch(self):
        lh, mgr, client = self._setup()
        try:
            # a conflicting report lands in the same (epoch, step) round
            # before the vote (the "other group" in miniature)
            lhc = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            lhc.digest("rep_other", epoch=1, step=1, digest="other")
            lhc.close()
            decision = client.should_commit(
                0, 1, True, timeout=timedelta(seconds=10),
                digest="mine", epoch=1, fence=True,
            )
            # every rank voted True, but the lighthouse compare
            # disagreed: the fence turns the commit into an abort and
            # the reply carries the divergence flag
            assert decision is False
            assert client.last_divergence is True
        finally:
            client.close()
            mgr.shutdown()
            lh.shutdown()

    def test_sentinel_without_fence_reports_but_commits(self):
        lh, mgr, client = self._setup()
        try:
            lhc = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            lhc.digest("rep_other", epoch=1, step=2, digest="other")
            lhc.close()
            decision = client.should_commit(
                0, 2, True, timeout=timedelta(seconds=10),
                digest="mine", epoch=1, fence=False,
            )
            assert decision is True  # detection-only mode never vetoes
            assert client.last_divergence is True
        finally:
            client.close()
            mgr.shutdown()
            lh.shutdown()


class TestManagerSentinel:
    """Python Manager side: digest production + abstain, with a mocked
    coordination client (the real RPC surface is covered above)."""

    def _manager(self, store_server, monkeypatch, fence=False):
        monkeypatch.setenv("TORCHFT_DIVERGENCE_SENTINEL", "1")
        if fence:
            monkeypatch.setenv("TORCHFT_DIVERGENCE_FENCE", "1")
        store = StoreClient(store_server.address())
        store.set(MANAGER_ADDR_KEY, "dummy")
        store.set(REPLICA_ID_KEY, "dummy_id")
        patcher = patch(
            "torchft_tpu.manager.ManagerClient", autospec=True
        )
        patcher.start()
        transport = MagicMock()
        transport.metadata.return_value = "meta"
        manager = Manager(
            collectives=CollectivesDummy(rank=0, world_size=1),
            load_state_dict=lambda s: None,
            state_dict=lambda: {"w": 1},
            min_replica_size=2,
            rank=1,
            world_size=2,
            store_addr=store_server.address(),
            checkpoint_transport=transport,
            timeout=timedelta(seconds=10),
        )
        return manager, patcher

    @staticmethod
    def _quorum_result():
        from torchft_tpu.coordination import QuorumResult

        q = QuorumResult()
        q.quorum_id = 9
        q.replica_rank = 1
        q.replica_world_size = 2
        q.max_rank = 1
        q.max_world_size = 2
        q.max_step = 0
        q.store_address = "store/prefix"
        return q

    def test_digest_flows_into_vote(self, monkeypatch):
        store_server = StoreServer()
        manager, patcher = self._manager(store_server, monkeypatch)
        try:
            manager._client._quorum.return_value = self._quorum_result()
            manager._client.should_commit.return_value = True
            manager.start_quorum()
            t = np.array([2.0, 4.0], dtype=np.float32)
            manager.allreduce(t).wait()
            assert manager.should_commit()
            kwargs = manager._client.should_commit.call_args.kwargs
            digest = kwargs["digest"]
            assert isinstance(digest, str) and digest != "-"
            assert kwargs["epoch"] == 9
            assert kwargs["fence"] is False
            # deterministic: the same reduced bytes fold to the same
            # digest (this equality IS the cross-group invariant)
            from torchft_tpu.checkpointing import delta

            expected = delta.tree_digest(
                [delta.tree_digest(delta.leaf_digests([t]))]
            )
            assert digest == expected
        finally:
            manager.shutdown(wait=False)
            patcher.stop()
            store_server.shutdown()

    def test_doomed_step_abstains(self, monkeypatch):
        store_server = StoreServer()
        manager, patcher = self._manager(store_server, monkeypatch)
        try:
            manager._client._quorum.return_value = self._quorum_result()
            manager._client.should_commit.return_value = False
            manager.start_quorum()
            t = np.array([1.0], dtype=np.float32)
            manager.allreduce(t).wait()
            manager.report_error(RuntimeError("boom"))
            assert manager.should_commit() is False
            kwargs = manager._client.should_commit.call_args.kwargs
            assert kwargs["digest"] == "-"
        finally:
            manager.shutdown(wait=False)
            patcher.stop()
            store_server.shutdown()

    def test_fence_implies_sentinel_and_flag(self, monkeypatch):
        store_server = StoreServer()
        manager, patcher = self._manager(
            store_server, monkeypatch, fence=True
        )
        try:
            assert manager._divergence_sentinel is True
            manager._client._quorum.return_value = self._quorum_result()
            manager._client.should_commit.return_value = True
            manager.start_quorum()
            t = np.array([1.0], dtype=np.float32)
            manager.allreduce(t).wait()
            assert manager.should_commit()
            assert (
                manager._client.should_commit.call_args.kwargs["fence"]
                is True
            )
        finally:
            manager.shutdown(wait=False)
            patcher.stop()
            store_server.shutdown()

    def test_sentinel_off_sends_no_digest(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_DIVERGENCE_SENTINEL", raising=False)
        monkeypatch.delenv("TORCHFT_DIVERGENCE_FENCE", raising=False)
        store_server = StoreServer()
        store = StoreClient(store_server.address())
        store.set(MANAGER_ADDR_KEY, "dummy")
        store.set(REPLICA_ID_KEY, "dummy_id")
        patcher = patch(
            "torchft_tpu.manager.ManagerClient", autospec=True
        )
        patcher.start()
        transport = MagicMock()
        transport.metadata.return_value = "meta"
        manager = Manager(
            collectives=CollectivesDummy(rank=0, world_size=1),
            load_state_dict=lambda s: None,
            state_dict=lambda: {"w": 1},
            min_replica_size=2,
            rank=1,
            world_size=2,
            store_addr=store_server.address(),
            checkpoint_transport=transport,
            timeout=timedelta(seconds=10),
        )
        try:
            manager._client._quorum.return_value = self._quorum_result()
            manager._client.should_commit.return_value = True
            manager.start_quorum()
            assert manager.should_commit()
            assert (
                manager._client.should_commit.call_args.kwargs["digest"]
                is None
            )
        finally:
            manager.shutdown(wait=False)
            patcher.stop()
            store_server.shutdown()

    def test_divergence_reply_emits_once(self, monkeypatch):
        from torchft_tpu import telemetry

        store_server = StoreServer()
        manager, patcher = self._manager(store_server, monkeypatch)
        try:
            manager._client._quorum.return_value = self._quorum_result()
            manager._client.should_commit.return_value = True
            manager._client.last_divergence = True
            telemetry.EVENTS.clear()
            before = telemetry.DIVERGENCE_TOTAL.value
            manager.start_quorum()
            np_t = np.array([1.0], dtype=np.float32)
            manager.allreduce(np_t).wait()
            manager.should_commit()
            manager.start_quorum()
            manager.allreduce(np_t).wait()
            manager.should_commit()
            events = telemetry.EVENTS.recent(event="divergence_detected")
            assert len(events) == 1  # latched once, not per step
            assert telemetry.DIVERGENCE_TOTAL.value == before + 1
        finally:
            manager.shutdown(wait=False)
            patcher.stop()
            store_server.shutdown()
