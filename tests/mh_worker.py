"""Worker process for tests/test_multihost.py: one rank of a replica group
whose inner mesh spans 2 processes (multi-controller JAX on CPU).

argv: gid rank world coordinator store_addr lighthouse_addr out_path
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    gid, rank, world = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    coordinator, store_addr, lighthouse_addr, out_path = sys.argv[4:8]

    from torchft_tpu.parallel.multihost import global_mesh, initialize_group

    # before any backend use: joins the group's jax runtime
    initialize_group(coordinator, world, rank)
    assert len(jax.devices()) == 2 * world, jax.devices()

    from datetime import timedelta

    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.manager import Manager
    from torchft_tpu.models.transformer import TransformerConfig
    from torchft_tpu.parallel.ft import FTTrainer
    from torchft_tpu.parallel.mesh import MeshConfig
    from torchft_tpu.parallel.train_step import TrainStep

    cfg = TransformerConfig(
        vocab_size=64,
        d_model=16,
        n_layers=2,
        n_heads=2,
        head_dim=8,
        d_ff=32,
        dtype=jnp.float32,
    )
    # dp spans the two processes, tp is intra-process: the jitted step's
    # collectives cross the process boundary
    mesh = global_mesh(MeshConfig(dp=2, tp=2))
    ts = TrainStep(cfg, optax.sgd(0.05), mesh)

    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=15)),
        load_state_dict=None,  # wired by FTTrainer.init
        state_dict=None,
        min_replica_size=2,
        replica_id=f"mh{gid}",
        store_addr=store_addr,
        rank=rank,
        world_size=world,
        lighthouse_addr=lighthouse_addr,
        timeout=timedelta(seconds=15),
    )
    try:
        trainer = FTTrainer(manager, ts)
        trainer.init(jax.random.PRNGKey(0))

        data_rng = np.random.default_rng(500 + gid)
        while manager.current_step() < 3:
            tokens = jnp.asarray(
                data_rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32
            )
            trainer.step(tokens)

        total = jax.jit(
            lambda p: sum(
                jnp.sum(l.astype(jnp.float64))
                for l in jax.tree_util.tree_leaves(p)
            )
        )(trainer.params)
        checksum = float(total)
        if rank == 0:
            with open(out_path, "w") as f:
                f.write(f"{manager.current_step()} {checksum:.10f}\n")
    finally:
        manager.shutdown(wait=False)


if __name__ == "__main__":
    main()
