"""Launcher supervisor tests (torchx.py analogue coverage)."""

import os
import sys

import pytest

from torchft_tpu.launcher import launch


def test_clean_run(tmp_path):
    code = launch(
        [sys.executable, "-c", "import sys; sys.exit(0)"],
        num_groups=2,
        nproc=1,
        lighthouse_addr="localhost:1",  # unused by the trivial cmd
    )
    assert code == 0


def test_restart_on_failure(tmp_path):
    # first run of group 0 fails (marker absent), restart succeeds
    marker = tmp_path / "marker"
    script = (
        "import os, sys\n"
        f"m = {str(marker)!r} + os.environ['REPLICA_GROUP_ID']\n"
        "if os.environ['REPLICA_GROUP_ID'] == '0' and not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.exit(3)\n"
        "sys.exit(0)\n"
    )
    code = launch(
        [sys.executable, "-c", script],
        num_groups=2,
        nproc=1,
        lighthouse_addr="localhost:1",
        max_restarts=2,
    )
    assert code == 0
    assert (tmp_path / "marker0").exists()


def test_restart_exhaustion():
    code = launch(
        [sys.executable, "-c", "import sys; sys.exit(2)"],
        num_groups=1,
        nproc=1,
        lighthouse_addr="localhost:1",
        max_restarts=1,
    )
    assert code == 1


# ---------------------------------------------------------------------------
# Kubernetes artifact (reference torchx.py:11-76 analogue)
# ---------------------------------------------------------------------------


def test_emit_k8s_manifests():
    yaml = pytest.importorskip("yaml")

    from torchft_tpu.k8s import (
        COORD_PORT,
        LIGHTHOUSE_PORT,
        STORE_PORT,
        emit_manifests,
    )

    text = emit_manifests(
        ["python", "examples/train_hsdp.py"],
        name="job",
        image="gcr.io/p/i:v1",
        num_groups=3,
        nproc=4,
        min_replicas=2,
        max_restarts=5,
        tpu_accelerator="tpu-v5-lite-podslice",
        tpu_topology="2x4",
    )
    docs = list(yaml.safe_load_all(text))
    kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
    assert ("Deployment", "job-lighthouse") in kinds
    assert ("Service", "job-lighthouse") in kinds
    for gid in range(3):
        assert ("Job", f"job-g{gid}") in kinds
        assert ("Service", f"job-g{gid}") in kinds

    lh = next(d for d in docs if d["kind"] == "Deployment")
    lh_args = lh["spec"]["template"]["spec"]["containers"][0]["args"]
    assert "--min_replicas" in lh_args and "2" in lh_args  # min_replicas wired

    job = next(
        d for d in docs if d["kind"] == "Job" and d["metadata"]["name"] == "job-g1"
    )
    spec = job["spec"]
    assert spec["completionMode"] == "Indexed"
    assert spec["completions"] == 4 and spec["parallelism"] == 4
    pod = spec["template"]["spec"]
    c = pod["containers"][0]
    # the pod command is the k8s-worker bootstrap wrapping the user cmd
    assert c["command"][:4] == ["python", "-m", "torchft_tpu.launcher", "--k8s-worker"]
    assert c["command"][-2:] == ["python", "examples/train_hsdp.py"]
    env = {e["name"]: e for e in c["env"]}
    assert env["REPLICA_GROUP_ID"]["value"] == "1"
    assert env["NUM_REPLICA_GROUPS"]["value"] == "3"
    assert env["WORLD_SIZE"]["value"] == "4"
    assert env["TORCHFT_LIGHTHOUSE"]["value"] == f"job-lighthouse:{LIGHTHOUSE_PORT}"
    assert env["TORCHFT_GROUP_HOST0"]["value"] == "job-g1-0.job-g1"
    assert "job-completion-index" in str(env["RANK"]["valueFrom"])
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
    # headless service exposes store + coordinator ports
    svc = next(
        d for d in docs if d["kind"] == "Service" and d["metadata"]["name"] == "job-g1"
    )
    ports = {p["name"]: p["port"] for p in svc["spec"]["ports"]}
    assert ports == {"store": STORE_PORT, "coord": COORD_PORT}


def test_k8s_apply_status_down_roundtrip(tmp_path, capsys):
    """Round-5 review missing #1: the k8s story must be runnable, not just
    templated. Drive the launcher's apply/status/down verbs against a FAKE
    kubectl that records its invocations and serves canned API JSON; the
    applied manifests must round-trip through a YAML parser with the
    session label every verb selects on."""
    import json as _json

    yaml = pytest.importorskip("yaml")

    from torchft_tpu.launcher import main

    log = tmp_path / "kubectl.log"
    stdin_copy = tmp_path / "applied.yaml"
    canned = {
        "items": [
            {
                "kind": "Job",
                "metadata": {"name": "sess-g0"},
                "status": {"active": 2, "succeeded": 0, "failed": 1},
            },
            {
                "kind": "Deployment",
                "metadata": {"name": "sess-lighthouse"},
                "status": {"availableReplicas": 1},
            },
        ]
    }
    fake = tmp_path / "kubectl"
    fake.write_text(
        "#!/bin/bash\n"
        f"echo \"$@\" >> {log}\n"
        "if [ \"$1\" = apply ]; then\n"
        f"  cat > {stdin_copy}\n"
        "elif [ \"$1\" = get ]; then\n"
        f"  cat {tmp_path}/canned.json\n"
        "fi\n"
    )
    fake.chmod(0o755)
    (tmp_path / "canned.json").write_text(_json.dumps(canned))

    main([
        "--k8s-apply", "--name", "sess", "--groups", "2",
        "--kubectl", str(fake), "--", "python", "train.py",
    ])
    docs = list(yaml.safe_load_all(stdin_copy.read_text()))
    assert len(docs) == 6
    for d in docs:
        assert d["metadata"]["labels"]["torchft-session"] == "sess", d

    main(["--k8s-status", "--name", "sess", "--kubectl", str(fake)])
    out = capsys.readouterr().out
    st = _json.loads(out)
    assert st["jobs"]["sess-g0"] == {
        "active": 2, "succeeded": 0, "failed": 1,
    }
    assert st["lighthouse"]["sess-lighthouse"] == {"available": 1}

    main(["--k8s-down", "--name", "sess", "--kubectl", str(fake)])
    lines = log.read_text().splitlines()
    assert lines[0].startswith("apply -n default -f -")
    assert "get jobs,deployments -n default -l torchft-session=sess" in lines[1]
    assert (
        "delete jobs,services,deployments -n default -l torchft-session=sess"
        in lines[2]
    )


def test_k8s_worker_bootstrap_hosts_store(monkeypatch):
    """Rank 0's bootstrap must host a reachable KV store and point the
    child at it; a nonzero child exit propagates."""
    from torchft_tpu.launcher import k8s_worker

    monkeypatch.setenv("RANK", "0")
    monkeypatch.setenv("WORLD_SIZE", "1")
    monkeypatch.setenv("TORCHFT_GROUP_HOST0", "localhost")
    # ephemeral store port: parallel test runs must not fight over the
    # fixed in-cluster port
    monkeypatch.setenv("TORCHFT_STORE_PORT", "0")

    child = (
        "import os, sys\n"
        "from datetime import timedelta\n"
        "from torchft_tpu.store import StoreClient\n"
        "addr = os.environ['TORCHFT_STORE_ADDR']\n"
        "c = StoreClient(addr, connect_timeout=timedelta(seconds=5))\n"
        "c.set('k8s', 'ok')\n"
        "assert c.get('k8s') == b'ok'\n"
        "c.close()\n"
        "sys.exit(7)\n"
    )
    assert k8s_worker([sys.executable, "-c", child]) == 7
