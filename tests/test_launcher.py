"""Launcher supervisor tests (torchx.py analogue coverage)."""

import os
import sys

import pytest

from torchft_tpu.launcher import launch


def test_clean_run(tmp_path):
    code = launch(
        [sys.executable, "-c", "import sys; sys.exit(0)"],
        num_groups=2,
        nproc=1,
        lighthouse_addr="localhost:1",  # unused by the trivial cmd
    )
    assert code == 0


def test_restart_on_failure(tmp_path):
    # first run of group 0 fails (marker absent), restart succeeds
    marker = tmp_path / "marker"
    script = (
        "import os, sys\n"
        f"m = {str(marker)!r} + os.environ['REPLICA_GROUP_ID']\n"
        "if os.environ['REPLICA_GROUP_ID'] == '0' and not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.exit(3)\n"
        "sys.exit(0)\n"
    )
    code = launch(
        [sys.executable, "-c", script],
        num_groups=2,
        nproc=1,
        lighthouse_addr="localhost:1",
        max_restarts=2,
    )
    assert code == 0
    assert (tmp_path / "marker0").exists()


def test_restart_exhaustion():
    code = launch(
        [sys.executable, "-c", "import sys; sys.exit(2)"],
        num_groups=1,
        nproc=1,
        lighthouse_addr="localhost:1",
        max_restarts=1,
    )
    assert code == 1
