"""PR 2 observability tests: distributed spans (nesting, carrier
propagation, Chrome export schema), the collective flight recorder (ring
wraparound, SIGUSR2 dump validity, deadline trigger), the step watchdog,
the lighthouse cluster aggregation endpoints (/cluster.json, /trace),
checkpoint-transport trace propagation, and the parameter server's
/metrics route. The docs<->code catalog drift checks that used to live
here moved into ``python -m torchft_tpu.analysis`` (docdrift rules);
``tests/test_analysis.py`` keeps them in tier-1 through the one gate.
"""

import json
import os
import signal
import threading
import time
import urllib.request
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu import telemetry
from torchft_tpu.telemetry import read_trail
from torchft_tpu.telemetry.events import EventTrail
from torchft_tpu.telemetry.flight import FlightRecorder, StepWatchdog
from torchft_tpu.telemetry.tracing import Tracer, read_spans


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_and_trace_identity(self):
        t = Tracer()
        t.set_context(replica_id="gA", step=7, quorum_epoch=3)
        with t.span("outer", rank=0) as outer:
            with t.span("inner") as inner:
                pass
        spans = t.recent()
        assert [s["name"] for s in spans] == ["inner", "outer"]
        by_name = {s["name"]: s for s in spans}
        assert by_name["outer"]["trace_id"] == "gA:7:3"
        assert by_name["inner"]["trace_id"] == "gA:7:3"
        assert by_name["inner"]["parent_id"] == outer.span_id
        assert "parent_id" not in by_name["outer"]
        assert by_name["outer"]["attrs"]["rank"] == 0
        assert inner.dur_s <= outer.dur_s

    def test_carrier_propagation_across_tracers(self):
        # two Tracer instances stand in for two replicas
        a, b = Tracer(), Tracer()
        a.set_context(replica_id="gA", step=1, quorum_epoch=1)
        with a.span("heal_recv") as client_span:
            carrier = a.inject()
            wire = Tracer.format_carrier(carrier)
        parsed = Tracer.parse_carrier(wire)
        with b.span("checkpoint_serve", parent=parsed):
            pass
        serve = b.recent("checkpoint_serve")[-1]
        assert serve["parent_id"] == client_span.span_id
        assert serve["trace_id"] == "gA:1:1"  # adopted from the carrier

    def test_explicit_trace_id_and_error_attr(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("boom", trace_id="g:1:2"):
                raise ValueError("nope")
        s = t.recent("boom")[-1]
        assert s["trace_id"] == "g:1:2"
        assert "nope" in s["attrs"]["error"]

    def test_jsonl_sink_roundtrip(self, tmp_path):
        t = Tracer()
        t.configure(str(tmp_path / "spans.jsonl"))
        with t.span("op_a"):
            pass
        t.close()
        spans = read_spans(str(tmp_path / "spans.jsonl"))
        assert [s["name"] for s in spans] == ["op_a"]
        assert spans[0]["dur_s"] >= 0

    def test_chrome_export_schema(self, tmp_path):
        t = Tracer()
        t.set_context(replica_id="gB", step=2, quorum_epoch=5)
        with t.span("quorum"):
            pass
        events = t.chrome_events()
        # metadata event naming the replica lane + the span itself
        assert any(e.get("ph") == "M" for e in events)
        xs = [e for e in events if e.get("ph") == "X"]
        assert xs, events
        for e in xs:
            for key in ("name", "ph", "ts", "dur", "pid", "tid", "args"):
                assert key in e, (key, e)
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        # whole document round-trips through JSON (Perfetto-loadable shape)
        doc = json.loads(
            json.dumps({"displayTimeUnit": "ms", "traceEvents": events})
        )
        assert doc["traceEvents"]

    def test_drain_chrome_fragment_is_joinable(self):
        t = Tracer()
        t.set_context(replica_id="gC", step=0, quorum_epoch=0)
        for _ in range(3):
            with t.span("s"):
                pass
        frag = t.drain_chrome_fragment(max_events=8)
        events = json.loads(f"[{frag}]")
        assert len(events) >= 3
        # drained: a second call returns only new spans
        assert t.drain_chrome_fragment() == ""

    def test_drain_byte_cap_keeps_tail_pending(self):
        # spans past the byte budget must stay queued for the next batch,
        # not be silently dropped (incident windows are span-heavy)
        t = Tracer()
        t.set_context(replica_id="gD", step=0, quorum_epoch=0)
        for i in range(6):
            with t.span(f"op{i}", pad="x" * 200):
                pass
        first = t.drain_chrome_fragment(max_events=64, max_bytes=900)
        second = t.drain_chrome_fragment(max_events=64, max_bytes=1 << 20)
        names = [
            e["name"]
            for e in json.loads(f"[{first},{second}]")
            if e.get("ph") == "X"
        ]
        assert names == [f"op{i}" for i in range(6)], names

    def test_requeue_last_batch_restores_spans(self):
        # a failed piggyback RPC requeues its drained batch (manager's
        # quorum-error path), so the outage keeps its spans
        t = Tracer()
        t.set_context(replica_id="gE", step=1, quorum_epoch=1)
        with t.span("will_fail_to_ship"):
            pass
        frag = t.drain_chrome_fragment()
        assert "will_fail_to_ship" in frag
        t.requeue_last_batch()
        again = t.drain_chrome_fragment()
        assert "will_fail_to_ship" in again
        t.requeue_last_batch()  # idempotence: batch was consumed above...
        t.requeue_last_batch()  # ...and double-requeue must not raise


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_wraparound_and_analyze(self):
        fr = FlightRecorder(size=8)
        seqs = [fr.record_issue("allreduce", "tcp", 100, rank=0) for _ in range(20)]
        snap = fr.snapshot()
        assert len(snap) == 8
        assert [r["seq"] for r in snap] == list(range(13, 21))
        # completing an overwritten record is a safe no-op
        fr.record_complete(seqs[0])
        # complete all but the oldest surviving two
        for s in range(15, 21):
            fr.record_complete(s)
        fr.record_complete(14, error=RuntimeError("peer gone"))
        digest = fr.analyze(fr.snapshot())
        assert digest["last_completed"]["seq"] == 20
        assert digest["first_stuck"]["seq"] == 13  # still "issued"
        failed = [r for r in fr.snapshot() if r["status"] == "failed"]
        assert [r["seq"] for r in failed] == [14]

    def test_dump_file_validity_and_rate_limit(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        fr = FlightRecorder(size=4)
        s = fr.record_issue("broadcast", "device", 64, rank=1)
        fr.record_complete(s)
        fr.record_issue("allreduce", "device", 128, rank=1)
        path = fr.dump("manual")
        assert path and os.path.exists(path)
        doc = json.loads(open(path).read())
        assert doc["reason"] == "manual"
        assert doc["last_completed"]["op"] == "broadcast"
        assert doc["first_stuck"]["op"] == "allreduce"
        assert len(doc["entries"]) == 2
        # rate-limited second dump; force overrides
        assert fr.dump("manual") is None
        assert fr.dump("manual", force=True) is not None

    def test_sigusr2_dump(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        assert telemetry.install_sigusr2()
        sq = telemetry.FLIGHT.record_issue("allgather", "tcp", 32, rank=0)
        telemetry.FLIGHT.record_complete(sq)
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.monotonic() + 10
        dumps = []
        while time.monotonic() < deadline and not dumps:
            dumps = [
                f for f in os.listdir(tmp_path) if f.startswith("tft_flight_")
            ]
            time.sleep(0.05)
        assert dumps, "SIGUSR2 produced no flight dump"
        doc = json.loads(open(tmp_path / dumps[0]).read())
        assert doc["reason"] == "signal"
        assert any(e["op"] == "allgather" for e in doc["entries"])

    def test_collectives_record_into_ring(self):
        from torchft_tpu.collectives import CollectivesDummy  # noqa: F401

        # the TCP backend records issue+completion through _count_op /
        # _track_flight; exercise via a world-1 CollectivesTcp (no sockets)
        from torchft_tpu.collectives import CollectivesTcp

        telemetry.FLIGHT.clear()
        c = CollectivesTcp(timeout=timedelta(seconds=5))
        c.configure("unused", 0, 1)
        try:
            c.allreduce([np.ones(4, np.float32)]).wait(timedelta(seconds=5))
            c.barrier().wait(timedelta(seconds=5))
        finally:
            c.shutdown()
        snap = telemetry.FLIGHT.snapshot()
        ops = [r["op"] for r in snap]
        assert "allreduce" in ops and "barrier" in ops
        assert all(r["status"] == "completed" for r in snap), snap


class TestDeadlineDump:
    def test_hung_collective_dump_identifies_stuck_op(
        self, tmp_path, monkeypatch
    ):
        """Forced collective hang: one group issues a barrier its peer
        never joins. The futures deadline manager fails the op AND writes
        a flight dump whose first_stuck names the wedged barrier."""
        from torchft_tpu.collectives_device import CollectivesDevice

        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setattr(telemetry.FLIGHT, "min_dump_interval_s", 0.0)
        telemetry.FLIGHT.clear()
        key = "store/torchft/7701/0"
        a = CollectivesDevice(timeout=timedelta(seconds=1))
        b = CollectivesDevice(timeout=timedelta(seconds=1))
        th = threading.Thread(target=lambda: b.configure(key, 1, 2))
        th.start()
        a.configure(key, 0, 2)
        th.join()
        try:
            work = a.barrier()  # b never issues: the op can never complete
            with pytest.raises(TimeoutError):
                work.wait(timedelta(seconds=10))
        finally:
            a.shutdown()
            b.shutdown()
        deadline = time.monotonic() + 10
        dumps = []
        while time.monotonic() < deadline and not dumps:
            dumps = [
                f for f in os.listdir(tmp_path) if f.startswith("tft_flight_")
            ]
            time.sleep(0.05)
        assert dumps, "deadline expiry produced no flight dump"
        docs = [json.loads(open(tmp_path / f).read()) for f in dumps]
        assert any(
            d["reason"] == "deadline"
            and d["first_stuck"]
            and d["first_stuck"]["op"] == "barrier"
            for d in docs
        ), docs


class TestStepWatchdog:
    def test_fires_dumps_and_latches(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        fired = []
        fr = FlightRecorder(size=4)
        wd = StepWatchdog(
            mult=0.0001,
            min_s=0.15,
            on_stall=lambda step, el, thr: fired.append((step, el, thr)),
            recorder=fr,
        )
        try:
            ev0 = len(telemetry.EVENTS.recent("watchdog_stall"))
            wd.arm(step=42)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not fired:
                time.sleep(0.02)
            assert fired and fired[0][0] == 42
            assert wd.stalled and wd.stalls == 1
            assert fired[0][2] >= 0.15  # threshold floor respected
            assert len(telemetry.EVENTS.recent("watchdog_stall")) == ev0 + 1
            assert any(
                f.startswith("tft_flight_") for f in os.listdir(tmp_path)
            )
            # fires once per armed step
            time.sleep(0.3)
            assert wd.stalls == 1
            wd.disarm()
            assert not wd.stalled
        finally:
            wd.stop()

    def test_disabled_by_mult_zero(self):
        wd = StepWatchdog(mult=0, min_s=0.01)
        wd.arm(step=1)  # no thread started
        assert wd._thread is None
        wd.stop()


# ---------------------------------------------------------------------------
# lighthouse cluster aggregation
# ---------------------------------------------------------------------------


class TestClusterAggregation:
    def test_cluster_json_and_merged_trace(self, tmp_path):
        from torchft_tpu.coordination import LighthouseClient, LighthouseServer
        from torchft_tpu.telemetry.native import fetch_merged_trace, poll_cluster

        t = Tracer()
        t.set_context(replica_id="repA", step=7, quorum_epoch=2)
        with t.span("quorum"):
            pass
        frag = t.drain_chrome_fragment()
        payload = {
            "summary": json.dumps({"quorums": 3, "heals_recv": 1}),
            "step": 7,
            "stuck": True,
            "last_heal_ts": 123.5,
            "spans": frag,
        }
        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            cli = LighthouseClient(
                lh.address(), connect_timeout=timedelta(seconds=5)
            )
            cli.heartbeat("repA", telemetry_payload=payload)
            cli.heartbeat("repB", telemetry_payload={"step": 5, "stuck": False})
            cli.close()

            cluster = poll_cluster(lh.address())
            assert cluster is not None
            reps = cluster["replicas"]
            assert reps["repA"]["step"] == 7
            assert reps["repA"]["stuck"] is True
            assert reps["repA"]["last_heal_ts"] == 123.5
            assert reps["repA"]["summary"]["quorums"] == 3
            assert reps["repB"]["step"] == 5
            assert reps["repA"]["last_seen_ms_ago"] >= 0

            out = str(tmp_path / "trace.json")
            trace = fetch_merged_trace(lh.address(), path=out)
            assert trace is not None and os.path.exists(out)
            xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
            assert xs, trace
            for e in xs:
                for key in ("name", "ph", "ts", "pid", "tid"):
                    assert key in e
            assert any(
                e.get("args", {}).get("trace_id") == "repA:7:2" for e in xs
            )
            # the dashboard grew the health table + stuck highlight
            with urllib.request.urlopen(
                f"{lh.address()}/status", timeout=5
            ) as resp:
                html = resp.read().decode()
            assert "Replica health" in html
            assert "STUCK" in html
        finally:
            lh.shutdown()


# ---------------------------------------------------------------------------
# checkpoint transport trace propagation (cross-replica parent/child)
# ---------------------------------------------------------------------------


class TestCheckpointTracePropagation:
    def test_serve_span_is_child_of_recv_span(self):
        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        src = HTTPTransport(timeout=timedelta(seconds=5))
        dst = HTTPTransport(timeout=timedelta(seconds=5))
        try:
            state = {"w": np.arange(8, dtype=np.float32)}
            src.send_checkpoint(
                dst_ranks=[1], step=3, state_dict=state,
                timeout=timedelta(seconds=5),
            )
            telemetry.TRACER.set_context(
                replica_id="healer", step=3, quorum_epoch=9
            )
            with telemetry.TRACER.span("heal_recv") as parent:
                got = dst.recv_checkpoint(
                    src_rank=0,
                    metadata=src.metadata(),
                    step=3,
                    timeout=timedelta(seconds=5),
                )
            np.testing.assert_array_equal(got["w"], state["w"])
            # the serve span is recorded on the HTTP server thread,
            # which finishes AFTER the client's recv returns — poll
            # briefly, and filter to THIS heal's trace so a straggler
            # serve span from a previous in-process test can't be
            # mistaken for ours
            deadline = time.time() + 5
            serves = []
            while not serves and time.time() < deadline:
                serves = [
                    s
                    for s in telemetry.TRACER.recent("checkpoint_serve")
                    if s["trace_id"] == "healer:3:9"
                ]
                if not serves:
                    time.sleep(0.01)
            assert serves, "serving side recorded no span"
            serve = serves[-1]
            assert serve["parent_id"] == parent.span_id
            assert serve["trace_id"] == "healer:3:9"
            assert serve["attrs"]["bytes"] > 0
        finally:
            src.shutdown()
            dst.shutdown()


# ---------------------------------------------------------------------------
# parameter server /metrics
# ---------------------------------------------------------------------------


class TestParameterServerMetrics:
    def test_scrape(self):
        from torchft_tpu.collectives import CollectivesDummy
        from torchft_tpu.parameter_server import ParameterServer

        class PS(ParameterServer):
            @classmethod
            def new_collectives(cls):
                return CollectivesDummy()

            def forward(self, session_id, collectives):
                pass

        ps = PS(port=0)
        try:
            port = ps._server.socket.getsockname()[1]
            with urllib.request.urlopen(
                f"http://localhost:{port}/metrics", timeout=5
            ) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                text = resp.read().decode()
            assert "tft_quorum_latency_seconds" in text
            assert "tft_flight_dumps_total" in text
        finally:
            ps.shutdown()


# ---------------------------------------------------------------------------
# event-trail rotation
# ---------------------------------------------------------------------------


class TestTrailRotation:
    def test_rolls_to_dot1_past_cap(self, tmp_path):
        path = str(tmp_path / "trail.jsonl")
        trail = EventTrail(path=path, max_bytes=512)
        for i in range(64):
            trail.emit("commit", step=i, pad="x" * 32)
        trail.close()
        rolled = path + ".1"
        assert os.path.exists(rolled), "no rotation happened"
        assert os.path.getsize(path) <= 1024
        # both generations parse; records are contiguous across the roll
        steps = [r["step"] for r in read_trail(rolled)] + [
            r["step"] for r in read_trail(path)
        ]
        assert steps[-1] == 63
        assert steps == sorted(steps)

    def test_env_knob_and_disable(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_EVENT_TRAIL_MAX_BYTES", "0")
        trail = EventTrail(path=str(tmp_path / "t.jsonl"))
        assert trail.max_bytes == 0
        for i in range(16):
            trail.emit("commit", step=i)
        trail.close()
        assert not os.path.exists(str(tmp_path / "t.jsonl.1"))

