"""Sampler sharding/resume + aux-subsystem tests (data.py, profiling.py,
docs-as-test from coordination_test.py:8-18)."""

import time

import numpy as np
import pytest

from torchft_tpu.data import DistributedSampler
from torchft_tpu.profiling import StepTimer, timed


class TestDistributedSampler:
    def test_disjoint_cover(self):
        n = 100
        seen = []
        for g in range(2):
            for r in range(2):
                s = DistributedSampler(
                    n, replica_group=g, num_replica_groups=2,
                    rank=r, num_replicas=2, shuffle=False,
                )
                idx = list(s)
                assert len(idx) == len(s) == 25
                seen.extend(idx)
        assert sorted(seen) == list(range(100))

    def test_shuffle_epochs_differ_but_agree_across_workers(self):
        a = DistributedSampler(64, 0, 2, shuffle=True, seed=1)
        b = DistributedSampler(64, 0, 2, shuffle=True, seed=1)
        a.set_epoch(0)
        b.set_epoch(0)
        assert list(a) == list(b)
        a.set_epoch(1)
        assert list(a) != list(b)

    def test_pad_tiles_small_dataset(self):
        s = DistributedSampler(1, replica_group=1, num_replica_groups=2,
                               rank=1, num_replicas=2, shuffle=False)
        assert len(list(s)) == len(s) == 1  # tiled, not starved

    def test_resume_position(self):
        s = DistributedSampler(16, 0, 2, shuffle=False)
        it = iter(s)
        first3 = [next(it) for _ in range(3)]
        state = s.state_dict()
        # fresh sampler resumes where the old one stopped
        s2 = DistributedSampler(16, 0, 2, shuffle=False)
        s2.load_state_dict(state)
        rest = list(s2)
        assert first3 + rest == list(iter(DistributedSampler(16, 0, 2, shuffle=False)))
        # and position resets after a full epoch
        assert s2.state_dict()["position"] == 0


class TestAux:
    def test_step_timer(self):
        t = StepTimer(window=4)
        assert t.tick() is None
        time.sleep(0.01)
        d = t.tick()
        assert d is not None and d > 0
        assert t.steps_per_sec() > 0

    def test_public_api_has_docstrings(self):
        # docs-as-test (reference coordination_test.py:8-18)
        import torchft_tpu
        from torchft_tpu import coordination, manager, collectives

        for obj in (
            coordination.LighthouseServer,
            coordination.ManagerServer,
            coordination.ManagerClient,
            manager.Manager,
            manager.Manager.start_quorum,
            manager.Manager.should_commit,
            manager.Manager.allreduce,
            collectives.Collectives,
        ):
            assert obj.__doc__ and obj.__doc__.strip(), f"{obj} missing docstring"
