"""Telemetry subsystem tests: registry semantics, Prometheus exposition,
FT event-trail round-trip, StepTimer outlier marking, the /metrics route
on the checkpoint HTTP server, and a 2-replica Manager integration run
asserting quorum/commit events fire.
"""

import json
import os
import re
import threading
import time
import urllib.request
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu import telemetry
from torchft_tpu.profiling import StepTimer
from torchft_tpu.telemetry import EventTrail, read_trail
from torchft_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_basic(self):
        r = MetricsRegistry()
        c = r.counter("c_total", "help text")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_get_or_create_idempotent(self):
        r = MetricsRegistry()
        assert r.counter("x_total") is r.counter("x_total")
        with pytest.raises(ValueError):
            r.gauge("x_total")  # type clash must be loud

    def test_label_children(self):
        r = MetricsRegistry()
        c = r.counter("ops_total", "ops", labelnames=("op", "plane"))
        c.labels(op="allreduce", plane="tcp").inc(3)
        c.labels("allreduce", "cma").inc()
        # same labels -> same child
        assert c.labels(op="allreduce", plane="tcp").value == 3
        # a labeled family cannot be observed directly
        with pytest.raises(ValueError):
            c.inc()
        # wrong arity is loud
        with pytest.raises(ValueError):
            c.labels("only-one")
        text = "\n".join(c.render())
        assert 'ops_total{op="allreduce",plane="tcp"} 3' in text
        assert 'ops_total{op="allreduce",plane="cma"} 1' in text

    def test_gauge(self):
        r = MetricsRegistry()
        g = r.gauge("g")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value == 6

    def test_histogram_buckets(self):
        r = MetricsRegistry()
        h = r.histogram("h_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(56.05)
        # cumulative semantics
        assert snap["buckets"]["0.1"] == 1
        assert snap["buckets"]["1"] == 3
        assert snap["buckets"]["10"] == 4
        # quantile interpolates within bounds, clamps past the last one
        assert 0.1 <= h.quantile(0.5) <= 1.0
        assert h.quantile(0.999) == 10.0
        assert r.histogram("empty_seconds").quantile(0.5) is None

    def test_histogram_time_context(self):
        r = MetricsRegistry()
        h = r.histogram("t_seconds")
        with h.time():
            time.sleep(0.01)
        assert h.count == 1
        assert h.sum >= 0.01

    def test_thread_safety_smoke(self):
        r = MetricsRegistry()
        c = r.counter("race_total", labelnames=("t",))
        h = r.histogram("race_seconds")
        n_threads, n_iter = 8, 2000

        def work(i):
            child = c.labels(t=str(i % 2))
            for _ in range(n_iter):
                child.inc()
                h.observe(0.001)

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = sum(child.value for _v, child in c._snapshot_children())
        assert total == n_threads * n_iter
        assert h.count == n_threads * n_iter

    def test_render_is_valid_prometheus(self):
        r = MetricsRegistry()
        r.counter("a_total", 'has "quotes" and \\ slashes').inc()
        r.gauge("b", "gauge", labelnames=("x",)).labels(x='v"al').set(2)
        r.histogram("c_seconds", buckets=(1.0,)).observe(0.5)
        _assert_prometheus_text(r.render())

    def test_dump_roundtrips_through_json(self):
        r = MetricsRegistry()
        r.counter("a_total").inc()
        r.histogram("c_seconds", buckets=(1.0,)).observe(0.5)
        d = json.loads(json.dumps(r.dump()))
        assert d["a_total"]["samples"][0]["value"] == 1
        assert d["c_seconds"]["samples"][0]["count"] == 1

    def test_reset_values_keeps_references_live(self):
        r = MetricsRegistry()
        c = r.counter("r_total", labelnames=("k",))
        child = c.labels(k="a")
        child.inc(5)
        r.reset_values()
        assert child.value == 0
        child.inc()  # the held reference must still be the rendered child
        assert 'r_total{k="a"} 1' in "\n".join(c.render())


_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? '
    r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$"
)


def _assert_prometheus_text(text: str) -> None:
    """Minimal exposition-format validator: every line is a comment or a
    well-formed sample; every sample's family has a preceding # TYPE."""
    typed = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            typed.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
        family = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", family)
        assert family in typed or base in typed, f"untyped sample: {line!r}"


# ---------------------------------------------------------------------------
# event trail
# ---------------------------------------------------------------------------


class TestEventTrail:
    def test_ring_buffer_and_filter(self):
        trail = EventTrail()
        trail.emit("commit", step=1)
        trail.emit("abort", step=2)
        trail.emit("commit", step=3)
        assert [e["step"] for e in trail.recent("commit")] == [1, 3]
        assert len(trail.recent()) == 3
        assert all("ts" in e for e in trail.recent())

    def test_jsonl_roundtrip(self, tmp_path):
        path = str(tmp_path / "trail.jsonl")
        trail = EventTrail(path=path)
        trail.emit("quorum_ready", quorum_id=7, participants=["a", "b"])
        trail.emit("peer_death", ring_rank=1)
        trail.close()
        records = read_trail(path)
        assert [r["event"] for r in records] == ["quorum_ready", "peer_death"]
        assert records[0]["participants"] == ["a", "b"]
        assert records[0]["ts"] <= records[1]["ts"]

    def test_read_trail_skips_torn_tail(self, tmp_path):
        path = tmp_path / "trail.jsonl"
        path.write_text('{"ts": 1, "event": "commit"}\n{"ts": 2, "eve')
        assert [r["event"] for r in read_trail(str(path))] == ["commit"]

    def test_env_var_sink(self, tmp_path, monkeypatch):
        path = str(tmp_path / "env_trail.jsonl")
        monkeypatch.setenv(telemetry.ENV_TRAIL_PATH, path)
        trail = EventTrail()  # picks the env path up lazily on first emit
        trail.emit("eviction", victim="g1")
        trail.close()
        assert read_trail(path)[0]["victim"] == "g1"

    def test_emit_mirrors_into_metric(self):
        before = telemetry.FT_EVENTS_TOTAL.labels(event="test_kind").value
        telemetry.EVENTS.emit("test_kind")
        after = telemetry.FT_EVENTS_TOTAL.labels(event="test_kind").value
        assert after == before + 1


# ---------------------------------------------------------------------------
# StepTimer outlier marking
# ---------------------------------------------------------------------------


class TestStepTimer:
    def test_outliers_excluded_from_steady_rate(self):
        t = StepTimer(window=8, record_metrics=False)
        assert t.tick() is None
        for _ in range(3):
            time.sleep(0.002)
            t.tick()
        time.sleep(0.05)
        t.mark_quorum()
        d = t.tick()
        assert d >= 0.05
        assert t.outlier_steps == 1
        assert t.outliers()[0][2] == ("quorum",)
        # the slow quorum step must not drag the steady rate down
        assert t.steps_per_sec() > t.steps_per_sec_all()

    def test_tick_kwargs_and_pending_marks_combine(self):
        t = StepTimer(record_metrics=False)
        t.tick()
        t.mark_heal()
        t.tick(quorum=True)
        assert t.outliers()[0][2] == ("heal", "quorum")
        assert t.last_tags == ("heal", "quorum")
        t.tick()
        assert t.last_tags == ()  # marks don't leak into the next step

    def test_records_into_registry_by_kind(self):
        hist = telemetry.STEP_DURATION
        steady0 = hist.labels(kind="steady").count
        heal0 = hist.labels(kind="heal").count
        t = StepTimer()
        t.tick()
        t.tick()  # steady
        t.tick(heal=True, quorum=True)  # heal wins the kind
        assert hist.labels(kind="steady").count == steady0 + 1
        assert hist.labels(kind="heal").count == heal0 + 1


# ---------------------------------------------------------------------------
# /metrics on the checkpoint HTTP server
# ---------------------------------------------------------------------------


class TestMetricsEndpoint:
    def test_scrape_includes_catalog(self):
        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        transport = HTTPTransport(timeout=timedelta(seconds=5))
        try:
            url = f"http://localhost:{transport._port}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                text = resp.read().decode()
        finally:
            transport.shutdown()
        _assert_prometheus_text(text)
        # acceptance names must be present even before any observation
        for name in (
            "tft_quorum_latency_seconds",
            "tft_allreduce_bytes_total",
            "tft_step_duration_seconds",
            "tft_commits_total",
            "tft_heal_duration_seconds",
        ):
            assert name in text, name

    def test_scrape_works_while_no_checkpoint_staged(self):
        # readers of /checkpoint/* block until staging; /metrics must not
        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        transport = HTTPTransport(timeout=timedelta(seconds=5))
        try:
            t0 = time.perf_counter()
            with urllib.request.urlopen(
                f"http://localhost:{transport._port}/metrics", timeout=5
            ) as resp:
                resp.read()
            assert time.perf_counter() - t0 < 2.0
        finally:
            transport.shutdown()


# ---------------------------------------------------------------------------
# Manager integration: 2 replica groups, real quorum + commit votes
# ---------------------------------------------------------------------------


def _train_group(gid, lighthouse_addr, steps, barrier):
    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.manager import Manager
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=15)),
        load_state_dict=lambda s: None,
        state_dict=lambda: {"w": np.zeros(4, np.float32)},
        min_replica_size=2,
        replica_id=f"telemetry_g{gid}_",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse_addr,
        timeout=timedelta(seconds=15),
        quorum_timeout=timedelta(seconds=30),
    )
    committed = 0
    try:
        barrier.wait(timeout=30)
        while committed < steps:
            manager.start_quorum()
            grad = np.full(8, float(gid + 1), np.float32)
            manager.allreduce(grad).wait()
            if manager.should_commit():
                committed += 1
        return {"gid": gid, "committed": committed, "grad": grad}
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def test_manager_2replica_quorum_commit_events():
    """2-replica CPU-mesh run: quorum + commit events must land in the
    trail and the catalog metrics must move."""
    from concurrent.futures import ThreadPoolExecutor

    from torchft_tpu.coordination import LighthouseServer

    telemetry.EVENTS.clear()
    quorums0 = telemetry.QUORUMS_TOTAL.value
    commits0 = telemetry.COMMITS_TOTAL.labels(outcome="committed").value
    lh = LighthouseServer(bind="[::]:0", min_replicas=2)
    steps = 3
    barrier = threading.Barrier(2)
    try:
        with ThreadPoolExecutor(max_workers=2) as pool:
            futs = [
                pool.submit(_train_group, g, lh.address(), steps, barrier)
                for g in range(2)
            ]
            results = [f.result(timeout=120) for f in futs]
        # while the lighthouse is still up: the cluster aggregation must
        # have received each replica's piggybacked telemetry (rides the
        # quorum traffic — no extra RPCs to trigger here)
        from torchft_tpu.telemetry.native import fetch_merged_trace, poll_cluster

        cluster = poll_cluster(lh.address())
        trace = fetch_merged_trace(lh.address())
    finally:
        lh.shutdown()

    assert cluster is not None
    groups = [
        rid for rid in cluster["replicas"] if rid.startswith("telemetry_g")
    ]
    assert len(groups) == 2, cluster
    for rid in groups:
        assert cluster["replicas"][rid]["step"] >= 0
        assert "quorums" in cluster["replicas"][rid]["summary"]

    # merged Chrome trace carries spans from BOTH replicas, and their
    # trace ids correlate on quorum epoch (trace_id = replica:step:epoch)
    assert trace is not None
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    epochs_by_replica = {}
    for e in xs:
        tid = e.get("args", {}).get("trace_id", "")
        rid, _, rest = tid.partition(":")
        _, _, epoch = rest.partition(":")
        if rid.startswith("telemetry_g"):
            epochs_by_replica.setdefault(rid, set()).add(epoch)
    assert len(epochs_by_replica) == 2, epochs_by_replica
    e1, e2 = epochs_by_replica.values()
    assert e1 & e2, f"no correlated quorum epoch: {epochs_by_replica}"

    assert all(r["committed"] == steps for r in results)
    # both groups averaged (1+2)/2 = 1.5 every step
    for r in results:
        np.testing.assert_allclose(r["grad"], 1.5)

    # events: every group emitted quorum_start/quorum_ready per step and a
    # commit per committed step (shared process ring holds both groups)
    kinds = [e["event"] for e in telemetry.EVENTS.recent()]
    assert kinds.count("quorum_ready") >= 2 * steps
    assert kinds.count("commit") == 2 * steps
    ready = telemetry.EVENTS.recent("quorum_ready")[-1]
    assert ready["num_participants"] == 2
    assert len(ready["participants"]) == 2

    # metrics: quorum RPC latency observed, commits counted
    assert telemetry.QUORUMS_TOTAL.value >= quorums0 + 2 * steps
    assert (
        telemetry.COMMITS_TOTAL.labels(outcome="committed").value
        == commits0 + 2 * steps
    )
    assert telemetry.QUORUM_LATENCY.count > 0
    assert telemetry.ALLREDUCE_BYTES.labels(plane="python-ring").value > 0 or any(
        child.value > 0
        for _v, child in telemetry.ALLREDUCE_BYTES._snapshot_children()
    )

    # summary digest is JSON-serializable and consistent
    s = json.loads(json.dumps(telemetry.summary()))
    assert s["commits"]["committed"] >= 2 * steps


# ---------------------------------------------------------------------------
# kill one replica: peer_death -> heal_end readable from the trail
# ---------------------------------------------------------------------------


def _death_then_heal_recorded(r):
    """True iff the trail shows the INDUCED failure: the victim's death
    detected (peer_death naming it, from the kill onward — startup-churn
    false positives about other replicas don't count) and the respawned
    victim's heal_end after it."""
    victim_prefix = f"group{len(r.trail_paths) - 1}_"
    survivor_events = []
    for path in r.trail_paths[:-1]:
        survivor_events.extend(read_trail(path))
    victim_events = read_trail(r.trail_paths[-1])

    deaths = [
        e
        for e in survivor_events
        if e["event"] == "peer_death"
        and str(e.get("replica", "")).startswith(victim_prefix)
        and e["ts"] >= r.t_kill_unix - 0.5
    ]
    heals = [
        e
        for e in victim_events
        if e["event"] == "heal_end" and e["ts"] >= r.t_respawn_unix
    ]
    return bool(
        deaths
        and heals
        and any(h["ts"] > min(d["ts"] for d in deaths) for h in heals)
        and any(h.get("bytes", 0) > 0 for h in heals)
    )


@pytest.mark.soak
def test_kill_one_replica_trail_records_death_then_heal():
    """Acceptance: a 2-replica run that SIGKILLs one replica produces an
    event trail containing peer_death followed by heal_end, and the
    recovery cost is readable from the recorded step-duration outliers.

    One retry, same as test_recovery: on a contended box the kill can
    land between plane epochs where no socket FIN reaches the survivor,
    so the death watch (legitimately) has nothing to report.

    total_steps leaves the survivor ~3s of post-kill runway: with the
    25-step default it can FINISH and exit ~1.2s after the kill — about
    one python+jax startup — so the respawned victim sometimes finds an
    empty lighthouse, forms a singleton quorum and replays from step 0
    with no one to heal from (no heal_end in the trail, by design)."""
    import warnings

    from torchft_tpu.benchmarks.recovery import measure_recovery

    for attempt in range(2):
        r = measure_recovery(
            total_steps=60,
            kill_at_step=6,
            step_sleep=0.05,
            op_timeout=1.0,
            heartbeat_timeout_ms=1000,
            timeout_s=120.0,
            num_groups=2,
        )
        if _death_then_heal_recorded(r):
            break
        warnings.warn(
            f"attempt {attempt}: trail lacks victim peer_death -> heal_end "
            f"({r.ft_events}); retrying once",
            stacklevel=1,
        )
    assert r.ft_events, "workers produced no event trail"
    assert r.ft_events.get("commit", 0) > 0, r.ft_events
    assert _death_then_heal_recorded(r), r.ft_events

    # recovery cost is readable from recorded outliers: the survivor's
    # step_outlier records (death-watch re-quorum) carry the blackout
    # duration, and the rejoiner's first measured step is tagged heal
    merged = []
    for path in r.trail_paths:
        merged.extend(read_trail(path))
    outliers = [e for e in merged if e["event"] == "step_outlier"]
    assert any("quorum" in e.get("tags", ()) for e in outliers), outliers
    victim_outliers = [
        e
        for e in read_trail(r.trail_paths[-1])
        if e["event"] == "step_outlier" and e["ts"] >= r.t_respawn_unix
    ]
    assert any("heal" in e.get("tags", ()) for e in victim_outliers), (
        victim_outliers
    )

    # acceptance (PR 2): the kill/respawn run produced a merged Chrome
    # trace at the lighthouse /trace endpoint with spans from BOTH
    # replicas carrying correlated quorum epochs
    assert r.merged_trace_path and os.path.exists(r.merged_trace_path)
    with open(r.merged_trace_path) as f:
        trace = json.load(f)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    epochs_by_replica = {}
    for e in xs:
        tid = e.get("args", {}).get("trace_id", "")
        rid, _, rest = tid.partition(":")
        _, _, epoch = rest.partition(":")
        if rid:
            epochs_by_replica.setdefault(rid, set()).add(epoch)
    assert len(epochs_by_replica) >= 2, epochs_by_replica
    # some PAIR of replicas shares a quorum epoch (the pre-kill victim,
    # the survivor and the respawned victim are three distinct ids — the
    # dead id and its replacement never coexist in one epoch)
    ids = list(epochs_by_replica)
    assert any(
        epochs_by_replica[a] & epochs_by_replica[b]
        for i, a in enumerate(ids)
        for b in ids[i + 1 :]
    ), epochs_by_replica
    # ... and the per-replica health snapshot reflects both groups
    assert r.cluster and len(r.cluster["replicas"]) >= 2
