"""Diagnosis plane (ISSUE 12): always-on profilers, collapsed-stack
exactness, the latch→capture trigger engine, bundle schema round-trips,
and the empty-surface status hints.

The end-to-end proof (injected straggler → exactly one bundle with the
delay frame dominant in the victim's native hot stack) lives in the
``diagnose_straggler`` faultmatrix scenario; these are the fast units.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict

import pytest

from torchft_tpu import telemetry
from torchft_tpu.telemetry import profiler as prof
from torchft_tpu.telemetry.diagnosis import (
    TRIGGER_EVENTS,
    DiagnosisEngine,
    read_bundles,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# collapsed-stack (folded) utilities
# ---------------------------------------------------------------------------


class TestFolded:
    def test_parse_render_roundtrip(self):
        text = "a;b;c 3\nx;y 1\n"
        assert prof.render_folded(prof.parse_folded(text)) == text

    def test_merge_exact_across_processes(self):
        # the cross-process merge contract: counts are integers on
        # identical keys, so merge = elementwise addition — EXACT, the
        # same property the lathist grid gives histograms
        a = "dp.pump;run;hop 10\ndp.pump;run;idle 4\nrpc.serve;loop 2\n"
        b = "dp.pump;run;hop 7\nblob.serve;conn 1\n"
        merged = prof.parse_folded(prof.merge_folded(a, b))
        pa, pb = prof.parse_folded(a), prof.parse_folded(b)
        for key in set(pa) | set(pb):
            assert merged[key] == pa.get(key, 0) + pb.get(key, 0)
        assert merged["dp.pump;run;hop"] == 17

    def test_subtract_is_window(self):
        before = "a;b 5\nc;d 2\n"
        after = "a;b 9\nc;d 2\ne;f 3\n"
        window = prof.parse_folded(prof.subtract_folded(after, before))
        assert window == {"a;b": 4, "e;f": 3}  # zero-count keys dropped

    def test_subtract_tolerates_reset(self):
        # a reset between snapshots must clamp at 0, not go negative
        assert prof.parse_folded(
            prof.subtract_folded("a;b 1\n", "a;b 5\n")
        ) == {}

    def test_malformed_lines_skipped(self):
        assert prof.parse_folded("garbage\na;b notanum\nx;y 2\n") == {
            "x;y": 2
        }


# ---------------------------------------------------------------------------
# Python sampler
# ---------------------------------------------------------------------------


class TestPySampler:
    def test_sample_once_names_thread_and_function(self):
        stop = threading.Event()

        def parked_in_named_function():
            stop.wait(5.0)

        t = threading.Thread(
            target=parked_in_named_function, name="tft_test_parked",
            daemon=True,
        )
        t.start()
        try:
            s = prof.PyStackSampler(hz=0)  # manual ticks only
            n = s.sample_once()
            assert n >= 1
            folded = s.folded()
            mine = [
                line for line in folded.splitlines()
                if line.startswith("tft_test_parked;")
            ]
            assert mine, folded
            assert "parked_in_named_function" in mine[0]
            assert s.samples_total() == n
            s.reset()
            assert s.folded() == "" and s.samples_total() == 0
        finally:
            stop.set()
            t.join()

    def test_metric_counts_py_plane(self):
        before = telemetry.PROF_SAMPLES.labels(plane="py").value
        s = prof.PyStackSampler(hz=0)
        n = s.sample_once()
        assert (
            telemetry.PROF_SAMPLES.labels(plane="py").value - before == n
        )

    def test_disarmed_starts_no_thread(self):
        s = prof.PyStackSampler(hz=0)
        assert s.ensure_started()._thread is None
        s.set_hz(50)
        try:
            assert s._thread is not None
            deadline = time.monotonic() + 2.0
            while s.samples_total() == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert s.samples_total() > 0
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# native sampler (through the C ABI)
# ---------------------------------------------------------------------------


def _dp_pair():
    from torchft_tpu import _native

    a = _native.NativeDataPlane(0, 2, nstripes=2)
    b = _native.NativeDataPlane(1, 2, nstripes=2)
    b.connect(0, "127.0.0.1", a.port, 5000)
    a.wait_ready(5000)
    b.wait_ready(5000)
    return a, b


def _dp_traffic(a, b, rounds: int = 30, tag0: int = 1):
    import numpy as np

    bufs = [np.ones(1 << 16, dtype=np.float32) for _ in range(2)]

    def run(dp, buf):
        for t in range(rounds):
            dp.allreduce(
                buf.ctypes.data, buf.size, "avg", tag=tag0 + t,
                timeout_ms=20000,
            )

    threads = [
        threading.Thread(target=run, args=(a, bufs[0]), daemon=True),
        threading.Thread(target=run, args=(b, bufs[1]), daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert bufs[0][0] == 1.0


class TestNativeProfiler:
    def test_armed_samples_dp_pump(self):
        from torchft_tpu import _native

        _native.prof_reset()
        _native.prof_set_hz(199.0)
        try:
            a, b = _dp_pair()
            try:
                _dp_traffic(a, b, rounds=60)
                time.sleep(0.2)
            finally:
                a.close()
                b.close()
            folded = _native.prof_snapshot()
            assert any(
                line.startswith("dp.pump;")
                for line in folded.splitlines()
            ), folded[:500]
            assert _native.prof_samples_total() > 0
            # counts fold into the py-side metric on poll
            before = telemetry.PROF_SAMPLES.labels(plane="native").value
            prof.poll_native_samples()
            assert (
                telemetry.PROF_SAMPLES.labels(plane="native").value
                > before
            )
            _native.prof_reset()
            assert _native.prof_snapshot() == ""
            assert _native.prof_samples_total() == 0
        finally:
            _native.prof_set_hz(prof.env_hz())

    def test_disarmed_profiler_zero_cost_on_dp_hop(self):
        # the ISSUE 12 satellite: a disarmed profiler adds ZERO to the
        # dp.hop hot path — the snapshot is identical (empty) before and
        # after real hop traffic, and no sample is ever recorded
        from torchft_tpu import _native

        _native.prof_set_hz(0.0)
        _native.prof_reset()
        try:
            before = _native.prof_snapshot()
            samples_before = _native.prof_samples_total()
            a, b = _dp_pair()
            try:
                _dp_traffic(a, b, rounds=40)
            finally:
                a.close()
                b.close()
            assert _native.prof_snapshot() == before == ""
            assert _native.prof_samples_total() == samples_before == 0
        finally:
            _native.prof_set_hz(prof.env_hz())


# ---------------------------------------------------------------------------
# trigger engine
# ---------------------------------------------------------------------------


def _mk_engine(tmp_path, **kw) -> DiagnosisEngine:
    kw.setdefault("directory", str(tmp_path / "diag"))
    kw.setdefault("replica_id", "g1")
    kw.setdefault("window_s", 0.01)
    kw.setdefault("burst_hz", 0.0)  # units don't need real burst samples
    kw.setdefault("synchronous", True)
    os.makedirs(kw["directory"], exist_ok=True)
    return DiagnosisEngine(**kw)


_TRIGGER_FIXTURE: Dict[str, Dict] = {
    "straggler_detected": {"group": "g1", "p50_s": 0.4},
    "perf_regression": {"replica": "g1", "series": "local_s", "step": 7},
    "slo_breach": {"slo": "step_time", "threshold_s": 0.5},
    "watchdog_stall": {"step": 9, "elapsed_s": 120.0},
    "divergence_detected": {"step": 11, "fence": False},
}


class TestTriggerEngine:
    def test_debounce_once_per_episode_all_five(self, tmp_path):
        # every trigger captures exactly once per episode, across ALL
        # five latch events; the matching *_cleared re-arms; latches
        # with no cleared event re-arm only after rearm_s
        now = [0.0]
        eng = _mk_engine(tmp_path, rearm_s=600.0, clock=lambda: now[0])
        eng.install()
        try:
            for kind, fields in _TRIGGER_FIXTURE.items():
                telemetry.emit(kind, **fields)
                telemetry.emit(kind, **fields)  # same episode: debounced
            assert eng.bundle_count == len(TRIGGER_EVENTS)

            # the three clearable triggers re-arm on their *_cleared
            telemetry.emit("straggler_cleared", group="g1")
            telemetry.emit(
                "perf_regression_cleared", replica="g1", series="local_s"
            )
            telemetry.emit("slo_recovered", slo="step_time")
            for kind in (
                "straggler_detected", "perf_regression", "slo_breach"
            ):
                telemetry.emit(kind, **_TRIGGER_FIXTURE[kind])
            assert eng.bundle_count == len(TRIGGER_EVENTS) + 3

            # watchdog/divergence have no cleared event: still latched...
            telemetry.emit("watchdog_stall", **_TRIGGER_FIXTURE["watchdog_stall"])
            telemetry.emit(
                "divergence_detected",
                **_TRIGGER_FIXTURE["divergence_detected"],
            )
            assert eng.bundle_count == len(TRIGGER_EVENTS) + 3
            # ...until the re-arm window passes
            now[0] += 601.0
            telemetry.emit("watchdog_stall", **_TRIGGER_FIXTURE["watchdog_stall"])
            telemetry.emit(
                "divergence_detected",
                **_TRIGGER_FIXTURE["divergence_detected"],
            )
            assert eng.bundle_count == len(TRIGGER_EVENTS) + 5
        finally:
            eng.remove()

    def test_one_capture_per_process_across_engines(self, tmp_path):
        # review fix: the burst boost mutates the SHARED samplers, so a
        # subject-less latch that fans out to every installed engine
        # must produce ONE capture, not one per engine — a losing engine
        # would save the winner's burst rate as its own "pre-burst"
        # restore value (leaving the process at burst Hz forever) and
        # write a duplicate bundle for the same incident. The guard is
        # acquired on the EMITTING thread before the capture thread
        # spawns, so the second engine's fan-out deterministically
        # loses the try-acquire.
        pre_hz = prof.PROFILER.hz
        a = _mk_engine(tmp_path, synchronous=False, window_s=0.05)
        b = _mk_engine(tmp_path, synchronous=False, window_s=0.05)
        a.install()
        b.install()
        try:
            telemetry.emit("divergence_detected", step=3, fence=False)
            deadline = time.monotonic() + 5.0
            while (
                time.monotonic() < deadline
                and a.bundle_count + b.bundle_count < 1
            ):
                time.sleep(0.01)
            time.sleep(0.2)  # slack for a (buggy) second capture to land
            assert a.bundle_count + b.bundle_count == 1
            assert prof.PROFILER.hz == pre_hz
        finally:
            a.remove()
            b.remove()

    def test_distinct_slos_are_distinct_episodes(self, tmp_path):
        # review fix: the two SLOs share one event kind but are
        # independent streams — a live step_time episode must not
        # swallow a rejoin breach, and rejoin's recovery must not
        # re-arm step_time
        eng = _mk_engine(tmp_path).install()
        try:
            telemetry.emit("slo_breach", slo="step_time")
            telemetry.emit("slo_breach", slo="rejoin_commit")
            assert eng.bundle_count == 2
            telemetry.emit("slo_recovered", slo="rejoin_commit")
            telemetry.emit("slo_breach", slo="step_time")  # still latched
            assert eng.bundle_count == 2
            telemetry.emit("slo_breach", slo="rejoin_commit")  # re-armed
            assert eng.bundle_count == 3
        finally:
            eng.remove()

    def test_bundle_names_carry_pid(self, tmp_path):
        # review fix: process-local events can capture on every replica
        # sharing one fleet dir in the same second — the pid keeps the
        # bundle dirs from silently merging
        eng = _mk_engine(tmp_path).install()
        try:
            telemetry.emit("watchdog_stall", step=1)
        finally:
            eng.remove()
        assert f"_{os.getpid()}_" in eng.bundles[0]

    def test_remote_subject_filtered(self, tmp_path):
        eng = _mk_engine(tmp_path).install()
        try:
            telemetry.emit("straggler_detected", group="SOME_OTHER_GROUP")
            telemetry.emit(
                "perf_regression", replica="not_me", series="local_s"
            )
            assert eng.bundle_count == 0
            # prefix matching both ways (manager ids carry uuid suffixes)
            telemetry.emit("straggler_detected", group="g1-uuid-suffix")
            assert eng.bundle_count == 1
        finally:
            eng.remove()

    def test_disabled_without_dir(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TORCHFT_DIAG_DIR", raising=False)
        eng = DiagnosisEngine(
            directory=None, replica_id="g1", synchronous=True
        )
        eng.install()  # no-op: disabled
        telemetry.emit("watchdog_stall", step=1)
        assert eng.bundle_count == 0

    def test_bundle_schema_and_capture_contents(self, tmp_path):
        eng = _mk_engine(tmp_path, window_s=0.05)
        eng.install()
        try:
            telemetry.emit("slo_breach", slo="step_time", step=3)
        finally:
            eng.remove()
        bundles = read_bundles(eng.directory)
        assert len(bundles) == 1
        b = bundles[0]
        assert b["schema"] == 1
        assert b["trigger"]["event"] == "slo_breach"
        assert b["replica_id"] == "g1"
        assert set(b["files"]) >= {
            "native_folded", "python_folded", "flight", "jax_trace"
        }
        d = b["_dir"]
        for fname in ("bundle.json", "native.folded", "python.folded",
                      "flight.json"):
            assert os.path.isfile(os.path.join(d, fname)), fname
        # lathist deltas keyed by the native op set when the plane loads
        assert isinstance(b["lathist"], dict)
        with open(os.path.join(d, "flight.json"), encoding="utf-8") as f:
            flight = json.load(f)
        assert "entries" in flight and "first_stuck" in flight
        # the capture itself is announced
        kinds = [e["event"] for e in telemetry.EVENTS.recent()]
        assert "diagnosis_captured" in kinds
        assert (
            telemetry.DIAGNOSIS_BUNDLES.labels(trigger="slo_breach").value
            == 1
        )

    def test_bundle_roundtrips_through_postmortem_bundles(self, tmp_path):
        # the ISSUE 12 satellite: bundle schema round-trips through
        # `postmortem --bundles` — latch → capture → evidence on ONE
        # causal timeline, from disk alone
        eng = _mk_engine(tmp_path)
        eng.install()
        try:
            telemetry.emit("watchdog_stall", step=41, elapsed_s=99.0)
        finally:
            eng.remove()
        from torchft_tpu.telemetry import postmortem

        report = postmortem.analyze(
            str(tmp_path), bundles_dir=eng.directory
        )
        assert len(report["bundles"]) == 1
        assert report["bundles"][0]["trigger"]["event"] == "watchdog_stall"
        caps = [
            r for r in report["timeline"]
            if r.get("k") == "diagnosis_captured"
        ]
        assert len(caps) == 1
        assert caps[0]["st"] == 41  # the trigger's step coordinate
        assert caps[0]["path"] == report["bundles"][0]["_dir"]
        # without the flag the timeline stays bundle-free
        assert postmortem.analyze(str(tmp_path))["bundles"] == []
        # and the CLI path agrees
        rc = postmortem.main(
            [str(tmp_path), "--bundles", eng.directory]
        )
        assert rc == 0

    def test_burst_boost_restores_rate(self, tmp_path):
        sampler = prof.PROFILER
        before = sampler.hz
        eng = _mk_engine(tmp_path, burst_hz=123.0, window_s=0.05)
        eng.install()
        try:
            telemetry.emit("slo_breach", slo="step_time")
        finally:
            eng.remove()
        assert sampler.hz == before  # boosted for the window, restored
        assert eng.bundle_count == 1


# ---------------------------------------------------------------------------
# satellites: unified crash-time evidence + empty-surface hints
# ---------------------------------------------------------------------------


class TestFlightDumpStacks:
    def test_dump_carries_live_python_thread_stacks(self, tmp_path):
        stop = threading.Event()

        def wedged_in_named_place():
            stop.wait(5.0)

        t = threading.Thread(
            target=wedged_in_named_place, name="tft_test_wedged",
            daemon=True,
        )
        t.start()
        try:
            rec = telemetry.FlightRecorder(size=16)
            rec.record_issue("allreduce", "test", 128)
            os.environ["TORCHFT_FLIGHT_DIR"] = str(tmp_path)
            try:
                path = rec.dump("manual", force=True)
            finally:
                os.environ.pop("TORCHFT_FLIGHT_DIR", None)
            assert path
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
            stacks = payload["py_stacks"]
            mine = [
                s for s in stacks if s["thread"] == "tft_test_wedged"
            ]
            assert mine, [s["thread"] for s in stacks]
            assert any(
                "wedged_in_named_place" in fr for fr in mine[0]["frames"]
            )
        finally:
            stop.set()
            t.join()


class TestStatusHints:
    def test_critical_path_no_monitor_vs_empty_vs_ok(self):
        from torchft_tpu.telemetry import critical_path as cp

        cp.set_reporter(None)
        assert json.loads(cp.report_json())["status"] == "no-monitor"
        att = cp.CriticalPathAttributor()
        cp.set_reporter(att)
        try:
            assert json.loads(cp.report_json())["status"] == "empty"
            att.observe_step(
                5,
                {
                    "a": {"wall_s": 1.0, "local_s": 0.9,
                          "phases": {"compute": 0.9}},
                    "b": {"wall_s": 1.0, "local_s": 0.5,
                          "phases": {"compute": 0.5}},
                },
            )
            assert json.loads(cp.report_json())["status"] == "ok"
        finally:
            cp.set_reporter(None)

    def test_lighthouse_diagnosis_json_empty_then_ok(self):
        import urllib.request

        from datetime import timedelta

        from torchft_tpu.coordination import (
            LighthouseClient,
            LighthouseServer,
        )

        lh = LighthouseServer(bind="[::]:0", min_replicas=1)
        try:
            base = lh.address()
            with urllib.request.urlopen(
                base + "/diagnosis.json", timeout=5
            ) as resp:
                doc = json.loads(resp.read().decode())
            # a scraper can tell "fleet wired, nothing captured" from a
            # bare empty shape (the ambiguity that bit PR 11's bring-up)
            assert doc["status"] == "empty"
            assert doc["bundles_total"] == 0

            client = LighthouseClient(
                base.split("//", 1)[-1],
                connect_timeout=timedelta(seconds=5),
            )
            try:
                client.heartbeat(
                    "repl_a",
                    timeout=timedelta(seconds=5),
                    telemetry_payload={
                        "step": 12,
                        "diag_bundles": 2,
                        "diag_last": "diag_17_straggler_detected_2",
                        "diag_dir": "/tmp/diag",
                    },
                )
            finally:
                client.close()
            with urllib.request.urlopen(
                base + "/diagnosis.json", timeout=5
            ) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["status"] == "ok"
            assert doc["bundles_total"] == 2
            assert doc["replicas"]["repl_a"]["bundles"] == 2
            assert (
                doc["replicas"]["repl_a"]["last"]
                == "diag_17_straggler_detected_2"
            )

            # review fix: a cap overflow replaces the stored value with
            # a LOUD marker instead of silently serving the stale
            # predecessor's evidence path as if it were current
            client = LighthouseClient(
                base.split("//", 1)[-1],
                connect_timeout=timedelta(seconds=5),
            )
            try:
                client.heartbeat(
                    "repl_a",
                    timeout=timedelta(seconds=5),
                    telemetry_payload={
                        "step": 13,
                        "diag_bundles": 3,
                        "diag_last": "x" * 300,
                        "diag_dir": "/d/" + "y" * 600,
                    },
                )
            finally:
                client.close()
            with urllib.request.urlopen(
                base + "/diagnosis.json", timeout=5
            ) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["replicas"]["repl_a"]["last"] == "(oversized)"
            assert doc["replicas"]["repl_a"]["dir"] == "(oversized)"
        finally:
            lh.shutdown()
