"""ResNet-18 model family unit tests (BASELINE.md CIFAR-10 config;
reference train_ddp.py:34-80 trains the torchvision equivalent)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu.models import resnet

# compile-heavy slow tier: excluded from the default run (pyproject addopts)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def model():
    cfg = resnet.ResNetConfig(dtype=jnp.float32)
    params, stats = resnet.init(jax.random.PRNGKey(0), cfg)
    return cfg, params, stats


def test_param_count_matches_resnet18(model):
    _, params, _ = model
    n = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params))
    # torchvision resnet18 CIFAR variant: ~11.17M
    assert 11_100_000 < n < 11_250_000, n


def test_train_step_updates_running_stats_and_learns(model):
    cfg, params, stats = model
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)

    vg = jax.jit(
        jax.value_and_grad(
            lambda p, s: resnet.loss_fn(p, s, x, y, cfg), has_aux=True
        )
    )
    (loss0, new_stats), grads = vg(params, stats)
    assert np.isfinite(float(loss0))
    # running stats moved off their init
    assert float(jnp.abs(new_stats["stem"]["bn"]["mean"]).sum()) > 0
    # one SGD step reduces the loss on the same batch
    lr = 0.1
    params2 = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    (loss1, _), _ = vg(params2, new_stats)
    assert float(loss1) < float(loss0)


def test_eval_uses_running_stats(model):
    cfg, params, stats = model
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((4, 32, 32, 3)), jnp.float32
    )
    logits, st = resnet.apply(params, stats, x, cfg, train=False)
    assert logits.shape == (4, 10)
    # eval must not mutate state
    for a, b in zip(
        jax.tree_util.tree_leaves(stats), jax.tree_util.tree_leaves(st)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_apply_is_deterministic(model):
    cfg, params, stats = model
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    l1, _ = resnet.apply(params, stats, x, cfg, train=True)
    l2, _ = resnet.apply(params, stats, x, cfg, train=True)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
